"""Protobuf codec: message-code registry, framing, and the ApbTerm
term encoding.

Framing mirrors the reference exactly: a 4-byte big-endian length
prefix ({packet, 4}, reference src/antidote_pb_protocol.erl:42-58)
around [1-byte message code | protobuf payload] (the antidote_pb_codec
convention).  Terms (clocks, CRDT op parameters, read results) travel
as ApbTerm — the language-neutral replacement for the reference's
term_to_binary blobs (reference src/antidote_pb_process.erl:41-46).
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.pb import antidote_pb2 as pb

# ------------------------------------------------------------ msg codes

#: 1-byte message codes; requests low, responses high (the reference's
#: codec numbers its Apb messages the same way)
MSG_CODES = {
    pb.ApbStartTransaction: 10,
    pb.ApbAbortTransaction: 11,
    pb.ApbCommitTransaction: 12,
    pb.ApbReadObjects: 13,
    pb.ApbUpdateObjects: 14,
    pb.ApbStaticReadObjects: 15,
    pb.ApbStaticUpdateObjects: 16,
    pb.ApbGetConnectionDescriptor: 17,
    pb.ApbConnectToDcs: 18,
    pb.ApbCreateDc: 19,
    pb.ApbAdminStatus: 20,
    pb.ApbGetFlag: 21,
    pb.ApbSetFlag: 22,
    pb.ApbErrorResp: 100,
    pb.ApbStartTransactionResp: 101,
    pb.ApbOperationResp: 102,
    pb.ApbCommitResp: 103,
    pb.ApbReadObjectsResp: 104,
    pb.ApbStaticReadObjectsResp: 105,
    pb.ApbGetConnectionDescriptorResp: 106,
    pb.ApbAdminStatusResp: 107,
    pb.ApbFlagResp: 108,
}

CODE_TO_MSG = {code: cls for cls, code in MSG_CODES.items()}


def encode_msg(msg) -> bytes:
    """[len u32 BE][code u8][protobuf bytes]."""
    code = MSG_CODES[type(msg)]
    body = msg.SerializeToString()
    return struct.pack(">IB", len(body) + 1, code) + body


def decode_msg(code: int, body: bytes):
    cls = CODE_TO_MSG.get(code)
    if cls is None:
        raise ValueError(f"unknown message code {code}")
    msg = cls()
    msg.ParseFromString(body)
    return msg


#: frame size cap: a hostile or corrupt length prefix must not commit a
#: handler thread to buffering gigabytes
MAX_FRAME = 64 * 1024 * 1024


def read_frame(sock) -> Optional[Tuple[int, bytes]]:
    """Read one length-framed message from a socket; None on EOF."""
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    if n < 1:
        raise ValueError("empty frame")
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds cap {MAX_FRAME}")
    payload = _read_exact(sock, n)
    if payload is None:
        return None
    return payload[0], bytes(payload[1:])


def _read_exact(sock, n: int) -> Optional[bytearray]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# ----------------------------------------------------------- term codec

def term_to_pb(v, out: Optional[pb.ApbTerm] = None) -> pb.ApbTerm:
    t = out if out is not None else pb.ApbTerm()
    if v is None:
        t.none = True
    elif isinstance(v, bool):  # before int: bool is an int subclass
        t.boolean = v
    elif isinstance(v, int):
        t.integer = v
    elif isinstance(v, float):
        t.number = v
    elif isinstance(v, bytes):
        t.binary = v
    elif isinstance(v, str):
        t.text = v
    elif isinstance(v, tuple):
        for item in v:
            term_to_pb(item, t.tuple.items.add())
        if not v:
            t.tuple.SetInParent()
    elif isinstance(v, (list, frozenset, set)):
        items = sorted(v, key=repr) if isinstance(v, (set, frozenset)) else v
        for item in items:
            term_to_pb(item, t.list.items.add())
        if not items:
            t.list.SetInParent()
    elif isinstance(v, dict):
        for k in v:
            pair = t.map.pairs.add()
            term_to_pb(k, pair.key)
            term_to_pb(v[k], pair.value)
        if not v:
            t.map.SetInParent()
    else:
        raise TypeError(f"cannot encode {type(v).__name__} as ApbTerm")
    return t


def term_from_pb(t: pb.ApbTerm):
    which = t.WhichOneof("t")
    if which is None or which == "none":
        return None
    if which == "integer":
        return t.integer
    if which == "binary":
        return t.binary
    if which == "text":
        return t.text
    if which == "boolean":
        return t.boolean
    if which == "number":
        return t.number
    if which == "tuple":
        return tuple(term_from_pb(i) for i in t.tuple.items)
    if which == "list":
        return [term_from_pb(i) for i in t.list.items]
    if which == "map":
        return {term_from_pb(p.key): term_from_pb(p.value)
                for p in t.map.pairs}
    raise ValueError(f"bad ApbTerm field {which}")


def clock_to_pb(vc: Optional[VC], out: pb.ApbTerm) -> None:
    if vc is None:
        out.none = True
    else:
        term_to_pb(dict(vc), out)


def clock_from_pb(t: pb.ApbTerm) -> Optional[VC]:
    v = term_from_pb(t)
    return None if v is None else VC(v)


# ------------------------------------------------------------- objects

def encode_clock_token(vc: Optional[VC]) -> bytes:
    """Opaque causal-clock bytes for protocols whose clients only echo
    the token (the upstream compat protocol ships term_to_binary blobs
    the same way, reference src/antidote_pb_process.erl:41-46).
    termcodec, never pickle: tokens come back from untrusted clients."""
    from antidote_tpu.interdc import termcodec

    return termcodec.encode(dict(vc) if vc else {})


def decode_clock_token(data: bytes) -> Optional[VC]:
    from antidote_tpu.interdc import termcodec

    if not data:
        return None
    d = termcodec.decode(data)
    if not isinstance(d, dict):
        raise ValueError("malformed clock token")
    return VC(d) if d else None


def bound_to_pb(bo, out: pb.ApbBoundObject) -> None:
    if len(bo) == 2:
        key, type_name = bo
        bucket = None
    else:
        key, type_name, bucket = bo
    term_to_pb(key, out.key)
    out.type = type_name if isinstance(type_name, str) else type_name.name
    term_to_pb(bucket, out.bucket)


def bound_from_pb(b: pb.ApbBoundObject):
    bucket = term_from_pb(b.bucket)
    key = term_from_pb(b.key)
    if bucket is None:
        return (key, b.type)
    return (key, b.type, bucket)


def descriptor_to_bytes(desc) -> bytes:
    """DcDescriptor as an ApbTerm blob — flat primitives only, never
    pickle (client-supplied pickles would be remote code execution)."""
    t = term_to_pb((desc.dc_id, desc.n_partitions,
                    tuple(desc.pub_addrs), tuple(desc.logreader_addrs)))
    return t.SerializeToString()


def descriptor_from_bytes(data: bytes):
    from antidote_tpu.interdc.wire import DcDescriptor

    t = pb.ApbTerm()
    t.ParseFromString(data)
    dc_id, n_partitions, pub_addrs, logreader_addrs = term_from_pb(t)
    return DcDescriptor(dc_id=dc_id, n_partitions=int(n_partitions),
                        pub_addrs=tuple(pub_addrs),
                        logreader_addrs=tuple(logreader_addrs))


def props_to_pb(props, out: pb.ApbTxnProperties) -> None:
    if props is None:
        return
    out.ignore_client_clock = not props.update_clock
    if props.certify is True:
        out.certify = pb.ApbTxnProperties.CERTIFY
    elif props.certify is False:
        out.certify = pb.ApbTxnProperties.DONT_CERTIFY


def props_from_pb(p: pb.ApbTxnProperties):
    from antidote_tpu.txn.coordinator import TxnProperties

    certify = None
    if p.certify == pb.ApbTxnProperties.CERTIFY:
        certify = True
    elif p.certify == pb.ApbTxnProperties.DONT_CERTIFY:
        certify = False
    return TxnProperties(update_clock=not p.ignore_client_clock,
                        certify=certify)

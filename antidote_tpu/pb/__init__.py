"""Wire protocol: length-framed protobuf over TCP (the reference's
antidote_pb stack — listener, per-connection protocol loop, dispatch —
reference src/antidote_pb_sup.erl, src/antidote_pb_protocol.erl,
src/antidote_pb_process.erl).

Regenerate ``antidote_pb2.py`` after editing ``antidote.proto``:
``protoc --python_out=. antidote.proto`` in this directory.
"""

from antidote_tpu.pb.client import PbClient, PbError, PbServerError
from antidote_tpu.pb.server import DEFAULT_PORT, PbServer

__all__ = ["PbClient", "PbError", "PbServerError", "PbServer",
           "DEFAULT_PORT"]

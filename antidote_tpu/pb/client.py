"""Blocking protocol client — the antidotec_pb equivalent (the
reference's Erlang client library driving the :8087 endpoint, exercised
by reference test/singledc/pb_client_SUITE.erl).

API mirrors the server surface: start/read/update/commit/abort plus
static variants and DC management, with clocks as VCs and op
parameters as plain Python terms.
"""

from __future__ import annotations

import socket
from typing import Any, List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.pb import antidote_pb2 as pb
from antidote_tpu.pb import codec


class PbError(Exception):
    """Any protocol-level failure (transport faults AND server-reported
    errors — catch this to handle both)."""


class PbServerError(PbError):
    """The server processed the request and reported an error (e.g. a
    write-write certification abort).  The connection stays usable —
    unlike a transport-level :class:`PbError`, which marks it broken."""


class PbClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8087,
                 timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self._broken = False

    def close(self) -> None:
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ----------------------------------------------------------- low level

    def _call(self, msg):
        # one request in flight per connection: after a timeout or
        # partial read the stream is desynchronized (the server will
        # still write the old response), so the client must not be
        # reused — every later call would read the previous answer
        if self._broken:
            raise PbError("connection desynchronized by an earlier "
                          "timeout; open a new client")
        try:
            self.sock.sendall(codec.encode_msg(msg))
            frame = codec.read_frame(self.sock)
            if frame is None:
                raise PbError("connection closed")
            # decode failures (unknown code, corrupt payload) also mean
            # the stream can no longer be trusted
            resp = codec.decode_msg(*frame)
        except PbError:
            self._broken = True
            raise
        except Exception as e:  # noqa: BLE001 — any stream fault
            self._broken = True
            raise PbError(f"transport failure: {e}") from e
        if isinstance(resp, pb.ApbErrorResp):
            raise PbServerError(resp.message)
        return resp

    @staticmethod
    def _check(resp):
        if not resp.success:
            raise PbServerError(resp.error)
        return resp

    # -------------------------------------------------------- transactions

    def start_transaction(self, clock: Optional[VC] = None,
                          properties=None) -> bytes:
        req = pb.ApbStartTransaction()
        codec.clock_to_pb(clock, req.clock)
        codec.props_to_pb(properties, req.properties)
        return self._check(self._call(req)).txid

    def read_objects(self, objects: List, txid: bytes) -> List[Any]:
        req = pb.ApbReadObjects(txid=txid)
        for bo in objects:
            codec.bound_to_pb(bo, req.objects.add())
        resp = self._check(self._call(req))
        return [codec.term_from_pb(v) for v in resp.values]

    def update_objects(self, updates: List, txid: bytes) -> None:
        req = pb.ApbUpdateObjects(txid=txid)
        for bo, op_name, param in updates:
            u = req.updates.add()
            codec.bound_to_pb(bo, u.object)
            u.operation = op_name
            codec.term_to_pb(param, u.parameter)
        self._check(self._call(req))

    def commit_transaction(self, txid: bytes) -> VC:
        resp = self._check(self._call(pb.ApbCommitTransaction(txid=txid)))
        return codec.clock_from_pb(resp.commit_clock)

    def abort_transaction(self, txid: bytes) -> None:
        self._check(self._call(pb.ApbAbortTransaction(txid=txid)))

    # ------------------------------------------------------------- static

    def read_objects_static(self, clock: Optional[VC], objects: List,
                            properties=None) -> Tuple[List[Any], VC]:
        req = pb.ApbStaticReadObjects()
        codec.clock_to_pb(clock, req.clock)
        codec.props_to_pb(properties, req.properties)
        for bo in objects:
            codec.bound_to_pb(bo, req.objects.add())
        resp = self._check(self._call(req))
        return ([codec.term_from_pb(v) for v in resp.values],
                codec.clock_from_pb(resp.commit_clock))

    def update_objects_static(self, clock: Optional[VC], updates: List,
                              properties=None) -> VC:
        req = pb.ApbStaticUpdateObjects()
        codec.clock_to_pb(clock, req.clock)
        codec.props_to_pb(properties, req.properties)
        for bo, op_name, param in updates:
            u = req.updates.add()
            codec.bound_to_pb(bo, u.object)
            u.operation = op_name
            codec.term_to_pb(param, u.parameter)
        resp = self._check(self._call(req))
        return codec.clock_from_pb(resp.commit_clock)

    # ------------------------------------------------------ DC management

    def get_connection_descriptor(self):
        resp = self._check(self._call(pb.ApbGetConnectionDescriptor()))
        return codec.descriptor_from_bytes(resp.descriptor)

    def connect_to_dcs(self, descriptors: List) -> None:
        req = pb.ApbConnectToDcs(
            descriptors=[codec.descriptor_to_bytes(d) for d in descriptors])
        self._check(self._call(req))

    def create_dc(self, nodes: Optional[List[str]] = None) -> None:
        """Form the DC (reference antidote_pb_process create_dc,
        src/antidote_pb_process.erl:102-116)."""
        self._check(self._call(pb.ApbCreateDc(nodes=nodes or [])))

    # -------------------------------------------------------- admin plane

    def admin_status(self) -> dict:
        resp = self._check(self._call(pb.ApbAdminStatus()))
        return codec.term_from_pb(resp.info)

    def get_flag(self, name: str):
        resp = self._check(self._call(pb.ApbGetFlag(name=name)))
        return codec.term_from_pb(resp.value)

    def set_flag(self, name: str, value):
        req = pb.ApbSetFlag(name=name)
        codec.term_to_pb(value, req.value)
        resp = self._check(self._call(req))
        return codec.term_from_pb(resp.value)

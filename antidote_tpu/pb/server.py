"""Protocol server: length-framed protobuf over TCP.

The role of the reference's ranch listener + per-connection protocol
loop + dispatcher (reference src/antidote_pb_sup.erl:49-57,
src/antidote_pb_protocol.erl:42-88, src/antidote_pb_process.erl:49-135):
a threaded TCP server on port 8087, one handler thread per connection,
{packet,4} framing, 1-byte message code, errors caught and returned as
ApbErrorResp.  Interactive transactions are keyed by a server-issued
txid token and owned by the connection — a dropped connection aborts
its open transactions, like the reference's FSM being linked to the
socket process.
"""

from __future__ import annotations

import logging
import socketserver
import struct
import threading
import uuid
from typing import Dict

from antidote_tpu.api import TransactionAborted
from antidote_tpu.pb import antidote_pb2 as pb
from antidote_tpu.pb import codec

DEFAULT_PORT = 8087  # reference ?DEFAULT_PB_PORT

log = logging.getLogger(__name__)


class PbServer:
    """Serve one AntidoteTPU/DataCenter instance over TCP."""

    def __init__(self, db, port: int = DEFAULT_PORT, host: str = "127.0.0.1"):
        self.db = db
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                from antidote_tpu.pb import compat

                conn = _Connection(outer.db)
                cconn = compat.CompatConnection(outer.db)
                try:
                    while True:
                        frame = codec.read_frame(self.request)
                        if frame is None:
                            return
                        code, body = frame
                        # dual-protocol dispatch by message code: the
                        # upstream antidote_pb registry numbers from
                        # 107, the rebuild's own protocol from 10 —
                        # disjoint, so antidotec_pb-style clients and
                        # native clients share the port (pb/compat.py)
                        if compat.is_compat_code(code):
                            try:
                                req = compat.decode_request(code, body)
                                resp = cconn.process(req)
                            except Exception as e:  # noqa: BLE001
                                log.exception("pb compat request failed")
                                resp = compat.error_resp(str(e))
                            ccode, cbody = compat.encode_response(resp)
                            self.request.sendall(
                                struct.pack(">IB", len(cbody) + 1,
                                            ccode) + cbody)
                            continue
                        try:
                            req = codec.decode_msg(code, body)
                            resp = conn.process(req)
                        except Exception as e:  # noqa: BLE001 — wire errors
                            # must go back to the client, not kill the
                            # connection (reference antidote_pb_protocol
                            # catches and sends ApbErrorResp, :68-76)
                            log.exception("pb request failed")
                            resp = pb.ApbErrorResp(message=str(e))
                        self.request.sendall(codec.encode_msg(resp))
                finally:
                    conn.abort_all()
                    cconn.abort_all()

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> "PbServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)


class _Connection:
    """Per-connection dispatch state (the antidote_pb_process role)."""

    def __init__(self, db):
        self.db = db
        self.txns: Dict[bytes, object] = {}

    def abort_all(self) -> None:
        for tx in list(self.txns.values()):
            try:
                self.db.abort_transaction(tx)
            except Exception:  # noqa: BLE001
                pass
        self.txns.clear()

    # ------------------------------------------------------------ dispatch

    def process(self, req):
        handler = self._HANDLERS[type(req)]
        return handler(self, req)

    def _start_transaction(self, req: pb.ApbStartTransaction):
        clock = codec.clock_from_pb(req.clock)
        props = codec.props_from_pb(req.properties)
        try:
            tx = self.db.start_transaction(clock, props)
        except Exception as e:  # noqa: BLE001
            return pb.ApbStartTransactionResp(success=False, error=str(e))
        token = uuid.uuid4().bytes
        self.txns[token] = tx
        return pb.ApbStartTransactionResp(success=True, txid=token)

    def _tx(self, token: bytes):
        tx = self.txns.get(token)
        if tx is None:
            raise KeyError("unknown transaction id")
        return tx

    def _read_objects(self, req: pb.ApbReadObjects):
        try:
            tx = self._tx(req.txid)
            objects = [codec.bound_from_pb(b) for b in req.objects]
            values = self.db.read_objects(objects, tx)
        except TransactionAborted as e:
            # the coordinator aborted the txn on the failed read: drop
            # the token like the update handler does
            self.txns.pop(req.txid, None)
            return pb.ApbReadObjectsResp(success=False, error=str(e))
        except Exception as e:  # noqa: BLE001
            return pb.ApbReadObjectsResp(success=False, error=str(e))
        resp = pb.ApbReadObjectsResp(success=True)
        for v in values:
            codec.term_to_pb(v, resp.values.add())
        return resp

    def _update_objects(self, req: pb.ApbUpdateObjects):
        try:
            tx = self._tx(req.txid)
            updates = [
                (codec.bound_from_pb(u.object), u.operation,
                 codec.term_from_pb(u.parameter))
                for u in req.updates
            ]
            self.db.update_objects(updates, tx)
        except TransactionAborted as e:
            self.txns.pop(req.txid, None)
            return pb.ApbOperationResp(success=False, error=str(e))
        except Exception as e:  # noqa: BLE001
            return pb.ApbOperationResp(success=False, error=str(e))
        return pb.ApbOperationResp(success=True)

    def _commit(self, req: pb.ApbCommitTransaction):
        try:
            tx = self._tx(req.txid)
            commit_vc = self.db.commit_transaction(tx)
        except Exception as e:  # noqa: BLE001
            self.txns.pop(req.txid, None)
            return pb.ApbCommitResp(success=False, error=str(e))
        self.txns.pop(req.txid, None)
        resp = pb.ApbCommitResp(success=True)
        codec.clock_to_pb(commit_vc, resp.commit_clock)
        return resp

    def _abort(self, req: pb.ApbAbortTransaction):
        try:
            tx = self._tx(req.txid)
            self.txns.pop(req.txid, None)
            self.db.abort_transaction(tx)
        except Exception as e:  # noqa: BLE001
            return pb.ApbOperationResp(success=False, error=str(e))
        return pb.ApbOperationResp(success=True)

    def _static_read(self, req: pb.ApbStaticReadObjects):
        try:
            from antidote_tpu.obs.spans import tracer

            clock = codec.clock_from_pb(req.clock)
            props = codec.props_from_pb(req.properties)
            objects = [codec.bound_from_pb(b) for b in req.objects]
            # routed through the read serve plane (ISSUE 8): the one-
            # shot read allocates no interactive transaction and
            # coalesces with concurrent readers (mat/serve.py); the
            # instant marks the PB arrival on the serve-stage timeline
            tracer.instant("pb_static_read", "coordinator",
                           keys=len(objects))
            values, commit_vc = self.db.read_objects_static(
                clock, objects, props)
        except Exception as e:  # noqa: BLE001
            return pb.ApbStaticReadObjectsResp(success=False, error=str(e))
        resp = pb.ApbStaticReadObjectsResp(success=True)
        for v in values:
            codec.term_to_pb(v, resp.values.add())
        codec.clock_to_pb(commit_vc, resp.commit_clock)
        return resp

    def _static_update(self, req: pb.ApbStaticUpdateObjects):
        try:
            clock = codec.clock_from_pb(req.clock)
            props = codec.props_from_pb(req.properties)
            updates = [
                (codec.bound_from_pb(u.object), u.operation,
                 codec.term_from_pb(u.parameter))
                for u in req.updates
            ]
            commit_vc = self.db.update_objects_static(clock, updates, props)
        except Exception as e:  # noqa: BLE001
            return pb.ApbCommitResp(success=False, error=str(e))
        resp = pb.ApbCommitResp(success=True)
        codec.clock_to_pb(commit_vc, resp.commit_clock)
        return resp

    def _get_descriptor(self, req: pb.ApbGetConnectionDescriptor):
        desc_fn = getattr(self.db, "descriptor", None)
        if desc_fn is None:
            return pb.ApbGetConnectionDescriptorResp(
                success=False, error="not a DataCenter")
        return pb.ApbGetConnectionDescriptorResp(
            success=True, descriptor=codec.descriptor_to_bytes(desc_fn()))

    def _connect_to_dcs(self, req: pb.ApbConnectToDcs):
        observe = getattr(self.db, "observe_dcs_sync", None)
        if observe is None:
            return pb.ApbOperationResp(success=False,
                                       error="not a DataCenter")
        try:
            descs = [codec.descriptor_from_bytes(d) for d in req.descriptors]
            observe(descs)
        except Exception as e:  # noqa: BLE001
            return pb.ApbOperationResp(success=False, error=str(e))
        return pb.ApbOperationResp(success=True)

    def _create_dc(self, req: pb.ApbCreateDc):
        try:
            self.db.create_dc(list(req.nodes))
        except Exception as e:  # noqa: BLE001
            return pb.ApbOperationResp(success=False, error=str(e))
        return pb.ApbOperationResp(success=True)

    def _admin_status(self, req: pb.ApbAdminStatus):
        try:
            info = self.db.admin_status()
        except Exception as e:  # noqa: BLE001
            return pb.ApbAdminStatusResp(success=False, error=str(e))
        resp = pb.ApbAdminStatusResp(success=True)
        codec.term_to_pb(info, resp.info)
        return resp

    def _get_flag(self, req: pb.ApbGetFlag):
        try:
            value = self.db.get_flag(req.name)
        except Exception as e:  # noqa: BLE001
            return pb.ApbFlagResp(success=False, error=str(e))
        resp = pb.ApbFlagResp(success=True)
        codec.term_to_pb(value, resp.value)
        return resp

    def _set_flag(self, req: pb.ApbSetFlag):
        try:
            self.db.set_flag(req.name, codec.term_from_pb(req.value))
            value = self.db.get_flag(req.name)
        except Exception as e:  # noqa: BLE001
            return pb.ApbFlagResp(success=False, error=str(e))
        resp = pb.ApbFlagResp(success=True)
        codec.term_to_pb(value, resp.value)
        return resp

    _HANDLERS = {
        pb.ApbStartTransaction: _start_transaction,
        pb.ApbReadObjects: _read_objects,
        pb.ApbUpdateObjects: _update_objects,
        pb.ApbCommitTransaction: _commit,
        pb.ApbAbortTransaction: _abort,
        pb.ApbStaticReadObjects: _static_read,
        pb.ApbStaticUpdateObjects: _static_update,
        pb.ApbGetConnectionDescriptor: _get_descriptor,
        pb.ApbConnectToDcs: _connect_to_dcs,
        pb.ApbCreateDc: _create_dc,
        pb.ApbAdminStatus: _admin_status,
        pb.ApbGetFlag: _get_flag,
        pb.ApbSetFlag: _set_flag,
    }

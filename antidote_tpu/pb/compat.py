"""Public-protocol compatibility layer: serve antidotec_pb-style
clients speaking the upstream antidote_pb_codec protobuf
(pb/antidote_compat.proto — see its provenance note) next to the
rebuild's own ApbTerm protocol on ONE port.

Dispatch is by message code: the upstream registry numbers its
messages from 107 (reference src/antidote_pb_protocol.erl:59-66
delegates decoding by code), the rebuild's own protocol uses 10..22
for requests — disjoint spaces, so the server routes per frame and a
mixed client population just works.

Mapping notes:
- transaction descriptors and commit timestamps are opaque bytes to
  upstream clients (they echo them back), so the rebuild's own token /
  clock encodings ride inside unchanged.
- CRDT_type -> rebuild type names: COUNTER->counter_pn, ORSET->set_aw,
  LWWREG->register_lww, MVREG->register_mv, GMAP->map_go,
  RWSET->set_rw, RRMAP->map_rr, FATCOUNTER->counter_fat,
  FLAG_EW/FLAG_DW->flag_ew/flag_dw.
- upstream counters return sint32; values are clamped into int32 like
  the upstream codec's wire type forces.

Message codes follow the upstream registry (best-effort; the recorded
frames in tests/pb/ are the divergence-diff baseline):
107 ApbRegUpdate ... 128 ApbStaticReadObjectsResp, 0 ApbErrorResp.

DIVERGENCE-DIFF PROCEDURE (byte-level verification is impossible in
this environment — zero egress, the upstream codec dep not vendored —
so the corpus is built to make a future check MECHANICAL):

1. On a machine with the real client, capture one frame per message:
   drive antidotec_pb through the same canonical instances listed in
   tests/pb/test_pb_compat.py::_GOLDEN_FRAMES (each entry documents
   exactly which fields are set to which values), dumping the raw
   [u32 len][u8 code][payload] bytes per message.
2. Diff the captured (code, payload-hex) pairs against _GOLDEN_FRAMES
   row by row.  A code mismatch = fix this file's CODES table; a
   payload mismatch = fix the corresponding field numbers/types in
   antidote_compat.proto and regenerate (protoc), then update the
   golden hex — the test failure shows the reviewable byte diff.
3. Re-run tests/pb/test_pb_compat.py: the end-to-end session tests
   (interactive, static, map, error/abort) prove the fixed schema
   against the live server; the golden tests pin it for the future.
"""

from __future__ import annotations

import uuid
from typing import Dict

from antidote_tpu.pb import antidote_compat_pb2 as cpb

#: upstream message-code registry (requests the server accepts)
CODES = {
    "ApbErrorResp": 0,
    "ApbRegUpdate": 107,
    "ApbGetRegResp": 108,
    "ApbCounterUpdate": 109,
    "ApbGetCounterResp": 110,
    "ApbOperationResp": 111,
    "ApbSetUpdate": 112,
    "ApbGetSetResp": 113,
    "ApbTxnProperties": 114,
    "ApbBoundObject": 115,
    "ApbReadObjects": 116,
    "ApbUpdateOp": 117,
    "ApbUpdateObjects": 118,
    "ApbStartTransaction": 119,
    "ApbAbortTransaction": 120,
    "ApbCommitTransaction": 121,
    "ApbStaticUpdateObjects": 122,
    "ApbStaticReadObjects": 123,
    "ApbStartTransactionResp": 124,
    "ApbReadObjectResp": 125,
    "ApbReadObjectsResp": 126,
    "ApbCommitResp": 127,
    "ApbStaticReadObjectsResp": 128,
}

#: inbound decoders by code
_REQUESTS = {
    CODES["ApbReadObjects"]: cpb.ApbReadObjects,
    CODES["ApbUpdateObjects"]: cpb.ApbUpdateObjects,
    CODES["ApbStartTransaction"]: cpb.ApbStartTransaction,
    CODES["ApbAbortTransaction"]: cpb.ApbAbortTransaction,
    CODES["ApbCommitTransaction"]: cpb.ApbCommitTransaction,
    CODES["ApbStaticUpdateObjects"]: cpb.ApbStaticUpdateObjects,
    CODES["ApbStaticReadObjects"]: cpb.ApbStaticReadObjects,
}

TYPE_BY_ENUM = {
    cpb.COUNTER: "counter_pn",
    cpb.ORSET: "set_aw",
    cpb.LWWREG: "register_lww",
    cpb.MVREG: "register_mv",
    cpb.GMAP: "map_go",
    cpb.RWSET: "set_rw",
    cpb.RRMAP: "map_rr",
    cpb.FATCOUNTER: "counter_fat",
    cpb.FLAG_EW: "flag_ew",
    cpb.FLAG_DW: "flag_dw",
}

#: kinds of value response each type fills in ApbReadObjectResp
_VALUE_KIND = {
    "counter_pn": "counter", "counter_fat": "counter",
    "set_aw": "set", "set_rw": "set", "set_go": "set",
    "register_lww": "reg", "register_mv": "mvreg",
    "map_go": "map", "map_rr": "map",
    "flag_ew": "flag", "flag_dw": "flag",
}


def is_compat_code(code: int) -> bool:
    return code == 0 or code >= 100


def decode_request(code: int, body: bytes):
    cls = _REQUESTS.get(code)
    if cls is None:
        raise ValueError(f"unsupported compat message code {code}")
    msg = cls()
    msg.ParseFromString(body)
    return msg


def encode_response(msg) -> tuple:
    """(code, serialized bytes) for a compat response message."""
    return CODES[type(msg).__name__], msg.SerializeToString()


def _bound(bo) -> tuple:
    tname = TYPE_BY_ENUM.get(bo.type)
    if tname is None:
        raise ValueError(f"unsupported CRDT_type {bo.type}")
    return (bo.key, tname, bo.bucket)


def _ops_of(update_op) -> list:
    """[(op_name, arg)] for one ApbUpdateOperation (an op may expand:
    a set update can carry adds AND rems)."""
    u = update_op
    out = []
    if u.HasField("counterop"):
        out.append(("increment",
                    u.counterop.inc if u.counterop.HasField("inc")
                    else 1))
    if u.HasField("setop"):
        if u.setop.adds:
            out.append(("add_all", tuple(u.setop.adds)))
        if u.setop.rems:
            out.append(("remove_all", tuple(u.setop.rems)))
    if u.HasField("regop"):
        out.append(("assign", u.regop.value))
    if u.HasField("flagop"):
        out.append(("enable" if u.flagop.value else "disable", ()))
    if u.HasField("resetop"):
        out.append(("reset", ()))
    if u.HasField("mapop"):
        for nested in u.mapop.updates:
            ktuple = (nested.key.key,
                      TYPE_BY_ENUM[nested.key.type])
            for op_name, arg in _ops_of(nested.update):
                out.append(("update", (ktuple, (op_name, arg))))
        for rk in u.mapop.removedKeys:
            out.append(("remove", (rk.key, TYPE_BY_ENUM[rk.type])))
    return out


def _updates(update_ops) -> list:
    ups = []
    for uo in update_ops:
        bo = _bound(uo.boundobject)
        for op_name, arg in _ops_of(uo.operation):
            ups.append((bo, op_name, arg))
    return ups


def _value_resp(tname: str, value) -> "cpb.ApbReadObjectResp":
    resp = cpb.ApbReadObjectResp()
    kind = _VALUE_KIND.get(tname)
    if kind == "counter":
        v = int(value)
        if not -(1 << 31) <= v <= (1 << 31) - 1:
            # the upstream schema carries counters as sint32; a
            # silently saturated value would be WRONG data on the
            # client — refuse loudly instead (the server converts
            # this to an ApbErrorResp)
            raise ValueError(
                f"counter value {v} exceeds the compat protocol's "
                f"sint32 range; read it over the native protocol")
        resp.counter.value = v
    elif kind == "set":
        resp.set.value.extend(
            bytes(e) if isinstance(e, (bytes, bytearray))
            else str(e).encode() for e in value)
    elif kind == "reg":
        v = value if value is not None else b""
        resp.reg.value = (bytes(v) if isinstance(v, (bytes, bytearray))
                          else str(v).encode())
    elif kind == "mvreg":
        resp.mvreg.values.extend(
            bytes(e) if isinstance(e, (bytes, bytearray))
            else str(e).encode() for e in value)
    elif kind == "flag":
        resp.flag.value = bool(value)
    elif kind == "map":
        enum_by_type = {v: k for k, v in TYPE_BY_ENUM.items()}
        for (field, ntype), nval in sorted(
                value.items(), key=lambda kv: repr(kv[0])):
            ent = resp.map.entries.add()
            ent.key.key = (bytes(field)
                           if isinstance(field, (bytes, bytearray))
                           else str(field).encode())
            ent.key.type = enum_by_type.get(ntype, cpb.COUNTER)
            ent.value.CopyFrom(_value_resp(ntype, nval))
    else:
        raise ValueError(f"no compat value mapping for {tname!r}")
    return resp


class CompatConnection:
    """Per-connection upstream-protocol dispatch (the
    antidote_pb_process role for compat clients).  Shares the open-txn
    table semantics with the native connection: server-issued opaque
    descriptors, dropped connection aborts its transactions."""

    def __init__(self, db):
        self.db = db
        self.txns: Dict[bytes, object] = {}

    def abort_all(self) -> None:
        for tx in list(self.txns.values()):
            try:
                self.db.abort_transaction(tx)
            except Exception:  # noqa: BLE001 — connection teardown
                pass
        self.txns.clear()

    # -- clock threading ---------------------------------------------------

    def _clock_of(self, ts: bytes):
        from antidote_tpu.pb import codec

        return codec.decode_clock_token(ts) if ts else None

    def _clock_token(self, vc) -> bytes:
        from antidote_tpu.pb import codec

        return codec.encode_clock_token(vc)

    # -- dispatch ----------------------------------------------------------

    def process(self, msg):
        name = type(msg).__name__
        return getattr(self, "_on_" + name)(msg)

    def _on_ApbStartTransaction(self, msg):
        clock = self._clock_of(msg.timestamp
                               if msg.HasField("timestamp") else b"")
        tx = self.db.start_transaction(clock=clock)
        token = uuid.uuid4().bytes
        self.txns[token] = tx
        resp = cpb.ApbStartTransactionResp(success=True)
        resp.transaction_descriptor = token
        return resp

    def _tx(self, token: bytes):
        tx = self.txns.get(token)
        if tx is None:
            raise ValueError("unknown transaction descriptor")
        return tx

    def _on_ApbReadObjects(self, msg):
        tx = self._tx(msg.transaction_descriptor)
        bos = [_bound(bo) for bo in msg.boundobjects]
        vals = self.db.read_objects(bos, tx)
        resp = cpb.ApbReadObjectsResp(success=True)
        for (key, tname, bucket), v in zip(bos, vals):
            resp.objects.add().CopyFrom(_value_resp(tname, v))
        return resp

    def _on_ApbUpdateObjects(self, msg):
        tx = self._tx(msg.transaction_descriptor)
        self.db.update_objects(_updates(msg.updates), tx)
        return cpb.ApbOperationResp(success=True)

    def _on_ApbCommitTransaction(self, msg):
        tx = self.txns.pop(msg.transaction_descriptor, None)
        if tx is None:
            raise ValueError("unknown transaction descriptor")
        cvc = self.db.commit_transaction(tx)
        resp = cpb.ApbCommitResp(success=True)
        resp.commit_time = self._clock_token(cvc)
        return resp

    def _on_ApbAbortTransaction(self, msg):
        tx = self.txns.pop(msg.transaction_descriptor, None)
        if tx is not None:
            self.db.abort_transaction(tx)
        return cpb.ApbOperationResp(success=True)

    def _on_ApbStaticUpdateObjects(self, msg):
        clock = self._clock_of(
            msg.transaction.timestamp
            if msg.transaction.HasField("timestamp") else b"")
        cvc = self.db.update_objects_static(
            clock, _updates(msg.updates))
        resp = cpb.ApbCommitResp(success=True)
        resp.commit_time = self._clock_token(cvc)
        return resp

    def _on_ApbStaticReadObjects(self, msg):
        clock = self._clock_of(
            msg.transaction.timestamp
            if msg.transaction.HasField("timestamp") else b"")
        bos = [_bound(bo) for bo in msg.objects]
        vals, cvc = self.db.read_objects_static(clock, bos)
        resp = cpb.ApbStaticReadObjectsResp()
        resp.objects.success = True
        for (key, tname, bucket), v in zip(bos, vals):
            resp.objects.objects.add().CopyFrom(_value_resp(tname, v))
        resp.committime.success = True
        resp.committime.commit_time = self._clock_token(cvc)
        return resp


def error_resp(msg: str):
    e = cpb.ApbErrorResp()
    e.errmsg = msg.encode()
    e.errcode = 0
    return e

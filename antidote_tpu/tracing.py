"""RETIRED — the profile-capture API lives in
:mod:`antidote_tpu.obs.prof` (ISSUE 2 absorbed it; ISSUE 7 retires
this shim after the PR-2 call-site migration).  This module survives
one release as an import error so stale imports fail with a pointer
instead of an AttributeError three frames later; it will be deleted
next release.
"""

raise ImportError(
    "antidote_tpu.tracing was retired — use antidote_tpu.obs.prof: "
    "prof.profile(dir)/prof.start(dir)/prof.stop() for XProf captures, "
    "prof.annotate(name) for timeline annotations, and "
    "db.start_profiling/stop_profiling on the API facade. "
    "(This one-release import-error shim is deleted next release.)")

"""Re-export shim — the profile-capture API lives in
:mod:`antidote_tpu.obs.prof` now (ISSUE 2: one tracing namespace, not
two).  The capture functions, the kernel-span layer, and the txid span
tree all share the obs/ package; this module survives only so existing
imports (``from antidote_tpu import tracing``) keep working.

    with tracing.profile("/tmp/trace"):        # capture a window
        ... run traffic ...

    db.start_profiling("/tmp/trace")           # or explicit start/stop
    db.stop_profiling()

Annotations are no-ops outside an active capture (TraceAnnotation is
cheap), so they stay on permanently in the hot paths.
"""

from __future__ import annotations

from antidote_tpu.obs.prof import (  # noqa: F401
    active_dir,
    annotate,
    profile,
    start,
    stop,
)

"""Tracing / profiling — the SURVEY §5.1 first-class improvement.

The reference leans on BEAM tooling (observer, fprof) for runtime
visibility; the TPU rebuild's hot paths are XLA programs, so the
native story is the JAX profiler: capture a trace directory viewable
in TensorBoard/XProf (device timelines, HLO cost attribution,
host-side gaps), with the framework's hot operations labeled via
trace annotations so a capture reads as "device_flush / device_gc /
device_read / gate_fixpoint", not anonymous XLA modules.

Usage:
    with tracing.profile("/tmp/trace"):        # capture a window
        ... run traffic ...

    db.start_profiling("/tmp/trace")           # or explicit start/stop
    db.stop_profiling()

Annotations are no-ops outside an active capture (TraceAnnotation is
cheap), so they stay on permanently in the hot paths
(antidote_tpu/mat/device_plane.py, antidote_tpu/interdc/dep.py).
"""

from __future__ import annotations

import contextlib
import threading

_lock = threading.Lock()
_active_dir: str | None = None


def annotate(name: str):
    """Context manager labeling the enclosed host+device work in a
    profiler capture; no-op cost when no capture is active."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a JAX profiler trace of the enclosed block into
    ``log_dir`` (inspect with TensorBoard's profile plugin / XProf)."""
    start(log_dir)
    try:
        yield log_dir
    finally:
        stop()


def start(log_dir: str) -> None:
    """Begin a capture (idempotent per process: one capture at a time,
    mirroring jax.profiler's own constraint)."""
    global _active_dir
    import jax

    with _lock:
        if _active_dir is not None:
            raise RuntimeError(
                f"profiler already capturing to {_active_dir}")
        jax.profiler.start_trace(log_dir)
        _active_dir = log_dir


def stop() -> str:
    """End the capture; returns the trace directory."""
    global _active_dir
    import jax

    with _lock:
        if _active_dir is None:
            raise RuntimeError("no profiler capture active")
        jax.profiler.stop_trace()
        out, _active_dir = _active_dir, None
        return out


def active_dir() -> str | None:
    return _active_dir

"""Safe binary term codec for the inter-DC wire.

The reference ships Erlang external term format over ZeroMQ
(term_to_binary, reference src/inter_dc_txn.erl:95-105) — safe because
binary_to_term of data terms executes nothing.  The Python analogue
pickle is NOT safe (a malicious peer DC frame would be remote code
execution), so everything that crosses a DC boundary — txn frames, log
records, query requests/responses — uses this explicit tagged codec
instead: data in, data out, nothing executable.

Supported terms: None, bool, int (arbitrary precision), float, bytes,
str, tuple, list, dict, set, frozenset, VC, OpId, LogRecord, InterDcTxn
— exact round-trip (a frozenset decodes as a frozenset, a VC as a VC),
which matters because CRDT effects embed these types structurally.

Wire safety limits: frames cap at MAX_TERM_BYTES and nesting at
MAX_DEPTH so a hostile frame cannot commit the decoder to unbounded
work before the gap-repair layer even sees it.
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.oplog.records import LogRecord, OpId

MAX_TERM_BYTES = 64 * 1024 * 1024
MAX_DEPTH = 64

_T_NONE = b"N"
_T_TRUE = b"T"
_T_FALSE = b"F"
_T_INT = b"i"      # length-prefixed signed big-endian (arbitrary precision)
_T_FLOAT = b"f"    # IEEE double
_T_BYTES = b"b"
_T_STR = b"s"
_T_TUPLE = b"t"
_T_LIST = b"l"
_T_SET = b"e"
_T_FROZENSET = b"z"
_T_DICT = b"d"
_T_VC = b"V"
_T_OPID = b"O"
_T_RECORD = b"R"
_T_TXN = b"X"


class TermDecodeError(ValueError):
    """Malformed or hostile term frame."""


def encode(v: Any) -> bytes:
    out: List[bytes] = []
    _enc(v, out, 0)
    return b"".join(out)


def _u32(n: int) -> bytes:
    return struct.pack(">I", n)


def _enc(v: Any, out: List[bytes], depth: int) -> None:
    if depth > MAX_DEPTH:
        raise ValueError("term nesting too deep to encode")
    # exact-type dispatch where subclassing matters (VC is a dict, bool
    # is an int): check the special cases first
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, VC):
        out.append(_T_VC)
        _enc_seq(sorted(v.items(), key=lambda kv: repr(kv[0])), out, depth)
    elif isinstance(v, int):
        raw = v.to_bytes((v.bit_length() + 8) // 8 or 1, "big", signed=True)
        out.append(_T_INT + _u32(len(raw)) + raw)
    elif isinstance(v, float):
        out.append(_T_FLOAT + struct.pack(">d", v))
    elif isinstance(v, bytes):
        out.append(_T_BYTES + _u32(len(v)) + v)
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out.append(_T_STR + _u32(len(raw)) + raw)
    elif isinstance(v, OpId):
        out.append(_T_OPID)
        _enc_seq((v.dc, v.n), out, depth)
    elif isinstance(v, LogRecord):
        out.append(_T_RECORD)
        _enc_seq((v.op_id, v.txid, v.payload), out, depth)
    elif type(v).__name__ == "InterDcTxn":
        out.append(_T_TXN)
        _enc_seq((v.dc_id, v.partition, v.prev_log_opid, v.snapshot_vc,
                  v.timestamp, tuple(v.records)), out, depth)
    elif isinstance(v, tuple):
        out.append(_T_TUPLE)
        _enc_seq(v, out, depth)
    elif isinstance(v, list):
        out.append(_T_LIST)
        _enc_seq(v, out, depth)
    elif isinstance(v, frozenset):
        out.append(_T_FROZENSET)
        _enc_seq(sorted(v, key=repr), out, depth)
    elif isinstance(v, set):
        out.append(_T_SET)
        _enc_seq(sorted(v, key=repr), out, depth)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        _enc_seq([x for kv in sorted(v.items(), key=lambda kv: repr(kv[0]))
                  for x in kv], out, depth)
    else:
        raise TypeError(
            f"cannot encode {type(v).__name__} for the inter-DC wire")


def _enc_seq(items, out: List[bytes], depth: int) -> None:
    items = list(items)
    out.append(_u32(len(items)))
    for item in items:
        _enc(item, out, depth + 1)


def decode(data: bytes) -> Any:
    if len(data) > MAX_TERM_BYTES:
        raise TermDecodeError("term frame exceeds size cap")
    v, pos = _dec(data, 0, 0)
    if pos != len(data):
        raise TermDecodeError("trailing bytes after term")
    return v


def _need(data: bytes, pos: int, n: int) -> None:
    if pos + n > len(data):
        raise TermDecodeError("truncated term")


def _dec(data: bytes, pos: int, depth: int) -> Tuple[Any, int]:
    if depth > MAX_DEPTH:
        raise TermDecodeError("term nesting too deep")
    _need(data, pos, 1)
    tag = data[pos:pos + 1]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_FLOAT:
        _need(data, pos, 8)
        return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
    if tag in (_T_INT, _T_BYTES, _T_STR):
        _need(data, pos, 4)
        (n,) = struct.unpack(">I", data[pos:pos + 4])
        pos += 4
        _need(data, pos, n)
        raw = data[pos:pos + n]
        pos += n
        if tag == _T_INT:
            return int.from_bytes(raw, "big", signed=True), pos
        if tag == _T_BYTES:
            return bytes(raw), pos
        try:
            return raw.decode("utf-8"), pos
        except UnicodeDecodeError as e:
            raise TermDecodeError("bad utf-8 in str term") from e
    if tag in (_T_TUPLE, _T_LIST, _T_SET, _T_FROZENSET, _T_DICT,
               _T_VC, _T_OPID, _T_RECORD, _T_TXN):
        _need(data, pos, 4)
        (n,) = struct.unpack(">I", data[pos:pos + 4])
        pos += 4
        if n > len(data) - pos:  # each item needs >= 1 byte
            raise TermDecodeError("sequence length exceeds frame")
        items = []
        for _ in range(n):
            item, pos = _dec(data, pos, depth + 1)
            items.append(item)
        if tag == _T_TUPLE:
            return tuple(items), pos
        if tag == _T_LIST:
            return items, pos
        if tag == _T_SET:
            return set(items), pos
        if tag == _T_FROZENSET:
            return frozenset(items), pos
        if tag == _T_DICT:
            if n % 2:
                raise TermDecodeError("odd dict item count")
            return {items[i]: items[i + 1] for i in range(0, n, 2)}, pos
        if tag == _T_VC:
            if any(not (isinstance(kv, tuple) and len(kv) == 2
                        and isinstance(kv[1], int)) for kv in items):
                raise TermDecodeError("bad VC entry")
            return VC({k: v for k, v in items}), pos
        if tag == _T_OPID:
            if n != 2 or not isinstance(items[1], int):
                raise TermDecodeError("bad OpId shape")
            return OpId(items[0], items[1]), pos
        if tag == _T_RECORD:
            if n != 3 or not isinstance(items[0], OpId) \
                    or not isinstance(items[2], tuple):
                raise TermDecodeError("bad LogRecord shape")
            return LogRecord(items[0], items[1], items[2]), pos
        # _T_TXN
        from antidote_tpu.interdc.wire import InterDcTxn

        if n != 6:
            raise TermDecodeError("bad InterDcTxn arity")
        dc_id, partition, prev, svc, ts, records = items
        if svc is not None and not isinstance(svc, VC):
            raise TermDecodeError("bad snapshot_vc")
        if not (isinstance(partition, int) and isinstance(prev, int)
                and isinstance(ts, int)):
            raise TermDecodeError("bad InterDcTxn field types")
        if not isinstance(records, (tuple, list)) or any(
                not isinstance(r, LogRecord) for r in records):
            raise TermDecodeError("bad records")
        return InterDcTxn(dc_id=dc_id, partition=partition,
                          prev_log_opid=prev, snapshot_vc=svc,
                          timestamp=ts, records=list(records)), pos
    raise TermDecodeError(f"unknown term tag {tag!r}")

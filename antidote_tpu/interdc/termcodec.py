"""Safe binary term codec for the inter-DC wire.

The reference ships Erlang external term format over ZeroMQ
(term_to_binary, reference src/inter_dc_txn.erl:95-105) — safe because
binary_to_term of data terms executes nothing.  The Python analogue
pickle is NOT safe (a malicious peer DC frame would be remote code
execution), so everything that crosses a DC boundary — txn frames, log
records, query requests/responses — uses this explicit tagged codec
instead: data in, data out, nothing executable.

Supported terms: None, bool, int (arbitrary precision), float, bytes,
str, tuple, list, dict, set, frozenset, VC, OpId, LogRecord, InterDcTxn,
InterDcBatch — exact round-trip (a frozenset decodes as a frozenset, a
VC as a VC), which matters because CRDT effects embed these types
structurally.

Wire economy (ISSUE 6): ints carry single-byte payload tags for the
common widths (a µs timestamp used to cost 5 bytes of length framing on
top of its magnitude; now 1 tag + 8 bytes, and small counters 1 tag + 1
byte), and VC encodings are memoized per frame — a transaction's commit
VC appears at least twice per legacy frame (the txn header and the
trailing commit record) and dozens of times across a batch frame, so
every repeat after the first collapses to a 5-byte back-reference.
Exact round-trip semantics are unchanged; references decode to fresh VC
copies (VCs are mutable dicts — decoded structures must not alias).

The batch frame (``InterDcBatch``) is columnar: uniform int64 columns
(op ids, commit times) as raw packed bytes, one interned type-name
table, and per-txn irregular fields (keys, effects, txids, snapshot
VCs) through the memoizing term encoder — the layout mirrors the ingest
plane's packed rows (antidote_tpu/mat/ingest.py) where one upload
carries many ops' uniform columns.

Wire safety limits: frames cap at MAX_TERM_BYTES and nesting at
MAX_DEPTH so a hostile frame cannot commit the decoder to unbounded
work before the gap-repair layer even sees it.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.oplog.records import LogRecord, OpId

MAX_TERM_BYTES = 64 * 1024 * 1024
MAX_DEPTH = 64

_T_NONE = b"N"
_T_TRUE = b"T"
_T_FALSE = b"F"
_T_INT = b"i"      # length-prefixed signed big-endian (arbitrary precision)
_T_INT1 = b"1"     # signed 1-byte int (small counters, column indices)
_T_INT8 = b"8"     # signed 8-byte big-endian int (timestamps, op ids)
_T_FLOAT = b"f"    # IEEE double
_T_BYTES = b"b"
_T_BYTES1 = b"C"   # bytes with 1-byte length
_T_STR = b"s"
_T_STR1 = b"S"     # str with 1-byte length
_T_STRREF1 = b"r"  # 1-byte back-reference to a str already in this frame
_T_STRREF = b"Q"   # u32 back-reference (frames with >256 distinct strs)
_T_TUPLE = b"t"
_T_TUPLE1 = b"u"   # tuple with 1-byte count
_T_LIST = b"l"
_T_SET = b"e"
_T_FROZENSET = b"z"
_T_DICT = b"d"
_T_VC = b"V"
_T_VCREF = b"v"    # back-reference to a VC already in this frame
_T_OPID = b"O"
_T_RECORD = b"R"
_T_TXN = b"X"
_T_BATCH = b"Y"

#: strings shorter than this are cheaper inline than as a memo entry
_STR_MEMO_MIN = 2


class TermDecodeError(ValueError):
    """Malformed or hostile term frame."""


class _EncCtx:
    """Per-frame encoder state: the VC and string memos
    (key -> emission index).

    VC index assignment is post-order (a VC registers after its
    contents encode); strings are leaves, so theirs is emission order —
    each matching the decoder's append order exactly.
    """

    __slots__ = ("vc_memo", "str_memo")

    def __init__(self):
        self.vc_memo: Dict[Tuple, int] = {}
        self.str_memo: Dict[str, int] = {}


def encode(v: Any) -> bytes:
    out: List[bytes] = []
    _enc(v, out, 0, _EncCtx())
    return b"".join(out)


def _u32(n: int) -> bytes:
    return struct.pack(">I", n)


def _vc_key(v: VC):
    return tuple(sorted(v.items(), key=lambda kv: repr(kv[0])))


def _enc_int(v: int, out: List[bytes]) -> None:
    if -128 <= v <= 127:
        out.append(_T_INT1 + struct.pack(">b", v))
    elif -(2 ** 63) <= v < 2 ** 63:
        out.append(_T_INT8 + struct.pack(">q", v))
    else:
        raw = v.to_bytes((v.bit_length() + 8) // 8 or 1, "big", signed=True)
        out.append(_T_INT + _u32(len(raw)) + raw)


def _enc(v: Any, out: List[bytes], depth: int, ctx: _EncCtx) -> None:
    if depth > MAX_DEPTH:
        raise ValueError("term nesting too deep to encode")
    # exact-type dispatch where subclassing matters (VC is a dict, bool
    # is an int): check the special cases first
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, VC):
        key = _vc_key(v)
        ref = ctx.vc_memo.get(key)
        if ref is not None:
            out.append(_T_VCREF + _u32(ref))
            return
        out.append(_T_VC)
        _enc_seq(sorted(v.items(), key=lambda kv: repr(kv[0])), out,
                 depth, ctx)
        ctx.vc_memo[key] = len(ctx.vc_memo)
    elif isinstance(v, int):
        _enc_int(v, out)
    elif isinstance(v, float):
        out.append(_T_FLOAT + struct.pack(">d", v))
    elif isinstance(v, bytes):
        if len(v) < 256:
            out.append(_T_BYTES1 + bytes((len(v),)) + v)
        else:
            out.append(_T_BYTES + _u32(len(v)) + v)
    elif isinstance(v, str):
        ref = ctx.str_memo.get(v)
        if ref is not None:
            if ref < 256:
                out.append(_T_STRREF1 + bytes((ref,)))
            else:
                out.append(_T_STRREF + _u32(ref))
            return
        raw = v.encode("utf-8")
        if len(raw) < 256:
            out.append(_T_STR1 + bytes((len(raw),)) + raw)
        else:
            out.append(_T_STR + _u32(len(raw)) + raw)
        if len(v) >= _STR_MEMO_MIN:
            ctx.str_memo[v] = len(ctx.str_memo)
    elif isinstance(v, OpId):
        out.append(_T_OPID)
        _enc_seq((v.dc, v.n), out, depth, ctx)
    elif isinstance(v, LogRecord):
        out.append(_T_RECORD)
        _enc_seq((v.op_id, v.txid, v.payload), out, depth, ctx)
    elif type(v).__name__ == "InterDcTxn":
        out.append(_T_TXN)
        # trace_ctx (ISSUE 7) rides as a 7th element only when present,
        # so pre-ISSUE-7 frames (and hand-built txns) keep the 6-arity
        # form byte-for-byte; the decoder accepts both
        fields = (v.dc_id, v.partition, v.prev_log_opid, v.snapshot_vc,
                  v.timestamp, tuple(v.records))
        if getattr(v, "trace_ctx", None) is not None:
            fields = fields + (tuple(v.trace_ctx),)
        _enc_seq(fields, out, depth, ctx)
    elif type(v).__name__ == "InterDcBatch":
        _enc_batch(v, out, depth, ctx)
    elif isinstance(v, tuple):
        if len(v) < 256:
            out.append(_T_TUPLE1 + bytes((len(v),)))
            for item in v:
                _enc(item, out, depth + 1, ctx)
        else:
            out.append(_T_TUPLE)
            _enc_seq(v, out, depth, ctx)
    elif isinstance(v, list):
        out.append(_T_LIST)
        _enc_seq(v, out, depth, ctx)
    elif isinstance(v, frozenset):
        out.append(_T_FROZENSET)
        _enc_seq(sorted(v, key=repr), out, depth, ctx)
    elif isinstance(v, set):
        out.append(_T_SET)
        _enc_seq(sorted(v, key=repr), out, depth, ctx)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        _enc_seq([x for kv in sorted(v.items(), key=lambda kv: repr(kv[0]))
                  for x in kv], out, depth, ctx)
    else:
        raise TypeError(
            f"cannot encode {type(v).__name__} for the inter-DC wire")


def _enc_seq(items, out: List[bytes], depth: int, ctx: _EncCtx) -> None:
    items = list(items)
    out.append(_u32(len(items)))
    for item in items:
        _enc(item, out, depth + 1, ctx)


# ---------------------------------------------------------------------------
# batch frame (ISSUE 6): columnar packed layout
#
# One frame carries a contiguous run of committed txns from one
# (origin DC, partition) stream plus an optional piggybacked heartbeat.
# Uniform per-txn and per-update quantities go out as raw packed int64
# columns (like the ingest plane's packed rows); repeated strings (type
# names) intern into one table; irregular leaves (keys, effects, txids,
# snapshot VCs) ride the memoizing term encoder, so a VC repeated
# across the batch costs 5 bytes after its first appearance.

def _enc_varint(z: int, b: bytearray) -> None:
    while True:
        byte = z & 0x7F
        z >>= 7
        if z:
            b.append(byte | 0x80)
        else:
            b.append(byte)
            return


def _varint_col(vals) -> bytes:
    """Delta-from-previous, zigzag, LEB128 — opid and commit-time
    columns are near-monotone, so a txn's entry is typically 1-3 bytes
    instead of a fixed 8."""
    b = bytearray()
    prev = 0
    for v in vals:
        d = v - prev
        prev = v
        _enc_varint(d * 2 if d >= 0 else -d * 2 - 1, b)
    return bytes(b)


def _dec_varint(data: bytes, pos: int) -> Tuple[int, int]:
    """One zigzag LEB128 value."""
    z = 0
    shift = 0
    while True:
        _need(data, pos, 1)
        byte = data[pos]
        pos += 1
        z |= (byte & 0x7F) << shift
        shift += 7
        if not byte & 0x80:
            break
        if shift > 70:
            raise TermDecodeError("varint overlong")
    return (z >> 1 if not z & 1 else -((z + 1) >> 1)), pos


def _dec_varint_col(data: bytes, pos: int, n: int,
                    lo=-(2 ** 63), hi=2 ** 63 - 1):
    vals = []
    prev = 0
    for _ in range(n):
        d, pos = _dec_varint(data, pos)
        prev += d
        if not lo <= prev <= hi:
            raise TermDecodeError("varint column out of range")
        vals.append(prev)
    return vals, pos


#: VC-row sentinels: 254 = same entries as the previous txn's row,
#: 255 = irregular row (falls back to the general term encoder)
_VCROW_SAME = 254
_VCROW_TERM = 255


def _enc_batch(b, out: List[bytes], depth: int, ctx: _EncCtx) -> None:
    txns = b.txns()
    if not txns:
        raise ValueError("empty InterDcBatch (pings ship standalone)")
    out.append(_T_BATCH)
    _enc(b.dc_id, out, depth + 1, ctx)
    _enc(b.partition, out, depth + 1, ctx)
    _enc(txns[0].prev_log_opid, out, depth + 1, ctx)
    _enc(b.ping_ts, out, depth + 1, ctx)
    # per-frame trace header (ISSUE 7): (sample permille, ship wall µs)
    # or None — a small term, not a column (uniform across the frame)
    hdr = getattr(b, "trace_hdr", None)
    _enc(tuple(hdr) if hdr is not None else None, out, depth + 1, ctx)
    n = len(txns)
    out.append(_u32(n))
    # uniform per-txn columns (varint delta: near-monotone sequences)
    out.append(_varint_col([t.records[-1].op_id.n for t in txns]))
    out.append(_varint_col([t.timestamp for t in txns]))
    # origin-commit wallclock column (ISSUE 7): near-monotone like the
    # commit times, so a txn's entry is 1-3 bytes; 0 marks "absent"
    # (hand-built txns without a trace context)
    out.append(_varint_col(
        [(t.trace_ctx[0] if getattr(t, "trace_ctx", None) else 0)
         for t in txns]))
    out.append(_varint_col([len(t.records) - 1 for t in txns]))
    # commit-record arity/flag: 0/1 = 4-tuple certified flag, 2 = the
    # legacy 3-tuple payload (no flag) — preserved bit-for-bit
    cert = bytearray()
    for t in txns:
        payload = t.records[-1].payload
        cert.append(2 if len(payload) < 4 else (1 if payload[3] else 0))
    out.append(bytes(cert))
    # snapshot VCs as a columnar section: one interned dc-id table for
    # the whole batch, then per txn a row of (dc index, i64) entries —
    # a repeat of the previous row is one byte, an irregular clock
    # falls back to the general (still VC-memoized) term encoder
    dc_table: List = []
    dc_idx: Dict = {}
    rows: List = []
    for t in txns:
        svc = t.snapshot_vc
        if not isinstance(svc, VC) or len(svc) > 253:
            rows.append(None)
            continue
        entries = sorted(svc.items(), key=lambda kv: repr(kv[0]))
        if any(not isinstance(ts, int)
               or not -(2 ** 63) <= ts < 2 ** 63 for _dc, ts in entries):
            rows.append(None)
            continue
        for dc, _ts in entries:
            if dc not in dc_idx:
                dc_idx[dc] = len(dc_table)
                dc_table.append(dc)
        rows.append(entries)
    if len(dc_table) > 253:
        dc_table, rows = [], [None] * n  # degenerate: all irregular
    out.append(_T_LIST)
    _enc_seq(dc_table, out, depth, ctx)
    prev_row = object()
    last_ts: Dict[int, int] = {}  # dc column -> last emitted value
    for t, row in zip(txns, rows):
        if row is None:
            out.append(bytes((_VCROW_TERM,)))
            _enc(t.snapshot_vc, out, depth + 1, ctx)
        elif prev_row is not None and row == prev_row:
            out.append(bytes((_VCROW_SAME,)))
        else:
            out.append(bytes((len(row),)))
            out.append(bytes(dc_idx[dc] for dc, _ts in row))
            # per-column delta varints: a steady stream's clock entries
            # creep, so a row is a few bytes instead of 8 per entry
            vb = bytearray()
            for dc, ts in row:
                c = dc_idx[dc]
                d = ts - last_ts.get(c, 0)
                last_ts[c] = ts
                _enc_varint(d * 2 if d >= 0 else -d * 2 - 1, vb)
            out.append(bytes(vb))
        prev_row = row
    # remaining irregular per-txn fields
    for t in txns:
        _enc(t.records[-1].txid, out, depth + 1, ctx)
        # commit payload's (dc, time) dc is the origin for every txn a
        # sender ships; None marks that common case
        cdc = t.records[-1].payload[1][0]
        _enc(None if cdc == b.dc_id else cdc, out, depth + 1, ctx)
    # flattened update-record columns
    ups = [r for t in txns for r in t.records[:-1]]
    out.append(_u32(len(ups)))
    out.append(_varint_col([r.op_id.n for r in ups]))
    # interned type-name table + per-update single-byte/uint32 indices
    table: Dict[str, int] = {}
    idx = []
    for r in ups:
        tname = r.payload[2]
        if tname not in table:
            table[tname] = len(table)
        idx.append(table[tname])
    out.append(_T_LIST)
    _enc_seq(list(table), out, depth, ctx)
    if len(table) <= 256:
        out.append(b"\x01" + bytes(idx))
    else:
        out.append(b"\x04" + struct.pack(f">{len(idx)}I", *idx))
    for r in ups:
        _enc(r.payload[1], out, depth + 1, ctx)   # key
        _enc(r.payload[3], out, depth + 1, ctx)   # effect


def batch_packable(txn) -> bool:
    """Whether a txn fits the batch frame's columnar contract: update
    records then one commit, every op id on the origin's stream, one
    txid, int64-range op ids and commit time.  Locally-committed txns
    always do; the check guards hand-built frames so the ship worker
    can fall back to a legacy per-txn frame instead of corrupting a
    batch."""
    if txn.is_ping() or not txn.records:
        return False
    commit = txn.records[-1]
    # commit payload: exactly the 3/4-tuple shapes the decoder
    # rebuilds, a 2-tuple (dc, time) pair, a real bool flag; a None
    # commit dc only round-trips when it IS the origin (the encoder's
    # None marks "same as origin")
    if commit.kind() != "commit" or len(commit.payload) not in (3, 4) \
            or not (isinstance(commit.payload[1], tuple)
                    and len(commit.payload[1]) == 2):
        return False
    if len(commit.payload) == 4 and not isinstance(commit.payload[3],
                                                   bool):
        return False
    if commit.payload[1][0] is None and txn.dc_id is not None:
        return False
    txid = commit.txid
    i64 = -(2 ** 63), 2 ** 63 - 1
    for r in txn.records:
        if r.op_id.dc != txn.dc_id or r.txid != txid \
                or not isinstance(r.op_id.n, int) \
                or not i64[0] <= r.op_id.n <= i64[1]:
            return False
        if r is not commit and (r.kind() != "update"
                                or len(r.payload) != 4
                                or not isinstance(r.payload[2], str)):
            return False
    # the batch carries the commit VC/time ONCE per txn: the header
    # fields must be the commit record's own (always true via from_ops)
    return isinstance(txn.timestamp, int) \
        and i64[0] <= txn.timestamp <= i64[1] \
        and commit.payload[1][1] == txn.timestamp \
        and commit.payload[2] == txn.snapshot_vc


def _check_trace_pair(pair, permille_idx: int, what: str) -> None:
    """Validate a decoded wire trace pair (ISSUE 7): two ints, wall
    µs >= 0, sample permille in 0..1000.  The sender clamps permille
    on encode (sender._trace_permille); without the matching decode
    check a hostile frame carrying permille >= 1000 would make the
    receiver force-adopt EVERY txn it carries into the span ring,
    evicting legitimately sampled trees."""
    if not (isinstance(pair, tuple) and len(pair) == 2
            and all(isinstance(x, int) for x in pair)):
        raise TermDecodeError(f"bad {what}")
    if not 0 <= pair[permille_idx] <= 1000 \
            or pair[1 - permille_idx] < 0:
        raise TermDecodeError(f"{what} out of range")


class _DecCtx:
    """Per-frame decoder memo state, mirroring :class:`_EncCtx`."""

    __slots__ = ("vcs", "strs")

    def __init__(self):
        self.vcs: List[VC] = []
        self.strs: List[str] = []


def decode(data: bytes) -> Any:
    if len(data) > MAX_TERM_BYTES:
        raise TermDecodeError("term frame exceeds size cap")
    v, pos = _dec(data, 0, 0, _DecCtx())
    if pos != len(data):
        raise TermDecodeError("trailing bytes after term")
    return v


def _need(data: bytes, pos: int, n: int) -> None:
    if pos + n > len(data):
        raise TermDecodeError("truncated term")


def _dec_u32(data: bytes, pos: int) -> Tuple[int, int]:
    _need(data, pos, 4)
    return struct.unpack(">I", data[pos:pos + 4])[0], pos + 4


def _dec(data: bytes, pos: int, depth: int,
         ctx: _DecCtx) -> Tuple[Any, int]:
    if depth > MAX_DEPTH:
        raise TermDecodeError("term nesting too deep")
    _need(data, pos, 1)
    tag = data[pos:pos + 1]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT1:
        _need(data, pos, 1)
        return struct.unpack(">b", data[pos:pos + 1])[0], pos + 1
    if tag == _T_INT8:
        _need(data, pos, 8)
        return struct.unpack(">q", data[pos:pos + 8])[0], pos + 8
    if tag == _T_FLOAT:
        _need(data, pos, 8)
        return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
    if tag == _T_VCREF:
        ref, pos = _dec_u32(data, pos)
        if ref >= len(ctx.vcs):
            raise TermDecodeError("VC back-reference out of range")
        # a fresh copy: VCs are mutable dicts, decoded structures must
        # not alias one another through the memo
        return VC(ctx.vcs[ref]), pos
    if tag in (_T_STRREF1, _T_STRREF):
        if tag == _T_STRREF1:
            _need(data, pos, 1)
            ref = data[pos]
            pos += 1
        else:
            ref, pos = _dec_u32(data, pos)
        if ref >= len(ctx.strs):
            raise TermDecodeError("str back-reference out of range")
        return ctx.strs[ref], pos
    if tag == _T_BATCH:
        return _dec_batch(data, pos, depth, ctx)
    if tag in (_T_INT, _T_BYTES, _T_STR, _T_BYTES1, _T_STR1):
        if tag in (_T_BYTES1, _T_STR1):
            _need(data, pos, 1)
            n = data[pos]
            pos += 1
        else:
            n, pos = _dec_u32(data, pos)
        _need(data, pos, n)
        raw = data[pos:pos + n]
        pos += n
        if tag == _T_INT:
            return int.from_bytes(raw, "big", signed=True), pos
        if tag in (_T_BYTES, _T_BYTES1):
            return bytes(raw), pos
        try:
            s = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise TermDecodeError("bad utf-8 in str term") from e
        if len(s) >= _STR_MEMO_MIN:
            ctx.strs.append(s)
        return s, pos
    if tag in (_T_TUPLE, _T_TUPLE1, _T_LIST, _T_SET, _T_FROZENSET,
               _T_DICT, _T_VC, _T_OPID, _T_RECORD, _T_TXN):
        if tag == _T_TUPLE1:
            _need(data, pos, 1)
            n = data[pos]
            pos += 1
        else:
            n, pos = _dec_u32(data, pos)
        if n > len(data) - pos:  # each item needs >= 1 byte
            raise TermDecodeError("sequence length exceeds frame")
        items = []
        for _ in range(n):
            item, pos = _dec(data, pos, depth + 1, ctx)
            items.append(item)
        if tag in (_T_TUPLE, _T_TUPLE1):
            return tuple(items), pos
        if tag == _T_LIST:
            return items, pos
        if tag == _T_SET:
            return set(items), pos
        if tag == _T_FROZENSET:
            return frozenset(items), pos
        if tag == _T_DICT:
            if n % 2:
                raise TermDecodeError("odd dict item count")
            return {items[i]: items[i + 1] for i in range(0, n, 2)}, pos
        if tag == _T_VC:
            if any(not (isinstance(kv, tuple) and len(kv) == 2
                        and isinstance(kv[1], int)) for kv in items):
                raise TermDecodeError("bad VC entry")
            vc = VC({k: v for k, v in items})
            ctx.vcs.append(vc)
            return vc, pos
        if tag == _T_OPID:
            if n != 2 or not isinstance(items[1], int):
                raise TermDecodeError("bad OpId shape")
            return OpId(items[0], items[1]), pos
        if tag == _T_RECORD:
            if n != 3 or not isinstance(items[0], OpId) \
                    or not isinstance(items[2], tuple):
                raise TermDecodeError("bad LogRecord shape")
            return LogRecord(items[0], items[1], items[2]), pos
        # _T_TXN (6-arity pre-ISSUE-7 form, or 7 with a trace_ctx)
        from antidote_tpu.interdc.wire import InterDcTxn

        if n not in (6, 7):
            raise TermDecodeError("bad InterDcTxn arity")
        dc_id, partition, prev, svc, ts, records = items[:6]
        trace_ctx = items[6] if n == 7 else None
        if svc is not None and not isinstance(svc, VC):
            raise TermDecodeError("bad snapshot_vc")
        if not (isinstance(partition, int) and isinstance(prev, int)
                and isinstance(ts, int)):
            raise TermDecodeError("bad InterDcTxn field types")
        if not isinstance(records, (tuple, list)) or any(
                not isinstance(r, LogRecord) for r in records):
            raise TermDecodeError("bad records")
        if trace_ctx is not None:
            _check_trace_pair(trace_ctx, permille_idx=1,
                              what="InterDcTxn trace_ctx")
        return InterDcTxn(dc_id=dc_id, partition=partition,
                          prev_log_opid=prev, snapshot_vc=svc,
                          timestamp=ts, records=list(records),
                          trace_ctx=trace_ctx), pos
    raise TermDecodeError(f"unknown term tag {tag!r}")


def _dec_batch(data: bytes, pos: int, depth: int,
               ctx: _DecCtx) -> Tuple[Any, int]:
    from antidote_tpu.interdc.wire import InterDcBatch, InterDcTxn

    dc_id, pos = _dec(data, pos, depth + 1, ctx)
    partition, pos = _dec(data, pos, depth + 1, ctx)
    first_prev, pos = _dec(data, pos, depth + 1, ctx)
    ping_ts, pos = _dec(data, pos, depth + 1, ctx)
    # pre-ISSUE-7 layout detection (rolling-upgrade compat): the old
    # frame goes straight from ping_ts to the u32 txn count, whose
    # high byte is <= 3 (frames cap at 64 MiB); every term tag the new
    # trace-header position can legally start with is printable ASCII.
    # An unupgraded peer's batches must keep decoding — dropping them
    # as malformed would force its whole stream through per-txn gap
    # repair until both sides upgrade.
    _need(data, pos, 1)
    pre_issue7 = data[pos] <= 3
    if pre_issue7:
        trace_hdr = None
    else:
        trace_hdr, pos = _dec(data, pos, depth + 1, ctx)
    if not isinstance(partition, int) or not isinstance(first_prev, int) \
            or not (ping_ts is None or isinstance(ping_ts, int)):
        raise TermDecodeError("bad InterDcBatch header")
    if trace_hdr is not None:
        _check_trace_pair(trace_hdr, permille_idx=0,
                          what="InterDcBatch trace header")
    n, pos = _dec_u32(data, pos)
    if n == 0 or n > len(data) - pos:
        raise TermDecodeError("bad batch txn count")
    commit_ops, pos = _dec_varint_col(data, pos, n)
    commit_ts, pos = _dec_varint_col(data, pos, n)
    if pre_issue7:
        commit_wall = [0] * n  # no wall column: trace_ctx stays None
    else:
        commit_wall, pos = _dec_varint_col(data, pos, n, lo=0)
    n_ups_col, pos = _dec_varint_col(data, pos, n, lo=0, hi=len(data))
    _need(data, pos, n)
    cert_col = data[pos:pos + n]
    pos += n
    if any(c > 2 for c in cert_col):
        raise TermDecodeError("bad batch certified flag")
    # columnar snapshot-VC section
    dc_table, pos = _dec(data, pos, depth, ctx)
    if not isinstance(dc_table, list) or len(dc_table) > 253:
        raise TermDecodeError("bad batch VC dc table")
    svcs: List = []
    last_ts: Dict[int, int] = {}
    for _ in range(n):
        _need(data, pos, 1)
        k = data[pos]
        pos += 1
        if k == _VCROW_TERM:
            svc, pos = _dec(data, pos, depth + 1, ctx)
            if svc is not None and not isinstance(svc, VC):
                raise TermDecodeError("bad batch snapshot_vc")
        elif k == _VCROW_SAME:
            if not svcs:
                raise TermDecodeError("VC row backref before first row")
            svc = VC(svcs[-1]) if svcs[-1] is not None else None
        else:
            _need(data, pos, k)
            idxs = data[pos:pos + k]
            pos += k
            if any(i >= len(dc_table) for i in idxs):
                raise TermDecodeError("VC row dc index out of table")
            entries = {}
            for i in idxs:
                d, pos = _dec_varint(data, pos)
                v = last_ts.get(i, 0) + d
                if not -(2 ** 63) <= v < 2 ** 63:
                    raise TermDecodeError("VC row value out of range")
                last_ts[i] = v
                entries[dc_table[i]] = v
            svc = VC(entries)
            if len(svc) != k:
                raise TermDecodeError("duplicate dc in VC row")
        svcs.append(svc)
    txids, cdcs = [], []
    for _ in range(n):
        txid, pos = _dec(data, pos, depth + 1, ctx)
        cdc, pos = _dec(data, pos, depth + 1, ctx)
        txids.append(txid)
        cdcs.append(dc_id if cdc is None else cdc)
    m, pos = _dec_u32(data, pos)
    if m != sum(n_ups_col):
        raise TermDecodeError("batch update columns disagree")
    up_ops, pos = _dec_varint_col(data, pos, m)
    table, pos = _dec(data, pos, depth, ctx)
    if not isinstance(table, list) or any(not isinstance(s, str)
                                          for s in table):
        raise TermDecodeError("bad batch type-name table")
    _need(data, pos, 1)
    width = data[pos]
    pos += 1
    if width == 1:
        _need(data, pos, m)
        idx = tuple(data[pos:pos + m])
        pos += m
    elif width == 4:
        _need(data, pos, 4 * m)
        idx = struct.unpack(f">{m}I", data[pos:pos + 4 * m])
        pos += 4 * m
    else:
        raise TermDecodeError("bad batch type-index width")
    if any(i >= len(table) for i in idx):
        raise TermDecodeError("batch type index out of table")
    keys, effects = [], []
    for _ in range(m):
        key, pos = _dec(data, pos, depth + 1, ctx)
        eff, pos = _dec(data, pos, depth + 1, ctx)
        keys.append(key)
        effects.append(eff)
    txns = []
    prev = first_prev
    u = 0
    for i in range(n):
        records = []
        for _j in range(n_ups_col[i]):
            records.append(LogRecord(
                OpId(dc_id, up_ops[u]), txids[i],
                ("update", keys[u], table[idx[u]], effects[u])))
            u += 1
        if cert_col[i] == 2:
            payload = ("commit", (cdcs[i], commit_ts[i]), svcs[i])
        else:
            payload = ("commit", (cdcs[i], commit_ts[i]), svcs[i],
                       bool(cert_col[i]))
        records.append(LogRecord(OpId(dc_id, commit_ops[i]), txids[i],
                                 payload))
        # per-txn trace context rebuilt from the wall column + the
        # frame header's sample permille (0 wall = absent)
        tctx = None
        if commit_wall[i]:
            tctx = (commit_wall[i],
                    trace_hdr[0] if trace_hdr is not None else 0)
        txns.append(InterDcTxn(dc_id=dc_id, partition=partition,
                               prev_log_opid=prev, snapshot_vc=svcs[i],
                               timestamp=commit_ts[i], records=records,
                               trace_ctx=tctx))
        prev = commit_ops[i]
    return InterDcBatch(dc_id=dc_id, partition=partition, _txns=txns,
                        ping_ts=ping_ts, trace_hdr=trace_hdr), pos

"""Jitted kernels of the device-resident dependency-gate ring.

ISSUE 3: the batched gate path used to re-pack every queued txn into
fresh host arrays, upload six tensors, and fetch three back on EVERY
``process_queues`` call — worst-case repack cost per delivery.  These
kernels keep the gate state resident instead: a padded ring of
dependency rows that is appended to incrementally (one small H2D
scatter per batch of arrivals, ring buffers donated so the update is
in-place), retired/compacted in place, and driven by a fixpoint whose
only mandatory fetch is a scalar applied-count.

Ring layout (all arrays ``cap`` rows; ``d_pad`` dense clock columns):

- ``ss``     int64[cap, d_pad]  snapshot VC of each queued txn
- ``origin`` int32[cap]         dense column of the txn's origin DC
- ``pos``    int32[cap]         per-origin FIFO position (monotone)
- ``ts``     int64[cap]         commit timestamp (pings carry ts-1,
                                the exclusive-advance hardening —
                                interdc/dep.py module doc)
- ``ping``   bool[cap]
- ``live``   bool[cap]          slot holds a still-queued txn; dead
                                and never-used slots are inert in
                                every kernel (no sentinel rows needed)

Host-side slot bookkeeping (mirror queues, free list, column map)
lives in :class:`antidote_tpu.interdc.dep._DeviceRing`; these kernels
are pure array programs.  Every public entry point carries
``@kernel_span`` (tools/trace_lint.py now enforces the rule for
antidote_tpu/interdc/ as well as mat/).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu.clocks import dense
from antidote_tpu.obs.prof import kernel_span

#: FIFO-position infinity: larger than any real queue position, small
#: enough that +1 arithmetic cannot overflow int32
BIG_POS = np.int32(np.iinfo(np.int32).max // 2)


@kernel_span("interdc.dep")
@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def ring_append(ss, origin, pos, ts, ping, live,
                slots, u_ss, u_origin, u_pos, u_ts, u_ping):
    """Scatter a padded batch of arrivals into ring ``slots``.

    Update rows are padded to a power-of-two batch (bounding the jit
    cache); padding rows carry ``slots == cap`` which ``mode="drop"``
    discards.  The six ring buffers are donated — an append updates
    the resident state in place, no copy."""
    ss = ss.at[slots].set(u_ss, mode="drop")
    origin = origin.at[slots].set(u_origin, mode="drop")
    pos = pos.at[slots].set(u_pos, mode="drop")
    ts = ts.at[slots].set(u_ts, mode="drop")
    ping = ping.at[slots].set(u_ping, mode="drop")
    live = live.at[slots].set(True, mode="drop")
    return ss, origin, pos, ts, ping, live


@kernel_span("interdc.dep")
@partial(jax.jit, donate_argnums=(0,))
def ring_retire(live, slots):
    """Mark ``slots`` dead (txns popped outside the ring replay: the
    host walk ran in between, or a wave aborted on PartitionRetired).
    Padding slots carry ``cap`` and are dropped."""
    return live.at[slots].set(False, mode="drop")


@kernel_span("interdc.dep")
@partial(jax.jit, static_argnames=("new_d",))
def ring_gather(ss, origin, pos, ts, ping, idx, n_live, new_d):
    """Re-layout the ring through a device-side gather: grow capacity
    (``idx`` longer than the ring), shrink it (lazy compaction once
    dead slots exceed the threshold), or widen the clock domain
    (``new_d`` > current width; new columns read 0 = the dense
    missing-entry semantics).  ``idx[i]`` is the OLD slot written to
    new slot i; rows at or past ``n_live`` come out dead.  No H2D
    beyond the index vector itself — the resident rows never round-
    trip through the host."""
    if new_d > ss.shape[1]:
        ss = jnp.pad(ss, ((0, 0), (0, new_d - ss.shape[1])))
    ss = ss[idx]
    origin = origin[idx]
    pos = pos[idx]
    ts = ts[idx]
    ping = ping[idx]
    live = jnp.arange(idx.shape[0], dtype=jnp.int32) < n_live
    return ss, origin, pos, ts, ping, live


@kernel_span("interdc.dep")
@jax.jit
def ring_fixpoint(ss, origin, pos, ts, ping, live, pvc):
    """Iterate-until-stable over the LIVE ring rows — the same monotone
    cascade as :func:`antidote_tpu.interdc.dep.gate_fixpoint` (dominance
    test with the origin column zeroed, per-origin FIFO prefix,
    watermark + blocked-head ts-1 advance, reference
    src/inter_dc_dep_vnode.erl:96-154) with dead/unused slots gated out
    by ``live`` instead of sentinel rows.

    Returns ``(applied bool[cap], round int32[cap], final pvc int64[D],
    new_live bool[cap], applied_count int32)``.  The caller's only
    mandatory fetch is the scalar count; the dense mask and rounds are
    fetched once per admission wave, and ``new_live`` (= live minus the
    applied set) stays on device as the next resident live mask when
    the wave replays completely."""
    d = pvc.shape[0]
    n = ss.shape[0]
    big = jnp.asarray(np.iinfo(np.int32).max, jnp.int32)

    def round_(pvc):
        deps = dense.set_dc(ss, origin, 0)
        ready = live & (ping | dense.ge(pvc, deps))          # [N]
        # dead rows neither block (pos -> +inf) nor advance anything
        notready_pos = jnp.where(ready | ~live, big, pos)
        blocked_min = jnp.full((d,), big, jnp.int32).at[origin].min(
            notready_pos, mode="drop")
        applied = ready & (pos < blocked_min[origin])
        wm = jnp.zeros((d,), ts.dtype).at[origin].max(
            jnp.where(applied, ts, 0), mode="drop")
        # blocked-head rule (reference src/inter_dc_dep_vnode.erl:
        # 137-143): a live head that cannot apply still advances its
        # origin's clock to ts-1 — FIFO + gap repair mean the origin's
        # stream is complete below it
        head_blocked = live & (~ready) & (pos == blocked_min[origin])
        hb = jnp.zeros((d,), ts.dtype).at[origin].max(
            jnp.where(head_blocked, ts - 1, 0), mode="drop")
        return applied, jnp.maximum(pvc, jnp.maximum(wm, hb))

    def note_round(rounds, applied, r):
        newly = applied & (rounds < 0)
        return jnp.where(newly, r, rounds)

    def cond(carry):
        _, _, _, changed = carry
        return changed

    def body(carry):
        rounds, pvc, r, _ = carry
        applied, new_pvc = round_(pvc)
        rounds = note_round(rounds, applied, r)
        return (rounds, new_pvc, r + 1, jnp.any(new_pvc != pvc))

    rounds0 = jnp.full((n,), -1, jnp.int32)
    rounds, pvc, r, _ = jax.lax.while_loop(
        cond, body,
        (rounds0, pvc, jnp.asarray(0, jnp.int32), jnp.asarray(True)))
    # the loop exits after a round that did not advance pvc; evaluate
    # once more at the stable clock (no-progress-first-round case)
    applied, _ = round_(pvc)
    rounds = note_round(rounds, applied, r)
    return (applied, rounds, pvc, live & ~applied,
            jnp.sum(applied, dtype=jnp.int32))


def ring_alloc(cap: int, d_pad: int):
    """Fresh all-dead ring buffers, created ON DEVICE (``jnp.zeros``
    lowers to a device fill — a rebuild uploads nothing)."""
    return (jnp.zeros((cap, d_pad), dtype=jnp.int64),
            jnp.zeros((cap,), dtype=jnp.int32),
            jnp.zeros((cap,), dtype=jnp.int32),
            jnp.zeros((cap,), dtype=jnp.int64),
            jnp.zeros((cap,), dtype=bool),
            jnp.zeros((cap,), dtype=bool))

"""DataCenter: one DC's full assembly — node + inter-DC replication +
stable-time plane + membership.

Combines what the reference spreads over inter_dc_manager,
antidote_dc_manager, and the six registered vnode types (reference
src/antidote_app.erl:42-59): per-partition log senders tapping local
appends, per-(origin, partition) gap-repair buffers feeding per-partition
dependency gates, the GST tracker, the durable metadata store, and the
connect / restart-recovery protocol.

One DataCenter = one process = one DC.  The reference's extra node
dimension (many BEAM nodes per DC, riak_core ring) maps to the device
mesh in this rebuild: partitions are rows of sharded arrays, not
processes (SURVEY §2.7, §7).
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from antidote_tpu import stats
from antidote_tpu.api import AntidoteTPU
from antidote_tpu.bcounter import BCounterMgr
from antidote_tpu.clocks import VC
from antidote_tpu.config import Config
from antidote_tpu.interdc import query as idc_query
from antidote_tpu.interdc.dep import gate_from_config
from antidote_tpu.interdc.interest import (InterestSpec,
                                           interest_from_config)
from antidote_tpu.interdc.sender import InterDcLogSender
from antidote_tpu.interdc.sub_buf import SubBuf
from antidote_tpu.interdc.transport import InboxWorker, LinkDown, Transport
from antidote_tpu.interdc.wire import (
    DcDescriptor,
    InterDcBatch,
    InterDcTxn,
    frame_from_bin,
)
from antidote_tpu.meta.device_stable import make_stable_tracker
from antidote_tpu.oplog.partition import BelowRetentionFloor
from antidote_tpu.meta.stable_store import StableMetaData
from antidote_tpu.obs import pipeline as obs_pipeline
from antidote_tpu.obs import probe as obs_probe
from antidote_tpu.obs.spans import tracer
from antidote_tpu.txn.node import Node


class DataCenter(AntidoteTPU):
    #: process-global streamed-cut identity (ISSUE 19): never reused
    #: within a process, so a receiver's stale cursor can never match
    #: a NEWER cut's pages by bid coincidence (a restarted server's
    #: empty cache already answers None — a miss, not a collision)
    _ckpt_bid = itertools.count(1)

    def __init__(self, dc_id, bus: Transport, config: Optional[Config] = None,
                 data_dir: Optional[str] = None):
        self.bus = bus
        cfg = config or Config()
        node = Node(dc_id=dc_id, config=cfg, data_dir=data_dir,
                    on_log_append=self._on_local_append)
        # AntidoteTPU wires the coordinator API around the node
        super().__init__(node=node)
        base = data_dir or cfg.data_dir
        self.meta = StableMetaData(
            os.path.join(base, f"{dc_id}_meta.pkl"),
            recover=cfg.recover_meta_data_on_start)
        # ring placement over a real mesh: the stable fold is a device
        # collective, host fold as oracle (meta/device_stable.py)
        self.stable = make_stable_tracker(cfg, dc_id, cfg.n_partitions)
        #: drop inbound heartbeats (reference inter_dc_manager:drop_ping,
        #: src/inter_dc_manager.erl:254-260 — lets tests age the GST)
        self.drop_ping = False
        self.connected_dcs: List[Any] = []

        #: (origin_dc, partition) -> SubBuf
        self.sub_bufs: Dict[Any, SubBuf] = {}
        self._build_interdc_plumbing()
        node.wait_hook = self._wait_hook

        #: this DC's interest spec (ISSUE 18, docs/interest_routing.md):
        #: None = full stream.  Built through the one-factory knob hop
        #: (loud InterestError at boot on a malformed interest_ranges)
        #: and announced to the transport BEFORE any peer link forms,
        #: so the restart re-join below subscribes already filtered.
        self.interest = interest_from_config(cfg)

        self._rx_lock = threading.Lock()
        self._inbox = bus.register(self.descriptor(), self._handle_query)
        if self.interest is not None:
            # transports that cannot route interest (external stubs
            # without the hook) simply deliver the full stream — a safe
            # superset; only a declared spec needs the announcement
            bus.set_local_interest(self.node.dc_id, self.interest)
        self._worker = InboxWorker(self._inbox, self._deliver)
        self._hb_worker: Optional[_Ticker] = None
        self._bc_worker: Optional[_Ticker] = None
        self._staleness: Optional[stats.StalenessSampler] = None
        self._causal_probe: Optional[obs_probe.CausalProbe] = None
        self._fleet_scraper = None  # obs_fleet.FleetScraper when elected
        node.bcounter_mgr = BCounterMgr(self)

        # re-join DCs we knew before a restart; an unreachable peer must
        # not kill the boot (whole-cluster crash: someone restarts first)
        # — the heartbeat ticker retries until it comes back (reference
        # retry loop, src/inter_dc_manager.erl:87-109)
        self._retry_descs: List[DcDescriptor] = []
        for desc in (self.meta.get("connected_descriptors") or []):
            try:
                self._connect(desc)
            except LinkDown:
                logging.getLogger(__name__).warning(
                    "restart re-join: %r unreachable, will retry",
                    desc.dc_id)
                self._retry_descs.append(desc)
        # restore the stable-snapshot floor persisted at shutdown (see
        # close()): stability is a permanent local fact.  The meta store
        # itself loads nothing under recover_meta_data_on_start=False,
        # so that flag implicitly gates this too — merely conservative
        last_stable = self.meta.get("last_stable_vc")
        if last_stable:
            self.stable.seed_floor(VC(last_stable))
        # re-apply runtime flags persisted before the restart (reference
        # recovers replicated env flags from stable metadata,
        # src/dc_meta_data_utilities.erl:79-104)
        for name, value in (self.meta.get("runtime_flags") or {}).items():
            try:
                node.set_flag(name, value)
            except (KeyError, ValueError):
                logging.getLogger(__name__).warning(
                    "ignoring persisted unknown flag %r", name)
        self.meta.mark_started()
        # the pipeline-snapshot plane (/debug/pipeline) and the causal
        # probe's peer discovery both see every DC in the process
        obs_pipeline.register(self)

    # ---------------------------------------------------------- admin plane

    def set_flag(self, name: str, value) -> None:
        """Apply + persist a runtime flag: survives restarts via the
        stable meta store (the reference's replicated-then-stored env
        flag path, src/dc_meta_data_utilities.erl:79-104)."""
        self.node.set_flag(name, value)
        flags = dict(self.meta.get("runtime_flags") or {})
        flags[name] = self.node.get_flag(name)
        self.meta.put("runtime_flags", flags)

    def admin_status(self) -> dict:
        st = super().admin_status()
        st["connected_dcs"] = [str(d) for d in self.connected_dcs]
        with self._rx_lock:  # the delivery worker grows gate queues
            st["pending_interdc"] = sum(
                g.pending() for g in self.dep_gates)
        return st

    def repartition(self, new_n: int) -> None:
        """Resize the DC's ring (Node.repartition) and rebuild the
        inter-DC plumbing at the new width.  Only a *disconnected* DC
        may resize: partition counts are part of the cluster contract
        (observe_dc refuses mismatched descriptors), so every DC of a
        federation resizes separately and the cluster re-forms with
        fresh descriptors afterwards."""
        # stop the background workers first: the heartbeat ticker's
        # retry path calls _connect concurrently, and the staleness
        # sampler stays bound to the old tracker — both must be rebuilt
        # against the resized plumbing
        was_running = self._hb_worker is not None
        self._stop_bg_processes()
        if self.connected_dcs or self.sub_bufs:
            if was_running:
                self.start_bg_processes()
            raise RuntimeError(
                "repartition requires a disconnected DC: drop inter-DC "
                "links first; peers must resize to the same count "
                "before the cluster re-forms")
        # only once the resize actually proceeds: pending re-join
        # retries carry the OLD partition count and must not relink
        self._retry_descs = []
        with self._rx_lock:
            floor = self.stable.get_stable_snapshot()
            self.node.repartition(new_n)
            self.stable = make_stable_tracker(
                self.node.config, self.node.dc_id,
                self.node.config.n_partitions)
            # stability is permanent: the resized tracker keeps the old
            # published floor (same rule as the restart restore above)
            self.stable.seed_floor(floor)
            self._build_interdc_plumbing()
            # the quiesced pre-resize node had applied every record in
            # its logs; the redistribution preserves that set, so every
            # resized partition's dependency clock may start at the
            # node-wide frontier (per-partition seeds alone would
            # under-state it: each new log holds only a re-cut slice)
            node_frontier = VC()
            for pm in self.node.partitions:
                node_frontier = node_frontier.join(pm.log.max_commit_vc)
            for g in self.dep_gates:
                g.seed_clock(node_frontier)
            # persisted peers carry the old partition count — stale
            self.meta.delete("connected_descriptors")
        if was_running:
            self.start_bg_processes()

    def _build_interdc_plumbing(self) -> None:
        """Senders, dependency gates, stable-time sources, and the
        recovered watermark/clock seeds for the node's CURRENT partition
        list — shared by boot and repartition (restart recovery:
        reference check_node_restart, src/inter_dc_manager.erl:156-201 +
        logging_vnode {start_timer}, src/logging_vnode.erl:301-322)."""
        node = self.node
        dc_id = node.dc_id
        n = node.config.n_partitions
        # streamed CKPT_READ state (ISSUE 19): served cut pages keyed
        # (requester, partition) — latest bid only — and the client's
        # resumable pull cursors.  Both describe the CURRENT ring, so
        # a repartition rebuild drops them (a receiver quoting a
        # pre-resize bid gets None per page and restarts cleanly)
        self._ckpt_serve_cache = {}
        self._ckpt_pull_state = {}
        # a rebuild (repartition) replaces the senders: stop the old
        # ship workers first so staged txns flush at the old width
        for s in getattr(self, "senders", []):
            s.close()
        self.senders = [
            InterDcLogSender(dc_id, p, self.bus, enabled=False,
                             config=node.config)
            for p in range(n)
        ]
        self.dep_gates = [
            gate_from_config(pm, dc_id, node.clock.now_us, node.config)
            for pm in node.partitions
        ]

        # stable-time sources: per partition, dep-gate watermarks + own
        # min-prepared (the quantity the outbound ping carries)
        def _source(p):
            def pull():
                gate = self.dep_gates[p]
                return VC(gate.applied_vc).set_dc(
                    dc_id, node.partitions[p].min_prepared())
            return pull

        self.stable.sources = [_source(p) for p in range(n)]
        node.stable_vc_provider = self.stable.get_stable_snapshot
        for p, pm in enumerate(node.partitions):
            self.senders[p].seed_watermark(
                pm.log.op_counters.get(dc_id, 0))
            self.dep_gates[p].seed_clock(pm.log.max_commit_vc)
            # retention floor for checkpoint truncation (ISSUE 10):
            # with peers subscribed, keep log history back to the ship
            # watermark (minus the retain_ops margin — applied in the
            # partition log) so ordinary gap repair stays answerable;
            # with no peers, truncation may reach the cut and a later
            # join bootstraps from the checkpoint
            pm.log.retention_opid_source = (
                lambda _s=self.senders[p]:
                _s.last_sent_opid if self.connected_dcs else None)

    # ---------------------------------------------------------- membership

    def descriptor(self) -> DcDescriptor:
        addrs = self.bus.local_addrs()
        pub = addrs[0] if addrs else (self.node.dc_id,)
        logreader = addrs[1] if addrs else (self.node.dc_id,)
        return DcDescriptor(dc_id=self.node.dc_id,
                            n_partitions=self.node.config.n_partitions,
                            pub_addrs=pub, logreader_addrs=logreader)

    def observe_dc(self, desc: DcDescriptor) -> None:
        """Subscribe to a remote DC (reference inter_dc_manager:observe_dc,
        src/inter_dc_manager.erl:68-85: partition counts must match)."""
        if desc.dc_id == self.node.dc_id:
            return
        if desc.n_partitions != self.node.config.n_partitions:
            raise ValueError(
                f"inter_dc_connect: {desc.dc_id!r} has {desc.n_partitions} "
                f"partitions, local DC has {self.node.config.n_partitions}")
        self._connect(desc)
        descs = [d for d in (self.meta.get("connected_descriptors") or [])
                 if d.dc_id != desc.dc_id] + [desc]
        self.meta.put("connected_descriptors", descs)

    def _connect(self, desc: DcDescriptor) -> None:
        if desc.dc_id in self.connected_dcs:
            return
        if desc.n_partitions != self.node.config.n_partitions:
            # observe_dc checks this too, but _connect is also reached
            # by the restart/retry path — a stale descriptor (e.g. from
            # before a repartition) must never half-link
            raise ValueError(
                f"descriptor {desc.dc_id} has {desc.n_partitions} "
                f"partitions, local DC has "
                f"{self.node.config.n_partitions}")
        # transport-level subscription first (dial + probe for TCP; no-op
        # in-proc) so a dead peer fails before we commit membership state
        self.bus.connect(self.node.dc_id, desc)
        # sub_bufs before connected_dcs: the subscription is live, and a
        # frame passing the connected-guard must find its buffer
        for p in range(self.node.config.n_partitions):
            # crash recovery: resume the stream where the local log
            # left off (reference src/inter_dc_sub_buf.erl:58-76)
            last = self.node.partitions[p].log.op_counters.get(
                desc.dc_id, 0)
            if self.node.partitions[p].log.renumbered:
                # checkpoint-seeded resize (ISSUE 19): the re-cut log's
                # per-origin counter is a LOCAL max-join over the old
                # slots, while a peer that also resized renumbered its
                # per-partition stream by its OWN join — the two no
                # longer describe the same chain, so resuming from the
                # local counter would mis-align gap repair (and lazy
                # LOG_READ repair into renumbered history is fenced to
                # BELOW_FLOOR anyway).  Re-handshake PROACTIVELY: a
                # fresh checkpoint cut from the origin seeds VC-gated
                # merge bases (idempotent against anything already
                # applied) and hands back the watermark in the
                # origin's CURRENT numbering.
                tracer.instant("renumbered_bootstrap", "interdc",
                               origin=str(desc.dc_id), partition=p)
                wm = self._bootstrap_from_ckpt(desc.dc_id, p)
                if wm is not None:
                    last = wm
                else:
                    logging.getLogger(__name__).warning(
                        "partition %d is renumbered (seeded resize) "
                        "but origin %r is unreachable or not "
                        "checkpointing — resuming its stream from the "
                        "local counter; gap repair may escalate to a "
                        "checkpoint bootstrap", p, desc.dc_id)
            self.sub_bufs[(desc.dc_id, p)] = SubBuf(
                desc.dc_id, p,
                deliver=self._make_gate_deliver(p),
                deliver_batch=self._make_gate_deliver_batch(p),
                fetch_range=self._fetch_range,
                bootstrap=self._bootstrap_from_ckpt,
                last_opid=last,
                filtered=self.interest is not None)
        if self.interest is not None:
            # partial-subscription qualifier (ISSUE 18): surfaced in
            # queue_stats so operators can tell a lagging origin from a
            # partially-subscribed one; the gate's advancement rule is
            # untouched — heartbeat pings are interest-independent
            for g in self.dep_gates:
                g.note_subscription(desc.dc_id,
                                    len(self.interest.ranges))
        self.connected_dcs.append(desc.dc_id)
        for s in self.senders:
            s.enabled = True

    def set_interest(self, ranges) -> None:
        """Re-declare this DC's subscription at runtime (ISSUE 18,
        docs/interest_routing.md §3).  Widening backfills lazily in two
        halves: the sender starts a new interest-class chain at its
        current stream base, so the SubBuf sees the first new-class
        frame as an ordinary gap and the ranged LOG_READ / CKPT_READ
        repair ships the widened history ABOVE the old class watermark;
        the history BELOW it (txns of the new ranges elided while we
        were not subscribed, now under the SubBuf's duplicate floor) is
        fetched explicitly by :meth:`_backfill_widened`.  Validation is
        loud — malformed ranges raise InterestError, and calling this
        with routing off is a config error, not a silent no-op."""
        if not self.node.config.interest_routing:
            raise ValueError(
                "set_interest requires Config.interest_routing=True")
        spec = None if ranges is None else InterestSpec(ranges)
        with self._rx_lock:
            old = self.interest
            self.interest = spec
            self.bus.set_local_interest(self.node.dc_id, spec)
            for buf in self.sub_bufs.values():
                buf.filtered = spec is not None
            for g in self.dep_gates:
                for origin in self.connected_dcs:
                    g.note_subscription(
                        origin, None if spec is None
                        else len(spec.ranges))
        # outside _rx_lock: the backfill blocks on fetches and device
        # quiesce, and its range sits at or BELOW the captured SubBuf
        # watermarks — the live stream drops those opids as duplicates,
        # so no delivery can race an apply into the backfilled span
        if old is not None and spec != old:
            self._backfill_widened(old)

    def _backfill_widened(self, old: InterestSpec) -> None:
        """Fetch the newly-subscribed ranges' history that sits BELOW
        the stream watermarks (docs/interest_routing.md §3): those
        txns were elided under the old spec, so the SubBuf's duplicate
        floor would drop a re-delivery — they are fetched with the NEW
        ranges over [1, watermark], the ones the OLD spec already
        delivered are dropped (txn-granular match: exact regardless of
        how the range sets overlap), and the remainder goes straight
        to the dependency gate, which admits it like any repaired
        arrival.  The old-spec filter alone is NOT exact: full-frame
        fallbacks (spec races, identity slices) deliver supersets, so
        a fetched txn may already be applied even though the old spec
        did not match it — the local log's per-origin commit index
        settles it exactly (opids at or below the local retention
        floor were applied by definition: they are in our own
        checkpoint).  BELOW_FLOOR at the ORIGIN escalates to the
        ranged checkpoint: seed states merge in as VC-gated bases
        (CRDT join — idempotent against anything already applied) and
        the retained suffix (cut, watermark] tops up via LOG_READ.
        Neither the SubBuf watermark nor the gate clock moves — both
        describe the live stream, which this pre-history fill never
        touches.  An unreachable origin is logged and skipped; its
        below-watermark history stays out until the spec is
        re-declared."""
        new_ranges = None if self.interest is None else \
            self.interest.ranges
        for (origin, p), buf in sorted(self.sub_bufs.items(),
                                       key=lambda kv: repr(kv[0])):
            wm = buf.last_opid
            if wm <= 0:
                continue  # no history behind the watermark
            stats.registry.interest_backfills.inc()
            ans = idc_query.fetch_log_range(
                self.bus, self.node.dc_id, origin, p, 1, wm,
                ranges=new_ranges)
            if ans is not None and idc_query.is_below_floor(ans):
                ckpt = idc_query.fetch_ckpt_bootstrap(
                    self.bus, self.node.dc_id, origin, p,
                    ranges=new_ranges)
                if ckpt is None:
                    logging.getLogger(__name__).warning(
                        "widen backfill of (%r, %d): origin below "
                        "retention floor and not checkpointing — "
                        "pre-watermark history of the new ranges is "
                        "unavailable", origin, p)
                    continue
                # seeds only — origin_dc/op_counter stay untouched:
                # the cut's commit watermark is the FULL stream's, and
                # moving the per-origin counter to it would skip the
                # old spec's retained suffix on a restart
                self.node.partitions[p].bootstrap_seed(
                    (key, tn, state, VC(vc))
                    for key, (tn, state, vc) in ckpt["keys"].items())
                cut = int(ckpt["commit_opid"])
                ans = (idc_query.fetch_log_range(
                    self.bus, self.node.dc_id, origin, p, cut + 1, wm,
                    ranges=new_ranges) if cut < wm else [])
            if ans is None or idc_query.is_below_floor(ans):
                logging.getLogger(__name__).warning(
                    "widen backfill of (%r, %d) failed (origin "
                    "unreachable or still below floor) — retry by "
                    "re-declaring the spec", origin, p)
                continue
            pm = self.node.partitions[p]
            floor = 0
            try:
                applied = pm.scan_log(lambda lg: {
                    done[-1].op_id.n for _prev, done in
                    lg.committed_txns_in_range(origin, 1, wm)})
            except BelowRetentionFloor as e:
                floor = int(e.floor)
                applied = pm.scan_log(lambda lg: {
                    done[-1].op_id.n for _prev, done in
                    lg.committed_txns_in_range(origin, floor + 1, wm)})
            fresh = [t for t in sorted(ans, key=lambda t: t.last_opid())
                     if not old.matches_txn(t)
                     and t.last_opid() > floor
                     and t.last_opid() not in applied]
            if fresh:
                self.dep_gates[p].enqueue_batch(fresh)

    def observe_dcs_sync(self, descs: List[DcDescriptor],
                         timeout: float = 30.0) -> None:
        """Connect and wait until each remote DC's entry appears in the
        stable snapshot (reference observe_dcs_sync + wait_for_stable_snapshot,
        src/inter_dc_manager.erl:214-230, 265-280)."""
        for desc in descs:
            self.observe_dc(desc)
        deadline = time.monotonic() + timeout
        want = [d.dc_id for d in descs if d.dc_id != self.node.dc_id]
        while True:
            st = self.stable.get_stable_snapshot()
            if all(st.get_dc(dc) > 0 for dc in want):
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"stable snapshot never covered {want}: {st}")
            self._wait_hook()

    # --------------------------------------------------------- background

    def start_bg_processes(self) -> None:
        """Delivery worker + heartbeat timer (reference
        inter_dc_manager:start_bg_processes, src/inter_dc_manager.erl:112-145)."""
        self._worker.start()
        if self._hb_worker is None:
            self._hb_worker = _Ticker(self.node.config.heartbeat_s,
                                      self.tick_heartbeats)
            self._hb_worker.start()
        if self._bc_worker is None:
            self._bc_worker = _Ticker(
                self.node.config.bcounter_transfer_period_s,
                self.node.bcounter_mgr.transfer_periodic)
            self._bc_worker.start()
        if self._staleness is None:
            self._staleness = stats.StalenessSampler(
                self.stable.get_stable_snapshot, self.node.clock.now_us,
                period_s=self.node.config.staleness_sample_s,
                # per-peer replication lag rides the same snapshot fetch
                peers_source=lambda: list(self.connected_dcs),
                local_dc=self.node.dc_id,
                # per-partition safe-time lag (ISSUE 7): each source is
                # the partition's dep-gate watermarks + min-prepared —
                # read at sample time so a repartition's rebuilt source
                # list is picked up
                safe_time_sources=lambda: [
                    (p, src())
                    for p, src in enumerate(self.stable.sources)])
            self._staleness.start()
        if self._causal_probe is None \
                and self.node.config.obs_causal_probe_s > 0:
            self._causal_probe = obs_probe.CausalProbe(
                self, period_s=self.node.config.obs_causal_probe_s)
            self._causal_probe.start()
        if self._fleet_scraper is None \
                and self.node.config.fleet_scrape_s > 0:
            # fleet federation (ISSUE 17): remote peers come from
            # extra["fleet_peers"] (metrics-server roots); the local
            # registry + pipeline plane always federate
            from antidote_tpu.obs import fleet as obs_fleet

            self._fleet_scraper = obs_fleet.FleetScraper(
                endpoints=list(
                    self.node.config.extra.get("fleet_peers", ())),
                period_s=self.node.config.fleet_scrape_s,
                name=str(self.node.dc_id))
            self._fleet_scraper.start()
        stats.install_error_monitor()
        if self.node.config.metrics_port is not None:
            # process-global: all DCs share one registry and one server
            stats.ensure_metrics_server(self.node.config.metrics_port)

    def tick_heartbeats(self) -> None:
        """One heartbeat round: each partition broadcasts its min-prepared
        time (reference 1 s ping, src/inter_dc_log_sender_vnode.erl:133-143).
        Also retries peers that were unreachable at restart re-join."""
        if self._retry_descs:
            still = []
            for desc in self._retry_descs:
                try:
                    self._connect(desc)
                except LinkDown:
                    still.append(desc)
                except ValueError:
                    logging.getLogger(__name__).warning(
                        "dropping stale descriptor %r (partition-count "
                        "mismatch)", desc.dc_id)
            self._retry_descs = still
        for p, sender in enumerate(self.senders):
            sender.ping(self.node.partitions[p].min_prepared())

    def pump(self) -> int:
        """Drain the inbound txn stream synchronously (deterministic mode)."""
        return self._worker.pump()

    def _wait_hook(self) -> None:
        # called from clock-wait spins: make progress on inbound
        # replication, then yield briefly
        self.pump()
        time.sleep(0.002)

    # ----------------------------------------------------------- inbound

    def _deliver(self, data: bytes) -> None:
        try:
            frame = frame_from_bin(data)
        except ValueError:
            # frames arrive from other administrative domains over the
            # network: a malformed one is dropped (and logged), never
            # allowed to kill the delivery worker — the opid watermark
            # treats it as loss and gap repair re-fetches
            logging.getLogger(__name__).warning(
                "dropping malformed inter-DC frame (%d bytes)", len(data))
            return
        # one-at-a-time delivery: the background worker and wait-hook
        # pumps may race, but sub_bufs/dep gates assume a single writer
        # (the reference gets this from one gen_server per buffer)
        with self._rx_lock:
            if frame.dc_id not in self.connected_dcs:
                return  # not subscribed to this origin
            buf = self.sub_bufs.get((frame.dc_id, frame.partition))
            if buf is None:
                return  # connect raced the stream; repair catches up
            if isinstance(frame, InterDcBatch):
                # the ship plane's coalesced frame: the whole span goes
                # through the sub-buffer as one arrival batch, with the
                # piggybacked heartbeat (if any) trailing it
                tracer.adopt_from_wire(frame.trace_hdr, frame.txns())
                for txn in frame.txns():
                    tracer.instant("interdc_rx", "interdc",
                                   txid=getattr(txn.records[-1], "txid",
                                                None),
                                   origin=str(frame.dc_id),
                                   partition=frame.partition)
                buf.process_batch(frame.delivery_txns(
                    include_ping=not self.drop_ping))
                return
            txn = frame
            txid = (None if txn.is_ping()
                    else getattr(txn.records[-1], "txid", None))
            if txn.is_ping() and self.drop_ping:
                return
            if txid is None:
                buf.process(txn)
                return
            if txn.trace_ctx is not None:
                tracer.adopt_from_wire((txn.trace_ctx[1], 0), [txn])
            # arrival marker only: buf.process may drain a backlog of
            # OTHER buffered transactions, so a span here would charge
            # their apply cost to this txid.  The per-txn deliver span
            # lives in the gate deliver callback, at release time.
            tracer.instant("interdc_rx", "interdc", txid=txid,
                           origin=str(txn.dc_id), partition=txn.partition)
            buf.process(txn)

    def _make_gate_deliver(self, p: int):
        def deliver(txn: InterDcTxn) -> None:
            if not txn.is_ping():
                # point event, not a span: enqueue can synchronously
                # drain the gate's whole backlog, and a span here would
                # charge those OTHER transactions' apply cost to this
                # txid (per-txn apply timing is depgate_admit's job)
                tracer.instant("interdc_deliver", "interdc",
                               txid=getattr(txn.records[-1], "txid",
                                            None),
                               origin=str(txn.dc_id),
                               partition=txn.partition)
            self.dep_gates[p].enqueue(txn)
        return deliver

    def _make_gate_deliver_batch(self, p: int):
        def deliver_batch(txns: List[InterDcTxn]) -> None:
            for txn in txns:
                if not txn.is_ping():
                    # point events, like the per-txn deliver path (the
                    # per-txn apply timing is depgate_admit's job)
                    tracer.instant("interdc_deliver", "interdc",
                                   txid=getattr(txn.records[-1],
                                                "txid", None),
                                   origin=str(txn.dc_id),
                                   partition=txn.partition)
            self.dep_gates[p].enqueue_batch(txns)
        return deliver_batch

    def _fetch_range(self, origin_dc, partition: int, first: int,
                     last: int) -> Optional[List[InterDcTxn]]:
        return idc_query.fetch_log_range(
            self.bus, self.node.dc_id, origin_dc, partition, first, last,
            ranges=None if self.interest is None else self.interest.ranges)

    def _bootstrap_from_ckpt(self, origin_dc, partition: int
                             ) -> Optional[int]:
        """BELOW_FLOOR escalation (ISSUE 10): fetch the origin's
        partition checkpoint, merge its seed states into the local
        partition (local concurrent writes survive — the seeds are
        VC-gated merge bases, PartitionManager.bootstrap_seed), seed
        the dependency gate's clock with the cut frontier, and return
        the origin's commit watermark at the cut for the SubBuf to
        jump to.  None = unreachable / origin does not checkpoint.

        With Config.ckpt_stream (ISSUE 19) the cut arrives as a
        manifest + validated pages under a bounded in-flight window,
        and an origin kill mid-pull resumes at the first un-acked page
        on the retry (the cursor state lives per (origin, partition)).
        An origin predating the streamed kinds falls back to the
        one-shot CKPT_READ."""
        ranges = (None if self.interest is None
                  else self.interest.ranges)
        if getattr(self.node.config, "ckpt_stream", True):
            state = self._ckpt_pull_state.setdefault(
                (origin_dc, partition), {})
            try:
                ans = idc_query.fetch_ckpt_bootstrap_streamed(
                    self.bus, self.node.dc_id, origin_dc, partition,
                    ranges=ranges,
                    window_bytes=int(getattr(
                        self.node.config, "ckpt_stream_window_bytes",
                        4 << 20)),
                    state=state)
            except Exception as e:  # noqa: BLE001 — version fallback
                # an origin without the streamed kinds errors the
                # manifest request (transport-dependent exception
                # type); the one-shot path below serves it instead
                logging.getLogger(__name__).info(
                    "streamed ckpt bootstrap of (%r, %d) unavailable "
                    "(%s); falling back to one-shot CKPT_READ",
                    origin_dc, partition, e)
            else:
                if ans is None:
                    return None
                return idc_query.install_ckpt_bootstrap(
                    self.node.partitions[partition],
                    self.dep_gates[partition],
                    origin_dc, partition, ans)
        ans = idc_query.fetch_ckpt_bootstrap(
            self.bus, self.node.dc_id, origin_dc, partition,
            ranges=ranges)
        if ans is None:
            return None
        return idc_query.install_ckpt_bootstrap(
            self.node.partitions[partition], self.dep_gates[partition],
            origin_dc, partition, ans)

    # ------------------------------------------------------------ queries

    def _handle_query(self, from_dc, kind: str, payload) -> Any:
        if kind == idc_query.LOG_READ:
            # 3-arity = the pre-ISSUE-18 full answer; 4-arity carries
            # the requester's interest ranges (validated loudly in
            # answer_log_read — a hostile range set errors the request,
            # never silently changes the answer)
            if len(payload) == 4:
                partition, first, last, ranges = payload
            else:
                partition, first, last = payload
                ranges = None
            pm = self.node.partitions[partition]
            # runs on the requester's thread
            return pm.scan_log(
                lambda log: idc_query.answer_log_read(
                    log, self.node.dc_id, partition, first, last,
                    ranges=ranges))
        if kind == idc_query.SNAPSHOT_READ:
            objects, clock = payload
            # served through the read serve plane (ISSUE 8): the
            # remote reader's fold coalesces with local readers
            tracer.instant("interdc_snapshot_read", "interdc",
                           origin=str(from_dc), keys=len(objects))
            return idc_query.answer_snapshot_read(self, objects, clock)
        if kind == idc_query.CKPT_READ:
            # 1-arity = the pre-ISSUE-18 full checkpoint; 2-arity
            # carries the requester's interest ranges
            if len(payload) == 2:
                partition, ranges = payload
            else:
                (partition,) = payload
                ranges = None
            # a remote SubBuf fell below our retention floor: cut a
            # fresh checkpoint and hand over the seed states (ISSUE 10)
            tracer.instant("interdc_ckpt_read", "interdc",
                           origin=str(from_dc), partition=partition)
            return idc_query.answer_ckpt_read(
                self.node.partitions[partition], self.node.dc_id,
                partition, ranges=ranges)
        if kind == idc_query.CKPT_MANIFEST:
            partition, ranges, page_bytes = payload
            tracer.instant("interdc_ckpt_manifest", "interdc",
                           origin=str(from_dc), partition=partition)
            man, pages = idc_query.answer_ckpt_manifest(
                self.node.partitions[partition], self.node.dc_id,
                partition, ranges=ranges, page_bytes=int(page_bytes),
                bid=next(DataCenter._ckpt_bid))
            if man is None:
                return None
            # only the LATEST cut per (requester, partition) stays
            # cached: a re-pull supersedes the old bid, and a page
            # fetch quoting it answers None (the receiver restarts)
            self._ckpt_serve_cache[(from_dc, partition)] = (
                man["bid"], pages)
            return man
        if kind == idc_query.CKPT_SEG:
            partition, bid, names = payload
            return idc_query.answer_ckpt_seg(
                self._ckpt_serve_cache.get((from_dc, partition)),
                bid, names)
        if kind == idc_query.CHECK_UP:
            return True
        if kind == idc_query.BCOUNTER_REQUEST:
            if self.node.bcounter_mgr is None:
                return None
            return self.node.bcounter_mgr.handle_remote_request(
                from_dc, payload)
        raise ValueError(f"unknown inter-DC query kind {kind!r}")

    # ----------------------------------------------------------- outbound

    def _on_local_append(self, partition: int, rec) -> None:
        self.senders[partition].on_append(rec)

    # ----------------------------------------------------------- shutdown

    def _stop_bg_processes(self) -> None:
        if self._hb_worker is not None:
            self._hb_worker.stop()
            self._hb_worker = None
        if self._bc_worker is not None:
            self._bc_worker.stop()
            self._bc_worker = None
        if self._staleness is not None:
            self._staleness.stop()
            self._staleness = None
        if self._causal_probe is not None:
            self._causal_probe.stop()
            self._causal_probe = None
        if self._fleet_scraper is not None:
            self._fleet_scraper.stop()
            self._fleet_scraper = None

    def close(self) -> None:
        obs_pipeline.unregister(self)
        self._stop_bg_processes()
        # flush + stop the ship workers before the inbound worker: a
        # staged batch published now still reaches live peers
        for s in self.senders:
            s.close()
        self._worker.stop()
        # persist the published stable snapshot: stability is permanent,
        # and the restarted tracker floors itself here so None-clock
        # reads keep seeing everything that was stable before the
        # shutdown (heartbeat advancement is not logged)
        self.meta.put("last_stable_vc",
                      dict(self.stable.get_stable_snapshot()))
        self.bus.unregister(self.node.dc_id)
        super().close()


class _Ticker:
    def __init__(self, period_s: float, fn):
        import threading

        self.period_s = period_s
        self.fn = fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.fn()
            except Exception:  # noqa: BLE001 — timers must not die
                import logging

                logging.getLogger(__name__).exception("ticker task failed")

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def connect_dcs(dcs: List[DataCenter], sync: bool = True,
                timeout: float = 30.0) -> None:
    """Full-mesh descriptor exchange (the test harness's connect_cluster,
    reference test/utils/test_utils.erl:259-289): every DC observes every
    other, a heartbeat round seeds the stable times, and each DC waits
    until its stable snapshot covers all peers."""
    descs = [dc.descriptor() for dc in dcs]
    for dc in dcs:
        for desc in descs:
            if desc.dc_id != dc.node.dc_id:
                dc.observe_dc(desc)
    if not sync:
        return
    deadline = time.monotonic() + timeout
    want = {dc.node.dc_id for dc in dcs}
    while True:
        for dc in dcs:
            dc.tick_heartbeats()
        for dc in dcs:
            dc.pump()
        done = all(
            all(dc.stable.get_stable_snapshot().get_dc(peer) > 0
                for peer in want - {dc.node.dc_id})
            for dc in dcs)
        if done:
            return
        if time.monotonic() > deadline:
            raise TimeoutError("DC mesh never stabilized")
        time.sleep(0.001)

"""Inter-DC replication (reference §2.3: inter_dc_* modules).

Txn stream pub/sub with opid-watermark gap repair, causal dependency
gating, and DC membership — transport-agnostic (in-process bus for
simulated DCs and tests; the C++ TCP transport for real deployments).
"""

from antidote_tpu.interdc.wire import InterDcTxn  # noqa: F401
from antidote_tpu.interdc.transport import InProcBus  # noqa: F401
from antidote_tpu.interdc.dc import DataCenter  # noqa: F401

"""Inter-DC wire format.

Mirrors the reference's ``#interdc_txn{}`` record (reference
include/inter_dc_repl.hrl:16-25) and its binary framing
(src/inter_dc_txn.erl:95-105): a fixed-width big-endian partition-id
prefix — the pub/sub subscription topic — followed by the serialized
body.  An empty ``records`` list is a heartbeat/ping
(src/inter_dc_txn.erl:63-71).

``prev_log_opid`` is the origin stream's opid watermark *before* this
txn: the op number of the last record previously broadcast for this
(origin DC, partition) stream.  The commit record is appended last at
the origin, so it carries the stream's highest opid at commit time —
``last_opid()`` below — and watermarks are monotone per stream even when
concurrent transactions interleave their update records in the log.
Gap repair compares exactly these two numbers
(src/inter_dc_sub_buf.erl:98-142).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.oplog.records import LogRecord

#: topic prefix width (the reference uses 20 bytes for sext-encoded ids,
#: include/antidote_message_types.hrl:17; 8-byte big-endian is enough
#: for integer partition ids and keeps prefix-match subscription)
PARTITION_PREFIX_LEN = 8


@dataclass
class InterDcTxn:
    dc_id: Any
    partition: int
    #: opid watermark of this stream before this txn (gap detection)
    prev_log_opid: int
    #: the txn's snapshot VC (causal dependencies); None for heartbeats
    snapshot_vc: Optional[VC]
    #: commit time at the origin DC — or the stable/min-prepared time for
    #: heartbeats (src/inter_dc_log_sender_vnode.erl:133-143)
    timestamp: int
    #: update records + the trailing commit record; [] = heartbeat
    records: List[LogRecord] = field(default_factory=list)

    # ------------------------------------------------------------ queries

    def is_ping(self) -> bool:
        return not self.records

    def last_opid(self) -> int:
        """New stream watermark after this txn (the commit record's opid,
        appended last at the origin; heartbeats keep the old watermark)."""
        if self.is_ping():
            return self.prev_log_opid
        return self.records[-1].op_id.n

    def commit_record(self) -> LogRecord:
        return self.records[-1]

    def commit_time(self) -> int:
        return self.timestamp

    def update_records(self) -> List[LogRecord]:
        return [r for r in self.records if r.kind() == "update"]

    # ------------------------------------------------------- construction

    @staticmethod
    def from_ops(dc_id, partition: int, prev_log_opid: int,
                 records: List[LogRecord]) -> "InterDcTxn":
        """Build from an assembled op group; commit time and snapshot come
        from the trailing commit record (reference inter_dc_txn:from_ops,
        src/inter_dc_txn.erl:48-61)."""
        commit = records[-1]
        assert commit.kind() == "commit", "op group must end with a commit"
        (_dc, commit_time), snapshot_vc = commit.payload[1], commit.payload[2]
        return InterDcTxn(dc_id=dc_id, partition=partition,
                          prev_log_opid=prev_log_opid,
                          snapshot_vc=snapshot_vc, timestamp=commit_time,
                          records=records)

    @staticmethod
    def ping(dc_id, partition: int, prev_log_opid: int,
             timestamp: int) -> "InterDcTxn":
        return InterDcTxn(dc_id=dc_id, partition=partition,
                          prev_log_opid=prev_log_opid, snapshot_vc=None,
                          timestamp=timestamp, records=[])

    # -------------------------------------------------------------- bytes

    def to_bin(self) -> bytes:
        """Topic prefix + serialized body (src/inter_dc_txn.erl:95-105).

        The body is the safe tagged term codec, NOT pickle: frames
        arrive from other DCs over the network, and decoding them must
        never execute anything (antidote_tpu/interdc/termcodec.py)."""
        from antidote_tpu.interdc import termcodec

        return partition_prefix(self.partition) + termcodec.encode(self)

    @staticmethod
    def from_bin(data: bytes) -> "InterDcTxn":
        from antidote_tpu.interdc import termcodec

        txn = termcodec.decode(bytes(data[PARTITION_PREFIX_LEN:]))
        if not isinstance(txn, InterDcTxn):
            raise ValueError("corrupt inter-DC txn frame")
        return txn


def partition_prefix(partition: int) -> bytes:
    return struct.pack(">Q", partition)


@dataclass
class DcDescriptor:
    """DC membership descriptor exchanged on connect (reference
    inter_dc_manager:get_descriptor, src/inter_dc_manager.erl:49-61)."""

    dc_id: Any
    n_partitions: int
    #: transport addresses: publisher + log-reader endpoints.  For the
    #: in-process bus these are just the registry key; for the TCP
    #: transport, ("host", port) pairs.
    pub_addrs: Tuple = ()
    logreader_addrs: Tuple = ()

"""Inter-DC wire format.

Mirrors the reference's ``#interdc_txn{}`` record (reference
include/inter_dc_repl.hrl:16-25) and its binary framing
(src/inter_dc_txn.erl:95-105): a fixed-width big-endian partition-id
prefix — the pub/sub subscription topic — followed by the serialized
body.  An empty ``records`` list is a heartbeat/ping
(src/inter_dc_txn.erl:63-71).

``prev_log_opid`` is the origin stream's opid watermark *before* this
txn: the op number of the last record previously broadcast for this
(origin DC, partition) stream.  The commit record is appended last at
the origin, so it carries the stream's highest opid at commit time —
``last_opid()`` below — and watermarks are monotone per stream even when
concurrent transactions interleave their update records in the log.
Gap repair compares exactly these two numbers
(src/inter_dc_sub_buf.erl:98-142).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.oplog.records import LogRecord

#: topic prefix width (the reference uses 20 bytes for sext-encoded ids,
#: include/antidote_message_types.hrl:17; 8-byte big-endian is enough
#: for integer partition ids and keeps prefix-match subscription)
PARTITION_PREFIX_LEN = 8


@dataclass
class InterDcTxn:
    dc_id: Any
    partition: int
    #: opid watermark of this stream before this txn (gap detection)
    prev_log_opid: int
    #: the txn's snapshot VC (causal dependencies); None for heartbeats
    snapshot_vc: Optional[VC]
    #: commit time at the origin DC — or the stable/min-prepared time for
    #: heartbeats (src/inter_dc_log_sender_vnode.erl:133-143)
    timestamp: int
    #: update records + the trailing commit record; [] = heartbeat
    records: List[LogRecord] = field(default_factory=list)
    #: trace-propagation context stamped by the origin's sender
    #: (ISSUE 7): ``(origin commit wallclock µs, tracer sample rate in
    #: permille)``.  The wallclock is what remote-side visibility-lag
    #: histograms subtract from; the permille lets the receiver replay
    #: the origin's deterministic sampling decision so a sampled txn's
    #: span tree stitches across DCs even when local rates differ.
    #: None on heartbeats, pre-ISSUE-7 frames, and hand-built txns.
    trace_ctx: Optional[Tuple[int, int]] = None

    # ------------------------------------------------------------ queries

    def is_ping(self) -> bool:
        return not self.records

    def origin_commit_wall_us(self) -> Optional[int]:
        """Origin commit wallclock carried by the trace context, or
        None when the frame predates ISSUE 7 / was hand-built."""
        return self.trace_ctx[0] if self.trace_ctx else None

    def last_opid(self) -> int:
        """New stream watermark after this txn (the commit record's opid,
        appended last at the origin; heartbeats keep the old watermark)."""
        if self.is_ping():
            return self.prev_log_opid
        return self.records[-1].op_id.n

    def commit_record(self) -> LogRecord:
        return self.records[-1]

    def commit_time(self) -> int:
        return self.timestamp

    def update_records(self) -> List[LogRecord]:
        return [r for r in self.records if r.kind() == "update"]

    # ------------------------------------------------------- construction

    @staticmethod
    def from_ops(dc_id, partition: int, prev_log_opid: int,
                 records: List[LogRecord]) -> "InterDcTxn":
        """Build from an assembled op group; commit time and snapshot come
        from the trailing commit record (reference inter_dc_txn:from_ops,
        src/inter_dc_txn.erl:48-61)."""
        commit = records[-1]
        assert commit.kind() == "commit", "op group must end with a commit"
        (_dc, commit_time), snapshot_vc = commit.payload[1], commit.payload[2]
        return InterDcTxn(dc_id=dc_id, partition=partition,
                          prev_log_opid=prev_log_opid,
                          snapshot_vc=snapshot_vc, timestamp=commit_time,
                          records=records)

    @staticmethod
    def ping(dc_id, partition: int, prev_log_opid: int,
             timestamp: int) -> "InterDcTxn":
        return InterDcTxn(dc_id=dc_id, partition=partition,
                          prev_log_opid=prev_log_opid, snapshot_vc=None,
                          timestamp=timestamp, records=[])

    # -------------------------------------------------------------- bytes

    def to_bin(self) -> bytes:
        """Topic prefix + serialized body (src/inter_dc_txn.erl:95-105).

        The body is the safe tagged term codec, NOT pickle: frames
        arrive from other DCs over the network, and decoding them must
        never execute anything (antidote_tpu/interdc/termcodec.py)."""
        from antidote_tpu.interdc import termcodec

        return partition_prefix(self.partition) + termcodec.encode(self)

    @staticmethod
    def from_bin(data: bytes) -> "InterDcTxn":
        from antidote_tpu.interdc import termcodec

        txn = termcodec.decode(bytes(data[PARTITION_PREFIX_LEN:]))
        if not isinstance(txn, InterDcTxn):
            raise ValueError("corrupt inter-DC txn frame")
        return txn


@dataclass
class InterDcBatch:
    """A coalesced run of committed txns from ONE (origin DC, partition)
    stream — the batched shipping plane's wire frame (ISSUE 6).

    The txns are contiguous under the stream's opid watermark scheme:
    ``_txns[i].prev_log_opid == _txns[i-1].last_opid()``, so the whole
    frame gap-checks as one span (``first_prev_opid`` .. ``last_opid``)
    in the receiver's SubBuf, and the encoder only ships the span base
    plus the per-txn commit opids.  ``ping_ts`` piggybacks the
    partition's heartbeat (min-prepared time) on a traffic-carrying
    frame so a busy stream pays no standalone ping frames; the receiver
    materializes it as a trailing ping txn.

    The binary form is columnar (termcodec ``_T_BATCH``): uniform int64
    columns for op ids / commit times / update counts, an interned
    type-name table, and memoized VC encoding for the snapshot clocks —
    the per-txn framing, kind strings, and repeated OpId dc / txid /
    VC payloads of the legacy per-txn frames are shared or elided.
    """

    dc_id: Any
    partition: int
    _txns: List["InterDcTxn"]
    #: piggybacked heartbeat stamp (min-prepared time), or None
    ping_ts: Optional[int] = None
    #: compact per-frame trace header (ISSUE 7): ``(tracer sample rate
    #: in permille, ship wallclock µs at frame close)``.  The per-txn
    #: origin-commit wallclocks ride their own varint column (the
    #: txns' ``trace_ctx``); the frame-level header carries what is
    #: uniform across the frame.  None on pre-ISSUE-7 frames.
    trace_hdr: Optional[Tuple[int, int]] = None

    # ------------------------------------------------------------ queries

    def txns(self) -> List["InterDcTxn"]:
        return self._txns

    def first_prev_opid(self) -> int:
        return self._txns[0].prev_log_opid

    def last_opid(self) -> int:
        return self._txns[-1].last_opid()

    def ping_txn(self) -> Optional["InterDcTxn"]:
        """The piggybacked heartbeat as a txn for the delivery path
        (its watermark rides the batch's last opid)."""
        if self.ping_ts is None:
            return None
        return InterDcTxn.ping(self.dc_id, self.partition,
                               self.last_opid(), self.ping_ts)

    def delivery_txns(self, include_ping: bool = True
                      ) -> List["InterDcTxn"]:
        """The frame's txns in stream order, with the piggybacked
        heartbeat (unless suppressed — drop_ping receivers) trailing —
        the ONE unwrap every receiver feeds to SubBuf.process_batch."""
        txns = list(self._txns)
        ping = self.ping_txn() if include_ping else None
        if ping is not None:
            txns.append(ping)
        return txns

    # ------------------------------------------------------- construction

    @staticmethod
    def from_txns(txns: List["InterDcTxn"],
                  ping_ts: Optional[int] = None,
                  trace_hdr: Optional[Tuple[int, int]] = None
                  ) -> "InterDcBatch":
        assert txns, "empty batch (pings ship standalone)"
        head = txns[0]
        for a, b in zip(txns, txns[1:]):
            assert b.prev_log_opid == a.last_opid(), \
                "batch txns must be opid-contiguous"
            assert (b.dc_id, b.partition) == (a.dc_id, a.partition), \
                "batch txns must share one stream"
        return InterDcBatch(dc_id=head.dc_id, partition=head.partition,
                            _txns=list(txns), ping_ts=ping_ts,
                            trace_hdr=trace_hdr)

    # -------------------------------------------------------------- bytes

    def to_bin(self) -> bytes:
        from antidote_tpu.interdc import termcodec

        return partition_prefix(self.partition) + termcodec.encode(self)


def frame_from_bin(data: bytes):
    """Decode one pub/sub frame: an :class:`InterDcTxn` (legacy per-txn
    or heartbeat) or an :class:`InterDcBatch` (the ship plane's
    coalesced frame)."""
    from antidote_tpu.interdc import termcodec

    frame = termcodec.decode(bytes(data[PARTITION_PREFIX_LEN:]))
    if not isinstance(frame, (InterDcTxn, InterDcBatch)):
        raise ValueError("corrupt inter-DC frame")
    return frame


def partition_prefix(partition: int) -> bytes:
    return struct.pack(">Q", partition)


@dataclass
class DcDescriptor:
    """DC membership descriptor exchanged on connect (reference
    inter_dc_manager:get_descriptor, src/inter_dc_manager.erl:49-61)."""

    dc_id: Any
    n_partitions: int
    #: transport addresses: publisher + log-reader endpoints.  For the
    #: in-process bus these are just the registry key; for the TCP
    #: transport, ("host", port) pairs.
    pub_addrs: Tuple = ()
    logreader_addrs: Tuple = ()

"""Causal dependency gate — the inter_dc_dep_vnode equivalent.

Per origin-DC FIFO queues of inbound transactions for one partition; a
transaction applies only when the partition's vector clock dominates the
txn's snapshot with the origin entry zeroed (the origin dependency is
already guaranteed by FIFO order + opid continuity) — reference
try_store, src/inter_dc_dep_vnode.erl:121-154.  Applying a txn appends
its records to the local log without assigning local ids and pushes the
effects into the materializer store (:144-152).  Heartbeats advance the
origin's clock entry to their stamp MINUS ONE — a deliberate hardening
over the reference's inclusive advance (:124-125): the heartbeat's
contract is "no future txn commits with a SMALLER time"
(inter_dc_log_sender_vnode.erl:92), and a commit at EXACTLY the stamp
can still be in flight (Clock-SI commit time = max of prepare times =
the max-prepare partition's min_prepared), so the inclusive form lets a
causal reader pass the stable wait and miss that txn (see
_process_host).  Queues are processed to fixpoint whenever the clock
advances (:96-117).

At a handful of DCs the fixpoint is a host walk over queue heads.  At
hundreds of DCs (BASELINE config 5) the walk is the bottleneck, so past
``batch_threshold`` queued txns the gate switches to the batched device
form: every queued txn's dependency vector is packed into one dense
[N, D] tensor and :func:`gate_fixpoint` runs the whole
iterate-until-stable cascade — dominance test, per-origin FIFO prefix,
watermark advance — as a ``lax.while_loop`` on device (the data-parallel
fixpoint named in SURVEY §7 hard-part (d)).  One device round trip
replaces O(rounds × queued) host VC comparisons.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict

import numpy as np

from antidote_tpu import stats
from antidote_tpu.clocks import VC
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.txn.manager import PartitionRetired


class DependencyGate:
    def __init__(self, pm, own_dc, now_us: Callable[[], int],
                 batch_threshold: int = 48, adapt: bool = True):
        self.pm = pm  # PartitionManager
        self.own_dc = own_dc
        self.now_us = now_us
        #: origin DC -> FIFO of InterDcTxn waiting on their dependencies
        self.queues: Dict[Any, deque] = {}
        #: origin DC -> timestamp watermark of applied txns / heartbeats
        #: (seeded from the recovered log's max commit VC at restart,
        #: reference set_dependency_clock src/inter_dc_dep_vnode.erl:82-83)
        self.applied_vc = VC()
        #: tap invoked after the partition VC advances (feeds the
        #: stable-time tracker, throttled by the caller if needed)
        self.on_clock_update: Callable[[], None] = lambda: None
        #: queued-txn count below which the host head-walk always runs
        #: (dense packing overhead can never pay off on a few txns)
        self.batch_threshold = batch_threshold
        #: above the threshold, pick the path by MEASURED per-txn cost
        #: (EWMA), re-probing the out-of-favor path periodically — the
        #: host/device crossover depends on platform and queue shape
        #: (round-2 verdict: the fixed threshold lost to the host walk
        #: in the measured CPU regime), so it is learned, not guessed.
        #: ``adapt=False`` pins the path by threshold alone (benches).
        self.adapt = adapt
        self._cost_host: float | None = None
        self._cost_batched: float | None = None
        self._batched_warm = False
        self._path_calls = 0
        self._last_proc_us = 0

    # ------------------------------------------------------------ clocks

    def partition_vc(self) -> VC:
        """Applied watermarks per origin + own entry at the local clock
        (any local snapshot entry a remote txn carries is a past local
        time, so `now` always dominates it)."""
        return VC(self.applied_vc).set_dc(self.own_dc, self.now_us())

    def seed_clock(self, vc: VC) -> None:
        self.applied_vc = self.applied_vc.join(vc)

    # ------------------------------------------------------------- ingest

    def enqueue(self, txn: InterDcTxn) -> None:
        # gate-wait clock: _apply reads it back for the dep-gate wait
        # histogram and the admit span of the txn's trace tree
        txn._obs_enq_us = self.now_us()
        q = self.queues.setdefault(txn.dc_id, deque())
        q.append(txn)
        # a txn landing behind its own origin's blocked head cannot
        # change the fixpoint (FIFO: it only applies after the head, and
        # the head's dependencies are unchanged) — skip the full
        # reprocess for backlogged queues so ingest under a partition
        # stays O(1) per frame, except for an occasional pass that picks
        # up heads gated only on the advancing local wall clock
        if len(q) > 1 and (self.now_us() - self._last_proc_us) < 50_000:
            return
        self.process_queues()

    def process_queues(self) -> None:
        """Drain every origin queue to fixpoint: applying a txn (or ping)
        advances the clock, which may unblock other origins' heads.

        A BLOCKED head still advances its origin's clock to
        ``timestamp - 1`` (the reference's blocked-txn rule,
        src/inter_dc_dep_vnode.erl:137-143): delivery is FIFO and
        gap-repaired, so the origin's stream is complete below the
        head's commit time, and another origin's head may depend on a
        time up to it.  Without this, three DCs can cross-deadlock
        after a partition window whose heartbeats were lost — each
        head waiting on a clock entry only another blocked head's
        stream can provide (caught by the multidc chaos test)."""
        self._last_proc_us = self.now_us()
        advanced_any = False
        while True:
            pend = self.pending()
            if pend == 0:
                break
            if pend >= self.batch_threshold:
                advanced_any |= self._timed_pass(pend)
            else:
                advanced_any |= self._process_host()
            head_advanced = False
            for origin, q in self.queues.items():
                if q and not q[0].is_ping() and \
                        self.applied_vc.get_dc(origin) < \
                        q[0].timestamp - 1:
                    self._advance(origin, q[0].timestamp - 1)
                    head_advanced = True
            if not head_advanced:
                break
            advanced_any = True  # clock moved: rerun, it may unblock
        if advanced_any:
            self.on_clock_update()

    def _timed_pass(self, pend: int) -> bool:
        """One above-threshold gating pass via the currently-favored
        path, timing it to keep the per-txn cost estimates honest."""
        import time as _time

        use_batched = self._pick_batched()
        t0 = _time.perf_counter()
        advanced = (self._process_batched() if use_batched
                    else self._process_host())
        per = (_time.perf_counter() - t0) / pend
        if use_batched:
            if not self._batched_warm:
                # the first batched pass pays the one-time XLA compile;
                # seeding the EWMA with it would misjudge the device
                # path by orders of magnitude
                self._batched_warm = True
                return advanced
            self._cost_batched = per if self._cost_batched is None \
                else 0.7 * self._cost_batched + 0.3 * per
        else:
            self._cost_host = per if self._cost_host is None \
                else 0.7 * self._cost_host + 0.3 * per
        return advanced

    def _pick_batched(self) -> bool:
        if not self.adapt:
            return True
        self._path_calls += 1
        if self._cost_batched is None:
            return True   # learn the device path first
        if self._cost_host is None:
            return False  # then the host path at the same scale
        if self._path_calls % 32 == 0:
            # periodic probe of the out-of-favor path: the crossover
            # moves with queue depth and platform load
            return self._cost_batched >= self._cost_host
        return self._cost_batched < self._cost_host

    def _process_host(self) -> bool:
        advanced = False
        progress = True
        while progress:
            progress = False
            for origin, q in self.queues.items():
                while q:
                    txn = q[0]
                    if txn.is_ping():
                        # EXCLUSIVE advance: the ping's contract is "no
                        # FUTURE txn will commit with a SMALLER time"
                        # (reference inter_dc_log_sender_vnode.erl:92)
                        # — the stream is complete only BELOW the
                        # stamp.  A commit at EXACTLY the stamp can
                        # still be in flight: Clock-SI picks commit
                        # time = max(prepare times), so the max-prepare
                        # partition's min_prepared EQUALS the pending
                        # commit's time, and its heartbeat can outrun
                        # the commit record.  The reference advances
                        # inclusively (inter_dc_dep_vnode.erl:122-125)
                        # and carries this µs-level race; in-process
                        # delivery here hits it ~5% of runs (caught by
                        # tests/multidc/test_ring_placement.py under
                        # load), so we harden to ts-1.
                        self._advance(origin, txn.timestamp - 1)
                        q.popleft()
                        progress = advanced = True
                        continue
                    deps = VC(txn.snapshot_vc).set_dc(origin, 0)
                    if self.partition_vc().ge(deps):
                        try:
                            self._apply(txn)
                        except PartitionRetired:
                            # the slice is mid-handoff (cutover set the
                            # retired flag before the ring re-aim): stop
                            # this pass with the txn still queued — the
                            # new owner's sub-buffers resume at the
                            # transferred opid counters, so nothing is
                            # lost when refresh_ring drops this gate
                            return advanced
                        q.popleft()
                        progress = advanced = True
                    else:
                        break
        return advanced

    def _process_batched(self) -> bool:
        """One-shot device gating: pack every queued txn into dense
        tensors, run :func:`gate_fixpoint`, then pop+apply the computed
        FIFO prefixes in queue order.  Equivalent to the host walk (the
        device fixpoint is the same monotone cascade, evaluated
        data-parallel)."""
        import jax.numpy as jnp

        # dense columns: every DC named by a queued txn, the applied
        # watermarks, and the local DC (whose entry reads `now`)
        cols: Dict[Any, int] = {}

        def col_of(dc):
            if dc not in cols:
                cols[dc] = len(cols)
            return cols[dc]

        col_of(self.own_dc)
        for dc in self.applied_vc:
            col_of(dc)
        flat = []  # (origin, pos, txn)
        for origin, q in self.queues.items():
            col_of(origin)
            for pos, txn in enumerate(q):
                if not txn.is_ping():
                    for dc in txn.snapshot_vc:
                        col_of(dc)
                flat.append((origin, pos, txn))
        n = len(flat)
        if n == 0:
            return False
        d = len(cols)
        # pad to stable shapes so the jit cache stays small; padding rows
        # are never ready (deps=+inf) and never block (pos=+inf/2)
        n_pad = max(8, 1 << (n - 1).bit_length())
        d_pad = max(8, 1 << (d - 1).bit_length())
        BIG = np.int64(2**62)
        ss = np.zeros((n_pad, d_pad), dtype=np.int64)
        # padding rows must never be ready: the sentinel sits in column 1
        # because gate_fixpoint zeroes each row's own origin column
        # (padding origin_col is 0, which would erase a column-0 sentinel)
        ss[n:, 1] = BIG
        origin_col = np.zeros(n_pad, dtype=np.int32)
        pos_arr = np.full(n_pad, np.iinfo(np.int32).max // 2, np.int32)
        ts = np.zeros(n_pad, dtype=np.int64)
        ping = np.zeros(n_pad, dtype=bool)
        for i, (origin, pos, txn) in enumerate(flat):
            origin_col[i] = cols[origin]
            pos_arr[i] = pos
            # exclusive ping advance (see _process_host): the kernel
            # folds applied rows' ts into the clock, so a ping row
            # carries ts-1
            ts[i] = txn.timestamp - 1 if txn.is_ping() else txn.timestamp
            if txn.is_ping():
                ping[i] = True
            else:
                for dc, t in txn.snapshot_vc.items():
                    ss[i, cols[dc]] = t
        pvc = np.zeros(d_pad, dtype=np.int64)
        for dc, c in cols.items():
            pvc[c] = self.applied_vc.get_dc(dc)
        # own entry is *replaced* by now, exactly like partition_vc()
        # (the two gating paths must agree regardless of queue depth)
        pvc[cols[self.own_dc]] = self.now_us()

        from antidote_tpu.obs import prof

        with prof.annotate("gate_fixpoint"):
            applied, rounds, new_pvc = gate_fixpoint(
                jnp.asarray(ss), jnp.asarray(origin_col),
                jnp.asarray(pos_arr), jnp.asarray(ts), jnp.asarray(ping),
                jnp.asarray(pvc))
        applied = np.asarray(applied)
        rounds = np.asarray(rounds)
        new_pvc = np.asarray(new_pvc)

        # replay in (round, fifo pos) order: round-r txns depend only on
        # rounds < r, so this is a causal apply order (see gate_fixpoint)
        order = sorted(
            (i for i in range(n) if applied[i]),
            key=lambda i: (int(rounds[i]), flat[i][1]))
        advanced = False
        for i in order:
            origin, pos, txn = flat[i]
            q = self.queues[origin]
            assert q[0] is txn, "device fixpoint applied out of FIFO order"
            q.popleft()
            if txn.is_ping():
                # exclusive ping advance (see _process_host)
                self._advance(origin, txn.timestamp - 1)
            else:
                try:
                    self._apply(txn)
                except PartitionRetired:
                    # mid-handoff (see _process_host): re-queue and
                    # stop WITHOUT folding the fixpoint clock — the
                    # fold would cover the unapplied remainder
                    q.appendleft(txn)
                    return advanced
            advanced = True
        # fold the kernel's final clock back AFTER the replay (it
        # includes the blocked-head ts-1 advances; advancing before the
        # records hit the materializer would let a concurrent
        # partition_vc() reader see a stable time covering unapplied
        # txns).  Applied watermarks are already in via _apply, so only
        # the ts-1 component is new; the own column carried `now`, not
        # an applied watermark — skip it.
        for dc, c in cols.items():
            if dc != self.own_dc and int(new_pvc[c]) > \
                    self.applied_vc.get_dc(dc):
                self._advance(dc, int(new_pvc[c]))
                advanced = True
        return advanced

    def _advance(self, origin, ts: int) -> None:
        if ts > self.applied_vc.get_dc(origin):
            self.applied_vc = self.applied_vc.set_dc(origin, ts)

    def _apply(self, txn: InterDcTxn) -> None:
        # getattr: harness fakes (tests/unit/test_dep_gate.py) enqueue
        # opaque record stubs — an untagged span still times the apply
        txid = (getattr(txn.records[-1], "txid", None)
                if txn.records else None)
        enq = getattr(txn, "_obs_enq_us", None)
        wait_s = (max(self.now_us() - enq, 0) / 1e6
                  if enq is not None else 0.0)
        with tracer.span("depgate_admit", "interdc", txid=txid,
                         origin=str(txn.dc_id), wait_s=wait_s):
            self.pm.apply_remote(txn.records, txn.dc_id, txn.timestamp,
                                 txn.snapshot_vc)
        stats.registry.depgate_wait.observe(wait_s)
        recorder.record("interdc", "depgate_admit", txid=txid,
                        origin=str(txn.dc_id), wait_s=wait_s,
                        timestamp=txn.timestamp)
        self._advance(txn.dc_id, txn.timestamp)

    def pending(self) -> int:
        return sum(len(q) for q in self.queues.values())


def ready_mask(queued_ss, queued_origin, partition_vc):
    """Batched dependency check on device: which queued txns may apply now.

    ``queued_ss``: int64[N, D] snapshot VCs; ``queued_origin``: int32[N]
    dense origin columns; ``partition_vc``: int64[D].  Returns bool[N].
    The origin entry is zeroed before the dominance test exactly as in
    try_store (reference src/inter_dc_dep_vnode.erl:131-136).
    """
    from antidote_tpu.clocks import dense

    deps = dense.set_dc(queued_ss, queued_origin, 0)
    return dense.ge(partition_vc, deps)


_GATE_JIT = None


def gate_fixpoint(ss, origin, pos, ts, is_ping, pvc):
    """Device iterate-until-stable over the whole queued set: returns
    (applied bool[N], round int32[N], final partition VC int64[D]).

    Each round evaluates, data-parallel over all N queued txns:
      ready    = ping | (pvc >= deps)           (:func:`ready_mask`)
      applied  = ready ∧ FIFO-prefix            (a txn applies only if
                 every earlier txn of its origin queue applies — the
                 per-origin min position of a not-ready txn bounds it)
      pvc     |= per-origin max commit ts of applied txns
    and repeats while pvc still advances — the same monotone cascade the
    host walk performs head-by-head (reference
    src/inter_dc_dep_vnode.erl:96-154), as one ``lax.while_loop``.
    Terminates because applied/pvc are monotone; the round count is
    bounded by the longest dependency chain through the queues (up to
    the total queued-txn count for a fully serialized cascade).

    ``round[i]`` is the round at which txn i became applicable.  A
    round-r txn's dependencies were satisfied by the clock of round r-1,
    so it cannot depend on any other round-r txn: replaying applies
    sorted by (round, fifo pos) is causally safe, which is how the host
    caller restores the reference's apply-in-dependency-order behavior.
    """
    global _GATE_JIT
    if _GATE_JIT is None:
        import jax
        import jax.numpy as jnp

        from antidote_tpu.clocks import dense

        def _fixpoint(ss, origin, pos, ts, is_ping, pvc):
            d = pvc.shape[0]
            n = ss.shape[0]
            big = jnp.asarray(np.iinfo(np.int32).max, jnp.int32)

            def round_(pvc):
                ready = is_ping | ready_mask(ss, origin, pvc)   # [N]
                notready_pos = jnp.where(ready, big, pos)
                blocked_min = jnp.full((d,), big, jnp.int32).at[origin].min(
                    notready_pos, mode="drop")
                applied = ready & (pos < blocked_min[origin])
                wm = jnp.zeros((d,), ts.dtype).at[origin].max(
                    jnp.where(applied, ts, 0), mode="drop")
                # blocked-head rule (reference
                # src/inter_dc_dep_vnode.erl:137-143): a head that
                # cannot apply still advances its origin's clock to
                # ts-1 — FIFO + gap repair mean the origin's stream is
                # complete below it, and other origins' heads may
                # depend on a time up to it.  Padding rows contribute
                # ts-1 = -1, which the max-with-0 init discards.
                head_blocked = (~ready) & (pos == blocked_min[origin])
                hb = jnp.zeros((d,), ts.dtype).at[origin].max(
                    jnp.where(head_blocked, ts - 1, 0), mode="drop")
                return applied, jnp.maximum(pvc, jnp.maximum(wm, hb))

            def note_round(rounds, applied, r):
                newly = applied & (rounds < 0)
                return jnp.where(newly, r, rounds)

            def cond(carry):
                _, _, _, changed = carry
                return changed

            def body(carry):
                rounds, pvc, r, _ = carry
                applied, new_pvc = round_(pvc)
                rounds = note_round(rounds, applied, r)
                return (rounds, new_pvc, r + 1,
                        jnp.any(new_pvc != pvc))

            rounds0 = jnp.full((n,), -1, jnp.int32)
            rounds, pvc, r, _ = jax.lax.while_loop(
                cond, body,
                (rounds0, pvc, jnp.asarray(0, jnp.int32),
                 jnp.asarray(True)))
            # the loop exits after a round that did not advance pvc;
            # evaluate once more at the stable clock (covers the
            # no-progress-first-round case)
            applied, _ = round_(pvc)
            rounds = note_round(rounds, applied, r)
            return applied, rounds, pvc

        from antidote_tpu.obs import prof as _prof

        # kernel-span wrapped: the gate's padded-shape jit cache is the
        # classic recompilation-storm source (every new (n_pad, d_pad)
        # pair compiles), which the compile-miss counter now attributes
        _GATE_JIT = _prof.profiler.wrap(
            jax.jit(_fixpoint), name="gate_fixpoint",
            subsystem="interdc.dep")
    return _GATE_JIT(ss, origin, pos, ts, is_ping, pvc)

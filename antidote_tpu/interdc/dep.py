"""Causal dependency gate — the inter_dc_dep_vnode equivalent.

Per origin-DC FIFO queues of inbound transactions for one partition; a
transaction applies only when the partition's vector clock dominates the
txn's snapshot with the origin entry zeroed (the origin dependency is
already guaranteed by FIFO order + opid continuity) — reference
try_store, src/inter_dc_dep_vnode.erl:121-154.  Applying a txn appends
its records to the local log without assigning local ids and pushes the
effects into the materializer store (:144-152).  Heartbeats advance the
origin's clock entry to their stamp MINUS ONE — a deliberate hardening
over the reference's inclusive advance (:124-125): the heartbeat's
contract is "no future txn commits with a SMALLER time"
(inter_dc_log_sender_vnode.erl:92), and a commit at EXACTLY the stamp
can still be in flight (Clock-SI commit time = max of prepare times =
the max-prepare partition's min_prepared), so the inclusive form lets a
causal reader pass the stable wait and miss that txn (see
_process_host).  Queues are processed to fixpoint whenever the clock
advances (:96-117).

At a handful of DCs the fixpoint is a host walk over queue heads.  At
hundreds of DCs (BASELINE config 5) the walk is the bottleneck, so past
``batch_threshold`` queued txns the gate switches to the batched device
form.  ISSUE 3 made that form *device-resident*: instead of re-packing
every queued txn into fresh host tensors per pass (six uploads + three
fetches per ``process_queues`` call — worst-case repack cost on every
delivery), each gate keeps a persistent padded ring on device
(interdc/gate_kernels.py) that is appended to incrementally on arrival
(one small donated scatter per batch of arrivals), retired/compacted in
place, and driven by :func:`gate_kernels.ring_fixpoint` — the same
data-parallel iterate-until-stable cascade (SURVEY §7 hard-part (d)),
whose only mandatory fetch is a scalar applied-count.  A short
coalescing window on ``enqueue`` turns a burst of deliveries into ONE
device dispatch; the GATE_* metric families (stats.py) record the
amortization ratio the benches gate on.  ``device_ring=False`` keeps
the pre-ISSUE-3 repack form (the benches' comparison baseline).
"""

from __future__ import annotations

import time
from collections import deque
from itertools import islice
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from antidote_tpu import stats
from antidote_tpu.clocks import VC
from antidote_tpu.config import Config as _Config
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.txn.manager import PartitionRetired

#: the gate knobs' single source of truth is Config's field defaults
#: (config.py) — direct DependencyGate(...) constructions (tests,
#: benches' "production defaults" rows) inherit exactly what a
#: config-built node gets, so tuning a default cannot silently fork
#: the two populations
_KNOB = {k: _Config.__dataclass_fields__[f"gate_{k}"].default
         for k in ("batch_threshold", "device_ring", "ring_capacity",
                   "coalesce_us", "compact_frac")}

#: dispatch kinds of the device gate path (the ``kind`` label of
#: antidote_gate_device_dispatches_total; ``fixpoint`` is shared with
#: the legacy repack path so dispatch-amortization diffs are honest)
GATE_DISPATCH_KINDS = ("fixpoint", "append", "retire", "gather")


def _note_gate_dispatch(kind: str, h2d: int = 0, d2h: int = 0) -> None:
    reg = stats.registry
    reg.gate_dispatches.inc(kind=kind)
    if h2d:
        reg.gate_h2d_bytes.inc(h2d)
    if d2h:
        reg.gate_d2h_bytes.inc(d2h)


def _note_gate_admitted(n: int) -> None:
    """Bump the admitted counter and refresh the amortization gauge —
    admitted txns per device dispatch over the process lifetime, the
    panel the steady-stream bench gates on."""
    reg = stats.registry
    reg.gate_admitted_batched.inc(n)
    total = sum(reg.gate_dispatches.value(kind=k)
                for k in GATE_DISPATCH_KINDS)
    if total:
        reg.gate_admitted_per_dispatch.set(
            reg.gate_admitted_batched.value() / total)


def _pack_txn_row(txn, cols: Dict[Any, int], ss_row) -> Tuple[int, bool]:
    """Encode one queued txn into a dense dependency row: fill
    ``ss_row`` (an int64[D] view) with the snapshot VC under the
    ``cols`` column map and return (ts, is_ping), with the ping's
    EXCLUSIVE ts-1 advance (see _process_host) already applied.  The
    ONE row encoding shared by the ring append, the ring bulk load,
    and the legacy repack packer — test_batched_matches_host_walk
    relies on the three staying bit-for-bit equivalent."""
    if txn.is_ping():
        return txn.timestamp - 1, True
    for dc, t in txn.snapshot_vc.items():
        ss_row[cols[dc]] = t
    return txn.timestamp, False


def gate_from_config(pm, own_dc, now_us: Callable[[], int],
                     config) -> "DependencyGate":
    """A DependencyGate honoring the node Config's gate_* knobs — the
    one construction path every assembly (single-DC, inter-DC, and
    cluster federation) must share, so a knob like
    ``gate_device_ring=False`` cannot silently apply to some gates and
    not others."""
    return DependencyGate(
        pm, own_dc, now_us,
        batch_threshold=config.gate_batch_threshold,
        device_ring=config.gate_device_ring,
        ring_capacity=config.gate_ring_capacity,
        coalesce_us=config.gate_coalesce_us,
        compact_frac=config.gate_compact_frac)


class DependencyGate:
    def __init__(self, pm, own_dc, now_us: Callable[[], int],
                 batch_threshold: int = _KNOB["batch_threshold"],
                 adapt: bool = True,
                 device_ring: bool = _KNOB["device_ring"],
                 ring_capacity: int = _KNOB["ring_capacity"],
                 coalesce_us: int = _KNOB["coalesce_us"],
                 compact_frac: float = _KNOB["compact_frac"]):
        self.pm = pm  # PartitionManager
        self.own_dc = own_dc
        self.now_us = now_us
        #: origin DC -> FIFO of InterDcTxn waiting on their dependencies
        self.queues: Dict[Any, deque] = {}
        #: origin DC -> timestamp watermark of applied txns / heartbeats
        #: (seeded from the recovered log's max commit VC at restart,
        #: reference set_dependency_clock src/inter_dc_dep_vnode.erl:82-83)
        self.applied_vc = VC()
        #: tap invoked after the partition VC advances (feeds the
        #: stable-time tracker, throttled by the caller if needed)
        self.on_clock_update: Callable[[], None] = lambda: None
        #: queued-txn count below which the host head-walk always runs
        #: (dense packing overhead can never pay off on a few txns)
        self.batch_threshold = batch_threshold
        #: above the threshold, pick the path by MEASURED per-txn cost
        #: (EWMA), re-probing the out-of-favor path periodically — the
        #: host/device crossover depends on platform and queue shape
        #: (round-2 verdict: the fixed threshold lost to the host walk
        #: in the measured CPU regime), so it is learned, not guessed.
        #: ``adapt=False`` pins the path by threshold alone (benches).
        self.adapt = adapt
        #: the device-resident ring form (ISSUE 3); False = the legacy
        #: per-pass repack (kept as the benches' comparison baseline)
        self.device_ring = device_ring
        #: initial ring capacity (rounded up to a power of two; grows
        #: by device-side gather on demand)
        self.ring_capacity = ring_capacity
        #: enqueue-coalescing window, µs: while the batched regime is
        #: active and a pass ran within the window, further enqueues
        #: only stage — one device dispatch admits the whole burst.
        #: 0 disables (every head enqueue processes immediately).
        self.coalesce_us = coalesce_us
        #: dead-slot fraction past which the ring compacts (shrinks)
        self.compact_frac = compact_frac
        #: origins this DC is PARTIALLY subscribed to (ISSUE 18,
        #: docs/interest_routing.md §4): origin -> announced range
        #: count.  A qualifier, not a gate rule — ``applied_vc[origin]``
        #: for these origins means "applied within the subscribed
        #: ranges"; advancement itself is untouched because heartbeat
        #: pings are interest-independent and their min_prepared bounds
        #: subscribed and elided txns alike.
        self.subscribed_ranges: Dict[Any, int] = {}
        self._ring: Optional[_DeviceRing] = None
        self._cost_host: float | None = None
        self._cost_batched: float | None = None
        self._batched_warm = False
        self._path_calls = 0
        self._last_proc_us = 0

    # ------------------------------------------------------------ clocks

    def partition_vc(self) -> VC:
        """Applied watermarks per origin + own entry at the local clock
        (any local snapshot entry a remote txn carries is a past local
        time, so `now` always dominates it)."""
        return VC(self.applied_vc).set_dc(self.own_dc, self.now_us())

    def seed_clock(self, vc: VC) -> None:
        self.applied_vc = self.applied_vc.join(vc)

    def note_subscription(self, origin, n_ranges: Optional[int]) -> None:
        """Record that ``origin``'s stream is interest-filtered to
        ``n_ranges`` key ranges (None = full subscription again) — the
        partial-subscription qualifier queue_stats surfaces so an
        operator can tell a lagging origin from a partially-subscribed
        one (ISSUE 18)."""
        if n_ranges is None:
            self.subscribed_ranges.pop(origin, None)
        else:
            self.subscribed_ranges[origin] = int(n_ranges)

    # ------------------------------------------------------------- ingest

    def enqueue(self, txn: InterDcTxn) -> None:
        self.enqueue_batch([txn])

    def enqueue_batch(self, txns: List[InterDcTxn]) -> None:
        """Stage one arrival — a single delivery or a whole wire
        batch's txns (ISSUE 6) — then run at most ONE gating pass: the
        ring appends the arrival in one scatter and the fixpoint
        admits it in one dispatch, instead of a pass per txn.

        Skip rules: txns landing behind their origins' blocked heads
        cannot change the fixpoint (FIFO: they only apply after the
        head, whose dependencies are unchanged) — an all-backlogged
        arrival skips the reprocess so ingest under a partition stays
        O(1) per frame, except for an occasional pass that picks up
        heads gated only on the advancing local wall clock.  And the
        coalescing window (ISSUE 3): in the batched regime, arrivals
        right after a pass stage instead of dispatching — the next
        pass admits the whole burst with ONE device fixpoint."""
        if not txns:
            return
        now = self.now_us()
        head_new = False
        for txn in txns:
            # gate-wait clock: _apply reads it back for the dep-gate
            # wait histogram and the admit span of the txn's trace tree
            txn._obs_enq_us = now
            q = self.queues.setdefault(txn.dc_id, deque())
            q.append(txn)
            head_new |= len(q) == 1
        since_proc = now - self._last_proc_us
        if not head_new and since_proc < 50_000:
            return
        if (self.coalesce_us > 0 and 0 <= since_proc < self.coalesce_us
                and self.pending() >= self.batch_threshold):
            stats.registry.gate_coalesced.inc(len(txns))
            return
        self.process_queues()

    def process_queues(self) -> None:
        """Drain every origin queue to fixpoint: applying a txn (or ping)
        advances the clock, which may unblock other origins' heads.

        A BLOCKED head still advances its origin's clock to
        ``timestamp - 1`` (the reference's blocked-txn rule,
        src/inter_dc_dep_vnode.erl:137-143): delivery is FIFO and
        gap-repaired, so the origin's stream is complete below the
        head's commit time, and another origin's head may depend on a
        time up to it.  Without this, three DCs can cross-deadlock
        after a partition window whose heartbeats were lost — each
        head waiting on a clock entry only another blocked head's
        stream can provide (caught by the multidc chaos test)."""
        self._last_proc_us = self.now_us()
        advanced_any = False
        while True:
            pend = self.pending()
            if pend == 0:
                break
            if pend >= self.batch_threshold:
                advanced_any |= self._timed_pass(pend)
            else:
                advanced_any |= self._process_host()
            head_advanced = False
            for origin, q in self.queues.items():
                if q and not q[0].is_ping() and \
                        self.applied_vc.get_dc(origin) < \
                        q[0].timestamp - 1:
                    self._advance(origin, q[0].timestamp - 1)
                    head_advanced = True
            if not head_advanced:
                break
            advanced_any = True  # clock moved: rerun, it may unblock
        if advanced_any:
            self.on_clock_update()

    def _timed_pass(self, pend: int) -> bool:
        """One above-threshold gating pass via the currently-favored
        path, timing it to keep the per-txn cost estimates honest."""
        import time as _time

        use_batched = self._pick_batched()
        t0 = _time.perf_counter()
        advanced = (self._process_batched() if use_batched
                    else self._process_host())
        per = (_time.perf_counter() - t0) / pend
        if use_batched:
            if not self._batched_warm:
                # the first batched pass pays the one-time XLA compile;
                # seeding the EWMA with it would misjudge the device
                # path by orders of magnitude
                self._batched_warm = True
                return advanced
            self._cost_batched = per if self._cost_batched is None \
                else 0.7 * self._cost_batched + 0.3 * per
        else:
            self._cost_host = per if self._cost_host is None \
                else 0.7 * self._cost_host + 0.3 * per
        return advanced

    def _pick_batched(self) -> bool:
        if not self.adapt:
            return True
        self._path_calls += 1
        if self._cost_batched is None:
            return True   # learn the device path first
        if self._cost_host is None:
            return False  # then the host path at the same scale
        if self._path_calls % 32 == 0:
            # periodic probe of the out-of-favor path: the crossover
            # moves with queue depth and platform load
            return self._cost_batched >= self._cost_host
        return self._cost_batched < self._cost_host

    def _process_host(self) -> bool:
        advanced = False
        progress = True
        while progress:
            progress = False
            for origin, q in self.queues.items():
                while q:
                    txn = q[0]
                    if txn.is_ping():
                        # EXCLUSIVE advance: the ping's contract is "no
                        # FUTURE txn will commit with a SMALLER time"
                        # (reference inter_dc_log_sender_vnode.erl:92)
                        # — the stream is complete only BELOW the
                        # stamp.  A commit at EXACTLY the stamp can
                        # still be in flight: Clock-SI picks commit
                        # time = max(prepare times), so the max-prepare
                        # partition's min_prepared EQUALS the pending
                        # commit's time, and its heartbeat can outrun
                        # the commit record.  The reference advances
                        # inclusively (inter_dc_dep_vnode.erl:122-125)
                        # and carries this µs-level race; in-process
                        # delivery here hits it ~5% of runs (caught by
                        # tests/multidc/test_ring_placement.py under
                        # load), so we harden to ts-1.
                        self._advance(origin, txn.timestamp - 1)
                        q.popleft()
                        progress = advanced = True
                        continue
                    deps = VC(txn.snapshot_vc).set_dc(origin, 0)
                    if self.partition_vc().ge(deps):
                        try:
                            self._apply(txn)
                        except PartitionRetired:
                            # the slice is mid-handoff (cutover set the
                            # retired flag before the ring re-aim): stop
                            # this pass with the txn still queued — the
                            # new owner's sub-buffers resume at the
                            # transferred opid counters, so nothing is
                            # lost when refresh_ring drops this gate
                            return advanced
                        q.popleft()
                        progress = advanced = True
                    else:
                        break
        return advanced

    # ------------------------------------------------- batched (device)

    def _process_batched(self) -> bool:
        """One above-threshold gating pass on device: the resident-ring
        form by default, the legacy repack form under
        ``device_ring=False``.  Both compute exactly the host walk's
        applied set, order, and final clock."""
        if not self.device_ring:
            return self._process_batched_repack()
        if self._ring is None:
            self._ring = _DeviceRing(self)
        ring = self._ring
        ring.sync()
        if ring.n_live == 0:
            return False
        napp, applied, rounds, new_pvc = ring.run_fixpoint()
        advanced = False
        completed = True
        if napp:
            # replay in (round, fifo pos) order: round-r txns depend
            # only on rounds < r, so this is a causal apply order (see
            # gate_kernels.ring_fixpoint)
            order = sorted(ring.applied_entries(applied),
                           key=lambda e: (int(rounds[e[0]]), e[2]))
            ring.begin_wave()
            for slot, origin, _pos, txn in order:
                q = self.queues[origin]
                assert q[0] is txn, \
                    "device fixpoint applied out of FIFO order"
                q.popleft()
                if txn.is_ping():
                    # exclusive ping advance (see _process_host)
                    ring.pop_applied(slot)
                    self._advance(origin, txn.timestamp - 1)
                else:
                    try:
                        self._apply(txn)
                    except PartitionRetired:
                        # mid-handoff (see _process_host): re-queue and
                        # stop WITHOUT folding the fixpoint clock — the
                        # fold would cover the unapplied remainder.
                        # Slots admitted so far retire at the next sync.
                        q.appendleft(txn)
                        completed = False
                        break
                    ring.pop_applied(slot)
                advanced = True
            ring.finish_wave(completed)
            _note_gate_admitted(len(ring.last_wave))
        if not completed:
            return advanced
        # fold the kernel's final clock back AFTER the replay (it
        # includes the blocked-head ts-1 advances; advancing before the
        # records hit the materializer would let a concurrent
        # partition_vc() reader see a stable time covering unapplied
        # txns).  Applied watermarks are already in via _apply, so only
        # the ts-1 component is new; the own column carried `now`, not
        # an applied watermark — skip it.
        for dc, c in ring.cols.items():
            if dc != self.own_dc and int(new_pvc[c]) > \
                    self.applied_vc.get_dc(dc):
                self._advance(dc, int(new_pvc[c]))
                advanced = True
        return advanced

    def _process_batched_repack(self) -> bool:
        """The pre-ISSUE-3 one-shot device gating: pack every queued
        txn into dense tensors, run :func:`gate_fixpoint`, then
        pop+apply the computed FIFO prefixes in queue order.
        Equivalent to the host walk (the device fixpoint is the same
        monotone cascade, evaluated data-parallel) — and to the ring
        form, which amortizes exactly this path's per-pass repack,
        upload, and fetch (GATE_* counters record both)."""
        import jax.numpy as jnp

        # dense columns: every DC named by a queued txn, the applied
        # watermarks, and the local DC (whose entry reads `now`)
        cols: Dict[Any, int] = {}

        def col_of(dc):
            if dc not in cols:
                cols[dc] = len(cols)
            return cols[dc]

        col_of(self.own_dc)
        for dc in self.applied_vc:
            col_of(dc)
        flat = []  # (origin, pos, txn)
        for origin, q in self.queues.items():
            col_of(origin)
            for pos, txn in enumerate(q):
                if not txn.is_ping():
                    for dc in txn.snapshot_vc:
                        col_of(dc)
                flat.append((origin, pos, txn))
        n = len(flat)
        if n == 0:
            return False
        d = len(cols)
        # pad to stable shapes so the jit cache stays small; padding rows
        # are never ready (deps=+inf) and never block (pos=+inf/2)
        n_pad = max(8, 1 << (n - 1).bit_length())
        d_pad = max(8, 1 << (d - 1).bit_length())
        BIG = np.int64(2**62)
        ss = np.zeros((n_pad, d_pad), dtype=np.int64)
        # padding rows must never be ready: the sentinel sits in column 1
        # because gate_fixpoint zeroes each row's own origin column
        # (padding origin_col is 0, which would erase a column-0 sentinel)
        ss[n:, 1] = BIG
        origin_col = np.zeros(n_pad, dtype=np.int32)
        pos_arr = np.full(n_pad, np.iinfo(np.int32).max // 2, np.int32)
        ts = np.zeros(n_pad, dtype=np.int64)
        ping = np.zeros(n_pad, dtype=bool)
        for i, (origin, pos, txn) in enumerate(flat):
            origin_col[i] = cols[origin]
            pos_arr[i] = pos
            ts[i], ping[i] = _pack_txn_row(txn, cols, ss[i])
        pvc = np.zeros(d_pad, dtype=np.int64)
        for dc, c in cols.items():
            pvc[c] = self.applied_vc.get_dc(dc)
        # own entry is *replaced* by now, exactly like partition_vc()
        # (the two gating paths must agree regardless of queue depth)
        pvc[cols[self.own_dc]] = self.now_us()

        from antidote_tpu.obs import prof

        with prof.annotate("gate_fixpoint"):
            applied, rounds, new_pvc = gate_fixpoint(
                jnp.asarray(ss), jnp.asarray(origin_col),
                jnp.asarray(pos_arr), jnp.asarray(ts), jnp.asarray(ping),
                jnp.asarray(pvc))
        applied = np.asarray(applied)
        rounds = np.asarray(rounds)
        new_pvc = np.asarray(new_pvc)
        _note_gate_dispatch(
            "fixpoint",
            h2d=(ss.nbytes + origin_col.nbytes + pos_arr.nbytes
                 + ts.nbytes + ping.nbytes + pvc.nbytes),
            d2h=applied.nbytes + rounds.nbytes + new_pvc.nbytes)

        # replay in (round, fifo pos) order: round-r txns depend only on
        # rounds < r, so this is a causal apply order (see gate_fixpoint)
        order = sorted(
            (i for i in range(n) if applied[i]),
            key=lambda i: (int(rounds[i]), flat[i][1]))
        advanced = False
        admitted = 0
        for i in order:
            origin, pos, txn = flat[i]
            q = self.queues[origin]
            assert q[0] is txn, "device fixpoint applied out of FIFO order"
            q.popleft()
            if txn.is_ping():
                # exclusive ping advance (see _process_host)
                self._advance(origin, txn.timestamp - 1)
            else:
                try:
                    self._apply(txn)
                except PartitionRetired:
                    # mid-handoff (see _process_host): re-queue and
                    # stop WITHOUT folding the fixpoint clock — the
                    # fold would cover the unapplied remainder
                    q.appendleft(txn)
                    _note_gate_admitted(admitted)
                    return advanced
            admitted += 1
            advanced = True
        _note_gate_admitted(admitted)
        # fold the kernel's final clock back AFTER the replay (it
        # includes the blocked-head ts-1 advances; advancing before the
        # records hit the materializer would let a concurrent
        # partition_vc() reader see a stable time covering unapplied
        # txns).  Applied watermarks are already in via _apply, so only
        # the ts-1 component is new; the own column carried `now`, not
        # an applied watermark — skip it.
        for dc, c in cols.items():
            if dc != self.own_dc and int(new_pvc[c]) > \
                    self.applied_vc.get_dc(dc):
                self._advance(dc, int(new_pvc[c]))
                advanced = True
        return advanced

    def _advance(self, origin, ts: int) -> None:
        if ts > self.applied_vc.get_dc(origin):
            self.applied_vc = self.applied_vc.set_dc(origin, ts)

    def _apply(self, txn: InterDcTxn) -> None:
        # getattr: harness fakes (tests/unit/test_dep_gate.py) enqueue
        # opaque record stubs — an untagged span still times the apply
        txid = (getattr(txn.records[-1], "txid", None)
                if txn.records else None)
        enq = getattr(txn, "_obs_enq_us", None)
        wait_s = (max(self.now_us() - enq, 0) / 1e6
                  if enq is not None else 0.0)
        with tracer.span("depgate_admit", "interdc", txid=txid,
                         origin=str(txn.dc_id), wait_s=wait_s):
            self.pm.apply_remote(txn.records, txn.dc_id, txn.timestamp,
                                 txn.snapshot_vc)
        stats.registry.depgate_wait.observe(wait_s)
        recorder.record("interdc", "depgate_admit", txid=txid,
                        origin=str(txn.dc_id), wait_s=wait_s,
                        timestamp=txn.timestamp)
        # visibility SLO (ISSUE 7): the txn's records just landed in
        # the local log + materializer — THIS is ingest-visibility
        # time.  The carried origin-commit wallclock (wire trace_ctx)
        # turns it into the commit->remote-visible latency Cure's
        # whole design is about, per (observing dc, origin peer).
        tctx = getattr(txn, "trace_ctx", None)
        if tctx is not None:
            vis_lag_s = max(time.time_ns() // 1000 - tctx[0], 0) / 1e6
            stats.registry.vis_lag.observe(
                vis_lag_s, dc=str(self.own_dc), peer=str(txn.dc_id))
            tracer.instant("interdc_visible", "interdc", txid=txid,
                           origin=str(txn.dc_id),
                           vis_lag_s=round(vis_lag_s, 6))
        self._advance(txn.dc_id, txn.timestamp)

    def pending(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def queue_stats(self) -> dict:
        """This gate's backlog + ring occupancy for the pipeline
        snapshot (obs/pipeline.py): per-origin queue depths, the
        applied watermark vector, and — when the device ring is live —
        its slot occupancy."""
        ring = None
        if self._ring is not None:
            ring = {"live_slots": self._ring.n_live,
                    "capacity": self._ring.cap,
                    "clock_columns": len(self._ring.cols),
                    "retire_pending": len(self._ring.retire_pending)}
        return {
            "pending": self.pending(),
            "queues": {str(o): len(q) for o, q in self.queues.items()
                       if q},
            "applied_vc": {str(k): v
                           for k, v in dict(self.applied_vc).items()},
            # partially-subscribed origins (ISSUE 18): their applied
            # watermark means "within the subscribed ranges" — rendered
            # so a lag investigation doesn't mistake filtering for it
            "partial_origins": {str(o): n for o, n
                                in self.subscribed_ranges.items()},
            "ring": ring,
        }


class _DeviceRing:
    """Host bookkeeping of one gate's device-resident ring (ISSUE 3).

    The device side (interdc/gate_kernels.py) holds padded per-slot
    rows; this side maps slots to queued txns:

    - ``mirror``: origin -> deque of (slot, txn, pos) in FIFO order —
      always a suffix-extended copy of the gate's queue (pops happen
      only at the head, appends only at the tail, so ``sync`` can
      reconcile by identity from the head);
    - ``slot_entry``: slot -> (origin, pos, txn) for replaying an
      admission wave from the fetched applied-mask;
    - ``free`` / ``retire_pending``: reusable slots, and slots whose
      device ``live`` bit is still set because their txn left the
      queue outside a ring replay (host-walk pass in between, or a
      wave aborted on PartitionRetired) — retired in ONE scatter at
      the next sync, before the fixpoint can see them;
    - ``cols``: persistent dense column map (grows only; a width
      overflow re-lays the ring out via a device-side gather, no
      re-upload).

    FIFO positions are per-origin monotone counters, NOT queue
    indices: a popped head leaves a gap, which the fixpoint's
    min-position prefix rule tolerates by construction.
    """

    def __init__(self, gate: DependencyGate):
        self.gate = gate
        self.init_cap = max(8, 1 << (max(gate.ring_capacity, 8) - 1)
                            .bit_length())
        self.cap = 0
        self.d_pad = 8
        self.cols: Dict[Any, int] = {}
        self.dev = None  # (ss, origin, pos, ts, ping, live) on device
        self.mirror: Dict[Any, deque] = {}
        self.slot_entry: List[Optional[Tuple[Any, int, Any]]] = []
        self.free: List[int] = []
        self.retire_pending: List[int] = []
        self.pos_next: Dict[Any, int] = {}
        self.n_live = 0
        #: slots admitted by the wave currently replaying
        self.last_wave: List[int] = []
        self._pending_live = None

    # ------------------------------------------------------------ columns

    def _col_of(self, dc) -> int:
        c = self.cols.get(dc)
        if c is None:
            c = self.cols[dc] = len(self.cols)
        return c

    # --------------------------------------------------------------- sync

    def sync(self) -> None:
        """Reconcile the ring with the gate's queues: retire slots
        popped outside a ring replay, re-layout (grow / widen /
        compact) when needed, and append new arrivals — each step at
        most one small device dispatch."""
        gate = self.gate
        # 0. FIFO positions are monotone per origin and never reset in
        #    place; long before int32 arithmetic could wrap, renumber
        #    through a full rebuild (queues keep every live txn, so
        #    this loses nothing)
        if self.pos_next and max(self.pos_next.values()) > (1 << 30):
            self.invalidate()
        # 1. heads popped outside the ring replay (host-walk pass ran
        #    in between, or an aborted wave re-queued its remainder)
        for origin, dq in self.mirror.items():
            q = gate.queues.get(origin)
            while dq and (not q or dq[0][1] is not q[0]):
                slot, _txn, _pos = dq.popleft()
                self.slot_entry[slot] = None
                self.retire_pending.append(slot)
                self.n_live -= 1
        # 2. new tail arrivals (per-origin FIFO order preserved)
        fresh: List[Tuple[Any, Any]] = []
        for origin, q in gate.queues.items():
            have = len(self.mirror.get(origin) or ())
            if len(q) > have:
                for txn in islice(q, have, None):
                    fresh.append((origin, txn))
        # 3. column map growth (persistent: existing rows keep their
        #    columns; a new DC is a fresh zero column)
        self._col_of(gate.own_dc)
        for origin, txn in fresh:
            self._col_of(origin)
            if not txn.is_ping():
                for dc in txn.snapshot_vc:
                    self._col_of(dc)
        need_d = max(8, 1 << (len(self.cols) - 1).bit_length())
        # 4a. empty-ring bulk fast path: with nothing resident, a large
        #     arrival batch uploads as six dense arrays directly (the
        #     repack path's exact economy — no scatter, no stale state
        #     to reconcile), so a bulk-packed queue pays no ring
        #     penalty; the scatter append below is the incremental
        #     steady-state path
        if self.dev is None or (self.n_live == 0
                                and 2 * len(fresh) >= self.cap):
            if fresh:
                self._bulk_load(need_d, fresh)
            elif self.dev is None:
                self._build(need_d, 0)
            return
        avail = len(self.free) + len(self.retire_pending)
        dead = self.cap - self.n_live
        if need_d > self.d_pad or len(fresh) > avail:
            self._gather(need_d, self.n_live + len(fresh))
        elif (self.cap > self.init_cap
              and dead > self.cap * self.gate.compact_frac):
            # lazy compaction: dead slots passed the threshold and
            # the live set fits a smaller ring — shrink so the
            # fixpoint stops paying for a drained backlog's peak
            self._gather(need_d, self.n_live + len(fresh))
        # 4b. retire BEFORE append: a freed device slot must read dead
        #     before its row can be reused, and before the fixpoint
        #     can re-admit a txn that already left the queue
        if self.retire_pending:
            self._dispatch_retire()
        if fresh:
            self._dispatch_append(fresh)

    def _build(self, d_pad: int, total: int) -> None:
        """Fresh all-dead ring (first use, or after invalidate()); the
        buffers are created on device, so a build uploads nothing —
        the queued txns then stage through the normal append path."""
        from antidote_tpu.interdc import gate_kernels as gk

        assert not self.mirror or all(
            not dq for dq in self.mirror.values())
        self.cap = max(self.init_cap,
                       1 << (max(total, 1) - 1).bit_length())
        self.d_pad = d_pad
        self.dev = gk.ring_alloc(self.cap, self.d_pad)
        self.mirror = {}
        self.slot_entry = [None] * self.cap
        self.free = list(range(self.cap - 1, -1, -1))
        self.retire_pending = []
        self.pos_next = {}
        self.n_live = 0
        stats.registry.gate_ring_rebuilds.inc()

    def _bulk_load(self, d_pad: int,
                   fresh: List[Tuple[Any, Any]]) -> None:
        """Empty-ring bulk load: pack the whole arrival batch into
        dense host arrays and upload them as the NEW ring (one H2D of
        exactly the rows that exist — what the legacy repack paid per
        pass, paid here once per backlog).  Any previous device state
        is garbage by construction (n_live == 0), so pending retires
        die with it."""
        import jax.numpy as jnp

        from antidote_tpu.interdc import gate_kernels as gk

        k = len(fresh)
        self.cap = max(self.init_cap,
                       1 << (max(k, 1) - 1).bit_length())
        self.d_pad = d_pad
        ss = np.zeros((self.cap, d_pad), np.int64)
        origin = np.zeros(self.cap, np.int32)
        pos = np.full(self.cap, gk.BIG_POS, np.int32)
        ts = np.zeros(self.cap, np.int64)
        ping = np.zeros(self.cap, dtype=bool)
        live = np.zeros(self.cap, dtype=bool)
        live[:k] = True
        self.mirror = {}
        self.slot_entry = [None] * self.cap
        self.pos_next = {}
        self.retire_pending = []
        for i, (o, txn) in enumerate(fresh):
            p = self.pos_next.get(o, 0)
            self.pos_next[o] = p + 1
            origin[i] = self.cols[o]
            pos[i] = p
            ts[i], ping[i] = _pack_txn_row(txn, self.cols, ss[i])
            self.slot_entry[i] = (o, p, txn)
            self.mirror.setdefault(o, deque()).append((i, txn, p))
        self.n_live = k
        self.free = list(range(self.cap - 1, k - 1, -1))
        self.dev = tuple(jnp.asarray(a)
                         for a in (ss, origin, pos, ts, ping, live))
        _note_gate_dispatch(
            "append",
            h2d=(ss.nbytes + origin.nbytes + pos.nbytes + ts.nbytes
                 + ping.nbytes + live.nbytes))

    def invalidate(self) -> None:
        """Drop the device state; the next sync rebuilds from the
        queues (defensive escape hatch — no steady-state caller)."""
        self.dev = None
        self.mirror = {}
        self.slot_entry = []
        self.free = []
        self.retire_pending = []
        self.pos_next = {}
        self.n_live = 0

    def _gather(self, d_pad: int, total: int) -> None:
        """Re-layout the ring via a device-side gather: grow, shrink
        (compaction), or widen the clock columns.  Only the index
        vector crosses the host/device boundary."""
        from antidote_tpu.interdc import gate_kernels as gk

        new_cap = max(self.init_cap,
                      1 << (max(total, 1) - 1).bit_length())
        idx = np.zeros(new_cap, np.int32)
        new_entry: List[Optional[Tuple[Any, int, Any]]] = [None] * new_cap
        new_mirror: Dict[Any, deque] = {}
        i = 0
        for origin, dq in self.mirror.items():
            nd = new_mirror[origin] = deque()
            for slot, txn, pos in dq:
                idx[i] = slot
                new_entry[i] = self.slot_entry[slot]
                nd.append((i, txn, pos))
                i += 1
        assert i == self.n_live
        n_live = np.asarray(i, np.int32)
        self.dev = gk.ring_gather(*self.dev[:5], idx, n_live,
                                  new_d=d_pad)
        _note_gate_dispatch("gather", h2d=idx.nbytes + n_live.nbytes)
        self.cap = new_cap
        self.d_pad = d_pad
        self.mirror = new_mirror
        self.slot_entry = new_entry
        self.free = list(range(new_cap - 1, i - 1, -1))
        self.retire_pending = []  # dead rows did not survive the gather

    def _dispatch_retire(self) -> None:
        from antidote_tpu.interdc import gate_kernels as gk

        k = len(self.retire_pending)
        k_pad = max(8, 1 << (k - 1).bit_length())
        slots = np.full(k_pad, self.cap, np.int32)  # padding: dropped
        slots[:k] = self.retire_pending
        ss, origin, pos, ts, ping, live = self.dev
        self.dev = (ss, origin, pos, ts, ping,
                    gk.ring_retire(live, slots))
        _note_gate_dispatch("retire", h2d=slots.nbytes)
        self.free.extend(self.retire_pending)
        self.retire_pending = []

    def _dispatch_append(self, fresh: List[Tuple[Any, Any]]) -> None:
        from antidote_tpu.interdc import gate_kernels as gk

        k = len(fresh)
        k_pad = max(8, 1 << (k - 1).bit_length())
        u_ss = np.zeros((k_pad, self.d_pad), np.int64)
        u_origin = np.zeros(k_pad, np.int32)
        u_pos = np.full(k_pad, gk.BIG_POS, np.int32)
        u_ts = np.zeros(k_pad, np.int64)
        u_ping = np.zeros(k_pad, dtype=bool)
        slots = np.full(k_pad, self.cap, np.int32)  # padding: dropped
        for i, (origin, txn) in enumerate(fresh):
            slot = self.free.pop()
            pos = self.pos_next.get(origin, 0)
            self.pos_next[origin] = pos + 1
            slots[i] = slot
            u_origin[i] = self.cols[origin]
            u_pos[i] = pos
            u_ts[i], u_ping[i] = _pack_txn_row(txn, self.cols, u_ss[i])
            self.slot_entry[slot] = (origin, pos, txn)
            self.mirror.setdefault(origin, deque()).append(
                (slot, txn, pos))
            self.n_live += 1
        self.dev = gk.ring_append(*self.dev, slots, u_ss, u_origin,
                                  u_pos, u_ts, u_ping)
        _note_gate_dispatch(
            "append",
            h2d=(slots.nbytes + u_ss.nbytes + u_origin.nbytes
                 + u_pos.nbytes + u_ts.nbytes + u_ping.nbytes))

    # ----------------------------------------------------------- fixpoint

    def run_fixpoint(self):
        """One device fixpoint over the resident ring.  Mandatory D2H
        is the scalar applied-count; the dense mask + rounds come back
        only when a wave actually admitted something, the final clock
        always (it carries the blocked-head ts-1 advances)."""
        from antidote_tpu.interdc import gate_kernels as gk
        from antidote_tpu.obs import prof

        gate = self.gate
        pvc = np.zeros(self.d_pad, np.int64)
        for dc, c in self.cols.items():
            pvc[c] = gate.applied_vc.get_dc(dc)
        # own entry is *replaced* by now, exactly like partition_vc()
        pvc[self.cols[gate.own_dc]] = gate.now_us()
        with prof.annotate("gate_ring_fixpoint"):
            applied_d, rounds_d, pvc_d, live_d, n_d = gk.ring_fixpoint(
                *self.dev, pvc)
        napp = int(np.asarray(n_d))
        d2h = np.dtype(np.int32).itemsize  # the scalar count
        if napp:
            applied = np.asarray(applied_d)
            rounds = np.asarray(rounds_d)
            d2h += applied.nbytes + rounds.nbytes
        else:
            applied = rounds = None
        new_pvc = np.asarray(pvc_d)
        d2h += new_pvc.nbytes
        _note_gate_dispatch("fixpoint", h2d=pvc.nbytes, d2h=d2h)
        self._pending_live = live_d
        return napp, applied, rounds, new_pvc

    def applied_entries(self, applied) -> List[Tuple[int, Any, int, Any]]:
        """(slot, origin, pos, txn) for every applied live slot."""
        out = []
        for slot in np.nonzero(applied)[0]:
            e = self.slot_entry[slot]
            if e is not None:
                out.append((int(slot),) + e)
        return out

    # ------------------------------------------------------------- waves

    def begin_wave(self) -> None:
        self.last_wave = []

    def pop_applied(self, slot: int) -> None:
        """The gate replayed this slot's txn (popped + applied)."""
        origin, _pos, _txn = self.slot_entry[slot]
        head = self.mirror[origin].popleft()
        assert head[0] == slot, "ring mirror diverged from queue order"
        self.slot_entry[slot] = None
        self.n_live -= 1
        self.last_wave.append(slot)

    def finish_wave(self, completed: bool) -> None:
        """Adopt the fixpoint's ``new_live`` when the wave replayed
        fully (the applied slots are already dead on device — zero
        extra dispatches); otherwise keep the old live mask and retire
        the partial wave's slots at the next sync."""
        if completed and self._pending_live is not None:
            ss, origin, pos, ts, ping, _live = self.dev
            self.dev = (ss, origin, pos, ts, ping, self._pending_live)
            self.free.extend(self.last_wave)
        else:
            self.retire_pending.extend(self.last_wave)
        self._pending_live = None


def ready_mask(queued_ss, queued_origin, partition_vc):
    """Batched dependency check on device: which queued txns may apply now.

    ``queued_ss``: int64[N, D] snapshot VCs; ``queued_origin``: int32[N]
    dense origin columns; ``partition_vc``: int64[D].  Returns bool[N].
    The origin entry is zeroed before the dominance test exactly as in
    try_store (reference src/inter_dc_dep_vnode.erl:131-136).
    """
    from antidote_tpu.clocks import dense

    deps = dense.set_dc(queued_ss, queued_origin, 0)
    return dense.ge(partition_vc, deps)


_GATE_JIT = None


def gate_fixpoint(ss, origin, pos, ts, is_ping, pvc):
    """Device iterate-until-stable over the whole queued set: returns
    (applied bool[N], round int32[N], final partition VC int64[D]).

    Each round evaluates, data-parallel over all N queued txns:
      ready    = ping | (pvc >= deps)           (:func:`ready_mask`)
      applied  = ready ∧ FIFO-prefix            (a txn applies only if
                 every earlier txn of its origin queue applies — the
                 per-origin min position of a not-ready txn bounds it)
      pvc     |= per-origin max commit ts of applied txns
    and repeats while pvc still advances — the same monotone cascade the
    host walk performs head-by-head (reference
    src/inter_dc_dep_vnode.erl:96-154), as one ``lax.while_loop``.
    Terminates because applied/pvc are monotone; the round count is
    bounded by the longest dependency chain through the queues (up to
    the total queued-txn count for a fully serialized cascade).

    ``round[i]`` is the round at which txn i became applicable.  A
    round-r txn's dependencies were satisfied by the clock of round r-1,
    so it cannot depend on any other round-r txn: replaying applies
    sorted by (round, fifo pos) is causally safe, which is how the host
    caller restores the reference's apply-in-dependency-order behavior.

    This is the legacy repack path's kernel; the resident-ring form is
    :func:`antidote_tpu.interdc.gate_kernels.ring_fixpoint` (the same
    cascade with a ``live`` mask instead of sentinel padding rows).
    """
    global _GATE_JIT
    if _GATE_JIT is None:
        import jax
        import jax.numpy as jnp

        from antidote_tpu.clocks import dense

        def _fixpoint(ss, origin, pos, ts, is_ping, pvc):
            d = pvc.shape[0]
            n = ss.shape[0]
            big = jnp.asarray(np.iinfo(np.int32).max, jnp.int32)

            def round_(pvc):
                ready = is_ping | ready_mask(ss, origin, pvc)   # [N]
                notready_pos = jnp.where(ready, big, pos)
                blocked_min = jnp.full((d,), big, jnp.int32).at[origin].min(
                    notready_pos, mode="drop")
                applied = ready & (pos < blocked_min[origin])
                wm = jnp.zeros((d,), ts.dtype).at[origin].max(
                    jnp.where(applied, ts, 0), mode="drop")
                # blocked-head rule (reference
                # src/inter_dc_dep_vnode.erl:137-143): a head that
                # cannot apply still advances its origin's clock to
                # ts-1 — FIFO + gap repair mean the origin's stream is
                # complete below it, and other origins' heads may
                # depend on a time up to it.  Padding rows contribute
                # ts-1 = -1, which the max-with-0 init discards.
                head_blocked = (~ready) & (pos == blocked_min[origin])
                hb = jnp.zeros((d,), ts.dtype).at[origin].max(
                    jnp.where(head_blocked, ts - 1, 0), mode="drop")
                return applied, jnp.maximum(pvc, jnp.maximum(wm, hb))

            def note_round(rounds, applied, r):
                newly = applied & (rounds < 0)
                return jnp.where(newly, r, rounds)

            def cond(carry):
                _, _, _, changed = carry
                return changed

            def body(carry):
                rounds, pvc, r, _ = carry
                applied, new_pvc = round_(pvc)
                rounds = note_round(rounds, applied, r)
                return (rounds, new_pvc, r + 1,
                        jnp.any(new_pvc != pvc))

            rounds0 = jnp.full((n,), -1, jnp.int32)
            rounds, pvc, r, _ = jax.lax.while_loop(
                cond, body,
                (rounds0, pvc, jnp.asarray(0, jnp.int32),
                 jnp.asarray(True)))
            # the loop exits after a round that did not advance pvc;
            # evaluate once more at the stable clock (covers the
            # no-progress-first-round case)
            applied, _ = round_(pvc)
            rounds = note_round(rounds, applied, r)
            return applied, rounds, pvc

        from antidote_tpu.obs import prof as _prof

        # kernel-span wrapped: the gate's padded-shape jit cache is the
        # classic recompilation-storm source (every new (n_pad, d_pad)
        # pair compiles), which the compile-miss counter now attributes
        _GATE_JIT = _prof.profiler.wrap(
            jax.jit(_fixpoint), name="gate_fixpoint",
            subsystem="interdc.dep")
    return _GATE_JIT(ss, origin, pos, ts, is_ping, pvc)

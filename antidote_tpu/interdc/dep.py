"""Causal dependency gate — the inter_dc_dep_vnode equivalent.

Per origin-DC FIFO queues of inbound transactions for one partition; a
transaction applies only when the partition's vector clock dominates the
txn's snapshot with the origin entry zeroed (the origin dependency is
already guaranteed by FIFO order + opid continuity) — reference
try_store, src/inter_dc_dep_vnode.erl:121-154.  Applying a txn appends
its records to the local log without assigning local ids and pushes the
effects into the materializer store (:144-152).  Heartbeats just advance
the origin's clock entry (:124-125).  Queues are processed to fixpoint
whenever the clock advances (:96-117).

``ready_mask`` is the batched device form of the same dominance test:
at hundreds of DCs the queue-to-fixpoint walk is a dense [N, D] >= [D]
reduction evaluated for every queued txn at once (the data-parallel
iterate-until-stable named in SURVEY §7 hard-part (d)); the 256-DC GST
convergence benchmark drives it.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict

from antidote_tpu.clocks import VC
from antidote_tpu.interdc.wire import InterDcTxn


class DependencyGate:
    def __init__(self, pm, own_dc, now_us: Callable[[], int]):
        self.pm = pm  # PartitionManager
        self.own_dc = own_dc
        self.now_us = now_us
        #: origin DC -> FIFO of InterDcTxn waiting on their dependencies
        self.queues: Dict[Any, deque] = {}
        #: origin DC -> timestamp watermark of applied txns / heartbeats
        #: (seeded from the recovered log's max commit VC at restart,
        #: reference set_dependency_clock src/inter_dc_dep_vnode.erl:82-83)
        self.applied_vc = VC()
        #: tap invoked after the partition VC advances (feeds the
        #: stable-time tracker, throttled by the caller if needed)
        self.on_clock_update: Callable[[], None] = lambda: None

    # ------------------------------------------------------------ clocks

    def partition_vc(self) -> VC:
        """Applied watermarks per origin + own entry at the local clock
        (any local snapshot entry a remote txn carries is a past local
        time, so `now` always dominates it)."""
        return VC(self.applied_vc).set_dc(self.own_dc, self.now_us())

    def seed_clock(self, vc: VC) -> None:
        self.applied_vc = self.applied_vc.join(vc)

    # ------------------------------------------------------------- ingest

    def enqueue(self, txn: InterDcTxn) -> None:
        self.queues.setdefault(txn.dc_id, deque()).append(txn)
        self.process_queues()

    def process_queues(self) -> None:
        """Drain every origin queue to fixpoint: applying a txn (or ping)
        advances the clock, which may unblock other origins' heads."""
        advanced = False
        progress = True
        while progress:
            progress = False
            for origin, q in self.queues.items():
                while q:
                    txn = q[0]
                    if txn.is_ping():
                        self._advance(origin, txn.timestamp)
                        q.popleft()
                        progress = advanced = True
                        continue
                    deps = VC(txn.snapshot_vc).set_dc(origin, 0)
                    if self.partition_vc().ge(deps):
                        self._apply(txn)
                        q.popleft()
                        progress = advanced = True
                    else:
                        break
        if advanced:
            self.on_clock_update()

    def _advance(self, origin, ts: int) -> None:
        if ts > self.applied_vc.get_dc(origin):
            self.applied_vc = self.applied_vc.set_dc(origin, ts)

    def _apply(self, txn: InterDcTxn) -> None:
        self.pm.apply_remote(txn.records, txn.dc_id, txn.timestamp,
                             txn.snapshot_vc)
        self._advance(txn.dc_id, txn.timestamp)

    def pending(self) -> int:
        return sum(len(q) for q in self.queues.values())


def ready_mask(queued_ss, queued_origin, partition_vc):
    """Batched dependency check on device: which queued txns may apply now.

    ``queued_ss``: int64[N, D] snapshot VCs; ``queued_origin``: int32[N]
    dense origin columns; ``partition_vc``: int64[D].  Returns bool[N].
    The origin entry is zeroed before the dominance test exactly as in
    try_store (reference src/inter_dc_dep_vnode.erl:131-136).
    """
    from antidote_tpu.clocks import dense

    deps = dense.set_dc(queued_ss, queued_origin, 0)
    return dense.ge(partition_vc, deps)

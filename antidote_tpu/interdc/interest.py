"""Interest specs — per-subscriber key-range filters for the pub plane.

An interest spec is a versioned set of half-open string key ranges a
subscriber announces in its hello.  The sender cuts one slice of every
staged frame per distinct spec (*interest class*) and each subscriber
receives the slice matching its class; subscribers sharing a spec share
one slice buffer, generalizing the staged-once contract from "one
buffer" to "one buffer per interest class" (docs/interest_routing.md).

Matching is txn-granular: a txn whose write-set intersects the spec
ships whole, so causal prev-opid chains and write atomicity stay
intact.  Both the full stream and every class chain use the ORIGINAL
origin opid numbering — a class chain is a subsequence with its
``prev_log_opid`` links rewritten to be gapless for its receiver; the
per-class watermark bookkeeping lives in :func:`slice_batch` /
:func:`slice_txn` / :func:`slice_ping` and the rules are pinned in the
design note (§2: init at first-encounter frame base, advance only on
emission).

Validation is loud (:class:`InterestError`): a malformed, empty, or
overlapping spec is rejected at subscribe time, never silently
downgraded to a full or empty stream.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from antidote_tpu.interdc.wire import InterDcBatch, InterDcTxn

#: wire tags (termcodec terms; see docs/interest_routing.md §1)
SPEC_TAG = "interest"
HELLO_TAG = "interest_hello"
SPEC_VERSION = 1

Range = Tuple[str, str]


class InterestError(ValueError):
    """A malformed interest spec — rejected loudly at subscribe."""


def _validate_ranges(ranges) -> Tuple[Range, ...]:
    """Canonicalize ``ranges`` (sort) or raise :class:`InterestError`."""
    try:
        items = list(ranges)
    except TypeError:
        raise InterestError(f"ranges not iterable: {ranges!r}")
    if not items:
        raise InterestError("empty interest spec: an empty range set is "
                            "a disconnect, not a subscription")
    out = []
    for r in items:
        if (not isinstance(r, (tuple, list)) or len(r) != 2):
            raise InterestError(f"range must be a (lo, hi) pair: {r!r}")
        lo, hi = r
        if not isinstance(lo, str) or not isinstance(hi, str):
            raise InterestError(f"range bounds must be str: {r!r}")
        if not lo < hi:
            raise InterestError(f"empty/inverted range [lo, hi): {r!r}")
        out.append((lo, hi))
    out.sort()
    for (_, hi_a), (lo_b, _) in zip(out, out[1:]):
        if lo_b < hi_a:
            raise InterestError(
                f"overlapping ranges: [..., {hi_a!r}) and [{lo_b!r}, ...)"
                " — overlap makes the interest-class identity ambiguous")
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class InterestSpec:
    """A validated, canonical set of half-open [lo, hi) key ranges."""

    ranges: Tuple[Range, ...]

    def __init__(self, ranges) -> None:
        object.__setattr__(self, "ranges", _validate_ranges(ranges))

    def class_key(self):
        """Hashable interest-class identity — subscribers sharing it
        share one slice buffer."""
        return (SPEC_VERSION, self.ranges)

    # ------------------------------------------------------------ matching

    def matches_key(self, key) -> bool:
        """Non-str keys are unclassifiable and match EVERY spec — ship
        everywhere, never silently drop."""
        if not isinstance(key, str):
            return True
        return any(lo <= key < hi for lo, hi in self.ranges)

    def matches_txn(self, txn: InterDcTxn) -> bool:
        """Txn-granular: any update record's key inside the spec ships
        the whole txn; a txn with no update records (ping/commit-only)
        matches every spec."""
        saw_update = False
        for r in txn.records:
            if r.payload[0] == "update":
                saw_update = True
                if self.matches_key(r.payload[1]):
                    return True
        return not saw_update

    # ---------------------------------------------------------------- wire

    def to_wire(self):
        return (SPEC_TAG, SPEC_VERSION, self.ranges)

    @classmethod
    def from_wire(cls, term) -> "InterestSpec":
        """Strict decode of a spec wire term — hostile input raises
        :class:`InterestError`, never yields a silent full/empty spec."""
        if (not isinstance(term, (tuple, list)) or len(term) != 3
                or term[0] != SPEC_TAG):
            raise InterestError(f"not an interest spec term: {term!r}")
        if term[1] != SPEC_VERSION:
            raise InterestError(
                f"unknown interest spec version {term[1]!r} (have "
                f"{SPEC_VERSION}) — refusing to guess a subset")
        return cls(term[2])


def interest_from_config(config) -> Optional[InterestSpec]:
    """The one-factory hop for the interest knobs: a spec only when
    ``interest_routing`` is on AND ``interest_ranges`` is declared
    (validation errors surface loudly at construction, i.e. DC start)."""
    if not config.interest_routing:
        return None
    if config.interest_ranges is None:
        return None
    return InterestSpec(config.interest_ranges)


# --------------------------------------------------------------- hello wire

def hello_term(dc_id, spec: Optional[InterestSpec]):
    """The subscriber hello: a plain dc_id when spec-less (pre-upgrade
    form, full stream) or the tagged interest hello."""
    if spec is None:
        return dc_id
    return (HELLO_TAG, SPEC_VERSION, dc_id, spec.to_wire())


def parse_hello(term):
    """(dc_id, spec_or_None) from a subscriber hello.  A plain term is
    the pre-upgrade full-stream hello; a tagged term must carry a valid
    spec or :class:`InterestError` is raised (the acceptor closes the
    connection — loud, never a silent full/empty stream)."""
    if (isinstance(term, (tuple, list)) and len(term) >= 1
            and term[0] == HELLO_TAG):
        if len(term) != 4 or term[1] != SPEC_VERSION:
            raise InterestError(f"malformed interest hello: {term!r}")
        return term[2], InterestSpec.from_wire(term[3])
    return term, None


# ------------------------------------------------------------ frame slicing
#
# Slice functions implement the class-watermark chain rules
# (docs/interest_routing.md §2).  Each takes the frame OBJECT, the spec,
# and the class watermark, returning (sliced_or_None, new_wm, elided):
# the caller owns the watermark dict and must initialize a first-seen
# class at the frame's base BEFORE calling (see Sender._cut_slices).

def slice_batch(batch: InterDcBatch, spec: InterestSpec, wm: int):
    """Cut the class's subsequence of ``batch``: matching txns ship
    whole with prev-opid links rewritten onto the class chain; the
    watermark advances to the last selected txn's opid.  A batch with a
    piggybacked ping but no matching txns degenerates to a standalone
    class ping at the watermark; no ping and no match skips the frame
    entirely (watermark unchanged — it only moves on emission)."""
    selected, elided = [], 0
    for txn in batch.delivery_txns(include_ping=False):
        if spec.matches_txn(txn):
            selected.append(txn)
        else:
            elided += 1
    if not selected:
        if batch.ping_ts is None:
            return None, wm, elided
        ping = InterDcTxn.ping(batch.dc_id, batch.partition, wm,
                               batch.ping_ts)
        return ping, wm, elided
    prev = wm
    rewritten = []
    for txn in selected:
        rewritten.append(dataclasses.replace(txn, prev_log_opid=prev))
        prev = txn.last_opid()
    sliced = InterDcBatch.from_txns(rewritten, ping_ts=batch.ping_ts,
                                    trace_hdr=batch.trace_hdr)
    return sliced, prev, elided


def slice_txn(txn: InterDcTxn, spec: InterestSpec, wm: int):
    """Single-txn frame: ship rewritten onto the class chain or elide."""
    if not spec.matches_txn(txn):
        return None, wm, 1
    return (dataclasses.replace(txn, prev_log_opid=wm),
            txn.last_opid(), 0)


def slice_ping(txn: InterDcTxn, spec: InterestSpec, wm: int):
    """Standalone heartbeat: interest-INDEPENDENT (the partial-
    subscription GST argument rests on pings reaching every class), so
    always emitted — re-anchored at the class watermark."""
    return (dataclasses.replace(txn, prev_log_opid=wm), wm, 0)


__all__ = [
    "InterestError", "InterestSpec", "interest_from_config",
    "hello_term", "parse_hello",
    "slice_batch", "slice_txn", "slice_ping",
    "SPEC_TAG", "HELLO_TAG", "SPEC_VERSION",
]

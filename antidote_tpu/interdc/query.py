"""Inter-DC query RPC: log-range repair reads.

Client side mirrors inter_dc_query (reference src/inter_dc_query.erl:76-79)
and the server side inter_dc_query_response (src/inter_dc_query_response.erl:97-126):
read the partition's whole log, reassemble transactions, and return the
*locally-originated* ones whose commit-record opid falls in the requested
range, with the prev-opid chain reconstructed so the requester's gap
check can consume them like live frames.
"""

from __future__ import annotations

from typing import List, Optional

from antidote_tpu.interdc.transport import LinkDown, Transport
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.oplog.records import TxnAssembler

LOG_READ = "log_read"
BCOUNTER_REQUEST = "bcounter_request"
CHECK_UP = "check_up"


def fetch_log_range(transport: Transport, own_dc, origin_dc, partition: int,
                    first: int, last: int) -> Optional[List[InterDcTxn]]:
    """Ask ``origin_dc`` for its committed txns with commit opid in
    [first, last]; None when the origin is unreachable."""
    try:
        return transport.request(own_dc, origin_dc, LOG_READ,
                                 (partition, first, last))
    except LinkDown:
        return None


def answer_log_read(partition_log, dc_id, partition: int, first: int,
                    last: int) -> List[InterDcTxn]:
    """Server side: replay the partition log in order, reassembling this
    DC's own transactions, and emit those whose commit opid is in range.

    The prev-opid watermark chain is rebuilt from the commit-record
    sequence itself — identical to what the live sender produced, since
    its watermark is always the previous commit record's opid
    (antidote_tpu/interdc/sender.py).
    """
    asm = TxnAssembler()
    out: List[InterDcTxn]= []
    prev = 0
    for rec in partition_log.records():
        if rec.op_id.dc != dc_id:
            continue
        done = asm.process(rec)
        if done is None:
            continue
        commit_opid = done[-1].op_id.n
        if first <= commit_opid <= last:
            out.append(InterDcTxn.from_ops(dc_id, partition, prev, done))
        prev = commit_opid
    return out

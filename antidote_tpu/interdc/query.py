"""Inter-DC query RPC: log-range repair reads + remote snapshot reads.

Client side mirrors inter_dc_query (reference src/inter_dc_query.erl:76-79)
and the server side inter_dc_query_response (src/inter_dc_query_response.erl:97-126):
read the partition's whole log, reassemble transactions, and return the
*locally-originated* ones whose commit-record opid falls in the requested
range, with the prev-opid chain reconstructed so the requester's gap
check can consume them like live frames.

ISSUE 8 adds the SNAPSHOT_READ kind: a causal one-shot read of bound
objects at a clock, answered at the remote DC through its read serve
plane (api.read_objects_static's fast path — no interactive
transaction, coalesced with the serving DC's own readers).  This is
the cross-DC remote-read leg the causal probe and federated clients
use instead of replaying log ranges for a value question.

ISSUE 10 adds retention awareness: a LOG_READ whose range reaches
below the origin's truncation floor gets the explicit BELOW_FLOOR
answer (the records are reclaimed — their history lives in the
origin's checkpoint), and the CKPT_READ kind fetches that checkpoint:
per-key seed states at the cut frontier plus the stream watermarks.
The requesting SubBuf escalates a BELOW_FLOOR repair to a
CKPT_READ bootstrap (seed state + suffix) instead of wedging in
gap-repair retries (interdc/sub_buf.py).
"""

from __future__ import annotations

import logging
import pickle
from typing import List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.interdc.interest import InterestSpec
from antidote_tpu.interdc.transport import LinkDown, Transport
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.partition import BelowRetentionFloor

log = logging.getLogger(__name__)

LOG_READ = "log_read"
BCOUNTER_REQUEST = "bcounter_request"
CHECK_UP = "check_up"
SNAPSHOT_READ = "snapshot_read"
CKPT_READ = "ckpt_read"
#: streamed CKPT_READ (ISSUE 19): the manifest message carries the cut
#: watermarks plus an ordered page list; pages are fetched in batches
#: bounded by the requester's window and validated per fetch, so a
#: donor kill or torn fetch resumes at the first un-acked page instead
#: of refetching the whole cut
CKPT_MANIFEST = "ckpt_manifest"
CKPT_SEG = "ckpt_seg"

#: first element of a LOG_READ answer that could not be served because
#: the range lies below the origin's retention floor
BELOW_FLOOR = "below_floor"


def below_floor_answer(floor: int) -> Tuple[str, int]:
    """The LOG_READ answer for a range the origin's log no longer
    holds: (marker, the origin's floor commit opid)."""
    return (BELOW_FLOOR, int(floor))


def is_below_floor(ans) -> bool:
    """True iff ``ans`` is a BELOW_FLOOR answer (and not a txn list —
    a real answer is a list, never a 2-tuple led by the marker)."""
    return (isinstance(ans, tuple) and len(ans) == 2
            and ans[0] == BELOW_FLOOR)


def fetch_log_range(transport: Transport, own_dc, origin_dc, partition: int,
                    first: int, last: int,
                    ranges: Optional[tuple] = None
                    ) -> Optional[List[InterDcTxn]]:
    """Ask ``origin_dc`` for its committed txns with commit opid in
    [first, last]; None when the origin is unreachable.  ``ranges``
    (ISSUE 18) restricts the answer to txns whose write-set intersects
    the interest ranges — the widen-backfill path; the 3-tuple payload
    stays the pre-upgrade full-answer form."""
    payload = ((partition, first, last) if ranges is None
               else (partition, first, last, tuple(ranges)))
    try:
        return transport.request(own_dc, origin_dc, LOG_READ, payload)
    except LinkDown:
        return None


def answer_log_read(partition_log, dc_id, partition: int, first: int,
                    last: int,
                    ranges: Optional[tuple] = None) -> List[InterDcTxn]:
    """Server side: emit this DC's committed transactions whose commit
    opid is in range, through the partition log's per-origin op-id
    offset index (ISSUE 9) — O(requested range) file reads instead of
    the full-partition replay the pre-index form paid, so repair cost
    no longer scales with unrelated log volume.

    The prev-opid watermark chain is rebuilt from the commit-record
    sequence itself — identical to what the live sender produced, since
    its watermark is always the previous commit record's opid
    (antidote_tpu/interdc/sender.py).

    A range reaching below a TRUNCATED prefix answers BELOW_FLOOR
    (ISSUE 10): a silently partial answer would let the requester
    advance its watermark past history it never received, so the
    impossibility is explicit and the requester bootstraps from the
    checkpoint instead.

    ``ranges`` (ISSUE 18, validated loudly — InterestError on hostile
    input) filters the answer to txns whose write-set intersects the
    requester's interest, keeping the ORIGINAL prev chains: the
    requester's SubBuf delivers repair answers by opid and advances
    authoritatively over the whole requested range, so the elided
    opids are covered without being shipped (docs/interest_routing.md
    §3).
    """
    spec = None if ranges is None else InterestSpec(ranges)
    try:
        txns = [InterDcTxn.from_ops(dc_id, partition, prev, done)
                for prev, done in partition_log.committed_txns_in_range(
                    dc_id, first, last)]
    except BelowRetentionFloor as e:
        return below_floor_answer(e.floor)
    if spec is not None:
        txns = [t for t in txns if spec.matches_txn(t)]
    return txns


def fetch_snapshot_read(transport: Transport, own_dc, origin_dc,
                        objects: List, clock: Optional[VC]
                        ) -> Optional[Tuple[List, VC]]:
    """Ask ``origin_dc`` for the values of ``objects`` (bound-object
    tuples) at ``clock`` (None = its stable snapshot); returns
    (values, snapshot VC) or None when the origin is unreachable.  The
    payload crosses administrative domains, so clocks travel as plain
    dicts (the termcodec VC form is for wire frames)."""
    try:
        values, vc = transport.request(
            own_dc, origin_dc, SNAPSHOT_READ,
            ([tuple(o) for o in objects],
             None if clock is None else dict(clock)))
    except LinkDown:
        return None
    return list(values), VC(vc)


def fetch_ckpt_bootstrap(transport: Transport, own_dc, origin_dc,
                         partition: int,
                         ranges: Optional[tuple] = None
                         ) -> Optional[dict]:
    """Ask ``origin_dc`` for its partition checkpoint (the BELOW_FLOOR
    escalation): {keys: {key: (type, state, vc dict)}, clock: vc dict,
    commit_opid, op_counter} or None when the origin is unreachable or
    does not checkpoint (the requester keeps buffering and retries).
    ``ranges`` (ISSUE 18) asks for only the seed keys intersecting the
    requester's interest; the 1-tuple payload stays the pre-upgrade
    full-checkpoint form."""
    payload = (partition,) if ranges is None else (partition,
                                                   tuple(ranges))
    try:
        return transport.request(own_dc, origin_dc, CKPT_READ, payload)
    except LinkDown:
        return None


def install_ckpt_bootstrap(pm, gate, origin_dc, partition: int,
                           ans: dict) -> int:
    """Receiver-side install of a CKPT_READ answer — the ONE home for
    the bootstrap semantics (DataCenter and the federated member both
    route here; the PR-6 adopt_from_wire lesson): merge the origin's
    seed states into the local partition (local concurrent writes
    survive — PartitionManager.bootstrap_seed), seed the dependency
    gate's clock with the cut frontier, and return the origin's
    commit watermark at the cut for the SubBuf to jump to."""
    with tracer.span("ckpt_bootstrap_install", "interdc",
                     origin=str(origin_dc), partition=partition,
                     keys=len(ans["keys"])):
        pm.bootstrap_seed(
            ((key, tn, state, VC(vc))
             for key, (tn, state, vc) in ans["keys"].items()),
            origin_dc=origin_dc, op_counter=ans["op_counter"])
        gate.seed_clock(VC(ans["clock"]))
        # make the seeds DURABLE before the caller jumps the stream
        # watermark: they exist only in the host store, but the jump is
        # made durable by the very next suffix append — a crash before
        # the next watermark-triggered checkpoint would recover the
        # advanced watermark with no seeds and silently serve holes for
        # the origin's below-cut history, with nothing left to
        # re-request.  A failed (or disabled, Config.ckpt=False)
        # persist keeps the live install — only crash-durability is at
        # risk — but must be loud.
        try:
            persisted = pm.checkpoint_now()
        except Exception:  # noqa: BLE001 — never fail the install
            persisted = None
            log.exception(
                "checkpoint after ckpt bootstrap of partition %d from "
                "%s failed", partition, origin_dc)
        if persisted is None:
            log.error(
                "partition %d: bootstrap seeds from %s are NOT durable "
                "(checkpointing disabled or failed) — a crash before "
                "the next checkpoint loses the origin's below-cut "
                "history", partition, origin_dc)
    return ans["commit_opid"]


def answer_ckpt_read(pm, own_dc, partition: int,
                     ranges: Optional[tuple] = None) -> Optional[dict]:
    """Server side of CKPT_READ: cut a fresh checkpoint on the owning
    PartitionManager and answer with its seeds + watermarks (None when
    checkpointing is disabled).  ``ranges`` (ISSUE 18, validated
    loudly) keeps only the seed keys inside the requester's interest —
    non-str keys are unclassifiable and always ship; the watermarks
    stay the FULL checkpoint's (the requester's jump covers the elided
    keys' history the same way a filtered repair answer does)."""
    ans = pm.ckpt_bootstrap_answer(own_dc)
    if ans is None:
        return None
    if ranges is not None:
        spec = InterestSpec(ranges)
        ans = dict(ans)
        ans["keys"] = {k: v for k, v in ans["keys"].items()
                       if spec.matches_key(k)}
    # clocks cross administrative domains as plain dicts, like
    # SNAPSHOT_READ's (the termcodec VC form is for wire frames)
    return ans


def answer_ckpt_manifest(pm, own_dc, partition: int,
                         ranges: Optional[tuple], page_bytes: int,
                         bid: int):
    """Server side of the streamed CKPT_READ (ISSUE 19): cut a fresh
    checkpoint (same cut as :func:`answer_ckpt_read`) and split its
    seed keys into CRC-framed pages of roughly ``page_bytes`` each —
    framed exactly like on-disk bundle segments, so the receiver's
    torn-fetch validation is shared.  Returns ``(manifest, pages)``
    where the manifest carries the cut watermarks, ``bid`` (the cut's
    identity — a page fetch quoting a stale bid answers None and the
    receiver restarts), and the ordered ``(name, n_keys, n_bytes)``
    page list; ``(None, None)`` when the partition does not
    checkpoint.  The caller caches ``pages`` keyed by bid until the
    next manifest request supersedes it."""
    from antidote_tpu.oplog.checkpoint import frame_segment_bytes

    ans = answer_ckpt_read(pm, own_dc, partition, ranges=ranges)
    if ans is None:
        return None, None
    pages = {}
    meta: List[Tuple[str, int, int]] = []
    cur: dict = {}
    cur_bytes = 0

    def flush():
        nonlocal cur, cur_bytes
        if not cur:
            return
        name = f"page-{len(meta):06d}"
        raw = frame_segment_bytes(cur)
        pages[name] = raw
        meta.append((name, len(cur), len(raw)))
        cur = {}
        cur_bytes = 0

    for key, val in ans["keys"].items():
        cur[key] = val
        cur_bytes += len(pickle.dumps((key, val),
                                      protocol=pickle.HIGHEST_PROTOCOL))
        if cur_bytes >= max(1, int(page_bytes)):
            flush()
    flush()
    man = {k: v for k, v in ans.items() if k != "keys"}
    man["bid"] = int(bid)
    man["segments"] = meta
    return man, pages


def answer_ckpt_seg(cache_entry, bid: int, names) -> List:
    """Server side of a streamed page fetch: raw framed bytes per
    requested name, or None per name when the quoted cut is no longer
    cached (superseded by a newer manifest, or the server restarted) —
    the receiver re-pulls the manifest and restarts its cursor."""
    if cache_entry is None or cache_entry[0] != bid:
        return [None for _ in names]
    return [cache_entry[1].get(n) for n in names]


def fetch_ckpt_bootstrap_streamed(transport: Transport, own_dc,
                                  origin_dc, partition: int,
                                  ranges: Optional[tuple],
                                  window_bytes: int,
                                  state: dict) -> Optional[dict]:
    """Streamed CKPT_READ client (ISSUE 19): pull the manifest, then
    pages in batches bounded by ``window_bytes`` (the in-flight byte
    cap — backpressure against a huge cut), validating every fetch;
    the per-page ack watermark lives in ``state`` (caller-owned, keyed
    per (origin, partition)), so an origin kill or a torn fetch
    resumes at the first un-acked page on the next call instead of
    refetching the cut.  A bid change on re-pull (the origin re-cut or
    restarted) restarts the cursor, counted in STREAM_RESTARTS /
    STREAM_RESUME_REFETCH_BYTES.  Returns the assembled answer in the
    exact :func:`fetch_ckpt_bootstrap` shape, or None when the origin
    is unreachable (state preserved — the next call resumes) or does
    not checkpoint (state cleared).  An origin that predates the
    streamed kinds raises — the caller falls back to the one-shot
    CKPT_READ."""
    from antidote_tpu import stats
    from antidote_tpu.oplog.checkpoint import _parse_segment_bytes

    def _manifest():
        stats.registry.stream_manifest_fetches.inc()
        return transport.request(
            own_dc, origin_dc, CKPT_MANIFEST,
            (partition, None if ranges is None else tuple(ranges),
             max(1, int(window_bytes) // 4)))

    def _adopt(man):
        state.clear()
        state["bid"] = man["bid"]
        state["segments"] = [tuple(s) for s in man["segments"]]
        state["fields"] = {k: v for k, v in man.items()
                           if k not in ("bid", "segments")}
        state["pages"] = {}

    try:
        if "bid" not in state:
            man = _manifest()
            if man is None:
                state.clear()
                return None  # origin does not checkpoint
            _adopt(man)
        strikes = 0
        while True:
            todo = [m for m in state["segments"]
                    if m[0] not in state["pages"]]
            if not todo:
                break
            batch, acc = [], 0
            for name, _k, nb in todo:
                if batch and acc + int(nb) > int(window_bytes):
                    break
                batch.append(name)
                acc += int(nb)
            raws = transport.request(
                own_dc, origin_dc, CKPT_SEG,
                (partition, state["bid"], list(batch)))
            progressed = False
            stale = False
            for name, raw in zip(batch, raws):
                if raw is None:
                    stale = True  # cut superseded / origin restarted
                    break
                entries = _parse_segment_bytes(raw)
                if entries is None:
                    stats.registry.stream_torn_fetches.inc()
                    log.warning(
                        "torn ckpt-stream page %r of partition %d "
                        "from %r — re-pulling; resume at the last "
                        "acked page", name, partition, origin_dc)
                    break
                state["pages"][name] = entries
                stats.registry.stream_seg_fetches.inc()
                stats.registry.stream_seg_bytes.inc(len(raw))
                progressed = True
            if stale:
                man = _manifest()
                if man is None:
                    state.clear()
                    return None  # origin dropped its checkpoint
                if man["bid"] != state["bid"]:
                    # acked progress is against a dead cut: discard
                    # it, loudly counted
                    refetch = sum(int(b) for n, _k, b
                                  in state["segments"]
                                  if n in state["pages"])
                    stats.registry.stream_resume_refetch_bytes.inc(
                        refetch)
                    stats.registry.stream_restarts.inc()
                    _adopt(man)
            strikes = 0 if progressed else strikes + 1
            if strikes > 8:
                state.clear()
                log.warning(
                    "streamed ckpt bootstrap of partition %d from %r "
                    "kept losing to torn fetches or re-cuts — giving "
                    "up this round (the requester retries)",
                    partition, origin_dc)
                return None
    except LinkDown:
        # state preserved: the next call resumes at the first
        # un-acked page against the same cached cut (the exact-resume
        # contract; a donor restart answers None and restarts cleanly)
        return None
    keys: dict = {}
    for name, _k, _b in state["segments"]:
        keys.update(state["pages"][name])
    ans = dict(state["fields"])
    ans["keys"] = keys
    state.clear()
    return ans


def answer_snapshot_read(db, objects, clock) -> Tuple[List, dict]:
    """Server side: serve the one-shot causal read through the DC's
    read serve plane (api.read_objects_static — the fast path when the
    ring is local, the interactive path on a federated member whose
    ring spans nodes), coalescing with the serving DC's own readers."""
    values, vc = db.read_objects_static(
        None if clock is None else VC(clock),
        [tuple(o) for o in objects])
    return values, dict(vc)

"""Inter-DC query RPC: log-range repair reads + remote snapshot reads.

Client side mirrors inter_dc_query (reference src/inter_dc_query.erl:76-79)
and the server side inter_dc_query_response (src/inter_dc_query_response.erl:97-126):
read the partition's whole log, reassemble transactions, and return the
*locally-originated* ones whose commit-record opid falls in the requested
range, with the prev-opid chain reconstructed so the requester's gap
check can consume them like live frames.

ISSUE 8 adds the SNAPSHOT_READ kind: a causal one-shot read of bound
objects at a clock, answered at the remote DC through its read serve
plane (api.read_objects_static's fast path — no interactive
transaction, coalesced with the serving DC's own readers).  This is
the cross-DC remote-read leg the causal probe and federated clients
use instead of replaying log ranges for a value question.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.interdc.transport import LinkDown, Transport
from antidote_tpu.interdc.wire import InterDcTxn

LOG_READ = "log_read"
BCOUNTER_REQUEST = "bcounter_request"
CHECK_UP = "check_up"
SNAPSHOT_READ = "snapshot_read"


def fetch_log_range(transport: Transport, own_dc, origin_dc, partition: int,
                    first: int, last: int) -> Optional[List[InterDcTxn]]:
    """Ask ``origin_dc`` for its committed txns with commit opid in
    [first, last]; None when the origin is unreachable."""
    try:
        return transport.request(own_dc, origin_dc, LOG_READ,
                                 (partition, first, last))
    except LinkDown:
        return None


def answer_log_read(partition_log, dc_id, partition: int, first: int,
                    last: int) -> List[InterDcTxn]:
    """Server side: emit this DC's committed transactions whose commit
    opid is in range, through the partition log's per-origin op-id
    offset index (ISSUE 9) — O(requested range) file reads instead of
    the full-partition replay the pre-index form paid, so repair cost
    no longer scales with unrelated log volume.

    The prev-opid watermark chain is rebuilt from the commit-record
    sequence itself — identical to what the live sender produced, since
    its watermark is always the previous commit record's opid
    (antidote_tpu/interdc/sender.py).
    """
    return [InterDcTxn.from_ops(dc_id, partition, prev, done)
            for prev, done in partition_log.committed_txns_in_range(
                dc_id, first, last)]


def fetch_snapshot_read(transport: Transport, own_dc, origin_dc,
                        objects: List, clock: Optional[VC]
                        ) -> Optional[Tuple[List, VC]]:
    """Ask ``origin_dc`` for the values of ``objects`` (bound-object
    tuples) at ``clock`` (None = its stable snapshot); returns
    (values, snapshot VC) or None when the origin is unreachable.  The
    payload crosses administrative domains, so clocks travel as plain
    dicts (the termcodec VC form is for wire frames)."""
    try:
        values, vc = transport.request(
            own_dc, origin_dc, SNAPSHOT_READ,
            ([tuple(o) for o in objects],
             None if clock is None else dict(clock)))
    except LinkDown:
        return None
    return list(values), VC(vc)


def answer_snapshot_read(db, objects, clock) -> Tuple[List, dict]:
    """Server side: serve the one-shot causal read through the DC's
    read serve plane (api.read_objects_static — the fast path when the
    ring is local, the interactive path on a federated member whose
    ring spans nodes), coalescing with the serving DC's own readers."""
    values, vc = db.read_objects_static(
        None if clock is None else VC(clock),
        [tuple(o) for o in objects])
    return values, dict(vc)

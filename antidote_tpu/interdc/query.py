"""Inter-DC query RPC: log-range repair reads + remote snapshot reads.

Client side mirrors inter_dc_query (reference src/inter_dc_query.erl:76-79)
and the server side inter_dc_query_response (src/inter_dc_query_response.erl:97-126):
read the partition's whole log, reassemble transactions, and return the
*locally-originated* ones whose commit-record opid falls in the requested
range, with the prev-opid chain reconstructed so the requester's gap
check can consume them like live frames.

ISSUE 8 adds the SNAPSHOT_READ kind: a causal one-shot read of bound
objects at a clock, answered at the remote DC through its read serve
plane (api.read_objects_static's fast path — no interactive
transaction, coalesced with the serving DC's own readers).  This is
the cross-DC remote-read leg the causal probe and federated clients
use instead of replaying log ranges for a value question.

ISSUE 10 adds retention awareness: a LOG_READ whose range reaches
below the origin's truncation floor gets the explicit BELOW_FLOOR
answer (the records are reclaimed — their history lives in the
origin's checkpoint), and the CKPT_READ kind fetches that checkpoint:
per-key seed states at the cut frontier plus the stream watermarks.
The requesting SubBuf escalates a BELOW_FLOOR repair to a
CKPT_READ bootstrap (seed state + suffix) instead of wedging in
gap-repair retries (interdc/sub_buf.py).
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.interdc.interest import InterestSpec
from antidote_tpu.interdc.transport import LinkDown, Transport
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.partition import BelowRetentionFloor

log = logging.getLogger(__name__)

LOG_READ = "log_read"
BCOUNTER_REQUEST = "bcounter_request"
CHECK_UP = "check_up"
SNAPSHOT_READ = "snapshot_read"
CKPT_READ = "ckpt_read"

#: first element of a LOG_READ answer that could not be served because
#: the range lies below the origin's retention floor
BELOW_FLOOR = "below_floor"


def below_floor_answer(floor: int) -> Tuple[str, int]:
    """The LOG_READ answer for a range the origin's log no longer
    holds: (marker, the origin's floor commit opid)."""
    return (BELOW_FLOOR, int(floor))


def is_below_floor(ans) -> bool:
    """True iff ``ans`` is a BELOW_FLOOR answer (and not a txn list —
    a real answer is a list, never a 2-tuple led by the marker)."""
    return (isinstance(ans, tuple) and len(ans) == 2
            and ans[0] == BELOW_FLOOR)


def fetch_log_range(transport: Transport, own_dc, origin_dc, partition: int,
                    first: int, last: int,
                    ranges: Optional[tuple] = None
                    ) -> Optional[List[InterDcTxn]]:
    """Ask ``origin_dc`` for its committed txns with commit opid in
    [first, last]; None when the origin is unreachable.  ``ranges``
    (ISSUE 18) restricts the answer to txns whose write-set intersects
    the interest ranges — the widen-backfill path; the 3-tuple payload
    stays the pre-upgrade full-answer form."""
    payload = ((partition, first, last) if ranges is None
               else (partition, first, last, tuple(ranges)))
    try:
        return transport.request(own_dc, origin_dc, LOG_READ, payload)
    except LinkDown:
        return None


def answer_log_read(partition_log, dc_id, partition: int, first: int,
                    last: int,
                    ranges: Optional[tuple] = None) -> List[InterDcTxn]:
    """Server side: emit this DC's committed transactions whose commit
    opid is in range, through the partition log's per-origin op-id
    offset index (ISSUE 9) — O(requested range) file reads instead of
    the full-partition replay the pre-index form paid, so repair cost
    no longer scales with unrelated log volume.

    The prev-opid watermark chain is rebuilt from the commit-record
    sequence itself — identical to what the live sender produced, since
    its watermark is always the previous commit record's opid
    (antidote_tpu/interdc/sender.py).

    A range reaching below a TRUNCATED prefix answers BELOW_FLOOR
    (ISSUE 10): a silently partial answer would let the requester
    advance its watermark past history it never received, so the
    impossibility is explicit and the requester bootstraps from the
    checkpoint instead.

    ``ranges`` (ISSUE 18, validated loudly — InterestError on hostile
    input) filters the answer to txns whose write-set intersects the
    requester's interest, keeping the ORIGINAL prev chains: the
    requester's SubBuf delivers repair answers by opid and advances
    authoritatively over the whole requested range, so the elided
    opids are covered without being shipped (docs/interest_routing.md
    §3).
    """
    spec = None if ranges is None else InterestSpec(ranges)
    try:
        txns = [InterDcTxn.from_ops(dc_id, partition, prev, done)
                for prev, done in partition_log.committed_txns_in_range(
                    dc_id, first, last)]
    except BelowRetentionFloor as e:
        return below_floor_answer(e.floor)
    if spec is not None:
        txns = [t for t in txns if spec.matches_txn(t)]
    return txns


def fetch_snapshot_read(transport: Transport, own_dc, origin_dc,
                        objects: List, clock: Optional[VC]
                        ) -> Optional[Tuple[List, VC]]:
    """Ask ``origin_dc`` for the values of ``objects`` (bound-object
    tuples) at ``clock`` (None = its stable snapshot); returns
    (values, snapshot VC) or None when the origin is unreachable.  The
    payload crosses administrative domains, so clocks travel as plain
    dicts (the termcodec VC form is for wire frames)."""
    try:
        values, vc = transport.request(
            own_dc, origin_dc, SNAPSHOT_READ,
            ([tuple(o) for o in objects],
             None if clock is None else dict(clock)))
    except LinkDown:
        return None
    return list(values), VC(vc)


def fetch_ckpt_bootstrap(transport: Transport, own_dc, origin_dc,
                         partition: int,
                         ranges: Optional[tuple] = None
                         ) -> Optional[dict]:
    """Ask ``origin_dc`` for its partition checkpoint (the BELOW_FLOOR
    escalation): {keys: {key: (type, state, vc dict)}, clock: vc dict,
    commit_opid, op_counter} or None when the origin is unreachable or
    does not checkpoint (the requester keeps buffering and retries).
    ``ranges`` (ISSUE 18) asks for only the seed keys intersecting the
    requester's interest; the 1-tuple payload stays the pre-upgrade
    full-checkpoint form."""
    payload = (partition,) if ranges is None else (partition,
                                                   tuple(ranges))
    try:
        return transport.request(own_dc, origin_dc, CKPT_READ, payload)
    except LinkDown:
        return None


def install_ckpt_bootstrap(pm, gate, origin_dc, partition: int,
                           ans: dict) -> int:
    """Receiver-side install of a CKPT_READ answer — the ONE home for
    the bootstrap semantics (DataCenter and the federated member both
    route here; the PR-6 adopt_from_wire lesson): merge the origin's
    seed states into the local partition (local concurrent writes
    survive — PartitionManager.bootstrap_seed), seed the dependency
    gate's clock with the cut frontier, and return the origin's
    commit watermark at the cut for the SubBuf to jump to."""
    with tracer.span("ckpt_bootstrap_install", "interdc",
                     origin=str(origin_dc), partition=partition,
                     keys=len(ans["keys"])):
        pm.bootstrap_seed(
            ((key, tn, state, VC(vc))
             for key, (tn, state, vc) in ans["keys"].items()),
            origin_dc=origin_dc, op_counter=ans["op_counter"])
        gate.seed_clock(VC(ans["clock"]))
        # make the seeds DURABLE before the caller jumps the stream
        # watermark: they exist only in the host store, but the jump is
        # made durable by the very next suffix append — a crash before
        # the next watermark-triggered checkpoint would recover the
        # advanced watermark with no seeds and silently serve holes for
        # the origin's below-cut history, with nothing left to
        # re-request.  A failed (or disabled, Config.ckpt=False)
        # persist keeps the live install — only crash-durability is at
        # risk — but must be loud.
        try:
            persisted = pm.checkpoint_now()
        except Exception:  # noqa: BLE001 — never fail the install
            persisted = None
            log.exception(
                "checkpoint after ckpt bootstrap of partition %d from "
                "%s failed", partition, origin_dc)
        if persisted is None:
            log.error(
                "partition %d: bootstrap seeds from %s are NOT durable "
                "(checkpointing disabled or failed) — a crash before "
                "the next checkpoint loses the origin's below-cut "
                "history", partition, origin_dc)
    return ans["commit_opid"]


def answer_ckpt_read(pm, own_dc, partition: int,
                     ranges: Optional[tuple] = None) -> Optional[dict]:
    """Server side of CKPT_READ: cut a fresh checkpoint on the owning
    PartitionManager and answer with its seeds + watermarks (None when
    checkpointing is disabled).  ``ranges`` (ISSUE 18, validated
    loudly) keeps only the seed keys inside the requester's interest —
    non-str keys are unclassifiable and always ship; the watermarks
    stay the FULL checkpoint's (the requester's jump covers the elided
    keys' history the same way a filtered repair answer does)."""
    ans = pm.ckpt_bootstrap_answer(own_dc)
    if ans is None:
        return None
    if ranges is not None:
        spec = InterestSpec(ranges)
        ans = dict(ans)
        ans["keys"] = {k: v for k, v in ans["keys"].items()
                       if spec.matches_key(k)}
    # clocks cross administrative domains as plain dicts, like
    # SNAPSHOT_READ's (the termcodec VC form is for wire frames)
    return ans


def answer_snapshot_read(db, objects, clock) -> Tuple[List, dict]:
    """Server side: serve the one-shot causal read through the DC's
    read serve plane (api.read_objects_static — the fast path when the
    ring is local, the interactive path on a federated member whose
    ring spans nodes), coalescing with the serving DC's own readers."""
    values, vc = db.read_objects_static(
        None if clock is None else VC(clock),
        [tuple(o) for o in objects])
    return values, dict(vc)

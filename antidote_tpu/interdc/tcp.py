"""TCP inter-DC transport — the erlzmq replacement.

The reference's transport is ZeroMQ via a C NIF: one PUB socket per
node for the txn stream (port 8086, reference src/inter_dc_pub.erl:87-92)
and a REQ/ROUTER pair for log-repair / bounded-counter RPC (port 8085,
src/inter_dc_query_receive_socket.erl:109-139).  This module provides
the same two channels over plain TCP so DCs in *different OS processes
or hosts* form a cluster:

- **Pub channel**: each DC binds a listener; subscribers dial in, send a
  one-frame hello naming themselves, then receive every published frame
  (4-byte big-endian length framing, matching the PB server's
  ``{packet,4}`` convention).  Dropped subscriber connections reconnect
  with backoff; any frames missed while down are recovered by the
  opid-watermark gap repair (antidote_tpu/interdc/sub_buf.py), exactly
  as ZMQ loss is in the reference.
- **Query channel**: each DC binds a second listener; requests are
  ``(origin, kind, payload)`` term frames answered synchronously by the
  DC's query handler (log-range reads, bcounter transfers, check-up).
  One persistent connection per target, re-dialed on failure;
  unreachable targets raise LinkDown like the in-process bus.

Everything on both channels is the safe tagged term codec
(antidote_tpu/interdc/termcodec.py) — never pickle: peers are other
administrative domains.

ISSUE 12 — zero-copy fan-out: a published frame is STAGED once
(header + payload framed a single time) and every subscriber's send
worker writes views of that one staging buffer; the per-subscriber
header re-framing the pre-ISSUE-12 Python mode paid (one fresh bytes
object per subscriber per frame) survives only behind
``Config.fabric_native=False`` as the bench baseline, counted by the
``antidote_fabric_pub_subscriber_copies_total`` family the config12
bench gates on.  The native hub already stages once in C++ and shares
the frame by refcount across subscriber queues; its bindings are now
split by GIL policy like cluster/nativelink.py's (quick bookkeeping
via PyDLL, the blocking create/publish/close class via CDLL — the
[gil-policy] lint rule pins the table), and ``fab_publish`` runs
OUTSIDE the transport lock behind a busy-refcount so publishers never
convoy on it and close() cannot free the hub under a call.
"""

from __future__ import annotations

import logging
import queue
import select
import socket
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.obs import nativeobs
from antidote_tpu.interdc import termcodec
from antidote_tpu.interdc.interest import (InterestError, hello_term,
                                           parse_hello)
from antidote_tpu.interdc.transport import LinkDown, Transport
from antidote_tpu.interdc.wire import DcDescriptor

log = logging.getLogger(__name__)

_MAX_FRAME = termcodec.MAX_TERM_BYTES


def _send_frame(sock: socket.socket, data: bytes) -> None:
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    if n > _MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds cap")
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class _SubSender:
    """One Python-mode subscriber's bounded send worker (the ROADMAP
    publish-stall satellite): ``publish`` only ENQUEUES a frame per
    subscriber, and each subscriber's own worker thread drains its
    queue — so one peer with a full TCP window delays nobody else, and
    the publisher never blocks.  A queue that overflows (the peer
    stalled past ``QUEUE_DEPTH`` frames) drops the subscriber, like
    the native hub's bounded per-subscriber queues and ZMQ's
    drop-on-slow PUB semantics; the peer resubscribes and the opid
    watermark gap-repairs whatever it missed.  Per-send timing still
    feeds ``antidote_ship_subscriber_send_seconds{peer}`` from the
    worker — the gauge stays accurate per send, it just no longer
    measures a stall every OTHER peer is paying for.

    ``framed=True`` (the ISSUE-12 staged mode) means offered buffers
    already carry their length header — ONE staging shared by every
    subscriber, this worker writes it verbatim (zero per-subscriber
    copies); ``framed=False`` keeps the legacy per-subscriber header
    concat as the fabric_native=False bench baseline."""

    QUEUE_DEPTH = 128

    def __init__(self, conn: socket.socket, label: str, on_dead,
                 framed: bool = False, interest_spec=None):
        self.conn = conn
        self.label = label
        self.framed = framed
        #: InterestSpec this peer announced in its hello, or None =
        #: full stream (ISSUE 18); publish picks this peer's slice by
        #: ``interest_spec.class_key()``
        self.interest_spec = interest_spec
        self._on_dead = on_dead
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(
            maxsize=self.QUEUE_DEPTH)
        self._dead = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"antidote-sub-{label}")
        self._thread.start()

    def offer(self, data: bytes) -> None:
        """Non-blocking enqueue; overflow drops the subscriber (a
        mid-stream stall would desync or convoy the stream anyway)."""
        try:
            self._q.put_nowait(data)
            stats.registry.pub_queue_depth.set(self._q.qsize(),
                                               peer=self.label)
            if self._dead:
                # a concurrent _die (worker send failure) removed the
                # gauge between our put and set: re-remove so a
                # dropped subscriber can't leave a frozen series
                stats.registry.pub_queue_depth.remove(peer=self.label)
        except queue.Full:
            log.warning("pub: dropping stalled subscriber %r "
                        "(send queue full)", self.label)
            self._die()

    def _run(self) -> None:
        while True:
            data = self._q.get()
            if data is None:
                return
            t0 = time.perf_counter()
            try:
                if self.framed:
                    # staged zero-copy path: the shared buffer goes
                    # out as-is — no per-subscriber bytes are built
                    self.conn.sendall(data)
                else:
                    _send_frame(self.conn, data)
            except OSError:
                self._die()
                return
            stats.registry.ship_subscriber_send.set(
                time.perf_counter() - t0, peer=self.label)
            stats.registry.pub_queue_depth.set(self._q.qsize(),
                                               peer=self.label)
            if self._dead:
                # a concurrent _die (offer-side queue overflow) removed
                # the gauge between our send and set: re-remove so a
                # dropped subscriber can't leave a frozen series
                stats.registry.ship_subscriber_send.remove(
                    peer=self.label)
                stats.registry.pub_queue_depth.remove(peer=self.label)
                return

    def _die(self) -> None:
        if self._dead:
            return
        self._dead = True
        try:
            self.conn.close()
        except OSError:
            pass
        stats.registry.ship_subscriber_send.remove(peer=self.label)
        stats.registry.pub_queue_depth.remove(peer=self.label)
        self._on_dead(self)

    def close(self) -> None:
        self._dead = True
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # worker will die on the closed socket
        try:
            self.conn.close()
        except OSError:
            pass


class _FabLib:
    """Dual ctypes binding of the native hub, split by GIL policy
    exactly like cluster/nativelink.py's _Lib (the [gil-policy] lint
    rule pins both tables):

    - BLOCKING class binds via ``CDLL`` (GIL released): fab_create
      binds a socket, fab_close joins the event thread, and
      fab_publish / fab_sub_count / fab_queued_bytes contend the hub
      mutex the EVENT THREAD holds across its whole per-poll
      subscriber sweep (pump_hello/pump_send over every queued frame)
      — a PyDLL call parked on that mutex would freeze every Python
      thread for the sweep's duration.  None may run inside a lock
      region.
    - QUICK bookkeeping (fab_port — an immutable field read, no
      mutex) binds via ``PyDLL`` (GIL held): a CDLL call re-acquires
      the GIL on return, which against busy threads costs up to a
      scheduler timeslice per call.

    The telemetry plane (ISSUE 16) splits the same way: the
    cursor/enable pair is atomics-only (no mutex, no syscall) — quick
    class; the drain is a bulk memcpy of up to 128 KiB — CDLL class,
    GIL released, never called inside a lock region.
    """

    def __init__(self, path: str):
        import ctypes

        quick = ctypes.PyDLL(path)
        slow = ctypes.CDLL(path)
        self.fab_create = slow.fab_create
        self.fab_create.restype = ctypes.c_void_p
        self.fab_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        self.fab_publish = slow.fab_publish
        # returns the frame's publish seq (> 0, monotonic) — the key
        # the telemetry drain joins SUB_DRAIN events back to txids on
        self.fab_publish.restype = ctypes.c_longlong
        self.fab_publish.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        self.fab_close = slow.fab_close
        self.fab_close.restype = None
        self.fab_close.argtypes = [ctypes.c_void_p]
        self.fab_port = quick.fab_port
        self.fab_port.restype = ctypes.c_int
        self.fab_port.argtypes = [ctypes.c_void_p]
        self.fab_sub_count = slow.fab_sub_count
        self.fab_sub_count.restype = ctypes.c_int
        self.fab_sub_count.argtypes = [ctypes.c_void_p]
        self.fab_queued_bytes = slow.fab_queued_bytes
        self.fab_queued_bytes.restype = ctypes.c_longlong
        self.fab_queued_bytes.argtypes = [ctypes.c_void_p]
        self.fab_tel_cursor = quick.fab_tel_cursor
        self.fab_tel_cursor.restype = ctypes.c_int
        self.fab_tel_cursor.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.c_int]
        self.fab_tel_enable = quick.fab_tel_enable
        self.fab_tel_enable.restype = None
        self.fab_tel_enable.argtypes = [ctypes.c_void_p, ctypes.c_int]
        self.fab_tel_drain = slow.fab_tel_drain
        self.fab_tel_drain.restype = ctypes.c_long
        self.fab_tel_drain.argtypes = [
            ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_void_p,
            ctypes.c_long, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.POINTER(ctypes.c_ulonglong)]


class TcpTransport(Transport):
    """One DC's endpoint of the TCP fabric.  Construct one per DC
    process (``transport_from_config`` is the Config-routed path);
    ``register`` binds the listeners, ``connect`` subscribes to a peer
    discovered via descriptor exchange.

    ``native_pub`` selects the publish fan-out plane: "auto" = the C++
    hub when g++ built it, else the staged Python fan-out; True =
    require the hub; "python" = force the staged Python fan-out (one
    framing shared by every subscriber — tests and the config12 bench
    pin the staged plane with it even where the hub builds); False =
    the exact legacy Python path (per-subscriber framing), the
    Config.fabric_native=False bench baseline."""

    def __init__(self, host: str = "127.0.0.1", pub_port: int = 0,
                 query_port: int = 0, connect_timeout: float = 5.0,
                 request_timeout: float = 30.0,
                 native_pub: "bool | str" = "auto",
                 telemetry: bool = True):
        self.host = host
        self._pub_port = pub_port
        self._query_port = query_port
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self._dc_id: Any = None
        self._inbox: "queue.Queue[bytes]" = queue.Queue()
        self._handler: Optional[Callable[[Any, str, Any], Any]] = None
        #: live subscriber send workers on OUR pub listener (Python
        #: mode): one _SubSender each — publish enqueues per
        #: subscriber instead of sending serially, so a slow peer
        #: cannot stall the stream (ISSUE 8 satellite; the per-peer
        #: send-duration gauge from ISSUE 7 stays per-send accurate)
        self._subscribers: List[_SubSender] = []
        #: this endpoint's own interest spec (ISSUE 18) — announced in
        #: the subscribe-side hello; None = full stream.  Read fresh at
        #: every (re)dial, AND re-announced immediately on every live
        #: sub connection when it changes (ISSUE 19): a widened
        #: interest takes effect at the publisher without waiting for
        #: a reconnect, matching the in-proc bus's immediacy
        #: (docs/interest_routing.md §3)
        self._local_interest = None
        #: serializes re-hello sends across live sub sockets (sendall
        #: must not run under self._lock, and two concurrent
        #: set_local_interest calls must not interleave frames)
        self._rehello_lock = threading.Lock()
        #: target dc_id -> (addr, persistent request socket or None)
        self._peers: Dict[Any, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._pub_srv: Optional[socket.socket] = None
        self._query_srv: Optional[socket.socket] = None
        #: native C++ publish hub (the erlzmq PUB role,
        #: antidote_tpu/native/fabric.cpp): the commit path only copies
        #: the frame into per-subscriber bounded queues; a stalled or
        #: overflowing peer is dropped by the event thread without ever
        #: blocking the publisher.  "auto" = use it when g++ built it.
        self._native_pub = native_pub
        self._hub = None
        self._hub_lib = None
        #: publishers currently inside fab_publish — close() must not
        #: fab_close (which frees the C++ object) under them; the call
        #: itself runs OUTSIDE self._lock so publishers never convoy
        #: on the transport lock (and the [gil-policy] rule holds)
        self._hub_busy = 0
        self._hub_cv = threading.Condition(self._lock)
        #: last hub gauge pull (fab_sub_count/fab_queued_bytes take
        #: the hub mutex — sampled on a cadence, not per frame)
        self._hub_gauge_t = 0.0
        #: telemetry plane (ISSUE 16): drain cursor + cumulative
        #: overwrite losses live here (C only knows head); the buffer
        #: is reused so the 50 ms cadence never allocates
        self._tel_tail = 0
        self._tel_dropped = 0
        self._tel_buf = None  # allocated with the hub (_open_native_hub)
        self._tel_enabled = bool(telemetry)
        self._tel_name: Optional[str] = None
        self._tel_lock = threading.Lock()
        #: single-drainer guard: concurrent publishers hitting the
        #: gauge cadence together must not interleave cursor updates;
        #: losers skip (try-acquire) rather than convoy
        self._tel_drain_lock = threading.Lock()
        #: publish seq (low 32) -> sampled txids the frame carried;
        #: bounded FIFO (oldest evicted) — the drain joins SUB_DRAIN
        #: events back to txids to emit native_fanout spans
        self._seq_txids: "OrderedDict[int, tuple]" = OrderedDict()
        #: staged zero-copy Python fan-out (ISSUE 12): frame once,
        #: every subscriber sends views of the one staging buffer.
        #: False only under the full-legacy knob — the bench baseline.
        self._staged = native_pub is not False

    # ------------------------------------------------------------ registry

    def register(self, desc: DcDescriptor,
                 query_handler: Callable[[Any, str, Any], Any]
                 ) -> "queue.Queue[bytes]":
        self._dc_id = desc.dc_id
        self._handler = query_handler
        if self._native_pub and self._native_pub != "python":
            self._hub = self._open_native_hub()
        if self._hub is None:
            if self._native_pub is True:
                raise RuntimeError("native pub hub unavailable "
                                   "(g++ missing or build failed)")
            self._pub_srv = self._bind(self._pub_port)
            self._spawn(self._accept_pub_loop,
                        name="antidote-fab-pub-accept")
        self._query_srv = self._bind(self._query_port)
        self._spawn(self._accept_query_loop,
                    name="antidote-fab-query-accept")
        return self._inbox

    def _open_native_hub(self):
        import ctypes

        from antidote_tpu.native.build import ensure_built

        so = ensure_built("fabric")
        if so is None:
            return None
        lib = _FabLib(so)
        hub = lib.fab_create(self.host.encode(), self._pub_port)
        if not hub:
            return None
        self._hub_lib = lib
        self._tel_buf = ctypes.create_string_buffer(
            nativeobs.EVENT_SIZE * nativeobs.RING_CAPACITY)
        # the watchdog probe outlives a single drain cadence: a hub
        # whose PUBLISHERS go quiet still beats (the event thread
        # polls), so a stale heartbeat really means a wedged thread
        self._tel_name = f"fabric:{self._dc_id}"
        nativeobs.watchdog.register(self._tel_name, self._tel_probe)
        if not self._tel_enabled:
            lib.fab_tel_enable(hub, 0)
        return hub

    def unregister(self, dc_id) -> None:
        self.close()

    def local_addrs(self) -> Optional[Tuple[Tuple, Tuple]]:
        """((host, pub_port),), ((host, query_port),) once the listeners
        are bound (register) — what goes into this DC's descriptor."""
        if self._query_srv is None:
            return None
        with self._lock:
            if self._hub is not None:
                pub_port = self._hub_lib.fab_port(self._hub)
            elif self._pub_srv is not None:
                pub_port = self._pub_srv.getsockname()[1]
            else:
                return None
        return (((self.host, pub_port),),
                ((self.host, self._query_srv.getsockname()[1]),))

    def _bind(self, port: int) -> socket.socket:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, port))
        srv.listen(64)
        return srv

    def _spawn(self, fn, *args, name: Optional[str] = None) -> None:
        # every fabric thread carries a component name (ISSUE 12):
        # /debug/pipeline's threads section and the causal-probe dumps
        # attribute a blocked send to "antidote-fab-..." instead of
        # Thread-N
        t = threading.Thread(target=fn, args=args, daemon=True,
                             name=name or "antidote-fab-io")
        t.start()
        self._threads.append(t)

    # ----------------------------------------------------------- pub side

    def _accept_pub_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._pub_srv.accept()
            except OSError:
                return
            # hello frame names the subscriber; an ISSUE-18 tagged
            # hello additionally carries its interest spec.  A
            # malformed spec closes the connection LOUDLY — the peer
            # must never end up on a silent full or empty stream it
            # didn't subscribe to
            try:
                conn.settimeout(self.connect_timeout)
                hello = _recv_frame(conn)
                term = termcodec.decode(hello) if hello else None
                peer, spec = parse_hello(term)
                conn.settimeout(None)
            except InterestError as e:
                log.error("pub: rejecting subscriber with malformed "
                          "interest spec: %s", e)
                conn.close()
                continue
            except (OSError, ValueError):
                conn.close()
                continue
            log.debug("pub: subscriber %r connected (interest=%s)",
                      peer, spec.ranges if spec else "full")
            if spec is not None:
                stats.registry.interest_peer_ranges.set(
                    len(spec.ranges), peer=str(peer))
            # bounded sends: each subscriber gets its own worker +
            # queue (_SubSender), so a hung peer or full TCP window
            # stalls only its own stream; the send timeout below
            # bounds each individual send, after which the worker
            # drops the connection (mid-frame would desync anyway)
            # and the peer resubscribes + gap-repairs — ZMQ's
            # drop-on-slow PUB semantics
            conn.settimeout(self.connect_timeout)
            with self._lock:
                sender = _SubSender(
                    conn, str(peer), self._drop_subscriber,
                    framed=self._staged, interest_spec=spec)
                self._subscribers.append(sender)
            # live re-SUBSCRIBE (ISSUE 19): the peer may re-send its
            # hello on this same connection when its interest changes;
            # a per-subscriber reader adopts the new spec immediately
            self._spawn(self._rehello_loop, sender,
                        name=f"antidote-fab-rehello-{peer}")

    def _rehello_loop(self, sender: "_SubSender") -> None:
        """Read re-sent hellos from one live subscriber connection and
        adopt the new interest spec immediately (ISSUE 19) — the very
        next published frame is sliced for the widened interest,
        parity with the in-proc bus's immediate set_local_interest
        (pre-ISSUE-19 TCP converged only at the next reconnect).  A
        malformed re-hello drops the subscriber LOUDLY, exactly like a
        malformed first hello; a pre-upgrade subscriber never writes,
        so this reader just idles on select."""
        conn = sender.conn
        while not self._stop.is_set() and not sender._dead:
            try:
                ready, _, _ = select.select([conn], [], [], 0.25)
            except (OSError, ValueError):
                return  # connection closed under us
            if not ready:
                continue
            try:
                frame = _recv_frame(conn)
            except (OSError, ValueError):
                return
            if frame is None:
                return  # peer hung up; the send worker cleans up
            try:
                _peer, new_spec = parse_hello(termcodec.decode(frame))
            except (InterestError, ValueError) as e:
                log.error("pub: dropping subscriber %r after a "
                          "malformed re-hello: %s", sender.label, e)
                sender._die()
                return
            with self._lock:
                sender.interest_spec = new_spec
            if new_spec is not None:
                stats.registry.interest_peer_ranges.set(
                    len(new_spec.ranges), peer=sender.label)
            else:
                stats.registry.interest_peer_ranges.remove(
                    peer=sender.label)
            log.debug("pub: subscriber %r re-announced interest=%s",
                      sender.label,
                      new_spec.ranges if new_spec else "full")

    def _drop_subscriber(self, sender: "_SubSender") -> None:
        with self._lock:
            if sender in self._subscribers:
                self._subscribers.remove(sender)
        if sender.interest_spec is not None:
            stats.registry.interest_peer_ranges.remove(
                peer=sender.label)

    #: seq -> txids attribution entries kept live; frames the drain
    #: never joins (unsampled cadence gaps) age out by eviction
    _TEL_SEQ_CAP = 512

    #: opt-in span-attribution capability: the log sender only passes
    #: ``txids=`` to transports that declare this — the base
    #: publish(origin, data) signature stays the contract for
    #: everything else (test stubs, InProcBus, external buses)
    accepts_txids = True

    #: interest-routing capability (ISSUE 18): the log sender only cuts
    #: per-class slices (and passes ``slices=``) for transports that
    #: declare this
    accepts_interest = True

    def set_local_interest(self, dc_id, spec) -> None:
        """Adopt the spec for future dials AND re-announce it NOW on
        every live sub connection (ISSUE 19): the publisher's re-hello
        reader adopts it before its next published frame, so a widened
        interest starts filling immediately instead of at the next
        reconnect.  A failed send closes that one connection — the
        subscribe loop re-dials and the fresh hello carries the new
        spec, so the announcement is never silently lost."""
        with self._lock:
            self._local_interest = spec
            socks = [p.get("sub_sock") for p in self._peers.values()
                     if p.get("sub_sock") is not None]
        if self._dc_id is None or not socks:
            return
        payload = termcodec.encode(hello_term(self._dc_id, spec))
        with self._rehello_lock:  # sends OUTSIDE self._lock, in order
            for sock in socks:
                try:
                    # lock-ok: _rehello_lock EXISTS to order these
                    # sends — racing widen calls must not interleave
                    # hello frames on a live socket; it never nests
                    # inside self._lock and guards nothing else
                    _send_frame(sock, payload)
                except OSError:
                    # kick the subscribe loop into a re-dial, whose
                    # hello re-reads the spec — non-fatal by design
                    try:
                        sock.close()
                    except OSError:
                        pass

    def interest_classes(self) -> Dict:
        """Distinct interest specs across live Python-mode subscribers.
        The native hub does not slice (docs/interest_routing.md non-
        goal) and hub mode has no Python subscriber list, so this is
        naturally empty there — hub peers get the full stream, a safe
        superset."""
        with self._lock:
            return {s.interest_spec.class_key(): s.interest_spec
                    for s in self._subscribers
                    if s.interest_spec is not None}

    def publish(self, origin, data: bytes, txids: Tuple = (),
                slices=None) -> None:
        with self._lock:
            hub = self._hub
            if hub is not None:
                # the busy refcount (not the lock) protects the hub
                # pointer across the call: close() waits it out before
                # fab_close frees the C++ object, and fab_publish —
                # a CDLL call that can contend the hub mutex against
                # an event thread mid-send — runs OUTSIDE the
                # transport lock so publishers never convoy on it
                # (the [gil-policy] rule)
                self._hub_busy += 1
            else:
                senders = list(self._subscribers)
        if hub is not None:
            try:
                seq = int(self._hub_lib.fab_publish(hub, data, len(data)))
                stats.registry.pub_frames.inc()
                if txids and seq > 0:
                    # remember which sampled txns rode this frame so
                    # the telemetry drain can hang native_fanout spans
                    # off its SUB_DRAIN events (seq is the join key;
                    # the ring stores its low 32 bits)
                    with self._tel_lock:
                        self._seq_txids[seq & 0xFFFFFFFF] = tuple(txids)
                        while len(self._seq_txids) > self._TEL_SEQ_CAP:
                            self._seq_txids.popitem(last=False)
                # gauge pulls contend the hub mutex against the event
                # thread's send sweep (CDLL — GIL released), so they
                # ride a cadence instead of every frame: two extra
                # mutex+GIL crossings per frame would tax the hot
                # publish path for a gauge nobody reads that often
                now = time.monotonic()
                if now - self._hub_gauge_t >= 0.05:
                    self._hub_gauge_t = now
                    stats.registry.pub_fanout.set(
                        self._hub_lib.fab_sub_count(hub))
                    stats.registry.hub_queued_bytes.set(
                        self._hub_lib.fab_queued_bytes(hub))
                    # the flight-recorder drain rides the same cadence
                    # (never per frame): quick cursor read, then a CDLL
                    # bulk copy only when events are pending — still
                    # under the busy refcount, still outside the lock
                    self._telemetry_drain(hub)
            finally:
                with self._hub_cv:
                    self._hub_busy -= 1
                    self._hub_cv.notify_all()
            return
        # enqueue-only fan-out: the per-subscriber workers send in
        # parallel, so the publisher (and every healthy peer) is
        # never behind one slow peer's TCP window (the ROADMAP
        # publish-stall item, closed)
        stats.registry.pub_frames.inc()
        if self._staged:
            # ISSUE 12 zero-copy: header + payload framed ONCE; every
            # subscriber's worker writes views of this one staging
            # buffer verbatim (framed=True) — zero per-subscriber
            # Python copies, asserted structurally by the config12
            # bench via the copies-per-frame counter.  ISSUE 18
            # generalizes "one buffer" to "one buffer per interest
            # class": subscribers sharing a spec share one staged
            # slice; spec-less subscribers (and classes the sender
            # didn't cut — a hello that raced the class snapshot)
            # still share the ONE full staging, bit-for-bit today's
            staged = struct.pack(">I", len(data)) + data
            staged_by_class: Dict = {}
            stats.registry.pub_fanout.set(len(senders))
            for sender in senders:
                spec = sender.interest_spec
                if slices is None or spec is None:
                    sender.offer(staged)
                    continue
                ck = spec.class_key()
                if ck not in slices:
                    sender.offer(staged)  # race fallback: full frame
                    continue
                payload = slices[ck]
                if payload is None:
                    continue  # frame elided for this class entirely
                frame = staged_by_class.get(ck)
                if frame is None:
                    frame = struct.pack(">I", len(payload)) + payload
                    staged_by_class[ck] = frame
                sender.offer(frame)
        else:
            for sender in senders:
                # legacy baseline (fabric_native=False): each worker
                # re-frames the payload — one fresh bytes object per
                # subscriber per frame, the copy the staged path
                # eliminates (slices are a staged-mode feature; the
                # baseline ships the full stream)
                stats.registry.pub_sub_copies.inc()
                sender.offer(data)

    # ----------------------------------------------------- telemetry plane

    def _pin_hub(self):
        """Take the busy refcount on the live hub (None = no hub);
        close() waits it out before fab_close frees the C++ object."""
        with self._lock:
            hub = self._hub
            if hub is None:
                return None
            self._hub_busy += 1
        return hub

    def _unpin_hub(self) -> None:
        with self._hub_cv:
            self._hub_busy -= 1
            self._hub_cv.notify_all()

    def set_telemetry(self, on: bool) -> None:
        """Flip native event recording (Config.native_telemetry).
        Heartbeats keep beating either way, so the watchdog still
        works with recording off."""
        self._tel_enabled = bool(on)
        hub = self._pin_hub()
        if hub is None:
            return
        try:
            self._hub_lib.fab_tel_enable(hub, 1 if on else 0)
        finally:
            self._unpin_hub()

    def _tel_probe(self) -> int:
        """Watchdog probe: the hub ring's last-heartbeat wall-ns
        (0 = hub gone).  PyDLL cursor read — atomics only."""
        import ctypes

        hub = self._pin_hub()
        if hub is None:
            return 0
        try:
            out = (ctypes.c_ulonglong * 4)()
            self._hub_lib.fab_tel_cursor(hub, out, 4)
            return int(out[2])
        finally:
            self._unpin_hub()

    def telemetry_drain(self,
                        max_events: int = nativeobs.RING_CAPACITY) -> int:
        """Drain the hub's flight-recorder ring into the NATIVE_*
        families; returns events folded.  Public face for the gossip
        tick and tests; publish()'s gauge cadence calls the pinned
        inner helper directly."""
        hub = self._pin_hub()
        if hub is None:
            return 0
        try:
            return self._telemetry_drain(hub, max_events)
        finally:
            self._unpin_hub()

    def _telemetry_drain(self, hub,
                         max_events: int = nativeobs.RING_CAPACITY) -> int:
        """Caller holds the busy refcount.  Quick cursor read; CDLL
        bulk copy only when events are pending (never inside a lock
        region — the [gil-policy] drain class)."""
        import ctypes

        if not self._tel_drain_lock.acquire(blocking=False):
            return 0  # another publisher is mid-drain; skip, not wait
        try:
            cur = (ctypes.c_ulonglong * 4)()
            self._hub_lib.fab_tel_cursor(hub, cur, 4)
            head, hb_wall, oldest = int(cur[0]), int(cur[2]), int(cur[3])
            n = 0
            if head != self._tel_tail and self._tel_buf is not None:
                new_tail = ctypes.c_ulonglong()
                dropped = ctypes.c_ulonglong()
                n = int(self._hub_lib.fab_tel_drain(
                    hub, self._tel_tail, self._tel_buf,
                    min(max_events, nativeobs.RING_CAPACITY),
                    ctypes.byref(new_tail), ctypes.byref(dropped)))
                self._tel_tail = int(new_tail.value)
                self._tel_dropped += int(dropped.value)
                if n > 0:
                    with self._tel_lock:
                        seq_txids = dict(self._seq_txids)
                    nativeobs.fold_events(
                        nativeobs.decode_events(self._tel_buf, n),
                        seq_txids=seq_txids)
            nativeobs.publish_ring_gauges(
                "fabric", hb_wall, self._tel_dropped, head,
                self._tel_tail, oldest_enq_ns=oldest)
            return n
        finally:
            self._tel_drain_lock.release()

    def telemetry_info(self) -> dict:
        """The hub ring's /debug/pipeline face: occupancy, losses,
        heartbeat age (obs/pipeline.py embeds it)."""
        import ctypes

        hub = self._pin_hub()
        if hub is None:
            return {}
        try:
            out = (ctypes.c_ulonglong * 4)()
            self._hub_lib.fab_tel_cursor(hub, out, 4)
        finally:
            self._unpin_hub()
        head = int(out[0])
        return {
            "head": head,
            "tail": self._tel_tail,
            "occupancy": min(head - self._tel_tail,
                             nativeobs.RING_CAPACITY),
            "dropped_events": self._tel_dropped,
            "heartbeat_count": int(out[1]),
            "heartbeat_age_s": nativeobs.heartbeat_age_s(int(out[2])),
            "enabled": self._tel_enabled,
        }

    # ----------------------------------------------------- subscribe side

    def connect(self, origin, desc: DcDescriptor) -> None:
        """Subscribe to ``desc``'s pub stream and remember its query
        address (reference inter_dc_sub connect + probe,
        src/inter_dc_sub.erl:126-145)."""
        if desc.dc_id == self._dc_id:
            return
        with self._lock:
            if desc.dc_id in self._peers:
                self._peers[desc.dc_id]["desc"] = desc
                return
            self._peers[desc.dc_id] = {"desc": desc, "req_sock": None,
                                       "req_lock": threading.Lock()}
        # probe the query channel so a dead peer fails fast, like the
        # reference's 5 s recv-probe on connect; a failed probe must
        # leave no trace, so the caller's retry probes again and spawns
        # the subscribe loop then
        try:
            self.request(origin, desc.dc_id, "check_up", None)
        except LinkDown:
            with self._lock:
                self._peers.pop(desc.dc_id, None)
            raise
        self._spawn(self._subscribe_loop, desc.dc_id,
                    name=f"antidote-fab-subscribe-{desc.dc_id}")

    def _subscribe_loop(self, target) -> None:
        """Dial the peer's pub listener; deliver frames to the inbox;
        reconnect with backoff on drop (gap repair recovers the hole)."""
        backoff = 0.05
        while not self._stop.is_set():
            with self._lock:
                peer = self._peers.get(target)
            if peer is None:
                return
            addr = tuple(peer["desc"].pub_addrs[0])
            with self._lock:
                spec = self._local_interest
            try:
                sock = socket.create_connection(
                    addr, timeout=self.connect_timeout)
                # spec-less = the pre-upgrade plain-dc_id hello (full
                # stream); the spec is re-read each dial so a widened
                # interest takes effect on reconnect (ISSUE 18), and
                # set_local_interest re-hellos the LIVE socket
                # registered below so it also takes effect between
                # reconnects (ISSUE 19)
                _send_frame(sock, termcodec.encode(
                    hello_term(self._dc_id, spec)))
                sock.settimeout(None)
                with self._lock:
                    live = self._peers.get(target)
                    if live is not None:
                        live["sub_sock"] = sock
                backoff = 0.05
                try:
                    while not self._stop.is_set():
                        frame = _recv_frame(sock)
                        if frame is None:
                            break
                        self._inbox.put(frame)
                finally:
                    with self._lock:
                        live = self._peers.get(target)
                        if live is not None \
                                and live.get("sub_sock") is sock:
                            live["sub_sock"] = None
                sock.close()
            except (OSError, ValueError):
                # ValueError = corrupt/desynced stream (oversized length
                # header): drop the connection and resubscribe — gap
                # repair recovers whatever the bad stream lost
                pass
            if self._stop.wait(backoff):
                return
            backoff = min(backoff * 2, 2.0)

    # ---------------------------------------------------------- query side

    def _accept_query_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._query_srv.accept()
            except OSError:
                return
            self._spawn(self._serve_query_conn, conn,
                        name="antidote-fab-query-serve")

    def _serve_query_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    frame = _recv_frame(conn)
                except ValueError:
                    return
                if frame is None:
                    return
                try:
                    origin, kind, payload = termcodec.decode(frame)
                    result = self._handler(origin, kind, payload)
                    reply = termcodec.encode(("ok", result))
                except Exception as e:  # noqa: BLE001 — must answer
                    log.exception("query handler failed")
                    reply = termcodec.encode(("error", str(e)))
                try:
                    _send_frame(conn, reply)
                except OSError:
                    return

    def request(self, origin, target, kind: str, payload) -> Any:
        with self._lock:
            peer = self._peers.get(target)
        if peer is None:
            raise LinkDown(f"unknown DC {target!r}")
        with peer["req_lock"]:
            for attempt in (0, 1):
                sock = peer["req_sock"]
                try:
                    if sock is None:
                        addr = tuple(peer["desc"].logreader_addrs[0])
                        sock = socket.create_connection(
                            addr, timeout=self.connect_timeout)
                        sock.settimeout(self.request_timeout)
                        peer["req_sock"] = sock
                    _send_frame(sock, termcodec.encode(
                        (origin, kind, payload)))
                    frame = _recv_frame(sock)
                    if frame is None:
                        raise OSError("connection closed mid-request")
                    status, result = termcodec.decode(frame)
                    if status == "error":
                        raise LinkDown(
                            f"remote query failed at {target!r}: {result}")
                    return result
                except (OSError, ValueError) as e:
                    if peer["req_sock"] is not None:
                        peer["req_sock"].close()
                        peer["req_sock"] = None
                    if attempt == 1:
                        raise LinkDown(
                            f"DC {target!r} unreachable: {e}") from e

    # ------------------------------------------------------------ shutdown

    def close(self) -> None:
        self._stop.set()
        if self._tel_name is not None:
            nativeobs.watchdog.unregister(self._tel_name)
        with self._lock:
            hub, self._hub = self._hub, None
        if hub is not None:
            with self._hub_cv:
                # publishers inside fab_publish pinned the hub with the
                # busy refcount; fab_close deletes the C++ object, so
                # wait them out (the shut publishers drain in µs — the
                # call is a queue copy, never a send)
                drained = self._hub_cv.wait_for(
                    lambda: self._hub_busy == 0, timeout=5.0)
            if drained:
                # freed outside the lock (joins the event thread); no
                # new publisher can reach it: they read self._hub
                # under the lock, and it is None now
                self._hub_lib.fab_close(hub)
            else:
                # a publisher is STILL inside fab_publish after the
                # grace period (a starved thread on a loaded box):
                # freeing the hub under its live call would be a
                # use-after-free — leak it instead (one event thread +
                # a few buffers, once, at shutdown)
                log.error("pub hub close timed out with a publisher "
                          "still in fab_publish; leaking the hub")
        for srv in (self._pub_srv, self._query_srv):
            if srv is not None:
                try:
                    # wake the accept() thread: close() alone leaves the
                    # kernel file (and the LISTEN entry) alive until the
                    # in-syscall accept returns, blocking an in-process
                    # rebind of the port (see cluster/link.py close)
                    srv.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    srv.close()
                except OSError:
                    pass
        with self._lock:
            for sender in self._subscribers:
                sender.close()
            self._subscribers.clear()
            for peer in self._peers.values():
                if peer["req_sock"] is not None:
                    peer["req_sock"].close()
                    peer["req_sock"] = None


def transport_from_config(config=None, **kwargs) -> TcpTransport:
    """The ONE Config-routed TcpTransport construction path (the
    gate_from_config discipline, pinned by concurrency_lint's
    [knob-routing] rule): ``Config.fabric_native`` selects the publish
    fan-out plane — "auto" uses the C++ hub when the toolchain built
    it and the staged zero-copy Python fan-out otherwise; ``True``
    requires the hub (register fails loudly without a compiler);
    ``False`` keeps the exact legacy per-subscriber-framing Python
    path, bit-for-bit, as the benches' comparison baseline."""
    from antidote_tpu.config import Config

    cfg = config or Config()
    if cfg.fabric_native not in ("auto", True, False):
        # "python" is a valid DIRECT TcpTransport mode (tests/benches
        # pin the staged fan-out with it) but not a valid Config knob:
        # build_link would route the same value to the NATIVE node
        # fabric — fail loudly instead of splitting the cluster
        raise ValueError(
            f"Config.fabric_native must be 'auto', True, or False "
            f"(got {cfg.fabric_native!r})")
    kwargs.setdefault("telemetry", cfg.native_telemetry)
    return TcpTransport(native_pub=cfg.fabric_native, **kwargs)

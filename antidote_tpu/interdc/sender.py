"""Per-partition inter-DC log sender — with the batched shipping plane.

Every local log append streams here (reference src/logging_vnode.erl:422
→ src/inter_dc_log_sender_vnode.erl:119-131); a TxnAssembler groups the
records per txid until the commit record arrives, then the whole txn
ships with the stream's opid watermark.  A periodic heartbeat/ping
carries the partition's min-prepared time so remote GSTs keep advancing
through quiet periods (reference :133-143, ?HEARTBEAT_PERIOD
include/antidote.hrl:55).

ISSUE 6 rebuilt the wire economy around a per-stream ship buffer:
under ``Config.interdc_ship`` a committed txn only STAGES on the
committing thread — an async worker coalesces staged txns under a time
window + byte/txn budget (``interdc_ship_us`` / ``interdc_ship_bytes``
/ ``interdc_ship_txns``) into ONE columnar batch frame
(wire.InterDcBatch) and publishes it off the commit path, with a
bounded buffer backpressuring committers so a stalled transport cannot
let staged txns grow without bound.  Heartbeats piggyback on batch
frames while the stream has traffic and only pay a standalone ping
frame when it is quiet.  ``interdc_ship=False`` keeps the legacy
one-frame-per-txn path as the benches' comparison baseline.

Both paths publish through a per-stream ordered outbox: frames enter
it in watermark order inside the same critical section that advances
``last_sent_opid``, and leave it under a dedicated publish lock — the
pre-ISSUE-6 code published after dropping the lock, so two committing
threads could emit frames out of opid order and force a spurious
SubBuf gap-repair fetch at every receiver.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import List, Optional

from antidote_tpu import stats
from antidote_tpu.config import Config as _Config
from antidote_tpu.interdc import interest as idc_interest
from antidote_tpu.interdc import termcodec
from antidote_tpu.interdc.transport import Transport
from antidote_tpu.interdc.wire import InterDcBatch, InterDcTxn
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.records import LogRecord, TxnAssembler

#: the ship knobs' single source of truth is Config's field defaults
#: (config.py) — direct InterDcLogSender(...) constructions (tests,
#: benches) inherit exactly what a config-built DC gets
_KNOB = {k: _Config.__dataclass_fields__[f"interdc_{k}"].default
         for k in ("ship", "ship_us", "ship_bytes", "ship_txns")}

#: staged-txn cap: past ``ship_txns * this`` the committing thread
#: blocks until the worker drains (the ingest plane's 4x rule)
SHIP_BACKPRESSURE_FACTOR = 4
#: upper bound on a committer's backpressure wait — a wedged transport
#: must degrade to unbounded staging (with a log line), never deadlock
#: the partition lock the committer holds
_BACKPRESSURE_TIMEOUT_S = 5.0


def _note_frame(kind: str, nbytes: int, ntxns: int = 0,
                piggyback: bool = False) -> None:
    """Count one published frame and refresh the amortization gauges —
    txns per batch frame (up) and wire bytes per txn-carrying frame's
    txn (down), the ratios the replication bench gates on."""
    reg = stats.registry
    reg.ship_frames.inc(kind=kind)
    if kind == "batch" and ntxns:
        # ship_txns counts BATCH-carried txns only: the txns-per-frame
        # gauge must not be inflated by legacy per-txn frames
        reg.ship_txns.inc(ntxns)
    if kind != "ping":
        reg.ship_bytes.inc(nbytes)
    if piggyback:
        reg.ship_piggybacked_pings.inc()
    batches = reg.ship_frames.value(kind="batch")
    if batches:
        reg.ship_txns_per_frame.set(reg.ship_txns.value() / batches)
    carried = reg.ship_txns.value() + reg.ship_frames.value(kind="txn")
    if carried:
        reg.ship_bytes_per_txn.set(reg.ship_bytes.value() / carried)


def _trace_permille() -> int:
    """The process tracer's sample rate as an integer permille — the
    frame trace header's compact form (ISSUE 7).  Receivers replay the
    origin's deterministic per-txid decision at this rate, so a
    sampled txn's remote-side spans record even when the local rate
    differs."""
    return max(0, min(1000, int(round(tracer.sample_rate * 1000))))


def _est_term_bytes(v) -> int:
    """Cheap encoded-size estimate for the ship buffer's byte budget
    (soft budget: the worker closes a frame early past it, so an
    estimate is enough — exact sizing would mean encoding on the
    commit path, the cost this plane removes)."""
    if isinstance(v, (str, bytes)):
        return len(v) + 5
    if isinstance(v, (tuple, list, set, frozenset)):
        return 5 + sum(_est_term_bytes(x) for x in v)
    if isinstance(v, dict):
        return 5 + sum(_est_term_bytes(k) + _est_term_bytes(x)
                       for k, x in v.items())
    return 9


def est_txn_bytes(txn: InterDcTxn) -> int:
    n = 32 + 16 * len(txn.snapshot_vc or ())
    for r in txn.records:
        n += 24
        if r.kind() == "update":
            n += (_est_term_bytes(r.payload[1])
                  + len(r.payload[2]) + _est_term_bytes(r.payload[3]))
    return n


class InterDcLogSender:
    def __init__(self, dc_id, partition: int, transport: Transport,
                 enabled: bool = True, config=None):
        self.dc_id = dc_id
        self.partition = partition
        self.transport = transport
        #: publishing gate: off until the DC joins a cluster (reference
        #: start_bg_processes ordering, src/inter_dc_manager.erl:112-145)
        self.enabled = enabled
        self.assembler = TxnAssembler()
        #: opid watermark of the last staged-or-broadcast record for
        #: this stream (seeded from the recovered log at restart by the
        #: manager, reference {start_timer} src/logging_vnode.erl:301-322)
        self.last_sent_opid = 0
        self.ship = _KNOB["ship"] if config is None else config.interdc_ship
        self.ship_us = (_KNOB["ship_us"] if config is None
                        else config.interdc_ship_us)
        self.ship_bytes = (_KNOB["ship_bytes"] if config is None
                           else config.interdc_ship_bytes)
        self.ship_txns = max(1, _KNOB["ship_txns"] if config is None
                             else config.interdc_ship_txns)
        #: interest routing (ISSUE 18): when on AND the transport can
        #: route slices, _drain_outbox cuts one slice per live interest
        #: class before publishing.  Off (the default) the publish path
        #: is bit-for-bit the pre-ISSUE-18 one — no classes queried, no
        #: slices cut, the plain publish signature used.
        self.interest_routing = (
            _Config.__dataclass_fields__["interest_routing"].default
            if config is None else config.interest_routing)
        #: per-interest-class watermark chains (docs/interest_routing.md
        #: §2): class_key -> opid of the last txn EMITTED to that class.
        #: Initialized at the first frame a class is seen (that frame's
        #: base) and advanced only on emission — both rules keep every
        #: class's stream gapless without ever advancing past a skipped
        #: txn.  Mutated only in _cut_slices, under ``_pub_lock``.
        self._class_wm: dict = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        #: per-stream ordered outbox: (kind, txid, frame, ntxns,
        #: piggyback) appended in watermark order under ``_lock``,
        #: published FIFO under ``_pub_lock``
        self._outbox: deque = deque()
        self._pub_lock = threading.Lock()
        #: ship buffer: staged (txn, est_bytes) awaiting the worker
        self._buf: List[tuple] = []
        self._buf_bytes = 0
        self._buf_since = 0.0
        self._pending_ping: Optional[int] = None
        #: worker is encoding a popped chunk outside the lock — the
        #: stream has an in-flight frame not yet in the outbox
        self._draining = False
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    # ------------------------------------------------------------ staging

    def on_append(self, rec: LogRecord) -> None:
        """Tap for locally-appended records.  Only records originated by
        this DC stream out (remote records are re-broadcast by nobody —
        full-mesh topology, reference inter_dc_query_response returns
        locally-originated txns only)."""
        if rec.op_id.dc != self.dc_id:
            return
        done = self.assembler.process(rec)
        if done is None:
            return
        txid = getattr(done[-1], "txid", None)
        with self._lock:
            txn = InterDcTxn.from_ops(self.dc_id, self.partition,
                                      self.last_sent_opid, done)
            # trace context (ISSUE 7): the origin commit wallclock the
            # remote visibility-lag histograms subtract from, plus the
            # sample rate receivers replay the sampling decision at.
            # Stamped here — the commit record was just appended, so
            # this wall instant IS commit time to within the staging
            # hop this plane already made asynchronous.
            txn.trace_ctx = (time.time_ns() // 1000, _trace_permille())
            self.last_sent_opid = txn.last_opid()
            if not self.enabled:
                return
            if self.ship and termcodec.batch_packable(txn):
                tracer.instant("interdc_ship_stage", "interdc",
                               txid=txid, partition=self.partition,
                               dc=str(self.dc_id))
                self._stage_locked(txn)
                return
            if self.ship:
                # rare unpackable txn (hand-built records): close the
                # open batch ahead of it so the stream stays ordered
                while self._draining:
                    self._cv.wait(0.05)
                self._close_batch_locked()
            # legacy per-txn frame: ORDERED inside the watermark
            # critical section; encoding is deferred to the drain
            # (under _pub_lock) so committers don't serialize on it
            self._outbox.append(("txn", txid, txn, 1, False))
        self._drain_outbox()

    def _stage_locked(self, txn: InterDcTxn) -> None:
        # backpressure: the buffer is bounded; a committer ahead of the
        # worker waits for drain (bounded — see _BACKPRESSURE_TIMEOUT_S)
        cap = self.ship_txns * SHIP_BACKPRESSURE_FACTOR
        deadline = time.monotonic() + _BACKPRESSURE_TIMEOUT_S
        while len(self._buf) >= cap and not self._closed:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                logging.getLogger(__name__).warning(
                    "ship buffer backpressure timed out (%d staged) — "
                    "staging anyway", len(self._buf))
                break
            # lock-ok: deliberate commit-rate throttle — bounded by
            # _BACKPRESSURE_TIMEOUT_S, releases the sender lock while
            # sleeping; the committer's partition lock is the point
            # (back-pressure must reach the commit path to matter)
            self._cv.wait(remaining)
        if not self._buf:
            self._buf_since = time.monotonic()
        self._buf.append((txn, est_txn_bytes(txn)))
        self._buf_bytes += self._buf[-1][1]
        stats.registry.ship_queue_depth.set(
            len(self._buf), dc=str(self.dc_id),
            partition=str(self.partition))
        self._ensure_worker_locked()
        self._cv.notify_all()

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._ship_loop, daemon=True,
                name=f"interdc-ship-{self.dc_id}-p{self.partition}")
            self._worker.start()

    # --------------------------------------------------------- heartbeats

    def ping(self, min_prepared_time: int) -> None:
        """Broadcast a heartbeat carrying this partition's min-prepared
        time (reference ping path src/inter_dc_log_sender_vnode.erl:133-143).

        Unlike txn publishing, pings are NOT gated on ``enabled``: the
        reference's heartbeat timers run unconditionally once started,
        which is what lets two DCs connect *sequentially* with sync
        waits — the second DC's pings must flow before it has observed
        anyone.  Callers only tick this from started heartbeat loops.

        With the ship plane active and txns staged, the ping
        piggybacks on the next batch frame instead of paying its own
        frame (and, published out of band, it would race the staged
        txns' watermarks into a spurious gap repair at every
        receiver); a quiet stream still pays the standalone frame."""
        with self._lock:
            if self.ship and (self._buf or self._draining
                              or self._pending_ping is not None):
                # monotone: a later tick's stamp supersedes
                self._pending_ping = (min_prepared_time
                                      if self._pending_ping is None
                                      else max(self._pending_ping,
                                               min_prepared_time))
                self._cv.notify_all()
                return
            txn = InterDcTxn.ping(self.dc_id, self.partition,
                                  self.last_sent_opid, min_prepared_time)
            self._outbox.append(("ping", None, txn, 0, False))
        self._drain_outbox()

    # ---------------------------------------------------------- ship loop

    def _chunk_locked(self) -> List[InterDcTxn]:
        """Pop the next frame's txns: up to the txn budget, closing
        early once the estimated size passes the byte budget."""
        chunk: List[InterDcTxn] = []
        total = 0
        for txn, est in self._buf:
            if chunk and (len(chunk) >= self.ship_txns
                          or total + est > self.ship_bytes):
                break
            chunk.append(txn)
            total += est
        del self._buf[:len(chunk)]
        self._buf_bytes -= total
        return chunk

    def _ship_loop(self) -> None:
        while True:
            with self._lock:
                while (not self._closed and not self._buf
                       and self._pending_ping is None):
                    self._cv.wait(0.1)
                if self._closed and not self._buf \
                        and self._pending_ping is None:
                    return
                # coalescing window: hold the frame open for more
                # commits until the window expires or a budget fills
                while (not self._closed and self._buf
                       and len(self._buf) < self.ship_txns
                       and self._buf_bytes < self.ship_bytes):
                    remaining = (self.ship_us / 1e6
                                 - (time.monotonic() - self._buf_since))
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                chunk = self._chunk_locked()
                ping, self._pending_ping = self._pending_ping, None
                if self._buf:
                    self._buf_since = time.monotonic()
                stats.registry.ship_queue_depth.set(
                    len(self._buf), dc=str(self.dc_id),
                    partition=str(self.partition))
                self._draining = True
                ping_prev = self.last_sent_opid
            # encode OUTSIDE the lock: a committing thread staging the
            # next txn must not wait out a 64-txn frame encode.  The
            # finally block clears _draining even if encoding throws —
            # a stuck flag would wedge the unpackable-txn barrier and
            # the ping piggyback forever.
            entry = None
            try:
                if chunk:
                    batch = InterDcBatch.from_txns(
                        chunk, ping_ts=ping,
                        trace_hdr=(_trace_permille(),
                                   time.time_ns() // 1000))
                    entry = ("batch", batch, batch.to_bin(), len(chunk),
                             ping is not None)
                elif ping is not None:
                    # drained-under-our-feet race: the stamp still
                    # flows.  The OBJECT rides the outbox (deferred
                    # encode, like the ping() path) so interest slicing
                    # can re-anchor it per class watermark.
                    txn = InterDcTxn.ping(self.dc_id, self.partition,
                                          ping_prev, ping)
                    entry = ("ping", None, txn, 0, False)
            except Exception:  # noqa: BLE001 — the worker must survive
                logging.getLogger(__name__).exception(
                    "ship frame encode failed (%d txns dropped to gap "
                    "repair)", len(chunk))
            finally:
                with self._lock:
                    if entry is not None:
                        self._outbox.append(entry)
                    self._draining = False
                    self._cv.notify_all()
            try:
                self._drain_outbox()
            except Exception:  # noqa: BLE001 — a transport error must
                # not kill the drainer; the receivers' opid watermarks
                # treat the lost frame as loss and gap-repair refetches
                logging.getLogger(__name__).exception(
                    "ship publish failed; receivers will gap-repair")

    # ------------------------------------------------------------ publish

    def _drain_outbox(self) -> None:
        """Publish queued frames FIFO.  Frames enter the outbox in
        watermark order (under ``_lock``); ``_pub_lock`` serializes the
        actual publishes, so per-stream frame order holds even when
        several threads race here (the pre-ISSUE-6 ordering bug)."""
        while True:
            with self._pub_lock:
                with self._lock:
                    if not self._outbox:
                        return
                    kind, meta, frame, ntxns, piggy = self._outbox.popleft()
                # the frame OBJECT (batch rides in meta even when the
                # ship worker pre-encoded; txn/ping entries defer) —
                # interest slicing needs it to cut class subsequences
                obj = meta if kind == "batch" else (
                    frame if not isinstance(frame, bytes) else None)
                if not isinstance(frame, bytes):
                    # deferred encode: entries staged under the
                    # watermark lock carry the object; the bytes are
                    # produced here, still ordered by _pub_lock
                    frame = frame.to_bin()
                # interest routing (ISSUE 18): cut one slice per live
                # interest class, under _pub_lock like the deferred
                # encode (pure compute — never under the transport
                # lock).  Routing off, or a transport that can't route
                # (accepts_interest unset), or no spec'd subscriber:
                # the publish below is bit-for-bit pre-ISSUE-18.
                slice_kw = {}
                if (self.interest_routing and obj is not None
                        and getattr(self.transport, "accepts_interest",
                                    False)):
                    classes = self.transport.interest_classes()
                    if classes:
                        slice_kw = {"slices": self._cut_slices(
                            kind, obj, len(frame), classes)}
                if kind == "batch":
                    # a telemetry-capable transport (accepts_txids,
                    # ISSUE 16) takes the frame's SAMPLED txids along
                    # so the native hub can attribute the frame's
                    # fan-out telemetry back to them (the native_fanout
                    # span in txn_journey trees); every other transport
                    # keeps the plain publish(origin, data) signature —
                    # test stubs and external buses never see the kwarg
                    txids = ()
                    if getattr(self.transport, "accepts_txids", False):
                        txids = tuple(
                            txid for txn in meta.txns()
                            if (txid := getattr(txn.records[-1], "txid",
                                                None)) is not None
                            and tracer.sampled(txid))
                    # the kwarg only exists when the transport opted
                    # in above — plain buses keep publish(origin, data)
                    kw = {"txids": txids} if txids else {}
                    kw.update(slice_kw)
                    with tracer.span("interdc_send_batch", "interdc",
                                     partition=self.partition,
                                     dc=str(self.dc_id), txns=ntxns):
                        # lock-ok: _pub_lock EXISTS to order publishes
                        # — only the async ship worker and close take
                        # it, never the commit path
                        self.transport.publish(self.dc_id, frame, **kw)
                    for txn in meta.txns():
                        txid = getattr(txn.records[-1], "txid", None)
                        tracer.instant("interdc_send", "interdc",
                                       txid=txid,
                                       partition=self.partition,
                                       dc=str(self.dc_id))
                    recorder.record("interdc", "send_batch",
                                    partition=self.partition, txns=ntxns,
                                    bytes=len(frame),
                                    piggyback_ping=piggy)
                elif kind == "txn":
                    with tracer.span("interdc_send", "interdc",
                                     txid=meta, partition=self.partition,
                                     dc=str(self.dc_id)):
                        # lock-ok: publish-ordering lock (see above) —
                        # the legacy per-txn frame path
                        self.transport.publish(self.dc_id, frame,
                                               **slice_kw)
                    recorder.record("interdc", "send", txid=meta,
                                    partition=self.partition)
                else:  # ping
                    with tracer.span("interdc_send_ping", "interdc",
                                     partition=self.partition,
                                     dc=str(self.dc_id)):
                        # lock-ok: publish-ordering lock (see above) —
                        # standalone heartbeat frames
                        self.transport.publish(self.dc_id, frame,
                                               **slice_kw)
                _note_frame(kind, len(frame), ntxns, piggy)

    def _cut_slices(self, kind: str, obj, full_len: int,
                    classes: dict) -> dict:
        """One encoded slice per interest class for the frame about to
        publish: {class_key: bytes | None}, None = the frame carries
        nothing for that class.  A class whose slice would be identical
        to the full frame (every txn matched, chain already aligned) is
        simply ABSENT — the transport's absent-class fallback ships the
        one full staging buffer, so all-match traffic costs zero extra
        copies.  Runs under ``_pub_lock`` (pure compute + encode, like
        the deferred to_bin above — never under the transport lock)."""
        reg = stats.registry
        slices: dict = {}
        built = elided_total = saved = 0
        for ck, spec in classes.items():
            wm = self._class_wm.get(ck)
            if wm is None:
                # first frame this class is seen: its chain starts at
                # this frame's base — earlier history is the receiver's
                # ranged gap-repair's job, not the pub stream's
                wm = (obj.first_prev_opid() if kind == "batch"
                      else obj.prev_log_opid)
            if kind == "batch":
                sliced, new_wm, elided = idc_interest.slice_batch(
                    obj, spec, wm)
            elif kind == "txn":
                sliced, new_wm, elided = idc_interest.slice_txn(
                    obj, spec, wm)
            else:
                sliced, new_wm, elided = idc_interest.slice_ping(
                    obj, spec, wm)
            self._class_wm[ck] = new_wm
            elided_total += elided
            if sliced is None:
                slices[ck] = None
                saved += full_len
                continue
            base = (obj.first_prev_opid() if kind == "batch"
                    else obj.prev_log_opid)
            if elided == 0 and wm == base:
                continue  # identical to the full frame: share it
            data = sliced.to_bin()
            slices[ck] = data
            built += 1
            saved += max(full_len - len(data), 0)
        reg.interest_frames.inc()
        if built:
            reg.interest_slice_buffers.inc(built)
        frames = reg.interest_frames.value()
        if frames:
            reg.interest_slices_per_frame.set(
                reg.interest_slice_buffers.value() / frames)
        if elided_total:
            reg.interest_filtered_txns.inc(elided_total)
        if saved:
            reg.interest_filtered_bytes.inc(saved)
        return slices

    # ----------------------------------------------------------- plumbing

    def _close_batch_locked(self) -> None:
        """Flush the staged buffer into the outbox as one batch frame
        (ordering barrier ahead of a legacy frame; caller holds
        ``_lock`` with ``_draining`` false)."""
        if not self._buf:
            return
        chunks = []
        while self._buf:
            chunks.append(self._chunk_locked())
        ping, self._pending_ping = self._pending_ping, None
        for i, chunk in enumerate(chunks):
            batch = InterDcBatch.from_txns(
                chunk, ping_ts=ping if i == len(chunks) - 1 else None,
                trace_hdr=(_trace_permille(), time.time_ns() // 1000))
            self._outbox.append(("batch", batch, batch,
                                 len(chunk), ping is not None
                                 and i == len(chunks) - 1))
        stats.registry.ship_queue_depth.set(
            0, dc=str(self.dc_id), partition=str(self.partition))

    def seed_watermark(self, opid: int) -> None:
        with self._lock:
            self.last_sent_opid = max(self.last_sent_opid, opid)

    def pending_ship(self) -> int:
        with self._lock:
            return (len(self._buf) + len(self._outbox)
                    + (1 if self._draining else 0))

    def queue_stats(self) -> dict:
        """This stream's ship-buffer state for the pipeline snapshot
        (obs/pipeline.py): staged depth/bytes, oldest-staged age,
        outbox length, and the opid watermark."""
        with self._lock:
            # _buf_since can be 0.0 with txns still staged (flush_ship
            # expires the window that way) — a scrape then must not
            # report process-uptime-sized staged age
            age_us = (int((time.monotonic() - self._buf_since) * 1e6)
                      if self._buf and self._buf_since > 0 else 0)
            return {
                "staged_txns": len(self._buf),
                "staged_bytes": self._buf_bytes,
                "oldest_age_us": max(age_us, 0),
                "outbox_frames": len(self._outbox),
                "draining": self._draining,
                "pending_ping": self._pending_ping is not None,
                "last_sent_opid": self.last_sent_opid,
                "enabled": self.enabled,
            }

    def flush_ship(self, timeout: float = 2.0) -> None:
        """Drain the ship buffer synchronously (tests / shutdown): wake
        the worker and wait until everything staged has published."""
        deadline = time.monotonic() + timeout
        with self._lock:
            self._buf_since = 0.0  # expire the window
            self._ensure_worker_locked()
            self._cv.notify_all()
        while time.monotonic() < deadline:
            with self._lock:
                if not self._buf and not self._outbox \
                        and not self._draining \
                        and self._pending_ping is None:
                    return
                self._buf_since = 0.0
                self._cv.notify_all()
            self._drain_outbox()
            time.sleep(0.001)

    def close(self) -> None:
        """Stop the ship worker, flushing staged txns first (restart
        recovery would re-ship them from the log either way, but a
        clean shutdown should not force every peer through repair)."""
        with self._lock:
            self._closed = True
            self._cv.notify_all()
            worker = self._worker
        if worker is not None and worker.is_alive():
            worker.join(timeout=2.0)
        self._drain_outbox()

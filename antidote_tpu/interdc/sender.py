"""Per-partition inter-DC log sender.

Every local log append streams here (reference src/logging_vnode.erl:422
→ src/inter_dc_log_sender_vnode.erl:119-131); a TxnAssembler groups the
records per txid until the commit record arrives, then the whole txn is
broadcast with the stream's opid watermark.  A periodic heartbeat/ping
carries the partition's min-prepared time so remote GSTs keep advancing
through quiet periods (reference :133-143, ?HEARTBEAT_PERIOD
include/antidote.hrl:55).
"""

from __future__ import annotations

import threading

from antidote_tpu.interdc.transport import Transport
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.records import LogRecord, TxnAssembler


class InterDcLogSender:
    def __init__(self, dc_id, partition: int, transport: Transport,
                 enabled: bool = True):
        self.dc_id = dc_id
        self.partition = partition
        self.transport = transport
        #: publishing gate: off until the DC joins a cluster (reference
        #: start_bg_processes ordering, src/inter_dc_manager.erl:112-145)
        self.enabled = enabled
        self.assembler = TxnAssembler()
        #: opid watermark of the last broadcast record for this stream
        #: (seeded from the recovered log at restart by the manager,
        #: reference {start_timer} handler src/logging_vnode.erl:301-322)
        self.last_sent_opid = 0
        self._lock = threading.Lock()

    def on_append(self, rec: LogRecord) -> None:
        """Tap for locally-appended records.  Only records originated by
        this DC stream out (remote records are re-broadcast by nobody —
        full-mesh topology, reference inter_dc_query_response returns
        locally-originated txns only)."""
        if rec.op_id.dc != self.dc_id:
            return
        done = self.assembler.process(rec)
        if done is None:
            return
        with self._lock:
            txn = InterDcTxn.from_ops(self.dc_id, self.partition,
                                      self.last_sent_opid, done)
            self.last_sent_opid = txn.last_opid()
        if self.enabled:
            # the commit record closes the group, so its txid correlates
            # this broadcast with the coordinator/log/device spans
            txid = getattr(done[-1], "txid", None)
            with tracer.span("interdc_send", "interdc", txid=txid,
                             partition=self.partition,
                             dc=str(self.dc_id)):
                self.transport.publish(self.dc_id, txn.to_bin())
            recorder.record("interdc", "send", txid=txid,
                            partition=self.partition,
                            records=len(done))

    def ping(self, min_prepared_time: int) -> None:
        """Broadcast a heartbeat carrying this partition's min-prepared
        time (reference ping path src/inter_dc_log_sender_vnode.erl:133-143).

        Unlike txn publishing, pings are NOT gated on ``enabled``: the
        reference's heartbeat timers run unconditionally once started,
        which is what lets two DCs connect *sequentially* with sync
        waits — the second DC's pings must flow before it has observed
        anyone.  Callers only tick this from started heartbeat loops."""
        with self._lock:
            txn = InterDcTxn.ping(self.dc_id, self.partition,
                                  self.last_sent_opid, min_prepared_time)
        self.transport.publish(self.dc_id, txn.to_bin())

    def seed_watermark(self, opid: int) -> None:
        with self._lock:
            self.last_sent_opid = max(self.last_sent_opid, opid)

"""Inter-DC transport abstraction + in-process bus.

The reference's transport is ZeroMQ (erlzmq2 C NIF): PUB/SUB for the txn
stream and REQ/ROUTER for log-repair / bounded-counter RPC (reference
src/inter_dc_pub.erl, src/inter_dc_sub.erl, src/inter_dc_query.erl,
src/zmq_utils.erl).  Here the same two channels sit behind a small
interface so simulated multi-DC runs (tests, benchmarks) use an
in-process bus, and real deployments use the native TCP transport
(antidote_tpu/native, task: erlzmq replacement).

The in-process bus also carries the test-side failure injection the
reference gets from its harness: per-link down/up (cookie-partition
analogue, reference test/utils/test_utils.erl:239-256) and message-drop
windows for exercising the gap-repair path.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from antidote_tpu.interdc.wire import DcDescriptor


class LinkDown(Exception):
    """Request channel unavailable (partitioned or unknown DC)."""


class Transport:
    """Both channels of the inter-DC fabric."""

    def publish(self, origin, data: bytes) -> None:
        """Broadcast a txn frame to every connected subscriber (PUB side,
        reference src/inter_dc_pub.erl:87-92)."""
        raise NotImplementedError

    def request(self, origin, target, kind: str, payload) -> Any:
        """Synchronous RPC to ``target``'s query handler (REQ/ROUTER side,
        reference src/inter_dc_query.erl:76-79).  Raises LinkDown when the
        target is unreachable."""
        raise NotImplementedError

    def connect(self, origin, desc: DcDescriptor) -> None:
        """Subscribe ``origin`` to a peer's streams.  The in-process bus
        delivers to every registered DC, so this is a no-op there; the
        TCP transport dials the peer's listeners here."""

    def local_addrs(self):
        """((pub_addr, ...), (logreader_addr, ...)) for this endpoint's
        descriptor, or None when addressing is by registry key (in-proc)."""
        return None

    # ------------------------------------------------- interest routing
    # (ISSUE 18, docs/interest_routing.md).  Transports that cannot
    # route by interest keep these no-ops: every subscriber then gets
    # the full stream, which is always a safe superset.

    def set_local_interest(self, dc_id, spec) -> None:
        """Announce this endpoint's interest spec (None = full stream)
        to publishers — hello payload on TCP, registry entry in-proc."""

    def interest_classes(self) -> Dict:
        """{class_key: InterestSpec} of the distinct specs live
        subscribers announced — the sender cuts one slice per entry.
        Empty dict = nobody filters, stage the full frame only."""
        return {}


class InProcBus(Transport):
    """Registry of DCs in one process.

    Published frames are *enqueued* per subscriber and drained either by
    the subscriber's background delivery thread or by an explicit
    ``pump()`` (deterministic tests) — mirroring the reference's
    asynchronous ZMQ delivery, and avoiding cross-DC lock chains (the
    publisher may hold partition locks while broadcasting, exactly like
    logging_vnode does when it forwards appends).
    """

    #: capability probe for Sender._drain_outbox: this transport can
    #: route per-interest-class slices (ISSUE 18)
    accepts_interest = True

    def __init__(self):
        self._lock = threading.RLock()
        #: dc_id -> (descriptor, inbox queue, query handler)
        self._dcs: Dict[Any, Tuple[DcDescriptor, "queue.Queue[bytes]",
                                   Callable]] = {}
        #: (a, b) unordered pairs that are DOWN
        self._cut: set = set()
        #: dc_ids whose *inbound* pub/sub frames are dropped (message-loss
        #: injection for the gap-repair tests)
        self._drop_rx: set = set()
        #: dc_id -> InterestSpec for subscribers that announced one
        #: (spec-less DCs receive the full stream)
        self._interest: Dict[Any, Any] = {}

    # ------------------------------------------------------------ registry

    def register(self, desc: DcDescriptor,
                 query_handler: Callable[[Any, str, Any], Any]
                 ) -> "queue.Queue[bytes]":
        inbox: "queue.Queue[bytes]" = queue.Queue()
        with self._lock:
            self._dcs[desc.dc_id] = (desc, inbox, query_handler)
        return inbox

    def unregister(self, dc_id) -> None:
        with self._lock:
            self._dcs.pop(dc_id, None)
            self._interest.pop(dc_id, None)

    def set_local_interest(self, dc_id, spec) -> None:
        with self._lock:
            if spec is None:
                self._interest.pop(dc_id, None)
            else:
                self._interest[dc_id] = spec

    def interest_classes(self) -> Dict:
        with self._lock:
            return {s.class_key(): s for s in self._interest.values()}

    def descriptor(self, dc_id) -> DcDescriptor:
        with self._lock:
            if dc_id not in self._dcs:
                raise LinkDown(f"unknown DC {dc_id!r}")
            return self._dcs[dc_id][0]

    def dc_ids(self) -> List[Any]:
        with self._lock:
            return list(self._dcs.keys())

    # ---------------------------------------------------- failure injection

    def set_link(self, a, b, up: bool) -> None:
        """Partition / heal the pair of DCs (both channels)."""
        pair = frozenset((a, b))
        with self._lock:
            if up:
                self._cut.discard(pair)
            else:
                self._cut.add(pair)

    def link_up(self, a, b) -> bool:
        return frozenset((a, b)) not in self._cut

    def set_drop_rx(self, dc_id, drop: bool) -> None:
        """Silently drop pub/sub frames inbound to ``dc_id`` (lost-message
        injection; the request channel stays up so gap repair can run)."""
        with self._lock:
            if drop:
                self._drop_rx.add(dc_id)
            else:
                self._drop_rx.discard(dc_id)

    # ------------------------------------------------------------- channels

    def publish(self, origin, data: bytes, slices=None) -> None:
        with self._lock:
            targets = [(dc_id, inbox) for dc_id, (_d, inbox, _q)
                       in self._dcs.items() if dc_id != origin]
            targets = [(dc_id, inbox, self._interest.get(dc_id))
                       for dc_id, inbox in targets
                       if self.link_up(origin, dc_id)
                       and dc_id not in self._drop_rx]
        for dc_id, inbox, spec in targets:
            payload = data
            if slices is not None and spec is not None:
                # a class the sender didn't cut (spec raced in after
                # the snapshot) falls back to the FULL frame — a safe
                # superset, both chains share the origin opid numbering
                payload = slices.get(spec.class_key(), data)
                if payload is None:
                    continue  # frame elided for this class entirely
            self._deliver_to(dc_id, inbox, payload)

    def _deliver_to(self, dc_id, inbox, payload: bytes) -> None:
        """Single-subscriber delivery hop — a seam the interest bench's
        metering bus overrides to count per-target delivered bytes."""
        inbox.put(payload)

    def request(self, origin, target, kind: str, payload) -> Any:
        with self._lock:
            if not self.link_up(origin, target):
                raise LinkDown(f"link {origin!r}-{target!r} is down")
            if target not in self._dcs:
                raise LinkDown(f"unknown DC {target!r}")
            handler = self._dcs[target][2]
        return handler(origin, kind, payload)


class InboxWorker:
    """Background delivery thread draining one DC's inbox (the reference's
    per-socket ZMQ receive loop, src/inter_dc_sub.erl:89-95)."""

    def __init__(self, inbox: "queue.Queue[bytes]",
                 deliver: Callable[[bytes], None]):
        self.inbox = inbox
        self.deliver = deliver
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                data = self.inbox.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self.deliver(data)
            except Exception:  # noqa: BLE001 — the delivery worker is
                # the DC's only inbound path; one bad frame or handler
                # bug must not halt all replication (pump() stays
                # unguarded so deterministic tests surface errors)
                import logging

                logging.getLogger(__name__).exception(
                    "inbound frame delivery failed")

    def pump(self, max_frames: int = 100000) -> int:
        """Drain synchronously (deterministic mode); returns frames handled."""
        n = 0
        while n < max_frames:
            try:
                data = self.inbox.get_nowait()
            except queue.Empty:
                break
            self.deliver(data)
            n += 1
        return n

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

"""Gap detection / message-loss repair for one (origin DC, partition)
inbound stream.

Mirrors inter_dc_sub_buf (reference src/inter_dc_sub_buf.erl): compare
the incoming txn's ``prev_log_opid`` with the last opid this replica has
observed for the stream —

- equal   → deliver, advance the watermark,
- smaller → duplicate, drop,
- larger  → messages were lost: enter ``buffering``, queue the txn, and
  ask the origin DC's log reader for the missing opid range
  (src/inter_dc_sub_buf.erl:112-142, query :155-158).

On first contact the watermark is seeded from the local durable log so a
restarted replica resumes where it crashed (src/inter_dc_sub_buf.erl:58-76).

ISSUE 6 adds batch frames: the ship plane's coalesced frame carries a
contiguous opid span of txns, so :meth:`SubBuf.process_batch` applies
the same tri-state per txn but hands every deliverable txn of a frame
downstream as ONE batch (``deliver_batch``) — the dependency gate
appends the whole arrival in one ring scatter and admits it with one
fixpoint instead of per-txn passes.  Duplicate prefixes inside a
re-sent batch drop txn-by-txn; a gap anywhere buffers the remainder
and triggers the same repair fetch as the per-txn path.

ISSUE 10 adds the retention-aware escalation: when the origin has
TRUNCATED its log below the requested repair range, the fetch answers
the explicit BELOW_FLOOR marker instead of a txn list.  A SubBuf with
a ``bootstrap`` callback then re-seeds from the origin's checkpoint —
the callback installs the origin's per-key seed states + clocks into
the local partition and returns the origin's commit watermark at its
cut; the stream watermark jumps there and ordinary repair fetches the
retained suffix.  Without the callback (or while the origin is
unreachable) the stream stays ``buffering`` and retries on the next
frame — behind, but never wedged on an answer that cannot come.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, List, Optional

from antidote_tpu import stats
from antidote_tpu.interdc import query as idc_query
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer


def _note_admit(txn: InterDcTxn) -> None:
    """Per-txn SubBuf-admission instant (ISSUE 7): the stage between
    wire arrival (``interdc_rx``) and gate delivery
    (``interdc_deliver``) in a sampled txn's journey — the hop where
    gap-repair delay, if any, was paid."""
    if not txn.is_ping():
        tracer.instant("subbuf_admit", "interdc",
                       txid=getattr(txn.records[-1], "txid", None),
                       origin=str(txn.dc_id), partition=txn.partition)


class SubBuf:
    def __init__(self, origin_dc, partition: int,
                 deliver: Callable[[InterDcTxn], None],
                 fetch_range: Callable[[Any, int, int, int],
                                       Optional[List[InterDcTxn]]],
                 last_opid: int = 0,
                 deliver_batch: Optional[
                     Callable[[List[InterDcTxn]], None]] = None,
                 bootstrap: Optional[Callable[[Any, int],
                                              Optional[int]]] = None,
                 filtered: bool = False):
        self.origin_dc = origin_dc
        self.partition = partition
        #: the local DC subscribed with an interest spec (ISSUE 18):
        #: the stream is an interest-class subsequence and repair
        #: fetches carry the ranges (via the DC's fetch_range /
        #: bootstrap closures) — counted as backfills.  No delivery-
        #: logic change: a filtered repair answer is covered by the
        #: authoritative-advance rule below exactly like an aborted-txn
        #: hole (docs/interest_routing.md §3).
        self.filtered = filtered
        #: hand one txn to the dependency gate
        self._deliver = deliver
        #: hand a whole in-order arrival batch to the dependency gate
        #: (one gate pass); falls back to per-txn delivery when unset
        self._deliver_batch = deliver_batch
        #: fetch_range(origin_dc, partition, first, last) -> [InterDcTxn]
        #: or None when the origin is unreachable (repair retried on the
        #: next incoming frame), or a BELOW_FLOOR marker (ISSUE 10)
        self._fetch_range = fetch_range
        #: bootstrap(origin_dc, partition) -> new watermark opid or
        #: None — the BELOW_FLOOR escalation: install the origin's
        #: checkpoint seed states locally and return its commit
        #: watermark at the cut (wired by the DC layer; None = no
        #: escalation available, stay buffering)
        self._bootstrap = bootstrap
        self.last_opid = last_opid
        self.state = "normal"  # | "buffering"
        self._queue: deque = deque()

    def gap_stats(self) -> dict:
        """This stream's gap/repair state for the pipeline snapshot
        (obs/pipeline.py)."""
        return {"state": self.state, "buffered_txns": len(self._queue),
                "last_opid": self.last_opid, "filtered": self.filtered}

    def process(self, txn: InterDcTxn) -> None:
        if self.state == "buffering":
            self._queue.append(txn)
            self._try_repair()
            return
        self._handle(txn)

    def process_batch(self, txns: List[InterDcTxn]) -> None:
        """One batch frame's txns (in stream order, opid-contiguous,
        optionally ending in the piggybacked ping).  Semantically
        identical to processing each txn through :meth:`process`; the
        only difference is that consecutive deliverable txns reach the
        gate as one arrival batch."""
        if self.state == "buffering":
            self._queue.extend(txns)
            self._try_repair()
            return
        fresh: List[InterDcTxn] = []
        for i, txn in enumerate(txns):
            if txn.prev_log_opid == self.last_opid:
                fresh.append(txn)
                self.last_opid = txn.last_opid()
            elif txn.prev_log_opid < self.last_opid:
                continue  # duplicate / already covered
            else:
                # gap: flush what is deliverable, buffer the remainder
                self._flush_batch(fresh)
                self._note_gap(txn)
                self._queue.extend(txns[i:])
                self.state = "buffering"
                self._try_repair()
                return
        self._flush_batch(fresh)

    def _flush_batch(self, txns: List[InterDcTxn]) -> None:
        if not txns:
            return
        for txn in txns:
            _note_admit(txn)
        if self._deliver_batch is not None:
            self._deliver_batch(txns)
        else:
            for txn in txns:
                self._deliver(txn)

    def _handle(self, txn: InterDcTxn) -> None:
        if txn.prev_log_opid == self.last_opid:
            _note_admit(txn)
            self._deliver(txn)
            self.last_opid = txn.last_opid()
        elif txn.prev_log_opid < self.last_opid:
            # duplicate / already covered (e.g. replayed after restart)
            return
        else:
            self._note_gap(txn)
            self._queue.append(txn)
            self.state = "buffering"
            self._try_repair()

    def _note_gap(self, txn: InterDcTxn) -> None:
        """Gap detection: the stream lost frames and the txns behind
        the hole now wait on a repair fetch — the journey stage that
        explains a visibility-lag outlier.  Gaps are rare and
        diagnostic by nature, so the flight-recorder event is
        UNCONDITIONAL (untagged tracer instants are thinned ~19/20 at
        the default sample rate — exactly wrong for the record an
        operator chases a lag outlier with); the timeline instant
        rides the sampler as usual."""
        recorder.record("interdc", "subbuf_gap",
                        origin=str(self.origin_dc),
                        partition=self.partition,
                        expected=self.last_opid, got=txn.prev_log_opid)
        tracer.instant("subbuf_gap", "interdc", origin=str(self.origin_dc),
                       partition=self.partition,
                       expected=self.last_opid, got=txn.prev_log_opid)

    def _try_repair(self) -> None:
        """Fetch (last_opid, first_queued.prev_log_opid] from the origin
        and drain; stays in buffering if the origin is unreachable."""
        while self._queue:
            head = self._queue[0]
            if head.prev_log_opid <= self.last_opid:
                txn = self._queue.popleft()
                if txn.prev_log_opid == self.last_opid:
                    _note_admit(txn)
                    self._deliver(txn)
                    self.last_opid = txn.last_opid()
                # else: duplicate, drop
                continue
            t0 = time.perf_counter()
            if self.filtered:
                # interest-routed stream: this fetch carries the local
                # ranges — the widen-backfill path rides it (ISSUE 18)
                stats.registry.interest_backfills.inc()
            with tracer.span("subbuf_gap_repair", "interdc",
                             origin=str(self.origin_dc),
                             partition=self.partition,
                             first=self.last_opid + 1,
                             last=head.prev_log_opid):
                missing = self._fetch_range(
                    self.origin_dc, self.partition,
                    self.last_opid + 1, head.prev_log_opid)
            # unconditional, like _note_gap: the repair record must
            # survive the sampler for the outlier hunt it exists for
            recorder.record("interdc", "subbuf_repair",
                            origin=str(self.origin_dc),
                            partition=self.partition,
                            first=self.last_opid + 1,
                            last=head.prev_log_opid,
                            fetched=len(missing or ()),
                            reachable=missing is not None,
                            dur_s=round(time.perf_counter() - t0, 6))
            if missing is None:
                return  # origin unreachable; retry on next frame
            if idc_query.is_below_floor(missing):
                # the origin truncated its log below the requested
                # range (ISSUE 10): no repair answer can ever come —
                # escalate to a checkpoint-state bootstrap (seed state
                # + suffix) instead of wedging in repair retries
                recorder.record("interdc", "subbuf_below_floor",
                                origin=str(self.origin_dc),
                                partition=self.partition,
                                first=self.last_opid + 1,
                                floor=missing[1])
                if self._bootstrap is None:
                    return  # no escalation wired: stay buffering
                with tracer.span("subbuf_bootstrap", "interdc",
                                 origin=str(self.origin_dc),
                                 partition=self.partition,
                                 floor=missing[1]):
                    new_wm = self._bootstrap(self.origin_dc,
                                             self.partition)
                recorder.record("interdc", "subbuf_bootstrap",
                                origin=str(self.origin_dc),
                                partition=self.partition,
                                watermark=new_wm,
                                ok=new_wm is not None)
                if new_wm is None or int(new_wm) <= self.last_opid:
                    # unreachable, no checkpoint, or no progress (the
                    # origin's cut is not past our watermark yet) —
                    # retry on the next frame rather than spin
                    return
                stats.registry.ckpt_bootstraps.inc()
                self.last_opid = int(new_wm)
                continue  # drain the queue / repair above the cut
            for txn in sorted(missing, key=lambda t: t.last_opid()):
                if txn.last_opid() > self.last_opid:
                    _note_admit(txn)
                    self._deliver(txn)
                    self.last_opid = txn.last_opid()
            # A successful answer authoritatively covers the requested
            # range: opids in it that came back are applied above, and
            # ones that didn't belong to aborted/uncommitted records that
            # will never be broadcast — so the watermark advances to the
            # head's prev even if nothing (or not everything) came back.
            self.last_opid = max(self.last_opid, head.prev_log_opid)
        self.state = "normal"

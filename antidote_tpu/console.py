"""Operator console — the antidote_console / antidote-admin analogue
(reference src/antidote_console.erl:31-60, rel/files/antidote-admin).

Talks to a running node over the wire protocol (pb/server.py), so it
works against any live DC without touching its process:

    python -m antidote_tpu.console [--host H] [--port P] COMMAND

Commands:
    status                  node/DC status (partitions, clocks, flags)
    ring                    partition map summary
    descriptor [FILE]       print (or save) this DC's connection descriptor
    connect FILE [FILE...]  connect this DC to peers by descriptor file
    create-dc [NODE...]     form the DC (single-node; see api.create_dc)
    flag get NAME           read a runtime flag
    flag set NAME VALUE     set a runtime flag (bool/int/str inferred)
"""

from __future__ import annotations

import argparse
import json
import sys

# the console only speaks TCP — it must come up instantly and never
# touch (or wait on) an accelerator backend, so pin jax to CPU before
# the package import pulls it in
import jax

jax.config.update("jax_platforms", "cpu")

from antidote_tpu.pb.client import PbClient, PbError  # noqa: E402
from antidote_tpu.pb import codec  # noqa: E402


def _parse_value(raw: str):
    low = raw.lower()
    if low in ("true", "on", "1"):
        return True
    if low in ("false", "off", "0"):
        return False
    try:
        return int(raw)
    except ValueError:
        return raw


def _jsonable(term):
    if isinstance(term, dict):
        return {str(k): _jsonable(v) for k, v in term.items()}
    if isinstance(term, (list, tuple)):
        return [_jsonable(v) for v in term]
    if isinstance(term, bytes):
        return term.decode(errors="replace")
    return term


def cmd_status(cl: PbClient, args) -> int:
    print(json.dumps(_jsonable(cl.admin_status()), indent=2, sort_keys=True))
    return 0


def cmd_ring(cl: PbClient, args) -> int:
    st = cl.admin_status()
    print(f"dc {st['dc_id']}: {st['n_partitions']} partitions")
    for p in st["partitions"]:
        dev = ", ".join(f"{t}={n}" for t, n in
                        sorted(dict(p["device_keys"]).items()) if n)
        print(f"  p{p['partition']}: host_keys={p['host_keys']}"
              f" prepared={p['prepared_txns']}"
              + (f" device[{dev}]" if dev else ""))
    return 0


def cmd_descriptor(cl: PbClient, args) -> int:
    desc = cl.get_connection_descriptor()
    blob = codec.descriptor_to_bytes(desc)
    if args.file:
        with open(args.file, "wb") as f:
            f.write(blob)
        print(f"descriptor for {desc.dc_id} written to {args.file}")
    else:
        sys.stdout.buffer.write(blob)
    return 0


def cmd_connect(cl: PbClient, args) -> int:
    descs = []
    for path in args.files:
        with open(path, "rb") as f:
            descs.append(codec.descriptor_from_bytes(f.read()))
    cl.connect_to_dcs(descs)
    print(f"connected to {[d.dc_id for d in descs]}")
    return 0


def cmd_create_dc(cl: PbClient, args) -> int:
    cl.create_dc(args.nodes or None)
    print("dc formed")
    return 0


def cmd_flag(cl: PbClient, args) -> int:
    if args.action == "get":
        print(json.dumps({args.name: cl.get_flag(args.name)}))
    else:
        value = cl.set_flag(args.name, _parse_value(args.value))
        print(json.dumps({args.name: value}))
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="antidote_tpu.console",
                                 description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8087)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status").set_defaults(fn=cmd_status)
    sub.add_parser("ring").set_defaults(fn=cmd_ring)
    d = sub.add_parser("descriptor")
    d.add_argument("file", nargs="?")
    d.set_defaults(fn=cmd_descriptor)
    c = sub.add_parser("connect")
    c.add_argument("files", nargs="+")
    c.set_defaults(fn=cmd_connect)
    cd = sub.add_parser("create-dc")
    cd.add_argument("nodes", nargs="*")
    cd.set_defaults(fn=cmd_create_dc)
    f = sub.add_parser("flag")
    f.add_argument("action", choices=("get", "set"))
    f.add_argument("name")
    f.add_argument("value", nargs="?")
    f.set_defaults(fn=cmd_flag)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "action", None) == "set" and args.value is None:
        print("flag set requires a VALUE", file=sys.stderr)
        return 2
    try:
        with PbClient(host=args.host, port=args.port) as cl:
            return args.fn(cl, args)
    except PbError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"cannot reach {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""Process-level runtime tuning for nodes that SERVE (the reference
ships BEAM flags for the same purpose: +C no_time_warp, scheduler
settings — reference config/vm.args:26-34).

Two CPython knobs dominate a serving process's tail and throughput:

- the CYCLIC GC: with the default (700, 10, 10) thresholds every ~700
  container allocations trigger a young-gen pass and, regularly, full
  sweeps of the whole live heap — which for a database node is large
  (materializer caches, device plane directories, logs).  Measured on
  the config6 update mix: 1243 -> 2707 txn/s from gc.freeze() +
  raised thresholds alone.  freeze() moves the already-built object
  graph out of every future scan; the raised thresholds keep young-gen
  passes off the per-transaction path.  The GC stays ENABLED: real
  cycles in new garbage still collect, just in much larger batches.

- the GIL switch interval: a serving thread woken by the fabric waits
  up to a full interval for a busy peer thread to yield; 5 ms default
  puts a multi-ms floor under every cross-thread handoff.
"""

from __future__ import annotations

import gc
import sys

_tuned = False


def tune_runtime(switch_interval_s: float = 0.0005,
                 gc_thresholds=(50000, 50, 50)) -> None:
    """Idempotent per-process tuning; call when this process's main
    duty is serving a node (NodeServer does this automatically)."""
    global _tuned
    if _tuned:
        return
    _tuned = True
    sys.setswitchinterval(switch_interval_s)
    gc.freeze()
    gc.set_threshold(*gc_thresholds)


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check_vma=None):
    """``shard_map`` across jax versions: top-level ``jax.shard_map``
    (newer releases) vs ``jax.experimental.shard_map.shard_map``
    (<= 0.4.x), whose replication-check kwarg is ``check_rep`` where
    the new API says ``check_vma``.  Every collective build site goes
    through this resolver — an AttributeError here used to take the
    whole sharded plane (and its tier-1 tests) down on 0.4.x."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

    if check_vma is not None:
        # the replication-check kwarg was renamed across versions
        # (check_rep -> check_vma); the flag is semantic — call sites
        # disable a check their programs would fail — so try BOTH
        # spellings before ever dropping it
        for kw in ({"check_vma": check_vma}, {"check_rep": check_vma}):
            try:
                return sm(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)
            except TypeError:
                continue
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


#: process-wide serialization of XLA programs containing COLLECTIVES:
#: JAX's single-controller model does not support concurrent collective
#: programs over the same devices — two threads interleaving their
#: pmin/psum programs abort inside the XLA runtime (caught by the
#: causal-checker stress loops via the device stable fold).  Every
#: collective launch site takes this lock; real deployments run one
#: node per host process, so it is uncontended there.
import threading as _threading

COLLECTIVE_LOCK = _threading.Lock()

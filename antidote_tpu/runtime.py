"""Process-level runtime tuning for nodes that SERVE (the reference
ships BEAM flags for the same purpose: +C no_time_warp, scheduler
settings — reference config/vm.args:26-34).

Two CPython knobs dominate a serving process's tail and throughput:

- the CYCLIC GC: with the default (700, 10, 10) thresholds every ~700
  container allocations trigger a young-gen pass and, regularly, full
  sweeps of the whole live heap — which for a database node is large
  (materializer caches, device plane directories, logs).  Measured on
  the config6 update mix: 1243 -> 2707 txn/s from gc.freeze() +
  raised thresholds alone.  freeze() moves the already-built object
  graph out of every future scan; the raised thresholds keep young-gen
  passes off the per-transaction path.  The GC stays ENABLED: real
  cycles in new garbage still collect, just in much larger batches.

- the GIL switch interval: a serving thread woken by the fabric waits
  up to a full interval for a busy peer thread to yield; 5 ms default
  puts a multi-ms floor under every cross-thread handoff.
"""

from __future__ import annotations

import gc
import sys

_tuned = False


def tune_runtime(switch_interval_s: float = 0.0005,
                 gc_thresholds=(50000, 50, 50)) -> None:
    """Idempotent per-process tuning; call when this process's main
    duty is serving a node (NodeServer does this automatically)."""
    global _tuned
    if _tuned:
        return
    _tuned = True
    sys.setswitchinterval(switch_interval_s)
    gc.freeze()
    gc.set_threshold(*gc_thresholds)


#: process-wide serialization of XLA programs containing COLLECTIVES:
#: JAX's single-controller model does not support concurrent collective
#: programs over the same devices — two threads interleaving their
#: pmin/psum programs abort inside the XLA runtime (caught by the
#: causal-checker stress loops via the device stable fold).  Every
#: collective launch site takes this lock; real deployments run one
#: node per host process, so it is uncontended there.
import threading as _threading

COLLECTIVE_LOCK = _threading.Lock()

"""Op-based CRDT behaviour — the contract of the reference's antidote_crdt dep.

Every type implements the same six entry points the reference calls
(behaviour contract; call sites: reference src/materializer.erl:46-58,
src/clocksi_downstream.erl:43-67, src/antidote.erl:183-186, src/cure.erl:186-192):

- ``new()``                      -> empty state
- ``value(state)``               -> client-facing value
- ``downstream(op, state, ctx)`` -> effect (reads state at the origin replica)
- ``update(effect, state)``      -> state (pure effect application)
- ``require_state_downstream(op)`` -> bool
- ``is_operation(op)``           -> bool

The downstream/update split is what makes the store op-based: *downstream*
runs once at the origin inside the transaction; the produced *effect* is
what gets logged, replicated, and applied everywhere.  Effects of
concurrent operations must commute, and AntidoteDB delivers effects in
causal order — both invariants are property-tested in
tests/unit/test_crdt_convergence.py.

Unlike the reference (which pulls unique tokens from Erlang's RNG inside
downstream), token generation is injected via :class:`DownstreamCtx` so
the TPU data plane can use dense deterministic dots ``(dc_index, seq)``
and tests are reproducible.

States are immutable from the caller's perspective: ``update`` returns a
fresh state and never mutates its input (materializer snapshots alias
states across cache entries).

Ops are plain tuples ``(op_name, arg)`` mirroring the reference client
surface (reference test/singledc/pb_client_SUITE.erl:174-483):
``("increment", 1)``, ``("add_all", [b"x", b"y"])``, ``("assign", v)``,
``("update", ((key, type_name), nested_op))``, ``("enable", ())`` ...

Values of unlike Python types may legitimately coexist in one CRDT (two
clients write an int and a bytes); readers sort with :func:`sort_key`
so reads never crash on heterogeneous data.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Tuple

Op = Tuple[str, Any]
Effect = Any


class DownstreamCtx:
    """Source of unique dots/tokens for downstream generation.

    A dot is ``(actor, seq)`` with ``actor`` hashable.  The live
    transaction path injects ``mint`` = the node's dot minter, which
    uses the DC id as the actor and a node-monotone µs sequence — the
    shape the device data plane's dense ``(dc_column, seq)`` dot tables
    require (antidote_tpu/mat/device_plane.py).  Standalone contexts
    (unit tests, tools) fall back to a private actor + local counter.
    """

    def __init__(self, actor: Any = None, seq: int = 0,
                 mint: "Callable[[], Tuple[Any, int]] | None" = None):
        self.actor = actor if actor is not None else os.urandom(8).hex()
        self._seq = int(seq)
        self._mint = mint

    def dot(self) -> Tuple[Any, int]:
        if self._mint is not None:
            return self._mint()
        self._seq += 1
        return (self.actor, self._seq)

    @property
    def seq(self) -> int:
        return self._seq


class DownstreamError(Exception):
    """Raised when downstream generation fails (e.g. bounded counter over
    its bound — the reference returns {error, no_permissions},
    src/bcounter_mgr.erl:116-125)."""


class CRDT:
    """Base class; concrete types override the class methods."""

    name: str = "crdt"

    @classmethod
    def new(cls):
        raise NotImplementedError

    @classmethod
    def value(cls, state):
        raise NotImplementedError

    @classmethod
    def downstream(cls, op: Op, state, ctx: DownstreamCtx | None = None) -> Effect:
        raise NotImplementedError

    @classmethod
    def update(cls, effect: Effect, state):
        raise NotImplementedError

    @classmethod
    def require_state_downstream(cls, op: Op) -> bool:
        return True

    @classmethod
    def is_operation(cls, op: Op) -> bool:
        try:
            name, _ = op
        except (TypeError, ValueError):
            return False
        return name in cls.operations()

    @classmethod
    def operations(cls) -> frozenset:
        return frozenset()

    @classmethod
    def gen_downstream(cls, op: Op, state, ctx: DownstreamCtx | None = None) -> Effect:
        """Validating downstream entry point for the transaction layer
        (the equivalent of the reference's clocksi_downstream wrapper,
        src/clocksi_downstream.erl:41-68): unknown ops and malformed args
        surface uniformly as DownstreamError instead of raw TypeError/
        ValueError escaping to the coordinator."""
        if not cls.is_operation(op):
            raise DownstreamError(f"bad {cls.name} op {op!r}")
        try:
            return cls.downstream(op, state, ctx)
        except DownstreamError:
            raise
        except (TypeError, ValueError, KeyError, IndexError) as e:
            raise DownstreamError(f"malformed {cls.name} op {op!r}: {e}") from e


def sort_key(v) -> Tuple[str, str]:
    """Total order over arbitrary values for deterministic reads of
    heterogeneous sets/registers (type name first, then repr)."""
    return (type(v).__name__, repr(v))


def sorted_values(vals) -> list:
    """Natural sort when values are comparable, :func:`sort_key` fallback
    otherwise — reads must stay deterministic and never crash just because
    clients wrote values of unlike types to one object."""
    vals = list(vals)
    try:
        return sorted(vals)
    except TypeError:
        return sorted(vals, key=sort_key)


_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator: expose a type under its short name and the
    reference-compatible ``antidote_crdt_*`` alias."""
    _REGISTRY[cls.name] = cls
    _REGISTRY["antidote_crdt_" + cls.name] = cls
    return cls


def get_type(name) -> type:
    """Resolve a type name (or pass a type class through)."""
    if isinstance(name, type) and issubclass(name, CRDT):
        return name
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown CRDT type: {name!r}") from None


def is_type(name) -> bool:
    if isinstance(name, type):
        return issubclass(name, CRDT)
    return name in _REGISTRY


def all_types() -> Dict[str, type]:
    """Short-name -> class for every registered type."""
    return {n: c for n, c in _REGISTRY.items() if not n.startswith("antidote_crdt_")}

"""CRDT type system — op-based types matching the reference's antidote_crdt
behaviour (downstream/update split).  Importing this package registers all
thirteen types:

counters: counter_pn, counter_fat, counter_b
registers: register_lww, register_mv
sets: set_go, set_aw, set_rw
flags: flag_ew, flag_dw
maps: map_go, map_rr
sequences: rga
"""

from antidote_tpu.crdt.base import (  # noqa: F401
    CRDT,
    DownstreamCtx,
    DownstreamError,
    all_types,
    get_type,
    is_type,
)
from antidote_tpu.crdt.counters import CounterB, CounterFat, CounterPN  # noqa: F401
from antidote_tpu.crdt.registers import RegisterLWW, RegisterMV  # noqa: F401
from antidote_tpu.crdt.sets import SetAW, SetGO, SetRW  # noqa: F401
from antidote_tpu.crdt.flags import FlagDW, FlagEW  # noqa: F401
from antidote_tpu.crdt.maps import MapGO, MapRR  # noqa: F401
from antidote_tpu.crdt.rga import RGA  # noqa: F401

"""Set CRDTs: grow-only, add-wins (OR-set), remove-wins.

Reference types: antidote_crdt_set_go / _aw / _rw (exercised at
reference test/singledc/pb_client_SUITE.erl:193, 331-334, 360, 413-414).
"""

from __future__ import annotations

from antidote_tpu.crdt.base import (
    CRDT,
    DownstreamCtx,
    DownstreamError,
    register,
    sorted_values,
)


def _elems(name: str, arg):
    """Normalize add/remove vs add_all/remove_all to a list of elements."""
    return list(arg) if name.endswith("_all") else [arg]


@register
class SetGO(CRDT):
    """Grow-only set. State: frozenset. Effect: tuple of elements."""

    name = "set_go"

    @classmethod
    def new(cls):
        return frozenset()

    @classmethod
    def value(cls, state):
        return sorted_values(state)

    @classmethod
    def downstream(cls, op, state, ctx=None):
        name, arg = op
        if name not in ("add", "add_all"):
            raise DownstreamError(f"bad set_go op {op!r}")
        return tuple(_elems(name, arg))

    @classmethod
    def update(cls, effect, state):
        return state | frozenset(effect)

    @classmethod
    def require_state_downstream(cls, op):
        return False

    @classmethod
    def operations(cls):
        return frozenset({"add", "add_all"})


@register
class SetAW(CRDT):
    """Add-wins observed-remove set — the benchmark-headline type.

    State: dict element -> frozenset of dots.  An add mints a dot and
    lists the dots it observed for that element (they get superseded); a
    remove lists observed dots (they get dropped).  An element is present
    iff it has a live dot, so a remove only cancels adds it has seen —
    concurrent adds win.  Causal delivery makes plain dot-removal safe
    (no tombstones needed), exactly as in the reference library.

    The batched device form lives in antidote_tpu/mat/kernels.py (hashed
    dot-slot table, vmapped over keys).
    """

    name = "set_aw"

    @classmethod
    def new(cls):
        return {}

    @classmethod
    def value(cls, state):
        return sorted_values(state.keys())

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        name, arg = op
        if name in ("add", "add_all"):
            return (
                "add",
                tuple(
                    (e, ctx.dot(), tuple(state.get(e, ())))
                    for e in _elems(name, arg)
                ),
            )
        if name in ("remove", "remove_all"):
            return (
                "rmv",
                tuple((e, tuple(state.get(e, ()))) for e in _elems(name, arg)),
            )
        if name == "reset":
            return ("rmv", tuple((e, tuple(dots)) for e, dots in state.items()))
        raise DownstreamError(f"bad set_aw op {op!r}")

    @classmethod
    def update(cls, effect, state):
        kind, entries = effect
        out = dict(state)
        if kind == "add":
            for e, dot, observed in entries:
                dots = (out.get(e, frozenset()) - frozenset(observed)) | {dot}
                out[e] = frozenset(dots)
            return out
        if kind == "rmv":
            for e, observed in entries:
                dots = out.get(e, frozenset()) - frozenset(observed)
                if dots:
                    out[e] = dots
                else:
                    out.pop(e, None)
            return out
        raise DownstreamError(f"bad set_aw effect {effect!r}")

    @classmethod
    def operations(cls):
        return frozenset({"add", "add_all", "remove", "remove_all", "reset"})


@register
class SetRW(CRDT):
    """Remove-wins set: on concurrent add/remove of the same element the
    remove prevails.

    State: dict element -> (add_dots, remove_dots) frozensets.  An add
    mints an add-dot and cancels the remove-dots it observed; a remove
    mints a remove-dot and cancels the add-dots it observed.  Present iff
    add_dots nonempty and remove_dots empty: a concurrent remove's dot is
    not observed by the add, so it survives and suppresses the element.
    """

    name = "set_rw"

    @classmethod
    def new(cls):
        return {}

    @classmethod
    def value(cls, state):
        return sorted_values(
            e for e, (adds, rmvs) in state.items() if adds and not rmvs
        )

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        name, arg = op
        if name in ("add", "add_all"):
            return (
                "add",
                tuple(
                    (e, ctx.dot(), tuple(state.get(e, ((), ()))[1]))
                    for e in _elems(name, arg)
                ),
            )
        if name in ("remove", "remove_all"):
            return (
                "rmv",
                tuple(
                    (e, ctx.dot(), tuple(state.get(e, ((), ()))[0]))
                    for e in _elems(name, arg)
                ),
            )
        if name == "reset":
            # cancel every observed dot on both sides; nothing is minted
            return (
                "reset",
                tuple(
                    (e, tuple(adds), tuple(rmvs))
                    for e, (adds, rmvs) in state.items()
                ),
            )
        raise DownstreamError(f"bad set_rw op {op!r}")

    @classmethod
    def update(cls, effect, state):
        kind = effect[0]
        out = dict(state)
        if kind == "add":
            for e, dot, obs_rmvs in effect[1]:
                adds, rmvs = out.get(e, (frozenset(), frozenset()))
                out[e] = (
                    frozenset(adds) | {dot},
                    frozenset(rmvs) - frozenset(obs_rmvs),
                )
            return out
        if kind == "rmv":
            for e, dot, obs_adds in effect[1]:
                adds, rmvs = out.get(e, (frozenset(), frozenset()))
                out[e] = (
                    frozenset(adds) - frozenset(obs_adds),
                    frozenset(rmvs) | {dot},
                )
            return out
        if kind == "reset":
            for e, obs_adds, obs_rmvs in effect[1]:
                adds, rmvs = out.get(e, (frozenset(), frozenset()))
                adds = frozenset(adds) - frozenset(obs_adds)
                rmvs = frozenset(rmvs) - frozenset(obs_rmvs)
                if adds or rmvs:
                    out[e] = (adds, rmvs)
                else:
                    out.pop(e, None)
            return out
        raise DownstreamError(f"bad set_rw effect {effect!r}")

    @classmethod
    def operations(cls):
        return frozenset({"add", "add_all", "remove", "remove_all", "reset"})

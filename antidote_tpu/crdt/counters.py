"""Counter CRDTs: PN-counter, fat (resettable) counter, bounded counter.

Reference types: antidote_crdt_counter_pn / _fat / _b (exercised at
reference test/singledc/pb_client_SUITE.erl:174,415 and
src/bcounter_mgr.erl:60).
"""

from __future__ import annotations

from antidote_tpu.crdt.base import (
    CRDT,
    DownstreamCtx,
    DownstreamError,
    register,
)


@register
class CounterPN(CRDT):
    """Op-based PN-counter. State: int. Effect: signed int delta.

    The hot-path type: its batched device form is a masked segment-sum
    (antidote_tpu/mat/kernels.py).
    """

    name = "counter_pn"

    @classmethod
    def new(cls):
        return 0

    @classmethod
    def value(cls, state):
        return state

    @classmethod
    def downstream(cls, op, state, ctx=None):
        name, arg = op
        n = 1 if arg in ((), None) else int(arg)
        if name == "increment":
            return n
        if name == "decrement":
            return -n
        raise DownstreamError(f"bad counter_pn op {op!r}")

    @classmethod
    def update(cls, effect, state):
        return state + int(effect)

    @classmethod
    def require_state_downstream(cls, op):
        return False

    @classmethod
    def operations(cls):
        return frozenset({"increment", "decrement"})


@register
class CounterFat(CRDT):
    """Resettable ("fat") counter.

    State: dict dot -> signed delta.  Value: sum of deltas.
    increment/decrement mint a fresh dot; reset removes all *observed*
    dots, so concurrent increments survive a reset (causal delivery makes
    plain removal safe — an unobserved dot's effect is delivered after and
    re-adds nothing that reset saw).
    """

    name = "counter_fat"

    @classmethod
    def new(cls):
        return {}

    @classmethod
    def value(cls, state):
        return sum(state.values())

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        name, arg = op
        if name in ("increment", "decrement"):
            n = 1 if arg in ((), None) else int(arg)
            delta = n if name == "increment" else -n
            return ("dot", ctx.dot(), delta)
        if name == "reset":
            return ("reset", tuple(state.keys()))
        raise DownstreamError(f"bad counter_fat op {op!r}")

    @classmethod
    def update(cls, effect, state):
        kind = effect[0]
        if kind == "dot":
            _, dot, delta = effect
            out = dict(state)
            out[dot] = out.get(dot, 0) + delta
            return out
        if kind == "reset":
            _, observed = effect
            obs = set(observed)
            return {d: v for d, v in state.items() if d not in obs}
        raise DownstreamError(f"bad counter_fat effect {effect!r}")

    @classmethod
    def require_state_downstream(cls, op):
        return op[0] == "reset"

    @classmethod
    def operations(cls):
        return frozenset({"increment", "decrement", "reset"})


@register
class CounterB(CRDT):
    """Bounded counter (cannot go below zero).

    State: ``(P, D)`` where ``P[(i, j)]`` are rights transferred from
    replica i to j (``P[(i, i)]`` = rights minted by i's increments) and
    ``D[i]`` are decrements consumed by i — the Balegas et al. design the
    reference uses via antidote_crdt_counter_b + bcounter_mgr
    (src/bcounter_mgr.erl:103-125: decrement checked against local rights;
    insufficient rights => error, triggering a cross-DC transfer request).

    Ops carry the acting replica id: ("increment", (n, id)),
    ("decrement", (n, id)), ("transfer", (n, to_id, from_id)).
    """

    name = "counter_b"

    @classmethod
    def new(cls):
        return ({}, {})

    @classmethod
    def value(cls, state):
        p, d = state
        inc = sum(v for (i, j), v in p.items() if i == j)
        return inc - sum(d.values())

    @classmethod
    def local_permissions(cls, state, rid):
        p, d = state
        granted = sum(v for (i, j), v in p.items() if j == rid)
        given = sum(v for (i, j), v in p.items() if i == rid and j != rid)
        return granted - given - d.get(rid, 0)

    @classmethod
    def permissions(cls, state):
        """Per-replica rights map (drives bcounter_mgr's richest-DC
        preference list, reference src/bcounter_mgr.erl:194-209)."""
        p, d = state
        ids = {i for (i, _j) in p} | {j for (_i, j) in p} | set(d)
        return {r: cls.local_permissions(state, r) for r in ids}

    @staticmethod
    def _amount(n) -> int:
        n = int(n)
        if n <= 0:
            # negative amounts would bypass the rights check and break the
            # lower-bound guarantee
            raise DownstreamError(f"counter_b amount must be positive, got {n}")
        return n

    @classmethod
    def downstream(cls, op, state, ctx=None):
        name, arg = op
        if name == "increment":
            n, rid = arg
            return ("incr", cls._amount(n), rid)
        if name == "decrement":
            n, rid = arg
            n = cls._amount(n)
            if cls.local_permissions(state, rid) < n:
                raise DownstreamError("no_permissions")
            return ("decr", n, rid)
        if name == "transfer":
            n, to_id, from_id = arg
            n = cls._amount(n)
            if cls.local_permissions(state, from_id) < n:
                raise DownstreamError("no_permissions")
            return ("tx", n, from_id, to_id)
        raise DownstreamError(f"bad counter_b op {op!r}")

    @classmethod
    def update(cls, effect, state):
        p, d = state
        kind = effect[0]
        if kind == "incr":
            _, n, rid = effect
            p = dict(p)
            p[(rid, rid)] = p.get((rid, rid), 0) + n
            return (p, d)
        if kind == "decr":
            _, n, rid = effect
            d = dict(d)
            d[rid] = d.get(rid, 0) + n
            return (p, d)
        if kind == "tx":
            _, n, from_id, to_id = effect
            p = dict(p)
            p[(from_id, to_id)] = p.get((from_id, to_id), 0) + n
            return (p, d)
        raise DownstreamError(f"bad counter_b effect {effect!r}")

    @classmethod
    def require_state_downstream(cls, op):
        return op[0] in ("decrement", "transfer")

    @classmethod
    def operations(cls):
        return frozenset({"increment", "decrement", "transfer"})

"""RGA (replicated growable array) — collaborative sequences.

Reference type: antidote_crdt_rga (the long-sequence benchmark target,
BASELINE config 4: 100k-op collaborative-text logs).

State: tuple of vertices ``(uid, elem, visible)`` in RGA order, where
``uid = (lamport, actor)`` totally ordered.  Insertion uses the classic
RGA rule: place the new vertex after its reference vertex, skipping any
existing successors with a larger uid — concurrent inserts at the same
spot deterministically order newest-first.  Removal tombstones the vertex
(visible=False) so later concurrent inserts can still reference it.

Client ops (positions index the *visible* sequence at the origin):
- ``("add_right", (pos, elem))`` — insert elem to the right of the pos-th
  visible element; pos=0 inserts at the head.
- ``("remove", pos)`` — tombstone the pos-th visible element (1-based,
  matching the head=0 convention of add_right).

The batched device form (Euler-tour preorder merge over padded op
arrays) lives in antidote_tpu/mat/rga_kernel.py.
"""

from __future__ import annotations

from antidote_tpu.crdt.base import CRDT, DownstreamCtx, DownstreamError, register

_ROOT = (0, "")  # sentinel uid: insert-at-head reference


@register
class RGA(CRDT):
    name = "rga"

    @classmethod
    def new(cls):
        return ()

    @classmethod
    def value(cls, state):
        return [elem for _uid, elem, visible in state if visible]

    @classmethod
    def _visible_uid(cls, state, pos: int):
        """uid of the pos-th (1-based) visible vertex; pos=0 -> root."""
        if pos == 0:
            return _ROOT
        seen = 0
        for uid, _elem, visible in state:
            if visible:
                seen += 1
                if seen == pos:
                    return uid
        raise DownstreamError(f"rga position {pos} out of range ({seen} visible)")

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        name, arg = op
        if name in ("add_right", "addRight"):
            pos, elem = arg
            ref = cls._visible_uid(state, int(pos))
            lamport = 1 + max((uid[0] for uid, _e, _v in state), default=0)
            return ("ins", (lamport, str(ctx.actor)), ref, elem)
        if name == "remove":
            pos = int(arg)
            if pos == 0:
                raise DownstreamError("rga remove: positions are 1-based")
            return ("rm", cls._visible_uid(state, pos))
        raise DownstreamError(f"bad rga op {op!r}")

    @classmethod
    def update(cls, effect, state):
        kind = effect[0]
        if kind == "ins":
            _, uid, ref, elem = effect
            verts = list(state)
            if any(u == uid for u, _e, _v in verts):
                return state  # duplicate delivery
            if ref == _ROOT:
                i = 0
            else:
                try:
                    i = next(
                        j for j, (u, _e, _v) in enumerate(verts) if u == ref
                    ) + 1
                except StopIteration:
                    raise DownstreamError(
                        f"rga insert: unknown reference uid {ref!r}"
                    ) from None
            # RGA skip rule: concurrent siblings with larger uid stay first
            while i < len(verts) and verts[i][0] > uid:
                i += 1
            verts.insert(i, (uid, elem, True))
            return tuple(verts)
        if kind == "rm":
            _, uid = effect
            return tuple(
                (u, e, False if u == uid else v) for u, e, v in state
            )
        raise DownstreamError(f"bad rga effect {effect!r}")

    @classmethod
    def operations(cls):
        return frozenset({"add_right", "addRight", "remove"})

"""Flag CRDTs: enable-wins and disable-wins.

Reference types: antidote_crdt_flag_ew / _dw (exercised at reference
test/singledc/pb_client_SUITE.erl:477-483: enable/disable/reset ops).
"""

from __future__ import annotations

from antidote_tpu.crdt.base import CRDT, DownstreamCtx, DownstreamError, register


@register
class FlagEW(CRDT):
    """Enable-wins flag.  State: frozenset of enable-dots; enabled iff
    nonempty.  A concurrent enable's dot is unobserved by any disable, so
    it survives — enable wins."""

    name = "flag_ew"

    @classmethod
    def new(cls):
        return frozenset()

    @classmethod
    def value(cls, state):
        return bool(state)

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        name, _arg = op
        if name == "enable":
            return ("en", ctx.dot(), tuple(state))
        if name in ("disable", "reset"):
            return ("dis", tuple(state))
        raise DownstreamError(f"bad flag_ew op {op!r}")

    @classmethod
    def update(cls, effect, state):
        if effect[0] == "en":
            _, dot, observed = effect
            return (state - frozenset(observed)) | {dot}
        if effect[0] == "dis":
            return state - frozenset(effect[1])
        raise DownstreamError(f"bad flag_ew effect {effect!r}")

    @classmethod
    def operations(cls):
        return frozenset({"enable", "disable", "reset"})


@register
class FlagDW(CRDT):
    """Disable-wins flag.  State: (enable_dots, disable_dots); enabled iff
    enable_dots nonempty and disable_dots empty (same dot algebra as the
    remove-wins set, specialised to a single implicit element)."""

    name = "flag_dw"

    @classmethod
    def new(cls):
        return (frozenset(), frozenset())

    @classmethod
    def value(cls, state):
        en, dis = state
        return bool(en) and not dis

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        en, dis = state
        name, _arg = op
        if name == "enable":
            return ("en", ctx.dot(), tuple(dis))
        if name == "disable":
            return ("dis", ctx.dot(), tuple(en))
        if name == "reset":
            return ("reset", tuple(en), tuple(dis))
        raise DownstreamError(f"bad flag_dw op {op!r}")

    @classmethod
    def update(cls, effect, state):
        en, dis = state
        kind = effect[0]
        if kind == "en":
            _, dot, obs_dis = effect
            return (en | {dot}, dis - frozenset(obs_dis))
        if kind == "dis":
            _, dot, obs_en = effect
            return (en - frozenset(obs_en), dis | {dot})
        if kind == "reset":
            _, obs_en, obs_dis = effect
            return (en - frozenset(obs_en), dis - frozenset(obs_dis))
        raise DownstreamError(f"bad flag_dw effect {effect!r}")

    @classmethod
    def operations(cls):
        return frozenset({"enable", "disable", "reset"})

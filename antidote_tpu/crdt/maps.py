"""Map CRDTs: grow-only map and recursive-reset map.

Reference types: antidote_crdt_map_go / _rr (exercised at reference
test/singledc/pb_client_SUITE.erl:354-366 (map_go nested updates) and
:403-441 (map_rr update/remove/batch, nested maps)).

Map entries are keyed by ``(key, nested_type_name)``; nested ops are
delegated to the nested type's downstream/update, so a map effect is a
bag of nested effects.
"""

from __future__ import annotations

from antidote_tpu.crdt import base
from antidote_tpu.crdt.base import CRDT, DownstreamError, register


def _norm_keyt(key_t):
    """Normalize an entry key to ``(key, short_type_name)``."""
    key, typ = key_t
    cls = base.get_type(typ)
    return (key, cls.name)


def _nested(key_t):
    return base.get_type(key_t[1])


class _MapBase(CRDT):
    @classmethod
    def new(cls):
        return {}

    @classmethod
    def value(cls, state):
        return {
            key_t: _nested(key_t).value(nstate) for key_t, nstate in state.items()
        }

    #: MapRR entries must be removable, i.e. their nested type resettable;
    #: MapGO accepts any nested type (it has no remove).
    require_resettable = False

    @classmethod
    def _nested_downstream(cls, key_t, op, state, ctx):
        nested_cls = _nested(key_t)
        if cls.require_resettable and "reset" not in nested_cls.operations():
            # checked at update time, not just remove time — otherwise one
            # update with a non-resettable type poisons remove/reset forever
            raise DownstreamError(
                f"{cls.name}: nested type {nested_cls.name} is not resettable"
            )
        nstate = state.get(key_t, nested_cls.new())
        if not nested_cls.is_operation(op):
            raise DownstreamError(f"bad nested op {op!r} for {key_t!r}")
        return nested_cls.downstream(op, nstate, ctx)

    @classmethod
    def _update_effects(cls, pairs, state, ctx):
        effs = []
        for key_t, nop in pairs:
            key_t = _norm_keyt(key_t)
            effs.append((key_t, cls._nested_downstream(key_t, nop, state, ctx)))
        return effs


@register
class MapGO(_MapBase):
    """Grow-only map: entries can be created and updated, never removed.
    State: dict (key, type_name) -> nested state."""

    name = "map_go"

    @classmethod
    def downstream(cls, op, state, ctx=None):
        name, arg = op
        if name != "update":
            raise DownstreamError(f"bad map_go op {op!r}")
        pairs = arg if isinstance(arg, list) else [arg]
        return ("upd", tuple(cls._update_effects(pairs, state, ctx)))

    @classmethod
    def update(cls, effect, state):
        kind, entries = effect
        if kind != "upd":
            raise DownstreamError(f"bad map_go effect {effect!r}")
        out = dict(state)
        for key_t, neff in entries:
            nested_cls = _nested(key_t)
            out[key_t] = nested_cls.update(neff, out.get(key_t, nested_cls.new()))
        return out

    @classmethod
    def operations(cls):
        return frozenset({"update"})


@register
class MapRR(_MapBase):
    """Recursive-reset map: removing an entry resets the nested CRDT, and
    an entry is visible iff its nested state is not bottom.

    A concurrent nested update survives a remove (its dots are unobserved
    by the reset), matching the reference's reset semantics: remove wins
    only over what it causally saw.  Nested types must support reset to be
    removable.
    """

    name = "map_rr"
    require_resettable = True

    @classmethod
    def downstream(cls, op, state, ctx=None):
        name, arg = op
        if name == "update":
            pairs = arg if isinstance(arg, list) else [arg]
            return cls._batch(pairs, [], state, ctx)
        if name == "remove":
            keys = arg if isinstance(arg, list) else [arg]
            return cls._batch([], keys, state, ctx)
        if name == "batch":
            updates, removes = arg
            return cls._batch(list(updates), list(removes), state, ctx)
        if name == "reset":
            return cls._batch([], list(state.keys()), state, ctx)
        raise DownstreamError(f"bad map_rr op {op!r}")

    @classmethod
    def _batch(cls, updates, removes, state, ctx):
        effs = cls._update_effects(updates, state, ctx)
        effs.extend(
            cls._update_effects(
                [(key_t, ("reset", ())) for key_t in removes], state, ctx
            )
        )
        return ("upd", tuple(effs))

    @classmethod
    def update(cls, effect, state):
        kind, entries = effect
        if kind != "upd":
            raise DownstreamError(f"bad map_rr effect {effect!r}")
        out = dict(state)
        for key_t, neff in entries:
            nested_cls = _nested(key_t)
            nstate = nested_cls.update(neff, out.get(key_t, nested_cls.new()))
            if nstate == nested_cls.new():
                out.pop(key_t, None)  # bottom => entry invisible
            else:
                out[key_t] = nstate
        return out

    @classmethod
    def operations(cls):
        return frozenset({"update", "remove", "batch", "reset"})

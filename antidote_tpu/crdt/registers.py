"""Register CRDTs: last-writer-wins and multi-value.

Reference types: antidote_crdt_register_lww / _mv (exercised at
reference test/singledc/pb_client_SUITE.erl:294-312, 354-434).
"""

from __future__ import annotations

import time

from antidote_tpu.crdt.base import (
    CRDT,
    DownstreamCtx,
    DownstreamError,
    register,
    sorted_values,
)


def _now_us() -> int:
    return time.time_ns() // 1000


@register
class RegisterLWW(CRDT):
    """Last-writer-wins register.

    State: ``(ts, tiebreak, value)``; empty = ``(0, (), None)``.
    Effect carries the origin timestamp plus a dot as a deterministic
    tiebreak; update keeps the lexicographically larger (ts, tiebreak).
    """

    name = "register_lww"

    @classmethod
    def new(cls):
        return (0, (), None)

    @classmethod
    def value(cls, state):
        return state[2]

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        name, arg = op
        if name == "assign":
            v, ts = arg, _now_us()
        elif name == "assign_ts":
            # client-chosen timestamp variant; a distinct op name so a
            # legitimate 2-tuple *value* is never misparsed as (v, ts)
            v, ts = arg
        else:
            raise DownstreamError(f"bad register_lww op {op!r}")
        actor, seq = ctx.dot()
        return (int(ts), (str(actor), seq), v)

    @classmethod
    def update(cls, effect, state):
        ts, tie, _v = effect
        cur_ts, cur_tie, _ = state
        return effect if (ts, tie) > (cur_ts, cur_tie) else state

    @classmethod
    def require_state_downstream(cls, op):
        return False

    @classmethod
    def operations(cls):
        return frozenset({"assign", "assign_ts"})


@register
class RegisterMV(CRDT):
    """Multi-value register: concurrent assigns all survive.

    State: frozenset of ``(dot, value)`` pairs.  An assign's effect
    carries a fresh dot plus the dots it observed; applying it removes the
    observed pairs and adds the new one.  Under causal delivery two
    concurrent assigns observe disjoint histories, so both pairs remain
    and ``value`` returns both (reference pb_client_SUITE expectation:
    mv-register read returns the list of concurrent values).
    """

    name = "register_mv"

    @classmethod
    def new(cls):
        return frozenset()

    @classmethod
    def value(cls, state):
        return sorted_values(v for _dot, v in state)

    @classmethod
    def downstream(cls, op, state, ctx=None):
        ctx = ctx or DownstreamCtx()
        name, arg = op
        if name == "assign":
            return ("asgn", arg, ctx.dot(), tuple(d for d, _v in state))
        if name == "reset":
            return ("reset", tuple(d for d, _v in state))
        raise DownstreamError(f"bad register_mv op {op!r}")

    @classmethod
    def update(cls, effect, state):
        kind = effect[0]
        if kind == "asgn":
            _, v, dot, observed = effect
            obs = set(observed)
            kept = {(d, val) for d, val in state if d not in obs}
            kept.add((dot, v))
            return frozenset(kept)
        if kind == "reset":
            _, observed = effect
            obs = set(observed)
            return frozenset((d, v) for d, v in state if d not in obs)
        raise DownstreamError(f"bad register_mv effect {effect!r}")

    @classmethod
    def operations(cls):
        return frozenset({"assign", "reset"})

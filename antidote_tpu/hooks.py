"""Commit hooks — per-bucket pre/post commit callbacks.

Mirrors the reference's antidote_hooks (reference
src/antidote_hooks.erl:29-53, 92-164): a pre-commit hook runs at update
time and may transform the operation or fail the transaction; a
post-commit hook runs after commit and its failures are only logged.

Hook signature: ``hook(key, type_name, op) -> (key, type_name, op)``
for pre-commit (return a possibly transformed triple, raise to abort);
post-commit hooks take the same arguments and their return value is
ignored.  Hooks are selected by the bucket they were registered under
(the reference passes {Key, Bucket} as one tuple; here the bucket is
implicit in the registration).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Tuple

logger = logging.getLogger(__name__)

Hook = Callable[[Any, str, Tuple], Tuple]


class HookRegistry:
    def __init__(self):
        self._pre: Dict[Any, Hook] = {}
        self._post: Dict[Any, Hook] = {}

    def register_pre_hook(self, bucket, hook: Hook) -> None:
        self._pre[bucket] = hook

    def register_post_hook(self, bucket, hook: Hook) -> None:
        self._post[bucket] = hook

    def unregister_hook(self, which: str, bucket) -> None:
        {"pre_commit": self._pre, "post_commit": self._post}[which].pop(
            bucket, None)

    def get_hooks(self, which: str, bucket):
        return {"pre_commit": self._pre, "post_commit": self._post}[
            which].get(bucket)

    def run_pre(self, bucket, key, type_name: str, op: Tuple):
        """Apply the pre-commit hook; exceptions abort the transaction
        (reference: failing pre-hook => update rejected)."""
        hook = self._pre.get(bucket)
        if hook is None:
            return key, type_name, op
        return hook(key, type_name, op)

    def run_post(self, bucket, key, type_name: str, op: Tuple) -> None:
        """Apply the post-commit hook; failures are logged, never raised
        (reference: post-hook errors don't fail the txn)."""
        hook = self._post.get(bucket)
        if hook is None:
            return
        try:
            hook(key, type_name, op)
        except Exception:
            logger.exception("post-commit hook failed for bucket %r", bucket)

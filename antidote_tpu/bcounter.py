"""Bounded-counter resource manager — the bcounter_mgr equivalent.

The one CRDT whose ops need cross-DC coordination: a decrement consumes
*rights*, and a DC without enough rights must get them transferred from a
richer DC (reference src/bcounter_mgr.erl).  Protocol, mirrored exactly:

- a decrement is checked against local rights at downstream-generation
  time; on failure the shortfall is queued and the client sees the same
  ``no_permissions`` abort the reference returns (reference
  src/bcounter_mgr.erl:103-125);
- a periodic transfer pass (``?TRANSFER_FREQ`` = 100 ms,
  reference include/antidote.hrl:79) walks the queue and asks remote DCs
  richest-first for the missing rights, splitting the request across the
  preference list (``transfer_periodic`` / ``request_remote`` /
  ``pref_list``, reference src/bcounter_mgr.erl:127-147, 165-209);
- the remote side applies a ``transfer`` update through the normal
  transaction API — so the granted rights replicate back over the
  ordinary inter-DC txn stream — rate-limited per (key, requester) by a
  grace period (``?GRACE_PERIOD`` = 1 s, reference
  src/bcounter_mgr.erl:103-114 + include/antidote.hrl:75).

The RPC rides the inter-DC query channel as ``BCOUNTER_REQUEST``
(reference src/inter_dc_query_receive_socket.erl:127-133).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.crdt import DownstreamError, get_type
from antidote_tpu.interdc import query as idc_query
from antidote_tpu.interdc.transport import LinkDown

#: key for the request queue / grace table: (key, bucket)
BoundKey = Tuple[Any, Any]


class BCounterMgr:
    """Per-DC bounded-counter manager (reference src/bcounter_mgr.erl)."""

    def __init__(self, dc) -> None:
        self.dc = dc
        self.dc_id = dc.node.dc_id
        cfg = dc.node.config
        self.transfer_period_s = cfg.bcounter_transfer_period_s
        self.grace_period_s = cfg.bcounter_grace_period_s
        self._lock = threading.Lock()
        #: queued shortfalls: bound key -> amount still needed
        self._requests: Dict[BoundKey, int] = {}
        #: (bound key, requester dc) -> monotonic time of last grant
        self._last_transfers: Dict[Tuple[BoundKey, Any], float] = {}

    # ------------------------------------------------------ downstream hop

    def generate_downstream(self, op, state, ctx, key=None, bucket=None):
        """The clocksi_downstream detour (reference
        src/clocksi_downstream.erl:47-56): normalize the acting replica to
        this DC and, on a rights shortfall, queue a transfer request
        before surfacing the same error."""
        cls = get_type("counter_b")
        name, arg = op
        try:
            op = (name, self._normalize_arg(name, arg))
        except (TypeError, ValueError) as e:
            # malformed args must abort the txn like any other downstream
            # failure, not escape as a raw unpack error
            raise DownstreamError(
                f"malformed counter_b op {name!r}: {e}") from e
        if name != "decrement":
            return cls.gen_downstream(op, state, ctx)
        amount = op[1][0]
        try:
            ds = cls.gen_downstream(op, state, ctx)
        except DownstreamError as e:
            # queue the shortfall for the periodic transfer pass — only
            # for a genuine rights shortfall, not op-validation errors
            # (reference queue_request, src/bcounter_mgr.erl:116-125)
            if key is not None and str(e) == "no_permissions":
                available = cls.local_permissions(state, self.dc_id)
                stats.registry.bcounter_denials.inc()
                stats.registry.bcounter_rights_held.set(
                    float(max(available, 0)), dc=str(self.dc_id))
                missing = max(amount - max(available, 0), 1)
                with self._lock:
                    bk = (key, bucket)
                    self._requests[bk] = max(
                        self._requests.get(bk, 0), missing)
            raise
        # rights remaining after this decrement lands — the gauge the
        # rights-economy Grafana panel trends (ISSUE 17)
        stats.registry.bcounter_rights_held.set(
            float(max(cls.local_permissions(state, self.dc_id) - amount,
                      0)), dc=str(self.dc_id))
        return ds

    def _normalize_arg(self, name: str, arg):
        """Clients may pass a bare amount; the replica id is always this
        DC (the reference substitutes its own DC id the same way)."""
        if name in ("increment", "decrement"):
            if isinstance(arg, int):
                return (arg, self.dc_id)
            if arg in ((), None):
                return (1, self.dc_id)
            n, rid = arg
            return (int(n), rid if rid is not None else self.dc_id)
        if name == "transfer":
            if len(arg) == 2:
                n, to_id = arg
                return (int(n), to_id, self.dc_id)
            n, to_id, from_id = arg
            return (int(n), to_id,
                    from_id if from_id is not None else self.dc_id)
        return arg

    # ---------------------------------------------------- periodic transfer

    def transfer_periodic(self) -> None:
        """One transfer pass: drain the request queue, asking remote DCs
        richest-first for the missing rights; also expire grace entries
        (reference transfer_periodic, src/bcounter_mgr.erl:127-147)."""
        with self._lock:
            requests = dict(self._requests)
            self._requests.clear()
            cutoff = time.monotonic() - self.grace_period_s
            before = len(self._last_transfers)
            self._last_transfers = {
                k: t for k, t in self._last_transfers.items() if t >= cutoff}
            expired = before - len(self._last_transfers)
        if expired:
            stats.registry.bcounter_grace_expiries.inc(expired)
        for (key, bucket), needed in requests.items():
            self._request_remote(key, bucket, needed)

    def _request_remote(self, key, bucket, needed: int) -> None:
        """Split ``needed`` across remote DCs in descending-rights order
        (reference request_remote, src/bcounter_mgr.erl:165-185)."""
        remaining = needed
        for remote_dc, available in self._pref_list(key, bucket):
            if remaining <= 0:
                break
            if available <= 0:
                continue
            ask = min(remaining, available)
            try:
                self.dc.bus.request(
                    self.dc_id, remote_dc, idc_query.BCOUNTER_REQUEST,
                    (key, bucket, ask, self.dc_id))
            except LinkDown:
                continue
            stats.registry.bcounter_transfer_requests.inc(
                peer=str(remote_dc))
            remaining -= ask

    def _pref_list(self, key, bucket) -> List[Tuple[Any, int]]:
        """Remote DCs sorted by their rights on this counter, richest
        first (reference pref_list, src/bcounter_mgr.erl:194-209)."""
        state = self._read_state(key)
        cls = get_type("counter_b")
        perms = cls.permissions(state)
        return sorted(
            ((rid, avail) for rid, avail in perms.items()
             if rid != self.dc_id),
            key=lambda t: t[1], reverse=True)

    def _read_state(self, key):
        pm = self.dc.node.partition_of(key)
        return pm.read(key, "counter_b", None)

    # -------------------------------------------------------- remote grants

    def handle_remote_request(self, from_dc, payload) -> Optional[bool]:
        """Serve a transfer request from ``from_dc``: apply a ``transfer``
        update through the normal txn API so the grant replicates over
        the ordinary inter-DC stream; suppress repeats inside the grace
        period (reference src/bcounter_mgr.erl:103-114)."""
        key, bucket, amount, requester = payload
        bk = (key, bucket)
        with self._lock:
            last = self._last_transfers.get((bk, requester))
            if last is not None and \
                    time.monotonic() - last < self.grace_period_s:
                stats.registry.bcounter_grace_suppressed.inc()
                return False
        bound = (key, "counter_b", bucket)
        try:
            self.dc.update_objects_static(
                None, [(bound, "transfer", (amount, requester, self.dc_id))])
        except Exception:
            # not enough local rights (or lost a race) — the requester
            # will retry on its next failed decrement, as in the reference;
            # a failed grant must NOT start the grace period, or a
            # momentarily-poor donor blocks the requester for a full
            # grace window after regaining rights
            return False
        with self._lock:
            self._last_transfers[(bk, requester)] = time.monotonic()
        stats.registry.bcounter_transfers_granted.inc(
            peer=str(requester))
        return True

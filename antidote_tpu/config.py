"""Node/DC configuration flags.

Mirrors the reference's OTP app env surface (reference
src/antidote.app.src:30-63): txn_cert, txn_prot, sync_log,
enable_logging, recover_from_log, recover_meta_data_on_start,
auto_start_read_servers — plus the rebuild's own knobs.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Config:
    #: write-write certification on commit (reference txn_cert)
    certify: bool = True
    #: transaction protocol: "clocksi" | "gr" (GentleRain, reference txn_prot)
    txn_prot: str = "clocksi"
    #: fsync the log on commit records (reference sync_log)
    sync_log: bool = False
    #: group-commit durable-log plane (antidote_tpu/oplog/log.py):
    #: commit-path appends STAGE framed record bytes per partition log
    #: and concurrent committers share ONE buffered write + ONE fsync —
    #: a caller-elected leader drains the window (a solo committer
    #: syncs immediately), committers release the partition lock before
    #: waiting on their durability ticket, and the batch write crosses
    #: into the native backend once per drain (oplog_append_batch).
    #: False = the exact per-record legacy path (one write + one inline
    #: fsync per commit record, held across the partition lock — the
    #: benches' comparison baseline, like mat_ingest / read_serve /
    #: interdc_ship / gate_device_ring)
    log_group: bool = True
    #: group-commit window, µs: a drain leader with company (other
    #: committers already waiting on durability tickets) holds the
    #: drain open this long so a burst shares one fsync; a solo
    #: committer drains immediately (zero added latency on uncontended
    #: commits).  0 disables the hold — drains still batch whatever
    #: staged while the previous fsync ran (self-clocking group commit)
    log_group_us: int = 300
    #: staged-record budget per log: past it the window closes at once
    #: and, on the non-synced path, staged records are written through
    #: (backpressure — staged bytes cannot grow unboundedly when
    #: sync_on_commit never drains them)
    log_group_records: int = 512
    #: staged-BYTE budget per log (the interdc_ship_bytes analogue):
    #: large-payload workloads write through well before the record
    #: cap, bounding both the heap a partition log pins and the
    #: process-crash loss window of the non-synced path (staged bytes
    #: live in Python memory; written-through bytes reach the page
    #: cache, which survives a process crash)
    log_group_bytes: int = 256 * 1024
    #: publish commit effects only AFTER the durability ticket is
    #: covered (strict durability-before-visibility ordering): under
    #: the group-commit plane with sync_on_commit, the commit record
    #: stages, the committer waits out the shared fsync, and only THEN
    #: makes the effects visible (readers block on the prepared entry
    #: meanwhile).  Default off keeps the reference's async-log-ack
    #: window: visibility precedes durability, the ack follows the
    #: fsync (the PR-8 ROADMAP remaining item; ordering asserted by
    #: tests/unit/test_checkpoint.py)
    publish_after_durable: bool = False
    #: append records to the durable log at all (reference enable_logging)
    enable_logging: bool = True
    #: rebuild the materializer caches from the log at boot
    recover_from_log: bool = True
    #: per-partition checkpoint plane (antidote_tpu/oplog/checkpoint.py,
    #: ISSUE 10): periodically fold every dirty key's materialized
    #: state at a cut frontier (device keys via one batched fold per
    #: type plane, host keys via the materializer) into an atomic
    #: checksummed file; recovery becomes load-checkpoint +
    #: replay-suffix (O(delta) in the ops past the cut), restarts
    #: recover partitions in parallel, and eviction/read-below-base
    #: replay seeds from the checkpoint instead of offset 0.  False
    #: keeps today's full-scan recovery bit-for-bit (the benches'
    #: comparison baseline, like log_group / mat_ingest / read_serve).
    #: Requires recover_from_log: with boot-time recovery off there is
    #: no recovery cost to cut, and the plane stays off (a truncation
    #: could otherwise reclaim the only copy of history the seed set
    #: never covered)
    ckpt: bool = True
    #: published-op watermark per partition: past it the next commit
    #: writes a checkpoint
    ckpt_ops: int = 4096
    #: appended-byte watermark per partition log (the other trigger)
    ckpt_bytes: int = 4 * 1024 * 1024
    #: reclaim log bytes below the checkpoint cut (atomic rewrite
    #: behind a truncation marker; logical offsets stay stable).
    #: Bounded by the retention floor — min over peers of the inter-DC
    #: ship/ack watermark — so connected peers' gap repair keeps
    #: answering from the log; a peer beyond the floor gets the
    #: explicit BELOW_FLOOR answer and bootstraps from the checkpoint
    #: (interdc/query.py, interdc/sub_buf.py).  NOTE: with
    #: resize_from_ckpt on (the default) ring resizes fold from
    #: checkpoint seeds + suffix replay and accept a truncated log;
    #: only a deployment that BOTH truncates and forces the legacy
    #: full-history fold (resize_from_ckpt=False) must disable this
    #: knob before resizing in place.
    ckpt_truncate: bool = True
    #: opid safety margin kept below the peers' ship watermark when
    #: truncating: ordinary gap repair (lost frames) stays served from
    #: the log for this much recent history
    ckpt_retain_ops: int = 4096
    #: segmented checkpoint seed persistence (ISSUE 13): a watermark
    #: checkpoint writes ONLY a dirty-delta seed segment (keys whose
    #: frontier moved since the last cut) plus a small manifest, so
    #: persist cost tracks CHURN instead of total keyspace — the
    #: monolithic document re-pickled + double-fsynced the WHOLE
    #: carried seed set at every cut.  Segments are immutable,
    #: individually checksummed files; recovery reads each key's
    #: newest segment entry; a caller-elected compaction folds them
    #: when the dead-entry ratio crosses ckpt_seg_waste_frac.  False
    #: keeps the PR-9 one-document checkpoint bit-for-bit (the
    #: benches' comparison baseline, like ckpt / log_group); loading
    #: follows the on-disk document's shape either way, so flipping
    #: the knob across a restart recovers cleanly.
    ckpt_segmented: bool = True
    #: dead-entry fraction across seed segments past which the next
    #: checkpoint compacts them into one (superseded per-key entries
    #: accumulate one per re-fold of a dirty key; compaction is
    #: caller-elected on the checkpointing thread — no background
    #: thread, the mat/serve.py discipline)
    ckpt_seg_waste_frac: float = 0.5
    #: mmap-backed segment loads (ISSUE 19): manifest merges CRC and
    #: decode each seed segment through a read-only page-cache mapping
    #: instead of a full heap read(), so loading a merged seed set
    #: larger than RAM never materializes more than one segment body
    #: at a time.  False keeps the PR-12 read() path bit-for-bit.
    ckpt_mmap: bool = True
    #: checkpoint-seeded ring resizes (ISSUE 19): repartition /
    #: resize_cluster fold each slot from the adopted checkpoint's
    #: seeds + the retained log suffix — O(delta) per moved slot — and
    #: accept truncated logs (the below-cut history rides in the
    #: re-cut per-slot checkpoints, installed at the resize journal's
    #: commit point).  A partition with no adopted checkpoint folds
    #: its full history exactly as before.  False forces the legacy
    #: full-history fold bit-for-bit (the bench baseline), including
    #: the PR-9 truncated-log refusal.
    resize_from_ckpt: bool = True
    #: segment-granular checkpoint transfer (ISSUE 19): the handoff
    #: bundle pull and the CKPT_READ bootstrap fetch the manifest
    #: first, then segments through a resumable cursor — per-segment
    #: ack watermark, torn fetches refused and re-pulled, exact resume
    #: after a donor kill — instead of one whole-bundle message.
    #: False keeps the one-shot ship/answer path bit-for-bit (the
    #: bench baseline).
    ckpt_stream: bool = True
    #: in-flight byte budget per streamed transfer: a fetch round asks
    #: for whole segments up to this many bytes (at least one), the
    #: backpressure bound on donor reads and receiver staging memory
    ckpt_stream_window_bytes: int = 4 * 1024 * 1024
    #: number of partitions per node (reference ring size, default 16 prod
    #: / 4 in tests, config/vars.config:5)
    n_partitions: int = 4
    #: data directory for durable logs / metadata
    data_dir: str = "antidote_data"
    #: stable-snapshot read cache TTL, seconds.  Every transaction start
    #: reads the stable snapshot; computing it sweeps all partitions'
    #: min-prepared (a lock per partition — a convoy under concurrent
    #: clients).  A stale-by-milliseconds stable snapshot is always
    #: safe: stability is monotone, and the snapshot's own-DC entry is
    #: bumped to `now` regardless (the reference reads a 1 s-cadence
    #: gossiped value, far staler than this)
    stable_ttl_s: float = 0.002
    #: inter-DC heartbeat period, seconds (reference ?HEARTBEAT_PERIOD
    #: 1 s, include/antidote.hrl:55)
    heartbeat_s: float = 1.0
    #: cluster stable-gossip period, seconds — its own knob, NOT the
    #: inter-DC heartbeat (the reference separates ?META_DATA_SLEEP
    #: from ?HEARTBEAT_PERIOD, include/antidote.hrl:55,60).  None
    #: follows heartbeat_s, so existing single-knob tunings keep
    #: working; set explicitly to decouple.
    cluster_gossip_s: float | None = None
    #: native fabric routing (ISSUE 12) — ONE knob for both fabrics:
    #: the intra-DC node link (cluster/nativelink.py: C++ event loop,
    #: GIL-free waits, pipelined requests, the published-answer plane)
    #: and the inter-DC publish fan-out (interdc/tcp.py: native hub /
    #: staged zero-copy Python fan-out).  "auto" (default) uses the
    #: native planes when the C++ toolchain builds them and falls back
    #: to Python otherwise; True REQUIRES them (boot fails loudly
    #: without a compiler); False routes every call site through the
    #: exact legacy Python paths — NodeLink and the per-subscriber
    #: framed TcpTransport fan-out, bit-for-bit — as the benches'
    #: comparison baseline (like log_group / read_serve / interdc_ship)
    fabric_native: bool | str = "auto"
    #: worker threads answering node RPCs on the native fabric (the
    #: reference's per-vnode read-server pool is 20,
    #: include/antidote.hrl:28)
    fabric_workers: int = 16
    #: native-plane flight recorder (ISSUE 16): the C++ fabrics record
    #: fixed-size events into wait-free rings that Python drains into
    #: the NATIVE_* stats families and the sampled trace stream.
    #: False stops event recording (the rings' heartbeats keep
    #: beating, so the stall watchdog below still works)
    native_telemetry: bool = True
    #: native event-thread stall threshold, seconds: a ring heartbeat
    #: older than this force-dumps the flight recorder with the
    #: /debug/pipeline snapshot embedded (one dump per stall episode);
    #: 0 disables the watchdog
    native_watchdog_s: float = 5.0
    #: reload DC descriptors / env flags from disk at boot (reference
    #: recover_meta_data_on_start)
    recover_meta_data_on_start: bool = True
    #: cap on the causal clock wait (the reference spins forever,
    #: src/clocksi_interactive_coord.erl:915-926; a cap keeps tests and
    #: batch jobs from hanging on an unreachable dependency)
    clock_wait_timeout_s: float = 30.0
    #: bounded-counter transfer pass period (reference ?TRANSFER_FREQ
    #: 100 ms, include/antidote.hrl:79)
    bcounter_transfer_period_s: float = 0.1
    #: grace period suppressing repeated grants to the same requester
    #: (reference ?GRACE_PERIOD 1 s, include/antidote.hrl:75)
    bcounter_grace_period_s: float = 1.0
    #: Prometheus exposition port; None disables the HTTP endpoint
    #: (reference elli on :3001, src/antidote_sup.erl:118-128; 0 picks
    #: a free port)
    metrics_port: int | None = None
    #: staleness histogram sampling period (reference 10 s,
    #: src/antidote_stats_collector.erl:87-93)
    staleness_sample_s: float = 10.0
    #: serve supported CRDT types (set_aw, counter_pn) from the device
    #: shard store — the TPU data plane (antidote_tpu/mat/device_plane.py);
    #: the reference's materializer_vnode duty
    device_store: bool = True
    #: initial key capacity per partition plane (doubles on demand)
    device_key_capacity: int = 1024
    #: ring lanes per key (absorbs unstable ops between GC folds)
    device_lanes: int = 8
    #: initial element slots per key (OR-set; doubles up to max)
    device_slots: int = 8
    #: staged ops per plane that trigger a device append flush
    device_flush_ops: int = 256
    #: applied ops per plane that trigger a GST-driven device GC
    device_gc_ops: int = 2048
    #: dense DC/actor column cap before a key evicts to the host path
    device_max_dcs: int = 64
    #: per-key element-slot cap before an OR-set key evicts
    device_max_slots: int = 256
    #: coalesced ingest plane for the materializer stores
    #: (antidote_tpu/mat/ingest.py): each plane flush uploads ONE
    #: packed tensor and applies it with a single donated scatter,
    #: instead of ~10 per-column uploads.  False = the legacy
    #: per-column append path (the benches' comparison baseline).
    mat_ingest: bool = True
    #: ingest coalescing window, µs: staged rows younger than this may
    #: wait for more arrivals so a burst flushes as one dispatch even
    #: below device_flush_ops rows; 0 disables the window
    mat_coalesce_us: int = 2000
    #: hard staged-row cap per plane (ingest row budget): past it the
    #: committer flushes INLINE — backpressure so a lagging flusher
    #: cannot let staged rows grow unboundedly
    mat_coalesce_rows: int = 8192
    #: cross-transaction read-coalescing serve plane
    #: (antidote_tpu/mat/serve.py): concurrent snapshot reads of a
    #: partition stage into a short per-partition window and drain as
    #: ONE gathered device fold per snapshot-compatible group
    #: (Clock-SI rule: a group folds at the pointwise-max VC, valid
    #: for every waiter it covers), with each waiter's read-your-
    #: writes overlay applied on top by the coordinator.  False = the
    #: per-txn read path (the benches' comparison baseline, like
    #: mat_ingest / gate_device_ring / interdc_ship)
    read_serve: bool = True
    #: read-coalescing window, µs: once a drain leader observes OTHER
    #: waiters staged it holds the window open this long so a burst is
    #: served by one fold; a solo reader drains immediately (no added
    #: latency on uncontended reads).  0 disables the hold — drains
    #: still batch whatever staged while the previous drain ran
    read_coalesce_us: int = 400
    #: staged-key budget per window: past it the leader drains at once
    #: (latency backpressure, the mat_coalesce_rows analogue)
    read_coalesce_keys: int = 512
    #: run threshold device flushes/GCs on a background flusher thread
    #: (group commit: commits only stage; reads needing pending data
    #: still flush inline).  Committers flush inline past 4x the
    #: threshold (backpressure).
    device_async_flush: bool = True
    #: per-process interpreter tuning (GC freeze + thresholds, GIL
    #: switch interval — antidote_tpu/runtime.py) applied when a
    #: NodeServer starts.  Default on: a node process's main duty is
    #: serving.  Turn OFF when EMBEDDING a node in an application
    #: whose own GC/scheduling behavior must not change (the tuning
    #: mutates process-global state).
    tune_process: bool = True
    #: partition -> chip placement over jax.devices(): "ring" commits
    #: partition p's plane state to chip p % n_devices (the ring as
    #: the live data plane across a host's chips); "none" keeps the
    #: default device.  No-op with a single device.
    device_placement: str = "none"
    #: pod-scale sharded materializer (antidote_tpu/mat/sharded.py):
    #: shard every DevicePlane's key axis over ALL devices (one mesh,
    #: rule-table partition specs, cross-chip fused group reads,
    #: per-shard residency routing) instead of replicating state per
    #: partition.  "auto" activates with >1 device on a real
    #: accelerator backend only (the virtual CPU mesh the test suite
    #: runs under stays on the single-chip baseline); True forces it
    #: wherever >1 device exists (how the CPU-mesh tests/benches opt
    #: in); False pins the legacy single-chip DevicePlane bit-for-bit
    #: (the benches' comparison baseline).  Resolved once per node by
    #: mat/sharded.sharded_from_config — the ONE factory, so every
    #: partition of an assembly shards or none do.
    mat_sharded: bool | str = "auto"
    #: fraction of transactions traced end-to-end (txid-deterministic;
    #: antidote_tpu/obs/spans.py).  1.0 traces everything (tests /
    #: debugging), 0 disables span recording entirely.  The default
    #: keeps tracing overhead well under the 5%% budget on the txn
    #: bench while still collecting a steady trickle of full trees.
    trace_sample_rate: float = 0.05
    #: finished spans kept in the in-process ring (/debug/spans depth)
    trace_capacity: int = 65536
    #: per-kernel device-plane profiling (antidote_tpu/obs/prof.py):
    #: call/dispatch timing, compile-cache-miss counters, and buffer
    #: high-watermarks on every jitted mat//interdc entry point, served
    #: at /debug/prof.  Lightweight (µs of host bookkeeping per BATCH
    #: dispatch; honest completion fetches only for sampled txns or an
    #: open XProf capture); False turns every hook into a passthrough.
    kernel_profile: bool = True
    #: flight-recorder dump directory (None = <tempdir>/antidote_obs;
    #: antidote_tpu/obs/events.py)
    flight_recorder_dir: str | None = None
    #: queued-txn count past which a dependency gate leaves the host
    #: head-walk for the batched device path (interdc/dep.py; above it
    #: the adaptive picker still learns the cheaper path from measured
    #: cost)
    gate_batch_threshold: int = 48
    #: batched gate form: True = the device-resident ring (ISSUE 3 —
    #: incremental appends, in-place retire/compact, one fixpoint per
    #: admission wave); False = the legacy per-pass repack (kept as
    #: the benches' comparison baseline)
    gate_device_ring: bool = True
    #: initial gate-ring capacity in txn slots (rounded up to a power
    #: of two; grows by a device-side gather on demand)
    gate_ring_capacity: int = 256
    #: enqueue-coalescing window, µs: while the batched regime is
    #: active and a gating pass ran within the window, further
    #: deliveries only stage — one device dispatch then admits the
    #: whole burst.  0 processes every head enqueue immediately (the
    #: pre-ISSUE-3 behavior).
    gate_coalesce_us: int = 2000
    #: dead-slot fraction past which the gate ring compacts (shrinks)
    #: so the fixpoint stops paying for a drained backlog's peak
    gate_compact_frac: float = 0.75
    #: batched inter-DC shipping plane (antidote_tpu/interdc/sender.py):
    #: committed txns coalesce per (origin, partition) stream into ONE
    #: columnar batch frame under a window + byte/txn budget, drained
    #: by an async sender thread so ``transport.publish`` leaves the
    #: committing thread entirely; heartbeats piggyback on batch
    #: frames.  False = the legacy one-frame-per-txn path (kept as the
    #: benches' comparison baseline, like mat_ingest/gate_device_ring)
    interdc_ship: bool = True
    #: ship coalescing window, µs: staged txns younger than this may
    #: wait for more commits so a burst ships as one frame; 0 drains
    #: immediately (frames still coalesce whatever is staged)
    interdc_ship_us: int = 2000
    #: soft byte budget per batch frame (estimated encoded size): past
    #: it the worker closes the frame early
    interdc_ship_bytes: int = 256 * 1024
    #: txn budget per batch frame
    interdc_ship_txns: int = 64
    #: probability a device-served set_aw read is cross-checked against
    #: a log replay at the same snapshot (the read-inclusion probe,
    #: antidote_tpu/obs/probe.py); violations dump the flight recorder.
    #: Default off: the oracle replay costs a per-key log scan.
    obs_selfcheck_set_aw: float = 0.0
    #: causal-probe auditor period, seconds (ISSUE 7,
    #: antidote_tpu/obs/probe.py): each round commits a unique probe
    #: element on this DC and causally reads it back on every other
    #: DC registered in the process, recording the observed
    #: write->remote-read staleness and alarming (flight-recorder
    #: dump + error log) on a causal-order violation.  0 disables
    #: (default — each round costs one txn per period plus a causal
    #: read per peer).
    obs_causal_probe_s: float = 0.0
    #: fleet scrape period, seconds (ISSUE 17,
    #: antidote_tpu/obs/fleet.py): each round merges the local
    #: registry + pipeline plane with every remote endpoint listed in
    #: ``extra["fleet_peers"]`` (``http://host:port`` metrics-server
    #: roots), refreshes the FLEET_* gauges and re-judges the merged
    #: samples against obs/slo.py's DEFAULT_OBJECTIVES (SLO_* gauges).
    #: 0 disables (default): scraping stays caller-elected per the
    #: mat/serve.py no-background-thread discipline.
    fleet_scrape_s: float = 0.0
    #: interest-routed replication master switch (ISSUE 18,
    #: antidote_tpu/interdc/interest.py): when True the sender cuts
    #: per-interest-class slices of every staged frame and each
    #: subscriber receives only txns whose write-set intersects its
    #: announced key ranges.  False (default-off first ship) preserves
    #: today's wire bytes and fan-out behavior bit-for-bit; under True
    #: a spec-less subscriber still gets the full stream untouched, so
    #: pre-upgrade peers interoperate (docs/interest_routing.md).
    interest_routing: bool = False
    #: this DC's subscription: a set of half-open [lo, hi) string key
    #: ranges, e.g. ``(("a", "m"),)``.  None = subscribe to the full
    #: stream even when routing is on.  Validated loudly at DC start
    #: (interest.InterestError on malformed/empty/overlapping ranges —
    #: never a silent full or empty stream).
    interest_ranges: tuple | None = None
    extra: dict = field(default_factory=dict)

"""Batched RGA merge kernel — the long-sequence materialization target.

The reference materializes an RGA by splicing one op at a time into a
linked list inside a gen_server (reference antidote_crdt rga `update`,
surveyed via the behaviour contract in SURVEY §2.6; host oracle:
antidote_tpu/crdt/rga.py).  At 100k-op collaborative-text logs
(BASELINE config 4) that sequential walk is the bottleneck.

Here the *entire* merge is a fixed-shape parallel program:

1. **Causal tree build.**  Every insert references the vertex to its
   left; with Lamport uids (child.lamport > parent.lamport — guaranteed
   by RGA's downstream generation) the document order is exactly the
   preorder of the tree whose siblings are ordered uid-descending.
   Parent resolution is a sort + searchsorted over packed uids; sibling
   order is one stable two-key sort.

2. **Euler tour.**  Preorder needs "next sibling of the nearest ancestor
   with one" — non-local.  The Euler tour successor is *local*: each
   vertex gets a down-slot (enter) and an up-slot (leave), and
   ``succ(down v) = down firstchild(v) | up v``,
   ``succ(up v) = down nextsib(v) | up parent(v)``.

3. **Pointer-doubling list rank** (Wyllie).  ``ceil(log2(2N))`` rounds of
   ``dist += dist[next]; next = next[next]`` turn the successor list into
   preorder ranks — O(log N) device steps, every one a dense gather the
   TPU is happy with.  No sequential splice anywhere.

Shapes are static: N insert lanes + M delete lanes, padding lanes carry
valid=False.  uids are (lamport, actor) packed into int32 as
``lamport << actor_bits | actor`` — callers must keep the packed value
strictly below INT32_MAX, which the padding sentinel owns (host asserts
in the synth generator; at the default 8 actor bits that is ~8.3M ops
per log).  Duplicate delivery of the same uid is tolerated (later copies
are parked, matching the host RGA's dedup); inserts referencing a uid
absent from the log are unresolvable and excluded together with their
subtrees (the host oracle raises instead — feed the kernel closed logs).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from antidote_tpu.obs.prof import kernel_span

_I32MAX = jnp.iinfo(jnp.int32).max


def pack_uid(lamport, actor, actor_bits: int = 8):
    """int32 packed uid; (0, 0) (the root sentinel) packs to 0."""
    return (lamport.astype(jnp.int32) << actor_bits) | actor.astype(jnp.int32)


def _lexsort2(primary, secondary):
    """argsort by (primary, secondary) via two stable argsorts."""
    o1 = jnp.argsort(secondary, stable=True)
    o2 = jnp.argsort(primary[o1], stable=True)
    return o1[o2]


def _merge_impl(
    ins_lamport: jax.Array,  # int32[N] lamport of inserted vertex
    ins_actor: jax.Array,    # int32[N] actor (origin DC) of vertex
    ref_lamport: jax.Array,  # int32[N] lamport of left-neighbour ref (0=head)
    ref_actor: jax.Array,    # int32[N] actor of ref
    elem: jax.Array,         # int32[N] interned payload token
    valid: jax.Array,        # bool[N]
    del_lamport: jax.Array,  # int32[M] delete targets
    del_actor: jax.Array,    # int32[M]
    del_valid: jax.Array,    # bool[M]
    actor_bits: int = 8,
):
    """Shared merge body; see :func:`rga_merge` / :func:`rga_merge_full`."""
    n = ins_lamport.shape[0]
    root = n            # virtual root vertex index
    parked = n + 1      # where padding / unresolvable lanes go

    uid = pack_uid(ins_lamport, ins_actor, actor_bits)
    uid = jnp.where(valid, uid, _I32MAX)          # park padding uids
    ref = pack_uid(ref_lamport, ref_actor, actor_bits)

    # -- parent resolution: uid -> vertex index ---------------------------
    by_uid = jnp.argsort(uid)                      # [N]
    sorted_uid = uid[by_uid]
    # dedup duplicate delivery: all but the first copy of a uid (the one
    # searchsorted binds to) are parked
    dup_sorted = jnp.concatenate(
        [jnp.zeros((1,), bool), sorted_uid[1:] == sorted_uid[:-1]])
    dup = jnp.zeros((n,), bool).at[by_uid].set(dup_sorted)
    pos = jnp.searchsorted(sorted_uid, ref)
    cpos = jnp.clip(pos, 0, n - 1)
    hit = (pos < n) & (sorted_uid[cpos] == ref)
    parent = jnp.where(
        ref == 0, root, jnp.where(hit, by_uid[cpos], parked))
    parent = jnp.where(valid & ~dup, parent, parked)

    # -- sibling lists: sort by (parent, uid desc) ------------------------
    sperm = _lexsort2(parent, -uid)                # [N] vertex ids
    sparent = parent[sperm]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sparent[1:] != sparent[:-1]])
    # first_child over [0..parked]; scatter only segment heads
    fc_idx = jnp.where(first, sparent, parked + 1)  # OOB -> dropped
    first_child = jnp.full((n + 2,), -1, jnp.int32).at[fc_idx].set(
        sperm.astype(jnp.int32), mode="drop")
    same = sparent[:-1] == sparent[1:]
    ns_src = jnp.where(same, sperm[:-1], n + 5)     # OOB -> dropped
    next_sib = jnp.full((n,), -1, jnp.int32).at[ns_src].set(
        sperm[1:].astype(jnp.int32), mode="drop")

    # -- Euler tour successors -------------------------------------------
    # slots: down_i = i for i in [0..n] (n = root), up_i = (n+1) + i
    s = 2 * (n + 1)
    up = n + 1
    v = jnp.arange(n + 1, dtype=jnp.int32)         # vertex ids incl. root
    fc = first_child[v]                            # [n+1]
    succ_down = jnp.where(fc >= 0, fc, up + v)
    ns = jnp.concatenate([next_sib, jnp.full((1,), -1, jnp.int32)])  # root
    par = jnp.concatenate(
        [parent.astype(jnp.int32), jnp.full((1,), root, jnp.int32)])
    succ_up = jnp.where(ns[v] >= 0, ns[v], up + par[v])
    succ_up = succ_up.at[root].set(up + root)      # terminal self-loop
    # parked vertices: self-loop both slots so they never rank
    parked_v = par[v] == parked
    succ_down = jnp.where(parked_v, v, succ_down)
    succ_up = jnp.where(parked_v, up + v, succ_up)
    succ = jnp.concatenate([succ_down, succ_up])   # [s]

    # -- Wyllie pointer-doubling list rank --------------------------------
    slot = jnp.arange(s, dtype=jnp.int32)
    dist = (succ != slot).astype(jnp.int32)
    steps = max(1, (s - 1).bit_length())

    def body(_, c):
        d, nx = c
        return d + d[nx], nx[nx]

    dist, fin = lax.fori_loop(0, steps, body, (dist, succ))
    # After >= log2(s) doublings every chain has collapsed onto its
    # terminal self-loop, so fin[x] is the chain's terminal: only
    # vertices whose tour actually ends at up_root are in the document
    # (a vertex under a parked/unresolvable ancestor terminates at that
    # ancestor's up-slot instead — excluded, with its whole subtree).
    vv = jnp.arange(n, dtype=jnp.int32)
    rank = dist[root] - dist[vv]
    reachable = (
        valid & (parent != parked)
        & (fin[vv] == up + root))
    rank = jnp.where(reachable, rank, _I32MAX)
    # subtree size: the tour walks 2*size-1 steps from down(v) to up(v)
    subtree = jnp.where(
        reachable, (dist[vv] - dist[up + vv] + 1) // 2, 0
    ).astype(jnp.int32)

    # -- tombstones -------------------------------------------------------
    duid = pack_uid(del_lamport, del_actor, actor_bits)
    dpos = jnp.searchsorted(sorted_uid, duid)
    dcpos = jnp.clip(dpos, 0, n - 1)
    dhit = del_valid & (dpos < n) & (sorted_uid[dcpos] == duid)
    tgt = jnp.where(dhit, by_uid[dcpos], n)        # OOB -> dropped
    deleted = jnp.zeros((n,), bool).at[tgt].set(True, mode="drop")
    visible = reachable & ~deleted

    # -- materialized document -------------------------------------------
    key = jnp.where(visible, rank, _I32MAX)
    doc_perm = jnp.argsort(key)
    doc = jnp.where(
        visible[doc_perm], elem[doc_perm].astype(jnp.int32), -1)
    return dict(doc=doc, n_visible=jnp.sum(visible).astype(jnp.int32),
                rank=rank, visible=visible, reachable=reachable,
                deleted=deleted, subtree=subtree, parent=parent, uid=uid)


@kernel_span("mat.rga")
@partial(jax.jit, static_argnames=("actor_bits",))
def rga_merge(
    ins_lamport: jax.Array,  # int32[N] lamport of inserted vertex
    ins_actor: jax.Array,    # int32[N] actor (origin DC) of vertex
    ref_lamport: jax.Array,  # int32[N] lamport of left-neighbour ref (0=head)
    ref_actor: jax.Array,    # int32[N] actor of ref
    elem: jax.Array,         # int32[N] interned payload token
    valid: jax.Array,        # bool[N]
    del_lamport: jax.Array,  # int32[M] delete targets
    del_actor: jax.Array,    # int32[M]
    del_valid: jax.Array,    # bool[M]
    actor_bits: int = 8,
):
    """Merge a full RGA op log in one shot.

    Returns ``(doc, n_visible, rank, visible)``:
    - ``doc``: int32[N] — ``elem`` of visible vertices in document order,
      padded with -1;
    - ``n_visible``: int32 scalar;
    - ``rank``: int32[N] preorder position of every vertex (1-based;
      padding lanes get huge ranks);
    - ``visible``: bool[N] — inserted, not tombstoned, not padding.
    """
    r = _merge_impl(ins_lamport, ins_actor, ref_lamport, ref_actor,
                    elem, valid, del_lamport, del_actor, del_valid,
                    actor_bits)
    return r["doc"], r["n_visible"], r["rank"], r["visible"]


@kernel_span("mat.rga")
@partial(jax.jit, static_argnames=("actor_bits",))
def rga_merge_full(ins_lamport, ins_actor, ref_lamport, ref_actor,
                   elem, valid, del_lamport, del_actor, del_valid,
                   actor_bits: int = 8):
    """:func:`rga_merge` variant for the incremental store's fold path
    (antidote_tpu/mat/rga_store.py).  Returns the full internals dict:
    ``rank`` (preorder, 1-based, tombstones ranked — they stay in the
    folded base as splice anchors), ``reachable``, ``deleted``,
    ``subtree`` sizes (preorder sub_end = rank-1 + size, the child-
    splice bound), ``visible``, ``doc``, ``n_visible``."""
    return _merge_impl(ins_lamport, ins_actor, ref_lamport, ref_actor,
                       elem, valid, del_lamport, del_actor, del_valid,
                       actor_bits)

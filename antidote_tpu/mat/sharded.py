"""Multi-chip sharded shard-store — the device-mesh ring.

The reference scales a DC by spreading vnodes over a riak_core ring of
Erlang nodes (SURVEY §2.7); the TPU rebuild scales by sharding ONE
shard-store over a ``jax.sharding.Mesh`` of chips: the key axis is
partitioned over the mesh's ``part`` axis, appends route to the owning
chip by key range, and the stable-time fold runs as an XLA collective
over ICI (the ``stable_time_functions:min_merge`` duty as a ``pmin``,
not a gossip of Erlang dicts).

Design (per "How to Scale Your Model" recipe: pick a mesh, annotate
shardings, let XLA insert collectives):

- **State**: one global shard state (e.g.
  :class:`~antidote_tpu.mat.store.OrsetShardState`) whose [K, ...] /
  [K*L, ...] arrays carry ``PartitionSpec("part")`` — contiguous key
  ranges per chip, the ring made literal.
- **Append**: the committed batch is replicated to every chip; each chip
  masks to its own key range and scatters locally (``shard_map``).  No
  all-to-all: for B ≪ K the duplicated decode is cheaper than routing,
  and every chip sees the batch anyway when it rides the replication
  stream.
- **GST fold**: each chip reduces its own applied frontier, then
  ``lax.pmin`` over ``part`` merges them — the cross-shard collective
  VERDICT/SURVEY name as the scaling hard-part — and the fold (GC) runs
  locally at the collective horizon.
- **Point reads**: each chip folds its own keys, foreign keys produce
  zeros, and a ``psum`` assembles the replicated result.

The recipe is type-agnostic: :class:`_ShardedBase` owns the mesh
bookkeeping, state sharding, and the collective GC (every shard state
exposes the same op_ss/op_dc/op_ct/valid2d/base_vc/has_base surface);
subclasses contribute only their store's append/read calls.

Exercised on the virtual 8-device CPU mesh by
tests/device/test_sharded_store.py and by the driver's
``dryrun_multichip``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from antidote_tpu.clocks import dense
from antidote_tpu.obs import prof
from antidote_tpu.runtime import COLLECTIVE_LOCK
from antidote_tpu.mat import ingest, store


class _ShardedBase:
    """Mesh bookkeeping + sharded state + collective GC, shared by the
    per-type stores.  ``n_keys`` must divide evenly by the mesh size;
    keys ``[i*K/n, (i+1)*K/n)`` live on chip i (contiguous ranges keep
    the ops rows aligned to shard boundaries: row = key*L + lane)."""

    #: the single-device store's GC fold for this state type
    _gc_fn = None
    #: names of state fields partitioned over the key axis (everything
    #: else — clock rows, scalars — replicates).  Explicit per class:
    #: a shape heuristic would misroute e.g. a [D] base_vc whenever
    #: n_dcs coincides with n_keys.
    _key_fields: frozenset = frozenset()
    #: the store's full-shard read (st, rv) -> key-sharded array
    _read_fn = None
    #: the store's point read (st, key_idx, rv) -> single [B, ...] array
    #: (tuple-returning reads like lww's need a bespoke override)
    _read_keys_fn = None
    #: the store's append; must accept ``active=`` (the this-chip's-keys
    #: filter: masked-off rows scatter nowhere and report no overflow)
    _append_store_fn = None

    def __init__(self, mesh: Mesh, n_keys: int, st,
                 ingest_settings: Optional[ingest.IngestSettings] = None):
        assert "part" in mesh.axis_names
        self.mesh = mesh
        self.n_shards = mesh.shape["part"]
        assert n_keys % self.n_shards == 0, (
            f"{n_keys} keys not divisible by {self.n_shards} shards")
        self.n_keys = n_keys
        self.keys_per_shard = n_keys // self.n_shards
        self.key_sh = NamedSharding(mesh, P("part"))
        self.rep = NamedSharding(mesh, P())
        #: coalesced-ingest knobs — built by the SAME factory the
        #: DevicePlane uses (ingest.ingest_from_config), so the mesh
        #: and single-shard assemblies honor identical knobs
        self.ingest = ingest_settings or ingest.ingest_from_config(None)
        self.st = self._shard_state(st)
        self._jits = {}

    # ------------------------------------------------------------ specs

    def _field_spec(self, name: str):
        return P("part") if name in self._key_fields else P()

    def _shard_state(self, st):
        data = {
            f.name: jax.device_put(
                getattr(st, f.name),
                NamedSharding(self.mesh, self._field_spec(f.name)))
            for f in dataclasses.fields(st) if f.name != "n_lanes"
        }
        return type(st)(**data, n_lanes=st.n_lanes)

    @property
    def _state_spec(self):
        data = {
            f.name: self._field_spec(f.name)
            for f in dataclasses.fields(self.st) if f.name != "n_lanes"
        }
        return type(self.st)(**data, n_lanes=self.st.n_lanes)

    def _sm(self, fn, in_specs, out_specs, donate: bool = False):
        key = fn.__name__
        if key not in self._jits:
            # kernel-span wrapped (obs/prof.py): multi-chip dispatches
            # and their compile misses show up per collective entry
            # point in /debug/prof and the KERNEL_* metrics
            from antidote_tpu.runtime import shard_map_compat

            self._jits[key] = prof.profiler.wrap(jax.jit(
                shard_map_compat(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False),
                # state-updating entries alias the multi-hundred-MB ops
                # tensor in place, like the single-device store's
                # donate_argnums (an inner donation is ignored under an
                # outer trace)
                donate_argnums=(0,) if donate else ()),
                name=f"sharded_{key.lstrip('_')}", subsystem="mat.sharded")
        return self._jits[key]

    def _rep_put(self, *arrays):
        return tuple(
            jax.device_put(jnp.asarray(a), self.rep) for a in arrays)

    def _local_mask(self, key_idx):
        """(local_idx, mine) for a replicated batch of GLOBAL keys in a
        shard_map body."""
        kps = self.keys_per_shard
        shard = jax.lax.axis_index("part")
        local = key_idx - shard.astype(key_idx.dtype) * kps
        return local, (local >= 0) & (local < kps)

    # ------------------------------------------------------- stable fold

    def gc_collective(self, local_frontiers: Optional[jax.Array] = None
                      ) -> jax.Array:
        """Fold at the cross-shard stable horizon and return it.

        ``local_frontiers``: int[n_shards, D] per-shard applied
        frontiers (each shard's view of how far every origin's stream
        has applied — in the live DC this is the dependency gate's
        watermark row per partition).  None derives each shard's
        frontier from its own ring (max applied commit VC), which is
        exact in the closed single-stream setting.

        The horizon is ``pmin`` over shards — no key can still receive
        an op at-or-below every shard's applied frontier — computed ON
        DEVICE over the mesh (ICI), exactly the
        stable_time_functions:min_merge duty (reference
        src/stable_time_functions.erl:39-85)."""
        gc = type(self)._gc_fn
        if local_frontiers is None:
            def local_gc(st):
                cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
                valid3 = st.valid2d[..., None]
                frontier = jnp.max(
                    jnp.where(valid3, cvc, 0), axis=(0, 1))
                base = jnp.where(st.has_base, st.base_vc, 0)
                frontier = jnp.maximum(frontier, base)
                gst = jax.lax.pmin(frontier, "part")
                return gc(st, gst), gst

            fn = self._sm(local_gc, in_specs=(self._state_spec,),
                          out_specs=(self._state_spec, P()),
                          donate=True)
            with COLLECTIVE_LOCK:
                self.st, gst = fn(self.st)
            return gst

        def local_gc_given(st, fr):
            gst = jax.lax.pmin(fr[jax.lax.axis_index("part")], "part")
            return gc(st, gst), gst

        fn = self._sm(local_gc_given,
                      in_specs=(self._state_spec, P()),
                      out_specs=(self._state_spec, P()), donate=True)
        with COLLECTIVE_LOCK:
            self.st, gst = fn(self.st, *self._rep_put(local_frontiers))
        return gst

    # ----------------------------------------------------------- append

    def append(self, key_idx, lane_off, *payload) -> jax.Array:
        """Scatter a committed batch (GLOBAL key indices + the store's
        per-op payload columns); returns bool[B] overflow (a key's
        owning shard ran out of ring lanes)."""
        base = self
        ap = type(self)._append_store_fn

        def local_append(st, key_idx, lane_off, *payload):
            local, mine = base._local_mask(key_idx)
            st, overflow = ap(
                st, jnp.where(mine, local, base.keys_per_shard),
                lane_off, *payload, active=mine)
            # the active-mask contract keeps foreign lanes' overflow
            # False, so a max-reduce assembles the global view
            return st, jax.lax.pmax(overflow, "part")

        fn = self._sm(
            local_append,
            in_specs=(self._state_spec,) + (P(),) * (2 + len(payload)),
            out_specs=(self._state_spec, P()), donate=True)
        # the pmax over shards is a collective launch like the GC fold's
        # pmin — runtime.py's invariant ("every collective launch site
        # takes this lock") covers it too, or a threaded append racing a
        # locked GC still aborts inside the XLA runtime
        args = self._rep_put(key_idx, lane_off, *payload)
        with COLLECTIVE_LOCK, prof.annotate("sharded_append"):
            self.st, overflow = fn(self.st, *args)
        return overflow

    def append_packed(self, packed, n_ops: Optional[int] = None
                      ) -> jax.Array:
        """Coalesced-ingest form of :meth:`append`: ONE replicated
        upload of the packed ``int64[B, 2+F]`` tensor (mat/ingest.py
        layout — [global key, lane_off, <ops-row columns>]) instead of
        one per payload column; each chip splits the index columns and
        masks to its own key range.  Same overflow contract."""
        base = self

        def local_append_packed(st, packed):
            key_idx, lane_off, rows = ingest.split_packed(
                packed, st.ops.dtype)
            local, mine = base._local_mask(key_idx)
            st, overflow = store._scatter_rows(
                st, jnp.where(mine, local, base.keys_per_shard),
                lane_off, rows, active=mine)
            return st, jax.lax.pmax(overflow, "part")

        fn = self._sm(local_append_packed,
                      in_specs=(self._state_spec, P()),
                      out_specs=(self._state_spec, P()), donate=True)
        packed = np.asarray(packed, dtype=np.int64)
        (dev,) = self._rep_put(packed)
        with COLLECTIVE_LOCK, prof.annotate("sharded_append_packed"):
            self.st, overflow = fn(self.st, dev)
        if n_ops is None:
            # padding rows carry an out-of-range key (the pack_rows
            # drop sentinel): counting them would inflate the
            # ops-per-dispatch amortization gauge the benches gate on
            n_ops = int(np.sum(packed[:, 0] < self.n_keys))
        ingest.note_dispatch(n_ops, packed.nbytes)
        return overflow

    # ------------------------------------------------------------- reads

    def read(self, read_vc) -> jax.Array:
        """Full-shard materialization at ``read_vc`` (sharded by key)."""
        (rv,) = self._rep_put(read_vc)
        read = type(self)._read_fn

        def local_read(st, rv):
            return read(st, rv)

        fn = self._sm(local_read, in_specs=(self._state_spec, P()),
                      out_specs=P("part"))
        # sharded over the mesh: the dispatch launches a multi-chip
        # program and must serialize with collective launches (the
        # read itself has no cross-shard reduce, but an interleaved
        # launch against a running pmin/psum still trips the runtime)
        with COLLECTIVE_LOCK, prof.annotate("sharded_read"):
            return fn(self.st, rv)

    def read_keys(self, key_idx, read_vc) -> jax.Array:
        """Point reads for GLOBAL key indices, replicated to every chip
        (foreign shards contribute zeros; a psum assembles the
        answer — the mask broadcast adapts to the result rank)."""
        base = self
        read_keys = type(self)._read_keys_fn
        key_idx, rv = self._rep_put(key_idx, read_vc)

        def local_read_keys(st, key_idx, rv):
            local, mine = base._local_mask(key_idx)
            out = read_keys(st, jnp.where(mine, local, 0), rv)
            m = mine.reshape(mine.shape + (1,) * (out.ndim - 1))
            return jax.lax.psum(jnp.where(m, out, 0), "part")

        fn = self._sm(local_read_keys,
                      in_specs=(self._state_spec, P(), P()),
                      out_specs=P())
        # the psum assembling the replicated answer is a collective —
        # same serialization rule as append/gc (runtime.py invariant)
        with COLLECTIVE_LOCK, prof.annotate("sharded_read_keys"):
            return fn(self.st, key_idx, rv)


class ShardedOrsetStore(_ShardedBase):
    """An OR-Set store whose key space is partitioned over a mesh."""

    _gc_fn = staticmethod(store.orset_gc)
    _read_fn = staticmethod(store.orset_read)
    _read_keys_fn = staticmethod(store.orset_read_keys)
    _append_store_fn = staticmethod(store.orset_append)
    _key_fields = frozenset({"dots", "ops", "valid"})

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_slots: int, n_dcs: int, dtype=jnp.int64,
                 ingest_settings=None):
        # int64 default like the other public shard inits: op_ct/op_ss
        # columns carry epoch-µs timestamps, which silently truncate in
        # int32 (callers that bench int32 pass it explicitly)
        super().__init__(mesh, n_keys, store.orset_shard_init(
            n_keys, n_lanes, n_slots, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)


class ShardedCounterStore(_ShardedBase):
    """The counter shard over the same mesh ring — the shared recipe
    (ranges over ``part``, replicated batches masked to the owning
    chip, GST fold as cross-shard ``pmin``) with counter store calls."""

    _gc_fn = staticmethod(store.counter_gc)
    _read_fn = staticmethod(store.counter_read)
    _read_keys_fn = staticmethod(store.counter_read_keys)
    _append_store_fn = staticmethod(store.counter_append)
    _key_fields = frozenset({"value", "ops", "valid"})

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_dcs: int, dtype=jnp.int64, ingest_settings=None):
        super().__init__(mesh, n_keys, store.counter_shard_init(
            n_keys, n_lanes, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)



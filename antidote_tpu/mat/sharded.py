"""Multi-chip sharded shard-store — the device-mesh ring.

The reference scales a DC by spreading vnodes over a riak_core ring of
Erlang nodes (SURVEY §2.7); the TPU rebuild scales by sharding ONE
shard-store over a ``jax.sharding.Mesh`` of chips: the key axis is
partitioned over the mesh's ``part`` axis, appends route to the owning
chip by key range, and the stable-time fold runs as an XLA collective
over ICI (the ``stable_time_functions:min_merge`` duty as a ``pmin``,
not a gossip of Erlang dicts).

Design (per "How to Scale Your Model" recipe: pick a mesh, annotate
shardings, let XLA insert collectives):

- **Rule table, not per-class field sets.**  :data:`PARTITION_RULES`
  maps state-field names to partition specs (the t5x/fmengine
  ``match_partition_rules`` pattern): per-key tables and the key-major
  op rings carry ``PartitionSpec("part")``, the clock row and base
  flag replicate.  One table covers EVERY plane type the DevicePlane
  serves (orset/mvreg/flag, lww, rwset, set_go, counter) — and it is
  what :func:`place_state` uses to shard a live plane's state in
  place (DevicePlane.place_sharded).
- **Arbitrary keyspaces.**  ``n_keys`` pads up to the next mesh
  multiple; the padded tail keys are sentinel-masked (appends AND the
  packed ingest path refuse them, reads slice them off), so a 100-key
  space shards over 8 chips without the caller caring.
- **Append**: the committed batch is replicated to every chip; each
  chip masks to its own key range and scatters locally
  (``shard_map``).  No all-to-all: for B ≪ K the duplicated decode is
  cheaper than routing, and every chip sees the batch anyway when it
  rides the replication stream.
- **GST fold**: each chip reduces its own applied frontier, then
  ``lax.pmin`` over ``part`` merges them — the cross-shard collective
  VERDICT/SURVEY name as the scaling hard-part — and the fold (GC)
  runs locally at the collective horizon (:meth:`gc_collective`, or
  :meth:`gc_at` for the live node's gossiped horizon).
- **Point reads**: each chip folds its own keys, foreign keys produce
  zeros, and a ``psum`` assembles the replicated result.  MANY waiter
  groups batch into ONE mesh program (:meth:`read_keys_groups`): a
  serve-window drain costs O(1) dispatches, not O(groups) — the
  ``full_shard_read_ms`` 174-vs-74 fused gap from the hardware
  self-capture, closed at the serve plane.

Every multi-chip dispatch here runs under ``runtime.COLLECTIVE_LOCK``
(machine-enforced by tools/concurrency_lint.py's [collective-lock]
rule) and counts into the device plane's read-dispatch counter, so
the benches' O(1)-per-drain assertions see one number.

Exercised on the virtual 8-device CPU mesh by
tests/device/test_sharded_store.py and by the driver's
``dryrun_multichip``.
"""

from __future__ import annotations

import dataclasses
import re
import time
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from antidote_tpu import stats
from antidote_tpu.clocks import dense
from antidote_tpu.obs import prof
from antidote_tpu.runtime import COLLECTIVE_LOCK
from antidote_tpu.mat import ingest, store


# ---------------------------------------------------------------------------
# partition-spec rule table
#
# The t5x / fmengine `match_partition_rules` pattern: ordered (regex,
# PartitionSpec) pairs, first full match wins.  The table replaces the
# per-class _key_fields frozensets — ONE place answers "how does this
# state field shard" for every shard-state dataclass in mat/store.py,
# and the same table shards a live DevicePlane's arrays in place.

PARTITION_RULES: Tuple[Tuple[str, P], ...] = (
    # per-key element/dot tables and per-key scalars: axis 0 is the
    # key axis -> contiguous key ranges per chip (the ring literal)
    (r"dots|adds|rmvs|present|value", P("part")),
    (r"base_(ts|tie|val)", P("part")),
    # packed op rings are key-major [K*L, ...]: rows shard WITH their
    # keys (row = key*L + lane), keeping scatters chip-local
    (r"ops|valid", P("part")),
    # the clock row and base flag are tiny and every chip folds with
    # them -> replicate
    (r"base_vc|has_base", P()),
)


def match_partition_rules(name: str,
                          rules: Sequence[Tuple[str, P]] = PARTITION_RULES
                          ) -> P:
    """Partition spec for a shard-state field name (first full-regex
    match wins, like t5x's rule matcher).  Unmatched names are a
    programming error — a new state field must take a position on
    sharding, silently replicating it could hide an N-fold memory
    regression."""
    for pat, spec in rules:
        if re.fullmatch(pat, name):
            return spec
    raise KeyError(f"no partition rule for state field {name!r}")


def state_shardings(mesh: Mesh, st) -> dict:
    """{field: NamedSharding} for a shard-state dataclass per the rule
    table.  A key axis that does not divide the mesh falls back to
    replication for that field (defensive: the DevicePlane's
    capacities are powers of two and always divide; hand-built states
    may not — replication is correct, just not distributed)."""
    n = mesh.shape["part"]
    out = {}
    for f in dataclasses.fields(st):
        if f.name == "n_lanes":
            continue
        spec = match_partition_rules(f.name)
        a = getattr(st, f.name)
        if spec == P("part") and (getattr(a, "ndim", 0) == 0
                                  or a.shape[0] % n):
            spec = P()
        out[f.name] = NamedSharding(mesh, spec)
    return out


def place_state(mesh: Mesh, st):
    """Re-place a shard state's arrays per the rule table (idempotent:
    device_put to an identical sharding is a no-op).  The live plane
    calls this after every flush/GC/grow so GSPMD output-sharding
    drift can never accumulate."""
    data = {name: jax.device_put(getattr(st, name), sh)
            for name, sh in state_shardings(mesh, st).items()}
    return type(st)(**data, n_lanes=st.n_lanes)


class _ShardedBase:
    """Mesh bookkeeping + sharded state + collective GC, shared by the
    per-type stores.  ``n_keys`` is padded up to the next mesh
    multiple; keys ``[i*K/n, (i+1)*K/n)`` live on chip i (contiguous
    ranges keep the ops rows aligned to shard boundaries:
    row = key*L + lane).  Padded tail keys (``n_keys_logical`` ≤ k <
    ``n_keys``) are sentinel-masked: appends refuse them, reads slice
    them off, and their lanes stay invalid forever so the GC fold
    ignores them."""

    #: the single-device store's GC fold for this state type
    _gc_fn = None
    #: the store's full-shard read (st, rv) -> key-sharded array pytree
    _read_fn = None
    #: the store's point read (st, key_idx, rv) -> [B, ...] array
    #: pytree (tuple-returning reads like lww's assemble generically
    #: via tree_map — no bespoke override needed)
    _read_keys_fn = None
    #: the store's append; must accept ``active=`` (the this-chip's-
    #: keys filter: masked-off rows scatter nowhere, no overflow)
    _append_store_fn = None

    def __init__(self, mesh: Mesh, n_keys: int, st,
                 ingest_settings: Optional[ingest.IngestSettings] = None):
        assert "part" in mesh.axis_names
        self.mesh = mesh
        self.n_shards = mesh.shape["part"]
        #: caller-visible keyspace; ``n_keys`` below is the padded
        #: device capacity (next mesh multiple)
        self.n_keys_logical = n_keys
        self.n_keys = n_keys + (-n_keys) % self.n_shards
        self.keys_per_shard = self.n_keys // self.n_shards
        self.key_sh = NamedSharding(mesh, P("part"))
        self.rep = NamedSharding(mesh, P())
        #: coalesced-ingest knobs — built by the SAME factory the
        #: DevicePlane uses (ingest.ingest_from_config), so the mesh
        #: and single-shard assemblies honor identical knobs
        self.ingest = ingest_settings or ingest.ingest_from_config(None)
        self.st = self._shard_state(self._pad_state(st))
        self._jits = {}

    # ------------------------------------------------------------ specs

    def _field_spec(self, name: str):
        return match_partition_rules(name)

    def _pad_state(self, st):
        """Zero-pad every key-sharded field's leading axis from the
        logical keyspace to the mesh multiple.  Zeros are the masked
        sentinel everywhere: padded lanes are ``valid=False`` (never
        folded), padded base rows never read (reads slice to the
        logical keyspace first)."""
        logical, padded = self.n_keys_logical, self.n_keys
        if padded == logical:
            return st
        data = {}
        for f in dataclasses.fields(st):
            if f.name == "n_lanes":
                continue
            a = getattr(st, f.name)
            if match_partition_rules(f.name) == P("part"):
                mult = a.shape[0] // logical  # 1 for [K,...], L for [K*L,...]
                assert a.shape[0] == logical * mult, (
                    f"{f.name}: axis 0 = {a.shape[0]} is not a "
                    f"multiple of n_keys = {logical}")
                pad = jnp.zeros(((padded - logical) * mult,)
                                + a.shape[1:], dtype=a.dtype)
                a = jnp.concatenate([a, pad], axis=0)
            data[f.name] = a
        return type(st)(**data, n_lanes=st.n_lanes)

    def _shard_state(self, st):
        data = {
            f.name: jax.device_put(
                getattr(st, f.name),
                NamedSharding(self.mesh, self._field_spec(f.name)))
            for f in dataclasses.fields(st) if f.name != "n_lanes"
        }
        return type(st)(**data, n_lanes=st.n_lanes)

    @property
    def _state_spec(self):
        data = {
            f.name: self._field_spec(f.name)
            for f in dataclasses.fields(self.st) if f.name != "n_lanes"
        }
        return type(self.st)(**data, n_lanes=self.st.n_lanes)

    def _sm(self, fn, in_specs, out_specs, donate: bool = False):
        key = fn.__name__
        if key not in self._jits:
            # kernel-span wrapped (obs/prof.py): multi-chip dispatches
            # and their compile misses show up per collective entry
            # point in /debug/prof and the KERNEL_* metrics
            from antidote_tpu.runtime import shard_map_compat

            self._jits[key] = prof.profiler.wrap(jax.jit(
                shard_map_compat(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False),
                # state-updating entries alias the multi-hundred-MB ops
                # tensor in place, like the single-device store's
                # donate_argnums (an inner donation is ignored under an
                # outer trace)
                donate_argnums=(0,) if donate else ()),
                name=f"sharded_{key.lstrip('_')}", subsystem="mat.sharded")
        return self._jits[key]

    def _rep_put(self, *arrays):
        return tuple(
            jax.device_put(jnp.asarray(a), self.rep) for a in arrays)

    def _local_mask(self, key_idx):
        """(local_idx, mine) for a replicated batch of GLOBAL keys in a
        shard_map body."""
        kps = self.keys_per_shard
        shard = jax.lax.axis_index("part")
        local = key_idx - shard.astype(key_idx.dtype) * kps
        return local, (local >= 0) & (local < kps)

    def _active_mask(self, key_idx):
        """:meth:`_local_mask` plus the padded-tail sentinel: the
        pack_rows drop sentinel (key == logical capacity) and any
        padded tail key can land INSIDE the last shard's range, so
        appends must also refuse keys at/above the logical keyspace
        — without this, a padding row would scatter a bogus valid op
        into a tail key and poison the derived GC frontier."""
        local, mine = self._local_mask(key_idx)
        return local, mine & (key_idx < self.n_keys_logical)

    def _note_collective(self, t0: float) -> None:
        stats.registry.shard_collective_seconds.inc(
            time.perf_counter() - t0)

    # ------------------------------------------------------- stable fold

    def gc_collective(self, local_frontiers: Optional[jax.Array] = None
                      ) -> jax.Array:
        """Fold at the cross-shard stable horizon and return it.

        ``local_frontiers``: int[n_shards, D] per-shard applied
        frontiers (each shard's view of how far every origin's stream
        has applied — in the live DC this is the dependency gate's
        watermark row per partition).  None derives each shard's
        frontier from its own ring (max applied commit VC), which is
        exact in the closed single-stream setting — but note an IDLE
        shard (no valid ops, no base; any padded tail makes the last
        shard permanently idle for derived frontiers once its real
        keys drain) reports frontier 0 and pins the pmin; live
        callers pass explicit frontiers (:meth:`gc_at`).

        The horizon is ``pmin`` over shards — no key can still receive
        an op at-or-below every shard's applied frontier — computed ON
        DEVICE over the mesh (ICI), exactly the
        stable_time_functions:min_merge duty (reference
        src/stable_time_functions.erl:39-85)."""
        gc = type(self)._gc_fn
        t0 = time.perf_counter()
        if local_frontiers is None:
            def local_gc(st):
                cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
                valid3 = st.valid2d[..., None]
                frontier = jnp.max(
                    jnp.where(valid3, cvc, 0), axis=(0, 1))
                base = jnp.where(st.has_base, st.base_vc, 0)
                frontier = jnp.maximum(frontier, base)
                gst = jax.lax.pmin(frontier, "part")
                return gc(st, gst), gst

            fn = self._sm(local_gc, in_specs=(self._state_spec,),
                          out_specs=(self._state_spec, P()),
                          donate=True)
            with COLLECTIVE_LOCK:
                self.st, gst = fn(self.st)
            self._note_collective(t0)
            return gst

        def local_gc_given(st, fr):
            gst = jax.lax.pmin(fr[jax.lax.axis_index("part")], "part")
            return gc(st, gst), gst

        fn = self._sm(local_gc_given,
                      in_specs=(self._state_spec, P()),
                      out_specs=(self._state_spec, P()), donate=True)
        with COLLECTIVE_LOCK:
            self.st, gst = fn(self.st, *self._rep_put(local_frontiers))
        self._note_collective(t0)
        return gst

    def gc_at(self, frontier) -> jax.Array:
        """Fold at an EXPLICIT stable horizon (dense int[D] — the live
        node's gossiped GST): every shard gets the same frontier, so
        the pmin is the identity and an idle/padded tail shard cannot
        pin the horizon at 0."""
        fr = np.tile(np.asarray(frontier, dtype=np.int64).reshape(1, -1),
                     (self.n_shards, 1))
        return self.gc_collective(fr)

    # ----------------------------------------------------------- append

    def append(self, key_idx, lane_off, *payload) -> jax.Array:
        """Scatter a committed batch (GLOBAL key indices + the store's
        per-op payload columns); returns bool[B] overflow (a key's
        owning shard ran out of ring lanes)."""
        base = self
        ap = type(self)._append_store_fn

        def local_append(st, key_idx, lane_off, *payload):
            local, mine = base._active_mask(key_idx)
            st, overflow = ap(
                st, jnp.where(mine, local, base.keys_per_shard),
                lane_off, *payload, active=mine)
            # the active-mask contract keeps foreign lanes' overflow
            # False, so a max-reduce assembles the global view
            return st, jax.lax.pmax(overflow, "part")

        fn = self._sm(
            local_append,
            in_specs=(self._state_spec,) + (P(),) * (2 + len(payload)),
            out_specs=(self._state_spec, P()), donate=True)
        # the pmax over shards is a collective launch like the GC fold's
        # pmin — runtime.py's invariant ("every collective launch site
        # takes this lock") covers it too, or a threaded append racing a
        # locked GC still aborts inside the XLA runtime
        args = self._rep_put(key_idx, lane_off, *payload)
        t0 = time.perf_counter()
        with COLLECTIVE_LOCK, prof.annotate("sharded_append"):
            self.st, overflow = fn(self.st, *args)
        self._note_collective(t0)
        return overflow

    def append_packed(self, packed, n_ops: Optional[int] = None
                      ) -> jax.Array:
        """Coalesced-ingest form of :meth:`append`: ONE replicated
        upload of the packed ``int64[B, 2+F]`` tensor (mat/ingest.py
        layout — [global key, lane_off, <ops-row columns>]) instead of
        one per payload column; each chip splits the index columns and
        masks to its own key range.  Same overflow contract."""
        base = self

        def local_append_packed(st, packed):
            key_idx, lane_off, rows = ingest.split_packed(
                packed, st.ops.dtype)
            local, mine = base._active_mask(key_idx)
            st, overflow = store._scatter_rows(
                st, jnp.where(mine, local, base.keys_per_shard),
                lane_off, rows, active=mine)
            return st, jax.lax.pmax(overflow, "part")

        fn = self._sm(local_append_packed,
                      in_specs=(self._state_spec, P()),
                      out_specs=(self._state_spec, P()), donate=True)
        packed = np.asarray(packed, dtype=np.int64)
        (dev,) = self._rep_put(packed)
        t0 = time.perf_counter()
        with COLLECTIVE_LOCK, prof.annotate("sharded_append_packed"):
            self.st, overflow = fn(self.st, dev)
        self._note_collective(t0)
        if n_ops is None:
            # padding rows carry an out-of-range key (the pack_rows
            # drop sentinel — and any padded tail key counts as
            # padding too): counting them would inflate the
            # ops-per-dispatch amortization gauge the benches gate on
            n_ops = int(np.sum(packed[:, 0] < self.n_keys_logical))
        # the upload replicates to every chip: account the real H2D
        ingest.note_dispatch(n_ops, packed.nbytes,
                             replicas=self.n_shards)
        return overflow

    # ------------------------------------------------------------- reads

    def read(self, read_vc):
        """Full-shard materialization at ``read_vc`` (sharded by key;
        a padded keyspace comes back host-side, sliced to the logical
        keys)."""
        from antidote_tpu.mat import device_plane as _dp

        (rv,) = self._rep_put(read_vc)
        read = type(self)._read_fn

        def local_read(st, rv):
            return read(st, rv)

        fn = self._sm(local_read, in_specs=(self._state_spec, P()),
                      out_specs=P("part"))
        # sharded over the mesh: the dispatch launches a multi-chip
        # program and must serialize with collective launches (the
        # read itself has no cross-shard reduce, but an interleaved
        # launch against a running pmin/psum still trips the runtime)
        _dp.count_read_dispatch()
        t0 = time.perf_counter()
        with COLLECTIVE_LOCK, prof.annotate("sharded_read"):
            out = fn(self.st, rv)
        self._note_collective(t0)
        if self.n_keys != self.n_keys_logical:
            out = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[:self.n_keys_logical], out)
        return out

    def _local_read_keys_body(self):
        """shard_map body for masked point reads: fold local keys,
        zero the foreign (and padded) ones — generically over the
        store's result pytree, so tuple reads (lww's (ts, tie, val),
        rwset's (adds, rmvs)) assemble without bespoke overrides.
        Booleans promote to ints under the zero-select exactly like
        the historical single-array path, so results are
        bit-compatible."""
        base = self
        read_keys = type(self)._read_keys_fn

        def masked(st, key_idx, rv, ok):
            local, mine = base._local_mask(key_idx)
            mine = mine & ok
            out = read_keys(st, jnp.where(mine, local, 0), rv)

            def zero_foreign(o):
                m = mine.reshape(mine.shape + (1,) * (o.ndim - 1))
                return jnp.where(m, o, 0)

            return jax.tree_util.tree_map(zero_foreign, out)

        return masked

    def read_keys(self, key_idx, read_vc):
        """Point reads for GLOBAL key indices, replicated to every chip
        (foreign shards contribute zeros; a psum assembles the
        answer — the mask broadcast adapts to the result rank)."""
        from antidote_tpu.mat import device_plane as _dp

        masked = self._local_read_keys_body()
        key_idx, rv = self._rep_put(key_idx, read_vc)

        def local_read_keys(st, key_idx, rv):
            out = masked(st, key_idx, rv,
                         jnp.ones(key_idx.shape, dtype=bool))
            return jax.tree_util.tree_map(
                lambda o: jax.lax.psum(o, "part"), out)

        fn = self._sm(local_read_keys,
                      in_specs=(self._state_spec, P(), P()),
                      out_specs=P())
        # the psum assembling the replicated answer is a collective —
        # same serialization rule as append/gc (runtime.py invariant)
        _dp.count_read_dispatch()
        t0 = time.perf_counter()
        with COLLECTIVE_LOCK, prof.annotate("sharded_read_keys"):
            out = fn(self.st, key_idx, rv)
        self._note_collective(t0)
        return out

    def read_keys_groups(self, groups: Sequence[Tuple[Any, Any]]
                         ) -> List[Any]:
        """Serve MANY waiter groups' point reads as ONE mesh program:
        ``groups`` is [(key_idx[B_g], read_vc[D])], the whole drain's
        worth of snapshot groups; the result list matches order, each
        entry the group's assembled [B_g, ...] pytree.

        The groups stack into [G, B] keys / [G, D] snapshots / [G, B]
        validity (ragged groups pad with masked rows), the per-group
        masked fold vmaps over G, and a single psum assembles every
        group at once — a drain costs O(1) dispatches instead of
        O(groups), the serve-plane mirror of the ingest plane's
        one-upload economy."""
        from antidote_tpu.mat import device_plane as _dp

        if not groups:
            return []
        G = len(groups)
        B = max(1, max(len(np.atleast_1d(k)) for k, _ in groups))
        D = len(np.atleast_1d(groups[0][1]))
        keys = np.zeros((G, B), dtype=np.int64)
        vcs = np.zeros((G, D), dtype=np.int64)
        ok = np.zeros((G, B), dtype=bool)
        for g, (k, rv) in enumerate(groups):
            k = np.atleast_1d(np.asarray(k))
            keys[g, :len(k)] = k
            ok[g, :len(k)] = True
            vcs[g] = np.asarray(rv)
        masked = self._local_read_keys_body()

        def local_read_groups(st, keys, vcs, ok):
            outs = jax.vmap(masked, in_axes=(None, 0, 0, 0))(
                st, keys, vcs, ok)
            return jax.tree_util.tree_map(
                lambda o: jax.lax.psum(o, "part"), outs)

        fn = self._sm(local_read_groups,
                      in_specs=(self._state_spec, P(), P(), P()),
                      out_specs=P())
        args = self._rep_put(keys, vcs, ok)
        _dp.count_read_dispatch()
        stats.registry.shard_fused_group_dispatches.inc()
        t0 = time.perf_counter()
        with COLLECTIVE_LOCK, prof.annotate("sharded_read_groups"):
            out = fn(self.st, *args)
        self._note_collective(t0)
        out = jax.tree_util.tree_map(np.asarray, out)
        return [
            jax.tree_util.tree_map(
                lambda o, _g=g: o[_g, :len(np.atleast_1d(groups[_g][0]))],
                out)
            for g in range(G)
        ]


class ShardedOrsetStore(_ShardedBase):
    """An OR-Set store whose key space is partitioned over a mesh."""

    _gc_fn = staticmethod(store.orset_gc)
    _read_fn = staticmethod(store.orset_read)
    _read_keys_fn = staticmethod(store.orset_read_keys)
    _append_store_fn = staticmethod(store.orset_append)

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_slots: int, n_dcs: int, dtype=jnp.int64,
                 ingest_settings=None):
        # int64 default like the other public shard inits: op_ct/op_ss
        # columns carry epoch-µs timestamps, which silently truncate in
        # int32 (callers that bench int32 pass it explicitly)
        super().__init__(mesh, n_keys, store.orset_shard_init(
            n_keys, n_lanes, n_slots, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)


class ShardedMvregStore(_ShardedBase):
    """Multi-value register over the mesh ring — shares the orset
    shard state (dot tables ARE the winner set) with the mvreg
    fold/read calls; flag_ew rides the same store (a flag is an mvreg
    of booleans at the plane layer)."""

    _gc_fn = staticmethod(store.mvreg_gc)
    _read_fn = staticmethod(store.mvreg_read)
    _read_keys_fn = staticmethod(store.mvreg_read_keys)
    _append_store_fn = staticmethod(store.orset_append)

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_slots: int, n_dcs: int, dtype=jnp.int64,
                 ingest_settings=None):
        super().__init__(mesh, n_keys, store.orset_shard_init(
            n_keys, n_lanes, n_slots, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)


class ShardedLwwStore(_ShardedBase):
    """Last-writer-wins register shard over the mesh; the tuple read
    ((ts, tie, val) per key) assembles generically through the
    tree_map'd psum."""

    _gc_fn = staticmethod(store.lww_gc)
    _read_fn = staticmethod(store.lww_read)
    _read_keys_fn = staticmethod(store.lww_read_keys)
    _append_store_fn = staticmethod(store.lww_append)

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_dcs: int, dtype=jnp.int64, ingest_settings=None):
        super().__init__(mesh, n_keys, store.lww_shard_init(
            n_keys, n_lanes, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)


class ShardedRwsetStore(_ShardedBase):
    """Remove-wins set shard over the mesh (adds/rmvs dot tables both
    key-sharded by the rule table; the (adds, rmvs) tuple read
    assembles like lww's)."""

    _gc_fn = staticmethod(store.rwset_gc)
    _read_fn = staticmethod(store.rwset_read)
    _read_keys_fn = staticmethod(store.rwset_read_keys)
    _append_store_fn = staticmethod(store.rwset_append)

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_slots: int, n_dcs: int, dtype=jnp.int64,
                 ingest_settings=None):
        super().__init__(mesh, n_keys, store.rwset_shard_init(
            n_keys, n_lanes, n_slots, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)


class ShardedSetGoStore(_ShardedBase):
    """Grow-only set shard over the mesh (presence bitmap key-sharded;
    full-shard reads go through store.setgo_read, added with this
    module so every plane type the DevicePlane serves has the same
    read surface)."""

    _gc_fn = staticmethod(store.setgo_gc)
    _read_fn = staticmethod(store.setgo_read)
    _read_keys_fn = staticmethod(store.setgo_read_keys)
    _append_store_fn = staticmethod(store.setgo_append)

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_slots: int, n_dcs: int, dtype=jnp.int64,
                 ingest_settings=None):
        super().__init__(mesh, n_keys, store.setgo_shard_init(
            n_keys, n_lanes, n_slots, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)


class ShardedCounterStore(_ShardedBase):
    """The counter shard over the same mesh ring — the shared recipe
    (ranges over ``part``, replicated batches masked to the owning
    chip, GST fold as cross-shard ``pmin``) with counter store calls."""

    _gc_fn = staticmethod(store.counter_gc)
    _read_fn = staticmethod(store.counter_read)
    _read_keys_fn = staticmethod(store.counter_read_keys)
    _append_store_fn = staticmethod(store.counter_append)

    def __init__(self, mesh: Mesh, n_keys: int, n_lanes: int,
                 n_dcs: int, dtype=jnp.int64, ingest_settings=None):
        super().__init__(mesh, n_keys, store.counter_shard_init(
            n_keys, n_lanes, n_dcs, dtype=dtype),
            ingest_settings=ingest_settings)


#: plane type -> sharded store class, the same keyspace the
#: DevicePlane serves (flag_ew shares mvreg's state and fold; flag_dw
#: is an rwset of one element at the plane layer; counter_pn is the
#: counter shard).  Maps and RGA stay host-composed: their device
#: residency is per-field sub-planes, which shard individually.
SHARDED_STORES = {
    "set_aw": ShardedOrsetStore,
    "register_mv": ShardedMvregStore,
    "flag_ew": ShardedMvregStore,
    "flag_dw": ShardedRwsetStore,
    "register_lww": ShardedLwwStore,
    "set_rw": ShardedRwsetStore,
    "set_go": ShardedSetGoStore,
    "counter_pn": ShardedCounterStore,
}


# ---------------------------------------------------------------------------
# factory + routing


@dataclass(frozen=True)
class ShardSettings:
    """Resolved pod-sharding knobs — built from Config by
    :func:`sharded_from_config` (the single factory, the
    gate_from_config / ingest_from_config lesson)."""

    #: mesh to shard the live DevicePlane over; None = single-chip
    #: legacy path (bit-for-bit the bench baseline)
    mesh: Optional[Mesh] = None
    axis: str = "part"

    @property
    def enabled(self) -> bool:
        return self.mesh is not None


def sharded_from_config(config) -> ShardSettings:
    """Resolve ``Config.mat_sharded`` (auto / True / False) to the
    node's shard mesh.  ``auto`` activates only with >1 device on a
    REAL accelerator backend: the virtual 8-device CPU mesh the tier-1
    suite runs under is a test rig, not a pod — auto-flipping there
    would silently re-route every existing test off the single-chip
    baseline.  ``True`` forces sharding wherever >1 device exists
    (how the CPU-mesh tests and benches opt in)."""
    knob = "auto" if config is None else getattr(config, "mat_sharded",
                                                 "auto")
    if knob is False:
        return ShardSettings()
    devs = jax.devices()
    if len(devs) < 2:
        return ShardSettings()
    if knob == "auto" and devs[0].platform == "cpu":
        return ShardSettings()
    return ShardSettings(mesh=Mesh(np.array(devs), ("part",)))


class ShardRouter:
    """Per-shard residency economy — the PR-3 host/device picker run
    per chip instead of per process.  Each shard's own overflow record
    decides whether NEW keys in its key range earn device residency:
    an eviction marks the owning shard saturated (new keys route
    host-side) until the next GC fold frees lanes and resets the
    economy.  Evictions migrate only the owning shard's keys — the
    other chips' residents are untouched."""

    def __init__(self, n_shards: int):
        self.n_shards = n_shards
        #: overflow evictions since the last fold, per shard (the
        #: saturation signal)
        self._overflow = [0] * n_shards
        #: lifetime evictions per shard (stats)
        self.evictions = [0] * n_shards

    def shard_of(self, idx: int, capacity: int) -> int:
        """Owning shard of key index ``idx`` under a contiguous
        P("part") layout of ``capacity`` keys."""
        kps = max(1, capacity // self.n_shards)
        return min(idx // kps, self.n_shards - 1)

    def note_evict(self, idx: int, capacity: int) -> None:
        s = self.shard_of(idx, capacity)
        self._overflow[s] += 1
        self.evictions[s] += 1
        stats.registry.shard_evictions.inc(shard=str(s))

    def note_fold(self) -> None:
        """A GC fold freed ring lanes everywhere: every shard's
        economy resets and saturated shards may earn residency
        again."""
        self._overflow = [0] * self.n_shards

    def admits(self, idx: int, capacity: int) -> bool:
        """May a NEW key at directory slot ``idx`` take device
        residency?  False while its owning shard is saturated
        (overflowed since the last fold) — the key serves host-side
        instead, exactly the per-process picker's economy at per-shard
        grain."""
        return self._overflow[self.shard_of(idx, capacity)] == 0

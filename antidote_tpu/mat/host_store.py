"""Host materializer store — per-key op lists + snapshot cache.

This is the latency path twin of the device shard store
(antidote_tpu/mat/store.py): transactions touch a handful of keys and
want µs reads, so those go through this in-process cache, while bulk
work (benchmarks, inter-DC apply floods) batches onto the device store.

Mirrors materializer_vnode's design (reference
src/materializer_vnode.erl): per key an op list and a small cache of
materialized snapshots; inserts trigger GC when the op list passes a
threshold (``?OPS_THRESHOLD`` 50); GC materializes at the current stable
time, keeps the newest ``?SNAPSHOT_MIN`` 3 snapshots once
``?SNAPSHOT_THRESHOLD`` 10 accumulate; a new snapshot is cached only if
>= ``?MIN_OP_STORE_SS`` 5 ops were applied (:36-47, 475-647).  Reads pick
the newest cached snapshot <= the read VC (vector_orddict:get_smaller,
src/vector_orddict.erl:74-87) and materialize forward; a miss falls back
to the log (:415-419).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.crdt import get_type
from antidote_tpu.mat.materializer import (
    MaterializedSnapshot,
    Payload,
    SnapshotGetResponse,
    materialize,
    materialize_eager,
    materialize_from_log,
)

OPS_THRESHOLD = 50
SNAPSHOT_THRESHOLD = 10
SNAPSHOT_MIN = 3
MIN_OP_STORE_SS = 5


@dataclass
class _KeyEntry:
    key: Any
    type_name: str
    #: committed ops, newest first: (op_seq, Payload)
    ops: List[Tuple[int, Payload]] = field(default_factory=list)
    next_seq: int = 0
    #: cached snapshots, newest first: (vc or None, MaterializedSnapshot)
    snapshots: List[Tuple[Optional[VC], MaterializedSnapshot]] = field(
        default_factory=list)
    #: True once GC pruned ops: reads with no suitable cached snapshot can
    #: no longer be served from memory and must replay the log
    pruned: bool = False


class HostStore:
    """One partition's in-memory versioned store."""

    def __init__(self, log_fallback: Optional[Callable[..., list]] = None,
                 has_history: Optional[Callable[[Any], bool]] = None,
                 seed_source: Optional[Callable[[Any], Optional[tuple]]]
                 = None):
        #: key -> entry
        self._data: Dict[Any, _KeyEntry] = {}
        #: optional PartitionLog.committed_payloads for cache misses
        self._log_fallback = log_fallback
        #: optional O(1) "does this key have any logged history" probe —
        #: without it, a read of a never-written key scans the whole log
        #: just to find nothing, every time
        self._has_history = has_history
        #: optional PartitionLog.seed_for (ISSUE 10): the checkpoint's
        #: (type_name, state, frontier VC) base for a key — a cache-
        #: miss entry built from the log fallback starts from it, so
        #: the (possibly truncated) below-cut history never replays
        self._seed_source = seed_source

    def entry_count(self) -> int:
        return len(self._data)

    def seed_state(self, key, type_name: str, state,
                   vc: Optional[VC] = None,
                   base_op_id: Optional[int] = None) -> None:
        """Install a key whose ONLY content is a materialized snapshot
        — the unlogged-eviction migration path (ISSUE 9 satellite): a
        device plane dropping a key with no durable log to replay
        hands its pre-purge fold state here instead of zeroing the
        key.  Reads at clocks covering ``vc`` (the key's commit
        frontier at eviction) serve the state, and later inserts apply
        on top; reads strictly below it have no history to replay
        anywhere — they take the pruned->log path, which is empty by
        construction in unlogged mode.

        ``base_op_id`` (ISSUE 10 bootstrap): which existing ops the
        snapshot claims to contain.  The default (``e.next_seq``) says
        ALL of them — right when the state was folded from this
        replica's own history (eviction export, checkpoint seed at
        recovery).  A checkpoint-BOOTSTRAP seed from another DC passes
        0: local ops it never saw must re-apply on top, and the ones
        it did fold are replay-gated by the seed's VC
        (op_covered_by)."""
        e = self._data.get(key)
        if e is None:
            e = self._data[key] = _KeyEntry(key, type_name)
        elif e.type_name != type_name:
            raise ValueError(
                f"type mismatch for {key!r}: {e.type_name} vs {type_name}")
        snap = MaterializedSnapshot(
            last_op_id=(e.next_seq if base_op_id is None
                        else base_op_id), value=state)
        # an empty VC is <= every read clock, so a frontier-less seed
        # (key evicted before any publish — not reachable in practice)
        # still serves rather than vanishing behind _best_snapshot's
        # None-vc skip
        e.snapshots.insert(0, (vc if vc is not None else VC(), snap))
        e.pruned = True

    def apply_to_seed(self, key, type_name: str, effect) -> bool:
        """Apply one committed effect directly ONTO the newest seeded
        snapshot (the unlogged decode-reject bounce): the seed's VC
        already covers the op's commit entry — the key's frontier was
        joined before the device stage that rejected it — so inserting
        it as an ordinary op would be skipped by the replay as
        already-in-base.  Effects commute and the seed is the newest
        state, so folding it in is exact.  False when the key has no
        seeded snapshot (export failed): the caller inserts the op
        normally instead."""
        e = self._data.get(key)
        if e is None or not e.snapshots or e.type_name != type_name:
            return False
        vc, snap = e.snapshots[0]
        e.snapshots[0] = (vc, MaterializedSnapshot(
            snap.last_op_id,
            materialize_eager(type_name, snap.value, [effect])))
        return True

    def insert(self, key, type_name: str, payload: Payload,
               stable_vc: Optional[VC] = None) -> None:
        """Store a committed op (the reference's materializer_vnode:update,
        src/materializer_vnode.erl:104-110); GC when the op list is full."""
        e = self._data.get(key)
        if e is None:
            e = self._data[key] = _KeyEntry(key, type_name)
        elif e.type_name != type_name:
            raise ValueError(
                f"type mismatch for {key!r}: {e.type_name} vs {type_name}")
        e.next_seq += 1
        e.ops.insert(0, (e.next_seq, payload))
        if len(e.ops) >= OPS_THRESHOLD and stable_vc is not None:
            self._gc(e, stable_vc)

    def _gc(self, e: _KeyEntry, stable_vc: VC) -> None:
        """Materialize at the stable time, cache the snapshot, and prune
        ops fully covered by it (op_insert_gc/prune_ops semantics)."""
        self.read_entry(e, stable_vc, cache=True, force_cache=True)
        if len(e.snapshots) >= SNAPSHOT_THRESHOLD:
            e.snapshots = e.snapshots[:SNAPSHOT_MIN]
        # Prune against the OLDEST retained snapshot: every servable base
        # then already contains the pruned ops; reads below it take the
        # pruned->log-replay path.  (Pruning at the newest would starve
        # reads based at older retained snapshots.)
        oldest = next(
            (vc for vc, _s in reversed(e.snapshots) if vc is not None), None)
        if oldest is None:
            return
        kept = [(i, p) for i, p in e.ops if not p.commit_vc().le(oldest)]
        if len(kept) < len(e.ops):
            e.pruned = True
        e.ops = kept

    def read(self, key, type_name: str, read_vc: Optional[VC],
             txid=None) -> Tuple[Any, Optional[VC]]:
        """Value + snapshot VC of ``key`` at ``read_vc`` (None = latest)."""
        e = self._data.get(key)
        if e is None:
            e = _KeyEntry(key, type_name)
            seed = self._seed_source(key) if self._seed_source \
                is not None else None
            if seed is not None and seed[0] == type_name:
                # checkpoint base (ISSUE 10): the entry starts from
                # the folded state at the cut; the fallback below only
                # contributes the retained suffix, and any of its ops
                # the seed already folded are replay-gated by its VC
                e.snapshots.insert(
                    0, (seed[2], MaterializedSnapshot(0, seed[1])))
                e.pruned = True
            if self._log_fallback is not None and (
                    self._has_history is None or self._has_history(key)):
                for i, p in self._log_fallback(key=key):
                    e.next_seq += 1
                    e.ops.insert(0, (e.next_seq, p))
            if e.ops or e.snapshots:
                self._data[key] = e
            else:
                return get_type(type_name).new(), None
        return self.read_entry(e, read_vc, txid=txid)

    def read_entry(self, e: _KeyEntry, read_vc: Optional[VC], txid=None,
                   cache: bool = True, force_cache: bool = False):
        base_vc, base = self._best_snapshot(e, read_vc)
        if base_vc is None and e.pruned:
            # history below every cached snapshot was GC'd — replay the
            # log (reference get_from_snapshot_log,
            # src/materializer_vnode.erl:415-419).  A checkpoint-seeded
            # key forces the ASSEMBLING scan: its per-key index only
            # covers the suffix past the cut, and a read that landed
            # here was not based on the seed — the scan is exact while
            # the below-cut bytes remain (ISSUE 10)
            if self._log_fallback is None:
                raise LookupError(
                    "read below pruned history and no log fallback")
            seeded = (self._seed_source is not None
                      and self._seed_source(e.key) is not None)
            payloads = self._log_fallback(key=e.key, scan=True) \
                if seeded else self._log_fallback(key=e.key)
            res = materialize_from_log(e.type_name, payloads, read_vc,
                                       txid)
            return res.value, res.snapshot_vc
        resp = SnapshotGetResponse(
            snapshot_time=base_vc,
            ops=[(i, p) for i, p in e.ops if i > base.last_op_id],
            materialized=base)
        res = materialize(e.type_name, txid, read_vc, resp)
        if cache and res.is_new_snapshot and (
                force_cache or res.ops_applied >= MIN_OP_STORE_SS):
            self._cache_snapshot(
                e, res.snapshot_vc,
                MaterializedSnapshot(res.first_hole, res.value))
        return res.value, res.snapshot_vc

    def _best_snapshot(self, e: _KeyEntry, read_vc: Optional[VC]):
        """Newest cached snapshot <= read_vc (get_smaller semantics)."""
        for vc, snap in e.snapshots:
            if vc is None:
                continue
            if read_vc is None or vc.le(read_vc):
                return vc, snap
        return None, MaterializedSnapshot(
            last_op_id=0, value=get_type(e.type_name).new())

    def _cache_snapshot(self, e: _KeyEntry, vc: Optional[VC],
                        snap: MaterializedSnapshot) -> None:
        """Insert keeping newest-first order (vector_orddict:insert by
        all_dots_greater; ties/concurrent go after)."""
        if vc is None:
            return
        pos = 0
        for i, (svc, _s) in enumerate(e.snapshots):
            if svc is not None and svc.all_dots_greater(vc):
                pos = i + 1
            else:
                break
        e.snapshots.insert(pos, (vc, snap))

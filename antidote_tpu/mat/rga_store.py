"""Incremental RGA store — steady-state collaborative editing on device.

The one-shot kernel (antidote_tpu/mat/rga_kernel.py) re-merges the whole
op log per call: O(history) per edit burst, unusable for a living
document (the reference's RGA materializes incrementally inside its
gen_server; SURVEY §5.7 names the long-log case a first-class target).
This store splits the document into

- a **base**: the stable prefix, materialized once into a frozen
  preorder (uid, parent-uid, element, live flag, subtree extent), and
- a **window**: the unstable op tail, kept as dense op lanes.

Reads merge only the window — O(window · log) for the tree/rank work —
then splice each window subtree into the base by binary search and
assemble the document with one O(doc) sort.  Steady-state cost drops
from "re-run the full multi-round merge over all history" to "tiny
merge + one sort", and the fold (the only full-history pass) amortizes
over its GC cadence.  The splice is exact RGA order, not an
approximation: a window vertex anchored at base vertex V must sit among
V's already-folded children in uid-descending order, so the base keeps a
child-search index sorted by ``(parent_uid, uid desc)`` and the splice
position for a root with uid *u* is the preorder position of V's first
child with uid < u (else the end of V's subtree).  Sibling-order
correctness against folded siblings is exactly what naive
"append-after-anchor" schemes get wrong.

Folding (at a stability threshold, the GST analogue) runs the full
merge ONCE over base + newly-stable window ops — tombstones keep their
rows (they remain splice anchors) but drop their live flag — and
rebuilds the preorder/search arrays; the window compacts to its
unstable suffix.  Fold cost is O(doc) but amortized at GC cadence, like
the reference's ``?OPS_THRESHOLD`` materializer GC.

Stability gives the two invariants the split relies on (same GST
contract as the OR-Set store, mat/store.py):
- causal closure: a stable vertex's parent is stable (or base), so the
  stable set folds as whole subtrees hanging off the base;
- no stable op is still in flight, so folded positions are final.

All shapes are static (PB base rows, NW window lanes, MD delete lanes);
capacity growth is a host-side repack.  Window and delete lanes carry
FULL commit vector clocks (origin column, commit time, snapshot VC
columns), so a read materializes exactly the snapshot's inclusion set —
``op in snapshot iff commit_vc(op) <= read_vc`` (the reference
materializer rule, src/materializer.erl:101-106) — and the fold horizon
is the gossiped dense GST, the same contract as the OR-Set store
(mat/store.py orset_gc).  Reads below the folded base are the caller's
log-replay case (DevicePlane ReadBelowBase).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu.clocks import dense
from antidote_tpu.mat import ingest, rga_kernel
from antidote_tpu.obs.prof import kernel_span
from antidote_tpu.mat.rga_kernel import _I32MAX, pack_uid

_I64MAX = jnp.iinfo(jnp.int64).max


@dataclass
class RgaStoreState:
    """Device arrays for one RGA document (a pytree).

    Base rows sit in document preorder; ``bsort_*`` is the uid-sorted
    view for lookups and ``ckey/cpos`` the (parent, uid-desc) child
    index for splices.  ``actor_bits`` is the uid packing width."""

    # base, in preorder (padding rows: buid = _I32MAX)
    buid: jax.Array       # int32[PB] packed uids
    bparent: jax.Array    # int32[PB] parent uid (0 = document head)
    belem: jax.Array      # int32[PB]
    blive: jax.Array      # bool[PB] (False = tombstone kept as anchor)
    bsub_end: jax.Array   # int32[PB] preorder index one past the subtree
    bn: jax.Array         # int32[] used rows
    # uid-sorted base view
    bsort_uid: jax.Array  # int32[PB]
    bsort_pos: jax.Array  # int32[PB] preorder index of that uid
    # child-search index, sorted by packed (parent_uid, uid desc)
    ckey: jax.Array       # int64[PB]
    cpos: jax.Array       # int32[PB]
    # window op lanes
    wlam: jax.Array       # int32[NW]
    wact: jax.Array       # int32[NW]
    wrlam: jax.Array      # int32[NW] left-neighbour ref (0 = head)
    wract: jax.Array      # int32[NW]
    welem: jax.Array      # int32[NW]
    wdc: jax.Array        # int32[NW] origin DC column
    wct: jax.Array        # int64[NW] commit time
    wss: jax.Array        # int64[NW, D] snapshot VC columns
    wn: jax.Array         # int32[]
    # pending delete lanes
    dlam: jax.Array       # int32[MD]
    dact: jax.Array       # int32[MD]
    ddc: jax.Array        # int32[MD]
    dct: jax.Array        # int64[MD]
    dss: jax.Array        # int64[MD, D]
    dn: jax.Array         # int32[]
    actor_bits: int

    @property
    def pb(self) -> int:
        return self.buid.shape[0]

    @property
    def nw(self) -> int:
        return self.wlam.shape[0]

    @property
    def md(self) -> int:
        return self.dlam.shape[0]

    @property
    def d(self) -> int:
        return self.wss.shape[1]


jax.tree_util.register_dataclass(
    RgaStoreState,
    data_fields=["buid", "bparent", "belem", "blive", "bsub_end", "bn",
                 "bsort_uid", "bsort_pos", "ckey", "cpos",
                 "wlam", "wact", "wrlam", "wract", "welem",
                 "wdc", "wct", "wss", "wn",
                 "dlam", "dact", "ddc", "dct", "dss", "dn"],
    meta_fields=["actor_bits"],
)


def rga_store_init(pb: int, nw: int, md: int, n_dcs: int = 1,
                   actor_bits: int = 8) -> RgaStoreState:
    i32 = lambda shape, fill=0: jnp.full(shape, fill, jnp.int32)
    i64 = lambda shape, fill=0: jnp.full(shape, fill, jnp.int64)
    return RgaStoreState(
        buid=i32((pb,), _I32MAX), bparent=i32((pb,)), belem=i32((pb,)),
        blive=jnp.zeros((pb,), bool), bsub_end=i32((pb,)),
        bn=jnp.zeros((), jnp.int32),
        bsort_uid=i32((pb,), _I32MAX), bsort_pos=i32((pb,)),
        ckey=jnp.full((pb,), _I64MAX, jnp.int64), cpos=i32((pb,)),
        wlam=i32((nw,)), wact=i32((nw,)), wrlam=i32((nw,)),
        wract=i32((nw,)), welem=i32((nw,)),
        wdc=i32((nw,)), wct=i64((nw,)), wss=i64((nw, n_dcs)),
        wn=jnp.zeros((), jnp.int32),
        dlam=i32((md,)), dact=i32((md,)),
        ddc=i32((md,)), dct=i64((md,)), dss=i64((md, n_dcs)),
        dn=jnp.zeros((), jnp.int32),
        actor_bits=actor_bits,
    )


def _ckey_pack(parent_uid, uid):
    """int64 child-search key: (parent asc, uid desc)."""
    return ((parent_uid.astype(jnp.int64) << 32)
            | (jnp.int64(_I32MAX) - uid.astype(jnp.int64)))


@kernel_span("mat.rga")
@partial(jax.jit, donate_argnums=(0,))
def rga_append(st: RgaStoreState, ins_lamport, ins_actor, ref_lamport,
               ref_actor, elem, ins_dc, ins_ct, ins_ss,
               del_lamport, del_actor, del_dc, del_ct, del_ss,
               n_ins=None, n_del=None):
    """Append one op block (B insert lanes + C delete lanes) into the
    window, each lane carrying its full commit VC (origin column,
    commit time, snapshot columns).  Returns (state, ok) — ok=False
    means the window or delete lanes are full: the caller folds (or
    grows) and retries.

    ``n_ins``/``n_del`` are the LOGICAL lane counts when the arrays
    are padded to a dispatch bucket (rga_append_padded): the padded
    tail is written into the invalid region beyond wn/dn — masked by
    every fold/read and overwritten by the next append — while the
    counters advance by the logical counts only.  Without bucketing,
    every distinct (B, C) pair mints its own XLA program (measured
    ~0.45 s/block on CPU: the whole config-4 steady-state deficit)."""
    b = ins_lamport.shape[0]
    c = del_lamport.shape[0]
    nb = b if n_ins is None else n_ins
    nc = c if n_del is None else n_del
    # physical room for the PADDED block: the dynamic_update_slice
    # below would clamp its start (corrupting valid lanes) if the pad
    # overhung — refuse conservatively, the caller folds/grows
    ok = (st.wn + b <= st.nw) & (st.dn + c <= st.md)
    i32 = lambda a: a.astype(jnp.int32)
    i64 = lambda a: a.astype(jnp.int64)

    def put_at(dst, src, n, cast):
        zero = jnp.zeros((), n.dtype)
        start = (jnp.where(ok, n, zero),) + (zero,) * (dst.ndim - 1)
        upd = jax.lax.dynamic_update_slice(dst, cast(src), start)
        return jnp.where(ok, upd, dst)

    put = lambda dst, src: put_at(dst, src, st.wn, i32)
    put64 = lambda dst, src: put_at(dst, src, st.wn, i64)
    putd = lambda dst, src: put_at(dst, src, st.dn, i32)
    putd64 = lambda dst, src: put_at(dst, src, st.dn, i64)

    return replace(
        st,
        wlam=put(st.wlam, ins_lamport), wact=put(st.wact, ins_actor),
        wrlam=put(st.wrlam, ref_lamport), wract=put(st.wract, ref_actor),
        welem=put(st.welem, elem),
        wdc=put(st.wdc, ins_dc), wct=put64(st.wct, ins_ct),
        wss=put64(st.wss, ins_ss),
        wn=jnp.where(ok, st.wn + nb, st.wn),
        dlam=putd(st.dlam, del_lamport), dact=putd(st.dact, del_actor),
        ddc=putd(st.ddc, del_dc), dct=putd64(st.dct, del_ct),
        dss=putd64(st.dss, del_ss),
        dn=jnp.where(ok, st.dn + nc, st.dn),
    ), ok


def _append_bucket(n: int, floor: int = 64) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def rga_append_padded(st: RgaStoreState, ins_cols, del_cols,
                      floor: int = 64):
    """:func:`rga_append` with both lane blocks padded to power-of-two
    buckets and the logical counts passed through — callers whose
    block sizes vary per call (the live plane's per-commit groups, the
    bench's lamport-sliced deletes) compile a handful of programs
    instead of one per distinct size.  ``ins_cols``/``del_cols`` are
    the positional argument tuples of rga_append (host arrays)."""
    b = int(np.asarray(ins_cols[0]).shape[0])
    c = int(np.asarray(del_cols[0]).shape[0])
    bp, cp = _append_bucket(b, floor), _append_bucket(c, floor)

    def pad(a, n):
        a = np.asarray(a)
        if a.shape[0] == n:
            return jnp.asarray(a)
        w = [(0, n - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return jnp.asarray(np.pad(a, w))

    return rga_append(
        st, *(pad(a, bp) for a in ins_cols),
        *(pad(a, cp) for a in del_cols), n_ins=b, n_del=c)


#: packed-append column layout (shared by insert AND delete rows so
#: one [bp+cp, 7+D] tensor carries both sections): [lam, act, rlam,
#: ract, elem, dc, ct, ss(D)] — delete rows use the same lam/act/dc/
#: ct/ss positions and leave rlam/ract/elem zero
_PK_LAM, _PK_ACT, _PK_RLAM, _PK_RACT, _PK_ELEM, _PK_DC, _PK_CT, \
    _PK_NSCAL = 0, 1, 2, 3, 4, 5, 6, 7


@kernel_span("mat.rga")
@partial(jax.jit, donate_argnums=(0,), static_argnames=("bp",))
def rga_append_packed(st: RgaStoreState, packed, bp, n_ins, n_del):
    """:func:`rga_append` fed from ONE packed tensor: rows ``[:bp]``
    are the (padded) insert lanes, rows ``[bp:]`` the delete lanes,
    columns per ``_PK_*``.  The split is static (``bp`` is the insert
    bucket), so the upload that used to be 13 per-column transfers is
    a single H2D — the coalesced-ingest economy (mat/ingest.py) on the
    RGA steady window."""
    d = st.d
    i32 = lambda a: a.astype(jnp.int32)
    ins = packed[:bp]
    dl = packed[bp:]
    return rga_append(
        st,
        i32(ins[:, _PK_LAM]), i32(ins[:, _PK_ACT]),
        i32(ins[:, _PK_RLAM]), i32(ins[:, _PK_RACT]),
        i32(ins[:, _PK_ELEM]), i32(ins[:, _PK_DC]),
        ins[:, _PK_CT], ins[:, _PK_NSCAL:_PK_NSCAL + d],
        i32(dl[:, _PK_LAM]), i32(dl[:, _PK_ACT]), i32(dl[:, _PK_DC]),
        dl[:, _PK_CT], dl[:, _PK_NSCAL:_PK_NSCAL + d],
        n_ins=n_ins, n_del=n_del)


def rga_append_coalesced(st: RgaStoreState, ins_cols, del_cols,
                         floor: int = 64):
    """:func:`rga_append_padded`'s bucketing with the coalesced-ingest
    upload contract: both lane blocks pack into ONE host tensor and
    ONE H2D (vs 13 per-column uploads), counted in the INGEST_*
    metrics.  Same argument tuples and return as rga_append_padded —
    the legacy form stays as the benches' comparison baseline."""
    b = int(np.asarray(ins_cols[0]).shape[0])
    c = int(np.asarray(del_cols[0]).shape[0])
    bp, cp = _append_bucket(b, floor), _append_bucket(c, floor)
    d = st.d
    packed = np.zeros((bp + cp, _PK_NSCAL + d), dtype=np.int64)
    for j, a in enumerate(ins_cols[:_PK_NSCAL]):
        packed[:b, j] = np.asarray(a)
    packed[:b, _PK_NSCAL:] = np.asarray(ins_cols[_PK_NSCAL])
    dl = packed[bp:]
    for j, a in zip((_PK_LAM, _PK_ACT, _PK_DC, _PK_CT), del_cols[:4]):
        dl[:c, j] = np.asarray(a)
    dl[:c, _PK_NSCAL:] = np.asarray(del_cols[4])
    st, ok = rga_append_packed(st, jnp.asarray(packed), bp=bp,
                               n_ins=b, n_del=c)
    ingest.note_dispatch(b + c, packed.nbytes)
    return st, ok


def _included(ss, dc, ct, rv):
    """bool[N]: commit_vc(op) <= rv columnwise (the materializer
    inclusion rule over dense lanes)."""
    cvc = dense.commit_vc(ss, dc, ct)
    return jnp.all(cvc <= rv[None, :].astype(jnp.int64), axis=1)


@kernel_span("mat.rga")
@jax.jit
def rga_read(st: RgaStoreState, read_vc):
    """Materialize the full RGA state at dense snapshot ``read_vc``
    (int64[D]): merge the snapshot-included window forest and splice it
    into the base preorder.  Returns ``(lam, act, elem, vis, n)`` —
    int32[PB+NW] arrays in document order INCLUDING tombstones (vis
    False), n = number of present vertices — i.e. exactly the host
    oracle's state tuple (crdt/rga.py), so downstream generation can
    read this reconstruction (positions index visible vertices; lamport
    max ranges over all).  Requires read_vc >= the fold horizon (the
    caller's ReadBelowBase contract): every base row is in-snapshot by
    construction."""
    nw, pb = st.nw, st.pb
    bits = st.actor_bits
    lanes = jnp.arange(nw, dtype=jnp.int32)
    winc = _included(st.wss, st.wdc, st.wct, read_vc)
    in_window = (lanes < st.wn) & winc

    wuid = pack_uid(st.wlam, st.wact, bits)
    # park invalid lanes, duplicates of base rows, and in-window dups
    in_base = _bsearch_hit(st.bsort_uid, wuid)[0]
    wuid = jnp.where(in_window & ~in_base, wuid, _I32MAX)
    by_uid = jnp.argsort(wuid)
    sorted_uid = wuid[by_uid]
    dup_sorted = jnp.concatenate(
        [jnp.zeros((1,), bool), sorted_uid[1:] == sorted_uid[:-1]])
    dup = jnp.zeros((nw,), bool).at[by_uid].set(dup_sorted)
    wuid = jnp.where(dup, _I32MAX, wuid)
    valid = wuid != _I32MAX

    ref = pack_uid(st.wrlam, st.wract, bits)
    # parent resolution: window first, then base anchor, else parked
    wpos = jnp.searchsorted(sorted_uid, ref)
    wcp = jnp.clip(wpos, 0, nw - 1)
    whit = (wpos < nw) & (sorted_uid[wcp] == ref) & ~dup[by_uid[wcp]]
    parent_w = by_uid[wcp]
    bhit, bidx = _bsearch_hit(st.bsort_uid, ref)
    is_root = valid & ~whit & (bhit | (ref == 0))
    parked_v = valid & ~whit & ~is_root
    valid = valid & ~parked_v  # unresolvable: excluded with subtree

    parked = nw  # sentinel vertex
    # segment key: real parent / unique per root / parked bucket
    parent_key = jnp.where(
        whit & valid, parent_w,
        jnp.where(is_root, nw + 1 + lanes, parked))

    rank, reachable, root_of, fin_ok = _window_tour(
        parent_key, wuid, valid, is_root, nw)

    # splice position for each root (gathered for every vertex via
    # root_of): first base child of the anchor with uid < root uid,
    # else the end of the anchor's subtree (head anchors end at bn)
    q = _ckey_pack(ref, wuid)
    ci = jnp.searchsorted(st.ckey, q)
    cic = jnp.clip(ci, 0, pb - 1)
    chit = (ci < pb) & ((st.ckey[cic] >> 32) == ref.astype(jnp.int64))
    anchor_pos = st.bsort_pos[bidx]
    sub_end = jnp.where(
        ref == 0, st.bn, st.bsub_end[jnp.clip(anchor_pos, 0, pb - 1)])
    splice = jnp.where(chit, st.cpos[cic], sub_end)       # [NW] (roots)

    # pending deletes: hide window and base targets (snapshot-included
    # deletes only — a tombstone newer than the read snapshot must not
    # hide its target yet)
    duid = pack_uid(st.dlam, st.dact, bits)
    dvalid = (jnp.arange(st.md, dtype=jnp.int32) < st.dn) \
        & _included(st.dss, st.ddc, st.dct, read_vc)
    dwp = jnp.searchsorted(sorted_uid, duid)
    dwc = jnp.clip(dwp, 0, nw - 1)
    dwhit = dvalid & (dwp < nw) & (sorted_uid[dwc] == duid)
    deleted_w = jnp.zeros((nw,), bool).at[
        jnp.where(dwhit, by_uid[dwc], nw)].set(True, mode="drop")
    dbhit, dbidx = _bsearch_hit(st.bsort_uid, duid)
    hidden_b = jnp.zeros((pb,), bool).at[
        jnp.where(dvalid & dbhit, st.bsort_pos[dbidx], pb)
    ].set(True, mode="drop")

    bpos_arr = jnp.arange(pb, dtype=jnp.int32)
    # presence = in the RGA state (tombstones included, as the host
    # oracle keeps them); visibility = live and not hidden at snapshot
    present_b = bpos_arr < st.bn
    present_w = reachable
    visible_w = present_w & ~deleted_w
    visible_b = st.blive & present_b & ~hidden_b

    # final order: (splice_pos, tier, uid desc among roots, tour rank)
    rshift = max(1, (2 * (nw + 1)).bit_length())
    ruid = wuid[root_of]
    w_primary = (splice[root_of].astype(jnp.int64) << 1)
    b_primary = (bpos_arr.astype(jnp.int64) << 1) | 1
    w_secondary = ((jnp.int64(_I32MAX) - ruid.astype(jnp.int64))
                   << rshift) | rank.astype(jnp.int64)
    primary = jnp.concatenate([
        jnp.where(present_b, b_primary, _I64MAX),
        jnp.where(present_w, w_primary, _I64MAX)])
    secondary = jnp.concatenate(
        [jnp.zeros((pb,), jnp.int64), w_secondary])
    perm = rga_kernel._lexsort2(primary, secondary)
    mask32 = (1 << bits) - 1
    lam_all = jnp.concatenate(
        [(st.buid >> bits) & (_I32MAX >> bits), st.wlam])
    act_all = jnp.concatenate([st.buid & mask32, st.wact])
    elems = jnp.concatenate([st.belem, st.welem])
    present = jnp.concatenate([present_b, present_w])[perm]
    vis = jnp.concatenate([visible_b, visible_w])[perm] & present
    lam = jnp.where(present, lam_all[perm], 0)
    act = jnp.where(present, act_all[perm], 0)
    elem_out = jnp.where(present, elems[perm], 0)
    n = jnp.sum(present).astype(jnp.int32)
    return lam, act, elem_out, vis, n


@kernel_span("mat.rga")
@jax.jit
def rga_read_doc(st: RgaStoreState, read_vc):
    """Visible document only: (doc int32[PB+NW] padded with -1,
    n_visible) — the bench-facing view over :func:`rga_read`."""
    lam, act, elem, vis, _n = rga_read(st, read_vc)
    order = jnp.argsort(~vis, stable=True)
    n_vis = jnp.sum(vis).astype(jnp.int32)
    doc = jnp.where(jnp.arange(vis.shape[0]) < n_vis,
                    elem[order], -1)
    return doc, n_vis


def _bsearch_hit(sorted_arr, q):
    """(hit bool[...], index) of q in a sorted int array."""
    n = sorted_arr.shape[0]
    p = jnp.searchsorted(sorted_arr, q)
    c = jnp.clip(p, 0, n - 1)
    return (p < n) & (sorted_arr[c] == q), c


def _window_tour(parent_key, uid, valid, is_root, nw):
    """Euler tour + Wyllie rank over the window forest.  Returns
    (rank, reachable, root_of, fin) — rank orders vertices within their
    subtree (tour distance: order-exact, not dense)."""
    parked = nw
    sperm = rga_kernel._lexsort2(parent_key, -uid)
    sparent = parent_key[sperm]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sparent[1:] != sparent[:-1]])
    fc_idx = jnp.where(first, sparent, 2 * nw + 3)
    first_child = jnp.full((nw + 1,), -1, jnp.int32).at[fc_idx].set(
        sperm.astype(jnp.int32), mode="drop")
    same = sparent[:-1] == sparent[1:]
    ns_src = jnp.where(same, sperm[:-1], 2 * nw + 5)
    next_sib = jnp.full((nw,), -1, jnp.int32).at[ns_src].set(
        sperm[1:].astype(jnp.int32), mode="drop")

    up = nw + 1
    s = 2 * (nw + 1)
    v = jnp.arange(nw + 1, dtype=jnp.int32)
    fc = first_child[v]
    succ_down = jnp.where(fc >= 0, fc, up + v)
    ns = jnp.concatenate([next_sib, jnp.full((1,), -1, jnp.int32)])
    pk = jnp.concatenate(
        [parent_key.astype(jnp.int32), jnp.full((1,), parked, jnp.int32)])
    # non-root, non-parked: up -> next sib | parent's up.  pk < nw is a
    # real parent; roots/parked handled below
    par_clip = jnp.clip(pk, 0, nw)
    succ_up = jnp.where(ns[v] >= 0, ns[v], up + par_clip[v])
    root_mask = jnp.concatenate([is_root, jnp.zeros((1,), bool)])
    succ_up = jnp.where(root_mask, up + v, succ_up)  # terminal self-loop
    parked_mask = jnp.concatenate(
        [~valid, jnp.ones((1,), bool)])  # incl. sentinel vertex
    succ_down = jnp.where(parked_mask, v, succ_down)
    succ_up = jnp.where(parked_mask, up + v, succ_up)
    succ = jnp.concatenate([succ_down, succ_up])

    slot = jnp.arange(s, dtype=jnp.int32)
    dist = (succ != slot).astype(jnp.int32)
    steps = max(1, (s - 1).bit_length())

    def body(_, c):
        d, nx = c
        return d + d[nx], nx[nx]

    dist, fin = jax.lax.fori_loop(0, steps, body, (dist, succ))
    vw = jnp.arange(nw, dtype=jnp.int32)
    # reachable iff the chain terminates at an anchored root's up-slot
    is_root_up = jnp.concatenate(
        [jnp.zeros((nw + 1,), bool), root_mask])
    term = fin[vw]
    reachable = valid & is_root_up[jnp.clip(term, 0, s - 1)]
    root_of = jnp.clip(term - up, 0, nw - 1)
    rank = dist[root_of] - dist[vw]          # 0 at the root, tour order
    rank = jnp.where(reachable, rank, 0)
    return rank, reachable, root_of, fin


@kernel_span("mat.rga")
@partial(jax.jit, donate_argnums=(0,), static_argnames=())
def rga_fold(st: RgaStoreState, gst):
    """Fold window ops whose commit VC <= the dense GST (int64[D]) into
    the base: one full merge over base + stable window (the amortized
    GC; tombstoned vertices keep their rows as anchors), then compact
    the window to its unstable suffix.  Requires the folded base to fit
    PB rows (the host wrapper grows first; see rga_fold_host)."""
    nw, pb, md = st.nw, st.pb, st.md
    bits = st.actor_bits
    mask32 = (1 << bits) - 1

    lanes = jnp.arange(nw, dtype=jnp.int32)
    in_window = lanes < st.wn
    stable_w = in_window & _included(st.wss, st.wdc, st.wct, gst)
    # duplicate deliveries of base rows must not re-enter the merge (a
    # kept window copy would shadow the base row's tombstone flag);
    # they are dropped from the window instead
    wuid_w = pack_uid(st.wlam, st.wact, bits)
    base_dup = in_window & _bsearch_hit(st.bsort_uid, wuid_w)[0]
    stable_w = stable_w & ~base_dup
    dlanes = jnp.arange(md, dtype=jnp.int32)
    stable_d = (dlanes < st.dn) & _included(st.dss, st.ddc, st.dct, gst)

    bpos = jnp.arange(pb, dtype=jnp.int32)
    in_base = bpos < st.bn
    blam = (st.buid >> bits).astype(jnp.int32)
    bact = (st.buid & mask32).astype(jnp.int32)
    bplam = (st.bparent >> bits).astype(jnp.int32)
    bpact = (st.bparent & mask32).astype(jnp.int32)

    ins_lam = jnp.concatenate([jnp.where(in_base, blam, 0), st.wlam])
    ins_act = jnp.concatenate([jnp.where(in_base, bact, 0), st.wact])
    ref_lam = jnp.concatenate([bplam, st.wrlam])
    ref_act = jnp.concatenate([bpact, st.wract])
    elem = jnp.concatenate([st.belem, st.welem])
    valid = jnp.concatenate([in_base, stable_w])
    prev_live = jnp.concatenate(
        [st.blive, jnp.ones((nw,), bool)])

    r = rga_kernel.rga_merge_full(
        ins_lam, ins_act, ref_lam, ref_act, elem, valid,
        st.dlam, st.dact, stable_d, actor_bits=bits)

    t = pb + nw
    rank = jnp.where(r["reachable"], r["rank"], _I32MAX)
    perm = jnp.argsort(rank)
    n_new = jnp.sum(r["reachable"]).astype(jnp.int32)
    live = prev_live & ~r["deleted"]
    parent = r["parent"]
    parent_uid = jnp.where(
        parent >= t, 0,
        r["uid"][jnp.clip(parent, 0, t - 1)]).astype(jnp.int32)

    take = lambda a: a[perm][:pb]
    reach_s = take(r["reachable"])
    new_pos = jnp.arange(pb, dtype=jnp.int32)
    buid = jnp.where(reach_s, take(r["uid"]).astype(jnp.int32), _I32MAX)
    bparent = jnp.where(reach_s, take(parent_uid), 0)
    belem = jnp.where(reach_s, take(elem), 0)
    blive = reach_s & take(live)
    bsub_end = jnp.where(
        reach_s, new_pos + take(r["subtree"]), 0)

    sort_perm = jnp.argsort(buid)
    bsort_uid = buid[sort_perm]
    bsort_pos = new_pos[sort_perm]

    ck = jnp.where(reach_s.astype(jnp.int64) > 0,
                   _ckey_pack(bparent, buid), _I64MAX)
    ck_perm = jnp.argsort(ck)
    ckey = ck[ck_perm]
    cpos = new_pos[ck_perm]

    # compact the window to the unstable suffix (stable order
    # preserved); folded ops and base duplicates both drop
    keep_w = in_window & ~stable_w & ~base_dup
    worder = jnp.argsort(~keep_w, stable=True)
    wn_new = jnp.sum(keep_w).astype(jnp.int32)
    def _compact(order, n_new, size):
        def go(a):
            live = jnp.arange(size) < n_new
            m = live.reshape((size,) + (1,) * (a.ndim - 1))
            return jnp.where(m, a[order], 0)
        return go

    cw = _compact(worder, wn_new, nw)
    keep_d = (dlanes < st.dn) & ~stable_d
    dorder = jnp.argsort(~keep_d, stable=True)
    dn_new = jnp.sum(keep_d).astype(jnp.int32)
    cd = _compact(dorder, dn_new, md)

    return replace(
        st,
        buid=buid, bparent=bparent, belem=belem, blive=blive,
        bsub_end=bsub_end, bn=n_new,
        bsort_uid=bsort_uid, bsort_pos=bsort_pos, ckey=ckey, cpos=cpos,
        wlam=cw(st.wlam), wact=cw(st.wact), wrlam=cw(st.wrlam),
        wract=cw(st.wract), welem=cw(st.welem),
        wdc=cw(st.wdc), wct=cw(st.wct), wss=cw(st.wss),
        wn=wn_new,
        dlam=cd(st.dlam), dact=cd(st.dact),
        ddc=cd(st.ddc), dct=cd(st.dct), dss=cd(st.dss),
        dn=dn_new,
    ), n_new


def rga_grow(st: RgaStoreState, pb: int | None = None,
             nw: int | None = None, md: int | None = None,
             n_dcs: int | None = None) -> RgaStoreState:
    """Host-side capacity regrade (never shrinks); rare."""
    pb = max(pb or st.pb, st.pb)
    nw = max(nw or st.nw, st.nw)
    md = max(md or st.md, st.md)
    d = max(n_dcs or st.d, st.d)
    if (pb, nw, md, d) == (st.pb, st.nw, st.md, st.d):
        return st

    def pad(a, n, fill=0):
        a = np.asarray(a)
        return jnp.asarray(np.pad(a, (0, n - len(a)),
                                  constant_values=fill))

    def pad2(a, n, cols):
        a = np.asarray(a)
        return jnp.asarray(np.pad(
            a, ((0, n - a.shape[0]), (0, cols - a.shape[1]))))

    return RgaStoreState(
        buid=pad(st.buid, pb, _I32MAX), bparent=pad(st.bparent, pb),
        belem=pad(st.belem, pb), blive=pad(st.blive, pb, False),
        bsub_end=pad(st.bsub_end, pb), bn=st.bn,
        bsort_uid=pad(st.bsort_uid, pb, _I32MAX),
        bsort_pos=pad(st.bsort_pos, pb),
        ckey=pad(st.ckey, pb, int(_I64MAX)), cpos=pad(st.cpos, pb),
        wlam=pad(st.wlam, nw), wact=pad(st.wact, nw),
        wrlam=pad(st.wrlam, nw), wract=pad(st.wract, nw),
        welem=pad(st.welem, nw),
        wdc=pad(st.wdc, nw), wct=pad(st.wct, nw),
        wss=pad2(st.wss, nw, d), wn=st.wn,
        dlam=pad(st.dlam, md), dact=pad(st.dact, md),
        ddc=pad(st.ddc, md), dct=pad(st.dct, md),
        dss=pad2(st.dss, md, d), dn=st.dn,
        actor_bits=st.actor_bits,
    )


def rga_remap_actors(st: RgaStoreState, perm) -> RgaStoreState:
    """Rewrite every packed actor id through ``perm`` (int32[2^bits],
    old id -> new id, 0 -> 0) and re-derive the base order.

    Needed because sibling order is uid-DESC and the host oracle breaks
    lamport ties by ACTOR STRING: the device's interned ids must order
    like the strings, so when a new actor arrives that does not sort
    after all existing ones, the owner re-interns in sorted order and
    remaps the document (actors per document are few — DC/node ids — so
    this is rare and bounded).  The base preorder depends on sibling
    order, hence the re-merge via a zero-horizon fold after the id
    rewrite."""
    bits = st.actor_bits
    mask = (1 << bits) - 1
    pm = jnp.asarray(perm, jnp.int32)

    def remap_uid(uid_arr):
        out = ((uid_arr >> bits) << bits) | pm[uid_arr & mask]
        return jnp.where(uid_arr == _I32MAX, _I32MAX, out)

    buid = remap_uid(st.buid)
    bparent = remap_uid(st.bparent)
    pos = jnp.arange(st.pb, dtype=jnp.int32)
    sort_perm = jnp.argsort(buid)
    in_base = pos < st.bn
    ck = jnp.where(in_base.astype(jnp.int64) > 0,
                   _ckey_pack(bparent, buid), _I64MAX)
    ck_perm = jnp.argsort(ck)
    st = replace(
        st,
        buid=buid, bparent=bparent,
        bsort_uid=buid[sort_perm], bsort_pos=pos[sort_perm],
        ckey=ck[ck_perm], cpos=pos[ck_perm],
        wact=pm[st.wact], wract=pm[st.wract], dact=pm[st.dact],
    )
    # zero-horizon fold: folds nothing from the window (commit times are
    # positive) but re-merges the base rows, rebuilding the preorder and
    # subtree extents under the remapped sibling order
    st, _bn = rga_fold(st, jnp.zeros((st.d,), jnp.int64))
    return st


def rga_fold_host(st: RgaStoreState, gst) -> RgaStoreState:
    """Host wrapper around :func:`rga_fold`: grows the base first when
    the folded document might not fit (worst case bn + stable window).
    ``gst`` is the dense stable VC (int64[D]); a scalar is treated as a
    single-column horizon for the simulation benches."""
    gst = np.asarray(gst, dtype=np.int64).reshape(-1)
    if gst.shape[0] != st.d:
        gst = np.pad(gst, (0, st.d - gst.shape[0]))
    need = int(st.bn) + int(st.wn)
    if need > st.pb:
        new_pb = st.pb
        while new_pb < need:
            new_pb *= 2
        st = rga_grow(st, pb=new_pb)
    st, _bn = rga_fold(st, jnp.asarray(gst))
    return st

"""Coalesced read serve plane — cross-transaction snapshot-read
batching (ISSUE 8).

PRs 3-5 closed the per-op legs of the WRITE pipeline (gate ring,
ingest plane, batched inter-DC wire), but every transaction's snapshot
read still bought its own device fold: the hardware self-capture put
``full_shard_read_ms`` at 174 (74 fused) and the 8-client txn bench is
read-dispatch starved.  Cure's snapshot reads (Akkoorath et al., ICDCS
2016) are pure functions of ``(key, snapshot VC)`` — exactly the shape
that batches — and Clock-SI's snapshot discipline (Du et al., SRDS
2013) gives the compatibility rule for grouping concurrent readers
under one fold.  This module is the serving-side mirror of the ingest
plane's economy (antidote_tpu/mat/ingest.py):

- **A per-partition coalescing window.**  Concurrent ``read_objects``
  / ``read_many`` calls STAGE ``(key, read_vc)`` requests into the
  partition's :class:`ReadServer`; whichever caller finds no drain in
  flight becomes the LEADER, holds the window open
  (``Config.read_coalesce_us`` — only while other waiters are staged;
  a solo reader drains immediately, so uncontended reads pay no added
  latency) up to ``Config.read_coalesce_keys`` staged keys, then
  drains the whole batch.  Followers staged while a drain is in
  flight are picked up by the next leader — group commit for the read
  path, the DeviceFlusher recipe on the serving side.
- **Clock-SI snapshot grouping.**  A drain groups waiters whose
  snapshot VCs are mutually coverable by one fold frontier: a waiter
  whose every key's commit frontier is dominated by its read VC can
  be served by a fold at ANY frontier at or above those ops — the
  group folds ONCE, at the least-blocking such frontier (the keys'
  frontier join raised over the pointwise-min of the member VCs;
  folding at the pointwise-max would be equally valid but gates the
  whole group at the freshest member's snapshot).  Waiters a
  frontier does NOT cover (an op exists between their snapshot and
  the key's frontier) group by exact VC equality instead — the
  fold's inclusion mask at that exact VC is the legacy per-txn
  semantics, so groups that must not merge never do.  Coverage is
  re-validated by frontier IDENTITY after the fold (the _cache_put
  discipline): a mid-window publish demotes the affected waiters to
  their own exact-VC folds instead of leaking an op from beyond
  their snapshot.  And a waiter whose snapshot is already blocked
  behind a PREPARED transaction is demoted to self-service — it pays
  the Clock-SI wait on its own thread, the legacy blocking scope,
  never convoying the window.
- **One gathered dispatch per group.**  A group's keys fold through
  ``read_many_begin``'s captured closures, and every capture sharing
  a chip runs as ONE ``fused_read`` program — so N concurrent readers
  of a hot shard cost one kernel launch instead of N.  Read-your-
  writes overlays stay with the caller (the coordinator applies own
  effects on top of the folded base, exactly as before).
- **The frontier-keyed value cache in front.**  The fold sits behind
  the partition's snapshot-versioned value cache (PartitionManager
  ``_val_cache``, keyed by frontier object identity and invalidated
  by the publish path whose ordering the PR-4 horizon fix pinned), so
  repeat reads of a stable key skip the device entirely; the READ_*
  cache counters make the hit ratio a first-class metric.

``Config.read_serve=False`` keeps the per-txn path byte-for-byte (the
benches' comparison baseline, like mat_ingest / gate_device_ring /
interdc_ship); ``serve_from_config`` is the one construction path so
an assembly cannot honor the knobs for some partitions and not others
(the gate_from_config lesson).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.clocks import VC
from antidote_tpu.obs.spans import tracer

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class ServeSettings:
    """The read serve plane's knobs — built from Config by
    :func:`serve_from_config` (the single factory) so every assembly
    honors the same values."""

    #: coalescing window; False = the legacy per-txn read path (kept
    #: as the benches' comparison baseline)
    enabled: bool = True
    #: window, µs: a leader with company holds the drain open this
    #: long; a solo reader drains immediately
    coalesce_us: int = 400
    #: staged-key budget: past it the leader drains at once
    key_budget: int = 512


def serve_from_config(config) -> ServeSettings:
    """The one construction path for serve settings — Node's partition
    factory routes through this, so single-node and cluster assemblies
    cannot silently honor different knobs."""
    if config is None:
        return ServeSettings()
    return ServeSettings(
        enabled=config.read_serve,
        coalesce_us=config.read_coalesce_us,
        key_budget=config.read_coalesce_keys)


class _Waiter:
    """One staged read call: its items, snapshot, and completion.
    ``solo`` marks a waiter the drain demoted to self-service (its
    snapshot is blocked behind a prepared transaction): its OWN thread
    runs the legacy read and pays the wait, so the window never
    convoys unrelated readers behind one blocked snapshot."""

    __slots__ = ("items", "vc", "txid", "done", "values", "error",
                 "solo")

    def __init__(self, items, vc, txid):
        self.items: List[Tuple[Any, str]] = [tuple(i) for i in items]
        self.vc: Optional[VC] = vc
        self.txid = txid
        self.done = False
        self.values: Optional[Dict] = None
        self.error: Optional[BaseException] = None
        self.solo = False


def _vc_key(vc: VC) -> tuple:
    """Hashable exact-equality key for a snapshot VC (the non-covered
    groups merge only on identical snapshots — identical inclusion
    masks, hence identical fold results)."""
    return tuple(sorted(dict(vc).items()))


class ReadServer:
    """Per-partition cross-transaction read-coalescing window.

    Threading: callers :meth:`stage` then :meth:`finish`; finish
    elects at most one LEADER at a time (the drain runs on a caller
    thread — no background thread per partition), and every drain
    marks its whole batch done in a finally, so followers can never
    wait on a dead leader.  Snapshots blocked behind a prepared txn
    never convoy the window: the drain demotes them to self-service
    and their own threads pay the Clock-SI wait (``solo``), exactly
    the legacy blocking scope.
    """

    def __init__(self, pm, settings: Optional[ServeSettings] = None):
        self._pm = pm
        self._s = settings or ServeSettings()
        self._cond = threading.Condition()
        self._staged: List[_Waiter] = []
        self._staged_keys = 0
        #: monotonic time the current window opened (first stage)
        self._open_since: Optional[float] = None
        self._leading = False
        #: direct (window-bypassing) reads in flight — the solo
        #: cross-partition fast path marks itself here so a SECOND
        #: concurrent reader sees the partition busy and stages
        #: (coalescing with the third, fourth, ...) instead of
        #: bypassing too
        self._direct = 0

    @property
    def enabled(self) -> bool:
        return self._s.enabled

    # ------------------------------------------------------------ staging

    def stage(self, items, snapshot_vc, txid=None) -> _Waiter:
        """Stage one read call's ``(key, type)`` items at
        ``snapshot_vc``; returns the ticket :meth:`finish` resolves.
        ``txid`` feeds trace correlation and the blocked-snapshot
        check/self-serve path; GROUP folds themselves run txid-less
        (an ACTIVE transaction cannot hold its own prepare, so there
        is no own-prepared entry to skip)."""
        w = _Waiter(items, snapshot_vc, txid)
        with self._cond:
            self._staged.append(w)
            self._staged_keys += len(w.items)
            if self._open_since is None:
                self._open_since = time.monotonic()
            self._cond.notify_all()
        return w

    def finish(self, w: _Waiter, timeout: float = 30.0) -> Dict:
        """Resolve a staged ticket: wait for a drain to serve it,
        leading one ourselves whenever no drain is in flight."""
        deadline = time.monotonic() + timeout
        while True:
            lead = False
            with self._cond:
                while not w.done and self._leading:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.1))
                if w.done:
                    break
                if time.monotonic() >= deadline:
                    # pathological (a wedged leader): un-stage if still
                    # ours so no later drain wastes a fold on it
                    if w in self._staged:
                        self._staged.remove(w)
                        self._staged_keys -= len(w.items)
                        if not self._staged:
                            # an emptied window must not keep its old
                            # open-stamp: the next stager would inherit
                            # an expired deadline and lose the hold
                            self._open_since = None
                    raise TimeoutError(
                        "coalesced read never drained (leader wedged?)")
                self._leading = True
                lead = True
            if lead:
                try:
                    self._lead_once()
                finally:
                    with self._cond:
                        self._leading = False
                        self._cond.notify_all()
        if w.solo:
            # the drain found this snapshot blocked behind a prepared
            # txn: pay the wait on OUR thread (exactly the legacy
            # behavior) instead of convoying the window behind it
            return self._pm.read_many(w.items, w.vc, txid=w.txid)
        if w.error is not None:
            raise w.error
        return w.values

    def read_many(self, items, snapshot_vc, txid=None) -> Dict:
        """Stage + finish in one call — the drop-in for a single
        partition's ``pm.read_many``.  Disabled servers delegate
        straight through (the legacy baseline)."""
        if not self._s.enabled:
            return self._pm.read_many(items, snapshot_vc, txid=txid)
        return self.finish(self.stage(items, snapshot_vc, txid))

    # ------------------------------------------------------------ leading

    def _lead_once(self) -> None:
        s = self._s
        with self._cond:
            if not self._staged:
                return
            if s.coalesce_us > 0:
                deadline = self._open_since + s.coalesce_us / 1e6
                # hold only while there is company: a solo reader pays
                # zero added latency, a burst is served by one fold
                while (len(self._staged) > 1
                       and self._staged_keys < s.key_budget):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            batch, self._staged = self._staged, []
            self._staged_keys = 0
            self._open_since = None
        if batch:
            self._drain(batch)

    # ------------------------------------------------------------ draining

    def _drain(self, batch: List[_Waiter]) -> None:
        """Group the batch by snapshot compatibility and fold each
        group once; every waiter is marked done in the finally.

        The fold dispatch economy is CROSS-group (ISSUE 20): every
        group's device captures begin first, then all captures sharing
        a device — including the mesh handle of a pod-sharded plane —
        run as ONE ``fused_read`` program, then each group finishes
        with its own revalidation.  A drain therefore costs O(devices)
        dispatches, not O(groups x types): on a sharded node every
        plane reports the SAME mesh, so the whole drain is one
        multi-chip program (the config18 bench's O(1) gate)."""
        try:
            n_keys = sum(len(w.items) for w in batch)
            # a solo drain is unambiguously that waiter's work: carry
            # its txid so the fold's kernel child-spans keep joining
            # the sampled txn's tree (multi-waiter drains are shared
            # work and stay untagged; the per-waiter read_serve
            # instants below attribute those)
            span_txid = batch[0].txid if len(batch) == 1 else None
            with tracer.span("read_serve_drain", "device",
                             txid=span_txid, waiters=len(batch),
                             keys=n_keys, partition=self._pm.partition):
                groups, solos = self._classify(batch)
                if solos:
                    # release the blocked snapshots to their own
                    # threads BEFORE folding, so they wait out their
                    # prepared txns concurrently with the drain
                    with self._cond:
                        for w in solos:
                            w.solo = True
                            w.done = True
                        self._cond.notify_all()
                self._serve_groups(groups, span_txid)
                served = len(batch) - len(solos)
                if groups:
                    reg = stats.registry
                    reg.read_serve_groups.inc(len(groups))
                    reg.read_serve_waiters.inc(served)
                    reg.read_coalesced_keys.inc(
                        sum(len(w.items) for w in batch
                            if not w.solo))
                    folds = reg.read_serve_groups.value()
                    if folds:
                        reg.read_waiters_per_dispatch.set(
                            reg.read_serve_waiters.value() / folds)
            for w in batch:
                if w.txid is not None:
                    tracer.instant("read_serve", "device", txid=w.txid,
                                   waiters=len(batch),
                                   partition=self._pm.partition)
        except BaseException as e:  # noqa: BLE001 — fanned to waiters
            for w in batch:
                if w.values is None and w.error is None:
                    w.error = e
        finally:
            with self._cond:
                for w in batch:
                    w.done = True
                self._cond.notify_all()

    def _classify(self, batch):
        """(groups, solos): ``groups`` is [(kind, waiters, fold_vc,
        fr_map)], ``solos`` the waiters demoted to self-service.

        ``covered``: every key's commit frontier is dominated by the
        waiter's VC, so ONE fold is valid for all of them (all the
        keys' ops are below every member's snapshot — the Clock-SI
        grouping rule).  The group folds at the LEAST-blocking valid
        frontier — the join of the group keys' frontiers with the
        pointwise MINIMUM of the member VCs: every key's ops are
        still included (fold ≥ its frontier), and the fold's Clock-SI
        gates (clock wait, prepared-txn wait) run no higher than they
        must, instead of at the pointwise max where one member's
        fresh snapshot would stall the whole group behind prepares
        none of them can observe.  Frontier objects are snapshotted
        here and re-checked by IDENTITY after the fold
        (:meth:`_serve_group`): a mid-window publish demotes the
        waiter instead of leaking a too-new op.  ``latest``: VC-less
        readers share one un-gated fold.  ``exact``: everyone else
        groups by exact VC equality — identical inclusion masks,
        byte-for-byte the legacy semantics.

        ``solos``: waiters whose OWN snapshot is already blocked
        behind a prepared transaction (checked under the lock, the
        legacy gating rule).  Legacy made only THAT reader wait;
        folding it with others would convoy the window — so it pays
        its wait on its own thread instead."""
        pm = self._pm
        fr_map: Dict[Any, Any] = {}
        blocked = set()
        with pm._lock:
            for w in batch:
                for key, _t in w.items:
                    if key not in fr_map:
                        fr_map[key] = pm.key_frontier.get(key)
            for i, w in enumerate(batch):
                if w.vc is not None and any(
                        pm._blocking_prepared(k, w.vc, w.txid)
                        for k, _t in w.items):
                    blocked.add(i)
        solos = [w for i, w in enumerate(batch) if i in blocked]
        covered: List[_Waiter] = []
        latest: List[_Waiter] = []
        exact: Dict[tuple, List[_Waiter]] = {}
        for i, w in enumerate(batch):
            if i in blocked:
                continue
            if w.vc is None:
                latest.append(w)
            elif all(fr_map[k] is not None and fr_map[k].le(w.vc)
                     for k, _t in w.items):
                covered.append(w)
            else:
                exact.setdefault(_vc_key(w.vc), []).append(w)
        groups = []
        if covered:
            # pointwise min of the member VCs (absent entry = 0) ...
            dcs = set()
            for w in covered:
                dcs.update(dict(w.vc))
            meet = VC({dc: min(w.vc.get_dc(dc) for w in covered)
                       for dc in dcs})
            # ... raised to every group key's frontier so no key's
            # committed ops fall outside the inclusion mask
            fold_vc = meet
            for w in covered:
                for k, _t in w.items:
                    fold_vc = fold_vc.join(fr_map[k])
            groups.append(("covered", covered, fold_vc, fr_map))
        if latest:
            groups.append(("latest", latest, None, None))
        for _k, ws in exact.items():
            groups.append(("exact", ws, ws[0].vc, None))
        return groups, solos

    def _serve_groups(self, groups, span_txid=None) -> None:
        """Fold every drain group and distribute values — with ONE
        fused dispatch per device across ALL the groups.

        Stage 1 begins every group (``read_many_begin`` captures the
        device folds, reader counts taken).  Stage 2 buckets every
        captured fold by its ``.device`` handle — a chip for a pinned
        plane, the Mesh for a pod-sharded one (jax.sharding.Mesh
        compares by content, so every sharded plane lands in one
        bucket) — and runs each bucket as one ``fused_read`` under
        ``collective_guard`` (multi-chip programs serialize on
        runtime.COLLECTIVE_LOCK).  Stage 3 finishes each group:
        ``read_many_finish`` distributes values, runs any non-fused
        lone folds, and RELEASES the reader counts — it runs exactly
        once per begun group, whatever stage 2 did.

        The read-dispatch delta over the whole drain feeds
        ``shard_read_dispatches_per_drain`` — the gauge the config18
        bench gates at O(1) on a sharded node (vs O(groups x types)
        unfused).

        Deadlock discipline: a begin that would FLUSH must never run
        while this thread still holds earlier begins' reader counts
        (the flush's quiesce wait can only be released by our own
        not-yet-run finishes).  The wave therefore begins groups with
        ``nowait=True`` — a group whose begin would flush or block on
        a prepared txn is DEFERRED to a sequential pass after the wave
        finishes (zero own readers outstanding), where the blocking
        begin is safe again."""
        pm = self._pm
        from antidote_tpu.mat.device_plane import (
            collective_guard, fused_read, read_dispatch_count)

        d0 = read_dispatch_count()
        began: List[tuple] = []
        deferred: List[tuple] = []
        by_dev: Dict[Any, list] = {}
        for kind, waiters, fold_vc, fr_map in groups:
            items = self._group_items(waiters)
            with tracer.span("read_serve_fold", "device",
                             txid=span_txid, keys=len(items)):
                try:
                    r = pm.read_many_begin(items, fold_vc, span_txid,
                                           nowait=True)
                except Exception as e:  # noqa: BLE001 — to waiters
                    for w in waiters:
                        w.error = e
                    continue
            if r is None:
                deferred.append((kind, waiters, fold_vc, fr_map))
                continue
            out, batches = r
            gi = len(began)
            began.append((kind, waiters, fold_vc, fr_map, out,
                          batches))
            self._collect_splits(by_dev, gi, batches)
        got_by = self._fuse(by_dev, collective_guard, fused_read)
        finished = set()
        try:
            for gi, rec in enumerate(began):
                finished.add(gi)
                self._finish_group(rec, got_by.get(gi), span_txid)
        finally:
            # whatever happened above, every begun group's finish must
            # run: it releases the reader counts read_many_begin took
            # (a leak wedges every publish)
            for gi, rec in enumerate(began):
                if gi not in finished:
                    _kind, waiters, fold_vc, _fr, out, batches = rec
                    try:
                        pm.read_many_finish(out, batches, fold_vc,
                                            span_txid)
                    except Exception as e:  # noqa: BLE001
                        for w in waiters:
                            if w.error is None:
                                w.error = e
        # sequential pass: the wave's readers are released, so these
        # groups' begins may flush / wait on prepares safely (the
        # pre-ISSUE-20 per-group shape, fused within each group)
        for kind, waiters, fold_vc, fr_map in deferred:
            self._serve_group_seq(kind, waiters, fold_vc, fr_map,
                                  span_txid, collective_guard,
                                  fused_read)
        delta = read_dispatch_count() - d0
        reg = stats.registry
        reg.shard_serve_drains.inc()
        reg.shard_read_dispatches_per_drain.set(delta)

    @staticmethod
    def _group_items(waiters) -> list:
        items = []
        seen = set()
        for w in waiters:
            for pair in w.items:
                if pair not in seen:
                    seen.add(pair)
                    items.append(pair)
        return items

    @staticmethod
    def _collect_splits(by_dev, gi, batches) -> None:
        """Bucket a begun group's fused-capable fold captures by their
        ``.device`` handle (a chip, or the Mesh of a sharded plane)."""
        for bi, (_t, _pairs, closure) in enumerate(batches):
            split = getattr(closure, "split", None) \
                if closure is not None else None
            if split is not None:
                by_dev.setdefault(
                    getattr(closure, "device", None), []).append(
                        (gi, bi, split))

    @staticmethod
    def _fuse(by_dev, collective_guard, fused_read):
        """One ``fused_read`` per device bucket (>=2 captures — a lone
        fold dispatches itself in finish); returns {gi: {bi: got}}."""
        got_by: Dict[int, Dict[int, dict]] = {}
        for dev, entries in by_dev.items():
            if dev is None or len(entries) < 2:
                continue
            try:
                with tracer.span("read_serve_fused", "device",
                                 folds=len(entries)), \
                        collective_guard(dev):
                    outs = fused_read([s for _gi, _bi, s in entries])
            except Exception:  # noqa: BLE001 — per-fold fallback
                log.exception("fused serve read failed; falling "
                              "back to per-type folds")
                continue
            for (gi, bi, _s), got in zip(entries, outs):
                got_by.setdefault(gi, {})[bi] = got
        return got_by

    def _serve_group_seq(self, kind, waiters, fold_vc, fr_map,
                         span_txid, collective_guard,
                         fused_read) -> None:
        """Sequential (blocking-begin) serve of one deferred group:
        begin may flush and wait, the group's own captures still fuse
        per device, finish runs in a finally."""
        pm = self._pm
        items = self._group_items(waiters)
        with tracer.span("read_serve_fold", "device", txid=span_txid,
                         keys=len(items)):
            try:
                out, batches = pm.read_many_begin(items, fold_vc,
                                                  span_txid)
            except Exception as e:  # noqa: BLE001 — fanned to waiters
                for w in waiters:
                    w.error = e
                return
        by_dev: Dict[Any, list] = {}
        self._collect_splits(by_dev, 0, batches)
        got_by = self._fuse(by_dev, collective_guard, fused_read)
        self._finish_group((kind, waiters, fold_vc, fr_map, out,
                            batches), got_by.get(0), span_txid)

    def _finish_group(self, rec, got_map, span_txid=None) -> None:
        """Stage-3 of one group: distribute the (possibly pre-fused)
        fold results to the group's waiters, with the covered groups'
        frontier-identity revalidation."""
        pm = self._pm
        kind, waiters, fold_vc, fr_map, out, batches = rec
        try:
            got = pm.read_many_finish(out, batches, fold_vc,
                                      span_txid, got_map)
        except Exception as e:  # noqa: BLE001 — fanned to waiters
            for w in waiters:
                w.error = e
            return
        broken: List[_Waiter] = []
        if kind == "covered":
            # frontier-identity revalidation: a publish between the
            # classify snapshot and the fold capture may have put an
            # op beyond a waiter's snapshot into the group fold
            with pm._lock:
                for w in waiters:
                    if any(pm.key_frontier.get(k) is not fr_map[k]
                           for k, _t in w.items):
                        broken.append(w)
        for w in waiters:
            if w in broken:
                continue
            w.values = {pair: got[pair] for pair in w.items}
        for w in broken:
            # rare: re-serve at the waiter's own exact VC (the legacy
            # inclusion mask cannot over-include, whatever published);
            # the waiter's txid rides along like the solo path's — the
            # legacy own-prepared exclusion and trace joins survive
            try:
                w.values = pm.read_many(w.items, w.vc, txid=w.txid)
            except Exception as e:  # noqa: BLE001 — per-waiter
                w.error = e


def read_groups(groups, snapshot_vc, txid=None) -> Dict:
    """Route a multi-partition local read through each partition's
    serve window: everything stages FIRST (so one caller's requests
    coalesce with concurrent readers on every partition), then the
    tickets resolve in order — the caller leads any partition whose
    window has no drain in flight.  Falls back to the legacy path
    (single-partition ``read_many`` / cross-partition
    ``read_many_fused``) when any partition lacks an enabled server,
    so ``read_serve=False`` keeps today's dispatch shape exactly."""
    pairs = [(pm, items, getattr(pm, "read_server", None))
             for pm, items in groups]
    if any(rs is None or not rs.enabled for _pm, _i, rs in pairs):
        if len(groups) == 1:
            pm, items = groups[0]
            return pm.read_many(items, snapshot_vc, txid=txid)
        from antidote_tpu.txn.manager import read_many_fused

        return read_many_fused(groups, snapshot_vc, txid)
    if len(pairs) > 1:
        idle = True
        for _pm, _i, rs in pairs:
            with rs._cond:
                if rs._staged or rs._leading or rs._direct:
                    idle = False
                    break
        if idle:
            # solo cross-partition read: every window is idle, so
            # staging would coalesce with nobody — keep the fused
            # one-program-per-chip shape instead (read_many_fused).
            # The _direct marker makes this visible to the NEXT
            # concurrent reader, which stages and coalesces with
            # everyone after it; a reader racing past the check
            # merely leads its own drain, exactly as if it had
            # arrived a moment later.
            from antidote_tpu.txn.manager import read_many_fused

            for _pm, _i, rs in pairs:
                with rs._cond:
                    rs._direct += 1
            try:
                return read_many_fused(groups, snapshot_vc, txid)
            finally:
                for _pm, _i, rs in pairs:
                    with rs._cond:
                        rs._direct -= 1
    tickets = [(rs, rs.stage(items, snapshot_vc, txid))
               for _pm, items, rs in pairs]
    out: Dict = {}
    err = None
    for rs, w in tickets:
        # resolve EVERY ticket even after a failure: each finish only
        # waits out (or leads) its partition's drain, and skipping one
        # would strand nothing but skip the leader duty a solo caller
        # owes its own staged request
        try:
            out.update(rs.finish(w))
        except Exception as e:  # noqa: BLE001 — first error wins
            if err is None:
                err = e
    if err is not None:
        raise err
    return out

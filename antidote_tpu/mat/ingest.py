"""Coalesced ingest plane for the materializer stores (ISSUE 4).

BENCH_r05 on the live chip put config-3 mvreg at 0.7x its bracket and
the config-4 RGA steady path under water — both per-op scatter-bound:
every plane flush uploaded ~10 separate per-column host arrays (one
``jnp.asarray`` each) and the benches' legacy form dispatched one
append per op.  The PR-3 gate ring already proved the cure on the
dependency gate: persistent device state, ONE small H2D per batch,
scalar-fetch completion.  This module generalizes that staging economy
to the shard stores:

- **One packed H2D per flush.**  Arriving ops coalesce host-side into
  a single ``int64[B, 2+F]`` tensor whose payload section is laid out
  EXACTLY like the store's packed ops rows (``[key_idx, lane_off,
  <ops-row columns>]``), so :func:`packed_append` splits the two index
  columns on device and lands the batch with the store's own
  single-scatter epilogue (``store._scatter_rows``) — no per-column
  uploads, no on-device column shuffle.
- **A coalescing window + row budget.**  ``Config.mat_coalesce_us``
  holds staged rows open so a burst flushes as one dispatch even below
  the ``device_flush_ops`` threshold's worth of rows;
  ``Config.mat_coalesce_rows`` is the hard staging cap past which the
  committer flushes inline (backpressure, like the gate ring's 4x
  rule).  GC/fold cadence stays on its own knobs (``device_gc_ops``,
  the benches' ``gc_every``) — append cadence and fold cadence are
  deliberately decoupled, the reference's amortized ``?OPS_THRESHOLD``
  recipe.
- **Honest completion.**  :func:`packed_append` is ``@kernel_span``
  (antidote_tpu/obs/prof.py), so sampled-txn completion is measured by
  the profiler's scalar device->host fetch, the same barrier the
  benches use — dispatch-only timings lie on the hardware tunnel.

``ingest_from_config`` is the ONE factory every assembly must route
through (DevicePlane and mat/sharded.py both take its settings), so a
knob like ``mat_ingest=False`` — the legacy per-column baseline the
benches compare against — cannot silently apply to some planes and
not others (the gate_from_config lesson, interdc/dep.py).

INGEST_* metric families (stats.py) record the economy: flushes by
trigger kind, coalesced ops, H2D bytes, and the ops-per-dispatch
amortization gauge the benches gate on directionally
(tools/bench_gate.py: ops/dispatch up, B/op down).

**Wire-to-scatter (ISSUE 6).**  The batched shipping plane delivers a
whole inter-DC batch frame's txns as ONE dependency-gate arrival
(interdc/sub_buf.py ``process_batch`` -> dep.py ``enqueue_batch``),
so the gate admits them in one wave and their decoded ops stage into
this plane back-to-back — inside one ``mat_coalesce_us`` window by
construction.  A wire frame of N txns therefore lands as a handful of
packed flushes (often one), not N per-txn staging rounds: the wire's
frame economy and this plane's dispatch economy compose end-to-end.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu import stats
from antidote_tpu.mat import store
from antidote_tpu.obs.prof import kernel_span
from antidote_tpu.obs.spans import tracer

#: flush trigger kinds (the ``kind`` label of
#: antidote_ingest_flushes_total): ``rows`` = the device_flush_ops
#: threshold, ``window`` = the coalescing window expired, ``budget`` =
#: the hard row cap forced an inline flush, ``read`` = a reader needed
#: pending rows, ``gc`` = a fold horizon flushed first, ``grow`` = a
#: capacity regrade drained stale-width rows, ``explicit`` = an
#: operator/test flush
INGEST_FLUSH_KINDS = ("rows", "window", "budget", "read", "gc", "grow",
                      "explicit")

_MIN_BUCKET = 64


def bucket(n: int) -> int:
    """Dispatch bucket (powers of FOUR, like the device plane's):
    coarse quantization keeps the XLA program count small at the cost
    of <=4x padding on the rare odd-sized batch."""
    b = _MIN_BUCKET
    while b < n:
        b *= 4
    return b


@dataclass(frozen=True)
class IngestSettings:
    """The ingest plane's knobs — built from Config by
    :func:`ingest_from_config` (the single factory) so every assembly
    honors the same values."""

    #: packed single-upload flushes; False = the legacy per-column
    #: append path (kept as the benches' comparison baseline)
    enabled: bool = True
    #: staging window, µs: rows younger than this may wait for more
    #: arrivals; 0 disables the window (threshold-only flushing)
    coalesce_us: int = 2000
    #: hard staged-row cap per plane: past it the committer flushes
    #: INLINE (backpressure so a lagging flusher cannot let staged
    #: rows grow unboundedly)
    row_budget: int = 8192


def ingest_from_config(config) -> IngestSettings:
    """The one construction path for ingest settings — DevicePlane and
    the sharded stores both call this, so the single-shard and mesh
    assemblies cannot silently honor different knobs."""
    if config is None:
        return IngestSettings()
    return IngestSettings(
        enabled=config.mat_ingest,
        coalesce_us=config.mat_coalesce_us,
        row_budget=config.mat_coalesce_rows)


# ---------------------------------------------------------------------------
# packed layout
#
# The payload section of a packed tensor IS the store's ops-row layout,
# so the device side never shuffles columns.  The plane's decoded rows
# arrive in ``_row_cols`` (append-argument) order; ``PACKED_PERMS``
# maps that order onto the ops layout per store append.  Keyed by
# __name__: the store appends are kernel_span-wrapped but keep their
# names (functools.wraps), and names are stable across the wrapping.

PACKED_PERMS = {
    # ops: [elem, is_add, dot_dc, dot_seq, op_dc, op_ct, obs(D), ss(D)]
    # cols: (slot, is_add, dot_dc, dot_seq, obs_vv, op_dc, op_ct, op_ss)
    "orset_append": (0, 1, 2, 3, 5, 6, 4, 7),
    # ops: [elem, kind, dot_dc, dot_seq, op_dc, op_ct, obs_add(D),
    #       obs_rmv(D), ss(D)]
    # cols: (slot, kind, dot_dc, dot_seq, obs_add, obs_rmv, op_dc,
    #        op_ct, op_ss)
    "rwset_append": (0, 1, 2, 3, 6, 7, 4, 5, 8),
    # ops: [delta, op_dc, op_ct, ss(D)] == cols order
    "counter_append": (0, 1, 2, 3),
    # ops: [ts, tie, val, op_dc, op_ct, ss(D)] == cols order
    "lww_append": (0, 1, 2, 3, 4, 5),
    # ops: [elem, op_dc, op_ct, ss(D)] == cols order
    "setgo_append": (0, 1, 2, 3),
}


def perm_for(append_fn) -> Optional[Tuple[int, ...]]:
    """The ops-layout permutation for a store append, or None when the
    plane has no packed form (RGA documents go through
    rga_store.rga_append_coalesced instead)."""
    return PACKED_PERMS.get(getattr(append_fn, "__name__", ""))


def packed_width(row_cols: Tuple[str, ...], d: int) -> int:
    """Ops-row column count for a plane's row tags ("s" scalar / "vv"
    dense [d] clock)."""
    return sum(d if tag == "vv" else 1 for tag in row_cols)


def pack_rows(rows, capacity: int, d: int, row_cols: Tuple[str, ...],
              perm: Tuple[int, ...]) -> np.ndarray:
    """Coalesce decoded plane rows into ONE packed host tensor
    ``int64[B, 2+F]`` (B = dispatch bucket): column 0 = key index
    (padding rows carry the ``capacity`` drop sentinel, exactly like
    the legacy packer), column 1 = lane offset, then the ops-row
    payload in store layout.  This is the single H2D of a flush."""
    n = len(rows)
    B = bucket(n)
    F = packed_width(row_cols, d)
    out = np.zeros((B, 2 + F), dtype=np.int64)
    out[:, 0] = capacity  # padding keys route to the drop slot
    # column offsets of each row field (in _row_cols index space)
    offs = [0] * len(row_cols)
    off = 2
    for pos in perm:
        offs[pos] = off
        off += d if row_cols[pos] == "vv" else 1
    for i, row in enumerate(rows):
        out[i, 0] = row[0]
        for j, (tag, v) in enumerate(zip(row_cols, row[1:])):
            o = offs[j]
            if tag == "vv":
                for col, s in v:
                    if s > out[i, o + col]:
                        out[i, o + col] = s
            else:
                out[i, o] = v
    out[:n, 1] = store.batch_lane_offsets(out[:n, 0])
    return out


def split_packed(packed: jax.Array, ops_dtype):
    """Device-side split of a packed tensor into the scatter epilogue's
    arguments — shared by :func:`packed_append` and the sharded
    stores' shard_map bodies (mat/sharded.py append_packed)."""
    key_idx = packed[:, 0].astype(jnp.int32)
    lane_off = packed[:, 1].astype(jnp.int32)
    rows = packed[:, 2:].astype(ops_dtype)
    return key_idx, lane_off, rows


@kernel_span("mat.ingest")
@partial(jax.jit, donate_argnums=(0,))
def packed_append(st, packed: jax.Array,
                  active: jax.Array | None = None):
    """Apply one coalesced flush: split the packed tensor's key/lane
    columns and land every row with the store's single donated
    scatter.  Generic over every packed-ring shard state (the payload
    section is already in that state's ops layout); returns
    (state, overflow[B]) with the stores' usual contract (padding and
    masked-off rows never overflow).

    DONATES ``st``'s buffers like the per-column appends it replaces —
    callers must treat the argument as consumed."""
    key_idx, lane_off, rows = split_packed(packed, st.ops.dtype)
    return store._scatter_rows(st, key_idx, lane_off, rows, active)


# ---------------------------------------------------------------------------
# metrics

def note_flush(kind: str) -> None:
    """Count one flush event by trigger kind.  The instant also lands
    on the trace timeline (ISSUE 7): a sampled txn's journey shows the
    packed flush that made its staged ops device-visible right after
    its ``depgate_admit`` span — untagged, so partial sample rates
    thin it instead of flooding the ring."""
    stats.registry.ingest_flushes.inc(kind=kind)
    tracer.instant("ingest_flush", "device", kind=kind)


def note_dispatch(ops: int, h2d_bytes: int, replicas: int = 1) -> None:
    """Record one packed device dispatch (``ops`` coalesced rows in
    one ``h2d_bytes`` upload) and refresh the amortization gauge —
    coalesced ops per dispatch over the process lifetime, the panel
    and bench row the ISSUE's acceptance gates on.  ``replicas``: how
    many chips the upload lands on (the sharded stores replicate the
    packed batch over the mesh, mat/sharded.py) — the byte counter
    reports the REAL H2D traffic, not the logical tensor size."""
    reg = stats.registry
    reg.ingest_dispatches.inc()
    reg.ingest_coalesced_ops.inc(ops)
    reg.ingest_h2d_bytes.inc(h2d_bytes * max(int(replicas), 1))
    total = reg.ingest_dispatches.value()
    if total:
        reg.ingest_ops_per_dispatch.set(
            reg.ingest_coalesced_ops.value() / total)

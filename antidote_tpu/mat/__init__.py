from antidote_tpu.mat.materializer import (  # noqa: F401
    MaterializedSnapshot,
    MaterializeResult,
    Payload,
    SnapshotGetResponse,
    materialize,
    materialize_eager,
    op_covered_by,
    op_in_read_snapshot,
)

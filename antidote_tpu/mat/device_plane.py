"""Live device data plane — the TPU shard store serving the running DC.

This is the integration layer that makes the device materializer
(antidote_tpu/mat/store.py) the system's spine instead of a benchmark
sidecar: PartitionManager routes committed effects of supported types
here (local commits, inter-DC applies, and log recovery all take the
same path), transaction reads come back from batched device folds, and
the gossiped stable snapshot (antidote_tpu/meta/gossip.py) drives the
device GC.  The modelled duty is the reference's materializer_vnode —
update/read as the running database's data plane (reference
src/materializer_vnode.erl:56-110), with the per-key gen_server walk
replaced by padded-batch appends and lattice folds.

Host-side duties (this module): interning arbitrary Python keys,
elements, and DC ids into dense indices; buffering staged effects into
padded append blocks (amortizing dispatch); and fallback policy.  A key
*evicts* to the host path — its device rows purged, its history rebuilt
into the host store by log replay — when it exceeds its element-slot or
ring-lane capacity; reads below the device base snapshot replay the log,
exactly the reference's snapshot-cache miss
(src/materializer_vnode.erl:415-419).

Correctness contract: the dense dot tables collapse each (element,
origin-DC) dot set to its max sequence, which is the ORSWOT invariant —
sound because dots are minted per-DC-monotone (txn/node.py mint_dot) and
write-write certification serializes same-key commits at a DC.  Ops
whose dots carry actors that are not DC ids (foreign tooling writing
through the log) still work: actors get their own dense columns, capped
by ``max_dcs`` before the key evicts to the host path.

Shapes are static per (capacity, bucket): append batches pad to
power-of-two buckets so XLA compiles a handful of programs, not one per
batch size.  Capacity growth (keys / element slots / DC columns) is a
rare host-side repack (store.orset_grow).
"""

from __future__ import annotations

import atexit
import contextlib
import logging
import threading
import time
from dataclasses import replace as dc_replace
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh as _Mesh

from antidote_tpu import stats
from antidote_tpu.clocks import VC, ClockDomain
from antidote_tpu.obs import prof
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.runtime import COLLECTIVE_LOCK
from antidote_tpu.mat import ingest, store
from antidote_tpu.mat.materializer import Payload

log = logging.getLogger(__name__)

#: "read latest": dominates every real µs timestamp without overflowing
#: int64 arithmetic in the fold
_VC_INF = (1 << 62)

#: the ONE dispatch-bucket quantizer (powers of four, floor 64) —
#: shared with the packed ingest packer so serving flushes and warm
#: compiles can never bucket to different shapes (mat/ingest.py)
_MIN_BUCKET = ingest._MIN_BUCKET
_bucket = ingest.bucket


#: read-fold dispatch counter (tests assert the fused cross-partition
#: path issues <= n_devices programs per multi-partition read)
_read_dispatches = 0


def count_read_dispatch() -> None:
    global _read_dispatches
    _read_dispatches += 1


def read_dispatch_count() -> int:
    return _read_dispatches


#: one compiled program per CANONICALIZED combination of fused store
#: calls (entries sorted by function, so access order doesn't mint new
#: programs); jax's own cache handles per-shape specialization under
#: each entry.  Any k same-type planes share one entry regardless of
#: which partitions they are.  Bounded: a pattern explosion clears the
#: table rather than growing it forever (the jit objects are cheap to
#: rebuild; the underlying executables live in jax's own cache).
_FUSED_CACHE: Dict[tuple, Any] = {}
_FUSED_CACHE_CAP = 64


def fused_read(splits: list) -> list:
    """Run many planes' batched read folds as ONE XLA program — the
    cross-partition read for a ring-placed node: all captures must sit
    on one chip; the caller groups by ``closure.device`` (reference:
    the coordinator's async batched reads,
    src/clocksi_interactive_coord.erl:731-747, lifted from
    per-partition to per-chip).  ``splits`` are the ``closure.split``
    pairs; returns their post-processed {key: state} dicts in order."""
    # canonical order: same multiset of store calls -> same program
    order = sorted(range(len(splits)),
                   key=lambda i: splits[i][0][0].__name__)
    fns = tuple(splits[i][0][0] for i in order)
    fn = _FUSED_CACHE.get(fns)
    if fn is None:
        if len(_FUSED_CACHE) >= _FUSED_CACHE_CAP:
            _FUSED_CACHE.clear()

        def body(argss, _fns=fns):
            return tuple(f(*a) for f, a in zip(_fns, argss))

        # one kernel-span name for every fused pattern: the per-pattern
        # jits differ, but the operator-facing question ("how long do
        # fused cross-partition reads take, how often do they compile")
        # is per call site
        fn = prof.profiler.wrap(jax.jit(body), name="fused_read",
                                subsystem="mat.device_plane")
        _FUSED_CACHE[fns] = fn
    count_read_dispatch()
    outs = fn(tuple(splits[i][0][1] for i in order))
    results: list = [None] * len(splits)
    for pos, i in enumerate(order):
        post = splits[i][1]
        results[i] = post(
            jax.tree_util.tree_map(np.asarray, outs[pos]))
    return results


def collective_guard(dev):
    """``COLLECTIVE_LOCK`` when ``dev`` is a mesh — the dispatch
    launches a multi-chip program, and runtime.py's invariant ("every
    collective launch site takes this lock") applies — else a no-op
    context, so the single-chip paths keep their lock-free read
    concurrency.  ``dev`` is the ``closure.device`` discriminator the
    fused-read callers already group by: sharded planes publish their
    mesh there (``_many_reader``), single-chip planes a Device."""
    if isinstance(dev, _Mesh):
        return COLLECTIVE_LOCK
    return contextlib.nullcontext()


class ReadBelowBase(Exception):
    """Read snapshot does not dominate the device base — serve from log."""


def _pack_rows(rows: List[tuple], capacity: int, d: int,
               cols: tuple) -> tuple:
    """Shared append packing: pad decoded rows to a power-of-two bucket
    and split them into per-column arrays.  ``cols`` tags each row field
    after the leading key index: "s" = int64 scalar, "vv" = (col, seq)
    pair list max-merged into a dense [B, d] vector clock.  Returns
    (key_idx[B], lane_off[B], arrays) in ``cols`` order — the exact
    argument order of the matching store ``*_append``."""
    n = len(rows)
    B = _bucket(n)
    key_idx = np.full(B, capacity, dtype=np.int32)
    arrays = [np.zeros((B, d) if tag == "vv" else B, dtype=np.int64)
              for tag in cols]
    for i, row in enumerate(rows):
        key_idx[i] = row[0]
        for a, tag, v in zip(arrays, cols, row[1:]):
            if tag == "vv":
                for col, s in v:
                    a[i, col] = max(a[i, col], s)
            else:
                a[i] = v
    lane_off = np.zeros(B, dtype=np.int32)
    lane_off[:n] = store.batch_lane_offsets(key_idx[:n])
    return key_idx, lane_off, arrays


#: (append_fn, state-shape signature, bucket) combos already compiled
#: (or being compiled) in this process — plane instances share XLA
#: programs class-wide, so one warm pass covers every partition
_WARMED: set = set()
_WARM_LOCK = threading.Lock()
_WARM_THREADS: List[threading.Thread] = []


def _join_warm_threads() -> None:
    # a daemon thread force-unwound MID-XLA-CALL at interpreter exit
    # aborts the process ("terminate called ... FATAL: exception not
    # rethrown"); give in-flight warms a bounded grace period instead
    for t in list(_WARM_THREADS):
        t.join(timeout=5.0)


atexit.register(_join_warm_threads)


class _PlaneBase:
    """Shared machinery: key directory, pending rows, flush/gc plumbing."""

    type_name: str = ""

    def __init__(self, domain: ClockDomain, key_capacity: int,
                 n_lanes: int, flush_ops: int, gc_ops: int,
                 max_dcs: int,
                 ingest_settings: Optional[ingest.IngestSettings] = None):
        self.domain = domain
        self.n_lanes = n_lanes
        self.flush_ops = flush_ops
        self.gc_ops = gc_ops
        self.max_dcs = max_dcs
        #: coalesced-ingest knobs (mat/ingest.py): packed single-H2D
        #: flushes, the staging window, and the row budget.  Built by
        #: the one factory (ingest_from_config) at the DevicePlane /
        #: sharded-store assembly so every plane honors the same knobs.
        self._ingest = ingest_settings or ingest.ingest_from_config(None)
        #: monotonic µs stamp of the oldest staged row (drives the
        #: coalescing window); meaningless while ``rows`` is empty
        self._stage_t0_us = 0
        self.key_index: Dict[Any, int] = {}
        self.rev_keys: List[Any] = []
        #: staged decoded rows (lists of python ints / pair-lists)
        self.rows: List[tuple] = []
        self.pending_keys: set = set()
        self._ops_since_gc = 0
        self._base_vc = VC()
        self._has_base = False
        #: newest stable snapshot seen (GC horizon for overflow retries)
        self._last_stable: Optional[VC] = None
        #: cached device-resident "read latest" snapshot (one device_put
        #: per domain width instead of one per read)
        self._inf_rv = None
        #: set by the owning PartitionManager: evict a key's history to
        #: the host store (log replay; ``state`` carries the pre-purge
        #: device fold when there is no log to replay — see
        #: ``evict_export``)
        self.on_evict: Callable[..., None] = \
            lambda k, t, state=None: None
        #: set (via DevicePlane.set_evict_handler) when the owning
        #: partition has NO durable log: an eviction must materialize
        #: the key's host state from the device fold BEFORE dropping
        #: the lanes — replaying the empty log silently zeroed the key
        #: (the PR-7-flagged bug, reproduced on clean HEAD)
        self.evict_export = False
        #: same condition, shared with map sub-planes (which export at
        #: the MAP level): drives the flush overflow path's emergency
        #: fold — with no log, dropping an overflowed row is DATA LOSS,
        #: so the ring folds fully into the base to make room first
        self.no_log_replay = False
        #: host-side join of every staged op's commit VC — the honest
        #: base bound after an emergency full fold (ring ops are all
        #: published, so their commit VCs are below this join).  Only
        #: maintained when ``no_log_replay`` (DevicePlane.stage).
        self._ring_vc_bound = VC()
        #: re-entrancy guard: the export fold must not recurse through
        #: a flush back into this key's own eviction
        self._exporting: set = set()
        self.capacity = key_capacity
        self.st = self._init_state(key_capacity)
        #: background compile kicked on the FIRST staged op for this
        #: plane (DevicePlane.stage): warming every type at node build
        #: would compile 11 types' programs nobody may ever use —
        #: costly, and on small hosts the compile threads compete with
        #: serving
        self._warm_kicked = False
        #: mesh this plane's state is GSPMD-sharded over (set by
        #: DevicePlane.place_sharded; None = single-chip).  While set,
        #: every state-array dispatch is a MULTI-CHIP program and must
        #: serialize under runtime.COLLECTIVE_LOCK (_collective_cm)
        self._mesh = None
        #: per-shard residency router (mat/sharded.ShardRouter), wired
        #: alongside the mesh
        self._router = None

    # -- subclass hooks -----------------------------------------------------

    def _init_state(self, key_capacity: int):
        raise NotImplementedError

    def _grow_dcs(self, new_d: int) -> None:
        raise NotImplementedError

    def _grow_keys(self, new_k: int) -> None:
        raise NotImplementedError

    #: row-field tags after the leading key index ("s" scalar / "vv"
    #: pair list) — must match the argument order of ``_append_fn``
    _row_cols: tuple = ()
    #: the store's ``*_append`` for this plane's shard state
    _append_fn = None

    def kick_warm(self) -> None:
        """Idempotent first-use trigger for warm_appends."""
        if not self._warm_kicked:
            self._warm_kicked = True
            self.warm_appends()

    def warm_appends(self, buckets: tuple = (64, 256)) -> None:
        """Compile this plane's append programs for every dispatch
        bucket BEFORE the serving path needs them, in a background
        thread (XLA compilation is C++ work that releases the GIL, so
        commits keep flowing).  Without this, the first flush at an
        unseen bucket shape pays a ~300 ms in-line compile UNDER the
        partition lock — measured as the dominant config6 p99 term and
        the cluster data node's commit convoy.  The warm rows are all
        padding (key index = capacity, _pack_rows' sentinel), so
        executing the program is a no-op on the discarded result."""
        if type(self)._append_fn is None or self._mesh is not None:
            # sharded planes never warm in the background: the copies'
            # dispatches are multi-chip programs, and a warm thread
            # cannot take COLLECTIVE_LOCK without convoying the
            # serving path behind a ~300ms compile
            return
        packed_mode = (self._ingest.enabled
                       and self._packed_perm() is not None)
        shapes = tuple(
            (tuple(x.shape), str(getattr(x, "dtype", "")))
            for x in jax.tree_util.tree_leaves(self.st))
        base_key = (id(ingest.packed_append) if packed_mode
                    else id(type(self)._append_fn), shapes)
        todo = []
        with _WARM_LOCK:
            for b in buckets:
                k = base_key + (b,)
                if k not in _WARMED:
                    _WARMED.add(k)
                    todo.append(b)
        if not todo:
            return
        d, cols, cap = self.domain.d, self._row_cols, self.capacity
        fn = type(self)._append_fn
        # the append DONATES its state buffers — warm on a copy, never
        # the live state.  The copy is taken HERE, synchronously: this
        # runs from __init__ (or a grow site under the partition lock),
        # before concurrent appends could donate the buffers out from
        # under a background tree_map.
        st_copy = jax.tree_util.tree_map(jnp.copy, self.st)

        def run():
            st = st_copy
            for b in todo:
                try:
                    if packed_mode:
                        # the serving path is the packed single-upload
                        # flush: warm ITS program at the same buckets
                        pk = np.zeros(
                            (b, 2 + ingest.packed_width(cols, d)),
                            dtype=np.int64)
                        pk[:, 0] = cap  # all padding: a no-op program
                        st, _over = ingest.packed_append(
                            st, jnp.asarray(pk))
                        continue
                    ki = np.full(b, cap, dtype=np.int32)
                    lo = np.zeros(b, dtype=np.int32)
                    arrays = [np.zeros((b, d) if tag == "vv" else b,
                                       dtype=np.int64) for tag in cols]
                    st, _over = fn(st, jnp.asarray(ki),
                                   jnp.asarray(lo),
                                   *(jnp.asarray(a) for a in arrays))
                except Exception:  # noqa: BLE001 — warm is best-effort
                    log.debug("append warm failed", exc_info=True)
                    return

        _WARM_THREADS[:] = [t for t in _WARM_THREADS if t.is_alive()]
        t = threading.Thread(target=run, daemon=True,
                             name=f"warm:{self.type_name}")
        _WARM_THREADS.append(t)
        t.start()

    def warm_reads(self, buckets: tuple = (1, 64)) -> None:
        """Background-compile this plane's READ fold at the CURRENT
        state shapes.  The first read after a capacity growth
        recompiles the fold on whatever client thread issued it —
        measured 0.35-1 s inline (the dominant config6 p99 spike
        together with the growth itself); warming runs it on a copy in
        a compile thread instead.  Buckets cover the single-key reader
        (shape 1) and the first batched-dispatch bucket."""
        if self._mesh is not None:
            return  # see warm_appends: no background mesh dispatches
        shapes = tuple(
            (tuple(x.shape), str(getattr(x, "dtype", "")))
            for x in jax.tree_util.tree_leaves(self.st))
        base_key = ("read", id(type(self)), shapes)
        todo = []
        with _WARM_LOCK:
            for b in buckets:
                k = base_key + (b,)
                if k not in _WARMED:
                    _WARMED.add(k)
                    todo.append(b)
        if not todo:
            return
        try:
            rv = self._read_vc_dense(None)
        except ReadBelowBase:  # pragma: no cover — latest never raises
            return
        # reads are pure but appends DONATE the state buffers — warm on
        # a copy taken here, under the caller's partition lock
        st_copy = jax.tree_util.tree_map(jnp.copy, self.st)
        specs = []
        for b in todo:
            pad = np.zeros(b, dtype=np.int32)
            try:
                spec, _post = self._many_split(
                    st_copy, [], np.zeros(0, dtype=np.int32), pad, rv)
            except NotImplementedError:
                return  # per-document planes (RGA) have no batch fold
            specs.append(spec)

        def run():
            for fn, args in specs:
                try:
                    jax.block_until_ready(fn(*args))
                except Exception:  # noqa: BLE001 — warm is best-effort
                    log.debug("read warm failed", exc_info=True)
                    return

        _WARM_THREADS[:] = [t for t in _WARM_THREADS if t.is_alive()]
        t = threading.Thread(target=run, daemon=True,
                             name=f"warm-read:{self.type_name}")
        _WARM_THREADS.append(t)
        t.start()

    def _collective_cm(self):
        """COLLECTIVE_LOCK while mesh-sharded (every dispatch on the
        state is a multi-chip program — runtime.py's invariant), a
        no-op context on the single-chip path."""
        if self._mesh is not None:
            return COLLECTIVE_LOCK
        return contextlib.nullcontext()

    def _reshard(self) -> None:
        """Re-place the state per the rule table (mat/sharded.py).
        GSPMD does not promise jit outputs keep their inputs'
        shardings, and a grow rebuilds arrays on the default device —
        re-placing after every flush/GC/grow keeps drift from
        accumulating (device_put to an identical sharding is free)."""
        if self._mesh is not None:
            from antidote_tpu.mat import sharded as _sharded

            self.st = _sharded.place_state(self._mesh, self.st)

    def _post_grow(self) -> None:
        """After any capacity growth: compile the append AND read
        programs for the new shapes off the serving threads (or, for
        a mesh-sharded plane, re-shard the regrown arrays in place —
        the grow rebuilt them unsharded on the default device)."""
        if self._mesh is not None:
            self._reshard()
            return
        self.warm_appends()
        self.warm_reads()

    def _packed_perm(self):
        """Ops-layout permutation for this plane's packed flushes, or
        None when the store has no packed form."""
        return ingest.perm_for(type(self)._append_fn)

    def _append_rows(self, rows: List[tuple]) -> np.ndarray:
        """Device-append decoded rows; returns bool[n] overflow.

        Coalesced path (mat/ingest.py, default): ONE packed host
        tensor, ONE upload, one donated-scatter dispatch.  Legacy path
        (``mat_ingest=False``): the historical per-column packing —
        ~10 separate uploads per flush — kept as the benches'
        comparison baseline."""
        n = len(rows)
        if n == 0:
            return np.zeros(0, dtype=bool)
        perm = self._packed_perm()
        if self._ingest.enabled and perm is not None:
            packed = ingest.pack_rows(rows, self.capacity,
                                      self.domain.d, self._row_cols,
                                      perm)
            with self._collective_cm():
                self.st, overflow = ingest.packed_append(
                    self.st, jnp.asarray(packed))
            ingest.note_dispatch(
                n, packed.nbytes,
                replicas=(self._mesh.shape["part"]
                          if self._mesh is not None else 1))
            return np.asarray(overflow)[:n]
        ki, lo, arrays = _pack_rows(rows, self.capacity, self.domain.d,
                                    self._row_cols)
        with self._collective_cm():
            self.st, overflow = type(self)._append_fn(
                self.st, jnp.asarray(ki), jnp.asarray(lo),
                *(jnp.asarray(a) for a in arrays))
        return np.asarray(overflow)[:n]

    def _purge_idx(self, idx: int) -> None:
        raise NotImplementedError

    def _device_gc(self, gst_dense: np.ndarray) -> None:
        raise NotImplementedError

    def _run_device_gc(self, gst_dense: np.ndarray) -> None:
        """The one `_device_gc` launch point: serialized under the
        collective lock while mesh-sharded (the fold is a multi-chip
        program)."""
        with self._collective_cm():
            self._device_gc(gst_dense)

    # -- directories --------------------------------------------------------

    def _dc_col(self, actor) -> Optional[int]:
        """Dense column for a DC id / dot actor; None = over capacity."""
        if not self.domain.contains(actor):
            if len(self.domain) >= self.max_dcs:
                return None
            if len(self.domain) >= self.domain.d:
                self.flush("grow")  # staged rows decoded at the old width
                new_d = min(self.domain.d * 2, self.max_dcs)
                self.domain = self.domain.grow(new_d)
                self._grow_dcs(new_d)
                self._post_grow()
        return self.domain.index_of(actor)

    def _key_idx(self, key) -> int:
        idx = self.key_index.get(key)
        if idx is None:
            if len(self.rev_keys) >= self.capacity:
                self.flush("grow")
                self.capacity *= 2
                self._grow_keys(self.capacity)
                self._post_grow()
            idx = len(self.rev_keys)
            self.key_index[key] = idx
            self.rev_keys.append(key)
        return idx

    def _ss_pairs(self, vc: VC) -> Optional[List[tuple]]:
        out = []
        for dc, t in vc.items():
            if not t:
                continue
            col = self._dc_col(dc)
            if col is None:
                return None
            out.append((col, int(t)))
        return out

    def _dense_vc(self, pairs: List[tuple]) -> np.ndarray:
        row = np.zeros(self.domain.d, dtype=np.int64)
        for col, t in pairs:
            row[col] = max(row[col], t)
        return row

    def _decode_obs(self, observed) -> Optional[List[tuple]]:
        """Dense (col, seq) pairs for an observed-dot list; None on a
        DC-column capacity miss (caller evicts to the host path)."""
        out = []
        for a, s in observed:
            col = self._dc_col(a)
            if col is None:
                return None
            out.append((col, int(s)))
        return out

    def _note_staged_vc(self, payload: Payload) -> None:
        """Track the join of staged commit VCs (unlogged mode only) —
        the honest base bound the emergency fold raises to."""
        if self.no_log_replay:
            self._ring_vc_bound = self._ring_vc_bound.join(
                payload.commit_vc())

    def _commit_rows(self, key, idx: int, rows: List[tuple]) -> None:
        """Stage decoded rows — unless a growth-triggered flush evicted
        the key mid-stage (the migration replayed the log, which already
        holds this op; staging would write into purged lanes)."""
        if self.key_index.get(key) != idx:
            return
        if not self.rows:
            self._stage_t0_us = time.monotonic_ns() // 1000
        self.rows.extend(rows)
        self.pending_keys.add(key)

    # -- lock-free read split ------------------------------------------------

    def read_begin(self, key, read_vc: Optional[VC]):
        """MUST run under the partition lock: flush the key's staged
        rows, resolve directories, and capture the (immutable) device
        state.  Returns a zero-arg closure that materializes the value
        and may run OUTSIDE the lock — the shard state is a functional
        pytree, so a concurrent flush/GC only swaps ``self.st`` with a
        new value and never mutates what the closure captured.  This is
        the read-concurrency analogue of the reference's shared-ETS
        readers next to the vnode process (reference
        src/clocksi_readitem_server.erl:95-110)."""
        if key in self.pending_keys:
            self.flush("read")
        idx = self.key_index.get(key)
        if idx is None:
            raise ReadBelowBase()  # evicted during the flush — host path
        rv = self._read_vc_dense(read_vc)
        st = self.st
        r = self._reader(st, idx, rv)
        if self._mesh is None:
            return r

        def locked_read():
            # mesh-sharded: the fold is a multi-chip launch — same
            # serialization rule as the appends (runtime.py invariant)
            with COLLECTIVE_LOCK:
                return r()

        return locked_read

    def _reader(self, st, idx: int, rv):
        """Subclass hook: closure materializing key ``idx`` of the
        captured state at dense snapshot ``rv``."""
        raise NotImplementedError

    def read_many_begin(self, keys: list, read_vc: Optional[VC]):
        """Batched :meth:`read_begin`: one captured state + one device
        fold for every device-owned key in ``keys``.  Returns a closure
        yielding {key: value} (non-owned keys absent — callers serve
        them from the host path); safe to run outside the lock like
        read_begin's closure."""
        if self.pending_keys and not self.pending_keys.isdisjoint(keys):
            self.flush("read")
        owned = [k for k in keys if k in self.key_index]
        if not owned:
            return dict
        rv = self._read_vc_dense(read_vc)
        idxs = np.asarray([self.key_index[k] for k in owned],
                          dtype=np.int32)
        pad = np.zeros(_bucket(len(idxs)), dtype=np.int32)
        pad[:len(idxs)] = idxs
        return self._many_reader(self.st, owned, idxs, pad, rv)

    def _many_split(self, st, owned: list, idxs: np.ndarray,
                    pad: np.ndarray, rv):
        """Subclass hook: ``((fn, args), post)`` — the batched read
        split into its device half (a jitted store call; ``fn(*args)``
        yields the fold's array pytree) and its host half (``post``
        maps the np-converted arrays to {key: state}).  The split is
        what lets the FUSED cross-partition path (fused_read, below)
        run many planes' folds from one chip as a single XLA program
        — one dispatch per chip instead of one per partition."""
        raise NotImplementedError

    def _many_reader(self, st, owned: list, idxs: np.ndarray,
                     pad: np.ndarray, rv):
        """Closure materializing the owned keys in one batched fold of
        the captured state (``pad`` = idxs padded to the dispatch
        bucket).  Carries ``.split``/``.device`` so a cross-partition
        caller can fuse this fold with other planes' (see fused_read);
        planes with no batched-fold form (RGA's per-document trees)
        override this without a split."""
        spec, post = self._many_split(st, owned, idxs, pad, rv)
        fn, args = spec

        def run():
            count_read_dispatch()
            with self._collective_cm():
                out = fn(*args)
                out = jax.tree_util.tree_map(np.asarray, out)
            return post(out)

        run.split = (spec, post)
        if self._mesh is not None:
            # the mesh IS the fusing discriminator: every sharded
            # plane's fold is the same multi-chip program family, so
            # cross-partition callers group them all into ONE
            # fused_read (leaf.devices() would be nondeterministic
            # for a sharded array — any of N chips — and break the
            # grouping)
            run.device = self._mesh
        else:
            leaf = jax.tree_util.tree_leaves(st)[0]
            run.device = next(iter(leaf.devices())) \
                if hasattr(leaf, "devices") else None
        return run

    def read_many(self, keys: list, read_vc: Optional[VC]) -> dict:
        """{key: state} for device-owned keys; callers take the host
        path for the rest."""
        return self.read_many_begin(keys, read_vc)()

    def read(self, key, read_vc: Optional[VC]):
        """The key's host-CRDT state at ``read_vc``, materialized by
        this plane's device fold (state shape documented on each
        subclass's ``_reader`` hook)."""
        return self.read_begin(key, read_vc)()

    def seed_effects(self, state) -> Optional[list]:
        """Effects that rebuild ``state`` exactly from bottom when
        staged through this plane's own decoder — the checkpoint-seed
        device re-init (ISSUE 13): a restarted node re-ingests each
        folded seed as ordinary rows (the packed ingest upload) and
        folds them into the device base at the seed frontier.  None =
        this plane cannot represent a bare state as effects (RGA's
        per-document trees, the STATE_LOSSY dot collapses) — the key
        stays on the host path, exactly the pre-seed behavior.  The
        round trip is the inverse of ``_reader``/the evict export:
        seed_effects(read()) staged onto an empty plane reads back
        identical (pinned per type by tests/unit/test_ckpt_segments
        .py)."""
        return None


    # -- lifecycle ----------------------------------------------------------

    def owns(self, key) -> bool:
        return key in self.key_index

    def _export_evict_state(self, key):
        """The key's latest device-fold state, captured BEFORE the
        purge, when there is no log to replay (``evict_export``);
        None otherwise.  Best-effort: a failed export falls back to
        the (empty) log replay rather than wedging the eviction."""
        if not self.evict_export or key in self._exporting:
            return None
        self._exporting.add(key)
        try:
            return self.read(key, None)
        except Exception:  # noqa: BLE001 — export must not break evict
            log.exception(
                "evict-state export failed for %r (%s); the key's "
                "unlogged history cannot migrate to the host store",
                key, self.type_name)
            return None
        finally:
            self._exporting.discard(key)

    def evict(self, key) -> None:
        """Purge the key's device rows and hand its history to the host
        path (on_evict replays the log into the host store; with no log
        to replay, the pre-purge device fold travels along — the state
        the host store is seeded from)."""
        idx = self.key_index.get(key)
        if idx is None:
            return
        state = self._export_evict_state(key)
        self.key_index.pop(key, None)
        self.rows = [r for r in self.rows if r[0] != idx]
        self.pending_keys.discard(key)
        self.rev_keys[idx] = _Evicted
        with self._collective_cm():
            self._purge_idx(idx)
        if self._router is not None:
            # the owning shard's lanes just overflowed (or the key was
            # displaced): charge that shard's economy so it stops
            # admitting new device residents until the next fold
            self._router.note_evict(idx, self.capacity)
        log.debug("device plane: evicted %r (%s)", key, self.type_name)
        recorder.record("device", "evict", plane=self.type_name,
                        key=key)
        self.on_evict(key, self.type_name, state)

    #: set by DevicePlane.stage when async flushing is wired: called
    #: with this plane to run flush/gc on the flusher thread
    _schedule = None

    def _window_due(self, n_rows: int) -> bool:
        """True when staged rows outlived the coalescing window
        (mat_coalesce_us): the next stage tick flushes the whole burst
        as one dispatch even below the flush_ops threshold — bounded
        device-state staleness, the gate-ring window's plane analogue."""
        return (n_rows > 0 and self._ingest.coalesce_us > 0
                and (time.monotonic_ns() // 1000 - self._stage_t0_us)
                >= self._ingest.coalesce_us)

    def maybe_flush_gc(self, stable_vc: Optional[VC]) -> None:
        if stable_vc is not None:
            self._last_stable = (stable_vc if self._last_stable is None
                                 else self._last_stable.join(stable_vc))
        n_rows = len(self.rows)
        window_due = self._window_due(n_rows)
        due_flush = n_rows >= self.flush_ops or window_due
        due_gc = (stable_vc is not None
                  and self._ops_since_gc >= self.gc_ops)
        if not (due_flush or due_gc):
            return
        if self._schedule is not None \
                and n_rows < min(4 * self.flush_ops,
                                 self._ingest.row_budget):
            # group commit: the committing transaction only stages; the
            # device work runs on the flusher thread.  Past 4x the
            # threshold (or the ingest row budget, whichever is
            # tighter) the committer flushes INLINE — backpressure so
            # a lagging flusher cannot let staged rows grow unboundedly
            self._schedule(self)
            return
        if due_flush:
            if n_rows >= self._ingest.row_budget:
                kind = "budget"
            elif n_rows >= self.flush_ops:
                kind = "rows"
            else:
                kind = "window"
            self.flush(kind)
        if due_gc:
            self.gc(self._last_stable or stable_vc)

    def flush_gc_now(self) -> None:
        """Flusher-thread entry: run any due flush/GC (caller holds the
        partition lock and has quiesced device readers)."""
        n_rows = len(self.rows)
        if n_rows >= self.flush_ops:
            self.flush("rows")
        elif self._window_due(n_rows):
            self.flush("window")
        if self._last_stable is not None \
                and self._ops_since_gc >= self.gc_ops:
            self.gc(self._last_stable)
        self._maybe_speculative_grow()

    def _maybe_speculative_grow(self) -> None:
        """Double the key directory BEFORE stage() must do it inline:
        a grow is a host repack + re-upload plus fresh XLA programs at
        the new shapes — on the commit path that was the dominant
        config6 p99 term (0.7-2.5 s in-run recompile spikes after a
        doubling).  Here it runs on the background flusher, under the
        partition lock with readers quiesced, and the new programs
        warm before the serving threads first use them."""
        if len(self.rev_keys) * 8 >= self.capacity * 7:
            self.flush("grow")
            self.capacity *= 2
            self._grow_keys(self.capacity)
            self._post_grow()

    def flush(self, kind: str = "explicit") -> None:
        """Drain staged rows into the device ring, padded to a bucket.
        Rows whose key ring is full force a GC at the newest stable
        snapshot and one retry; still-overflowing keys evict to the
        host path.  ``kind`` labels the flush trigger for the INGEST_*
        counters (mat/ingest.py INGEST_FLUSH_KINDS)."""
        if not self.rows:
            return
        ingest.note_flush(kind)
        rows, self.rows = self.rows, []
        self.pending_keys.clear()
        # chunk at the configured batch size: a backlog above flush_ops
        # would otherwise pad to a LARGER bucket and compile a fresh XLA
        # program mid-run (one 700ms stall per new shape on CPU); the
        # chunk size is the intended steady-state batch anyway
        step = max(self.flush_ops, _MIN_BUCKET)
        overflow = np.zeros(len(rows), dtype=bool)
        t0 = time.perf_counter()
        # the span and histogram cover the overflow-retry path too —
        # the forced GC + second append (possibly a fresh XLA compile)
        # dominate exactly the flushes the stage-latency panel hunts
        with prof.annotate(f"device_flush:{self.type_name}"), \
                tracer.span(f"device_flush:{self.type_name}", "device",
                            rows=len(rows)):
            for i in range(0, len(rows), step):
                overflow[i:i + step] = self._append_rows(
                    rows[i:i + step])
            self._ops_since_gc += len(rows)
            if overflow.any():
                retry = [r for r, o in zip(rows, overflow) if o]
                gst = None
                if self._last_stable is not None:
                    pairs = self._ss_pairs(self._last_stable)
                    if pairs is not None:
                        gst = self._dense_vc(pairs)
                        self._run_device_gc(gst)
                        self._base_vc = self._base_vc.join(
                            self._last_stable)
                        self._has_base = True
                        self._ops_since_gc = 0
                overflow2 = self._append_rows(retry)
                if gst is not None:
                    # invariant: every ring op with commit VC <=
                    # base_vc must be folded INTO the base — the
                    # retried rows landed after the fold above, so fold
                    # once more at the same horizon (rows above it are
                    # untouched)
                    self._run_device_gc(gst)
                if overflow2.any() and self.no_log_replay:
                    # EMERGENCY fold (unlogged mode): dropping an
                    # overflowed row here is permanent data loss — no
                    # log exists to replay it from — so fold the WHOLE
                    # ring into the base to free lanes and retry once
                    # more.  Sound: every ring op is published, so the
                    # host-side join of staged commit VCs bounds them;
                    # reads below the raised base take the log-replay
                    # path, which unlogged mode already degrades.
                    inf = np.full(self.domain.d, _VC_INF,
                                  dtype=np.int64)
                    self._run_device_gc(inf)
                    self._base_vc = self._base_vc.join(
                        self._ring_vc_bound)
                    self._has_base = True
                    self._ops_since_gc = 0
                    retry2 = [r for r, o in zip(retry, overflow2) if o]
                    overflow3 = self._append_rows(retry2)
                    if overflow3.any():
                        # structural caps (slots / DC columns): the
                        # rows are unrepresentable and, unlogged,
                        # unrecoverable — keep the loss loud
                        recorder.record(
                            "device", "evict_lost_rows",
                            plane=self.type_name,
                            rows=int(overflow3.sum()))
                    retry, overflow2 = retry2, overflow3
                bad_keys = {self.rev_keys[r[0]]
                            for r, o in zip(retry, overflow2) if o}
                for key in bad_keys:
                    if key is not _Evicted:
                        self.evict(key)
        self._reshard()
        stats.registry.device_flush_latency.observe(
            time.perf_counter() - t0)
        recorder.record("device", "flush", plane=self.type_name,
                        rows=len(rows),
                        overflow=int(overflow.sum()))

    def gc(self, stable_vc: VC) -> None:
        """Fold ops at/below the gossiped stable snapshot into the base
        (store.orset_gc / counter_gc contract: the GST is stable, folding
        is permanent)."""
        # let the flush's overflow-retry fold at this horizon too
        self._last_stable = (stable_vc if self._last_stable is None
                             else self._last_stable.join(stable_vc))
        self.flush("gc")
        pairs = self._ss_pairs(stable_vc)
        if pairs is None:
            return
        with prof.annotate(f"device_gc:{self.type_name}"), \
                tracer.span(f"device_gc:{self.type_name}", "device"):
            self._run_device_gc(self._dense_vc(pairs))
        self._reshard()
        if self._router is not None:
            # a fold freed ring lanes on every shard — reset the
            # overflow economy so shards re-admit device residents
            self._router.note_fold()
        recorder.record("device", "gc", plane=self.type_name,
                        horizon=dict(stable_vc))
        self._base_vc = self._base_vc.join(stable_vc)
        self._has_base = True
        self._ops_since_gc = 0

    def _read_vc_dense(self, read_vc: Optional[VC]):
        """Dense read snapshot (np for explicit VCs, the cached device
        array for read-latest — treat as immutable); raises
        ReadBelowBase when the requested snapshot does not dominate the
        device base (caller replays log)."""
        if read_vc is None:
            if self._inf_rv is None or \
                    self._inf_rv.shape[0] != self.domain.d:
                self._inf_rv = jnp.full((self.domain.d,), _VC_INF,
                                        dtype=jnp.int64)
            return self._inf_rv
        if self._has_base and not self._base_vc.le(read_vc):
            raise ReadBelowBase()
        pairs = self._ss_pairs(read_vc)
        if pairs is None:
            raise ReadBelowBase()  # unknown-DC flood: serve from log
        return self._dense_vc(pairs)


class _Evicted:
    """Sentinel occupying the rev_keys slot of an evicted key."""


class OrsetPlane(_PlaneBase):
    """Device plane for set_aw.  Row tuple:
    (key_idx, slot, is_add, dot_col, dot_seq, obs_pairs, op_dc_col,
    op_ct, ss_pairs)."""

    type_name = "set_aw"
    # (slot, is_add, dot_dc, dot_seq, obs_vv, op_dc, op_ct, op_ss)
    _row_cols = ("s", "s", "s", "s", "vv", "s", "s", "vv")
    _append_fn = staticmethod(store.orset_append)

    def __init__(self, domain, key_capacity, n_lanes, n_slots, flush_ops,
                 gc_ops, max_dcs, max_slots, ingest_settings=None):
        self.n_slots = n_slots
        self.max_slots = max_slots
        #: per key-idx: element -> slot and slot -> element
        self.elem_index: List[Dict[Any, int]] = []
        self.rev_elems: List[List[Any]] = []
        super().__init__(domain, key_capacity, n_lanes, flush_ops,
                         gc_ops, max_dcs,
                         ingest_settings=ingest_settings)

    def _init_state(self, key_capacity):
        return store.orset_shard_init(
            key_capacity, self.n_lanes, self.n_slots, self.domain.d,
            dtype=jnp.int64)

    def _grow_dcs(self, new_d):
        self.st = store.orset_grow(self.st, n_dcs=new_d)

    def _grow_keys(self, new_k):
        self.st = store.orset_grow(self.st, n_keys=new_k)

    def _grow_slots(self, new_e):
        self.flush("grow")
        self.n_slots = new_e
        self.st = store.orset_grow(self.st, n_slots=new_e)

    def _key_idx(self, key):
        idx = super()._key_idx(key)
        while len(self.elem_index) <= idx:
            self.elem_index.append({})
            self.rev_elems.append([])
        return idx

    def _slot(self, idx: int, elem) -> Optional[int]:
        slots = self.elem_index[idx]
        s = slots.get(elem)
        if s is None:
            if len(slots) >= self.n_slots:
                if len(slots) >= self.max_slots:
                    return None
                self._grow_slots(min(self.n_slots * 2, self.max_slots))
                self._post_grow()
            s = len(slots)
            slots[elem] = s
            self.rev_elems[idx].append(elem)
        return s

    def stage(self, key, payload: Payload) -> None:
        """Decode one committed set_aw effect into device rows; evicts
        the key (host fallback) on any capacity miss."""
        idx = self._key_idx(key)
        kind, entries = payload.effect
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        rows = []
        for entry in entries:
            if kind == "add":
                elem, dot, observed = entry
                actor, seq = dot
                dot_col = self._dc_col(actor)
                is_add = 1
            else:  # "rmv"
                elem, observed = entry
                dot_col, seq, is_add = 0, 0, 0
            slot = self._slot(idx, elem)
            obs_pairs = self._decode_obs(observed)
            if slot is None or obs_pairs is None or (
                    is_add and dot_col is None):
                self.evict(key)
                return
            rows.append((idx, slot, is_add, dot_col or 0, int(seq),
                         obs_pairs, op_dc_col, int(payload.commit_time),
                         ss_pairs))
        self._commit_rows(key, idx, rows)

    def seed_effects(self, state):
        # state: {elem: frozenset((actor, seq))} — one add per live
        # dot, empty observed set (removes nothing): the union of dots
        # IS the state, exactly what _reader reconstructs.  One ROW
        # per effect, so the seeder can chunk-fold dot-heavy keys
        # against the per-key lane budget.
        return [("add", [(elem, dot, ())])
                for elem, dots in state.items() for dot in dots]

    def _purge_idx(self, idx):
        self.st = store.orset_purge_keys(
            self.st, jnp.asarray([idx], dtype=np.int32))
        self.elem_index[idx] = {}
        self.rev_elems[idx] = []

    def _device_gc(self, gst_dense):
        self.st = store.orset_gc(self.st, jnp.asarray(gst_dense))


    def _reader(self, st, idx, rv):
        # captured under the lock; safe after release (see read_begin):
        # rev_elems[idx] / dc_ids are append-only, st is immutable
        elems = self.rev_elems[idx]
        domain = self.domain

        def run():
            dots = np.asarray(store.orset_read_keys(
                st, jnp.asarray([idx], dtype=np.int32),
                jnp.asarray(rv))[0])
            actors = domain.dc_ids
            state = {}
            for slot, elem in enumerate(list(elems)):
                if slot >= dots.shape[0]:
                    break  # slot grown after the capture: no dots yet
                live = frozenset(
                    (actors[j], int(s))
                    for j, s in enumerate(dots[slot][:len(actors)])
                    if s > 0)
                if live:
                    state[elem] = live
            return state

        return run

    def _many_split(self, st, owned, idxs, pad, rv):
        elem_lists = [self.rev_elems[i] for i in idxs]
        domain = self.domain

        def post(dots):
            actors = domain.dc_ids
            out = {}
            for i, k in enumerate(owned):
                state = {}
                for slot, elem in enumerate(list(elem_lists[i])):
                    if slot >= dots.shape[1]:
                        break  # slot grown after the capture
                    live = frozenset(
                        (actors[j], int(s))
                        for j, s in enumerate(dots[i, slot][:len(actors)])
                        if s > 0)
                    if live:
                        state[elem] = live
                out[k] = state
            return out

        return ((store.orset_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


class CounterPlane(_PlaneBase):
    """Device plane for counter_pn.  Row tuple:
    (key_idx, delta, op_dc_col, op_ct, ss_pairs)."""

    type_name = "counter_pn"
    # (delta, op_dc, op_ct, op_ss)
    _row_cols = ("s", "s", "s", "vv")
    _append_fn = staticmethod(store.counter_append)

    def _init_state(self, key_capacity):
        return store.counter_shard_init(
            key_capacity, self.n_lanes, self.domain.d, dtype=jnp.int64)

    def _grow_dcs(self, new_d):
        self.st = store.counter_grow(self.st, n_dcs=new_d)

    def _grow_keys(self, new_k):
        self.st = store.counter_grow(self.st, n_keys=new_k)

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        self._commit_rows(key, idx, [
            (idx, int(payload.effect), op_dc_col,
             int(payload.commit_time), ss_pairs)])

    def seed_effects(self, state):
        # state: int — one delta op rebuilds it
        return [int(state)] if state else []

    def _purge_idx(self, idx):
        self.st = store.counter_purge_keys(
            self.st, jnp.asarray([idx], dtype=np.int32))

    def _device_gc(self, gst_dense):
        self.st = store.counter_gc(self.st, jnp.asarray(gst_dense))


    def _reader(self, st, idx, rv):
        return lambda: int(store.counter_read_keys(
            st, jnp.asarray([idx], dtype=np.int32), jnp.asarray(rv))[0])

    def _many_split(self, st, owned, idxs, pad, rv):
        def post(vals):
            return {k: int(vals[i]) for i, k in enumerate(owned)}

        return ((store.counter_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


class MvregPlane(OrsetPlane):
    """Device plane for register_mv — the OR-Set ring with value slots
    (see store.py mvreg notes).  Row tuple identical to OrsetPlane's
    with elem := interned value; a reset row carries slot=n_slots (the
    drop slot) and seq=0, contributing only its observed VV."""

    type_name = "register_mv"

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        eff = payload.effect
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        if eff[0] == "asgn":
            _, v, dot, observed = eff
            try:
                slot = self._slot(idx, v)
            except TypeError:  # unhashable value — host path
                slot = None
            actor, seq = dot
            dot_col = self._dc_col(actor)
            ok = slot is not None and dot_col is not None
        else:  # "reset"
            _, observed = eff
            slot, dot_col, seq, ok = self.n_slots, 0, 0, True
        obs_pairs = self._decode_obs(observed) if ok else None
        if obs_pairs is None:
            self.evict(key)
            return
        self._commit_rows(key, idx, [
            (idx, slot, 1 if eff[0] == "asgn" else 0, dot_col or 0,
             int(seq), obs_pairs, op_dc_col, int(payload.commit_time),
             ss_pairs)])

    def seed_effects(self, state):
        # state: frozenset(((actor, seq), value)) — one un-observed
        # assign per live (dot, value) pair
        return [("asgn", v, dot, ()) for dot, v in state]

    def _device_gc(self, gst_dense):
        self.st = store.mvreg_gc(self.st, jnp.asarray(gst_dense))


    def _reader(self, st, idx, rv):
        vals = self.rev_elems[idx]
        domain = self.domain

        def run():
            dots = np.asarray(store.mvreg_read_keys(
                st, jnp.asarray([idx], dtype=np.int32),
                jnp.asarray(rv))[0])
            actors = domain.dc_ids
            pairs = set()
            for slot, v in enumerate(list(vals)):
                if slot >= dots.shape[0]:
                    break
                for j, s in enumerate(dots[slot][:len(actors)]):
                    if s > 0:
                        pairs.add(((actors[j], int(s)), v))
            return frozenset(pairs)

        return run

    def _many_split(self, st, owned, idxs, pad, rv):
        val_lists = [self.rev_elems[i] for i in idxs]
        domain = self.domain

        def post(dots):
            actors = domain.dc_ids
            out = {}
            for i, k in enumerate(owned):
                pairs = set()
                for slot, v in enumerate(list(val_lists[i])):
                    if slot >= dots.shape[1]:
                        break  # slot grown after the capture
                    for j, s in enumerate(dots[i, slot][:len(actors)]):
                        if s > 0:
                            pairs.add(((actors[j], int(s)), v))
                out[k] = frozenset(pairs)
            return out

        return ((store.mvreg_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


class FlagEwPlane(OrsetPlane):
    """Device plane for flag_ew — an OR-Set with one implicit element
    (slot 0 holds the enable dots; crdt/flags.py FlagEW)."""

    type_name = "flag_ew"

    def __init__(self, domain, key_capacity, n_lanes, flush_ops, gc_ops,
                 max_dcs, ingest_settings=None):
        super().__init__(domain, key_capacity, n_lanes, 1, flush_ops,
                         gc_ops, max_dcs, max_slots=1,
                         ingest_settings=ingest_settings)

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        eff = payload.effect
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        if eff[0] == "en":
            _, dot, observed = eff
            actor, seq = dot
            dot_col = self._dc_col(actor)
            is_add, ok = 1, dot_col is not None
        else:  # "dis"
            _, observed = eff
            dot_col, seq, is_add, ok = 0, 0, 0, True
        obs_pairs = self._decode_obs(observed) if ok else None
        if obs_pairs is None:
            self.evict(key)
            return
        self._commit_rows(key, idx, [
            (idx, 0, is_add, dot_col or 0, int(seq), obs_pairs,
             op_dc_col, int(payload.commit_time), ss_pairs)])

    def seed_effects(self, state):
        # state: frozenset((actor, seq)) enable dots — one
        # un-observed enable per dot
        return [("en", dot, ()) for dot in state]

    def _reader(self, st, idx, rv):
        domain = self.domain

        def run():
            dots = np.asarray(store.orset_read_keys(
                st, jnp.asarray([idx], dtype=np.int32),
                jnp.asarray(rv))[0])
            actors = domain.dc_ids
            return frozenset(
                (actors[j], int(s))
                for j, s in enumerate(dots[0][:len(actors)]) if s > 0)

        return run

    def _many_split(self, st, owned, idxs, pad, rv):
        domain = self.domain

        def post(dots):
            actors = domain.dc_ids
            return {
                k: frozenset(
                    (actors[j], int(s))
                    for j, s in enumerate(dots[i, 0][:len(actors)])
                    if s > 0)
                for i, k in enumerate(owned)
            }

        return ((store.orset_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


class RwsetPlane(OrsetPlane):
    """Device plane for set_rw (remove-wins) — two dot tables with
    cross-cancellation (store.rwset_*; host oracle crdt/sets.py SetRW).
    Row tuple: (key_idx, slot, kind, dot_col, dot_seq, obs_add_pairs,
    obs_rmv_pairs, op_dc_col, op_ct, ss_pairs).

    The reconstructed state collapses each (element, plane, DC) dot set
    to its max seq.  Unlike set_aw, the host oracle's add set CAN hold
    several live dots per DC column (adds don't cancel adds), so the
    reconstruction under-reports stale older dots — *value*-exact
    nonetheless: presence needs an empty remove plane, which requires a
    fresh add dot that the collapse always retains (see the kernel doc,
    mat/kernels.py rwset_apply).  Oracle tests therefore compare at
    value level for this type.  Because of the collapse the type is in
    DevicePlane.STATE_LOSSY: downstream generation never reads this
    fold — require_state_downstream reads take an exact log replay
    (PartitionManager.read(exact_state=True))."""

    type_name = "set_rw"
    # (slot, kind, dot_dc, dot_seq, obs_add, obs_rmv, op_dc, op_ct, op_ss)
    _row_cols = ("s", "s", "s", "s", "vv", "vv", "s", "s", "vv")
    _append_fn = staticmethod(store.rwset_append)

    def _init_state(self, key_capacity):
        return store.rwset_shard_init(
            key_capacity, self.n_lanes, self.n_slots, self.domain.d,
            dtype=jnp.int64)

    def _grow_dcs(self, new_d):
        self.st = store.rwset_grow(self.st, n_dcs=new_d)

    def _grow_keys(self, new_k):
        self.st = store.rwset_grow(self.st, n_keys=new_k)

    def _grow_slots(self, new_e):
        self.flush("grow")
        self.n_slots = new_e
        self.st = store.rwset_grow(self.st, n_slots=new_e)

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        kind_name, entries = payload.effect
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        rows = []
        for entry in entries:
            if kind_name == "add":
                elem, dot, obs_rmvs = entry
                kind, obs_adds = 0, ()
            elif kind_name == "rmv":
                elem, dot, obs_adds = entry
                kind, obs_rmvs = 1, ()
            else:  # "reset": mints nothing, cancels both planes
                elem, obs_adds, obs_rmvs = entry
                kind, dot = 2, (None, 0)
            actor, seq = dot
            dot_col = 0 if actor is None else self._dc_col(actor)
            slot = self._slot(idx, elem)
            oa = self._decode_obs(obs_adds)
            orm = self._decode_obs(obs_rmvs)
            if slot is None or oa is None or orm is None \
                    or dot_col is None:
                self.evict(key)
                return
            rows.append((idx, slot, kind, dot_col, int(seq), oa, orm,
                         op_dc_col, int(payload.commit_time), ss_pairs))
        self._commit_rows(key, idx, rows)


    def seed_effects(self, state):
        # STATE_LOSSY: the fold collapses per-DC dot sets, and a seed
        # staged from the collapsed form would under-cancel at exact
        # replicas — these keys recover host-path (log/seed replay)
        return None

    def _purge_idx(self, idx):
        self.st = store.rwset_purge_keys(
            self.st, jnp.asarray([idx], dtype=np.int32))
        self.elem_index[idx] = {}
        self.rev_elems[idx] = []

    def _device_gc(self, gst_dense):
        self.st = store.rwset_gc(self.st, jnp.asarray(gst_dense))

    @staticmethod
    def _dots_of(row, actors):
        return frozenset(
            (actors[j], int(s))
            for j, s in enumerate(row[:len(actors)]) if s > 0)


    def _reader(self, st, idx, rv):
        elems = self.rev_elems[idx]
        domain = self.domain

        def run():
            adds, rmvs = store.rwset_read_keys(
                st, jnp.asarray([idx], dtype=np.int32), jnp.asarray(rv))
            adds, rmvs = np.asarray(adds)[0], np.asarray(rmvs)[0]
            actors = domain.dc_ids
            state = {}
            for slot, elem in enumerate(list(elems)):
                if slot >= adds.shape[0]:
                    break  # slot grown after the capture
                a = self._dots_of(adds[slot], actors)
                r = self._dots_of(rmvs[slot], actors)
                if a or r:
                    state[elem] = (a, r)
            return state

        return run

    def _many_split(self, st, owned, idxs, pad, rv):
        elem_lists = [self.rev_elems[i] for i in idxs]
        domain = self.domain

        def post(out_arrays):
            adds, rmvs = out_arrays
            actors = domain.dc_ids
            out = {}
            for i, k in enumerate(owned):
                state = {}
                for slot, elem in enumerate(list(elem_lists[i])):
                    if slot >= adds.shape[1]:
                        break
                    a = self._dots_of(adds[i, slot], actors)
                    r = self._dots_of(rmvs[i, slot], actors)
                    if a or r:
                        state[elem] = (a, r)
                out[k] = state
            return out

        return ((store.rwset_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


class FlagDwPlane(RwsetPlane):
    """Device plane for flag_dw — the remove-wins lattice with one
    implicit element (slot 0; crdt/flags.py FlagDW).  State tuple
    (enable_dots, disable_dots)."""

    type_name = "flag_dw"

    def __init__(self, domain, key_capacity, n_lanes, flush_ops, gc_ops,
                 max_dcs, ingest_settings=None):
        super().__init__(domain, key_capacity, n_lanes, 1, flush_ops,
                         gc_ops, max_dcs, max_slots=1,
                         ingest_settings=ingest_settings)

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        eff = payload.effect
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        if eff[0] == "en":       # enable = add-plane dot, cancels dis
            _, dot, obs_dis = eff
            kind, obs_en = 0, ()
        elif eff[0] == "dis":    # disable = rmv-plane dot, cancels en
            _, dot, obs_en = eff
            kind, obs_dis = 1, ()
        else:                    # "reset": cancels both, mints nothing
            _, obs_en, obs_dis = eff
            kind, dot = 2, (None, 0)
        actor, seq = dot
        dot_col = 0 if actor is None else self._dc_col(actor)
        oa = self._decode_obs(obs_en)
        orm = self._decode_obs(obs_dis)
        if oa is None or orm is None or dot_col is None:
            self.evict(key)
            return
        self._commit_rows(key, idx, [
            (idx, 0, kind, dot_col, int(seq), oa, orm, op_dc_col,
             int(payload.commit_time), ss_pairs)])


    def _reader(self, st, idx, rv):
        domain = self.domain

        def run():
            adds, rmvs = store.rwset_read_keys(
                st, jnp.asarray([idx], dtype=np.int32), jnp.asarray(rv))
            actors = domain.dc_ids
            return (self._dots_of(np.asarray(adds)[0, 0], actors),
                    self._dots_of(np.asarray(rmvs)[0, 0], actors))

        return run

    def _many_split(self, st, owned, idxs, pad, rv):
        domain = self.domain

        def post(out_arrays):
            adds, rmvs = out_arrays
            actors = domain.dc_ids
            return {
                k: (self._dots_of(adds[i, 0], actors),
                    self._dots_of(rmvs[i, 0], actors))
                for i, k in enumerate(owned)
            }

        return ((store.rwset_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


class SetGoPlane(OrsetPlane):
    """Device plane for set_go — monotone presence, no dot algebra
    (store.setgo_*; host oracle crdt/sets.py SetGO).  Effect = tuple of
    elements; row tuple: (key_idx, slot, op_dc_col, op_ct, ss_pairs).
    Dot-collapse soundness is moot (no dots), so uncertified commits may
    stay on the device path (like counter_pn)."""

    type_name = "set_go"
    # (slot, op_dc, op_ct, op_ss)
    _row_cols = ("s", "s", "s", "vv")
    _append_fn = staticmethod(store.setgo_append)

    def _init_state(self, key_capacity):
        return store.setgo_shard_init(
            key_capacity, self.n_lanes, self.n_slots, self.domain.d,
            dtype=jnp.int64)

    def _grow_dcs(self, new_d):
        self.st = store.setgo_grow(self.st, n_dcs=new_d)

    def _grow_keys(self, new_k):
        self.st = store.setgo_grow(self.st, n_keys=new_k)

    def _grow_slots(self, new_e):
        self.flush("grow")
        self.n_slots = new_e
        self.st = store.setgo_grow(self.st, n_slots=new_e)

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        rows = []
        for elem in payload.effect:
            slot = self._slot(idx, elem)
            if slot is None:
                self.evict(key)
                return
            rows.append((idx, slot, op_dc_col,
                         int(payload.commit_time), ss_pairs))
        self._commit_rows(key, idx, rows)

    def seed_effects(self, state):
        # state: frozenset(elems) — one grow-only add (one row) per
        # element, chunkable against the lane budget like set_aw's
        return [(e,) for e in state]

    def _purge_idx(self, idx):
        self.st = store.setgo_purge_keys(
            self.st, jnp.asarray([idx], dtype=np.int32))
        self.elem_index[idx] = {}
        self.rev_elems[idx] = []

    def _device_gc(self, gst_dense):
        self.st = store.setgo_gc(self.st, jnp.asarray(gst_dense))


    def _reader(self, st, idx, rv):
        elems = self.rev_elems[idx]

        def run():
            present = np.asarray(store.setgo_read_keys(
                st, jnp.asarray([idx], dtype=np.int32),
                jnp.asarray(rv))[0])
            return frozenset(
                e for slot, e in enumerate(list(elems))
                if slot < present.shape[0] and present[slot])

        return run

    def _many_split(self, st, owned, idxs, pad, rv):
        elem_lists = [self.rev_elems[i] for i in idxs]

        def post(present):
            return {
                k: frozenset(
                    e for slot, e in enumerate(list(elem_lists[i]))
                    if slot < present.shape[1] and present[i, slot])
                for i, k in enumerate(owned)
            }

        return ((store.setgo_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


#: tiebreak packing: rank << _TIE_SHIFT | seq (seq must fit the low bits)
_TIE_SHIFT = 40
_TIE_SEQ_MAX = (1 << _TIE_SHIFT) - 1


class LwwPlane(_PlaneBase):
    """Device plane for register_lww.  Row tuple:
    (key_idx, ts, tie, val_id, op_dc_col, op_ct, ss_pairs).

    The host oracle's tiebreak is (actor string, seq) compared
    lexicographically (crdt/registers.py RegisterLWW); the device
    compares packed int64s, so the plane keeps a *sorted* actor-rank
    directory and repacks stored ties (store.lww_retie) on first sight
    of a new actor — rare, host-side, and exact."""

    type_name = "register_lww"
    # (ts, tie, val_id, op_dc, op_ct, op_ss)
    _row_cols = ("s", "s", "s", "s", "s", "vv")
    _append_fn = staticmethod(store.lww_append)

    def __init__(self, domain, key_capacity, n_lanes, flush_ops, gc_ops,
                 max_dcs, ingest_settings=None):
        #: sorted actor strings; rank = index in this list
        self.actors_sorted: List[str] = []
        self._rank: Dict[str, int] = {}
        #: interned values (value -> id, id -> value)
        self.val_index: Dict[Any, int] = {}
        self.rev_vals: List[Any] = []
        super().__init__(domain, key_capacity, n_lanes, flush_ops,
                         gc_ops, max_dcs,
                         ingest_settings=ingest_settings)

    def _init_state(self, key_capacity):
        return store.lww_shard_init(
            key_capacity, self.n_lanes, self.domain.d, dtype=jnp.int64)

    def _grow_dcs(self, new_d):
        self.st = store.lww_grow(self.st, n_dcs=new_d)

    def _grow_keys(self, new_k):
        self.st = store.lww_grow(self.st, n_keys=new_k)

    def _tie(self, actor: str, seq: int) -> Optional[int]:
        if seq > _TIE_SEQ_MAX:
            return None
        rank = self._rank.get(actor)
        if rank is None:
            self.flush("grow")  # staged rows carry old-rank ties
            new_sorted = sorted(self.actors_sorted + [actor])
            remap = np.asarray(
                [new_sorted.index(a) for a in self.actors_sorted],
                dtype=np.int64)
            if len(remap):
                self.st = store.lww_retie(self.st, remap, _TIE_SHIFT)
            self.actors_sorted = new_sorted
            self._rank = {a: i for i, a in enumerate(new_sorted)}
            rank = self._rank[actor]
        return (rank << _TIE_SHIFT) | int(seq)

    #: value-directory compaction threshold: dead interned values (every
    #: assign with a fresh payload leaves one behind) are dropped once
    #: the directory outgrows this
    _val_compact_at = 1 << 16

    def _val_id(self, v) -> Optional[int]:
        try:
            vid = self.val_index.get(v)
        except TypeError:
            return None  # unhashable value — host path
        if vid is None:
            if len(self.rev_vals) >= self._val_compact_at:
                self._compact_vals()
            vid = len(self.rev_vals)
            self.val_index[v] = vid
            self.rev_vals.append(v)
        return vid

    def _compact_vals(self) -> None:
        """Drop interned values no stored row references any more
        (superseded assigns): flush, host-scan the live val columns,
        rebuild the directory, and remap the device columns
        (store.lww_reval).  Keeps register-heavy workloads from leaking
        one value object per assign forever."""
        self.flush("grow")
        ops_val = np.asarray(self.st.ops[:, store._LVAL])
        valid = np.asarray(self.st.valid)
        bval = np.asarray(self.st.base_val)
        live = set(np.unique(ops_val[valid]).tolist())
        live.update(np.unique(bval[bval >= 0]).tolist())
        remap = np.full(len(self.rev_vals), -1, dtype=np.int64)
        new_vals: List[Any] = []
        for old in sorted(live):
            remap[old] = len(new_vals)
            new_vals.append(self.rev_vals[old])
        self.st = store.lww_reval(self.st, remap)
        self.rev_vals = new_vals
        self.val_index = {v: i for i, v in enumerate(new_vals)}
        log.debug("lww plane: value directory compacted to %d entries",
                  len(new_vals))

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        ts, tie_pair, v = payload.effect
        actor, seq = tie_pair
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        tie = self._tie(str(actor), int(seq))
        vid = self._val_id(v)
        if op_dc_col is None or ss_pairs is None or tie is None \
                or vid is None:
            self.evict(key)
            return
        self._commit_rows(key, idx, [
            (idx, int(ts), tie, vid, op_dc_col,
             int(payload.commit_time), ss_pairs)])

    def seed_effects(self, state):
        # state: (ts, (actor, seq), value), or the unwritten bottom
        # (0, (), None) — which needs no op at all
        ts, tie, v = state
        return [] if not tie and v is None else [(ts, tie, v)]

    def _purge_idx(self, idx):
        self.st = store.lww_purge_keys(
            self.st, jnp.asarray([idx], dtype=np.int32))

    def _device_gc(self, gst_dense):
        self.st = store.lww_gc(self.st, jnp.asarray(gst_dense))


    def _reader(self, st, idx, rv):
        # actors_sorted is REPLACED wholesale on a rank repack (which
        # also repacks st under the same lock) — capturing the list here
        # keeps ranks and state consistent after the lock is released
        acts = self.actors_sorted
        vals = self.rev_vals

        def run():
            ts, tie, val = (np.asarray(a) for a in store.lww_read_keys(
                st, jnp.asarray([idx], dtype=np.int32), jnp.asarray(rv)))
            if val[0] < 0:
                return (0, (), None)  # unwritten at this snapshot
            rank = int(tie[0]) >> _TIE_SHIFT
            seq = int(tie[0]) & _TIE_SEQ_MAX
            return (int(ts[0]), (acts[rank], seq), vals[int(val[0])])

        return run

    def _many_split(self, st, owned, idxs, pad, rv):
        # consistent with the captured state (see LwwPlane._reader)
        acts = self.actors_sorted
        vals = self.rev_vals

        def post(out_arrays):
            ts, tie, val = out_arrays
            out = {}
            for i, k in enumerate(owned):
                if val[i] < 0:
                    out[k] = (0, (), None)  # unwritten at this snapshot
                else:
                    rank = int(tie[i]) >> _TIE_SHIFT
                    seq = int(tie[i]) & _TIE_SEQ_MAX
                    out[k] = (int(ts[i]), (acts[rank], seq),
                              vals[int(val[i])])
            return out

        return ((store.lww_read_keys,
                 (st, jnp.asarray(pad), jnp.asarray(rv))), post)


#: bottom (empty) nested states as the planes reconstruct them — used by
#: the map_rr visibility rule (entry invisible iff nested state is
#: bottom, crdt/maps.py MapRR.update)
_BOTTOM = {
    "counter_pn": 0,
    "set_aw": {},
    "set_rw": {},
    "set_go": frozenset(),
    "register_mv": frozenset(),
    "register_lww": (0, (), None),
    "flag_ew": frozenset(),
    "flag_dw": (frozenset(), frozenset()),
}


class RgaPlane(_PlaneBase):
    """Device plane for rga — one VC-aware incremental store per key
    (antidote_tpu/mat/rga_store.py: folded base + op window with full
    commit-VC lanes).

    Documents are independent trees, so unlike the slotted planes there
    is no cross-key shard array: ``self.st`` maps key index -> its
    RgaStoreState, and a read folds exactly one document.  The
    reconstruction is EXACT host-oracle state — ``(uid, elem, visible)``
    tuples in RGA order including tombstones (crdt/rga.py) — so value
    reads AND downstream generation (positions over visible vertices,
    lamport max) are served from the device; rga is therefore NOT in
    STATE_LOSSY.

    Host directories per key: actor strings intern into the uid's
    ``actor_bits`` field (ids from 1; 0 is the root sentinel), elements
    into int32 ids.  A key evicts to the host path when its actors
    exceed 2^bits - 1 or a lamport would overflow the packed-uid width
    (reference materializer serves every type through one path,
    src/materializer_vnode.erl:56-110 — eviction is this plane's
    capacity escape hatch, like the slotted planes')."""

    type_name = "rga"

    def __init__(self, domain, key_capacity, flush_ops, gc_ops, max_dcs,
                 pb: int = 256, nw: int = 256, md: int = 64,
                 actor_bits: int = 8, ingest_settings=None):
        self.pb0, self.nw0, self.md0 = pb, nw, md
        self.actor_bits = actor_bits
        self._max_lam = 1 << (31 - actor_bits)
        #: per-key interning (index-aligned with rev_keys)
        self.actor_index: List[dict] = []
        self.rev_actors: List[list] = []
        self.elem_index: List[dict] = []
        self.rev_elems: List[list] = []
        super().__init__(domain, key_capacity, 1, flush_ops, gc_ops,
                         max_dcs, ingest_settings=ingest_settings)

    # -- storage hooks ------------------------------------------------------

    def _init_state(self, key_capacity):
        return {}  # key idx -> RgaStoreState

    def _grow_keys(self, new_k):
        pass  # dict-backed: nothing to repack

    def _grow_dcs(self, new_d):
        from antidote_tpu.mat import rga_store

        self.st = {i: rga_store.rga_grow(s, n_dcs=new_d)
                   for i, s in self.st.items()}

    def _key_idx(self, key):
        idx = self.key_index.get(key)
        if idx is None:
            from antidote_tpu.mat import rga_store

            idx = len(self.rev_keys)
            self.key_index[key] = idx
            self.rev_keys.append(key)
            self.actor_index.append({})
            self.rev_actors.append([])
            self.elem_index.append({})
            self.rev_elems.append([])
            self.st[idx] = rga_store.rga_store_init(
                self.pb0, self.nw0, self.md0, n_dcs=self.domain.d,
                actor_bits=self.actor_bits)
        return idx

    def _purge_idx(self, idx):
        self.st.pop(idx, None)
        self.actor_index[idx] = {}
        self.rev_actors[idx] = []
        self.elem_index[idx] = {}
        self.rev_elems[idx] = []

    # -- interning ----------------------------------------------------------

    def _actor_id(self, idx, actor) -> Optional[int]:
        """Interned actor id, kept in ACTOR-STRING order: sibling order
        is packed-uid-desc and the host oracle breaks lamport ties by
        the actor string, so ids must sort like the strings or replicas
        interning in different arrival orders diverge on concurrent
        same-lamport inserts (caught by the chaos suite).  An
        out-of-order arrival re-interns and remaps the document
        (rga_store.rga_remap_actors)."""
        d = self.actor_index[idx]
        a = d.get(actor)
        if a is not None:
            return a
        if len(d) >= (1 << self.actor_bits) - 1:
            return None  # uid width exhausted — evict
        rev = self.rev_actors[idx]
        if not rev or actor > rev[-1]:
            a = len(d) + 1
            d[actor] = a
            rev.append(actor)
            return a
        # re-intern in sorted order and remap the device state + any
        # staged rows of this key
        from antidote_tpu.mat import rga_store

        new_rev = sorted(rev + [actor])
        perm = np.zeros(1 << self.actor_bits, dtype=np.int32)
        new_ids = {s: i + 1 for i, s in enumerate(new_rev)}
        for s, old in d.items():
            perm[old] = new_ids[s]
        self.actor_index[idx] = new_ids
        self.rev_actors[idx] = new_rev
        st = self.st.get(idx)
        if st is not None:
            self.st[idx] = rga_store.rga_remap_actors(st, perm)
        remapped = []
        for r in self.rows:
            if r[0] == idx:
                r = (r[0], r[1], r[2], int(perm[r[3]]), r[4],
                     int(perm[r[5]]), *r[6:])
            remapped.append(r)
        self.rows = remapped
        return new_ids[actor]

    def _elem_id(self, idx, elem) -> int:
        d = self.elem_index[idx]
        e = d.get(elem)
        if e is None:
            e = len(self.rev_elems[idx])
            d[elem] = e
            self.rev_elems[idx].append(elem)
        return e

    # -- write path ---------------------------------------------------------

    def stage(self, key, payload: Payload) -> None:
        idx = self._key_idx(key)
        eff = payload.effect
        op_dc_col = self._dc_col(payload.commit_dc)
        ss_pairs = self._ss_pairs(payload.snapshot_vc)
        if op_dc_col is None or ss_pairs is None:
            self.evict(key)
            return
        if eff[0] == "ins":
            _, uid, ref, elem = eff
            lam, actor = uid
            rlam, ract_raw = (0, 0) if ref == (0, "") else ref
            act = self._actor_id(idx, actor)
            ract = 0 if rlam == 0 and ract_raw == 0 \
                else self._actor_id(idx, ract_raw)
            if act is None or ract is None \
                    or lam >= self._max_lam or rlam >= self._max_lam:
                self.evict(key)
                return
            row = (idx, 0, int(lam), act, int(rlam), ract,
                   self._elem_id(idx, elem), op_dc_col,
                   int(payload.commit_time), ss_pairs)
        elif eff[0] == "rm":
            _, uid = eff
            lam, actor = uid
            act = self._actor_id(idx, actor)
            if act is None or lam >= self._max_lam:
                self.evict(key)
                return
            row = (idx, 1, int(lam), act, 0, 0, 0, op_dc_col,
                   int(payload.commit_time), ss_pairs)
        else:
            self.evict(key)
            return
        self._commit_rows(key, idx, [row])

    def _append_rows(self, rows: List[tuple]) -> np.ndarray:
        """Per-key grouped append into each document's window; a full
        window folds at the newest stable horizon and/or grows — this
        plane's appends never report overflow (capacity misses evict at
        stage time)."""
        from antidote_tpu.mat import rga_store

        overflow = np.zeros(len(rows), dtype=bool)
        by_idx: Dict[int, list] = {}
        for r in rows:
            by_idx.setdefault(r[0], []).append(r)
        d = self.domain.d
        for idx, group in by_idx.items():
            st = self.st.get(idx)
            if st is None:
                continue  # evicted while staged; log replay covers it
            ins = [r for r in group if r[1] == 0]
            dels = [r for r in group if r[1] == 1]

            def col(rs, j, dt=np.int32):
                return np.asarray([r[j] for r in rs], dtype=dt)

            def ss(rs):
                m = np.zeros((len(rs), d), dtype=np.int64)
                for i, r in enumerate(rs):
                    for c, t in r[9]:
                        m[i, c] = max(m[i, c], t)
                return m

            # bucketed append: per-commit group sizes vary freely, and
            # un-padded blocks would mint one XLA program per distinct
            # (inserts, deletes) pair.  The coalesced form uploads the
            # whole block as ONE packed tensor (mat/ingest.py economy);
            # the legacy per-column form stays as the baseline knob.
            append = (rga_store.rga_append_coalesced
                      if self._ingest.enabled
                      else rga_store.rga_append_padded)
            ins_cols = (col(ins, 2), col(ins, 3), col(ins, 4),
                        col(ins, 5), col(ins, 6), col(ins, 7),
                        col(ins, 8, np.int64), ss(ins))
            del_cols = (col(dels, 2), col(dels, 3), col(dels, 7),
                        col(dels, 8, np.int64), ss(dels))
            st, ok = append(st, ins_cols, del_cols)
            if not bool(ok):
                # fold what is stable, then grow to fit the backlog
                if self._last_stable is not None:
                    pairs = self._ss_pairs(self._last_stable)
                    if pairs is not None:
                        st = rga_store.rga_fold_host(
                            st, self._dense_vc(pairs))
                        # the physical base advanced: reads below this
                        # horizon must take the log-replay path from now
                        # on (_read_vc_dense checks _base_vc)
                        self._base_vc = self._base_vc.join(
                            self._last_stable)
                        self._has_base = True
                # room for the PADDED block (the append refuses when
                # the pad would overhang, see rga_append)
                need_w = int(st.wn) + rga_store._append_bucket(len(ins))
                need_d = int(st.dn) + rga_store._append_bucket(len(dels))
                nw = st.nw
                while nw < need_w:
                    nw *= 2
                md = st.md
                while md < need_d:
                    md *= 2
                st = rga_store.rga_grow(st, nw=nw, md=md)
                st, ok = append(st, ins_cols, del_cols)
                assert bool(ok), "rga append must fit after grow"
            self.st[idx] = st
        return overflow

    def _device_gc(self, gst_dense):
        from antidote_tpu.mat import rga_store

        for idx, st in list(self.st.items()):
            if int(st.wn) == 0 and int(st.dn) == 0:
                continue  # quiescent document: nothing to fold
            self.st[idx] = rga_store.rga_fold_host(st, gst_dense)

    # -- read path ----------------------------------------------------------

    def _reader(self, st, idx, rv):
        from antidote_tpu.mat import rga_store

        sti = st[idx]
        actors = list(self.rev_actors[idx])
        elems = list(self.rev_elems[idx])

        def run():
            lam, act, elem, vis, n = rga_store.rga_read(
                sti, jnp.asarray(rv))
            lam = np.asarray(lam)
            act = np.asarray(act)
            elem = np.asarray(elem)
            vis = np.asarray(vis)
            n = int(n)
            # present vertices sort to the front in document order
            return tuple(
                ((int(lam[i]), actors[int(act[i]) - 1]),
                 elems[int(elem[i])], bool(vis[i]))
                for i in range(n))

        return run

    def _many_reader(self, st, owned, idxs, pad, rv):
        readers = [(k, self._reader(st, int(i), rv))
                   for k, i in zip(owned, idxs)]

        def run():
            return {k: r() for k, r in readers}

        return run

    def read_many_begin(self, keys: list, read_vc: Optional[VC]):
        """Documents fold one device call each (independent trees — no
        cross-key batching), so the base's padded-idx plumbing reduces
        to a reader per owned key."""
        if self.pending_keys and not self.pending_keys.isdisjoint(keys):
            self.flush("read")
        owned = [k for k in keys if k in self.key_index]
        if not owned:
            return dict
        rv = self._read_vc_dense(read_vc)
        idxs = np.asarray([self.key_index[k] for k in owned],
                          dtype=np.int32)
        return self._many_reader(self.st, owned, idxs, idxs, rv)


class MapPlane:
    """Field-composite device plane for map_go / map_rr.

    A map effect is a bag of nested effects keyed by ``key_t = (field,
    nested_type)`` (crdt/maps.py; reference antidote_crdt_map_rr
    semantics).  Each nested effect routes to a PRIVATE sub-plane of the
    nested type under the synthetic key ``(map_key, key_t)`` — the map
    rides the existing per-type ring/fold/GC machinery instead of
    needing its own kernels.  Reads fan back out: one batched sub-fold
    per nested type reassembles ``{key_t: nested_state}``.

    Visibility: map_go entries exist from their first update onward, a
    snapshot-dependent fact tracked by a private set_go presence plane
    over fields; map_rr entries are visible iff the nested state is not
    bottom (MapRR.update pops bottoms), checked on the reconstructed
    state.

    Fallback is map-granular: any capacity miss in any sub-plane evicts
    the WHOLE map key to the host path (log replay of the map's effects
    rebuilds it there — synthetic keys never appear in the log).  Nested
    types without a device plane (maps-in-maps, counter_fat, counter_b)
    evict the same way."""

    SUPPORTED = frozenset(_BOTTOM)

    def __init__(self, type_name: str, make_sub,
                 make_presence=None):
        self.type_name = type_name
        self._make_sub = make_sub
        self._subs: Dict[str, _PlaneBase] = {}
        self._presence = make_presence() if make_presence else None
        if self._presence is not None:
            self._presence.on_evict = \
                lambda mkey, t, state=None: self._presence_evicted(
                    mkey, state)
        #: map_key -> set of key_t ever staged on device.  Doubles as
        #: the plane's key directory (``key_index`` below) so operator
        #: surfaces can treat every plane uniformly.
        self.fields: Dict[Any, set] = {}
        self.pending_keys: set = set()
        self.on_evict: Callable[..., None] = \
            lambda k, t, state=None: None
        #: unlogged-eviction flags (see _PlaneBase): the MAP exports
        #: the reassembled state; sub-planes only get the emergency-
        #: fold behavior (no_log_replay, propagated at creation)
        self.evict_export = False
        self.no_log_replay = False
        self._exporting: set = set()
        #: set by a mid-decode eviction inside :meth:`stage`: the entry
        #: subset the export could not cover (see _set_stage_residual)
        self.stage_residual = None
        #: (key_t, state) of the sub whose eviction triggered ours —
        #: that sub's rows purged before our export ran (see
        #: _sub_evicted)
        self._evict_overlay = None
        #: (mkey, visible-set) when the PRESENCE plane's eviction
        #: triggered ours — its pre-purge fold replaces the export's
        #: visibility filter (see _presence_evicted)
        self._presence_vis_override = None
        self._evicting = None
        self._warm_kicked = False

    def kick_warm(self) -> None:
        """First-use warm trigger: existing sub-planes (presence
        included) warm-compile now, and every LAZILY created sub-plane
        warms at creation (see _PlaneBase.warm_appends)."""
        if self._warm_kicked:
            return
        self._warm_kicked = True
        orig = self._make_sub

        def warming_make(tn, _orig=orig):
            sub = _orig(tn)
            sub.warm_appends()
            return sub

        self._make_sub = warming_make
        for s in self._all_planes():
            s.warm_appends()

    # -- plumbing shared with _PlaneBase's interface ------------------------

    @property
    def rows(self):
        out = []
        for s in self._all_planes():
            out.extend(s.rows)
        return out

    def _all_planes(self):
        planes = list(self._subs.values())
        if self._presence is not None:
            planes.append(self._presence)
        return planes

    def owns(self, key) -> bool:
        return key in self.fields

    @property
    def key_index(self) -> Dict[Any, set]:
        """Key directory (uniform with _PlaneBase.key_index: len() =
        device-resident keys, ``in`` = ownership)."""
        return self.fields

    def _sub(self, ntype: str) -> _PlaneBase:
        sub = self._subs.get(ntype)
        if sub is None:
            sub = self._make_sub(ntype)
            sub.on_evict = \
                lambda skey, t, state=None: self._sub_evicted(
                    skey, state)
            sub.no_log_replay = self.no_log_replay
            sub.evict_export = self.evict_export
            self._subs[ntype] = sub
        return sub

    def _presence_evicted(self, mkey, state=None) -> None:
        if self._evicting == mkey:
            return  # our own purge loop
        # the presence plane purged its rows BEFORE this map-level
        # eviction can export, so the export's visibility filter would
        # see an empty set and seed the host with {} (the zeroing bug,
        # presence flavor): its own pre-purge export — the visibility
        # SET — rides along and replaces the filter (unlogged mode)
        self._presence_vis_override = (mkey, state) \
            if state is not None else None
        try:
            self.evict(mkey)
        finally:
            self._presence_vis_override = None

    def _sub_evicted(self, skey, state=None) -> None:
        mkey, key_t = skey
        if self._evicting == mkey:
            return  # our own purge loop
        # the triggering sub purged its rows BEFORE this map-level
        # eviction can export — its own pre-purge export (``state``)
        # is the only copy of that field's history; overlay it onto
        # the map export (unlogged mode)
        self._evict_overlay = (key_t, state) \
            if key_t is not None and state is not None else None
        try:
            self.evict(mkey)
        finally:
            self._evict_overlay = None

    # -- write path ---------------------------------------------------------

    def _note_staged_vc(self, payload: Payload) -> None:
        """Top-level no-op (sub-planes track their own bounds at
        :meth:`stage`, where the nested payloads are built)."""

    def stage(self, key, payload: Payload) -> None:
        """Decode one committed map effect into sub-plane stages; evicts
        the whole map on any nested capacity miss.

        ``stage_residual`` (consumed by DevicePlane.stage in unlogged
        mode): when the eviction fires MID-decode, some of this op's
        sub-entries were already staged and may be VISIBLE in the
        eviction's exported state (map_rr: every staged field; map_go:
        only fields that existed before this op — a new field's
        presence rows stage last and were dropped) — re-applying the
        FULL effect onto the seed would double-apply those.  The
        residual is the entry subset the export could not have
        covered."""
        _kind, entries = payload.effect
        pre_fields = set(self.fields.get(key, ()))
        # register the key BEFORE any reject so evict() always runs the
        # migration (the op is already in the log, like _PlaneBase.stage)
        self.fields.setdefault(key, set())
        self.stage_residual = None
        if any(kt[1] not in self.SUPPORTED for kt, _ in entries):
            self.evict(key)           # nested map / counter_fat / b
            self.stage_residual = payload.effect  # nothing staged
            return
        staged = []
        for key_t, neff in entries:
            sub = self._sub(key_t[1])
            skey = (key, key_t)
            sub_payload = dc_replace(
                payload, key=skey, type_name=key_t[1], effect=neff)
            sub._note_staged_vc(sub_payload)
            sub.stage(skey, sub_payload)
            if key not in self.fields:
                # a sub capacity miss evicted us mid-decode
                self._set_stage_residual(_kind, entries, staged,
                                         pre_fields)
                return
            self.fields[key].add(key_t)
            staged.append(key_t)
        if self._presence is not None and staged:
            pres_payload = dc_replace(
                payload, type_name="set_go", effect=tuple(staged))
            self._presence._note_staged_vc(pres_payload)
            self._presence.stage(key, pres_payload)
            if key not in self.fields:
                self._set_stage_residual(_kind, entries, staged,
                                         pre_fields)
                return
        self.pending_keys.add(key)

    def _set_stage_residual(self, kind, entries, staged,
                            pre_fields) -> None:
        """Entries of the current effect the mid-decode eviction's
        export could NOT include: everything except fields both staged
        AND visible at export time (see :meth:`stage`)."""
        visible = set(staged) & pre_fields \
            if self._presence is not None else set(staged)
        residual = tuple(e for e in entries if e[0] not in visible)
        self.stage_residual = (kind, residual) if residual else None

    _schedule = None

    def maybe_flush_gc(self, stable_vc: Optional[VC]) -> None:
        for p in self._all_planes():
            p._schedule = self._schedule  # async-flush wiring follows
            p.maybe_flush_gc(stable_vc)
        if not any(p.rows for p in self._all_planes()):
            self.pending_keys.clear()

    def flush(self, kind: str = "explicit") -> None:
        for p in self._all_planes():
            p.flush(kind)
        self.pending_keys.clear()

    def gc(self, stable_vc: VC) -> None:
        for p in self._all_planes():
            p.gc(stable_vc)

    def _export_evict_state(self, key):
        """The reassembled map state, captured BEFORE the sub purges,
        when there is no log to replay (see _PlaneBase).  A sub whose
        own eviction triggered ours already purged its rows — its
        pre-purge export rides in ``_evict_overlay`` and replaces that
        field here."""
        if not self.evict_export or key in self._exporting:
            return None
        self._exporting.add(key)
        try:
            if self._presence_vis_override is not None \
                    and self._presence_vis_override[0] == key:
                # the presence plane already purged: the normal read
                # would filter every field against an empty visibility
                # set — assemble from the (intact) sub planes and the
                # presence's own pre-purge fold instead
                vis = self._presence_vis_override[1] or frozenset()
                state = {}
                for key_t in self.fields.get(key, ()):
                    if key_t not in vis:
                        continue
                    sub = self._subs.get(key_t[1])
                    if sub is not None:
                        state[key_t] = sub.read((key, key_t), None)
            else:
                state = self.read(key, None)
        except Exception:  # noqa: BLE001 — export must not break evict
            log.exception(
                "map evict-state export failed for %r (%s)",
                key, self.type_name)
            return None
        finally:
            self._exporting.discard(key)
        if self._evict_overlay is not None and isinstance(state, dict):
            key_t, sub_state = self._evict_overlay
            state = dict(state)
            state[key_t] = sub_state
        return state

    def evict(self, key) -> None:
        """Purge every synthetic key of the map and hand its history to
        the host path (on_evict replays the map's log records; with no
        log, the pre-purge reassembled state travels along)."""
        if key not in self.fields:
            return
        state = self._export_evict_state(key)
        self._evicting = key
        try:
            for key_t in self.fields.pop(key, ()):
                sub = self._subs.get(key_t[1])
                if sub is not None:
                    # our own purge: the map already exported; a per-
                    # field export here would be O(fields) wasted folds
                    prev = sub.evict_export
                    sub.evict_export = False
                    try:
                        sub.evict((key, key_t))
                    finally:
                        sub.evict_export = prev
            if self._presence is not None:
                prev = self._presence.evict_export
                self._presence.evict_export = False  # see sub note
                try:
                    self._presence.evict(key)
                finally:
                    self._presence.evict_export = prev
        finally:
            self._evicting = None
        self.pending_keys.discard(key)
        log.debug("device plane: evicted %r (%s)", key, self.type_name)
        self.on_evict(key, self.type_name, state)

    # -- read path ----------------------------------------------------------

    def read_many_begin(self, keys: list, read_vc: Optional[VC]):
        """Lock-held capture (see _PlaneBase.read_begin): synthetic keys
        of ALL requested maps are grouped so each nested type costs ONE
        batched sub-fold (plus one presence fold for map_go) regardless
        of how many maps the transaction reads — the same
        one-fold-per-type batching the flat planes get from
        read_many_begin.  The closure reassembles per-map states outside
        the lock."""
        owned = [k for k in keys if k in self.fields]
        if not owned:
            return dict

        def group(ks):
            bt: Dict[str, list] = {}
            for k in ks:
                for kt in self.fields[k]:
                    bt.setdefault(kt[1], []).append((k, kt))
            return bt

        # Pre-flush BEFORE any capture: a flush inside a sub-capture
        # could overflow -> evict the map -> purge SIBLING subs, which
        # deletes (donated) arrays already captured for an earlier type.
        # After this loop the captures below cannot trigger a flush.
        for ntype, pairs in group(owned).items():
            sub = self._sub(ntype)
            if not sub.pending_keys.isdisjoint(pairs):
                sub.flush("read")
        if self._presence is not None and not \
                self._presence.pending_keys.isdisjoint(owned):
            self._presence.flush("read")
        owned = [k for k in owned if k in self.fields]  # flush may evict
        if not owned:
            return dict
        parts = []
        for ntype, pairs in group(owned).items():
            parts.append((pairs,
                          self._sub(ntype).read_many_begin(pairs, read_vc)))
        pres = (self._presence.read_many_begin(owned, read_vc)
                if self._presence is not None else None)

        def run():
            states: Dict[Any, dict] = {k: {} for k in owned}
            for pairs, cl in parts:
                got = cl()
                for k, kt in pairs:
                    ns = got.get((k, kt))
                    if ns is None:
                        continue
                    if pres is None and ns == _BOTTOM[kt[1]]:
                        continue      # map_rr: bottom => invisible
                    states[k][kt] = ns
            if pres is not None:
                vis = pres()
                for k in owned:
                    v = vis.get(k, frozenset())
                    states[k] = {kt: ns for kt, ns in states[k].items()
                                 if kt in v}
            return states

        return run

    def read_begin(self, key, read_vc: Optional[VC]):
        cl = self.read_many_begin([key], read_vc)
        if key not in self.fields:
            # evicted during the begin-flush — host/log path, exactly
            # the flat planes' contract (_PlaneBase.read_begin)
            raise ReadBelowBase()
        return lambda: cl()[key]

    def read(self, key, read_vc: Optional[VC]):
        """Map host state ({(field, nested_type): nested_state}) at
        ``read_vc``."""
        return self.read_begin(key, read_vc)()

    def read_many(self, keys: list, read_vc: Optional[VC]) -> dict:
        return self.read_many_begin(keys, read_vc)()


class DevicePlane:
    """Per-partition facade over the type planes; all calls run under
    the owning PartitionManager's lock (one-writer discipline, like the
    reference's single vnode process)."""

    def __init__(self, config=None, key_capacity: int = 1024,
                 n_lanes: int = 8, n_slots: int = 8,
                 flush_ops: int = 256, gc_ops: int = 2048,
                 max_dcs: int = 64, max_slots: int = 256,
                 ingest_settings: Optional[ingest.IngestSettings] = None):
        if config is not None:
            key_capacity = config.device_key_capacity
            n_lanes = config.device_lanes
            n_slots = config.device_slots
            flush_ops = config.device_flush_ops
            gc_ops = config.device_gc_ops
            max_dcs = config.device_max_dcs
            max_slots = config.device_max_slots
            # the ONE ingest factory (mat/ingest.py): the sharded
            # stores build their settings from the same call, so the
            # single-shard and mesh assemblies honor the same knobs
            ingest_settings = ingest.ingest_from_config(config)
        ing = ingest_settings or ingest.ingest_from_config(None)
        slotted = {"set_aw": OrsetPlane, "register_mv": MvregPlane,
                   "set_rw": RwsetPlane, "set_go": SetGoPlane}
        flat = {"counter_pn": CounterPlane, "register_lww": LwwPlane,
                "flag_ew": FlagEwPlane, "flag_dw": FlagDwPlane}

        def make(tn: str):
            """Fresh plane instance for a type (top level, or a map's
            private sub-plane)."""
            if tn in slotted:
                return slotted[tn](ClockDomain(8), key_capacity, n_lanes,
                                   n_slots, flush_ops, gc_ops, max_dcs,
                                   max_slots, ingest_settings=ing)
            return flat[tn](ClockDomain(8), key_capacity, n_lanes,
                            flush_ops, gc_ops, max_dcs,
                            ingest_settings=ing)

        self.planes: Dict[str, Any] = {
            tn: make(tn) for tn in (*slotted, *flat)}
        self.planes["map_go"] = MapPlane(
            "map_go", make, make_presence=lambda: make("set_go"))
        self.planes["map_rr"] = MapPlane("map_rr", make)
        self.planes["rga"] = RgaPlane(
            ClockDomain(8), key_capacity, flush_ops, gc_ops, max_dcs,
            ingest_settings=ing)
        #: mesh device this partition's plane states are committed to
        #: (None = default device); see place_on
        self.device = None
        #: jax.sharding.Mesh the plane states are GSPMD-sharded over
        #: (None = single-chip); see place_sharded.  Mutually exclusive
        #: with ``device`` — a plane is pinned to ONE chip or sharded
        #: over all of them, never both.
        self.mesh = None
        #: when set (by the owning PartitionManager), threshold flushes
        #: and GCs are SCHEDULED here instead of running inline on the
        #: committing transaction's back — group commit: the commit
        #: path only stages; the XLA work happens on the flusher thread
        #: under the partition lock (reads needing pending data still
        #: flush inline — they need the result)
        self.flush_scheduler = None
        #: keys evicted to the host path (sticky)
        self.host_only: set = set()
        #: no-log-to-replay mode (set by set_evict_handler): evictions
        #: export state and decode-reject ops bounce back to the caller
        self._evict_export = False
        #: types whose dense representation collapses dot sets per DC —
        #: only sound under write-write certification (module doc).
        #: counter_pn and set_go mint no dots and are exempt.
        #: counter_fat stays host-served entirely: its value is a SUM
        #: over live dots, so the per-column collapse cannot reproduce
        #: the exact per-dot state a reset's downstream generation
        #: needs (a lossy observed list would under-cancel at exact
        #: replicas — a value divergence, not just a representation
        #: one).  The ambiguity is pinned by oracle tests: two
        #: histories with identical per-column collapse give different
        #: values under the same prefix reset
        #: (tests/unit/test_counter_fat_collapse.py).  Maps count as
        #: dot-collapsing because their nested
        #: entries may (conservative for an all-counter map_go).
        self.dot_collapse_types = frozenset(
            {"set_aw", "register_mv", "flag_ew", "set_rw", "flag_dw",
             "map_go", "map_rr"})

    #: types whose HOST state can hold several live dots per
    #: (element, plane, DC) column — their update has no self-supersede
    #: (crdt/sets.py SetRW.update does ``adds | {dot}``) — so the device
    #: fold's per-column max-seq collapse is value-exact but NOT
    #: state-exact.  An effect generated from the collapsed state lists
    #: only the newest observed dot and under-cancels at exact replicas
    #: (permanent divergence); set_aw / register_mv / flag_ew are immune
    #: because their ops supersede every observed same-column dot.
    STATE_LOSSY = frozenset({"set_rw", "flag_dw"})

    def state_exact(self, type_name: str, key) -> bool:
        """True iff the device fold reconstructs this key's EXACT host
        state, safe to feed downstream generation
        (require_state_downstream reads, reference call site
        src/clocksi_downstream.erl:43-67).  Maps are exact iff no
        device-resident field has a lossy nested type."""
        if type_name in ("map_go", "map_rr"):
            flds = self.planes[type_name].fields.get(key)
            return flds is None or all(
                kt[1] not in self.STATE_LOSSY for kt in flds)
        return type_name not in self.STATE_LOSSY

    def place_on(self, device) -> None:
        """Commit every plane's state arrays to ``device`` — the ring as
        the live data plane across a host's chips: partition p's
        materializer lives on chip p % n (the reference instantiates
        every vnode layer per partition across nodes,
        src/antidote_app.erl:42-59; per-partition device placement is
        the same idea over the mesh).  JAX's committed-placement rule
        keeps every functional update (append/gc/grow return NEW
        arrays from committed inputs) on the same chip, so one call at
        partition build time pins the plane for its lifetime.  RGA
        documents (dict-of-states, created lazily per document) keep
        default placement."""
        import jax as _jax

        def _place(plane):
            if isinstance(plane, MapPlane):
                orig = plane._make_sub

                def placed_make(tn, _orig=orig):
                    sub = _orig(tn)
                    sub.st = _jax.device_put(sub.st, device)
                    return sub

                plane._make_sub = placed_make
                for s in plane._all_planes():
                    s.st = _jax.device_put(s.st, device)
            elif isinstance(plane, RgaPlane):
                pass  # per-document dict states: lazily created
            else:
                plane.st = _jax.device_put(plane.st, device)

        self.device = device
        for plane in self.planes.values():
            _place(plane)

    def place_sharded(self, mesh) -> None:
        """Shard every plane's state arrays over ``mesh`` per the named
        partition rules (mat/sharded.py PARTITION_RULES) — the pod-
        scale materializer: the key axis splits across chips, clock-
        domain directories replicate, and every subsequent dispatch on
        the state is ONE multi-chip GSPMD program serialized under
        runtime.COLLECTIVE_LOCK (_PlaneBase._collective_cm).  Each
        plane also gets a per-shard residency router (ShardRouter):
        evictions charge only the OWNING shard's overflow economy, so
        one hot shard spilling cannot stop the other shards' keys from
        staying device-resident.  RGA documents (host-side dict of
        per-document trees) keep default placement, exactly like
        place_on."""
        from antidote_tpu.mat import sharded as _sharded

        n_shards = int(mesh.shape["part"])

        def _wire(p):
            p._mesh = mesh
            p._router = _sharded.ShardRouter(n_shards)
            p.st = _sharded.place_state(mesh, p.st)

        def _place(plane):
            if isinstance(plane, MapPlane):
                orig = plane._make_sub

                def sharded_make(tn, _orig=orig):
                    sub = _orig(tn)
                    _wire(sub)
                    return sub

                plane._make_sub = sharded_make
                for s in plane._all_planes():
                    _wire(s)
            elif isinstance(plane, RgaPlane):
                pass  # per-document dict states: host-side, unsharded
            else:
                _wire(plane)

        self.mesh = mesh
        for plane in self.planes.values():
            _place(plane)

    def refresh_shard_stats(self) -> None:
        """Publish the SHARD_* residency families (stats.py): per-shard
        device-resident key counts across all sharded planes, plus the
        device-resident percentage the config18 bench gates on
        (resident keys vs resident + host-evicted)."""
        if self.mesh is None:
            return
        n_shards = int(self.mesh.shape["part"])
        per_shard = [0] * n_shards
        resident = 0

        def _count(p):
            nonlocal resident
            r = p._router
            if r is None:
                return
            for idx, k in enumerate(p.rev_keys):
                if k is _Evicted:
                    continue
                per_shard[r.shard_of(idx, p.capacity)] += 1
                resident += 1

        for plane in self.planes.values():
            if isinstance(plane, MapPlane):
                for s in plane._all_planes():
                    _count(s)
            elif not isinstance(plane, RgaPlane):
                _count(plane)
        for s, n in enumerate(per_shard):
            stats.registry.shard_resident_keys.set(n, shard=str(s))
        total = resident + len(self.host_only)
        if total:
            stats.registry.shard_device_resident_pct.set(
                100.0 * resident / total)

    def set_evict_handler(self, fn: Callable[..., None],
                          export_state: bool = False) -> None:
        """Wire the eviction migration.  ``export_state=True`` marks a
        partition with NO durable log: evictions then materialize the
        key's state from the device fold before purging (the handler
        receives it as ``state``) instead of replaying an empty log —
        the PR-7-flagged silent-zeroing fix."""
        def handler(key, type_name, state=None):
            self.host_only.add(key)
            fn(key, type_name, state)
        self._evict_export = export_state
        for p in self.planes.values():
            p.on_evict = handler
            p.evict_export = export_state
            p.no_log_replay = export_state
            if isinstance(p, MapPlane):
                for s in p._all_planes():
                    s.no_log_replay = export_state
                # subs export too: a sub-triggered map eviction purges
                # the sub BEFORE the map-level export, and the sub's
                # own pre-purge export is that field's only copy; the
                # presence plane likewise (its fold IS the visibility
                # set the map export filters by)
                for s in p._subs.values():
                    s.evict_export = export_state
                if p._presence is not None:
                    p._presence.evict_export = export_state

    def accepts(self, type_name: str, key) -> bool:
        if type_name not in self.planes or key in self.host_only:
            return False
        p = self.planes[type_name]
        r = getattr(p, "_router", None)
        if r is not None and key not in p.key_index:
            # per-shard adaptive admission: a NEW key would land at
            # the next directory index — if that index's owning shard
            # overflowed since the last fold, route the key host-side
            # instead of feeding a ring that will evict it right back
            return r.admits(len(p.rev_keys), p.capacity)
        return True

    def owns(self, type_name: str, key) -> bool:
        p = self.planes.get(type_name)
        return p is not None and p.owns(key)

    def seed_state(self, key, type_name: str, state, vc) -> bool:
        """Install a checkpoint seed as DEVICE-resident base state
        (ISSUE 13): decode the folded ``state`` back into plane rows
        via the type's own effect decoder (``seed_effects`` — the
        inverse of the evict/export fold, which already proves the
        state round-trips) and stage them like any committed op; the
        caller folds the staged rows into the device base at the seed
        clock (``gc``), so base VC = seed frontier and a read below it
        replay-gates to the log path exactly like
        ``HostStore.seed_state``.  The synthetic payload's commit VC
        is ``vc`` itself (snapshot = vc, commit entry drawn from it),
        so any read covering the frontier includes every seed row.

        Returns False — caller seeds the host path instead — when the
        type has no state→effect decoding (maps, RGA, STATE_LOSSY
        collapses), the key is already host-pinned, or a capacity miss
        evicted it mid-seed (the eviction's migration already host-
        seeded it from the checkpoint)."""
        p = self.planes.get(type_name)
        seed_fx = getattr(p, "seed_effects", None)
        if p is None or seed_fx is None or not self.accepts(
                type_name, key) or not vc:
            return False
        effs = seed_fx(state)
        if effs is None:
            return False
        if not p._warm_kicked:
            p.kick_warm()
        tracer.instant("ckpt_seed_device", "device", key=key,
                       type=type_name, effects=len(effs))
        # commit VC == the seed frontier exactly: snapshot_vc carries
        # the whole frontier and the commit entry is one of its own
        # components, so the join adds nothing
        dc, ct = max(vc.items(), key=lambda kv: kv[1])
        # intern the frontier's DC columns UP FRONT, before any state
        # lands: the caller's per-plane base fold (gc at the seed-
        # clock join) relies on every accepted seed's frontier being
        # internable — a bottom-state seed stages NO rows, so without
        # this check it could smuggle an un-internable DC into the
        # join, the fold's _ss_pairs would miss, and every seed in
        # the plane would be left un-gated (served un-replayed below
        # its frontier).  A frontier past the column capacity routes
        # host-path like any other capacity miss.
        if p._ss_pairs(VC(vc)) is None:
            return False
        p._key_idx(key)  # intern even a bottom-state seed (owns()=True)
        # chunk against the per-key lane budget: a dot-heavy key's
        # rows would overflow its ring lanes in one batch, and at boot
        # there is no stable horizon for the overflow-retry fold —
        # fold the staged chunk into the base at the seed frontier
        # (its exact commit VC) and keep going
        lanes = max(int(getattr(p, "n_lanes", 8)), 1)
        for i, eff in enumerate(effs):
            p.stage(key, Payload(
                key=key, type_name=type_name, effect=eff,
                commit_dc=dc, commit_time=int(ct), snapshot_vc=VC(vc),
                txid=("ckpt-seed", 0), certified=True))
            if not p.owns(key):
                # capacity miss mid-seed: the eviction migrated the
                # key (checkpoint seed + suffix replay) to the host
                return False
            if (i + 1) % lanes == 0 and i + 1 < len(effs):
                p.gc(VC(vc))
                if not p.owns(key):
                    return False  # overflow eviction during the fold
        stats.registry.ckpt_seed_device_keys.inc()
        return True

    def stage(self, key, type_name: str, payload: Payload,
              stable_vc: Optional[VC]):
        """Route one committed effect to its type plane.  Returns the
        BOUNCE effect (or None) when the key was evicted DURING the
        decode (unlogged mode only): the bounced part never landed on
        the device and the eviction's exported state predates it, so
        the caller must land it on the host path itself — with a log
        it would be replayed from there (PartitionManager._publish).
        For maps the bounce is the residual entry subset the export
        could not cover (MapPlane.stage_residual); for flat planes it
        is the whole effect."""
        p = self.planes[type_name]
        if not p._warm_kicked:
            p.kick_warm()
        if p._schedule is not self.flush_scheduler:
            p._schedule = self.flush_scheduler
        # the txid-correlated device-plane hop of the txn span tree
        # (instant: the XLA work happens later, at flush time) plus the
        # flight-recorder record of the _publish window the round-5
        # set_aw bug lives in
        tracer.instant("device_stage", "device", txid=payload.txid,
                       key=key, type=type_name)
        # per-op stage events get their OWN subsystem ring: at serving
        # rates they would otherwise evict the rare flush/evict/gc
        # events that bound the suspect _publish window from the shared
        # 512-deep "device" ring within a second
        recorder.record("device_stage", "stage", plane=type_name,
                        key=key, txid=payload.txid,
                        commit_time=payload.commit_time)
        p._note_staged_vc(payload)
        p.stage(key, payload)
        evicted_mid_decode = not p.owns(key)
        p.maybe_flush_gc(stable_vc)
        if not (self._evict_export and evicted_mid_decode):
            return None
        if isinstance(p, MapPlane):
            return p.stage_residual
        return payload.effect

    def read(self, key, type_name: str, read_vc: Optional[VC],
             txid=None):
        # txid-tagged so the per-read span joins its txn's tree and
        # obeys per-txid sampling; untagged reads fall back to
        # sampled()'s 1-in-N thinning instead of flooding the ring
        with tracer.span("device_read", "device", txid=txid, key=key,
                         type=type_name):
            t0 = time.perf_counter()
            value = self.planes[type_name].read(key, read_vc)
        stats.registry.device_read_latency.observe(
            time.perf_counter() - t0)
        return value

    def read_many(self, keys: list, type_name: str,
                  read_vc: Optional[VC], txid=None) -> dict:
        """{key: state} for device-owned keys; callers take the host
        path for the rest."""
        with tracer.span("device_read_many", "device", txid=txid,
                         n=len(keys), type=type_name):
            t0 = time.perf_counter()
            out = self.planes[type_name].read_many(keys, read_vc)
        stats.registry.device_read_latency.observe(
            time.perf_counter() - t0)
        return out

    def gc(self, stable_vc: VC) -> None:
        with tracer.span("device_gc_all", "device"):
            for p in self.planes.values():
                p.gc(stable_vc)
        self.refresh_shard_stats()

    def flush(self) -> None:
        with tracer.span("device_flush_all", "device"):
            for p in self.planes.values():
                p.flush()

    def pending(self) -> int:
        return sum(len(p.rows) for p in self.planes.values())

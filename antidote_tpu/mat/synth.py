"""Synthetic committed-op streams for benchmarks and dry runs.

One generator shared by bench.py and __graft_entry__.py so the causal
plausibility invariants (per-DC monotone commit counters, op snapshot
VC <= the commit frontier) live in one place.
"""

from __future__ import annotations

import numpy as np


def rga_trace(rng, n_ops: int, n_actors: int = 8,
              p_delete: float = 0.15, actor_bits: int = 8) -> dict:
    """A valid RGA op log: inserts reference earlier vertices (Lamport
    child > parent by construction: lamport_i = i+1, refs point backward)
    plus tombstones on random earlier inserts.

    Returns dense fields for rga_kernel.rga_merge (all lanes valid; the
    kernel accepts extra padding lanes with valid=False).  Vectorized —
    usable at 100k+ ops (BASELINE config 4).
    """
    n_ins = int(n_ops * (1.0 - p_delete))
    n_del = n_ops - n_ins
    assert n_actors <= (1 << actor_bits), "actor overflow"
    # packed uid must stay strictly below INT32_MAX (padding sentinel)
    assert (((n_ins + 1) << actor_bits) | ((1 << actor_bits) - 1)) \
        < 2**31 - 1, "lamport overflow"
    lam = np.arange(1, n_ins + 1, dtype=np.int32)
    actor = rng.integers(0, n_actors, size=n_ins).astype(np.int32)
    # ref: head with small probability, else a random earlier vertex,
    # biased to recent ones (typing locality)
    ref_idx = np.maximum(
        0, np.arange(n_ins) - 1 - rng.geometric(0.3, size=n_ins)
    ).astype(np.int64)
    at_head = (rng.random(n_ins) < 0.02) | (np.arange(n_ins) == 0)
    ref_lam = np.where(at_head, 0, lam[ref_idx]).astype(np.int32)
    ref_act = np.where(at_head, 0, actor[ref_idx]).astype(np.int32)
    elem = rng.integers(0, 64, size=n_ins).astype(np.int32)
    tgt = rng.integers(0, n_ins, size=max(n_del, 1)).astype(np.int64)
    return dict(
        ins_lamport=lam, ins_actor=actor, ref_lamport=ref_lam,
        ref_actor=ref_act, elem=elem,
        valid=np.ones(n_ins, dtype=bool),
        del_lamport=lam[tgt], del_actor=actor[tgt],
        del_valid=np.full(max(n_del, 1), n_del > 0),
    )


def orset_batch(rng, K: int, B: int, D: int, n_dcs: int,
                clock: np.ndarray, n_elems: int = 8,
                obs_lag: int = 1) -> dict:
    """One batch of B committed OR-Set ops over K keys.

    ``clock`` (int32[n_dcs], mutated in place) carries the per-DC commit
    counters across batches.  Every op's snapshot VC is <= the batch-end
    frontier, so applying the whole batch and folding at that frontier is
    causally valid.  Returns the dense field dict incl. the ``frontier``.
    """
    keys = rng.integers(0, K, size=B).astype(np.int32)
    elem = rng.integers(0, n_elems, size=B).astype(np.int32)
    is_add = rng.random(B) < 0.7
    dc = rng.integers(0, n_dcs, size=B).astype(np.int32)
    ct = np.zeros(B, dtype=np.int32)
    for d in range(n_dcs):
        m = dc == d
        ct[m] = clock[d] + 1 + np.arange(m.sum(), dtype=np.int32)
        clock[d] += int(m.sum())
    ss = np.zeros((B, D), dtype=np.int32)
    ss[:, :n_dcs] = np.minimum(clock[None, :], ct[:, None] - 1)
    if obs_lag:
        lag = rng.integers(0, obs_lag + 1, size=(B, D)).astype(np.int32)
    else:
        lag = 0
    obs = np.maximum(ss - lag, 0)
    frontier = np.zeros(D, dtype=np.int32)
    frontier[:n_dcs] = clock
    return dict(
        key_idx=keys, elem_slot=elem, is_add=is_add, dot_dc=dc,
        dot_seq=ct, obs_vv=obs, op_dc=dc.copy(), op_ct=ct.copy(), op_ss=ss,
        frontier=frontier,
    )

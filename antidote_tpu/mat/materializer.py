"""Pure materialization logic — the host twin of the device kernels.

Mirrors the reference's clocksi_materializer.erl semantics exactly
(reference src/clocksi_materializer.erl:82-268 and
src/materializer.erl:101-106):

- An op is *already covered* by a base snapshot B iff its commit VC
  (the op's snapshot VC with the origin-DC column bumped to its commit
  time) is <= B — unless it was written by the reading transaction
  itself (read-your-writes).
- An uncovered op is *included* for a read at snapshot S iff its commit
  VC is <= S on every DC column.
- Included ops apply oldest-first on top of the base snapshot value.
- The returned snapshot VC is the base time max'd with the commit VCs of
  every included op.
- *First-hole* tracking: the new snapshot covers the op-id prefix up to
  (oldest excluded op id) - 1; ops covered by the base snapshot do not
  open holes.  This is what lets cached snapshots record exactly which
  log prefix they contain so later reads know what to replay.

This host path is the semantic oracle: the batched TPU path
(antidote_tpu/mat/kernels.py) is property-tested against it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

from antidote_tpu.clocks import VC, vc_max
from antidote_tpu.crdt import get_type


@dataclass(frozen=True)
class Payload:
    """A committed update as seen by the materializer (the reference's
    #clocksi_payload record, include/antidote.hrl)."""

    key: Any
    type_name: str
    effect: Any
    commit_dc: Any
    commit_time: int
    snapshot_vc: VC
    txid: Any = None
    #: whether write-write certification gated this commit — the device
    #: plane's dense dot collapse is only sound for certified commits
    certified: bool = True

    def commit_vc(self) -> VC:
        return self.snapshot_vc.set_dc(self.commit_dc, self.commit_time)


@dataclass
class MaterializedSnapshot:
    """A cached materialized value (reference #materialized_snapshot)."""

    last_op_id: int
    value: Any


@dataclass
class SnapshotGetResponse:
    """Input to materialize (reference #snapshot_get_response): the base
    snapshot, its time (None = no base / bottom), and the candidate ops
    as (op_id, payload), most recent first."""

    snapshot_time: Optional[VC]
    ops: Sequence[Tuple[int, Payload]]
    materialized: MaterializedSnapshot
    is_newest: bool = True


@dataclass
class MaterializeResult:
    value: Any
    #: id such that the produced snapshot covers all ops with id <= this
    first_hole: int
    #: smallest VC describing the produced snapshot (None if no base and
    #: nothing applied)
    snapshot_vc: Optional[VC]
    #: True if at least one op was applied on top of the base
    is_new_snapshot: bool
    ops_applied: int


def op_covered_by(base_time: Optional[VC], op: Payload) -> bool:
    """Is the op already contained in a snapshot at ``base_time``?
    (the negation of the reference's belongs_to_snapshot_op)."""
    if base_time is None:
        return False
    return op.commit_vc().le(base_time)


def op_in_read_snapshot(read_vc: Optional[VC], op: Payload) -> bool:
    """May the op be included when reading at ``read_vc``?
    ``read_vc=None`` means 'latest' — include everything (the reference's
    ``ignore`` snapshot used by get_objects)."""
    if read_vc is None:
        return True
    return op.commit_vc().le(read_vc)


def materialize(type_name: str, txid: Any, min_snapshot_time: VC,
                response: SnapshotGetResponse) -> MaterializeResult:
    """Build the value of a key at ``min_snapshot_time`` from a base
    snapshot plus its candidate op list (most recent first)."""
    cls = get_type(type_name)
    base_time = response.snapshot_time
    ops = list(response.ops)

    first_hole = ops[0][0] if ops else 0
    included: List[Payload] = []  # collected newest-first
    snap_vc: Optional[VC] = base_time

    for op_id, op in ops:
        if op.type_name != cls.name:
            raise ValueError(
                f"corrupted ops cache: op type {op.type_name} != {cls.name}"
            )
        covered = op_covered_by(base_time, op) and not (
            txid is not None and op.txid == txid
        )
        if covered:
            continue  # already in the base snapshot; no hole
        if op_in_read_snapshot(min_snapshot_time, op):
            included.append(op)
            cvc = op.commit_vc()
            snap_vc = cvc if snap_vc is None else vc_max([snap_vc, cvc])
        else:
            # excluded: snapshot only covers ops below this id
            first_hole = op_id - 1

    value = response.materialized.value
    for op in reversed(included):  # apply oldest-first
        value = cls.update(op.effect, value)

    return MaterializeResult(
        value=value,
        first_hole=first_hole,
        snapshot_vc=snap_vc,
        is_new_snapshot=bool(included),
        ops_applied=len(included),
    )


def materialize_from_log(type_name: str, log_payloads: Sequence[Tuple[int, Payload]],
                         read_vc: Optional[VC], txid: Any = None
                         ) -> MaterializeResult:
    """Full log replay for one key from scratch — the snapshot-cache
    miss path shared by the host store's pruned-history fallback and the
    device plane's below-base fallback (reference get_from_snapshot_log,
    src/materializer_vnode.erl:415-419).  ``log_payloads``: [(seq,
    Payload)] in log order (PartitionLog.committed_payloads)."""
    ops = list(reversed(log_payloads))
    resp = SnapshotGetResponse(
        snapshot_time=None, ops=ops,
        materialized=MaterializedSnapshot(
            last_op_id=0, value=get_type(type_name).new()))
    return materialize(type_name, txid, read_vc, resp)


def materialize_eager(type_name: str, value: Any, effects: Sequence[Any]) -> Any:
    """Apply raw effects in order with no snapshot checks (reference
    src/clocksi_materializer.erl:272-274; used for read-your-writes)."""
    cls = get_type(type_name)
    for eff in effects:
        value = cls.update(eff, value)
    return value

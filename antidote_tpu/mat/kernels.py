"""Batched device materialization kernels (JAX/XLA, TPU-first).

The reference materializes one key at a time by walking its op list in a
gen_server (reference src/clocksi_materializer.erl:145-171 — the #1 hot
loop, see SURVEY §3.1).  Here materialization is a *batched tensor
program* over K keys at once; every step is data-parallel:

1. **Inclusion mask** — the per-op snapshot test (commit VC vs base/read
   VC) over the whole padded op block ``[K, L]`` in one fused op
   (semantics: src/materializer.erl:101-106, src/clocksi_materializer.erl:214-268).

2. **Effect application** without sequential scans.  Under causal
   delivery an OR-Set element's dot set always collapses to at most one
   live dot per origin DC, so state is a dense version-vector table
   ``dots[K, E, D]`` (E = element slots) and applying a batch of ops
   reduces to two segmented max-reductions:

   - ``last_seq[e, d]`` = max dot seq over included adds of element e
     from DC d
   - ``max_obs[e, d]``  = max observed-VV over included ops of element e

   A dot survives iff ``max(base, last_seq) > max_obs`` — any op whose
   observed VV dominates a dot was causally delivered after it and
   cancels it (the ORSWOT join).  No scan, no op ordering: max is
   associative and commutative, exactly because CRDT effects are.

   MV-registers are the same lattice with values as elements; EW-flags
   are a single implicit element; PN-counters are a masked sum.

Conventions:
- dots are ``(dc_index, seq)`` with seq monotonically increasing per
  origin DC (seq 0 = no dot);
- element slots are dense indices assigned host-side (hash interning);
- all arrays are fixed-shape; invalid / padding lanes carry valid=False.

Profiling (ISSUE 2): nothing here is jit-decorated — these folds are
pure building blocks composed INTO the jitted entry points of
mat/store.py / mat/rga_store.py, so the kernel-span layer
(antidote_tpu/obs/prof.py) times them at those call sites; wrapping
them here would fire inside jit traces and measure compilation, not
execution.  tools/trace_lint.py pins the invariant: any function in
this package that grows a ``@jax.jit`` decorator must also grow a
``@kernel_span``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from antidote_tpu.clocks import dense


def inclusion_mask(
    op_dc: jax.Array,      # int32[K, L] origin DC column per op
    op_ct: jax.Array,      # int[K, L] commit time
    op_ss: jax.Array,      # int[K, L, D] op snapshot VC
    op_valid: jax.Array,   # bool[K, L]
    base_vc: jax.Array,    # int[K, D] base snapshot time (zeros = bottom)
    has_base: jax.Array,   # bool[K] whether base_vc is a real snapshot
    read_vc: jax.Array,    # int[K, D] or int[D] read snapshot
) -> jax.Array:
    """bool[K, L]: which ops to apply on top of the base snapshot for a
    read at ``read_vc``.  Matches materialize()'s covered/included rules
    (host oracle: antidote_tpu/mat/materializer.py)."""
    cvc = dense.commit_vc(op_ss, op_dc, op_ct)          # [K, L, D]
    covered = dense.le(cvc, base_vc[:, None, :]) & has_base[:, None]
    if read_vc.ndim == 1:
        read_vc = read_vc[None, :]
    included = dense.le(cvc, read_vc[:, None, :])
    return op_valid & ~covered & included


def snapshot_vc_of(
    op_dc, op_ct, op_ss, mask, base_vc
) -> jax.Array:
    """int[K, D]: smallest VC describing the produced snapshot = base
    max'd with every included op's commit VC."""
    cvc = dense.commit_vc(op_ss, op_dc, op_ct)          # [K, L, D]
    cvc = jnp.where(mask[..., None], cvc, 0)
    return jnp.maximum(base_vc, jnp.max(cvc, axis=-2))


# ---------------------------------------------------------------------------
# counter_pn


def counter_read(base_val: jax.Array, deltas: jax.Array, mask: jax.Array):
    """int[K]: base + masked sum of deltas (counter_pn materialize)."""
    return base_val + jnp.sum(jnp.where(mask, deltas, 0), axis=-1)


# ---------------------------------------------------------------------------
# OR-set (set_aw) / MV-register — the dotted-version-vector lattice


def _orset_fold(base_dots, elem_slot, is_add, dot_dc, dot_seq, obs_vv, mask):
    """Batched fold of L ops per key into the element×DC dot tables.

    base_dots: [K, E, D]; elem_slot,is_add,dot_dc,dot_seq: [K, L];
    obs_vv: [K, L, D].  Returns live dot tables [K, E, D].

    Implemented as one-hot masked max-reductions over the op axis — NOT
    scatters: XLA fuses the one-hot compare into the reduction without
    materializing [K, L, E, D], while a vmapped ``.at[].max`` lowers to
    a giant scatter that runs ~1000x slower on TPU (measured: the
    scatter form made a 1M-key read take 836 ms; this form ~3 ms).
    Ops routed to slot >= E match no one-hot column and drop out, same
    as the previous mode="drop" contract.
    """
    k, e, d = base_dots.shape
    dt = base_dots.dtype
    add_mask = mask & is_add
    e_hot = elem_slot[..., None] == jnp.arange(e, dtype=elem_slot.dtype)
    d_hot = dot_dc[..., None] == jnp.arange(d, dtype=dot_dc.dtype)
    sel = (add_mask[..., None, None]
           & e_hot[..., :, None] & d_hot[..., None, :])      # [K, L, E, D]
    seqs = dot_seq.astype(dt)[..., None, None]
    last_seq = jnp.max(
        jnp.where(sel, seqs, jnp.zeros((), dt)), axis=1)     # [K, E, D]
    obs_sel = (mask[..., None] & e_hot)[..., None]           # [K, L, E, 1]
    obs = obs_vv.astype(dt)[:, :, None, :]                   # [K, L, 1, D]
    max_obs = jnp.max(
        jnp.where(obs_sel, obs, jnp.zeros((), dt)), axis=1)  # [K, E, D]
    merged = jnp.maximum(base_dots, last_seq)
    return jnp.where(merged > max_obs, merged, jnp.zeros((), dt))


def orset_apply(
    base_dots: jax.Array,  # int[K, E, D] live dot table
    elem_slot: jax.Array,  # int32[K, L] element slot per op
    is_add: jax.Array,     # bool[K, L] add vs remove
    dot_dc: jax.Array,     # int32[K, L] minting DC (adds)
    dot_seq: jax.Array,    # int[K, L] minted seq (adds; 0 for removes)
    obs_vv: jax.Array,     # int[K, L, D] observed VV per op
    mask: jax.Array,       # bool[K, L] inclusion mask
) -> jax.Array:
    """Apply a padded op block to the OR-set dot tables; returns the new
    ``dots[K, E, D]``.  Ops outside ``mask`` (padding / excluded by the
    snapshot test) are no-ops.  Associative: callers may split L into
    chunks and fold."""
    # ops routed to a slot >= E are dropped by scatter mode="drop";
    # padding lanes use slot E (out of range) for safety
    return _orset_fold(
        base_dots, elem_slot, is_add, dot_dc, dot_seq, obs_vv, mask
    )


def orset_present(dots: jax.Array) -> jax.Array:
    """bool[K, E]: element visible iff it has any live dot."""
    return jnp.any(dots > 0, axis=-1)


def mvreg_apply(base_dots, val_slot, dot_dc, dot_seq, obs_vv, mask):
    """MV-register fold: like the OR-set lattice over value slots, except
    an assign supersedes *every* pair it observed regardless of value —
    so the observed-VV cancellation applies across all rows, not just the
    assign's own slot.  Concurrent assigns (mutually unobserved dots)
    keep multiple live value slots.

    base_dots: [K, E, D]; val_slot/dot_dc/dot_seq: [K, L];
    obs_vv: [K, L, D]; mask: [K, L].  One-hot reductions, not scatters
    (see _orset_fold)."""
    k, e, d = base_dots.shape
    dt = base_dots.dtype
    e_hot = val_slot[..., None] == jnp.arange(e, dtype=val_slot.dtype)
    d_hot = dot_dc[..., None] == jnp.arange(d, dtype=dot_dc.dtype)
    sel = (mask[..., None, None]
           & e_hot[..., :, None] & d_hot[..., None, :])      # [K, L, E, D]
    seqs = dot_seq.astype(dt)[..., None, None]
    last_seq = jnp.max(
        jnp.where(sel, seqs, jnp.zeros((), dt)), axis=1)     # [K, E, D]
    max_obs = jnp.max(
        jnp.where(mask[..., None], obs_vv.astype(dt),
                  jnp.zeros((), dt)), axis=1)                # [K, D]
    merged = jnp.maximum(base_dots, last_seq)
    return jnp.where(merged > max_obs[:, None, :], merged, jnp.zeros((), dt))


def flag_ew_read(base_dots, dot_dc, dot_seq, is_enable, obs_vv, mask):
    """bool[K]: enable-wins flag = OR-set with one implicit element.
    base_dots: [K, D]; others [K, L(, D)]."""
    slot = jnp.zeros_like(dot_dc)
    dots = orset_apply(
        base_dots[:, None, :], slot, is_enable, dot_dc, dot_seq, obs_vv, mask
    )
    return jnp.any(dots[:, 0, :] > 0, axis=-1)


# ---------------------------------------------------------------------------
# set_rw (remove-wins) / flag_dw — the two-plane dotted lattice
#
# Remove-wins is the OR-Set algebra run twice with *cross*-cancellation:
# adds and removes each mint dots into their own table; an add's observed
# VV cancels remove-dots, a remove's cancels add-dots, a reset's cancels
# both (host oracle: crdt/sets.py SetRW).  Presence = any live add dot
# AND no live remove dot.  The per-DC max collapse is prefix-cancel
# insensitive exactly as for the OR-Set: an observed-VV gap at a column
# implies an included earlier op already canceled below the gap (causal
# delivery), so watermark-cancel agrees with exact dot-cancel on
# liveness.  (Reference semantics: antidote_crdt_set_rw, exercised at
# test/singledc/pb_client_SUITEs.erl:360.)

#: op kinds in the packed ring
RW_ADD, RW_RMV, RW_RESET = 0, 1, 2


def rwset_apply(
    base_adds: jax.Array,  # int[K, E, D] live add-dot table
    base_rmvs: jax.Array,  # int[K, E, D] live remove-dot table
    elem_slot: jax.Array,  # int32[K, L]
    kind: jax.Array,       # int[K, L] RW_ADD / RW_RMV / RW_RESET
    dot_dc: jax.Array,     # int32[K, L] minting DC (add/rmv rows)
    dot_seq: jax.Array,    # int[K, L] minted seq (0 = no dot)
    obs_add: jax.Array,    # int[K, L, D] observed add-VV (rmv/reset rows)
    obs_rmv: jax.Array,    # int[K, L, D] observed rmv-VV (add/reset rows)
    mask: jax.Array,       # bool[K, L] inclusion mask
):
    """Returns the new (adds, rmvs) dot tables [K, E, D].  Rows carry a
    zero observed-VV on the plane they do not cancel (an add's obs_add is
    0), so each plane takes its max-observed over ALL included rows."""
    k, e, d = base_adds.shape
    dt = base_adds.dtype
    e_hot = elem_slot[..., None] == jnp.arange(e, dtype=elem_slot.dtype)
    d_hot = dot_dc[..., None] == jnp.arange(d, dtype=dot_dc.dtype)
    obs_sel = (mask[..., None] & e_hot)[..., None]           # [K, L, E, 1]

    def plane(mint_kind, base, obs):
        sel = ((mask & (kind == mint_kind))[..., None, None]
               & e_hot[..., :, None] & d_hot[..., None, :])  # [K, L, E, D]
        seqs = dot_seq.astype(dt)[..., None, None]
        last = jnp.max(jnp.where(sel, seqs, jnp.zeros((), dt)), axis=1)
        o = obs.astype(dt)[:, :, None, :]                    # [K, L, 1, D]
        max_obs = jnp.max(jnp.where(obs_sel, o, jnp.zeros((), dt)), axis=1)
        merged = jnp.maximum(base, last)
        return jnp.where(merged > max_obs, merged, jnp.zeros((), dt))

    return plane(RW_ADD, base_adds, obs_add), \
        plane(RW_RMV, base_rmvs, obs_rmv)


def rwset_present(adds: jax.Array, rmvs: jax.Array) -> jax.Array:
    """bool[K, E]: element visible iff some live add dot and no live
    remove dot (remove wins over concurrency)."""
    return jnp.any(adds > 0, axis=-1) & ~jnp.any(rmvs > 0, axis=-1)


# ---------------------------------------------------------------------------
# set_go — grow-only presence (no dots, no cancellation)


def setgo_apply(base_present: jax.Array,  # bool[K, E]
                elem_slot: jax.Array,     # int32[K, L]
                mask: jax.Array):         # bool[K, L]
    """bool[K, E]: presence after applying the included add rows (the
    whole CRDT is a monotone OR; reference antidote_crdt_set_go)."""
    e = base_present.shape[1]
    e_hot = elem_slot[..., None] == jnp.arange(e, dtype=elem_slot.dtype)
    return base_present | jnp.any(mask[..., None] & e_hot, axis=1)


# ---------------------------------------------------------------------------
# register_lww


def lww_read(
    base_ts: jax.Array,    # int[K] base (ts) key
    base_tie: jax.Array,   # int[K] base tiebreak
    base_val: jax.Array,   # int[K] base interned value id
    op_ts: jax.Array,      # int[K, L]
    op_tie: jax.Array,     # int[K, L]
    op_val: jax.Array,     # int[K, L] interned value ids
    mask: jax.Array,       # bool[K, L]
):
    """(ts, tie, val)[K]: max (ts, tie) among base and included ops —
    last-writer-wins with a deterministic tiebreak.  Lexicographic max is
    computed in two masked reductions (no packing, no overflow)."""
    neg = jnp.asarray(-1, dtype=op_ts.dtype)
    ts = jnp.where(mask, op_ts, neg)
    mts = jnp.max(ts, axis=-1)                                   # [K]
    at_mts = mask & (ts == mts[:, None])
    mtie = jnp.max(jnp.where(at_mts, op_tie, neg), axis=-1)      # [K]
    idx = jnp.argmax(at_mts & (op_tie == mtie[:, None]), axis=-1)
    k = jnp.arange(ts.shape[0])
    cand_val = op_val[k, idx]
    take = (mts > base_ts) | ((mts == base_ts) & (mtie > base_tie))
    return (
        jnp.where(take, mts, base_ts),
        jnp.where(take, mtie, base_tie),
        jnp.where(take, cand_val, base_val),
    )

"""Pallas TPU kernels for the materializer hot path.

``orset_read_packed`` fuses the whole snapshot-read pipeline — per-op
commit-VC construction, the Clock-SI inclusion test, the ORSWOT
dot-table fold, and element presence — into one VMEM-resident pass over
key blocks of the *packed* store layout (antidote_tpu/mat/store.py
``OrsetShardState.ops``).  The jnp reference path
(antidote_tpu/mat/kernels.py inclusion_mask → orset_apply →
orset_present) materializes the [K, L, D] commit-VC tensor and the
[K, E, D] fold intermediates in HBM between XLA fusions; here the packed
rows are read from HBM exactly once and nothing but the [TK, E] presence
block leaves VMEM.  This replaces the reference's per-key materialize
walk (reference src/clocksi_materializer.erl:145-171) for bulk reads.

Mosaic lowering notes (learned against the real v5e compiler — the
failures are silent under interpret mode, so this kernel restricts
itself to patterns the hardware compiler accepts):
- NO 3D refs or values: slicing a middle axis of a 3D vector yields
  sublane-offset layouts that ``tpu.concatenate``/elementwise ops
  reject ("result/input offset mismatch on non-concat dimension").
  All inputs arrive as 2D blocks — the packed rows as [TK, L*F], the
  dot table flattened to [TK, E*D] (a free row-major bitcast outside
  the kernel).
- The scatter-max of the jnp path (``.at[elem_slot, dot_dc].max``) does
  not exist on the VPU; it is replaced by one-hot masked max-reductions
  over the (tiny, static) lane × DC axes — fully unrolled loops of
  [TK, E*D] maxes, which vectorize cleanly on the 128-lane VPU.
- Per-op scalars are extracted as single columns ``ops[:, j][:, None]``
  and lane-broadcast against [TK, E*D] tiles — the one relayout mosaic
  handles well.  Cross-DC reductions are unrolled into scalar compares
  against SMEM-resident base/read VCs instead of axis reductions over
  lane-offset slices.

All integer inputs are int32 (bool inputs arrive as int32 0/1); K is
blocked by ``block_k``.  Falls back to interpret mode off-TPU (tests run
the same kernel code on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from antidote_tpu.obs.prof import kernel_span

# index-map constants must stay int32: the package enables jax x64, and
# a plain Python 0 traces as i64 there, which mosaic rejects
_Z = np.int32(0)

# packed column order (store.py): [elem, is_add, dot_dc, dot_seq, op_dc,
# op_ct, obs_vv(D), op_ss(D)]
_NSCAL = 6


def _fold_live(dots, ops, lane_mask, e: int, d: int, l: int):
    """Shared ORSWOT fold body: the live dot table [TK, E*D] after
    applying the masked lanes — a dot survives iff its seq exceeds every
    observed VV that covered its (elem, dc) cell.  ``lane_mask(i)``
    yields lane i's inclusion∧valid column ([TK, 1] bool).  This is
    kernels.orset_apply restated as one-hot masked max-reductions over
    the static lane × DC axes (see module doc)."""
    f = _NSCAL + 2 * d
    tk = dots.shape[0]
    ed = e * d
    col = lambda j: ops[:, j][:, None]                  # [TK, 1]

    # flat (e, d) coordinate planes, built from offset-0 pieces only
    d_row = jax.lax.broadcasted_iota(jnp.int32, (tk, d), 1)
    d_col = jnp.concatenate([d_row] * e, axis=1)        # [TK, E*D]
    e_col = jnp.concatenate(
        [jnp.full((tk, d), np.int32(j)) for j in range(e)], axis=1)

    last_seq = jnp.zeros((tk, ed), jnp.int32)
    max_obs = jnp.zeros((tk, ed), jnp.int32)
    for i in range(l):                                  # static unroll
        off = i * f
        mask_i = lane_mask(i)
        add_i = mask_i & (col(off + 1) != _Z)
        at_e = e_col == col(off + 0)                    # [TK, E*D]
        at_d = d_col == col(off + 2)
        last_seq = jnp.maximum(
            last_seq, jnp.where(at_e & at_d & add_i, col(off + 3), _Z))
        # the op's observed VV, tiled across the E axis one DC column at
        # a time (obs depends only on the flat position's d coordinate)
        obs_t = jnp.zeros((tk, ed), jnp.int32)
        for dd in range(d):
            obs_t = jnp.where(d_col == np.int32(dd),
                              col(off + _NSCAL + dd), obs_t)
        max_obs = jnp.maximum(
            max_obs, jnp.where(at_e & mask_i, obs_t, _Z))

    merged = jnp.maximum(dots, last_seq)
    return jnp.where(merged > max_obs, merged, _Z)      # [TK, E*D]


def _fold_presence(dots, ops, lane_mask, e: int, d: int, l: int):
    """ORSWOT fold + element presence (read kernels): presence per
    element = max over its D chunk of the live table, via column
    maxes."""
    live = _fold_live(dots, ops, lane_mask, e, d, l)
    outs = []
    for j in range(e):
        m = live[:, j * d][:, None]
        for dd in range(1, d):
            m = jnp.maximum(m, live[:, j * d + dd][:, None])
        outs.append(m)
    return jnp.concatenate(outs, axis=1)                # [TK, E]


def _orset_read_kernel(
    dots_ref,       # [TK, E*D] VMEM (flattened dot table)
    ops_ref,        # [TK, L*F] VMEM (packed store rows)
    valid_ref,      # [TK, L]   VMEM
    base_ref,       # [1, D]    SMEM
    has_base_ref,   # [1, 1]    SMEM
    read_ref,       # [1, D]    SMEM
    out_ref,        # [TK, E]   VMEM
    *, e: int, d: int, l: int,
):
    f = _NSCAL + 2 * d
    tk = out_ref.shape[0]
    ops = ops_ref[:]
    valid = valid_ref[:]
    has_base = has_base_ref[0, 0] != _Z
    col = lambda j: ops[:, j][:, None]
    true_col = jnp.ones((tk, 1), jnp.bool_)

    def lane_mask(i):
        # inclusion test, unrolled across DC columns as scalar compares
        # (commit VC = op snapshot with the origin column bumped to the
        # commit time; the Clock-SI read rule, txn/coordinator.py)
        off = i * f
        opdc_i = col(off + 4)
        opct_i = col(off + 5)
        cov_i = true_col
        inc_i = true_col
        for dd in range(d):
            ss_c = col(off + _NSCAL + d + dd)
            cvc_c = jnp.where(opdc_i == np.int32(dd),
                              jnp.maximum(ss_c, opct_i), ss_c)
            cov_i = cov_i & (cvc_c <= base_ref[0, dd])
            inc_i = inc_i & (cvc_c <= read_ref[0, dd])
        return (valid[:, i][:, None] != _Z) & inc_i & ~(cov_i & has_base)

    out_ref[:] = _fold_presence(dots_ref[:], ops, lane_mask, e, d, l)


@kernel_span("mat.pallas")
@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def orset_read_packed(dots, ops, valid, base_vc, has_base, read_vc,
                      block_k: int = 256, interpret: bool = False):
    """bool[K, E]: full-shard presence read straight off the packed
    store layout, one HBM pass.  ``dots``: int[K, E, D]; ``ops``:
    int[K*L, F] with the store's column order; ``valid``: bool[K*L]."""
    k, e, d = dots.shape
    f = ops.shape[-1]
    l = ops.shape[0] // k
    i32 = lambda a: a.astype(jnp.int32)
    grid = (pl.cdiv(k, block_k),)
    row = lambda i: (i, _Z)
    bspec = lambda shp: pl.BlockSpec(shp, row, memory_space=pltpu.VMEM)
    smem = lambda shp: pl.BlockSpec(
        shp, lambda i: (_Z, _Z), memory_space=pltpu.SMEM)
    kern = functools.partial(_orset_read_kernel, e=e, d=d, l=l)
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            bspec((block_k, e * d)),
            bspec((block_k, l * f)),
            bspec((block_k, l)),
            smem((1, d)),
            smem((1, 1)),
            smem((1, d)),
        ],
        out_specs=bspec((block_k, e)),
        out_shape=jax.ShapeDtypeStruct((k, e), jnp.int32),
        interpret=interpret,
    )(
        i32(dots).reshape(k, e * d),        # row-major bitcast, free
        i32(ops).reshape(k, l * f),
        i32(valid).reshape(k, l),
        i32(base_vc)[None, :], i32(has_base).reshape(1, 1),
        i32(read_vc)[None, :],
    )
    return out > 0


def _orset_fold_kernel(
    dots_ref,       # [TK, E*D] VMEM (flattened dot table)
    ops_ref,        # [TK, L*F] VMEM (packed store rows)
    mask_ref,       # [TK, L]   VMEM (inclusion ∧ valid, precomputed)
    out_ref,        # [TK, E]   VMEM
    *, e: int, d: int, l: int,
):
    """Fold-only variant: the Clock-SI inclusion test runs OUTSIDE the
    kernel (one fused XLA pass producing mask[K, L]), so the unrolled
    per-lane × per-DC scalar-compare chains — the tiny-op cost that
    bounds the fully-fused kernel's block size — disappear.  ~60% fewer
    vector ops per block at the price of one extra HBM read of the op
    rows by the XLA mask pass."""
    mask = mask_ref[:]
    out_ref[:] = _fold_presence(
        dots_ref[:], ops_ref[:],
        lambda i: mask[:, i][:, None] != _Z, e, d, l)


@kernel_span("mat.pallas")
@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def orset_read_hybrid(dots, ops, valid, base_vc, has_base, read_vc,
                      block_k: int = 512, interpret: bool = False):
    """bool[K, E]: like :func:`orset_read_packed` but with the
    inclusion mask computed by XLA outside the kernel and only the
    ORSWOT fold in Pallas (see _orset_fold_kernel)."""
    from antidote_tpu.mat import kernels

    k, e, d = dots.shape
    f = ops.shape[-1]
    l = ops.shape[0] // k
    i32 = lambda a: a.astype(jnp.int32)
    opsv = i32(ops).reshape(k, l, f)
    base_b = jnp.broadcast_to(i32(base_vc), (k, d))
    has_b = jnp.broadcast_to(has_base.astype(bool), (k,))
    mask = kernels.inclusion_mask(
        opsv[..., 4], opsv[..., 5], opsv[..., _NSCAL + d:],
        valid.reshape(k, l), base_b, has_b, i32(read_vc))
    grid = (pl.cdiv(k, block_k),)
    row = lambda i: (i, _Z)
    bspec = lambda shp: pl.BlockSpec(shp, row, memory_space=pltpu.VMEM)
    kern = functools.partial(_orset_fold_kernel, e=e, d=d, l=l)
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            bspec((block_k, e * d)),
            bspec((block_k, l * f)),
            bspec((block_k, l)),
        ],
        out_specs=bspec((block_k, e)),
        out_shape=jax.ShapeDtypeStruct((k, e), jnp.int32),
        interpret=interpret,
    )(
        i32(dots).reshape(k, e * d),
        i32(ops).reshape(k, l * f),
        mask.astype(jnp.int32),
    )
    return out > 0


def _orset_gc_kernel(
    dots_ref,       # [TK, E*D] VMEM (flattened dot table)
    ops_ref,        # [TK, L*F] VMEM (packed store rows)
    valid_ref,      # [TK, L]   VMEM
    gst_ref,        # [1, D]    SMEM
    ndots_ref,      # [TK, E*D] VMEM out — folded dot table
    nvalid_ref,     # [TK, L]   VMEM out — surviving lanes
    *, e: int, d: int, l: int,
):
    """Fused GC fold (store.orset_gc semantics): every valid lane whose
    commit VC <= GST folds into the dot table and frees; the jnp path
    materializes the [K, L, D] commit-VC tensor and the [K, L, E, D]
    one-hot select in HBM between XLA fusions (measured 34 ms per GC at
    1M keys on the round-5 bench chip), here the packed rows are read
    once and only the folded table + lane bitmap leave VMEM."""
    f = _NSCAL + 2 * d
    tk = dots_ref.shape[0]
    ops = ops_ref[:]
    valid = valid_ref[:]
    col = lambda j: ops[:, j][:, None]
    true_col = jnp.ones((tk, 1), jnp.bool_)

    stable = []
    for i in range(l):                                  # static unroll
        off = i * f
        opdc_i = col(off + 4)
        opct_i = col(off + 5)
        st_i = true_col
        for dd in range(d):
            # commit VC column dd: the op snapshot with the origin
            # column bumped to the commit time (ct >= ss[origin], so
            # max == set; same form as the read kernels)
            ss_c = col(off + _NSCAL + d + dd)
            cvc_c = jnp.where(opdc_i == np.int32(dd),
                              jnp.maximum(ss_c, opct_i), ss_c)
            st_i = st_i & (cvc_c <= gst_ref[0, dd])
        stable.append((valid[:, i][:, None] != _Z) & st_i)

    ndots_ref[:] = _fold_live(
        dots_ref[:], ops, lambda i: stable[i], e, d, l)
    nvalid_ref[:] = jnp.concatenate(
        [((valid[:, i][:, None] != _Z) & ~stable[i]).astype(jnp.int32)
         for i in range(l)], axis=1)


@kernel_span("mat.pallas")
@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def orset_gc_packed(dots, ops, valid, gst,
                    block_k: int = 256, interpret: bool = False):
    """(new_dots int[K, E, D], new_valid bool[K*L]): the store GC fold
    as one HBM pass.  Semantics identical to store.orset_gc's
    dots/valid update (base_vc/has_base are caller-side scalars)."""
    k, e, d = dots.shape
    f = ops.shape[-1]
    l = ops.shape[0] // k
    i32 = lambda a: a.astype(jnp.int32)
    grid = (pl.cdiv(k, block_k),)
    row = lambda i: (i, _Z)
    bspec = lambda shp: pl.BlockSpec(shp, row, memory_space=pltpu.VMEM)
    smem = lambda shp: pl.BlockSpec(
        shp, lambda i: (_Z, _Z), memory_space=pltpu.SMEM)
    kern = functools.partial(_orset_gc_kernel, e=e, d=d, l=l)
    ndots, nvalid = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            bspec((block_k, e * d)),
            bspec((block_k, l * f)),
            bspec((block_k, l)),
            smem((1, d)),
        ],
        out_specs=(bspec((block_k, e * d)), bspec((block_k, l))),
        out_shape=(jax.ShapeDtypeStruct((k, e * d), jnp.int32),
                   jax.ShapeDtypeStruct((k, l), jnp.int32)),
        interpret=interpret,
    )(
        i32(dots).reshape(k, e * d),
        i32(ops).reshape(k, l * f),
        i32(valid).reshape(k, l),
        i32(gst)[None, :],
    )
    return ndots.reshape(k, e, d), (nvalid > 0).reshape(k * l)


@kernel_span("mat.pallas")
@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def orset_read_fused(
    dots, elem_slot, is_add, dot_dc, dot_seq, obs_vv,
    op_dc, op_ct, op_ss, valid, base_vc, has_base, read_vc,
    block_k: int = 256, interpret: bool = False,
):
    """bool[K, E]: presence at ``read_vc`` from per-field [K, L(, D)]
    views; semantics identical to kernels.inclusion_mask + orset_apply +
    orset_present with a shard-wide (unbatched) base/read VC.

    Compatibility entry: packs the fields into the store's row layout
    (one XLA fusion) and runs :func:`orset_read_packed`.  Callers that
    hold an ``OrsetShardState`` should use store.orset_read_full, which
    skips the repack."""
    k, e, d = dots.shape
    l = elem_slot.shape[1]
    i32 = lambda a: a.astype(jnp.int32)
    cols = [i32(elem_slot)[:, :, None], i32(is_add)[:, :, None],
            i32(dot_dc)[:, :, None], i32(dot_seq)[:, :, None],
            i32(op_dc)[:, :, None], i32(op_ct)[:, :, None],
            i32(obs_vv), i32(op_ss)]
    ops = jnp.concatenate(cols, axis=2).reshape(k * l, -1)
    return orset_read_packed(
        dots, ops, valid.reshape(k * l), base_vc, has_base, read_vc,
        block_k=block_k, interpret=interpret)

"""Pallas TPU kernels for the materializer hot path.

``orset_read_fused`` fuses the whole snapshot-read pipeline — per-op
commit-VC construction, the Clock-SI inclusion test, the ORSWOT
dot-table fold, and element presence — into one VMEM-resident pass over
key blocks.  The jnp reference path (antidote_tpu/mat/kernels.py
inclusion_mask → orset_apply → orset_present) materializes the [K, L, D]
commit-VC tensor and the [K, E, D] fold intermediates in HBM between
XLA fusions; here nothing leaves VMEM but the [TK, E] presence block.

The scatter-max of the jnp path (``.at[elem_slot, dot_dc].max``) does
not exist on the VPU; it is replaced by one-hot masked max-reductions
over the (tiny, static) element × DC axes — an unrolled L-step loop of
[TK, E, D] maxes, which vectorizes cleanly.

All integer inputs are int32 (bool inputs arrive as int32 0/1); shapes
are the shard-store layouts [K, L], [K, L, D], [K, E, D] with K blocked
by ``block_k``.  Falls back to interpret mode off-TPU (tests run the
same kernel code on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# index-map constants must stay int32: the package enables jax x64, and
# a plain Python 0 traces as i64 there, which mosaic rejects
_Z = np.int32(0)


def _orset_read_core(dots, elem_slot, is_add, dot_dc, dot_seq, obs,
                     op_dc, op_ct, ss, valid, base, has_base, read):
    """Shared kernel body: inclusion test + ORSWOT fold + presence, all
    on VMEM-resident [TK, ...] blocks.  ``base``/``read``: [D];
    ``has_base``: scalar int32."""
    tk, e, d = dots.shape
    l = elem_slot.shape[1]

    dc_cols = jax.lax.broadcasted_iota(jnp.int32, (tk, l, d), 2)
    at_dc = dc_cols == op_dc[:, :, None]
    cvc = jnp.where(at_dc, jnp.maximum(ss, op_ct[:, :, None]), ss)

    base = base[None, None, :]                          # [1, 1, D]
    read = read[None, None, :]
    # bool all-reduce lowers as a float min on this mosaic version; an
    # int32 min-reduce compiles cleanly
    all2 = lambda c: jnp.min(
        jnp.where(c, np.int32(1), _Z), axis=2) == np.int32(1)
    covered = all2(cvc <= base) & (has_base != _Z)
    included = all2(cvc <= read)
    mask = (valid != _Z) & ~covered & included          # [TK, L]
    add_mask = mask & (is_add != _Z)

    # The fold runs on FLAT [TK, E*D] tiles: mosaic rejects the
    # (TK,1,1)->(TK,E,D) broadcasts the nested-axis form needs (vpad
    # {0,0}->{*,*} on both minor dims), while (TK,1)->(TK,E*D) lane
    # broadcasts and minor-dim concats lower cleanly — and a flat minor
    # dim of E*D (e.g. 64) uses the 128-lane VPU far better than D=8.
    ed = e * d
    d_row = jax.lax.broadcasted_iota(jnp.int32, (tk, d), 1)
    d_col = jnp.concatenate([d_row] * e, axis=1)        # [TK, E*D]
    e_col = jnp.concatenate(
        [jnp.full((tk, d), np.int32(j)) for j in range(e)], axis=1)

    last_seq = jnp.zeros((tk, ed), jnp.int32)
    max_obs = jnp.zeros((tk, ed), jnp.int32)
    for i in range(l):                                  # static unroll
        at_e = e_col == elem_slot[:, i][:, None]
        at_d = d_col == dot_dc[:, i][:, None]
        seq_i = jnp.where(at_e & at_d & add_mask[:, i][:, None],
                          dot_seq[:, i][:, None], _Z)
        last_seq = jnp.maximum(last_seq, seq_i)
        obs_i = jnp.concatenate([obs[:, i, :]] * e, axis=1)
        max_obs = jnp.maximum(
            max_obs, jnp.where(at_e & mask[:, i][:, None], obs_i, _Z))

    # flatten dots by column-wise concat — mosaic has no 3D->2D reshape
    dots_flat = jnp.concatenate(
        [dots[:, j, :] for j in range(e)], axis=1)      # [TK, E*D]
    merged = jnp.maximum(dots_flat, last_seq)
    live = jnp.where(merged > max_obs, merged, _Z)
    # presence = max over each key's d-chunk, assembled column-wise so
    # every op stays 2D
    return jnp.concatenate(
        [jnp.max(live[:, j * d:(j + 1) * d], axis=1, keepdims=True)
         for j in range(e)], axis=1)                    # >0 iff present


def _orset_read_kernel(
    dots_ref,       # [TK, E, D]
    elem_ref,       # [TK, L]
    is_add_ref,     # [TK, L]
    dot_dc_ref,     # [TK, L]
    dot_seq_ref,    # [TK, L]
    obs_ref,        # [TK, L, D]
    op_dc_ref,      # [TK, L]
    op_ct_ref,      # [TK, L]
    op_ss_ref,      # [TK, L, D]
    valid_ref,      # [TK, L]
    base_ref,       # [1, D]
    has_base_ref,   # [1, 1] (SMEM)
    read_ref,       # [1, D]
    out_ref,        # [TK, E]
):
    out_ref[:] = _orset_read_core(
        dots_ref[:], elem_ref[:], is_add_ref[:], dot_dc_ref[:],
        dot_seq_ref[:], obs_ref[:], op_dc_ref[:], op_ct_ref[:],
        op_ss_ref[:], valid_ref[:], base_ref[0], has_base_ref[0, 0],
        read_ref[0])


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def orset_read_fused(
    dots, elem_slot, is_add, dot_dc, dot_seq, obs_vv,
    op_dc, op_ct, op_ss, valid, base_vc, has_base, read_vc,
    block_k: int = 2048, interpret: bool = False,
):
    """bool[K, E]: element presence at ``read_vc``; semantics identical
    to kernels.inclusion_mask + orset_apply + orset_present with a
    shard-wide (unbatched) base_vc/has_base/read_vc."""
    k, e, d = dots.shape
    l = elem_slot.shape[1]
    i32 = lambda a: a.astype(jnp.int32)
    # non-divisible K: the last block is padded by pallas; rows are
    # independent, so padded lanes compute garbage that is dropped on
    # the (bounds-masked) write
    grid = (pl.cdiv(k, block_k),)
    row = lambda i: (i, _Z)
    row3 = lambda i: (i, _Z, _Z)
    bspec = lambda shp, ix: pl.BlockSpec(shp, ix, memory_space=pltpu.VMEM)
    rep = lambda shp: pl.BlockSpec(
        shp, lambda i: (_Z,) * len(shp), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _orset_read_kernel,
        grid=grid,
        in_specs=[
            bspec((block_k, e, d), row3),
            bspec((block_k, l), row), bspec((block_k, l), row),
            bspec((block_k, l), row), bspec((block_k, l), row),
            bspec((block_k, l, d), row3),
            bspec((block_k, l), row), bspec((block_k, l), row),
            bspec((block_k, l, d), row3),
            bspec((block_k, l), row),
            rep((1, d)),
            pl.BlockSpec((1, 1), lambda i: (_Z, _Z),
                         memory_space=pltpu.SMEM),
            rep((1, d)),
        ],
        out_specs=bspec((block_k, e), row),
        out_shape=jax.ShapeDtypeStruct((k, e), jnp.int32),
        interpret=interpret,
    )(
        i32(dots), i32(elem_slot), i32(is_add), i32(dot_dc), i32(dot_seq),
        i32(obs_vv), i32(op_dc), i32(op_ct), i32(op_ss), i32(valid),
        i32(base_vc)[None, :], i32(has_base).reshape(1, 1),
        i32(read_vc)[None, :],
    )
    return out > 0


def _orset_read_packed_kernel(
    dots_ref,       # [TK, E, D]
    ops_ref,        # [TK, L, F]  packed store rows (F = 6 + 2D)
    valid_ref,      # [TK, L]
    base_ref,       # [1, D]
    has_base_ref,   # [1, 1] (SMEM)
    read_ref,       # [1, D]
    out_ref,        # [TK, E]
):
    d = dots_ref.shape[2]
    o = ops_ref[:]
    # column extraction happens in VMEM — the packed layout is read from
    # HBM exactly once (the whole point of this variant; the unpacked
    # entry materializes ten per-field slices in HBM first)
    out_ref[:] = _orset_read_core(
        dots_ref[:], o[:, :, 0], o[:, :, 1], o[:, :, 2], o[:, :, 3],
        o[:, :, 6:6 + d], o[:, :, 4], o[:, :, 5], o[:, :, 6 + d:6 + 2 * d],
        valid_ref[:], base_ref[0], has_base_ref[0, 0], read_ref[0])


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def orset_read_packed(dots, ops, valid, base_vc, has_base, read_vc,
                      block_k: int = 2048, interpret: bool = False):
    """bool[K, E]: full-shard presence read straight off the packed
    store layout (antidote_tpu/mat/store.py OrsetShardState.ops), one
    HBM pass.  ``ops``: int[K*L, F] with the store's column order
    [elem, is_add, dot_dc, dot_seq, op_dc, op_ct, obs(D), ss(D)];
    ``valid``: bool[K*L]."""
    k, e, d = dots.shape
    f = ops.shape[-1]
    l = ops.shape[0] // k
    i32 = lambda a: a.astype(jnp.int32)
    grid = (pl.cdiv(k, block_k),)
    row = lambda i: (i, _Z)
    row3 = lambda i: (i, _Z, _Z)
    bspec = lambda shp, ix: pl.BlockSpec(shp, ix, memory_space=pltpu.VMEM)
    rep = lambda shp: pl.BlockSpec(
        shp, lambda i: (_Z,) * len(shp), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _orset_read_packed_kernel,
        grid=grid,
        in_specs=[
            bspec((block_k, e, d), row3),
            bspec((block_k, l, f), row3),
            bspec((block_k, l), row),
            rep((1, d)),
            pl.BlockSpec((1, 1), lambda i: (_Z, _Z),
                         memory_space=pltpu.SMEM),
            rep((1, d)),
        ],
        out_specs=bspec((block_k, e), row),
        out_shape=jax.ShapeDtypeStruct((k, e), jnp.int32),
        interpret=interpret,
    )(
        i32(dots), i32(ops).reshape(k, l, f), i32(valid).reshape(k, l),
        i32(base_vc)[None, :], i32(has_base).reshape(1, 1),
        i32(read_vc)[None, :],
    )
    return out > 0

"""Pallas TPU kernels for the materializer hot path.

``orset_read_fused`` fuses the whole snapshot-read pipeline — per-op
commit-VC construction, the Clock-SI inclusion test, the ORSWOT
dot-table fold, and element presence — into one VMEM-resident pass over
key blocks.  The jnp reference path (antidote_tpu/mat/kernels.py
inclusion_mask → orset_apply → orset_present) materializes the [K, L, D]
commit-VC tensor and the [K, E, D] fold intermediates in HBM between
XLA fusions; here nothing leaves VMEM but the [TK, E] presence block.

The scatter-max of the jnp path (``.at[elem_slot, dot_dc].max``) does
not exist on the VPU; it is replaced by one-hot masked max-reductions
over the (tiny, static) element × DC axes — an unrolled L-step loop of
[TK, E, D] maxes, which vectorizes cleanly.

All integer inputs are int32 (bool inputs arrive as int32 0/1); shapes
are the shard-store layouts [K, L], [K, L, D], [K, E, D] with K blocked
by ``block_k``.  Falls back to interpret mode off-TPU (tests run the
same kernel code on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# index-map constants must stay int32: the package enables jax x64, and
# a plain Python 0 traces as i64 there, which mosaic rejects
_Z = np.int32(0)


def _orset_read_kernel(
    dots_ref,       # [TK, E, D]
    elem_ref,       # [TK, L]
    is_add_ref,     # [TK, L]
    dot_dc_ref,     # [TK, L]
    dot_seq_ref,    # [TK, L]
    obs_ref,        # [TK, L, D]
    op_dc_ref,      # [TK, L]
    op_ct_ref,      # [TK, L]
    op_ss_ref,      # [TK, L, D]
    valid_ref,      # [TK, L]
    base_ref,       # [1, D]
    has_base_ref,   # [1, 1] (SMEM)
    read_ref,       # [1, D]
    out_ref,        # [TK, E]
):
    tk, e, d = dots_ref.shape
    l = elem_ref.shape[1]

    ss = op_ss_ref[:]                                   # [TK, L, D]
    dc_cols = jax.lax.broadcasted_iota(jnp.int32, (tk, l, d), 2)
    at_dc = dc_cols == op_dc_ref[:][:, :, None]
    cvc = jnp.where(at_dc, jnp.maximum(ss, op_ct_ref[:][:, :, None]), ss)

    base = base_ref[0][None, None, :]                   # [1, 1, D]
    read = read_ref[0][None, None, :]
    # bool all-reduce lowers as a float min on this mosaic version; an
    # int32 min-reduce compiles cleanly
    all2 = lambda c: jnp.min(
        jnp.where(c, np.int32(1), _Z), axis=2) == np.int32(1)
    covered = all2(cvc <= base) & (has_base_ref[0, 0] != _Z)
    included = all2(cvc <= read)
    mask = (valid_ref[:] != _Z) & ~covered & included   # [TK, L]
    add_mask = mask & (is_add_ref[:] != _Z)

    obs = obs_ref[:]
    elem_slot = elem_ref[:]
    dot_dc = dot_dc_ref[:]
    dot_seq = dot_seq_ref[:]

    last_seq = jnp.zeros((tk, e, d), jnp.int32)
    max_obs = jnp.zeros((tk, e, d), jnp.int32)
    e_ids = jax.lax.broadcasted_iota(jnp.int32, (tk, e, d), 1)
    d_ids = jax.lax.broadcasted_iota(jnp.int32, (tk, e, d), 2)
    for i in range(l):                                  # static unroll
        at_e = e_ids == elem_slot[:, i][:, None, None]
        at_d = d_ids == dot_dc[:, i][:, None, None]
        seq_i = jnp.where(
            at_e & at_d & add_mask[:, i][:, None, None],
            dot_seq[:, i][:, None, None], _Z)
        last_seq = jnp.maximum(last_seq, seq_i)
        obs_i = jnp.where(
            at_e & mask[:, i][:, None, None],
            obs[:, i, :][:, None, :], _Z)
        max_obs = jnp.maximum(max_obs, obs_i)

    merged = jnp.maximum(dots_ref[:], last_seq)
    live = jnp.where(merged > max_obs, merged, _Z)
    out_ref[:] = jnp.max(live, axis=2)                  # >0 iff present


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def orset_read_fused(
    dots, elem_slot, is_add, dot_dc, dot_seq, obs_vv,
    op_dc, op_ct, op_ss, valid, base_vc, has_base, read_vc,
    block_k: int = 2048, interpret: bool = False,
):
    """bool[K, E]: element presence at ``read_vc``; semantics identical
    to kernels.inclusion_mask + orset_apply + orset_present with a
    shard-wide (unbatched) base_vc/has_base/read_vc."""
    k, e, d = dots.shape
    l = elem_slot.shape[1]
    i32 = lambda a: a.astype(jnp.int32)
    # non-divisible K: the last block is padded by pallas; rows are
    # independent, so padded lanes compute garbage that is dropped on
    # the (bounds-masked) write
    grid = (pl.cdiv(k, block_k),)
    row = lambda i: (i, _Z)
    row3 = lambda i: (i, _Z, _Z)
    bspec = lambda shp, ix: pl.BlockSpec(shp, ix, memory_space=pltpu.VMEM)
    rep = lambda shp: pl.BlockSpec(
        shp, lambda i: (_Z,) * len(shp), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _orset_read_kernel,
        grid=grid,
        in_specs=[
            bspec((block_k, e, d), row3),
            bspec((block_k, l), row), bspec((block_k, l), row),
            bspec((block_k, l), row), bspec((block_k, l), row),
            bspec((block_k, l, d), row3),
            bspec((block_k, l), row), bspec((block_k, l), row),
            bspec((block_k, l, d), row3),
            bspec((block_k, l), row),
            rep((1, d)),
            pl.BlockSpec((1, 1), lambda i: (_Z, _Z),
                         memory_space=pltpu.SMEM),
            rep((1, d)),
        ],
        out_specs=bspec((block_k, e), row),
        out_shape=jax.ShapeDtypeStruct((k, e), jnp.int32),
        interpret=interpret,
    )(
        i32(dots), i32(elem_slot), i32(is_add), i32(dot_dc), i32(dot_seq),
        i32(obs_vv), i32(op_dc), i32(op_ct), i32(op_ss), i32(valid),
        i32(base_vc)[None, :], i32(has_base).reshape(1, 1),
        i32(read_vc)[None, :],
    )
    return out > 0

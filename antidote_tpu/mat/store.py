"""Device materializer store — the TPU-resident versioned key store.

The reference keeps, per partition, an ETS op ring + a cache of
materialized snapshots per key, GC'd by thresholds (reference
src/materializer_vnode.erl:36-47, 511-647; ring layout doc
include/antidote.hrl:81-90).  The TPU redesign collapses that to:

- a dense **op ring** ``[K, L]`` per shard (padded, cursor per key), and
- a single **base snapshot per key anchored at the GST**: because the
  batched kernels can materialize at *any* read VC >= base in one call,
  one base snapshot replaces the reference's per-key snapshot list.
  Reads below the GST fall back to log replay, exactly like the
  reference's snapshot-cache miss (src/materializer_vnode.erl:415-419).

The GC step is the reference's op_insert_gc turned into a batched fold:
every op whose commit VC has become stable (<= GST) is folded into the
base (an associative lattice join — see mat/kernels.py) and the ring is
compacted in-place with a cumsum scatter.  No per-key control flow; one
fused XLA program covers the whole shard.

Shapes: K keys, L ring lanes, E element slots, D dc columns.  Appends
whose key ring is full are reported back (overflow) so the control plane
can trigger a GC or spill to the log; reads of overflowed keys stay
correct via log replay.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu.clocks import dense
from antidote_tpu.mat import kernels


@dataclass
class OrsetShardState:
    """Device arrays for one OR-Set shard (a pytree)."""

    dots: jax.Array      # int[K, E, D] base snapshot (live dot table)
    base_vc: jax.Array   # int[D] snapshot time of the base (shard-wide GST)
    has_base: jax.Array  # bool[] whether base_vc is meaningful
    # --- op ring, [K, L] unless noted ---
    count: jax.Array     # int32[K] live ops per key
    elem_slot: jax.Array  # int32
    is_add: jax.Array    # bool
    dot_dc: jax.Array    # int32
    dot_seq: jax.Array   # int
    obs_vv: jax.Array    # int[K, L, D]
    op_dc: jax.Array     # int32
    op_ct: jax.Array     # int
    op_ss: jax.Array     # int[K, L, D]
    valid: jax.Array     # bool


jax.tree_util.register_dataclass(
    OrsetShardState,
    data_fields=[
        "dots", "base_vc", "has_base", "count", "elem_slot", "is_add",
        "dot_dc", "dot_seq", "obs_vv", "op_dc", "op_ct", "op_ss", "valid",
    ],
    meta_fields=[],
)


def orset_shard_init(n_keys: int, n_lanes: int, n_slots: int, n_dcs: int,
                     dtype=jnp.int32) -> OrsetShardState:
    K, L, E, D = n_keys, n_lanes, n_slots, n_dcs
    z = partial(jnp.zeros, dtype=dtype)
    return OrsetShardState(
        dots=z((K, E, D)),
        base_vc=z((D,)),
        has_base=jnp.zeros((), dtype=bool),
        count=jnp.zeros((K,), dtype=jnp.int32),
        elem_slot=jnp.full((K, L), E, dtype=jnp.int32),
        is_add=jnp.zeros((K, L), dtype=bool),
        dot_dc=jnp.zeros((K, L), dtype=jnp.int32),
        dot_seq=z((K, L)),
        obs_vv=z((K, L, D)),
        op_dc=jnp.zeros((K, L), dtype=jnp.int32),
        op_ct=z((K, L)),
        op_ss=z((K, L, D)),
        valid=jnp.zeros((K, L), dtype=bool),
    )


def _ring_append(count, valid, key_idx, lane_off, fields: dict):
    """Shared ring scatter: place B ops at (key, count[key]+lane_off).

    ``fields``: name -> (ring_array, batch_values).  Returns
    (new_count, new_valid, new_fields, overflow[B]); overflowed ops are
    NOT stored — the caller must GC or serve those keys from the log."""
    L = valid.shape[1]
    lane = count[key_idx] + lane_off
    overflow = lane >= L
    lane = jnp.where(overflow, L, lane)  # L = out of range -> dropped
    new_count = count.at[key_idx].add(
        jnp.where(overflow, 0, 1).astype(count.dtype), mode="drop")
    new_valid = valid.at[key_idx, lane].set(
        jnp.ones_like(overflow), mode="drop")
    new_fields = {
        name: a.at[key_idx, lane].set(v, mode="drop")
        for name, (a, v) in fields.items()
    }
    return new_count, new_valid, new_fields, overflow


def _ring_compact(keep, fields: dict):
    """Shared ring compaction: move kept ops to the lane prefix.

    ``fields``: name -> (ring_array, fill_value).  Returns
    (new_count, new_valid, new_fields)."""
    L = keep.shape[1]
    new_pos = jnp.where(keep, jnp.cumsum(keep, axis=1) - 1, L)  # L -> drop
    k_idx = jnp.broadcast_to(jnp.arange(keep.shape[0])[:, None], keep.shape)

    def compact(a, fill):
        out = jnp.full_like(a, fill)
        return out.at[k_idx, new_pos].set(a, mode="drop")

    new_valid = compact(keep, False)
    new_count = jnp.sum(keep, axis=1, dtype=jnp.int32)
    new_fields = {name: compact(a, fill) for name, (a, fill) in fields.items()}
    return new_count, new_valid, new_fields


@jax.jit
def orset_append(
    st: OrsetShardState,
    key_idx: jax.Array,   # int32[B]
    lane_off: jax.Array,  # int32[B] occurrence index of the key within batch
    elem_slot: jax.Array, is_add: jax.Array,
    dot_dc: jax.Array, dot_seq: jax.Array, obs_vv: jax.Array,
    op_dc: jax.Array, op_ct: jax.Array, op_ss: jax.Array,
) -> Tuple[OrsetShardState, jax.Array]:
    """Scatter a batch of B committed ops into the rings (see _ring_append
    for the overflow contract)."""
    count, valid, f, overflow = _ring_append(
        st.count, st.valid, key_idx, lane_off, {
            "elem_slot": (st.elem_slot, elem_slot),
            "is_add": (st.is_add, is_add),
            "dot_dc": (st.dot_dc, dot_dc),
            "dot_seq": (st.dot_seq, dot_seq),
            "obs_vv": (st.obs_vv, obs_vv),
            "op_dc": (st.op_dc, op_dc),
            "op_ct": (st.op_ct, op_ct),
            "op_ss": (st.op_ss, op_ss),
        })
    return replace(st, count=count, valid=valid, **f), overflow


@jax.jit
def orset_gc(st: OrsetShardState, gst: jax.Array) -> OrsetShardState:
    """Fold every ring op with commit VC <= GST into the base snapshot
    and compact the rings (the batched op_insert_gc/snapshot_insert_gc,
    reference src/materializer_vnode.erl:511-647).

    Safe because the GST is a *stable* time: no op with commit VC <= GST
    can still be in flight (reference dc_utilities:get_stable_snapshot
    contract), so folding is permanent and base_vc := max(base_vc, gst)."""
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)      # [K, L, D]
    stable = st.valid & dense.le(cvc, gst[None, None, :])
    dots = kernels.orset_apply(
        st.dots, st.elem_slot, st.is_add, st.dot_dc, st.dot_seq,
        st.obs_vv, stable,
    )
    keep = st.valid & ~stable
    E = st.dots.shape[1]
    count, valid, f = _ring_compact(keep, {
        "elem_slot": (st.elem_slot, E),
        "is_add": (st.is_add, False),
        "dot_dc": (st.dot_dc, 0),
        "dot_seq": (st.dot_seq, 0),
        "obs_vv": (st.obs_vv, 0),
        "op_dc": (st.op_dc, 0),
        "op_ct": (st.op_ct, 0),
        "op_ss": (st.op_ss, 0),
    })
    return replace(
        st,
        dots=dots,
        base_vc=jnp.maximum(st.base_vc, gst),
        has_base=jnp.ones((), dtype=bool),
        count=count,
        valid=valid,
        **f,
    )


@jax.jit
def orset_read(st: OrsetShardState, read_vc: jax.Array) -> jax.Array:
    """bool[K, E]: element presence for every key at ``read_vc`` in one
    batched materialization (base + included ring ops).

    Requires read_vc >= base_vc (reads under the base fall back to log
    replay at the control plane, as in the reference's cache miss)."""
    K = st.valid.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid, base_vc, has_base, read_vc)
    dots = kernels.orset_apply(
        st.dots, st.elem_slot, st.is_add, st.dot_dc, st.dot_seq,
        st.obs_vv, mask)
    return kernels.orset_present(dots)


# ---------------------------------------------------------------------------
# counter_pn shard — same ring machinery, scalar state


@dataclass
class CounterShardState:
    value: jax.Array     # int[K] base values
    base_vc: jax.Array   # int[D]
    has_base: jax.Array  # bool[]
    count: jax.Array     # int32[K]
    delta: jax.Array     # int[K, L]
    op_dc: jax.Array     # int32[K, L]
    op_ct: jax.Array     # int[K, L]
    op_ss: jax.Array     # int[K, L, D]
    valid: jax.Array     # bool[K, L]


jax.tree_util.register_dataclass(
    CounterShardState,
    data_fields=["value", "base_vc", "has_base", "count", "delta",
                 "op_dc", "op_ct", "op_ss", "valid"],
    meta_fields=[],
)


def counter_shard_init(n_keys: int, n_lanes: int, n_dcs: int,
                       dtype=jnp.int32) -> CounterShardState:
    K, L, D = n_keys, n_lanes, n_dcs
    z = partial(jnp.zeros, dtype=dtype)
    return CounterShardState(
        value=z((K,)),
        base_vc=z((D,)),
        has_base=jnp.zeros((), dtype=bool),
        count=jnp.zeros((K,), dtype=jnp.int32),
        delta=z((K, L)),
        op_dc=jnp.zeros((K, L), dtype=jnp.int32),
        op_ct=z((K, L)),
        op_ss=z((K, L, D)),
        valid=jnp.zeros((K, L), dtype=bool),
    )


@jax.jit
def counter_append(st: CounterShardState, key_idx, lane_off, delta,
                   op_dc, op_ct, op_ss):
    count, valid, f, overflow = _ring_append(
        st.count, st.valid, key_idx, lane_off, {
            "delta": (st.delta, delta),
            "op_dc": (st.op_dc, op_dc),
            "op_ct": (st.op_ct, op_ct),
            "op_ss": (st.op_ss, op_ss),
        })
    return replace(st, count=count, valid=valid, **f), overflow


@jax.jit
def counter_gc(st: CounterShardState, gst: jax.Array) -> CounterShardState:
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
    stable = st.valid & dense.le(cvc, gst[None, None, :])
    value = kernels.counter_read(st.value, st.delta, stable)
    keep = st.valid & ~stable
    count, valid, f = _ring_compact(keep, {
        "delta": (st.delta, 0),
        "op_dc": (st.op_dc, 0),
        "op_ct": (st.op_ct, 0),
        "op_ss": (st.op_ss, 0),
    })
    return replace(
        st,
        value=value,
        base_vc=jnp.maximum(st.base_vc, gst),
        has_base=jnp.ones((), dtype=bool),
        count=count,
        valid=valid,
        **f,
    )


@jax.jit
def counter_read(st: CounterShardState, read_vc: jax.Array) -> jax.Array:
    """int[K]: counter values at ``read_vc``."""
    K = st.valid.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid, base_vc, has_base, read_vc)
    return kernels.counter_read(st.value, st.delta, mask)


def batch_lane_offsets(key_idx: np.ndarray) -> np.ndarray:
    """Host helper: occurrence index of each key within the batch (0,1,...)
    in batch order — disambiguates same-key ops in one append."""
    out = np.zeros(len(key_idx), dtype=np.int32)
    seen: dict = {}
    for i, k in enumerate(key_idx):
        k = int(k)
        out[i] = seen.get(k, 0)
        seen[k] = out[i] + 1
    return out

"""Device materializer store — the TPU-resident versioned key store.

The reference keeps, per partition, an ETS op ring + a cache of
materialized snapshots per key, GC'd by thresholds (reference
src/materializer_vnode.erl:36-47, 511-647; ring layout doc
include/antidote.hrl:81-90).  The TPU redesign collapses that to:

- a dense **op ring** of L lanes per key (padded, free-slot bitmap), and
- a single **base snapshot per key anchored at the GST**: because the
  batched kernels can materialize at *any* read VC >= base in one call,
  one base snapshot replaces the reference's per-key snapshot list.
  Reads below the GST fall back to log replay, exactly like the
  reference's snapshot-cache miss (src/materializer_vnode.erl:415-419).

TPU-shaped storage decisions (each measured on v5e, 1M keys x 8 lanes):
- Every per-op field lives in ONE row-major ``ops[K*L, F]`` tensor
  (row = one ring slot): an append is a single flat row scatter
  (~13 ms for a 64k-op batch).  Per-field tensors cost a scatter per
  field (~108 ms total) and [K, L, ...]-shaped scatter targets are ~8x
  slower than flat row indices (XLA lowers multi-dim scatters badly).
- Readers get [K, L(, D)] *views* from per-column slices (the
  properties); the reshape fuses into the consuming fold.  A
  materialized [K*L, F] <-> [K, L, F] relayout costs ~19-30 ms — never
  round-trip the layouts.
- GC does NOT compact lanes.  Folded lanes are simply marked free
  (``valid &= ~stable`` — elementwise, fused) and appends place ops in
  free lanes by rank (a [B, L] cumsum over gathered bitmap rows).
  Lane order carries no meaning: materialization is an associative,
  commutative lattice fold (mat/kernels.py), so fragmentation is free.
  The reference compacts because its ring is a sequential Erlang tuple
  walked oldest-first (include/antidote.hrl:81-90); a batched fold has
  no such need — compaction cost 1.6 s/step in scatter form.
- GC is amortized: callers fold every G steps (the reference GCs per
  key every ``?OPS_THRESHOLD`` = 50 ops, src/materializer_vnode.erl:46
  — also amortized), sizing L to cover G batches of expected per-key
  arrivals.

Shapes: K keys, L ring lanes, E element slots, D dc columns.  Appends
whose key ring is full are reported back (overflow) so the control plane
can trigger a GC or spill to the log; reads of overflowed keys stay
correct via log replay.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu.clocks import dense
from antidote_tpu.mat import kernels
from antidote_tpu.obs.prof import kernel_span

# packed op-tensor columns (OR-Set): scalars, then obs VV, then op SS
_ELEM, _ISADD, _DOTDC, _DOTSEQ, _OPDC, _OPCT, _NSCAL = 0, 1, 2, 3, 4, 5, 6


def _gather_key_rows(st, key_idx: jax.Array, read_vc: jax.Array,
                     dc_col: int, ct_col: int, ss_off: int):
    """Shared transaction-read gather: the B requested keys' ring rows
    plus their Clock-SI inclusion mask at ``read_vc``.  Returns
    (ops[B, L, F], mask[B, L]).  Every per-type ``*_read_keys`` is this
    gather + that type's fold over its own columns."""
    L = st.n_lanes
    d = st._d
    flat = key_idx[:, None] * L + jnp.arange(L, dtype=key_idx.dtype)
    ops = st.ops[flat]                                   # [B, L, F]
    valid = st.valid[flat]                               # [B, L]
    B = key_idx.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (B, d))
    has_base = jnp.broadcast_to(st.has_base, (B,))
    mask = kernels.inclusion_mask(
        ops[..., dc_col], ops[..., ct_col], ops[..., ss_off:ss_off + d],
        valid, base_vc, has_base, read_vc)
    return ops, mask


def _free_lanes(valid2d: jax.Array, key_idx: jax.Array,
                lane_off: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Lane for each batch op = its (lane_off+1)-th free slot; lane == L
    signals overflow.  ``valid2d``: bool[K, L]; key_idx/lane_off: int[B]."""
    L = valid2d.shape[1]
    rows = valid2d[key_idx]                            # [B, L] gather
    free = ~rows
    rank = jnp.cumsum(free, axis=1) - 1                # rank among free
    hot = free & (rank == lane_off[:, None])
    lane = jnp.where(jnp.any(hot, axis=1), jnp.argmax(hot, axis=1), L)
    return lane.astype(jnp.int32), lane >= L


def _scatter_rows(st, key_idx: jax.Array, lane_off: jax.Array,
                  rows: jax.Array, active: jax.Array | None = None):
    """Shared append epilogue: place each packed row in its key's next
    free ring lane and mark it live.  ``active`` (bool[B], optional)
    drops masked-off ops entirely — no scatter, no overflow — the
    sharded stores' this-chip's-keys filter.  Returns (state,
    overflow[B]); overflowed ops are NOT stored."""
    L = st.n_lanes
    lane, overflow = _free_lanes(st.valid2d, key_idx, lane_off)
    if active is not None:
        overflow = overflow & active
    drop = (lane >= L) if active is None else ((lane >= L) | ~active)
    flat = jnp.where(drop, st.ops.shape[0], key_idx * L + lane)
    ops = st.ops.at[flat].set(rows, mode="drop")
    valid = st.valid.at[flat].set(True, mode="drop")
    return replace(st, ops=ops, valid=valid), overflow


@dataclass
class OrsetShardState:
    """Device arrays for one OR-Set shard (a pytree).

    ``ops[K*L, 6+2D]`` packs per-op fields column-wise:
    [elem_slot, is_add, dot_dc, dot_seq, op_dc, op_ct,
     obs_vv(D), op_ss(D)]; [K, L]-shaped views come from the
    properties.  ``n_lanes`` is static metadata."""

    dots: jax.Array      # int[K, E, D] base snapshot (live dot table)
    base_vc: jax.Array   # int[D] snapshot time of the base (shard GST)
    has_base: jax.Array  # bool[] whether base_vc is meaningful
    ops: jax.Array       # int[K*L, 6+2D] packed op ring (flat rows)
    valid: jax.Array     # bool[K*L] lane occupancy
    n_lanes: int

    @property
    def _d(self) -> int:
        return (self.ops.shape[-1] - _NSCAL) // 2

    def _col(self, c) -> jax.Array:
        return self.ops[:, c].reshape(-1, self.n_lanes)

    @property
    def valid2d(self) -> jax.Array:
        return self.valid.reshape(-1, self.n_lanes)

    @property
    def count(self) -> jax.Array:
        """int32[K]: live ops per key (derived from the bitmap)."""
        return jnp.sum(self.valid2d, axis=1, dtype=jnp.int32)

    @property
    def elem_slot(self):
        return self._col(_ELEM)

    @property
    def is_add(self):
        return self._col(_ISADD) != 0

    @property
    def dot_dc(self):
        return self._col(_DOTDC)

    @property
    def dot_seq(self):
        return self._col(_DOTSEQ)

    @property
    def op_dc(self):
        return self._col(_OPDC)

    @property
    def op_ct(self):
        return self._col(_OPCT)

    @property
    def obs_vv(self):
        d = self._d
        return self.ops[:, _NSCAL:_NSCAL + d].reshape(
            -1, self.n_lanes, d)

    @property
    def op_ss(self):
        d = self._d
        return self.ops[:, _NSCAL + d:].reshape(-1, self.n_lanes, d)


jax.tree_util.register_dataclass(
    OrsetShardState,
    data_fields=["dots", "base_vc", "has_base", "ops", "valid"],
    meta_fields=["n_lanes"],
)


def orset_shard_init(n_keys: int, n_lanes: int, n_slots: int, n_dcs: int,
                     dtype=jnp.int64) -> OrsetShardState:
    K, L, E, D = n_keys, n_lanes, n_slots, n_dcs
    ops = jnp.zeros((K * L, _NSCAL + 2 * D), dtype=dtype)
    ops = ops.at[:, _ELEM].set(E)  # empty lanes route to the drop slot
    return OrsetShardState(
        dots=jnp.zeros((K, E, D), dtype=dtype),
        base_vc=jnp.zeros((D,), dtype=dtype),
        has_base=jnp.zeros((), dtype=bool),
        ops=ops,
        valid=jnp.zeros((K * L,), dtype=bool),
        n_lanes=L,
    )


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def orset_append(
    st: OrsetShardState,
    key_idx: jax.Array,   # int32[B]
    lane_off: jax.Array,  # int32[B] occurrence index of the key in batch
    elem_slot: jax.Array, is_add: jax.Array,
    dot_dc: jax.Array, dot_seq: jax.Array, obs_vv: jax.Array,
    op_dc: jax.Array, op_ct: jax.Array, op_ss: jax.Array,
    active: jax.Array | None = None,
) -> Tuple[OrsetShardState, jax.Array]:
    """Scatter a batch of B committed ops into free ring lanes.  Returns
    (state, overflow[B]); overflowed ops are NOT stored — the caller
    must GC and retry or serve those keys from the log.

    ``active`` (bool[B], optional) drops masked-off ops entirely (no
    scatter, no overflow) — the sharded store's this-chip's-keys filter
    (antidote_tpu/mat/sharded.py)."""
    dt = st.ops.dtype
    col = lambda a: a.astype(dt)[:, None]
    rows = jnp.concatenate([
        col(elem_slot), col(is_add), col(dot_dc), col(dot_seq),
        col(op_dc), col(op_ct), obs_vv.astype(dt), op_ss.astype(dt),
    ], axis=1)                                          # [B, 6+2D]
    return _scatter_rows(st, key_idx, lane_off, rows, active)


def _orset_gc_impl(st: OrsetShardState, gst: jax.Array) -> OrsetShardState:
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)      # [K, L, D]
    stable = st.valid2d & dense.le(cvc, gst[None, None, :])
    dots = kernels.orset_apply(
        st.dots, st.elem_slot, st.is_add, st.dot_dc, st.dot_seq,
        st.obs_vv, stable,
    )
    return replace(
        st,
        dots=dots,
        base_vc=jnp.maximum(st.base_vc, gst.astype(st.base_vc.dtype)),
        has_base=jnp.ones((), dtype=bool),
        valid=st.valid & ~stable.reshape(-1),
    )


#: the same fold WITHOUT donation — orset_gc_full's jnp path, so its
#: flag-independent contract ("st stays valid") holds on every path
_orset_gc_nodonate = kernel_span("mat.store", name="orset_gc_nodonate")(
    jax.jit(_orset_gc_impl))


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def orset_gc(st: OrsetShardState, gst: jax.Array) -> OrsetShardState:
    """Fold every ring op with commit VC <= GST into the base snapshot
    and free its lane (the batched op_insert_gc/snapshot_insert_gc,
    reference src/materializer_vnode.erl:511-647).

    Safe because the GST is a *stable* time: no op with commit VC <= GST
    can still be in flight (reference dc_utilities:get_stable_snapshot
    contract), so folding is permanent and base_vc := max(base_vc, gst).
    Lanes are freed, not compacted (see module doc).

    DONATES ``st``'s buffers (the live planes' steady-state GC aliases
    the multi-hundred-MB ops tensor in place); callers that must keep
    ``st`` use :func:`orset_gc_full`, whose paths all preserve it."""
    return _orset_gc_impl(st, gst)


@kernel_span("mat.store")
@jax.jit
def orset_read(st: OrsetShardState, read_vc: jax.Array) -> jax.Array:
    """bool[K, E]: element presence for every key at ``read_vc`` in one
    batched materialization (base + included ring ops).

    Requires read_vc >= base_vc (reads under the base fall back to log
    replay at the control plane, as in the reference's cache miss)."""
    K = st.dots.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid2d, base_vc, has_base,
        read_vc)
    dots = kernels.orset_apply(
        st.dots, st.elem_slot, st.is_add, st.dot_dc, st.dot_seq,
        st.obs_vv, mask)
    return kernels.orset_present(dots)


def orset_read_full(st: OrsetShardState, read_vc: jax.Array,
                    fused: str | bool = "auto",
                    block_k: int | None = None) -> jax.Array:
    """bool[K, E]: full-shard presence read, flag-selecting the Pallas
    fused kernel (antidote_tpu/mat/pallas_kernels.py orset_read_packed —
    one HBM pass over the packed rows, nothing but the presence block
    leaves VMEM) over the jnp reference path (:func:`orset_read`).

    ``fused``: True / False / "auto" / "hybrid" (fused on a TPU backend
    when the shard's timestamps fit int32 — the Pallas path computes in
    int32, so µs-int64 live shards must use the jnp path; "hybrid" runs
    the inclusion mask in XLA and only the fold in Pallas).
    """
    if fused == "auto":
        fused = jax.default_backend() == "tpu"
    # the Pallas fold computes in int32; µs-int64 shards would truncate
    # their timestamps, so even an explicit fused request falls back
    if not fused or st.ops.dtype != jnp.int32:
        return orset_read(st, read_vc)
    from antidote_tpu.mat import pallas_kernels

    K = st.dots.shape[0]
    interpret = jax.default_backend() != "tpu"
    args = (st.dots, st.ops, st.valid, st.base_vc, st.has_base,
            read_vc.astype(st.ops.dtype))
    if fused == "hybrid":
        fn = pallas_kernels.orset_read_hybrid
        if block_k is not None:
            return fn(*args, block_k=min(block_k, K),
                      interpret=interpret)
        return _probe_block_k(
            fn, args,
            ("hybrid", jax.default_backend(), st.dots.shape,
             st.ops.shape),
            K, interpret)
    return pallas_kernels.orset_read_packed(
        *args, block_k=min(block_k or 256, K), interpret=interpret)


#: (variant, backend, shapes) -> largest block_k that compiled there
_BLOCK_K_CACHE: dict = {}


def _probe_block_k(fn, args, cache_key, K, interpret,
                   ladder=(512, 256, 128)):
    """Call ``fn(*args, block_k=..)`` with the largest block size this
    chip's scoped-VMEM budget accepts, probing the descending ladder
    once per ``cache_key`` (budgets differ per TPU generation —
    measured on v5 lite: block_k=512 requests 26.18M against the
    16.00M limit).  Pallas/Mosaic raises the VMEM overflow
    synchronously at the dispatching call, so the probe needs no
    execution round-trip."""
    bk = _BLOCK_K_CACHE.get(cache_key)
    if bk is not None:
        return fn(*args, block_k=min(bk, K), interpret=interpret)
    last = None
    for bk in ladder:
        try:
            out = fn(*args, block_k=min(bk, K), interpret=interpret)
        except Exception as e:  # noqa: BLE001 — inspect + reraise
            if "vmem" not in str(e).lower():
                raise
            last = e
            continue
        _BLOCK_K_CACHE[cache_key] = bk
        return out
    raise last


def orset_gc_full(st: OrsetShardState, gst: jax.Array,
                  fused: str | bool = "auto",
                  block_k: int | None = None) -> OrsetShardState:
    """:func:`orset_gc` flag-selecting the fused Pallas fold
    (pallas_kernels.orset_gc_packed — one HBM pass over the packed rows;
    the jnp path's [K, L, D] commit-VC tensor and one-hot select
    intermediates cost ~10x the pass's bandwidth floor, measured 34 ms
    vs a ~4 ms floor per GC at 1M keys on the round-5 bench chip).

    Same ``fused`` contract as :func:`orset_read_full`, EXCEPT "auto"
    resolves to the jnp path: measured on the round-5 bench chip the
    fused fold is SLOWER (58.8 ms vs 24.5 ms at 1M keys — XLA already
    fuses the GC chain well, and the kernel's unrolled one-hot fold is
    VPU-bound), unlike the read where the Pallas kernel wins 2.4x.
    Kept for explicit fused=True use on TPU generations with more
    VMEM/VPU headroom; the kernel is equality-tested against orset_gc
    (tests/unit/test_pallas_kernels.py).

    Unlike :func:`orset_gc`, ``st`` is NOT consumed on ANY path: the
    jnp fallback runs the non-donating jit and the fused path never
    donated — uniform semantics regardless of the flag (the previous
    flag-dependent donation was a use-after-donate hazard: caller code
    touching st afterwards worked under fused=True and crashed — or
    silently read donated buffers — under the default)."""
    if fused == "auto":
        fused = False
    if not fused or st.ops.dtype != jnp.int32:
        return _orset_gc_nodonate(st, gst)
    from antidote_tpu.mat import pallas_kernels

    K = st.dots.shape[0]
    interpret = jax.default_backend() != "tpu"
    args = (st.dots, st.ops, st.valid, gst.astype(st.ops.dtype))
    fn = pallas_kernels.orset_gc_packed
    if block_k is not None:
        ndots, nvalid = fn(*args, block_k=min(block_k, K),
                           interpret=interpret)
    else:
        ndots, nvalid = _probe_block_k(
            fn, args,
            ("gc", jax.default_backend(), st.dots.shape, st.ops.shape),
            K, interpret)
    return replace(
        st,
        dots=ndots.astype(st.dots.dtype),
        base_vc=jnp.maximum(st.base_vc, gst.astype(st.base_vc.dtype)),
        has_base=jnp.ones((), dtype=bool),
        valid=nvalid,
    )


@kernel_span("mat.store")
@jax.jit
def orset_read_keys(st: OrsetShardState, key_idx: jax.Array,
                    read_vc: jax.Array) -> jax.Array:
    """int[B, E, D]: folded live-dot tables for just the requested keys
    at ``read_vc`` — the transaction read path (B small), vs
    :func:`orset_read` which folds the whole shard.

    Gathers the B keys' ring rows ([B, L, F]) and base rows, then runs
    the same inclusion-mask + lattice fold as the full-shard read.
    Requires read_vc >= base_vc (callers fall back to log replay below
    the base, the reference's snapshot-cache miss)."""
    d = st._d
    ops, mask = _gather_key_rows(st, key_idx, read_vc,
                                 _OPDC, _OPCT, _NSCAL + d)
    return kernels.orset_apply(
        st.dots[key_idx], ops[..., _ELEM], ops[..., _ISADD] != 0,
        ops[..., _DOTDC], ops[..., _DOTSEQ], ops[..., _NSCAL:_NSCAL + d],
        mask)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def orset_purge_keys(st: OrsetShardState,
                     key_idx: jax.Array) -> OrsetShardState:
    """Free every ring lane and zero the base rows of the given keys —
    used when a key is evicted to the host path (element-slot or lane
    overflow); its history is then served by log replay.  Out-of-range
    indices (padding) are dropped."""
    L = st.n_lanes
    flat = (key_idx[:, None] * L
            + jnp.arange(L, dtype=key_idx.dtype)).reshape(-1)
    return replace(
        st,
        valid=st.valid.at[flat].set(False, mode="drop"),
        dots=st.dots.at[key_idx].set(0, mode="drop"),
    )


def orset_grow(st: OrsetShardState, n_keys: int | None = None,
               n_slots: int | None = None,
               n_dcs: int | None = None) -> OrsetShardState:
    """Host-side capacity regrade: widen keys / element slots / DC
    columns (never shrink).  One host repack + re-upload; rare (called
    when a directory fills), so simplicity over speed."""
    K, E, D = st.dots.shape
    L = st.n_lanes
    nk, ne, nd = (n_keys or K), (n_slots or E), (n_dcs or D)
    if (nk, ne, nd) == (K, E, D):
        return st
    ops = np.asarray(st.ops).reshape(K, L, -1)
    scal = ops[..., :_NSCAL]
    obs = ops[..., _NSCAL:_NSCAL + D]
    ss = ops[..., _NSCAL + D:]
    padD = ((0, 0), (0, 0), (0, nd - D))
    ops = np.concatenate(
        [scal, np.pad(obs, padD), np.pad(ss, padD)], axis=-1)
    if nk > K:
        # invalid-lane sentinel values don't matter (folds mask by
        # `valid`), so zero rows are fine
        ops = np.pad(ops, ((0, nk - K), (0, 0), (0, 0)))
    valid = np.pad(np.asarray(st.valid).reshape(K, L), ((0, nk - K), (0, 0)))
    dots = np.pad(np.asarray(st.dots),
                  ((0, nk - K), (0, ne - E), (0, nd - D)))
    return OrsetShardState(
        dots=jnp.asarray(dots),
        base_vc=jnp.asarray(np.pad(np.asarray(st.base_vc), (0, nd - D))),
        has_base=st.has_base,
        ops=jnp.asarray(ops.reshape(nk * L, -1)),
        valid=jnp.asarray(valid.reshape(-1)),
        n_lanes=L,
    )


# ---------------------------------------------------------------------------
# register_mv shard — the OR-Set ring layout with a cross-slot fold
#
# An MV-register is structurally an OR-Set over *value slots*: an assign
# mints a dot for its value and cancels the dots it observed, concurrent
# assigns keep multiple live slots (reference antidote_crdt_register_mv
# semantics, crdt/registers.py host oracle).  The one difference is the
# cancellation scope: an assign's observed VV kills dots in EVERY slot
# (it observed the whole register), not just its own slot — which is
# exactly kernels.mvreg_apply vs kernels.orset_apply.  The ring layout,
# append, purge, and grow are therefore shared with the OR-Set
# (OrsetShardState; a reset is a row with val_slot=E, dot_seq=0 — it
# contributes only its observed VV).


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def mvreg_gc(st: OrsetShardState, gst: jax.Array) -> OrsetShardState:
    """Fold stable assigns into the base dot table (same stability
    contract as orset_gc)."""
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
    stable = st.valid2d & dense.le(cvc, gst[None, None, :])
    dots = kernels.mvreg_apply(
        st.dots, st.elem_slot, st.dot_dc, st.dot_seq, st.obs_vv, stable)
    return replace(
        st,
        dots=dots,
        base_vc=jnp.maximum(st.base_vc, gst.astype(st.base_vc.dtype)),
        has_base=jnp.ones((), dtype=bool),
        valid=st.valid & ~stable.reshape(-1),
    )


@kernel_span("mat.store")
@jax.jit
def mvreg_read(st: OrsetShardState, read_vc: jax.Array) -> jax.Array:
    """int[K, E, D]: live value-slot dot tables at ``read_vc``."""
    K = st.dots.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid2d, base_vc, has_base,
        read_vc)
    return kernels.mvreg_apply(
        st.dots, st.elem_slot, st.dot_dc, st.dot_seq, st.obs_vv, mask)


@kernel_span("mat.store")
@jax.jit
def mvreg_read_keys(st: OrsetShardState, key_idx: jax.Array,
                    read_vc: jax.Array) -> jax.Array:
    """int[B, E, D]: live dot tables for just the requested keys (the
    transaction read path; see orset_read_keys)."""
    d = st._d
    ops, mask = _gather_key_rows(st, key_idx, read_vc,
                                 _OPDC, _OPCT, _NSCAL + d)
    return kernels.mvreg_apply(
        st.dots[key_idx], ops[..., _ELEM], ops[..., _DOTDC],
        ops[..., _DOTSEQ], ops[..., _NSCAL:_NSCAL + d], mask)


# ---------------------------------------------------------------------------
# register_lww shard — packed ring over (ts, tiebreak, value-id) rows
#
# Last-writer-wins needs no dot algebra: the fold is a lexicographic max
# over (ts, tie) among the base and every included op
# (kernels.lww_read), which is commutative/idempotent, so GC folding and
# ring fragmentation are free exactly as for the OR-Set.  The tiebreak
# is a host-packed int64 (actor rank << seq bits | seq; the device plane
# owns the rank directory and repacks on actor arrival) so the device
# compare matches the host oracle's (ts, (actor, seq)) order
# (crdt/registers.py RegisterLWW.update).

# packed columns (lww): [ts, tie, val, op_dc, op_ct, op_ss(D)]
_LTS, _LTIE, _LVAL, _LOPDC, _LOPCT, _LNSCAL = 0, 1, 2, 3, 4, 5


@dataclass
class LwwShardState:
    """``ops[K*L, 5+D]`` packs [ts, tie, val, op_dc, op_ct, op_ss(D)];
    base value id -1 = unwritten (host maps to the empty register)."""

    base_ts: jax.Array   # int[K]
    base_tie: jax.Array  # int[K]
    base_val: jax.Array  # int[K] interned value ids (-1 = none)
    base_vc: jax.Array   # int[D]
    has_base: jax.Array  # bool[]
    ops: jax.Array       # int[K*L, 5+D]
    valid: jax.Array     # bool[K*L]
    n_lanes: int

    @property
    def _d(self) -> int:
        return self.ops.shape[-1] - _LNSCAL

    def _col(self, c) -> jax.Array:
        return self.ops[:, c].reshape(-1, self.n_lanes)

    @property
    def valid2d(self) -> jax.Array:
        return self.valid.reshape(-1, self.n_lanes)

    @property
    def op_ts(self):
        return self._col(_LTS)

    @property
    def op_tie(self):
        return self._col(_LTIE)

    @property
    def op_val(self):
        return self._col(_LVAL)

    @property
    def op_dc(self):
        return self._col(_LOPDC)

    @property
    def op_ct(self):
        return self._col(_LOPCT)

    @property
    def op_ss(self):
        return self.ops[:, _LNSCAL:].reshape(-1, self.n_lanes, self._d)


jax.tree_util.register_dataclass(
    LwwShardState,
    data_fields=["base_ts", "base_tie", "base_val", "base_vc",
                 "has_base", "ops", "valid"],
    meta_fields=["n_lanes"],
)


def lww_shard_init(n_keys: int, n_lanes: int, n_dcs: int,
                   dtype=jnp.int64) -> LwwShardState:
    K, L, D = n_keys, n_lanes, n_dcs
    return LwwShardState(
        base_ts=jnp.zeros((K,), dtype=dtype),
        base_tie=jnp.zeros((K,), dtype=dtype),
        base_val=jnp.full((K,), -1, dtype=dtype),
        base_vc=jnp.zeros((D,), dtype=dtype),
        has_base=jnp.zeros((), dtype=bool),
        ops=jnp.zeros((K * L, _LNSCAL + D), dtype=dtype),
        valid=jnp.zeros((K * L,), dtype=bool),
        n_lanes=L,
    )


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def lww_append(st: LwwShardState, key_idx, lane_off, ts, tie, val,
               op_dc, op_ct, op_ss, active: jax.Array | None = None):
    dt = st.ops.dtype
    col = lambda a: a.astype(dt)[:, None]
    rows = jnp.concatenate(
        [col(ts), col(tie), col(val), col(op_dc), col(op_ct),
         op_ss.astype(dt)], axis=1)
    return _scatter_rows(st, key_idx, lane_off, rows, active)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def lww_gc(st: LwwShardState, gst: jax.Array) -> LwwShardState:
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
    stable = st.valid2d & dense.le(cvc, gst[None, None, :])
    bts, btie, bval = kernels.lww_read(
        st.base_ts, st.base_tie, st.base_val,
        st.op_ts, st.op_tie, st.op_val, stable)
    return replace(
        st,
        base_ts=bts, base_tie=btie, base_val=bval,
        base_vc=jnp.maximum(st.base_vc, gst.astype(st.base_vc.dtype)),
        has_base=jnp.ones((), dtype=bool),
        valid=st.valid & ~stable.reshape(-1),
    )


@kernel_span("mat.store")
@jax.jit
def lww_read(st: LwwShardState, read_vc: jax.Array):
    """(ts, tie, val)[K] at ``read_vc``."""
    K = st.base_ts.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid2d, base_vc, has_base,
        read_vc)
    return kernels.lww_read(
        st.base_ts, st.base_tie, st.base_val,
        st.op_ts, st.op_tie, st.op_val, mask)


@kernel_span("mat.store")
@jax.jit
def lww_read_keys(st: LwwShardState, key_idx: jax.Array,
                  read_vc: jax.Array):
    """(ts, tie, val)[B] for just the requested keys."""
    ops, mask = _gather_key_rows(st, key_idx, read_vc,
                                 _LOPDC, _LOPCT, _LNSCAL)
    return kernels.lww_read(
        st.base_ts[key_idx], st.base_tie[key_idx], st.base_val[key_idx],
        ops[..., _LTS], ops[..., _LTIE], ops[..., _LVAL], mask)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def lww_purge_keys(st: LwwShardState, key_idx: jax.Array) -> LwwShardState:
    L = st.n_lanes
    flat = (key_idx[:, None] * L
            + jnp.arange(L, dtype=key_idx.dtype)).reshape(-1)
    return replace(
        st,
        valid=st.valid.at[flat].set(False, mode="drop"),
        base_ts=st.base_ts.at[key_idx].set(0, mode="drop"),
        base_tie=st.base_tie.at[key_idx].set(0, mode="drop"),
        base_val=st.base_val.at[key_idx].set(-1, mode="drop"),
    )


def lww_grow(st: LwwShardState, n_keys: int | None = None,
             n_dcs: int | None = None) -> LwwShardState:
    """Host-side capacity regrade (see orset_grow)."""
    K = st.base_ts.shape[0]
    D = st._d
    L = st.n_lanes
    nk, nd = (n_keys or K), (n_dcs or D)
    if (nk, nd) == (K, D):
        return st
    ops = np.asarray(st.ops).reshape(K, L, -1)
    scal = ops[..., :_LNSCAL]
    ss = ops[..., _LNSCAL:]
    ops = np.concatenate(
        [scal, np.pad(ss, ((0, 0), (0, 0), (0, nd - D)))], axis=-1)
    if nk > K:
        ops = np.pad(ops, ((0, nk - K), (0, 0), (0, 0)))
    valid = np.pad(np.asarray(st.valid).reshape(K, L), ((0, nk - K), (0, 0)))
    pad1 = lambda a, fill: np.pad(np.asarray(a), (0, nk - K),
                                  constant_values=fill)
    return LwwShardState(
        base_ts=jnp.asarray(pad1(st.base_ts, 0)),
        base_tie=jnp.asarray(pad1(st.base_tie, 0)),
        base_val=jnp.asarray(pad1(st.base_val, -1)),
        base_vc=jnp.asarray(np.pad(np.asarray(st.base_vc), (0, nd - D))),
        has_base=st.has_base,
        ops=jnp.asarray(ops.reshape(nk * L, -1)),
        valid=jnp.asarray(valid.reshape(-1)),
        n_lanes=L,
    )


def lww_reval(st: LwwShardState, remap: np.ndarray) -> LwwShardState:
    """Host-side value-id remap after the plane compacts its value
    directory (dead interned values dropped): every stored val column
    maps through ``remap`` (old id -> new id; dead ids map to -1 but are
    only present on invalid lanes).  Rare, host-side."""
    ops = np.array(np.asarray(st.ops))
    valid = np.asarray(st.valid)
    v = ops[:, _LVAL]
    ops[:, _LVAL] = np.where(
        valid, remap[np.clip(v, 0, len(remap) - 1)], v)
    bval = np.asarray(st.base_val)
    live = bval >= 0
    bval = np.where(live, remap[np.clip(bval, 0, len(remap) - 1)], bval)
    return replace(st, ops=jnp.asarray(ops), base_val=jnp.asarray(bval))


def lww_retie(st: LwwShardState, remap: np.ndarray,
              rank_shift: int) -> LwwShardState:
    """Host-side tiebreak repack after the actor-rank directory grows:
    every stored tie (rank << rank_shift | seq) has its rank remapped
    through ``remap`` (old rank -> new rank).  Rare (first sight of a
    new actor), so simplicity over speed."""
    mask = (1 << rank_shift) - 1

    def repack(packed, live):
        packed = np.asarray(packed)
        rank = (packed >> rank_shift).astype(np.int64)
        seq = packed & mask
        rank = np.where(live, remap[np.clip(rank, 0, len(remap) - 1)], rank)
        return (rank << rank_shift) | seq

    K = st.base_ts.shape[0]
    L = st.n_lanes
    base_live = np.asarray(st.base_val) >= 0
    ops = np.array(np.asarray(st.ops))
    ops[:, _LTIE] = repack(ops[:, _LTIE], np.asarray(st.valid))
    return replace(
        st,
        base_tie=jnp.asarray(repack(st.base_tie, base_live)),
        ops=jnp.asarray(ops),
    )


# ---------------------------------------------------------------------------
# set_rw shard — the remove-wins two-plane dot lattice
#
# Two dot tables per key (adds / removes) with cross-cancellation
# (kernels.rwset_apply; host oracle crdt/sets.py SetRW).  Ring, append,
# GC-fold, purge, and grow follow the OR-Set machinery; rows carry TWO
# observed VVs (the add-plane one zeroed on add rows and vice versa) so
# the fold needs no per-row kind test for cancellation.  flag_dw shares
# this store with a single implicit element slot (crdt/flags.py FlagDW).

# packed columns (set_rw): scalars, then obs_add VV, obs_rmv VV, op SS
_RELEM, _RKIND, _RDOTDC, _RDOTSEQ, _ROPDC, _ROPCT, _RNSCAL = \
    0, 1, 2, 3, 4, 5, 6


@dataclass
class RwsetShardState:
    """``ops[K*L, 6+3D]`` packs [elem_slot, kind, dot_dc, dot_seq,
    op_dc, op_ct, obs_add(D), obs_rmv(D), op_ss(D)]."""

    adds: jax.Array      # int[K, E, D] base add-dot table
    rmvs: jax.Array      # int[K, E, D] base remove-dot table
    base_vc: jax.Array   # int[D]
    has_base: jax.Array  # bool[]
    ops: jax.Array       # int[K*L, 6+3D]
    valid: jax.Array     # bool[K*L]
    n_lanes: int

    @property
    def _d(self) -> int:
        return (self.ops.shape[-1] - _RNSCAL) // 3

    def _col(self, c) -> jax.Array:
        return self.ops[:, c].reshape(-1, self.n_lanes)

    @property
    def valid2d(self) -> jax.Array:
        return self.valid.reshape(-1, self.n_lanes)

    @property
    def elem_slot(self):
        return self._col(_RELEM)

    @property
    def kind(self):
        return self._col(_RKIND)

    @property
    def dot_dc(self):
        return self._col(_RDOTDC)

    @property
    def dot_seq(self):
        return self._col(_RDOTSEQ)

    @property
    def op_dc(self):
        return self._col(_ROPDC)

    @property
    def op_ct(self):
        return self._col(_ROPCT)

    @property
    def obs_add(self):
        d = self._d
        return self.ops[:, _RNSCAL:_RNSCAL + d].reshape(
            -1, self.n_lanes, d)

    @property
    def obs_rmv(self):
        d = self._d
        return self.ops[:, _RNSCAL + d:_RNSCAL + 2 * d].reshape(
            -1, self.n_lanes, d)

    @property
    def op_ss(self):
        d = self._d
        return self.ops[:, _RNSCAL + 2 * d:].reshape(-1, self.n_lanes, d)


jax.tree_util.register_dataclass(
    RwsetShardState,
    data_fields=["adds", "rmvs", "base_vc", "has_base", "ops", "valid"],
    meta_fields=["n_lanes"],
)


def rwset_shard_init(n_keys: int, n_lanes: int, n_slots: int, n_dcs: int,
                     dtype=jnp.int64) -> RwsetShardState:
    K, L, E, D = n_keys, n_lanes, n_slots, n_dcs
    ops = jnp.zeros((K * L, _RNSCAL + 3 * D), dtype=dtype)
    ops = ops.at[:, _RELEM].set(E)  # empty lanes route to the drop slot
    return RwsetShardState(
        adds=jnp.zeros((K, E, D), dtype=dtype),
        rmvs=jnp.zeros((K, E, D), dtype=dtype),
        base_vc=jnp.zeros((D,), dtype=dtype),
        has_base=jnp.zeros((), dtype=bool),
        ops=ops,
        valid=jnp.zeros((K * L,), dtype=bool),
        n_lanes=L,
    )


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def rwset_append(st: RwsetShardState, key_idx, lane_off, elem_slot, kind,
                 dot_dc, dot_seq, obs_add, obs_rmv, op_dc, op_ct, op_ss,
                 active: jax.Array | None = None):
    dt = st.ops.dtype
    col = lambda a: a.astype(dt)[:, None]
    rows = jnp.concatenate([
        col(elem_slot), col(kind), col(dot_dc), col(dot_seq),
        col(op_dc), col(op_ct), obs_add.astype(dt), obs_rmv.astype(dt),
        op_ss.astype(dt),
    ], axis=1)
    return _scatter_rows(st, key_idx, lane_off, rows, active)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def rwset_gc(st: RwsetShardState, gst: jax.Array) -> RwsetShardState:
    """Fold stable ops into the base planes (orset_gc stability
    contract; max-collapse is prefix-cancel insensitive on both planes,
    so folding commutes with later cancellation)."""
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
    stable = st.valid2d & dense.le(cvc, gst[None, None, :])
    adds, rmvs = kernels.rwset_apply(
        st.adds, st.rmvs, st.elem_slot, st.kind, st.dot_dc, st.dot_seq,
        st.obs_add, st.obs_rmv, stable)
    return replace(
        st,
        adds=adds, rmvs=rmvs,
        base_vc=jnp.maximum(st.base_vc, gst.astype(st.base_vc.dtype)),
        has_base=jnp.ones((), dtype=bool),
        valid=st.valid & ~stable.reshape(-1),
    )


@kernel_span("mat.store")
@jax.jit
def rwset_read(st: RwsetShardState, read_vc: jax.Array):
    """(adds, rmvs)[K, E, D]: live dot tables for every key at
    ``read_vc`` (requires read_vc >= base_vc, as orset_read)."""
    K = st.adds.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid2d, base_vc, has_base,
        read_vc)
    return kernels.rwset_apply(
        st.adds, st.rmvs, st.elem_slot, st.kind, st.dot_dc, st.dot_seq,
        st.obs_add, st.obs_rmv, mask)


@kernel_span("mat.store")
@jax.jit
def rwset_read_keys(st: RwsetShardState, key_idx: jax.Array,
                    read_vc: jax.Array):
    """(adds, rmvs)[B, E, D] for just the requested keys (transaction
    read path; see orset_read_keys)."""
    d = st._d
    ops, mask = _gather_key_rows(st, key_idx, read_vc,
                                 _ROPDC, _ROPCT, _RNSCAL + 2 * d)
    return kernels.rwset_apply(
        st.adds[key_idx], st.rmvs[key_idx], ops[..., _RELEM],
        ops[..., _RKIND], ops[..., _RDOTDC], ops[..., _RDOTSEQ],
        ops[..., _RNSCAL:_RNSCAL + d],
        ops[..., _RNSCAL + d:_RNSCAL + 2 * d], mask)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def rwset_purge_keys(st: RwsetShardState,
                     key_idx: jax.Array) -> RwsetShardState:
    L = st.n_lanes
    flat = (key_idx[:, None] * L
            + jnp.arange(L, dtype=key_idx.dtype)).reshape(-1)
    return replace(
        st,
        valid=st.valid.at[flat].set(False, mode="drop"),
        adds=st.adds.at[key_idx].set(0, mode="drop"),
        rmvs=st.rmvs.at[key_idx].set(0, mode="drop"),
    )


def rwset_grow(st: RwsetShardState, n_keys: int | None = None,
               n_slots: int | None = None,
               n_dcs: int | None = None) -> RwsetShardState:
    """Host-side capacity regrade (see orset_grow)."""
    K, E, D = st.adds.shape
    L = st.n_lanes
    nk, ne, nd = (n_keys or K), (n_slots or E), (n_dcs or D)
    if (nk, ne, nd) == (K, E, D):
        return st
    ops = np.asarray(st.ops).reshape(K, L, -1)
    scal = ops[..., :_RNSCAL]
    blocks = [ops[..., _RNSCAL + i * D:_RNSCAL + (i + 1) * D]
              for i in range(3)]
    padD = ((0, 0), (0, 0), (0, nd - D))
    ops = np.concatenate(
        [scal] + [np.pad(b, padD) for b in blocks], axis=-1)
    if nk > K:
        ops = np.pad(ops, ((0, nk - K), (0, 0), (0, 0)))
    valid = np.pad(np.asarray(st.valid).reshape(K, L),
                   ((0, nk - K), (0, 0)))
    pad3 = ((0, nk - K), (0, ne - E), (0, nd - D))
    return RwsetShardState(
        adds=jnp.asarray(np.pad(np.asarray(st.adds), pad3)),
        rmvs=jnp.asarray(np.pad(np.asarray(st.rmvs), pad3)),
        base_vc=jnp.asarray(np.pad(np.asarray(st.base_vc), (0, nd - D))),
        has_base=st.has_base,
        ops=jnp.asarray(ops.reshape(nk * L, -1)),
        valid=jnp.asarray(valid.reshape(-1)),
        n_lanes=L,
    )


# ---------------------------------------------------------------------------
# set_go shard — monotone presence ring (no dots, no cancellation)

# packed columns (set_go): [elem_slot, op_dc, op_ct, op_ss(D)]
_GELEM, _GOPDC, _GOPCT, _GNSCAL = 0, 1, 2, 3


@dataclass
class SetGoShardState:
    """``ops[K*L, 3+D]`` packs [elem_slot, op_dc, op_ct, op_ss(D)];
    the base is a plain presence bitmap (grow-only union)."""

    present: jax.Array   # bool[K, E] base presence
    base_vc: jax.Array   # int[D]
    has_base: jax.Array  # bool[]
    ops: jax.Array       # int[K*L, 3+D]
    valid: jax.Array     # bool[K*L]
    n_lanes: int

    @property
    def _d(self) -> int:
        return self.ops.shape[-1] - _GNSCAL

    def _col(self, c) -> jax.Array:
        return self.ops[:, c].reshape(-1, self.n_lanes)

    @property
    def valid2d(self) -> jax.Array:
        return self.valid.reshape(-1, self.n_lanes)

    @property
    def elem_slot(self):
        return self._col(_GELEM)

    @property
    def op_dc(self):
        return self._col(_GOPDC)

    @property
    def op_ct(self):
        return self._col(_GOPCT)

    @property
    def op_ss(self):
        d = self._d
        return self.ops[:, _GNSCAL:].reshape(-1, self.n_lanes, d)


jax.tree_util.register_dataclass(
    SetGoShardState,
    data_fields=["present", "base_vc", "has_base", "ops", "valid"],
    meta_fields=["n_lanes"],
)


def setgo_shard_init(n_keys: int, n_lanes: int, n_slots: int, n_dcs: int,
                     dtype=jnp.int64) -> SetGoShardState:
    K, L, E, D = n_keys, n_lanes, n_slots, n_dcs
    ops = jnp.zeros((K * L, _GNSCAL + D), dtype=dtype)
    ops = ops.at[:, _GELEM].set(E)
    return SetGoShardState(
        present=jnp.zeros((K, E), dtype=bool),
        base_vc=jnp.zeros((D,), dtype=dtype),
        has_base=jnp.zeros((), dtype=bool),
        ops=ops,
        valid=jnp.zeros((K * L,), dtype=bool),
        n_lanes=L,
    )


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def setgo_append(st: SetGoShardState, key_idx, lane_off, elem_slot,
                 op_dc, op_ct, op_ss, active: jax.Array | None = None):
    dt = st.ops.dtype
    col = lambda a: a.astype(dt)[:, None]
    rows = jnp.concatenate(
        [col(elem_slot), col(op_dc), col(op_ct), op_ss.astype(dt)],
        axis=1)
    return _scatter_rows(st, key_idx, lane_off, rows, active)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def setgo_gc(st: SetGoShardState, gst: jax.Array) -> SetGoShardState:
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
    stable = st.valid2d & dense.le(cvc, gst[None, None, :])
    present = kernels.setgo_apply(st.present, st.elem_slot, stable)
    return replace(
        st,
        present=present,
        base_vc=jnp.maximum(st.base_vc, gst.astype(st.base_vc.dtype)),
        has_base=jnp.ones((), dtype=bool),
        valid=st.valid & ~stable.reshape(-1),
    )


@kernel_span("mat.store")
@jax.jit
def setgo_read(st: SetGoShardState, read_vc: jax.Array) -> jax.Array:
    """bool[K, E]: grow-only element presence for every key at
    ``read_vc`` in one batched materialization (base bitmap + included
    ring ops) — the full-shard form of :func:`setgo_read_keys`, added
    so every plane type the DevicePlane serves has the same read
    surface (the sharded stores' ``_read_fn`` slot)."""
    K = st.present.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid2d, base_vc, has_base,
        read_vc)
    return kernels.setgo_apply(st.present, st.elem_slot, mask)


@kernel_span("mat.store")
@jax.jit
def setgo_read_keys(st: SetGoShardState, key_idx: jax.Array,
                    read_vc: jax.Array) -> jax.Array:
    """bool[B, E]: element presence for the requested keys."""
    ops, mask = _gather_key_rows(st, key_idx, read_vc,
                                 _GOPDC, _GOPCT, _GNSCAL)
    return kernels.setgo_apply(
        st.present[key_idx], ops[..., _GELEM], mask)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def setgo_purge_keys(st: SetGoShardState,
                     key_idx: jax.Array) -> SetGoShardState:
    L = st.n_lanes
    flat = (key_idx[:, None] * L
            + jnp.arange(L, dtype=key_idx.dtype)).reshape(-1)
    return replace(
        st,
        valid=st.valid.at[flat].set(False, mode="drop"),
        present=st.present.at[key_idx].set(False, mode="drop"),
    )


def setgo_grow(st: SetGoShardState, n_keys: int | None = None,
               n_slots: int | None = None,
               n_dcs: int | None = None) -> SetGoShardState:
    """Host-side capacity regrade (see orset_grow)."""
    K, E = st.present.shape
    D = st._d
    L = st.n_lanes
    nk, ne, nd = (n_keys or K), (n_slots or E), (n_dcs or D)
    if (nk, ne, nd) == (K, E, D):
        return st
    ops = np.asarray(st.ops).reshape(K, L, -1)
    scal = ops[..., :_GNSCAL]
    ss = ops[..., _GNSCAL:]
    ops = np.concatenate(
        [scal, np.pad(ss, ((0, 0), (0, 0), (0, nd - D)))], axis=-1)
    if nk > K:
        ops = np.pad(ops, ((0, nk - K), (0, 0), (0, 0)))
    valid = np.pad(np.asarray(st.valid).reshape(K, L),
                   ((0, nk - K), (0, 0)))
    return SetGoShardState(
        present=jnp.asarray(np.pad(np.asarray(st.present),
                                   ((0, nk - K), (0, ne - E)))),
        base_vc=jnp.asarray(np.pad(np.asarray(st.base_vc), (0, nd - D))),
        has_base=st.has_base,
        ops=jnp.asarray(ops.reshape(nk * L, -1)),
        valid=jnp.asarray(valid.reshape(-1)),
        n_lanes=L,
    )


# ---------------------------------------------------------------------------
# counter_pn shard — same packed-ring machinery, scalar state

# packed columns (counter): [delta, op_dc, op_ct, op_ss(D)]
_CDELTA, _COPDC, _COPCT, _CNSCAL = 0, 1, 2, 3


@dataclass
class CounterShardState:
    """``ops[K*L, 3+D]`` packs [delta, op_dc, op_ct, op_ss(D)]."""

    value: jax.Array     # int[K] base values
    base_vc: jax.Array   # int[D]
    has_base: jax.Array  # bool[]
    ops: jax.Array       # int[K*L, 3+D]
    valid: jax.Array     # bool[K*L]
    n_lanes: int

    @property
    def _d(self) -> int:
        return self.ops.shape[-1] - _CNSCAL

    def _col(self, c) -> jax.Array:
        return self.ops[:, c].reshape(-1, self.n_lanes)

    @property
    def valid2d(self) -> jax.Array:
        return self.valid.reshape(-1, self.n_lanes)

    @property
    def count(self) -> jax.Array:
        return jnp.sum(self.valid2d, axis=1, dtype=jnp.int32)

    @property
    def delta(self):
        return self._col(_CDELTA)

    @property
    def op_dc(self):
        return self._col(_COPDC)

    @property
    def op_ct(self):
        return self._col(_COPCT)

    @property
    def op_ss(self):
        d = self._d
        return self.ops[:, _CNSCAL:].reshape(-1, self.n_lanes, d)


jax.tree_util.register_dataclass(
    CounterShardState,
    data_fields=["value", "base_vc", "has_base", "ops", "valid"],
    meta_fields=["n_lanes"],
)


def counter_shard_init(n_keys: int, n_lanes: int, n_dcs: int,
                       dtype=jnp.int64) -> CounterShardState:
    K, L, D = n_keys, n_lanes, n_dcs
    return CounterShardState(
        value=jnp.zeros((K,), dtype=dtype),
        base_vc=jnp.zeros((D,), dtype=dtype),
        has_base=jnp.zeros((), dtype=bool),
        ops=jnp.zeros((K * L, _CNSCAL + D), dtype=dtype),
        valid=jnp.zeros((K * L,), dtype=bool),
        n_lanes=L,
    )


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def counter_append(st: CounterShardState, key_idx, lane_off, delta,
                   op_dc, op_ct, op_ss,
                   active: jax.Array | None = None):
    """``active`` (bool[B], optional) drops masked-off ops entirely (no
    scatter, no overflow) — the sharded store's this-chip's-keys filter
    (same contract as orset_append)."""
    dt = st.ops.dtype
    col = lambda a: a.astype(dt)[:, None]
    rows = jnp.concatenate(
        [col(delta), col(op_dc), col(op_ct), op_ss.astype(dt)], axis=1)
    return _scatter_rows(st, key_idx, lane_off, rows, active)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def counter_gc(st: CounterShardState, gst: jax.Array) -> CounterShardState:
    cvc = dense.commit_vc(st.op_ss, st.op_dc, st.op_ct)
    stable = st.valid2d & dense.le(cvc, gst[None, None, :])
    value = kernels.counter_read(st.value, st.delta, stable)
    return replace(
        st,
        value=value,
        base_vc=jnp.maximum(st.base_vc, gst.astype(st.base_vc.dtype)),
        has_base=jnp.ones((), dtype=bool),
        valid=st.valid & ~stable.reshape(-1),
    )


@kernel_span("mat.store")
@jax.jit
def counter_read(st: CounterShardState, read_vc: jax.Array) -> jax.Array:
    """int[K]: counter values at ``read_vc``."""
    K = st.value.shape[0]
    base_vc = jnp.broadcast_to(st.base_vc, (K, st.base_vc.shape[0]))
    has_base = jnp.broadcast_to(st.has_base, (K,))
    mask = kernels.inclusion_mask(
        st.op_dc, st.op_ct, st.op_ss, st.valid2d, base_vc, has_base,
        read_vc)
    return kernels.counter_read(st.value, st.delta, mask)


@kernel_span("mat.store")
@jax.jit
def counter_read_keys(st: CounterShardState, key_idx: jax.Array,
                      read_vc: jax.Array) -> jax.Array:
    """int[B]: counter values for just the requested keys at ``read_vc``
    (the transaction read path; see orset_read_keys)."""
    ops, mask = _gather_key_rows(st, key_idx, read_vc,
                                 _COPDC, _COPCT, _CNSCAL)
    return kernels.counter_read(st.value[key_idx], ops[..., _CDELTA], mask)


@kernel_span("mat.store")
@partial(jax.jit, donate_argnums=(0,))
def counter_purge_keys(st: CounterShardState,
                       key_idx: jax.Array) -> CounterShardState:
    """Free ring lanes and zero base values of the given keys (host
    eviction; see orset_purge_keys)."""
    L = st.n_lanes
    flat = (key_idx[:, None] * L
            + jnp.arange(L, dtype=key_idx.dtype)).reshape(-1)
    return replace(
        st,
        valid=st.valid.at[flat].set(False, mode="drop"),
        value=st.value.at[key_idx].set(0, mode="drop"),
    )


def counter_grow(st: CounterShardState, n_keys: int | None = None,
                 n_dcs: int | None = None) -> CounterShardState:
    """Host-side capacity regrade for the counter shard (see orset_grow)."""
    K = st.value.shape[0]
    D = st._d
    L = st.n_lanes
    nk, nd = (n_keys or K), (n_dcs or D)
    if (nk, nd) == (K, D):
        return st
    ops = np.asarray(st.ops).reshape(K, L, -1)
    scal = ops[..., :_CNSCAL]
    ss = ops[..., _CNSCAL:]
    ops = np.concatenate(
        [scal, np.pad(ss, ((0, 0), (0, 0), (0, nd - D)))], axis=-1)
    if nk > K:
        ops = np.pad(ops, ((0, nk - K), (0, 0), (0, 0)))
    valid = np.pad(np.asarray(st.valid).reshape(K, L), ((0, nk - K), (0, 0)))
    return CounterShardState(
        value=jnp.asarray(np.pad(np.asarray(st.value), (0, nk - K))),
        base_vc=jnp.asarray(np.pad(np.asarray(st.base_vc), (0, nd - D))),
        has_base=st.has_base,
        ops=jnp.asarray(ops.reshape(nk * L, -1)),
        valid=jnp.asarray(valid.reshape(-1)),
        n_lanes=L,
    )


def batch_lane_offsets(key_idx: np.ndarray) -> np.ndarray:
    """Host helper: occurrence index of each key within the batch
    (0,1,...) in batch order — disambiguates same-key ops in one append.
    Vectorized (argsort + run-length ranks)."""
    key_idx = np.asarray(key_idx)
    n = len(key_idx)
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    order = np.argsort(key_idx, kind="stable")
    sk = key_idx[order]
    starts = np.r_[0, np.flatnonzero(sk[1:] != sk[:-1]) + 1]
    run_of = np.repeat(np.arange(len(starts)), np.diff(np.r_[starts, n]))
    occ = np.arange(n) - starts[run_of]
    out = np.empty(n, dtype=np.int32)
    out[order] = occ
    return out

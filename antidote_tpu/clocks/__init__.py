from antidote_tpu.clocks.vc import VC, ClockDomain, vc_max, vc_min  # noqa: F401
from antidote_tpu.clocks import dense  # noqa: F401

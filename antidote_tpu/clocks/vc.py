"""Host-side vector clocks for the control plane.

The transaction coordinator, inter-DC manager and metadata plane handle a
handful of clocks at a time (latency-bound, not throughput-bound), so they
use a plain dict-backed clock mirroring the reference's external
``vectorclock`` dep (DCID -> timestamp, missing = 0; call sites e.g.
reference src/clocksi_interactive_coord.erl:689-691).  The batched data
plane uses the dense kernels in :mod:`antidote_tpu.clocks.dense`;
:class:`ClockDomain` converts between the two representations.
"""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, Mapping

import numpy as np

DcId = Hashable


class VC(dict):
    """A vector clock: mapping DCID -> int timestamp, missing entries are 0."""

    def get_dc(self, dc: DcId) -> int:
        return self.get(dc, 0)

    def set_dc(self, dc: DcId, t: int) -> "VC":
        out = VC(self)
        out[dc] = int(t)
        return out

    def le(self, other: Mapping[DcId, int]) -> bool:
        return all(v <= other.get(dc, 0) for dc, v in self.items())

    def ge(self, other: Mapping[DcId, int]) -> bool:
        return all(self.get(dc, 0) >= v for dc, v in other.items())

    def lt(self, other: Mapping[DcId, int]) -> bool:
        return self.le(other) and self != other

    def gt(self, other: Mapping[DcId, int]) -> bool:
        return self.ge(other) and self != other

    def concurrent(self, other: Mapping[DcId, int]) -> bool:
        return not self.le(other) and not self.ge(other)

    def all_dots_greater(self, other: Mapping[DcId, int]) -> bool:
        keys = set(self) | set(other.keys())
        return all(self.get(dc, 0) > other.get(dc, 0) for dc in keys)

    def all_dots_smaller(self, other: Mapping[DcId, int]) -> bool:
        keys = set(self) | set(other.keys())
        return all(self.get(dc, 0) < other.get(dc, 0) for dc in keys)

    def join(self, other: Mapping[DcId, int]) -> "VC":
        """Elementwise max."""
        out = VC(self)
        for dc, v in other.items():
            if v > out.get(dc, 0):
                out[dc] = v
        return out

    def meet(self, other: Mapping[DcId, int]) -> "VC":
        """Elementwise min (entries missing on either side -> 0 -> dropped)."""
        keys = set(self) | set(other.keys())
        return VC.clean(
            {dc: min(self.get(dc, 0), other.get(dc, 0)) for dc in keys}
        )

    def __eq__(self, other) -> bool:  # zero entries are not distinguishing
        if not isinstance(other, Mapping):
            return NotImplemented
        keys = set(self) | set(other.keys())
        return all(self.get(dc, 0) == other.get(dc, 0) for dc in keys)

    def __ne__(self, other) -> bool:
        res = self.__eq__(other)
        return res if res is NotImplemented else not res

    __hash__ = None  # mutable

    @staticmethod
    def clean(m: Mapping[DcId, int]) -> "VC":
        """Drop explicit zero entries (canonical form)."""
        return VC({dc: int(v) for dc, v in m.items() if v != 0})

    @staticmethod
    def from_list(pairs: Iterable[tuple]) -> "VC":
        return VC.clean(dict(pairs))


def vc_min(clocks: Iterable[Mapping[DcId, int]]) -> VC:
    """Column-wise min over a collection of clocks; empty -> bottom.

    Matches the GST merge: a DC missing from any clock pins that column to 0
    (reference src/stable_time_functions.erl:51-85).
    """
    clocks = list(clocks)
    if not clocks:
        return VC()
    out = VC.clean(clocks[0])
    for c in clocks[1:]:
        out = out.meet(c)
    return out


def vc_max(clocks: Iterable[Mapping[DcId, int]]) -> VC:
    out = VC()
    for c in clocks:
        out = out.join(c)
    return out


class ClockDomain:
    """Assigns each DCID a dense column index and converts VC <-> dense rows.

    The dense capacity ``d`` is fixed per domain instance (XLA wants static
    shapes); `grow()` returns a wider copy when more DCs join than capacity
    allows — callers re-pad device state on growth.
    """

    def __init__(self, d: int = 8):
        self.d = int(d)
        self._index: Dict[DcId, int] = {}
        self._ids: list = []

    @property
    def dc_ids(self) -> list:
        return list(self._ids)

    def __len__(self) -> int:
        return len(self._ids)

    def index_of(self, dc: DcId) -> int:
        """Dense column of ``dc``, registering it on first sight."""
        if dc not in self._index:
            if len(self._ids) >= self.d:
                raise ValueError(
                    f"clock domain capacity {self.d} exhausted; grow() first"
                )
            self._index[dc] = len(self._ids)
            self._ids.append(dc)
        return self._index[dc]

    def contains(self, dc: DcId) -> bool:
        return dc in self._index

    def grow(self, new_d: int) -> "ClockDomain":
        if new_d < self.d:
            raise ValueError("cannot shrink a clock domain")
        out = ClockDomain(new_d)
        out._index = dict(self._index)
        out._ids = list(self._ids)
        return out

    def to_dense(self, vc: Mapping[DcId, int]) -> np.ndarray:
        # Pre-check capacity for all unseen DCs so a clock that overflows
        # the domain raises without partially mutating the index.
        unseen = [dc for dc, t in vc.items() if t and dc not in self._index]
        if len(self._ids) + len(unseen) > self.d:
            raise ValueError(
                f"clock domain capacity {self.d} exhausted; grow() first"
            )
        row = np.zeros((self.d,), dtype=np.int64)
        for dc, t in vc.items():
            if t:
                row[self.index_of(dc)] = t
        return row

    def from_dense(self, row) -> VC:
        row = np.asarray(row)
        return VC.clean({self._ids[i]: int(row[i]) for i in range(len(self._ids))})

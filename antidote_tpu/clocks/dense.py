"""Dense vector-clock kernels (JAX, TPU-first).

Vector clocks in the reference are Erlang dicts DCID -> timestamp with
missing entries treated as 0 (external `vectorclock` dep; call sites e.g.
reference src/materializer.erl:101-106, src/vector_orddict.erl:82,118,
src/stable_time_functions.erl:39-85).

Here a VC is a dense ``int64[..., D]`` row where column ``j`` is the
timestamp of the DC with dense index ``j`` (assigned by the control
plane's :class:`antidote_tpu.clocks.vc.ClockDomain`).  A missing DC is
simply a zero column, which matches the reference's missing-entry-is-zero
semantics exactly.  All comparisons are elementwise reductions over the
last axis and batch over any leading axes — this is what lets the
materializer test a whole op log (or a whole key batch) against a snapshot
in one fused XLA op instead of a per-op dict fold.

Timestamps are int64 microseconds (the reference uses erlang monotonic /
os timestamps in µs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

DTYPE = jnp.int64


def zeros(d: int) -> jax.Array:
    """The bottom clock (all zeros) over a ``d``-column domain."""
    return jnp.zeros((d,), dtype=DTYPE)


def le(a: jax.Array, b: jax.Array) -> jax.Array:
    """``a <= b`` pointwise-dominance: every entry of a is <= b.

    Mirrors vectorclock:le/2 (used at reference src/materializer.erl:106).
    Broadcasts: ``le(ops_vc[N, D], snap[D]) -> bool[N]``.
    """
    return jnp.all(a <= b, axis=-1)


def ge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Mirrors vectorclock:ge/2 (reference src/inter_dc_dep_vnode.erl:131)."""
    return jnp.all(a >= b, axis=-1)


def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.all(a == b, axis=-1)


def lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """Strictly-less dominance: a <= b and a /= b."""
    return jnp.logical_and(le(a, b), jnp.any(a != b, axis=-1))


def gt(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.logical_and(ge(a, b), jnp.any(a != b, axis=-1))


def concurrent(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.logical_and(jnp.logical_not(le(a, b)), jnp.logical_not(ge(a, b)))


def all_dots_greater(a: jax.Array, b: jax.Array) -> jax.Array:
    """Every entry of ``a`` strictly greater than ``b``.

    Mirrors vectorclock:all_dots_greater (reference src/vector_orddict.erl:118,
    used to keep the snapshot cache sorted most-recent-first).
    """
    return jnp.all(a > b, axis=-1)


def all_dots_smaller(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.all(a < b, axis=-1)


def join(a: jax.Array, b: jax.Array) -> jax.Array:
    """Least upper bound (elementwise max) — vectorclock:max/1."""
    return jnp.maximum(a, b)


def meet(a: jax.Array, b: jax.Array) -> jax.Array:
    """Greatest lower bound (elementwise min) — vectorclock:min/1."""
    return jnp.minimum(a, b)


def min_merge(stack: jax.Array, valid: jax.Array | None = None) -> jax.Array:
    """Column-wise min over a stack of clocks ``[..., N, D] -> [..., D]``.

    This is the GST (global stable time) merge: min per DC over partitions,
    then over nodes (reference src/stable_time_functions.erl:51-85 and
    src/meta_data_sender.erl:268-339).  A missing/invalid row forces the
    result to the bottom clock, mirroring the reference's
    "missing node => all-zero snapshot" rule
    (src/stable_time_functions.erl:78-85).

    ``valid``: optional bool[..., N]; rows with False count as missing.
    """
    if valid is not None:
        stack = jnp.where(valid[..., None], stack, jnp.zeros((), dtype=stack.dtype))
    return jnp.min(stack, axis=-2)


def max_merge(stack: jax.Array, valid: jax.Array | None = None) -> jax.Array:
    """Column-wise max over ``[..., N, D]``; invalid rows contribute zero."""
    if valid is not None:
        stack = jnp.where(valid[..., None], stack, jnp.zeros((), dtype=stack.dtype))
    return jnp.max(stack, axis=-2)


def set_dc(vc: jax.Array, dc: jax.Array, t: jax.Array) -> jax.Array:
    """Return ``vc`` with column ``dc`` replaced by ``t``; batches over rows.

    ``vc``: [..., D]; ``dc``: [...] int; ``t``: [...] int.
    Implemented as a one-hot select so it vectorizes (no scatter) — this is
    the hot "replace the origin-DC entry with the commit time" step of the
    snapshot-inclusion test (reference src/materializer.erl:105,
    src/clocksi_materializer.erl:224).
    """
    hot = jax.nn.one_hot(dc, vc.shape[-1], dtype=jnp.bool_)
    return jnp.where(hot, jnp.asarray(t, dtype=vc.dtype)[..., None], vc)


def get_dc(vc: jax.Array, dc: jax.Array) -> jax.Array:
    """Column ``dc`` of each row of ``vc`` (batched gather via one-hot)."""
    hot = jax.nn.one_hot(dc, vc.shape[-1], dtype=vc.dtype)
    return jnp.sum(vc * hot, axis=-1)


def commit_vc(op_ss: jax.Array, op_dc: jax.Array, op_ct: jax.Array) -> jax.Array:
    """The op's snapshot VC with its origin column bumped to its commit time.

    ``OpSS[dc <- commit_time]`` — the quantity the reference calls
    ``OpSSCommit`` (src/clocksi_materializer.erl:224).  Batched over ops.
    """
    return set_dc(op_ss, op_dc, op_ct)


def op_not_in_snapshot(ss: jax.Array, op_commit_vc: jax.Array) -> jax.Array:
    """True where the op is NEWER than snapshot ``ss`` (not contained in it).

    Mirrors materializer:belongs_to_snapshot_op/3 (reference
    src/materializer.erl:101-106): op is outside the snapshot iff
    ``not (OpSSCommit <= ss)``.  Batched: ``op_commit_vc[N, D], ss[D] -> bool[N]``.
    """
    return jnp.logical_not(le(op_commit_vc, ss))


def op_in_read_snapshot(read_vc: jax.Array, op_commit_vc: jax.Array) -> jax.Array:
    """True where the op may be included when reading at ``read_vc``.

    The dense form of the per-DC fold in is_op_in_snapshot (reference
    src/clocksi_materializer.erl:236-258): include iff no column of the
    op's commit VC exceeds the read snapshot.  In the dense domain a DC the
    reference would report missing is a zero column and compares as 0,
    which is exactly the dict fold's behavior for absent OpSSCommit entries.
    """
    return le(op_commit_vc, read_vc)

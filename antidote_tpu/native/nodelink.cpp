// Native node-fabric endpoint — the intra-DC RPC transport's IO plane.
//
// The reference's intra-DC transport is distributed Erlang: every vnode
// command is a gen_server call serviced by BEAM schedulers that
// multiplex thousands of processes without a global lock (reference
// src/clocksi_vnode.erl:99-209 call sites, include/antidote.hrl:28 —
// 20 read servers per vnode).  A pure-Python socket loop cannot match
// that: a peer's accept/serve thread waits for the busy interpreter's
// GIL timeslice just to READ a frame, putting a scheduler-latency floor
// of ~1-4 ms under every RPC (measured, round 3).  This endpoint moves
// everything except the handler itself off the GIL:
//
// - one C++ event thread per endpoint owns every socket (listener,
//   accepted, outbound) and does all framing, reads, and writes;
// - Python worker threads block INSIDE `nl_recv` (ctypes drops the GIL
//   for the duration of the call), so a request is parsed and queued
//   with zero interpreter involvement and the worker wakes holding a
//   complete message;
// - the client side is PIPELINED: `nl_send` enqueues a frame tagged
//   with a correlation id and returns immediately; any number of
//   requests ride one connection concurrently and `nl_wait` blocks
//   (GIL-free) on just its own id — a coordinator fans 2PC prepares
//   out to N peers in one thread with no thread spawns
//   (the reference's async broadcast-and-collect,
//   src/clocksi_interactive_coord.erl:514-577).
//
// Wire format (both directions): [4B length BE][8B corr id BE][payload]
// where length counts the payload only.  Payloads are the same
// termcodec frames the Python NodeLink speaks; the at-most-once
// request cache and all protocol semantics stay in Python
// (antidote_tpu/cluster/link.py) — this file is transport only, with
// ONE protocol-aware addition (ISSUE 12):
//
// - the PUBLISHED-ANSWER table: Python publishes (request key ->
//   encoded reply) pairs for registered read-only RPCs, and the event
//   thread answers a matching inbound request directly — the reply is
//   queued without the interpreter ever waking, so a busy peer's GIL
//   (the 1-4 ms scheduler-latency floor) stops taxing hot reads
//   (SNAPSHOT_READ at a covered clock, gap-repair ranges off the PR-8
//   index, handoff byte-reads).  The key is the request frame with the
//   per-request rid element spliced out: termcodec encodes the 4-tuple
//   (origin, rid, kind, payload) as concatenated element terms, and
//   the rid (ints, never memoized) cannot shift the string/VC memo
//   state, so origin+kind+payload bytes are a stable identity — the
//   origin MUST stay in the key because later memo back-references can
//   point into strings it registered.  A miss (nothing published,
//   frontier moved, unparseable frame) falls through to the Python
//   worker path unchanged — the universal fallback.  Published answers
//   are deterministic reply bytes, so a retry of an rid answered
//   natively reads the same bytes the at-most-once cache would have
//   remembered: exactly-once semantics are preserved without it.
//
// C ABI for ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

#include "tel_ring.h"

namespace {

constexpr size_t kMaxFrame = 256u << 20;  // payload cap, either direction
constexpr size_t kHdr = 12;               // 4B len + 8B corr

uint32_t rd_u32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

uint64_t rd_u64(const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
    return v;
}

void wr_u32(uint8_t* p, uint32_t v) {
    p[0] = (uint8_t)(v >> 24); p[1] = (uint8_t)(v >> 16);
    p[2] = (uint8_t)(v >> 8); p[3] = (uint8_t)v;
}

void wr_u64(uint8_t* p, uint64_t v) {
    for (int i = 7; i >= 0; i--) { p[i] = (uint8_t)v; v >>= 8; }
}

using Bytes = std::shared_ptr<std::vector<uint8_t>>;

struct OutFrame {
    Bytes data;
    size_t sent = 0;
    uint64_t corr = 0;  // 0 for server replies (nothing to fail)
};

struct Conn {
    int fd = -1;
    bool outbound = false;
    int peer = -1;        // outbound: peer index
    uint64_t token = 0;   // inbound: identifies the conn for replies
    bool connecting = false;
    //: marked by any thread (under mu); read lock-free by the event
    //: thread mid-iteration, hence atomic; reaped at the next loop top
    std::atomic<bool> dead{false};
    // incremental read state — EVENT THREAD ONLY, never locked
    uint8_t hdr[kHdr];
    size_t hdr_got = 0;
    Bytes body;
    size_t body_got = 0;
    uint64_t corr = 0;
    // write queue: senders push_back under mu; only the event thread
    // pops, so the front is stable across its unlocked send() calls
    std::deque<OutFrame> wq;
    //: corr ids ever queued on this conn, swept to FAIL on conn death;
    //: compacted lazily against the pending map
    std::vector<uint64_t> sent_corrs;
};

//: a frame fully parsed by the event thread, delivered under one brief
//: lock per readiness sweep (the lock must NEVER be held across the
//: read()/send() syscalls themselves — senders convoy behind it)
struct Parsed {
    Conn* conn;
    uint64_t corr;
    Bytes body;
};

struct InMsg {
    uint64_t token;
    uint64_t corr;
    Bytes payload;
    //: byte span of the rid element within payload (0,0 = the frame
    //: did not parse as a 4-tuple request — never publishable)
    uint32_t rid_start = 0;
    uint32_t rid_end = 0;
};

enum PendSt { P_WAIT = 0, P_DONE = 1, P_FAIL = 2 };

struct Pending {
    PendSt st = P_WAIT;
    Bytes data;
};

struct Peer {
    std::string host;
    int port = 0;
    Conn* conn = nullptr;  // owned by Ep::conns
    bool want_dial = false;
    std::deque<OutFrame> predial;  // frames queued before the dial
};

//: a published answer plus the rpc-kind id Python interned for it —
//: the TEL_EV_ANSWER event reports the kind, turning the flat
//: native_answered count into a per-kind latency family (ISSUE 16)
struct PubAns {
    Bytes data;
    uint16_t kind = 0;
};

struct Ep {
    int listen_fd = -1;
    uint16_t port = 0;
    int wake_r = -1, wake_w = -1;
    std::thread thread;
    std::mutex mu;
    std::condition_variable cv_in;    // inbound request queue
    std::condition_variable cv_done;  // pending completions
    std::deque<InMsg> inq;
    std::unordered_map<uint64_t, Pending> pend;
    std::map<int, Peer> peers;
    std::vector<std::unique_ptr<Conn>> conns;
    uint64_t next_token = 1;
    uint64_t next_corr = 1;
    bool stop = false;
    //: the published-answer table (ISSUE 12): request key -> encoded
    //: reply, consulted by the event thread before waking Python.
    //: Bounded FIFO (pub_order) so a hot server cannot grow it
    //: without limit; Python clears it wholesale on any state change
    //: that could invalidate an answer (truncation, ring moves).
    std::unordered_map<std::string, PubAns> published;
    std::deque<std::string> pub_order;
    size_t pub_cap = 4096;
    uint64_t native_answered = 0;
    //: flight-recorder ring (ISSUE 16): written ONLY by the event
    //: thread (deliver_all's native-answer branch, under the mutex it
    //: already holds) — single producer, zero added crossings
    tel::TelRing tel;
    //: invalidation generation: bumped by every wholesale clear, and
    //: nl_publish only installs an answer published AT the current
    //: generation — a worker that computed its reply before a
    //: truncation/ring move cleared the table cannot resurrect the
    //: stale answer afterwards (the check and the insert share the
    //: endpoint mutex, so there is no re-publish window)
    uint64_t pub_gen = 0;
};

void set_nonblock(int fd) {
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

void set_nodelay(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void wake(Ep* ep) {
    uint8_t b = 1;
    ssize_t r = write(ep->wake_w, &b, 1);
    (void)r;  // pipe full = loop already awake
}

// Fail (under ep->mu) every still-waiting corr queued on this conn.
void fail_corrs(Ep* ep, Conn* c) {
    bool any = false;
    for (uint64_t corr : c->sent_corrs) {
        auto it = ep->pend.find(corr);
        if (it != ep->pend.end() && it->second.st == P_WAIT) {
            it->second.st = P_FAIL;
            any = true;
        }
    }
    c->sent_corrs.clear();
    c->wq.clear();
    if (any) ep->cv_done.notify_all();
}

void fail_predial(Ep* ep, Peer* pr) {
    bool any = false;
    for (auto& f : pr->predial) {
        auto it = ep->pend.find(f.corr);
        if (it != ep->pend.end() && it->second.st == P_WAIT) {
            it->second.st = P_FAIL;
            any = true;
        }
    }
    pr->predial.clear();
    if (any) ep->cv_done.notify_all();
}

// Parse as much buffered input as available, WITHOUT ep->mu (all read
// state is event-thread-only); completed frames go to `out` for batch
// delivery.  Returns false when the conn must be dropped.
bool pump_read(Conn* c, std::vector<Parsed>* out) {
    for (;;) {
        if (c->hdr_got < kHdr) {
            ssize_t r = read(c->fd, c->hdr + c->hdr_got,
                             kHdr - c->hdr_got);
            if (r == 0) return false;
            if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
            c->hdr_got += (size_t)r;
            if (c->hdr_got < kHdr) continue;
            uint32_t len = rd_u32(c->hdr);
            if (len > kMaxFrame) return false;
            c->corr = rd_u64(c->hdr + 4);
            c->body = std::make_shared<std::vector<uint8_t>>(len);
            c->body_got = 0;
        }
        if (c->body_got < c->body->size()) {
            ssize_t r = read(c->fd, c->body->data() + c->body_got,
                             c->body->size() - c->body_got);
            if (r == 0) return false;
            if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
            c->body_got += (size_t)r;
        }
        if (c->body_got == c->body->size()) {
            out->push_back({c, c->corr, std::move(c->body)});
            c->body = nullptr;
            c->hdr_got = 0;
            c->body_got = 0;
        }
    }
}

// Skip one termcodec term starting at `pos`; returns the offset past
// it, or -1 when the term is malformed / an unskippable tag (batch).
// Mirrors antidote_tpu/interdc/termcodec.py's tag table — only the
// SPANS matter here, never the values (memo back-references are fixed
// width), so the skipper stays correct as long as the tag set is.
long term_skip(const uint8_t* d, long len, long pos, int depth) {
    if (depth > 64 || pos >= len) return -1;
    uint8_t tag = d[pos];
    long p = pos + 1;
    uint32_t n = 0;
    switch (tag) {
        case 'N': case 'T': case 'F':
            return p;
        case '1':                       // int8 payload
            return p + 1 <= len ? p + 1 : -1;
        case 'r':                       // str backref, 1 byte
            return p + 1 <= len ? p + 1 : -1;
        case '8': case 'f':             // int64 / double
            return p + 8 <= len ? p + 8 : -1;
        case 'Q': case 'v':             // str / VC backref, u32
            return p + 4 <= len ? p + 4 : -1;
        case 'C': case 'S':             // bytes / str, 1-byte length
            if (p + 1 > len) return -1;
            n = d[p];
            p += 1;
            return p + (long)n <= len ? p + (long)n : -1;
        case 'i': case 'b': case 's':   // length-prefixed payloads
            if (p + 4 > len) return -1;
            n = rd_u32(d + p);
            p += 4;
            return p + (long)n <= len ? p + (long)n : -1;
        case 'u':                       // tuple, 1-byte count
            if (p + 1 > len) return -1;
            n = d[p];
            p += 1;
            break;
        case 't': case 'l': case 'e': case 'z': case 'd':
        case 'V': case 'O': case 'R': case 'X':  // u32-count sequences
            if (p + 4 > len) return -1;
            n = rd_u32(d + p);
            p += 4;
            break;
        default:                        // 'Y' batch / unknown: bail
            return -1;
    }
    if ((long)n > len - p) return -1;   // each item needs >= 1 byte
    for (uint32_t i = 0; i < n; i++) {
        p = term_skip(d, len, p, depth + 1);
        if (p < 0) return -1;
    }
    return p;
}

// Locate the rid element's span inside a request frame — the 4-tuple
// (origin, rid, kind, payload) always encodes as tag 'u', count 4.
// Returns false when the frame is not that shape (a hand-built or
// hostile frame: never answered natively, never published).
bool rid_span(const uint8_t* d, long len, uint32_t* rid_s,
              uint32_t* rid_e) {
    if (len < 2 || d[0] != 'u' || d[1] != 4) return false;
    long e0 = term_skip(d, len, 2, 0);
    if (e0 <= 0) return false;
    long e1 = term_skip(d, len, e0, 0);
    if (e1 <= 0 || e1 > 0xFFFFFFFFL || len > 0xFFFFFFFFL) return false;
    *rid_s = (uint32_t)e0;
    *rid_e = (uint32_t)e1;
    return true;
}

// Queue a reply frame on a server conn (event thread, under ep->mu).
void queue_reply(Conn* c, uint64_t corr, const Bytes& payload) {
    auto frame = std::make_shared<std::vector<uint8_t>>(
        kHdr + payload->size());
    wr_u32(frame->data(), (uint32_t)payload->size());
    wr_u64(frame->data() + 4, corr);
    memcpy(frame->data() + kHdr, payload->data(), payload->size());
    c->wq.push_back({frame, 0, 0});
}

// Deliver a readiness sweep's parsed frames under ONE brief lock.
// Inbound requests consult the published-answer table first: a hit is
// answered right here on the event thread (the reply lands on the
// conn's write queue; the next poll iteration sees POLLOUT) and the
// interpreter never wakes — the GIL-free read-serving path (ISSUE 12).
void deliver_all(Ep* ep, std::vector<Parsed>* parsed) {
    if (parsed->empty()) return;
    bool any_in = false, any_done = false;
    {
        std::lock_guard<std::mutex> g(ep->mu);
        for (auto& p : *parsed) {
            if (p.conn->outbound) {
                auto it = ep->pend.find(p.corr);
                if (it != ep->pend.end() &&
                    it->second.st == P_WAIT) {
                    it->second.st = P_DONE;
                    it->second.data = std::move(p.body);
                    any_done = true;
                }
                // unknown corr: the waiter timed out and cancelled
            } else {
                uint32_t rs = 0, re = 0;
                bool keyed = rid_span(p.body->data(),
                                      (long)p.body->size(), &rs, &re);
                if (keyed && !ep->published.empty()) {
                    uint64_t t0 = tel::wall_ns();
                    std::string key;
                    key.reserve(p.body->size() - (re - rs));
                    key.append((const char*)p.body->data(), rs);
                    key.append((const char*)p.body->data() + re,
                               p.body->size() - re);
                    auto hit = ep->published.find(key);
                    if (hit != ep->published.end()) {
                        queue_reply(p.conn, p.corr, hit->second.data);
                        ep->native_answered++;
                        // dur = key build + lookup + reply queue: the
                        // native answer's whole serve cost (the wire
                        // halves live in the peer's own telemetry)
                        ep->tel.emit(
                            tel::TEL_EV_ANSWER, hit->second.kind,
                            tel::sat_u32(tel::wall_ns() - t0),
                            (uint32_t)hit->second.data->size(),
                            (uint32_t)ep->pub_gen);
                        continue;
                    }
                }
                ep->inq.push_back(
                    {p.conn->token, p.corr, std::move(p.body),
                     keyed ? rs : 0, keyed ? re : 0});
                any_in = true;
            }
        }
    }
    if (any_done) ep->cv_done.notify_all();
    if (any_in) ep->cv_in.notify_all();
    parsed->clear();
}

// Drain the write queue; ep->mu is taken only to peek/advance the
// queue, NEVER across the send() syscall.  Returns false when the conn
// must be dropped.
bool pump_write(Ep* ep, Conn* c) {
    for (;;) {
        Bytes cur;
        size_t off;
        {
            std::lock_guard<std::mutex> g(ep->mu);
            if (c->dead.load(std::memory_order_relaxed)) return true;
            if (c->wq.empty()) return true;
            cur = c->wq.front().data;
            off = c->wq.front().sent;
        }
        bool blocked = false;
        while (off < cur->size()) {
            ssize_t r = send(c->fd, cur->data() + off,
                             cur->size() - off, MSG_NOSIGNAL);
            if (r < 0) {
                if (errno == EAGAIN || errno == EWOULDBLOCK) {
                    blocked = true;
                    break;
                }
                return false;
            }
            off += (size_t)r;
        }
        std::lock_guard<std::mutex> g(ep->mu);
        // a concurrent nl_drop_peer / nl_set_peer may have cleared the
        // queue under us — re-check before touching the front
        if (c->dead.load(std::memory_order_relaxed) || c->wq.empty())
            return true;
        if (blocked) {
            c->wq.front().sent = off;
            return true;
        }
        c->wq.pop_front();
    }
}

// Close + erase a conn (event thread only, under ep->mu).
void reap(Ep* ep, std::vector<std::unique_ptr<Conn>>::iterator it) {
    Conn* c = it->get();
    if (c->outbound) {
        fail_corrs(ep, c);
        auto pit = ep->peers.find(c->peer);
        if (pit != ep->peers.end() && pit->second.conn == c)
            pit->second.conn = nullptr;
    }
    close(c->fd);
    ep->conns.erase(it);
}

void start_dials(Ep* ep) {
    for (auto& kv : ep->peers) {
        Peer& pr = kv.second;
        if (!pr.want_dial || pr.conn != nullptr) continue;
        pr.want_dial = false;
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_port = htons((uint16_t)pr.port);
        if (fd < 0 ||
            inet_pton(AF_INET, pr.host.c_str(), &addr.sin_addr) != 1) {
            if (fd >= 0) close(fd);
            fail_predial(ep, &pr);
            continue;
        }
        set_nonblock(fd);
        set_nodelay(fd);
        int rc = connect(fd, (sockaddr*)&addr, sizeof(addr));
        if (rc < 0 && errno != EINPROGRESS) {
            close(fd);
            fail_predial(ep, &pr);
            continue;
        }
        auto c = std::make_unique<Conn>();
        c->fd = fd;
        c->outbound = true;
        c->peer = kv.first;
        c->connecting = (rc < 0);
        for (auto& f : pr.predial) {
            c->sent_corrs.push_back(f.corr);
            c->wq.push_back(std::move(f));
        }
        pr.predial.clear();
        pr.conn = c.get();
        ep->conns.push_back(std::move(c));
    }
}

void event_loop(Ep* ep) {
    std::vector<pollfd> pfds;
    std::vector<Conn*> snap;
    std::vector<Parsed> parsed;
    for (;;) {
        ep->tel.beat();  // liveness: frozen count+wall = wedged thread
        pfds.clear();
        snap.clear();
        {
            std::lock_guard<std::mutex> g(ep->mu);
            if (ep->stop) break;
            // reap marked-dead conns before snapshotting fds: a revents
            // entry must never hit a conn whose fd was reused
            for (auto it = ep->conns.begin(); it != ep->conns.end();) {
                if ((*it)->dead.load(std::memory_order_relaxed)) {
                    reap(ep, it);
                    it = ep->conns.begin();  // iterator invalidated
                } else {
                    ++it;
                }
            }
            start_dials(ep);
            for (auto& c : ep->conns) {
                short ev = 0;
                if (c->connecting) {
                    ev = POLLOUT;
                } else {
                    ev = POLLIN;
                    if (!c->wq.empty()) ev |= POLLOUT;
                }
                snap.push_back(c.get());
                pfds.push_back({c->fd, ev, 0});
            }
        }
        size_t nsnap = snap.size();
        pfds.push_back({ep->listen_fd, POLLIN, 0});
        pfds.push_back({ep->wake_r, POLLIN, 0});
        if (poll(pfds.data(), pfds.size(), 1000) < 0 && errno != EINTR)
            break;
        if (pfds[nsnap + 1].revents & POLLIN) {
            uint8_t buf[256];
            while (read(ep->wake_r, buf, sizeof(buf)) > 0) {
            }
        }
        if (pfds[nsnap].revents & POLLIN) {
            for (;;) {
                int fd = accept(ep->listen_fd, nullptr, nullptr);
                if (fd < 0) break;
                set_nonblock(fd);
                set_nodelay(fd);
                auto c = std::make_unique<Conn>();
                c->fd = fd;
                std::lock_guard<std::mutex> g(ep->mu);
                c->token = ep->next_token++;
                ep->conns.push_back(std::move(c));
            }
        }
        // conns are created/erased ONLY by this thread, so the snapshot
        // pointers stay valid for the whole sweep; all socket IO below
        // runs WITHOUT ep->mu (holding it across syscalls convoys
        // every nl_send / nl_reply behind the event loop — measured at
        // ~0.9 ms per send under load before this split)
        for (size_t i = 0; i < nsnap; i++) {
            if (!pfds[i].revents) continue;
            Conn* c = snap[i];
            if (c->dead.load(std::memory_order_relaxed)) continue;
            bool ok = true;
            if (c->connecting) {
                if (pfds[i].revents & (POLLOUT | POLLERR | POLLHUP)) {
                    int err = 0;
                    socklen_t elen = sizeof(err);
                    getsockopt(c->fd, SOL_SOCKET, SO_ERROR, &err,
                               &elen);
                    if (err != 0) {
                        ok = false;
                    } else {
                        c->connecting = false;
                        ok = pump_write(ep, c);
                    }
                }
            } else {
                if (pfds[i].revents & (POLLERR | POLLNVAL))
                    ok = false;
                if (ok && (pfds[i].revents & POLLIN))
                    ok = pump_read(c, &parsed);
                if (ok && (pfds[i].revents & POLLOUT))
                    ok = pump_write(ep, c);
                // POLLHUP alone with readable data pending is handled
                // by pump_read returning false at EOF
            }
            if (!ok) {
                std::lock_guard<std::mutex> g(ep->mu);
                if (c->outbound) fail_corrs(ep, c);
                c->dead.store(true, std::memory_order_relaxed);
            }
        }
        deliver_all(ep, &parsed);
    }
    // teardown: fail every waiter, close every socket
    std::lock_guard<std::mutex> g(ep->mu);
    for (auto& c : ep->conns) {
        if (c->outbound) fail_corrs(ep, c.get());
        close(c->fd);
    }
    ep->conns.clear();
    for (auto& kv : ep->peers) {
        kv.second.conn = nullptr;
        fail_predial(ep, &kv.second);
    }
    for (auto& kv : ep->pend)
        if (kv.second.st == P_WAIT) kv.second.st = P_FAIL;
    ep->cv_done.notify_all();
    ep->cv_in.notify_all();
}

}  // namespace

extern "C" {

// Returns an opaque handle or 0 on failure.  Binds the listener
// immediately (port 0 = OS-assigned; see nl_port).
void* nl_create(const char* host, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
        bind(fd, (sockaddr*)&addr, sizeof(addr)) < 0 ||
        listen(fd, 128) < 0) {
        close(fd);
        return nullptr;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, (sockaddr*)&addr, &alen);
    set_nonblock(fd);
    auto* ep = new Ep();
    ep->listen_fd = fd;
    ep->port = ntohs(addr.sin_port);
    int pipefd[2];
    if (pipe(pipefd) < 0) {
        close(fd);
        delete ep;
        return nullptr;
    }
    ep->wake_r = pipefd[0];
    ep->wake_w = pipefd[1];
    set_nonblock(ep->wake_r);
    set_nonblock(ep->wake_w);
    ep->tel.beat();  // a watchdog probing before the thread's first
                     // iteration must see "just born", not "wedged"
    ep->thread = std::thread(event_loop, ep);
    return ep;
}

int nl_port(void* hp) { return ((Ep*)hp)->port; }

// Register / update a peer's address.  An existing connection to that
// peer is torn down (in-flight requests fail; the next send re-dials).
void nl_set_peer(void* hp, int peer, const char* host, int port) {
    Ep* ep = (Ep*)hp;
    std::lock_guard<std::mutex> g(ep->mu);
    Peer& pr = ep->peers[peer];
    bool changed = pr.host != host || pr.port != port;
    pr.host = host;
    pr.port = port;
    if (changed && pr.conn != nullptr) {
        fail_corrs(ep, pr.conn);
        pr.conn->dead = true;
        pr.conn = nullptr;
        wake(ep);
    }
}

// Queue a request to a peer; returns the correlation id (> 0),
// -1 unknown peer, -2 oversized, -3 endpoint closed.  Never blocks.
long long nl_send(void* hp, int peer, const uint8_t* data, long len) {
    Ep* ep = (Ep*)hp;
    if (len < 0 || (size_t)len > kMaxFrame) return -2;
    // frame built before taking the lock: the memcpy of a large
    // payload must not serialize other senders / the event loop
    auto frame = std::make_shared<std::vector<uint8_t>>(kHdr + len);
    wr_u32(frame->data(), (uint32_t)len);
    memcpy(frame->data() + kHdr, data, (size_t)len);
    {
        std::lock_guard<std::mutex> g(ep->mu);
        if (ep->stop) return -3;
        auto pit = ep->peers.find(peer);
        if (pit == ep->peers.end()) return -1;
        uint64_t corr = ep->next_corr++;
        wr_u64(frame->data() + 4, corr);
        ep->pend[corr] = Pending{};
        Peer& pr = pit->second;
        if (pr.conn != nullptr &&
            !pr.conn->dead.load(std::memory_order_relaxed)) {
            // compact the failure-sweep list once it outgrows the
            // truly-pending set: resolved corrs are gone from `pend`,
            // and a long-lived conn must not accumulate one entry per
            // RPC forever
            if (pr.conn->sent_corrs.size() >= 4096) {
                auto& sc = pr.conn->sent_corrs;
                size_t w = 0;
                for (uint64_t c2 : sc) {
                    auto it = ep->pend.find(c2);
                    if (it != ep->pend.end() &&
                        it->second.st == P_WAIT)
                        sc[w++] = c2;
                }
                sc.resize(w);
            }
            pr.conn->sent_corrs.push_back(corr);
            pr.conn->wq.push_back({frame, 0, corr});
        } else {
            pr.want_dial = true;
            pr.predial.push_back({frame, 0, corr});
        }
        wake(ep);
        return (long long)corr;
    }
}

// Wait for the reply to `corr`.  Returns:
//   > 0  bytes copied into out (entry consumed)
//   0    timeout (entry kept; wait again or nl_cancel)
//   -1   link failed / endpoint closed / unknown corr (entry consumed)
//   < -1 -(needed bytes): out too small, entry kept — retry bigger
long nl_wait(void* hp, unsigned long long corr, uint8_t* out, long cap,
             int timeout_ms) {
    Ep* ep = (Ep*)hp;
    std::unique_lock<std::mutex> lk(ep->mu);
    ep->cv_done.wait_for(
        lk, std::chrono::milliseconds(timeout_ms), [&] {
            if (ep->stop) return true;
            auto it = ep->pend.find(corr);
            return it == ep->pend.end() || it->second.st != P_WAIT;
        });
    auto it = ep->pend.find(corr);
    if (it == ep->pend.end()) return -1;
    if (it->second.st == P_WAIT) {
        if (ep->stop) {
            ep->pend.erase(it);
            return -1;
        }
        return 0;
    }
    if (it->second.st == P_FAIL) {
        ep->pend.erase(it);
        return -1;
    }
    long need = (long)it->second.data->size();
    if (need > cap) return -(need < 2 ? 2 : need);
    memcpy(out, it->second.data->data(), (size_t)need);
    ep->pend.erase(it);
    return need;
}

// Forget a pending request (after a timeout the caller abandons).
void nl_cancel(void* hp, unsigned long long corr) {
    Ep* ep = (Ep*)hp;
    std::lock_guard<std::mutex> g(ep->mu);
    ep->pend.erase(corr);
}

// Tear down the connection to a peer (stuck link): in-flight requests
// fail immediately; the next send re-dials fresh.
void nl_drop_peer(void* hp, int peer) {
    Ep* ep = (Ep*)hp;
    std::lock_guard<std::mutex> g(ep->mu);
    auto pit = ep->peers.find(peer);
    if (pit == ep->peers.end()) return;
    Peer& pr = pit->second;
    pr.want_dial = false;
    fail_predial(ep, &pr);
    if (pr.conn != nullptr) {
        fail_corrs(ep, pr.conn);
        pr.conn->dead = true;
        pr.conn = nullptr;
        wake(ep);
    }
}

// Receive a BATCH of inbound requests in one call — the GIL-economy
// path: a busy interpreter grants a worker one timeslice; draining the
// whole queue inside it collapses N GIL acquisitions into one (the
// same amortization a BEAM scheduler gets by running a vnode's mailbox
// to empty).  Packs up to max_msgs messages, each
// [8B conn token][8B corr][4B rid start][4B rid end][4B len][payload]
// — the rid span locates the per-request id inside the payload so the
// worker can splice it out when publishing the answer (0,0 = frame did
// not parse as a request tuple; never publishable).  Returns bytes
// written, 0 on timeout, -1 when the endpoint closed, or -(needed)
// when the FIRST message alone exceeds cap (message stays queued).
long nl_recv_batch(void* hp, uint8_t* out, long cap, int timeout_ms,
                   int max_msgs) {
    Ep* ep = (Ep*)hp;
    std::unique_lock<std::mutex> lk(ep->mu);
    ep->cv_in.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
        return ep->stop || !ep->inq.empty();
    });
    if (ep->stop) return -1;
    if (ep->inq.empty()) return 0;
    long written = 0;
    int n = 0;
    while (!ep->inq.empty() && n < max_msgs) {
        InMsg& m = ep->inq.front();
        long need = 28 + (long)m.payload->size();
        if (written + need > cap)
            return written > 0 ? written : -need;
        wr_u64(out + written, m.token);
        wr_u64(out + written + 8, m.corr);
        wr_u32(out + written + 16, m.rid_start);
        wr_u32(out + written + 20, m.rid_end);
        wr_u32(out + written + 24, (uint32_t)m.payload->size());
        memcpy(out + written + 28, m.payload->data(),
               m.payload->size());
        written += need;
        n++;
        ep->inq.pop_front();
    }
    return written;
}

// Publish one (request key -> reply payload) pair for the event
// thread to answer without Python (see the file header).  Replaces an
// existing entry; the table is a bounded FIFO — past the cap the
// oldest published key is evicted (its requests fall back to the
// Python path, which may re-publish).  Never blocks.  `gen` is the
// invalidation generation the publisher read (nl_pub_gen) BEFORE
// computing the answer: a clear that raced the handler bumped it, and
// the stale answer is silently dropped here instead of resurrecting
// into the freshly-cleared table.  `kind` is the rpc-kind id the
// Python side interned for this answer's RPC name (0 = unknown) — the
// TEL_EV_ANSWER event reports it so native answer latency is a
// per-kind family, not a flat count.
void nl_publish(void* hp, const uint8_t* key, long klen,
                const uint8_t* reply, long rlen,
                unsigned long long gen, int kind) {
    Ep* ep = (Ep*)hp;
    if (klen <= 0 || rlen < 0 || (size_t)rlen > kMaxFrame) return;
    auto data = std::make_shared<std::vector<uint8_t>>(reply,
                                                       reply + rlen);
    std::string k((const char*)key, (size_t)klen);
    std::lock_guard<std::mutex> g(ep->mu);
    if (ep->stop || gen != ep->pub_gen) return;
    auto it = ep->published.find(k);
    if (it == ep->published.end()) {
        ep->pub_order.push_back(k);
        ep->published.emplace(
            std::move(k), PubAns{std::move(data), (uint16_t)kind});
        while (ep->published.size() > ep->pub_cap &&
               !ep->pub_order.empty()) {
            ep->published.erase(ep->pub_order.front());
            ep->pub_order.pop_front();
        }
    } else {
        it->second = PubAns{std::move(data), (uint16_t)kind};
    }
}

// Drop every published answer (the wholesale invalidation Python
// calls on truncation / ring moves / ownership changes) and bump the
// generation so in-flight answers computed against the old state
// cannot publish after the clear.
void nl_publish_clear(void* hp) {
    Ep* ep = (Ep*)hp;
    std::lock_guard<std::mutex> g(ep->mu);
    ep->published.clear();
    ep->pub_order.clear();
    ep->pub_gen++;
}

// The current invalidation generation — read by the worker BEFORE it
// runs a handler whose answer it may publish (see nl_publish).
unsigned long long nl_pub_gen(void* hp) {
    Ep* ep = (Ep*)hp;
    std::lock_guard<std::mutex> g(ep->mu);
    return ep->pub_gen;
}

// Endpoint counters: out[0] = requests answered natively (no GIL),
// out[1] = live published entries, out[2] = inbound queue depth.
// Returns the number of slots filled.
int nl_counters(void* hp, unsigned long long* out, int n) {
    Ep* ep = (Ep*)hp;
    std::lock_guard<std::mutex> g(ep->mu);
    int filled = 0;
    if (n > 0) { out[0] = ep->native_answered; filled = 1; }
    if (n > 1) { out[1] = ep->published.size(); filled = 2; }
    if (n > 2) { out[2] = ep->inq.size(); filled = 3; }
    return filled;
}

// Wait until EVERY listed corr is terminal (or timeout), then pack all
// results in one call — a whole 2PC fan-out round costs the caller a
// single GIL re-acquisition.  Per corr: [1B status][4B len][payload]
// where status 0 = done (entry consumed), 1 = failed (consumed),
// 2 = still pending at timeout (kept: cancel or wait again).
// Returns bytes written, -1 endpoint closed, < -1 -(needed bytes).
long nl_collect(void* hp, const unsigned long long* corrs, int n,
                uint8_t* out, long cap, int timeout_ms) {
    Ep* ep = (Ep*)hp;
    std::unique_lock<std::mutex> lk(ep->mu);
    auto all_done = [&] {
        if (ep->stop) return true;
        for (int i = 0; i < n; i++) {
            auto it = ep->pend.find(corrs[i]);
            if (it != ep->pend.end() && it->second.st == P_WAIT)
                return false;
        }
        return true;
    };
    ep->cv_done.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                         all_done);
    if (ep->stop && n == 0) return -1;
    long need = 0;
    for (int i = 0; i < n; i++) {
        auto it = ep->pend.find(corrs[i]);
        need += 5;
        if (it != ep->pend.end() && it->second.st == P_DONE)
            need += (long)it->second.data->size();
    }
    if (need > cap) return -(need < 2 ? 2 : need);
    long pos = 0;
    for (int i = 0; i < n; i++) {
        auto it = ep->pend.find(corrs[i]);
        if (it == ep->pend.end() ||
            (ep->stop && it->second.st == P_WAIT) ||
            it->second.st == P_FAIL) {
            out[pos] = 1;
            wr_u32(out + pos + 1, 0);
            if (it != ep->pend.end()) ep->pend.erase(it);
            pos += 5;
        } else if (it->second.st == P_WAIT) {
            out[pos] = 2;
            wr_u32(out + pos + 1, 0);
            pos += 5;
        } else {
            out[pos] = 0;
            wr_u32(out + pos + 1, (uint32_t)it->second.data->size());
            memcpy(out + pos + 5, it->second.data->data(),
                   it->second.data->size());
            pos += 5 + (long)it->second.data->size();
            ep->pend.erase(it);
        }
    }
    return pos;
}

// Queue a reply to an inbound request.  Returns 1 if queued, 0 if the
// connection is gone (the client will retry; the at-most-once cache in
// Python answers without re-execution).
int nl_reply(void* hp, unsigned long long conn_token,
             unsigned long long corr, const uint8_t* data, long len) {
    Ep* ep = (Ep*)hp;
    if (len < 0 || (size_t)len > kMaxFrame) return 0;
    auto frame = std::make_shared<std::vector<uint8_t>>(kHdr + len);
    wr_u32(frame->data(), (uint32_t)len);
    wr_u64(frame->data() + 4, corr);
    memcpy(frame->data() + kHdr, data, (size_t)len);
    std::lock_guard<std::mutex> g(ep->mu);
    if (ep->stop) return 0;
    for (auto& c : ep->conns) {
        if (!c->outbound && c->token == conn_token && !c->dead) {
            c->wq.push_back({frame, 0, 0});
            wake(ep);
            return 1;
        }
    }
    return 0;
}

// Telemetry cursor — atomics only (no mutex, no syscall): safe as a
// PyDLL quick call from any thread, including inside lock regions.
// out[0]=head (next event number), out[1]=heartbeat count,
// out[2]=heartbeat wall-ns.  Returns slots filled.
int nl_tel_cursor(void* hp, unsigned long long* out, int n) {
    Ep* ep = (Ep*)hp;
    int filled = 0;
    if (n > 0) {
        out[0] = ep->tel.head.load(std::memory_order_acquire);
        filled = 1;
    }
    if (n > 1) {
        out[1] = ep->tel.hb_count.load(std::memory_order_relaxed);
        filled = 2;
    }
    if (n > 2) {
        out[2] = ep->tel.hb_wall_ns.load(std::memory_order_relaxed);
        filled = 3;
    }
    return filled;
}

// Bulk-copy events from the caller's cursor into buf (max_events *
// 32 B).  Lock-free but a real memcpy of up to 128 KiB — CDLL class
// (GIL released), never inside a lock region.  Returns events copied;
// *new_tail advances past everything considered, *dropped counts
// events overwritten before/during the copy (see tel_ring.h).
long nl_tel_drain(void* hp, unsigned long long tail, uint8_t* buf,
                  long max_events, unsigned long long* new_tail,
                  unsigned long long* dropped) {
    Ep* ep = (Ep*)hp;
    uint64_t nt = 0, dr = 0;
    long n = ep->tel.drain(tail, buf, max_events, &nt, &dr);
    *new_tail = nt;
    *dropped = dr;
    return n;
}

// Flip event recording (heartbeats keep beating either way) — one
// relaxed atomic store: PyDLL quick class.
void nl_tel_enable(void* hp, int on) {
    ((Ep*)hp)->tel.enabled.store(on ? 1 : 0,
                                 std::memory_order_relaxed);
}

// Stop the event loop and fail every waiter.  Safe to call while other
// threads are blocked in nl_recv / nl_wait — they return closed.  The
// handle stays valid until nl_free.
void nl_shutdown(void* hp) {
    Ep* ep = (Ep*)hp;
    {
        std::lock_guard<std::mutex> g(ep->mu);
        if (ep->stop) return;
        ep->stop = true;
        ep->cv_in.notify_all();
        ep->cv_done.notify_all();
    }
    wake(ep);
    ep->thread.join();
    close(ep->listen_fd);
    close(ep->wake_r);
    close(ep->wake_w);
}

// Free the handle.  Only after nl_shutdown AND after every thread that
// could touch the handle has returned.
void nl_free(void* hp) { delete (Ep*)hp; }

}  // extern "C"

// Per-op ORSWOT apply loop in C++ — the honest *upper bound* on what the
// reference's BEAM materializer hot loop (reference
// src/clocksi_materializer.erl:145-171 materialize_intern + antidote_crdt
// set_aw update) can do per scheduler core: one op at a time, hash-map
// state, generic observed-remove set semantics.  BEAM runs the same
// algorithm with immutable terms and a reduction-counting interpreter, so
// ops/s(BEAM) <= ops/s(this loop); reporting device_ops / cpp_ops is a
// conservative bound on the true device-vs-BEAM ratio (BASELINE.md asks
// for the BEAM yardstick; no Erlang runtime exists in this image, so we
// bound it instead of guessing).
//
// C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <chrono>
#include <unordered_map>
#include <vector>

namespace {

struct Dot {
    int32_t dc;
    int64_t seq;
};

// state per (key, elem): live dot list (tiny — semantics of ORSWOT keep
// one dot per writing DC in steady state)
struct KeyState {
    std::unordered_map<int32_t, std::vector<Dot>> elems;
};

}  // namespace

extern "C" {

// Applies n_ops sequentially; returns elapsed seconds.  Arrays are the
// same synthetic stream the Python baseline consumes: key[i], is_add[i],
// elem[i], dot_dc[i], dot_seq[i].
double orset_baseline_run(int64_t n_ops, const int64_t* key,
                          const uint8_t* is_add, const int32_t* elem,
                          const int32_t* dot_dc, const int64_t* dot_seq,
                          int64_t* out_live_dots) {
    std::unordered_map<int64_t, KeyState> states;
    auto t0 = std::chrono::steady_clock::now();
    for (int64_t i = 0; i < n_ops; i++) {
        KeyState& st = states[key[i]];
        std::vector<Dot>& dots = st.elems[elem[i]];
        // observed = snapshot of current dots (the downstream "observed
        // context", reference antidote_crdt_set_aw:downstream)
        std::vector<Dot> observed = dots;
        // remove observed dots (generic set difference, as BEAM does)
        std::vector<Dot> next;
        next.reserve(dots.size() + 1);
        for (const Dot& d : dots) {
            bool seen = false;
            for (const Dot& o : observed)
                if (o.dc == d.dc && o.seq == d.seq) { seen = true; break; }
            if (!seen) next.push_back(d);
        }
        if (is_add[i]) next.push_back(Dot{dot_dc[i], dot_seq[i]});
        dots.swap(next);
    }
    auto t1 = std::chrono::steady_clock::now();
    // fold a checksum so the optimizer cannot dead-code the loop
    int64_t live = 0;
    for (auto& [k, st] : states)
        for (auto& [e, dots] : st.elems) live += (int64_t)dots.size();
    *out_live_dots = live;
    return std::chrono::duration<double>(t1 - t0).count();
}

}  // extern "C"

"""On-demand g++ build of the native components (no pip/pybind11 in this
environment — plain C ABI + ctypes)."""

from __future__ import annotations

import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")
_LOCK = threading.Lock()


def lib_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, f"lib{name}.so")


def _src_mtime(src: str) -> float:
    """Staleness input: the .cpp AND every shared header beside it —
    a ring-layout change in tel_ring.h must rebuild both planes."""
    m = os.path.getmtime(src)
    for f in os.listdir(_NATIVE_DIR):
        if f.endswith(".h"):
            m = max(m, os.path.getmtime(os.path.join(_NATIVE_DIR, f)))
    return m


def ensure_built(name: str) -> str | None:
    """Compile antidote_tpu/native/<name>.cpp into lib<name>.so if stale.
    Returns the .so path, or None if no compiler is available."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
    out = lib_path(name)
    with _LOCK:
        if os.path.exists(out) and os.path.getmtime(out) >= _src_mtime(src):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               "-pthread", src, "-o", out]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
        except (FileNotFoundError, subprocess.CalledProcessError):
            return None
        return out

// Native-plane flight recorder ring (ISSUE 16) — the telemetry face of
// the GIL-free fabric.  PR 11 moved the hottest serving paths into C++,
// which made them invisible to the PR-6 observability plane; this ring
// is how they report back WITHOUT re-introducing the GIL/lock costs the
// move removed (Dapper's always-on low-overhead discipline, PAPERS.md).
//
// Shape: a fixed array of 32-byte events plus one monotonically
// increasing head.  The producer is wait-free — it writes the slot at
// ``head & (cap-1)`` and release-stores head+1; when the consumer lags
// the producer simply overwrites (never blocks, never allocates).  The
// consumer (Python's 50 ms drain) reads head, bulk-copies, re-reads
// head, and discards the prefix a concurrent overwrite may have torn —
// every lost event is COUNTED into ``dropped``, so backpressure is a
// statistic, not a stall.
//
// Producer discipline: each ring has at most one producer at a time.
// nodelink's ring is written only by the endpoint's event thread;
// fabric's ring is written by whichever thread holds the hub mutex at
// an existing lock site — in both cases emission adds ZERO mutex
// crossings and ZERO GIL acquisitions to the hot answer/publish paths
// (the [gil-policy] contract).
//
// The event layout and drain semantics are mirrored bit-for-bit by the
// pure-Python ``_PyRing`` twin in antidote_tpu/obs/nativeobs.py (the
// ``_PyLog`` pattern): tests assert byte-identical streams.

#ifndef ANTIDOTE_TPU_NATIVE_TEL_RING_H_
#define ANTIDOTE_TPU_NATIVE_TEL_RING_H_

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

namespace tel {

// Event kinds — mirrored by EVENT_KINDS in antidote_tpu/obs/nativeobs.py;
// the static-suite native-telemetry pass pins the two tables together.
enum : uint16_t {
    TEL_EV_ANSWER = 1,       // nodelink: RPC answered natively (no GIL)
    TEL_EV_PUB_STAGE = 2,    // fabric: frame framed + staged for fan-out
    TEL_EV_SUB_ENQUEUE = 3,  // fabric: frame queued on one subscriber
    TEL_EV_SUB_DRAIN = 4,    // fabric: frame fully written to a socket
    TEL_EV_DROP = 5,         // fabric: overflowing subscriber dropped
};

// One fixed-width slot.  32 bytes so a 4096-slot ring is two pages of
// cache-friendly sequential writes; Python decodes with struct format
// "<QIIHHIQ" (little-endian, matching every target we compile for).
struct TelEvent {
    uint64_t t_ns;    // wall-clock ns (CLOCK_REALTIME — comparable to
                      // Python time.time_ns(), so spans line up)
    uint32_t dur_ns;  // stage duration, saturated at ~4.29 s
    uint32_t bytes;   // payload / frame size
    uint16_t ev;      // TEL_EV_*
    uint16_t aux16;   // ANSWER: rpc-kind id; PUB_STAGE: queued count;
                      // SUB_*: fd low 16; DROP: low-16 frame hash
    uint32_t seq;     // fabric: publish sequence; nodelink: pub_gen
    uint64_t pad;     // reserved — keeps the slot 32 B / power of two
};
static_assert(sizeof(TelEvent) == 32, "TelEvent must stay 32 bytes");

inline uint64_t wall_ns() {
    timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

inline uint32_t sat_u32(uint64_t v) {
    return v > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)v;
}

struct TelRing {
    static constexpr uint64_t kCap = 4096;  // power of two (index mask)
    TelEvent slots[kCap];
    //: next event number; monotonic, never wraps (the slot index is
    //: ``head & (kCap-1)``) — the consumer's cursor lives in Python
    std::atomic<uint64_t> head{0};
    std::atomic<int> enabled{1};
    //: event-thread liveness: count bumps once per loop iteration and
    //: wall_ns records when — a wedged thread freezes both, which is
    //: exactly what the stall watchdog alarms on
    std::atomic<uint64_t> hb_count{0};
    std::atomic<uint64_t> hb_wall_ns{0};

    // Producer side — wait-free: one relaxed load, one slot write, one
    // release store.  Overwrite-on-full by construction.
    void emit(uint16_t ev, uint16_t aux16, uint32_t dur_ns,
              uint32_t bytes, uint32_t seq) {
        if (!enabled.load(std::memory_order_relaxed)) return;
        uint64_t h = head.load(std::memory_order_relaxed);
        TelEvent& e = slots[h & (kCap - 1)];
        e.t_ns = wall_ns();
        e.dur_ns = dur_ns;
        e.bytes = bytes;
        e.ev = ev;
        e.aux16 = aux16;
        e.seq = seq;
        e.pad = 0;
        head.store(h + 1, std::memory_order_release);
    }

    void beat() {
        hb_count.fetch_add(1, std::memory_order_relaxed);
        hb_wall_ns.store(wall_ns(), std::memory_order_relaxed);
    }

    // Consumer side.  Copies up to max_events events starting at the
    // caller's cursor ``tail`` into buf, advancing *new_tail past
    // every event CONSIDERED (copied or lost).  *dropped counts events
    // the producer overwrote before/during the copy: the lag beyond
    // kCap plus the torn prefix.  Torn rule: a producer writing event
    // e overwrites slot e&(kCap-1) BEFORE publishing head=e+1, so any
    // copied index <= head2 - kCap may be mid-overwrite — the prefix
    // up to and including that index is discarded, never returned.
    long drain(uint64_t tail, uint8_t* buf, long max_events,
               uint64_t* new_tail, uint64_t* dropped) {
        *dropped = 0;
        uint64_t h1 = head.load(std::memory_order_acquire);
        if (tail > h1) tail = h1;        // bogus cursor: clamp forward
        if (h1 - tail > kCap) {          // lagged past the ring: skip
            *dropped += h1 - tail - kCap;
            tail = h1 - kCap;
        }
        uint64_t avail = h1 - tail;
        uint64_t n = max_events < 0 ? 0
                     : (avail < (uint64_t)max_events
                            ? avail : (uint64_t)max_events);
        for (uint64_t i = 0; i < n; i++)
            memcpy(buf + i * sizeof(TelEvent),
                   &slots[(tail + i) & (kCap - 1)], sizeof(TelEvent));
        uint64_t h2 = head.load(std::memory_order_acquire);
        uint64_t torn = 0;
        // indices <= h2 - kCap may be torn (see the rule above)
        if (h2 >= kCap && h2 - kCap + 1 > tail) {
            torn = h2 - kCap + 1 - tail;
            if (torn > n) torn = n;
            if (torn > 0 && torn < n)
                memmove(buf, buf + torn * sizeof(TelEvent),
                        (size_t)(n - torn) * sizeof(TelEvent));
            *dropped += torn;
        }
        *new_tail = tail + n;
        return (long)(n - torn);
    }
};

}  // namespace tel

#endif  // ANTIDOTE_TPU_NATIVE_TEL_RING_H_

// Native inter-DC publish hub — the erlzmq PUB socket role (reference
// src/inter_dc_pub.erl:87-92 binds a ZMQ PUB via a C NIF; zmq_utils /
// zmq_context are native components of the reference's runtime).
//
// One event thread per hub: accepts subscribers on a listening TCP
// socket, consumes their one-frame hello, and drains per-subscriber
// bounded send queues with non-blocking writes.  The publisher's commit
// path (fab_publish) only copies the frame into each queue — it never
// touches a socket, so a stalled peer costs the publisher nothing; a
// subscriber whose queue overflows is dropped (ZMQ's drop-on-slow PUB
// semantics; the peer resubscribes and gap-repairs).
//
// Framing: 4-byte big-endian length prefix, matching the Python
// transport (antidote_tpu/interdc/tcp.py) byte-for-byte — Python
// subscribers and the native hub interoperate.
//
// C ABI for ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <memory>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

#include "tel_ring.h"

namespace {

constexpr size_t kMaxQueueBytes = 64u << 20;  // per-subscriber cap
constexpr size_t kMaxFrame = 64u << 20;

struct Sub {
    int fd;
    bool hello_done = false;      // first inbound frame pending
    bool dead = false;            // marked by the publisher; only the
                                  // event thread closes fds (fd reuse
                                  // during a poll snapshot would let a
                                  // stale revents hit a new subscriber)
    size_t hello_remaining = 0;   // bytes of hello left to skip
    uint8_t hello_hdr[4];
    size_t hello_hdr_got = 0;
    //: framed bytes (header included), shared across subscribers so a
    //: broadcast is one allocation regardless of fan-out
    std::deque<std::shared_ptr<const std::string>> queue;
    size_t queued_bytes = 0;
    size_t sent_in_head = 0;        // progress within queue.front()
    //: telemetry shadows of `queue` (ISSUE 16): enqueue wall-ns and
    //: publish seq per frame, popped in lockstep — queue-wait latency
    //: and hub frame age come from the front entries
    std::deque<uint64_t> enq_ns;
    std::deque<uint32_t> enq_seq;
};

struct Hub {
    int listen_fd = -1;
    int wake_r = -1, wake_w = -1;   // self-pipe: publisher -> event loop
    uint16_t port = 0;
    std::thread thread;
    std::mutex mu;                  // guards subs' queues + stop flag
    std::vector<std::unique_ptr<Sub>> subs;
    bool stop = false;
    //: flight-recorder ring (ISSUE 16): every emit site below already
    //: holds `mu`, so the ring sees one producer at a time with zero
    //: ADDED mutex crossings on the publish path
    tel::TelRing tel;
    uint64_t pub_seq = 0;           // fab_publish sequence (under mu)
    //: wall-ns of the oldest frame still queued on any subscriber
    //: (0 = none) — refreshed by the event loop each sweep; Python's
    //: drain turns it into the hub-frame-age gauge without locking
    std::atomic<uint64_t> oldest_enq_ns{0};
};

// FNV-1a over the frame payload — the DROP event's last-frame identity
// (low 16 bits).  Computed only on the drop path, never per publish.
uint16_t frame_hash16(const uint8_t* data, int len) {
    uint64_t h = 1469598103934665603ull;
    for (int i = 0; i < len; i++) {
        h ^= data[i];
        h *= 1099511628211ull;
    }
    return (uint16_t)(h ^ (h >> 16));
}

void set_nonblock(int fd) {
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

void wake(Hub* h) {
    uint8_t b = 1;
    ssize_t r = write(h->wake_w, &b, 1);
    (void)r;  // pipe full = loop already awake
}

// Returns false when the subscriber must be dropped.
bool pump_hello(Sub* s) {
    // consume [4-byte len][len bytes] without interpreting it
    while (s->hello_hdr_got < 4) {
        ssize_t r = read(s->fd, s->hello_hdr + s->hello_hdr_got,
                         4 - s->hello_hdr_got);
        if (r == 0) return false;
        if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
        s->hello_hdr_got += (size_t)r;
        if (s->hello_hdr_got == 4) {
            uint32_t n;
            memcpy(&n, s->hello_hdr, 4);
            n = ntohl(n);
            if (n > kMaxFrame) return false;
            s->hello_remaining = n;
        }
    }
    uint8_t buf[4096];
    while (s->hello_remaining > 0) {
        size_t want = s->hello_remaining < sizeof(buf)
                          ? s->hello_remaining : sizeof(buf);
        ssize_t r = read(s->fd, buf, want);
        if (r == 0) return false;
        if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
        s->hello_remaining -= (size_t)r;
    }
    s->hello_done = true;
    return true;
}

// Returns false when the subscriber must be dropped.  Runs on the
// event thread under h->mu; the SUB_DRAIN emit therefore adds no
// mutex crossing of its own.
bool pump_send(Hub* h, Sub* s) {
    while (!s->queue.empty()) {
        const std::string& head = *s->queue.front();
        while (s->sent_in_head < head.size()) {
            ssize_t r = send(s->fd, head.data() + s->sent_in_head,
                             head.size() - s->sent_in_head, MSG_NOSIGNAL);
            if (r < 0) {
                if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
                return false;
            }
            s->sent_in_head += (size_t)r;
        }
        // frame fully on the wire: dur = enqueue -> last byte written
        // (queue wait + send), the subscriber-queue-wait histogram
        if (!s->enq_ns.empty()) {
            h->tel.emit(tel::TEL_EV_SUB_DRAIN, (uint16_t)s->fd,
                        tel::sat_u32(tel::wall_ns() - s->enq_ns.front()),
                        (uint32_t)head.size(), s->enq_seq.front());
            s->enq_ns.pop_front();
            s->enq_seq.pop_front();
        }
        s->queued_bytes -= head.size();
        s->queue.pop_front();
        s->sent_in_head = 0;
    }
    return true;
}

void event_loop(Hub* h) {
    for (;;) {
        h->tel.beat();  // liveness: frozen count+wall = wedged thread
        std::vector<pollfd> pfds;
        pfds.push_back({h->listen_fd, POLLIN, 0});
        pfds.push_back({h->wake_r, POLLIN, 0});
        {
            std::lock_guard<std::mutex> g(h->mu);
            if (h->stop) break;
            // reap publisher-marked subscribers first (queue overflow)
            for (auto it = h->subs.begin(); it != h->subs.end();) {
                if ((*it)->dead) {
                    close((*it)->fd);
                    it = h->subs.erase(it);
                } else {
                    ++it;
                }
            }
            uint64_t oldest = 0;
            for (auto& s : h->subs) {
                short ev = 0;
                if (!s->hello_done) ev |= POLLIN;
                if (!s->queue.empty()) ev |= POLLOUT;
                pfds.push_back({s->fd, ev, 0});
                if (!s->enq_ns.empty() &&
                    (oldest == 0 || s->enq_ns.front() < oldest))
                    oldest = s->enq_ns.front();
            }
            h->oldest_enq_ns.store(oldest, std::memory_order_relaxed);
        }
        if (poll(pfds.data(), pfds.size(), 1000) < 0 && errno != EINTR)
            break;
        // drain wakeups
        if (pfds[1].revents & POLLIN) {
            uint8_t buf[256];
            while (read(h->wake_r, buf, sizeof(buf)) > 0) {
            }
        }
        if (pfds[0].revents & POLLIN) {
            for (;;) {
                int fd = accept(h->listen_fd, nullptr, nullptr);
                if (fd < 0) break;
                set_nonblock(fd);
                int one = 1;
                setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one,
                           sizeof(one));
                auto s = std::make_unique<Sub>();
                s->fd = fd;
                std::lock_guard<std::mutex> g(h->mu);
                h->subs.push_back(std::move(s));
            }
        }
        std::lock_guard<std::mutex> g(h->mu);
        if (h->stop) break;
        // pfds[2 + i] lines up with subs[i] only if the set did not
        // change since the snapshot; match by fd instead
        for (size_t pi = 2; pi < pfds.size(); pi++) {
            if (!pfds[pi].revents) continue;
            for (auto it = h->subs.begin(); it != h->subs.end(); ++it) {
                Sub* s = it->get();
                if (s->fd != pfds[pi].fd) continue;
                if (s->dead) break;
                bool ok = true;
                if (pfds[pi].revents & (POLLERR | POLLHUP | POLLNVAL))
                    ok = false;
                if (ok && (pfds[pi].revents & POLLIN) && !s->hello_done)
                    ok = pump_hello(s);
                if (ok && (pfds[pi].revents & POLLOUT))
                    ok = pump_send(h, s);
                if (!ok) {
                    close(s->fd);
                    h->subs.erase(it);
                }
                break;
            }
        }
    }
    // teardown
    std::lock_guard<std::mutex> g(h->mu);
    for (auto& s : h->subs) close(s->fd);
    h->subs.clear();
}

}  // namespace

extern "C" {

// Returns an opaque handle (heap pointer) or 0 on failure.
void* fab_create(const char* host, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        close(fd);
        return nullptr;
    }
    if (bind(fd, (sockaddr*)&addr, sizeof(addr)) < 0 ||
        listen(fd, 64) < 0) {
        close(fd);
        return nullptr;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, (sockaddr*)&addr, &alen);
    set_nonblock(fd);

    auto* h = new Hub();
    h->listen_fd = fd;
    h->port = ntohs(addr.sin_port);
    int pipefd[2];
    if (pipe(pipefd) < 0) {
        close(fd);
        delete h;
        return nullptr;
    }
    h->wake_r = pipefd[0];
    h->wake_w = pipefd[1];
    set_nonblock(h->wake_r);
    set_nonblock(h->wake_w);
    h->tel.beat();  // a watchdog probing before the thread's first
                    // iteration must see "just born", not "wedged"
    h->thread = std::thread(event_loop, h);
    return h;
}

int fab_port(void* hp) { return ((Hub*)hp)->port; }

// Broadcast one frame; returns the publish SEQUENCE (> 0, monotonic —
// the span-attribution handle telemetry events carry), or -1 on a bad
// length.  Never blocks: the event thread does the socket writes.  The
// per-subscriber queued count rides the PUB_STAGE event's aux16.
long long fab_publish(void* hp, const uint8_t* data, int len) {
    Hub* h = (Hub*)hp;
    if (len < 0 || (size_t)len > kMaxFrame) return -1;
    uint64_t t0 = tel::wall_ns();
    auto framed = std::make_shared<std::string>();
    framed->resize(4 + (size_t)len);
    uint32_t be = htonl((uint32_t)len);
    memcpy(&(*framed)[0], &be, 4);
    memcpy(&(*framed)[4], data, (size_t)len);
    int queued = 0;
    uint64_t seq;
    {
        std::lock_guard<std::mutex> g(h->mu);
        seq = ++h->pub_seq;
        uint64_t enq = tel::wall_ns();
        for (auto& s : h->subs) {
            if (s->dead) continue;
            if (s->queued_bytes + framed->size() > kMaxQueueBytes) {
                // overflowing subscriber: mark for the event thread to
                // drop (resubscribe + gap-repair); never close here
                s->dead = true;
                h->tel.emit(tel::TEL_EV_DROP, frame_hash16(data, len),
                            0, (uint32_t)len, (uint32_t)seq);
                continue;
            }
            s->queue.push_back(framed);
            s->enq_ns.push_back(enq);
            s->enq_seq.push_back((uint32_t)seq);
            s->queued_bytes += framed->size();
            queued++;
            h->tel.emit(tel::TEL_EV_SUB_ENQUEUE, (uint16_t)s->fd, 0,
                        (uint32_t)len, (uint32_t)seq);
        }
        // staging duration: frame copy + fan-out pushes (under mu, so
        // the ring stays single-producer with zero added crossings)
        h->tel.emit(tel::TEL_EV_PUB_STAGE, (uint16_t)queued,
                    tel::sat_u32(tel::wall_ns() - t0), (uint32_t)len,
                    (uint32_t)seq);
    }
    wake(h);
    return (long long)seq;
}

int fab_sub_count(void* hp) {
    Hub* h = (Hub*)hp;
    std::lock_guard<std::mutex> g(h->mu);
    return (int)h->subs.size();
}

// Total bytes queued across every live subscriber's bounded queue —
// the backpressure face of the hub for the FABRIC_* gauges (a rising
// value means a peer is draining slower than the stream publishes).
long long fab_queued_bytes(void* hp) {
    Hub* h = (Hub*)hp;
    std::lock_guard<std::mutex> g(h->mu);
    long long total = 0;
    for (auto& s : h->subs)
        if (!s->dead) total += (long long)s->queued_bytes;
    return total;
}

// Telemetry cursor — atomics only (no mutex, no syscall): safe as a
// PyDLL quick call from any thread, including inside lock regions.
// out[0]=head (next event number), out[1]=heartbeat count,
// out[2]=heartbeat wall-ns, out[3]=oldest queued frame's enqueue
// wall-ns (0 = hub queues empty).  Returns slots filled.
int fab_tel_cursor(void* hp, unsigned long long* out, int n) {
    Hub* h = (Hub*)hp;
    int filled = 0;
    if (n > 0) {
        out[0] = h->tel.head.load(std::memory_order_acquire);
        filled = 1;
    }
    if (n > 1) {
        out[1] = h->tel.hb_count.load(std::memory_order_relaxed);
        filled = 2;
    }
    if (n > 2) {
        out[2] = h->tel.hb_wall_ns.load(std::memory_order_relaxed);
        filled = 3;
    }
    if (n > 3) {
        out[3] = h->oldest_enq_ns.load(std::memory_order_relaxed);
        filled = 4;
    }
    return filled;
}

// Bulk-copy events from the caller's cursor into buf (max_events *
// 32 B).  Lock-free but a real memcpy of up to 128 KiB — CDLL class
// (GIL released), never inside a lock region.  Returns events copied;
// *new_tail advances past everything considered, *dropped counts
// events overwritten before/during the copy (see tel_ring.h).
long fab_tel_drain(void* hp, unsigned long long tail, uint8_t* buf,
                   long max_events, unsigned long long* new_tail,
                   unsigned long long* dropped) {
    Hub* h = (Hub*)hp;
    uint64_t nt = 0, dr = 0;
    long n = h->tel.drain(tail, buf, max_events, &nt, &dr);
    *new_tail = nt;
    *dropped = dr;
    return n;
}

// Flip event recording (heartbeats keep beating either way) — one
// relaxed atomic store: PyDLL quick class.
void fab_tel_enable(void* hp, int on) {
    ((Hub*)hp)->tel.enabled.store(on ? 1 : 0,
                                  std::memory_order_relaxed);
}

void fab_close(void* hp) {
    Hub* h = (Hub*)hp;
    {
        std::lock_guard<std::mutex> g(h->mu);
        h->stop = true;
    }
    wake(h);
    h->thread.join();
    close(h->listen_fd);
    close(h->wake_r);
    close(h->wake_w);
    delete h;
}

}  // extern "C"

// Durable append-only op log — native core.
//
// The reference persists per-partition op logs via Erlang disk_log with
// optional fsync-on-commit (reference src/logging_vnode.erl:896-919,
// :157-162).  This is the C++ equivalent: a single-file append log with
// CRC-framed records, explicit flush/fsync control (buffered appends on
// the update path, sync only on commit), and crash recovery that scans
// to the last valid record and truncates a torn tail.
//
// Record framing: [u32 len][u32 crc32(payload)][payload].
// All integers little-endian.  Exposed through a C ABI for ctypes.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
    if (crc_init_done) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t crc32(const uint8_t* buf, size_t len) {
    crc_init();
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < len; i++)
        c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

struct OpLog {
    int fd = -1;
    FILE* wf = nullptr;     // buffered append stream
    int64_t end = 0;        // logical end (valid data) in bytes
    std::string path;
};

constexpr size_t kHeader = 8;

}  // namespace

extern "C" {

void* oplog_open(const char* path, int create) {
    OpLog* log = new OpLog();
    log->path = path;
    int flags = O_RDWR | (create ? O_CREAT : 0);
    log->fd = ::open(path, flags, 0644);
    if (log->fd < 0) { delete log; return nullptr; }
    log->wf = fdopen(dup(log->fd), "ab");
    if (!log->wf) { ::close(log->fd); delete log; return nullptr; }
    struct stat st;
    fstat(log->fd, &st);
    log->end = st.st_size;
    return log;
}

// Scan from the start, validating framing + CRC; truncate at the first
// corrupt/partial record.  Returns the recovered end offset (-1 on error).
int64_t oplog_recover(void* h) {
    OpLog* log = static_cast<OpLog*>(h);
    struct stat st;
    if (fstat(log->fd, &st) != 0) return -1;
    int64_t size = st.st_size, off = 0;
    uint8_t hdr[kHeader];
    std::string buf;
    while (off + (int64_t)kHeader <= size) {
        if (pread(log->fd, hdr, kHeader, off) != (ssize_t)kHeader) break;
        uint32_t len, crc;
        memcpy(&len, hdr, 4);
        memcpy(&crc, hdr + 4, 4);
        if (len == 0 || off + (int64_t)kHeader + len > size) break;
        buf.resize(len);
        if (pread(log->fd, &buf[0], len, off + kHeader) != (ssize_t)len) break;
        if (crc32(reinterpret_cast<const uint8_t*>(buf.data()), len) != crc)
            break;
        off += kHeader + len;
    }
    if (off < size) {
        if (ftruncate(log->fd, off) != 0) return -1;
    }
    log->end = off;
    // reposition the buffered writer after truncation
    fflush(log->wf);
    fseeko(log->wf, 0, SEEK_END);
    return off;
}

// Like oplog_recover but resumes validation at `start` — a durable
// watermark the caller trusts (a checkpoint cut, ISSUE 10): a torn
// tail can only live at the END of an append-only file, bytes below
// the cut were validated by the run that wrote them, and every later
// read re-checks its record's CRC anyway — so open-time recovery cost
// becomes O(suffix), not O(file).  Returns the recovered end offset;
// -2 when `start` is not a valid record boundary (the caller falls
// back to the full scan — a bogus resume point must never truncate
// good data), -1 on error.
int64_t oplog_recover_from(void* h, int64_t start) {
    OpLog* log = static_cast<OpLog*>(h);
    struct stat st;
    if (fstat(log->fd, &st) != 0) return -1;
    int64_t size = st.st_size, off = start;
    if (start < 0 || start > size) return -2;
    uint8_t hdr[kHeader];
    std::string buf;
    bool validated_one = false;
    while (off + (int64_t)kHeader <= size) {
        if (pread(log->fd, hdr, kHeader, off) != (ssize_t)kHeader) break;
        uint32_t len, crc;
        memcpy(&len, hdr, 4);
        memcpy(&crc, hdr + 4, 4);
        if (len == 0 || off + (int64_t)kHeader + len > size) break;
        buf.resize(len);
        if (pread(log->fd, &buf[0], len, off + kHeader) != (ssize_t)len) break;
        if (crc32(reinterpret_cast<const uint8_t*>(buf.data()), len) != crc)
            break;
        off += kHeader + len;
        validated_one = true;
    }
    if (off < size && !validated_one)
        return -2;  // first suffix record invalid: bogus start or a
                    // tail torn right at the cut — full scan decides
    if (off < size) {
        if (ftruncate(log->fd, off) != 0) return -1;
    }
    log->end = off;
    fflush(log->wf);
    fseeko(log->wf, 0, SEEK_END);
    return off;
}

// Append one record; returns its start offset, or -1.
int64_t oplog_append(void* h, const uint8_t* data, int64_t len) {
    OpLog* log = static_cast<OpLog*>(h);
    uint8_t hdr[kHeader];
    uint32_t len32 = (uint32_t)len;
    uint32_t crc = crc32(data, (size_t)len);
    memcpy(hdr, &len32, 4);
    memcpy(hdr + 4, &crc, 4);
    if (fwrite(hdr, 1, kHeader, log->wf) != kHeader) return -1;
    if (fwrite(data, 1, (size_t)len, log->wf) != (size_t)len) return -1;
    int64_t off = log->end;
    log->end += kHeader + len;
    return off;
}

// Append n records with ONE call and ONE buffered write (the group-
// commit drain's crossing): `data` is the records' payloads
// concatenated, `lens` their lengths.  Each record gets the standard
// [len][crc] frame, so the on-disk bytes are identical to n
// oplog_append calls.  Returns the FIRST record's offset, or -1.
int64_t oplog_append_batch(void* h, const uint8_t* data,
                           const int64_t* lens, int64_t n) {
    OpLog* log = static_cast<OpLog*>(h);
    int64_t total = 0;
    for (int64_t i = 0; i < n; i++) {
        if (lens[i] <= 0) return -1;
        total += (int64_t)kHeader + lens[i];
    }
    if (total == 0) return log->end;
    std::string buf;
    buf.reserve((size_t)total);
    const uint8_t* p = data;
    for (int64_t i = 0; i < n; i++) {
        uint32_t len32 = (uint32_t)lens[i];
        uint32_t crc = crc32(p, (size_t)lens[i]);
        buf.append(reinterpret_cast<const char*>(&len32), 4);
        buf.append(reinterpret_cast<const char*>(&crc), 4);
        buf.append(reinterpret_cast<const char*>(p), (size_t)lens[i]);
        p += lens[i];
    }
    if (fwrite(buf.data(), 1, buf.size(), log->wf) != buf.size())
        return -1;
    int64_t off = log->end;
    log->end += total;
    return off;
}

int oplog_flush(void* h) {
    OpLog* log = static_cast<OpLog*>(h);
    return fflush(log->wf) == 0 ? 0 : -1;
}

// fsync-on-commit path (reference ?SYNC_LOG / append_commit).
int oplog_sync(void* h) {
    OpLog* log = static_cast<OpLog*>(h);
    if (fflush(log->wf) != 0) return -1;
    return fsync(log->fd) == 0 ? 0 : -1;
}

int64_t oplog_end_offset(void* h) {
    return static_cast<OpLog*>(h)->end;
}

// Read the record at `offset` into buf (capacity buflen).  Returns the
// payload length (caller retries with a larger buffer if > buflen),
// -1 on EOF/corruption.
int64_t oplog_read(void* h, int64_t offset, uint8_t* buf, int64_t buflen) {
    OpLog* log = static_cast<OpLog*>(h);
    fflush(log->wf);
    if (offset + (int64_t)kHeader > log->end) return -1;
    uint8_t hdr[kHeader];
    if (pread(log->fd, hdr, kHeader, offset) != (ssize_t)kHeader) return -1;
    uint32_t len, crc;
    memcpy(&len, hdr, 4);
    memcpy(&crc, hdr + 4, 4);
    if (offset + (int64_t)kHeader + len > log->end) return -1;
    if ((int64_t)len > buflen) return (int64_t)len;  // tell caller the size
    if (pread(log->fd, buf, len, offset + kHeader) != (ssize_t)len) return -1;
    if (crc32(buf, len) != crc) return -1;
    return (int64_t)len;
}

// Offset of the record following the one at `offset` (-1 past end).
int64_t oplog_next(void* h, int64_t offset) {
    OpLog* log = static_cast<OpLog*>(h);
    if (offset + (int64_t)kHeader > log->end) return -1;
    uint8_t hdr[kHeader];
    if (pread(log->fd, hdr, kHeader, offset) != (ssize_t)kHeader) return -1;
    uint32_t len;
    memcpy(&len, hdr, 4);
    int64_t nxt = offset + kHeader + len;
    return nxt <= log->end ? nxt : -1;
}

void oplog_close(void* h) {
    OpLog* log = static_cast<OpLog*>(h);
    fclose(log->wf);
    ::close(log->fd);
    delete log;
}

}  // extern "C"

"""Flight recorder — bounded per-subsystem rings of structured events.

The BEAM's crash-dump/`observer` story rebuilt for the serving stack:
every plane appends cheap structured events (a deque append — safe on
hot paths) into its own bounded ring, and on an anomaly — txn abort,
error-monitor trip, probe violation — the WHOLE recorder state dumps
to a JSON file, giving forensics the cross-subsystem record of the
window leading up to the event (the ISSUE 1 ``_publish``-window
evidence the round-6 set_aw hunt needs).

Dumps are rate-limited per reason so an abort storm cannot flood the
disk; ``force=True`` (probe violations) bypasses the limit.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger(__name__)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


class FlightRecorder:
    def __init__(self, capacity: int = 512,
                 dump_dir: Optional[str] = None,
                 min_dump_interval_s: float = 1.0,
                 max_dumps: int = 64):
        #: events kept per subsystem ring
        self.capacity = capacity
        #: where dump() writes; default under the system tempdir so a
        #: bare AntidoteTPU() (no data_dir plumbing) still dumps
        self.dump_dir = dump_dir or os.path.join(
            tempfile.gettempdir(), "antidote_obs")
        self.min_dump_interval_s = min_dump_interval_s
        #: dump files retained on disk — oldest deleted beyond this, so
        #: a long-lived process under a steady abort trickle (aborts are
        #: normal operation, one dump/s passes the rate limit) cannot
        #: fill the disk or grow ``dumps`` without bound
        self.max_dumps = max_dumps
        self._rings: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._last_dump: Dict[str, float] = {}
        #: paths written by dump(), oldest first (tests assert on it)
        self.dumps: List[str] = []
        #: per-subsystem count of events evicted by ring overflow —
        #: /healthz surfaces it so a flooded ring (events silently
        #: falling out before the dump that needs them) is visible
        #: BEFORE a forensic dump comes back empty
        self._dropped: Dict[str, int] = {}

    # ------------------------------------------------------------ recording

    def record(self, subsystem: str, kind: str, **fields) -> None:
        """Append one event; hot-path cheap (no serialization — fields
        stay live objects until a dump walks them)."""
        ring = self._rings.get(subsystem)
        if ring is None:
            with self._lock:
                ring = self._rings.setdefault(
                    subsystem, deque(maxlen=self.capacity))
        if len(ring) == ring.maxlen:
            # racy under threads, but a lock here would tax every hot-
            # path record for a diagnostic that only needs magnitude
            self._dropped[subsystem] = self._dropped.get(subsystem, 0) + 1
        ring.append((time.time_ns() // 1000, kind, fields))

    # -------------------------------------------------------------- queries

    def events(self, subsystem: Optional[str] = None,
               kind: Optional[str] = None) -> List[tuple]:
        """(t_us, kind, fields) tuples, oldest first."""
        with self._lock:
            if subsystem is not None:
                rings = [self._rings.get(subsystem, ())]
            else:
                rings = list(self._rings.values())
            out = [e for ring in rings for e in list(ring)]
        out.sort(key=lambda e: e[0])
        if kind is not None:
            out = [e for e in out if e[1] == kind]
        return out

    def snapshot(self) -> Dict[str, List[dict]]:
        """JSON-ready view of every ring (newest last)."""
        with self._lock:
            rings = {name: list(ring)
                     for name, ring in self._rings.items()}
        return {
            name: [{"t_us": t, "kind": k,
                    "fields": {f: _jsonable(v) for f, v in fs.items()}}
                   for t, k, fs in ring]
            for name, ring in rings.items()
        }

    def drop_counts(self) -> Dict[str, int]:
        """{subsystem: events evicted by ring overflow} since start/clear
        (the /healthz ring-occupancy signal)."""
        return dict(self._dropped)

    def ring_fill(self) -> Dict[str, float]:
        """{subsystem: fill fraction 0..1} of each ring."""
        with self._lock:
            return {name: len(ring) / (ring.maxlen or 1)
                    for name, ring in self._rings.items()}

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()
            self._last_dump.clear()
            self.dumps.clear()
            self._dropped.clear()

    def last_dump_age_s(self) -> float:
        """Seconds since the most recent dump under ANY reason (inf if
        never) — lets secondary triggers (the error monitor reacting to
        an anomaly's own ERROR log line) coalesce with the dump the
        primary trigger already wrote."""
        with self._lock:
            if not self._last_dump:
                return float("inf")
            return time.monotonic() - max(self._last_dump.values())

    # ---------------------------------------------------------------- dumps

    def dump(self, reason: str, extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write the full recorder state (+ the tracer's recent spans)
        to ``dump_dir``; returns the path, or None when rate-limited.
        Never raises: a forensic dump failing must not compound the
        anomaly it is recording."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason, -1e18)
            if not force and now - last < self.min_dump_interval_s:
                return None
            self._last_dump[reason] = now
        try:
            from antidote_tpu.obs.spans import tracer

            body = {
                "reason": reason,
                "at_us": time.time_ns() // 1000,
                "pid": os.getpid(),
                "extra": _jsonable(extra or {}),
                "events": self.snapshot(),
                "recent_spans": [s.to_trace_event()
                                 for s in tracer.spans()[-256:]],
            }
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                f"flightrec_{reason}_{time.time_ns() // 1000}.json")
            with open(path, "w") as f:
                json.dump(body, f)
            with self._lock:
                self.dumps.append(path)
                evicted = self.dumps[:-self.max_dumps] \
                    if len(self.dumps) > self.max_dumps else []
                del self.dumps[:len(evicted)]
            for old in evicted:
                try:
                    os.remove(old)
                except OSError:
                    pass  # already gone / foreign file: retention is best-effort
            log.warning("flight recorder dumped (%s) -> %s", reason, path)
            return path
        except Exception:  # noqa: BLE001 — forensics must not throw
            log.debug("flight-recorder dump failed", exc_info=True)
            return None


#: process-wide recorder (all DCs share it, like stats.registry)
recorder = FlightRecorder()

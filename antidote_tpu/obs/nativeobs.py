"""nativeobs — the Python face of the native-plane flight recorder
(ISSUE 16).

PR 11 moved the hottest serving paths (native RPC answers, staged
publish fan-out) into C++ event threads the PR-6 observability plane
cannot see.  The native planes now record fixed 32-byte events into
wait-free overwrite-on-full rings (antidote_tpu/native/tel_ring.h);
this module is everything Python does with them:

- the event-kind table and the kind -> stats-family mapping the
  static-suite native-telemetry pass pins against the C++ enum;
- ``decode_events`` / ``TelEvent`` — the struct layout (``<QIIHHIQ``,
  32 bytes, little-endian) mirrored against the C++ static_assert;
- ``_PyRing`` — a pure-Python twin of the C++ ring (the ``_PyLog``
  pattern from oplog/log.py): byte-identical emit/drain semantics,
  so the drain tests run with or without a toolchain;
- ``fold_events`` — turns a drained batch into the NATIVE_* metric
  families and injects synthetic ``native_answer``/``native_fanout``
  spans into the sampled trace stream (tools/txn_journey.py shows
  native hops with per-stage deltas);
- ``NativeStallWatchdog`` — turns the rings' heartbeats into
  detection: a wedged event thread past the threshold force-dumps
  the flight recorder with the /debug/pipeline snapshot embedded.

Nothing here runs on a native hot path: drains ride the existing
50 ms gauge cadence (interdc/tcp.py) and the gossip tick
(cluster/node.py), and the producer side is pure C++.
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional

from antidote_tpu import stats

# ---------------------------------------------------------------- layout

#: one ring slot: t_ns, dur_ns, bytes, ev, aux16, seq, pad — pinned
#: against the 32-byte static_assert in native/tel_ring.h
EVENT_STRUCT = struct.Struct("<QIIHHIQ")
EVENT_SIZE = EVENT_STRUCT.size

#: slots per ring (power of two, mirrors tel::TelRing::kCap)
RING_CAPACITY = 4096

EV_ANSWER = 1
EV_PUB_STAGE = 2
EV_SUB_ENQUEUE = 3
EV_SUB_DRAIN = 4
EV_DROP = 5

#: event id -> name, mirroring the TEL_EV_* enum in native/tel_ring.h
#: (the static-suite native-telemetry pass diffs the two tables)
EVENT_KINDS = {
    EV_ANSWER: "answer",
    EV_PUB_STAGE: "pub_stage",
    EV_SUB_ENQUEUE: "sub_enqueue",
    EV_SUB_DRAIN: "sub_drain",
    EV_DROP: "drop",
}

#: every event kind the C++ recorder can emit -> the stats families
#: its drain folds it into.  The static-suite pass walks THIS table:
#: a kind with no row, or a family that is not registered in stats.py
#: or documented in monitoring/, fails the suite — a native event
#: kind cannot ship dark.
EVENT_FAMILIES = {
    "answer": ("antidote_native_answer_latency_seconds",),
    "pub_stage": ("antidote_native_pub_stage_seconds",),
    "sub_enqueue": ("antidote_native_sub_enqueued_total",),
    "sub_drain": ("antidote_native_sub_queue_wait_seconds",),
    "drop": ("antidote_native_sub_dropped_total",),
}


class TelEvent(NamedTuple):
    t_ns: int    # wall-clock ns at emission (CLOCK_REALTIME)
    dur_ns: int  # stage duration (saturated u32)
    bytes: int   # payload / frame size
    ev: int      # EV_*
    aux16: int   # answer: kind id; pub_stage: queued count;
                 # sub_*: fd low16; drop: low-16 frame hash
    seq: int     # fabric: publish seq (low 32); nodelink: pub_gen


def decode_events(buf, n: int) -> List[TelEvent]:
    """Decode ``n`` packed slots from a drain buffer (pad dropped)."""
    return [TelEvent(*EVENT_STRUCT.unpack_from(buf, i * EVENT_SIZE)[:6])
            for i in range(n)]


# ------------------------------------------------------- kind interning

class KindInterner:
    """RPC-kind string <-> uint16 id table.  Python interns the kind at
    ``nl_publish`` time (the worker path — never the native answer
    path) and the drain maps TEL_EV_ANSWER's aux16 back to the name.
    Id 0 is reserved for "unknown" (a full table stops interning
    rather than wrapping)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids: Dict[str, int] = {}
        self._names: Dict[int, str] = {0: "?"}

    def id_of(self, kind) -> int:
        k = str(kind)
        with self._lock:
            i = self._ids.get(k)
            if i is None:
                if len(self._ids) >= 0xFFFF:
                    return 0
                i = len(self._ids) + 1
                self._ids[k] = i
                self._names[i] = k
            return i

    def name_of(self, i: int) -> str:
        with self._lock:
            return self._names.get(i, "?")


#: process-wide, like stats.registry — kind ids must mean the same
#: thing to every endpoint's drain in the process
kind_interner = KindInterner()


# ------------------------------------------------------------- _PyRing

class _PyRing:
    """Pure-Python twin of the C++ TelRing (the ``_PyLog`` pattern):
    same slot bytes, same monotonic head, same overwrite-on-full and
    torn-prefix drain rules — tests assert byte-identical streams
    against the C++ ring, and the drain tests still run where no
    toolchain exists.  Single-threaded by construction (a Python
    'producer' would hold the GIL anyway), so the torn-prefix rule
    only fires on the full-ring edge the C++ side also discards."""

    def __init__(self, cap: int = RING_CAPACITY):
        assert cap & (cap - 1) == 0, "capacity must be a power of two"
        self._cap = cap
        self._slots = [bytes(EVENT_SIZE)] * cap
        self.head = 0
        self.enabled = True
        self.hb_count = 0
        self.hb_wall_ns = 0

    def emit(self, ev: int, aux16: int, dur_ns: int, bytes_: int,
             seq: int, t_ns: Optional[int] = None) -> None:
        if not self.enabled:
            return
        if t_ns is None:
            t_ns = time.time_ns()
        self._slots[self.head & (self._cap - 1)] = EVENT_STRUCT.pack(
            t_ns, min(int(dur_ns), 0xFFFFFFFF), int(bytes_) & 0xFFFFFFFF,
            ev, int(aux16) & 0xFFFF, int(seq) & 0xFFFFFFFF, 0)
        self.head += 1

    def beat(self) -> None:
        self.hb_count += 1
        self.hb_wall_ns = time.time_ns()

    def cursor(self):
        """(head, hb_count, hb_wall_ns) — the PyDLL quick-read shape."""
        return (self.head, self.hb_count, self.hb_wall_ns)

    def drain(self, tail: int, max_events: int):
        """-> (payload bytes, new_tail, dropped): the C++ drain's
        semantics exactly, including the conservative discard of
        indices <= head - cap (a C++ producer may be mid-overwrite
        there; the twin discards them too so streams stay identical)."""
        dropped = 0
        h1 = self.head
        if tail > h1:
            tail = h1
        if h1 - tail > self._cap:
            dropped += h1 - tail - self._cap
            tail = h1 - self._cap
        n = min(h1 - tail, max(0, max_events))
        out = b"".join(self._slots[(tail + i) & (self._cap - 1)]
                       for i in range(n))
        torn = 0
        if h1 >= self._cap and h1 - self._cap + 1 > tail:
            torn = min(n, h1 - self._cap + 1 - tail)
            out = out[torn * EVENT_SIZE:]
            dropped += torn
        return out, tail + n, dropped


# ---------------------------------------------------------------- folds

def fold_events(events: List[TelEvent], *,
                seq_txids: Optional[Dict[int, tuple]] = None,
                reg: Optional["stats.Registry"] = None,
                max_answer_spans: int = 32) -> int:
    """Fold one drained batch into the NATIVE_* families and inject
    synthetic spans into the sampled trace stream.  Returns the event
    count folded (the bench's events-per-drain numerator).

    - ``answer`` -> per-kind native answer latency + (rate-thinned,
      capped) ``native_answer`` spans;
    - ``pub_stage``/``sub_enqueue``/``sub_drain`` -> staging / fan-out
      / queue-wait families; a ``sub_drain`` whose publish seq the
      transport attributed to sampled txids emits one
      ``native_fanout`` span per txid (span start = the frame's
      enqueue instant, duration = queue wait + send) — the native hop
      tools/txn_journey.py shows;
    - ``drop`` -> drop counter + a flight-recorder event carrying the
      last-frame identity (hash16, publish seq, size).
    """
    from antidote_tpu.obs.events import recorder
    from antidote_tpu.obs.spans import tracer

    reg = reg or stats.registry
    spans_left = max_answer_spans
    fanout_done = set()
    for e in events:
        kind = EVENT_KINDS.get(e.ev)
        if kind == "answer":
            name = kind_interner.name_of(e.aux16)
            reg.native_answer_latency.observe(e.dur_ns / 1e9, kind=name)
            # untagged spans thin via the tracer's counter-hash rate —
            # the cap keeps a hot answer plane from evicting sampled
            # txn trees out of the span ring
            if spans_left > 0 and tracer.sampled(None):
                spans_left -= 1
                tracer.record_span(
                    "native_answer", "native", None,
                    (e.t_ns - e.dur_ns) // 1000,
                    max(1, e.dur_ns // 1000),
                    kind=name, bytes=e.bytes)
        elif kind == "pub_stage":
            reg.native_pub_stage.observe(e.dur_ns / 1e9)
        elif kind == "sub_enqueue":
            reg.native_sub_enqueued.inc()
        elif kind == "sub_drain":
            reg.native_sub_queue_wait.observe(e.dur_ns / 1e9)
            if seq_txids and e.seq not in fanout_done:
                txids = seq_txids.get(e.seq)
                if txids:
                    # one span per txid on the FIRST subscriber drain
                    # of the frame (the fan-out's critical path)
                    fanout_done.add(e.seq)
                    for txid in txids:
                        tracer.record_span(
                            "native_fanout", "native", txid,
                            (e.t_ns - e.dur_ns) // 1000,
                            max(1, e.dur_ns // 1000),
                            pub_seq=e.seq, bytes=e.bytes)
        elif kind == "drop":
            reg.native_sub_dropped.inc()
            recorder.record(
                "native_fabric", "sub_drop", frame_hash16=e.aux16,
                pub_seq=e.seq, frame_bytes=e.bytes, t_ns=e.t_ns)
    return len(events)


def publish_ring_gauges(ring: str, hb_wall_ns: int, dropped_total: int,
                        head: int, tail: int, *,
                        oldest_enq_ns: Optional[int] = None,
                        now_ns: Optional[int] = None,
                        reg: Optional["stats.Registry"] = None) -> None:
    """Set the per-ring gauges a drain refreshes: heartbeat age,
    cumulative overwrite losses, and (fabric only) hub frame age."""
    reg = reg or stats.registry
    now_ns = time.time_ns() if now_ns is None else now_ns
    age = max(0.0, (now_ns - hb_wall_ns) / 1e9) if hb_wall_ns else 0.0
    reg.native_heartbeat_age.set(age, ring=ring)
    reg.native_ring_dropped.set(dropped_total, ring=ring)
    del head, tail  # occupancy lives in /debug/pipeline, not a gauge
    if oldest_enq_ns is not None:
        reg.native_frame_age.set(
            max(0.0, (now_ns - oldest_enq_ns) / 1e9)
            if oldest_enq_ns else 0.0)


def heartbeat_age_s(hb_wall_ns: int,
                    now_ns: Optional[int] = None) -> Optional[float]:
    """Seconds since a ring's last heartbeat (None = never beat)."""
    if not hb_wall_ns:
        return None
    now_ns = time.time_ns() if now_ns is None else now_ns
    return max(0.0, (now_ns - hb_wall_ns) / 1e9)


# ------------------------------------------------------------- watchdog

class NativeStallWatchdog:
    """Heartbeat -> detection: registered probes report each native
    event thread's last-beat wall-ns; ``check()`` (riding the gossip
    tick / gauge cadence — no thread of its own) force-dumps the
    flight recorder with the /debug/pipeline snapshot embedded when a
    probe's age crosses the threshold.  One dump per stall episode:
    the tripped latch re-arms only after the heartbeat recovers, so a
    wedged thread cannot storm the dump dir past the recorder's own
    rate limit."""

    def __init__(self, threshold_s: float = 5.0):
        #: stall age that trips a dump; <= 0 disables (the
        #: Config.native_watchdog_s knob lands here at node start)
        self.threshold_s = threshold_s
        self._lock = threading.Lock()
        self._probes: Dict[str, Callable[[], int]] = {}
        self._tripped: Dict[str, bool] = {}

    def register(self, name: str, probe: Callable[[], int]) -> None:
        """``probe() -> hb_wall_ns`` (0/raise = unknown, skipped)."""
        with self._lock:
            self._probes[name] = probe
            self._tripped.pop(name, None)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._probes.pop(name, None)
            self._tripped.pop(name, None)

    def ages(self, now_ns: Optional[int] = None) -> Dict[str, Optional[float]]:
        """{ring name: heartbeat age seconds (None = unknown)}."""
        now_ns = time.time_ns() if now_ns is None else now_ns
        with self._lock:
            probes = dict(self._probes)
        out: Dict[str, Optional[float]] = {}
        for name, probe in probes.items():
            try:
                out[name] = heartbeat_age_s(probe(), now_ns)
            except Exception:  # noqa: BLE001 — a closed lib is "unknown"
                out[name] = None
        return out

    def check(self, now_ns: Optional[int] = None) -> List[str]:
        """Names newly past the threshold (and the dump they caused)."""
        if self.threshold_s <= 0:
            return []
        ages = self.ages(now_ns)
        newly: List[str] = []
        with self._lock:
            for name, age in ages.items():
                if age is None:
                    continue
                if age >= self.threshold_s:
                    if not self._tripped.get(name):
                        self._tripped[name] = True
                        newly.append(name)
                else:
                    self._tripped[name] = False
        if newly:
            from antidote_tpu.obs import pipeline
            from antidote_tpu.obs.events import recorder
            try:
                snap = pipeline.snapshot()
            except Exception:  # noqa: BLE001 — forensics must not throw
                snap = {"error": "pipeline snapshot failed"}
            recorder.dump(
                "native_stall", force=True,
                extra={
                    "stalled": newly,
                    "threshold_s": self.threshold_s,
                    "heartbeat_ages_s": ages,
                    "pipeline": snap,
                })
        return newly


#: process-wide watchdog (the drains register probes; NodeServer's
#: gossip tick and the transport's gauge cadence call check())
watchdog = NativeStallWatchdog()

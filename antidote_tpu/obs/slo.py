"""Declarative SLOs with error budgets over registered metric
families (ISSUE 17).

Every signal the system emits today is judged by a human reading
Grafana.  This module makes the judgment itself machine-readable: an
:class:`Objective` binds a registered metric family to a target, an
observation window and a burn-rate threshold; :func:`evaluate` turns
a samples dict (obs/fleet.py's parsed-exposition shape — local
registry or fleet-merged) into a structured verdict the chaos plane,
``tools/slo_report.py`` and the ``/debug/health`` endpoint all serve
verbatim.

Three objective kinds, one burn-rate algebra:

- ``quantile``  — histogram family; the fraction of observations
  above ``target`` is the bad-event fraction, the error budget is
  ``1 - quantile`` (p99 => 1% of events may be slow), and
  ``burn_rate = bad_fraction / budget_fraction``.  Judged per label
  group (per peer, per DC, per source) — the WORST group decides,
  because "p99 fine on average" is exactly the lie a per-peer SLO
  exists to catch.
- ``counter_max`` — counter family; the summed value (delta against
  an optional ``baseline`` samples snapshot, clamped >= 0) must not
  exceed ``target``.  ``target == 0`` means any event at all exhausts
  the budget (probe violations, subscriber drops).
- ``gauge_max`` — gauge family; the worst child value must stay
  under ``target`` (heartbeat age, checkpoint age).

``burn_rate <= burn_threshold`` (default 1.0 = the budget exactly
spent) is the ok line; ``budget_remaining = max(0, 1 - burn_rate)``.
Burn rates are capped at :data:`BURN_CAP` so verdicts stay strict
JSON — ``Infinity`` is not JSON, and a zero-target breach reports the
cap instead.

Counters and histograms are cumulative since process start, so an
absolute evaluation conflates ancient history with now; callers that
need "over the window" semantics snapshot samples at window start and
pass them as ``baseline`` (``tools/slo_report.py --baseline /
--save-baseline``).  ``/debug/health`` serves the since-process-start
verdict, which is the right default for a freshly deployed node and
is documented as such in monitoring/README.md.

The DEFAULT_OBJECTIVES registry below is test-pinned and swept by the
``static_suite`` slo-coverage pass: every family must be registered
in stats.py and every objective documented in monitoring/README.md's
"SLO objectives" table, both directions.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

#: burn-rate cap: verdicts must stay strict JSON (``Infinity`` is
#: not), so a zero-target objective with any bad event reports this
BURN_CAP = 1e9


@dataclass(frozen=True)
class Objective:
    """One SLO: a metric family, a target, and the budget algebra
    knobs.  ``kind`` selects the evaluator (see module docstring)."""

    name: str
    family: str
    kind: str            # "quantile" | "counter_max" | "gauge_max"
    target: float
    quantile: float = 0.99       # quantile kind only
    window_s: float = 3600.0     # the window a baseline should span
    burn_threshold: float = 1.0  # burn rate at which ok flips false
    description: str = ""


#: the shipped SLO registry — swept by static_suite's slo-coverage
#: pass (family registered in stats.py, objective documented in
#: monitoring/README.md, both directions) and pinned by
#: tests/unit/test_slo.py.  Targets are deliberately loose: these are
#: availability floors for the chaos plane to gate on, not perf bars
#: (bench_gate owns those).
DEFAULT_OBJECTIVES: Tuple[Objective, ...] = (
    Objective(
        name="visibility_lag_p99",
        family="antidote_vis_visibility_lag_seconds",
        kind="quantile", target=5.0, quantile=0.99,
        description="remote-update visibility lag p99 per (dc, peer) "
                    "— the GentleRain headline metric"),
    Objective(
        name="commit_latency_p99",
        family="antidote_txn_commit_latency_seconds",
        kind="quantile", target=1.0, quantile=0.99,
        description="local commit latency p99"),
    Objective(
        name="probe_violations",
        family="antidote_vis_probe_violations_total",
        kind="counter_max", target=0.0,
        description="causal-probe ordering violations — zero is the "
                    "contract (Cure's atomic visibility)"),
    Objective(
        name="probe_staleness_p99",
        family="antidote_vis_probe_staleness_seconds",
        kind="quantile", target=5.0, quantile=0.99,
        description="causal-probe write-to-read round-trip p99"),
    Objective(
        name="native_heartbeat_fresh",
        family="antidote_native_heartbeat_age_seconds",
        kind="gauge_max", target=30.0,
        description="native event-thread heartbeat age per ring — a "
                    "stalled ring ages past this"),
    Objective(
        name="subscriber_drops",
        family="antidote_native_sub_dropped_total",
        kind="counter_max", target=0.0,
        description="native hub subscriber frame drops"),
    Objective(
        name="checkpoint_age",
        family="antidote_ckpt_age_seconds",
        kind="gauge_max", target=600.0,
        description="newest checkpoint age per partition — recovery "
                    "replay cost grows past this"),
)


def _grouped(series, drop=("le",)):
    """rows -> {label-key-tuple: rows}, dropping the bucket label so
    one histogram child's cumulative series stays together."""
    groups: Dict[tuple, list] = {}
    for labels, value in series:
        key = tuple(sorted((k, v) for k, v in labels.items()
                           if k not in drop))
        groups.setdefault(key, []).append((labels, value))
    return groups


def _base_index(baseline, sample_name):
    if not baseline:
        return {}
    return {tuple(sorted(labels.items())): value
            for labels, value in baseline.get(sample_name, [])}


def _result(obj: Objective, ok: bool, burn: float, no_data: bool,
            worst: Optional[dict], extra: Optional[dict] = None):
    burn = min(float(burn), BURN_CAP)
    out = {
        "ok": bool(ok),
        "kind": obj.kind,
        "family": obj.family,
        "target": obj.target,
        "window_s": obj.window_s,
        "burn_threshold": obj.burn_threshold,
        "burn_rate": round(burn, 6),
        "budget_remaining": round(max(0.0, 1.0 - burn), 6),
        "no_data": bool(no_data),
        "worst": worst,
        "description": obj.description,
    }
    if obj.kind == "quantile":
        out["quantile"] = obj.quantile
    if extra:
        out.update(extra)
    return out


def _eval_quantile(obj: Objective, samples, baseline):
    bucket_name = obj.family + "_bucket"
    base_idx = _base_index(baseline, bucket_name)
    worst = None
    total_all = bad_all = 0.0
    for gkey, rows in _grouped(samples.get(bucket_name, ())).items():
        by_le: Dict[float, float] = {}
        for labels, value in rows:
            le = labels.get("le")
            if le is None:
                continue
            try:
                bound = float(le)
            except ValueError:
                continue
            base = base_idx.get(tuple(sorted(labels.items())), 0.0)
            by_le[bound] = max(value - base, 0.0)
        if not by_le:
            continue
        les = sorted(by_le)
        total = by_le[les[-1]]  # the +Inf cumulative tail
        if total <= 0:
            continue
        # exposition buckets are cumulative: the count at the first
        # bound >= target is the good-event count
        good = total
        for le in les:
            if le >= obj.target:
                good = by_le[le]
                break
        bad = max(total - good, 0.0)
        want = obj.quantile * total
        p_est = les[-1]
        for le in les:
            if by_le[le] >= want:
                p_est = le
                break
        allowed = max(1.0 - obj.quantile, 1e-9)
        burn = min((bad / total) / allowed, BURN_CAP)
        total_all += total
        bad_all += bad
        if worst is None or burn > worst["burn_rate"]:
            worst = {"labels": dict(gkey), "burn_rate": round(burn, 6),
                     "p_estimate": (None if p_est == float("inf")
                                    else p_est),
                     "total": total, "bad": bad}
    if worst is None:
        return _result(obj, ok=True, burn=0.0, no_data=True,
                       worst=None)
    burn = worst["burn_rate"]
    return _result(obj, ok=burn <= obj.burn_threshold, burn=burn,
                   no_data=False, worst=worst,
                   extra={"observations": total_all,
                          "bad_events": bad_all})


def _eval_counter(obj: Objective, samples, baseline):
    base_idx = _base_index(baseline, obj.family)
    worst = None
    total = 0.0
    seen = False
    for labels, value in samples.get(obj.family, ()):
        seen = True
        delta = max(
            value - base_idx.get(tuple(sorted(labels.items())), 0.0),
            0.0)
        total += delta
        if worst is None or delta > worst["value"]:
            worst = {"labels": dict(labels), "value": delta}
    if not seen:
        return _result(obj, ok=True, burn=0.0, no_data=True,
                       worst=None)
    if obj.target <= 0:
        burn = 0.0 if total <= 0 else BURN_CAP
    else:
        burn = total / obj.target
    return _result(obj, ok=burn <= obj.burn_threshold, burn=burn,
                   no_data=False, worst=worst,
                   extra={"value": total})


def _eval_gauge(obj: Objective, samples, baseline):
    worst = None
    for labels, value in samples.get(obj.family, ()):
        if worst is None or value > worst["value"]:
            worst = {"labels": dict(labels), "value": value}
    if worst is None:
        return _result(obj, ok=True, burn=0.0, no_data=True,
                       worst=None)
    if obj.target <= 0:
        burn = 0.0 if worst["value"] <= 0 else BURN_CAP
    else:
        burn = max(worst["value"], 0.0) / obj.target
    return _result(obj, ok=burn <= obj.burn_threshold, burn=burn,
                   no_data=False, worst=worst)


_KINDS = {"quantile": _eval_quantile,
          "counter_max": _eval_counter,
          "gauge_max": _eval_gauge}


def evaluate(samples, objectives: Optional[Iterable[Objective]] = None,
             baseline=None) -> dict:
    """Judge ``samples`` (obs/fleet.py shape) against the objectives.

    Returns the verdict dict: ``{at_us, ok, failing, objectives}``
    where each objective entry carries the full budget arithmetic
    (burn_rate, budget_remaining, worst offender with its labels).
    ``baseline`` (same samples shape) turns cumulative counter and
    histogram families into window deltas — missing baseline series
    are treated as zero."""
    objectives = (DEFAULT_OBJECTIVES if objectives is None
                  else tuple(objectives))
    per: Dict[str, dict] = {}
    for obj in objectives:
        try:
            ev = _KINDS[obj.kind]
        except KeyError:
            raise ValueError(
                f"objective {obj.name!r}: unknown kind {obj.kind!r}")
        per[obj.name] = ev(obj, samples, baseline)
    failing = sorted(n for n, v in per.items() if not v["ok"])
    return {"at_us": time.time_ns() // 1000,
            "ok": not failing,
            "failing": failing,
            "objectives": per}


def refresh_gauges(verdict: dict) -> None:
    """Mirror a verdict into the SLO_* gauge families so Grafana's
    error-budget panels ride the normal scrape path."""
    from antidote_tpu import stats

    for name, v in verdict.get("objectives", {}).items():
        stats.registry.slo_burn_rate.set(
            v["burn_rate"], objective=name)
        stats.registry.slo_budget_remaining.set(
            v["budget_remaining"], objective=name)
        stats.registry.slo_ok.set(
            1.0 if v["ok"] else 0.0, objective=name)


def evaluate_registry(reg=None, objectives=None, baseline=None) -> dict:
    """Evaluate one process's own registry (the ``/debug/health``
    path).  Round-trips through the exposition text so the local and
    fleet paths are judged by identical parsing rules."""
    from antidote_tpu import stats
    from antidote_tpu.obs import fleet

    reg = stats.registry if reg is None else reg
    samples = fleet.parse_prometheus_text(reg.exposition())
    verdict = evaluate(samples, objectives=objectives,
                       baseline=baseline)
    if reg is stats.registry:
        refresh_gauges(verdict)
    return verdict


def health_json() -> str:
    """The ``/debug/health`` body: the local registry's verdict,
    cumulative since process start (see module docstring)."""
    import json

    return json.dumps(evaluate_registry(), indent=1, sort_keys=True)

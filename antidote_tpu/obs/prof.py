"""Device-plane profiler — the kernel-span layer over the jitted hot
paths, plus the XProf capture API (absorbed from the old
antidote_tpu.tracing module so the process has ONE tracing namespace;
that shim is retired to a one-release import error, ISSUE 7).

PR 1 made the *host* planes observable (txid spans, flight recorder,
stage histograms); the fused XLA/Pallas programs in antidote_tpu/mat/
stayed a black box.  This module closes that gap in the Dapper spirit
of always-on, sampled production profiling:

- **Kernel spans** — every jitted entry point of the materializer,
  sharded store, and dependency gate is wrapped (``@kernel_span`` at
  the definition, or :meth:`DeviceProfiler.wrap` around dynamically
  built jits).  Each call records dispatch wall time; when the call
  runs under a *sampled* txn span (obs/spans.py) or an active capture,
  completion is also measured honestly — a scalar device→host fetch,
  the benches/_util.py methodology (``block_until_ready`` does not
  block through the remote-TPU tunnel) — and a ``kernel:*`` child-span
  joins the transaction's trace tree.
- **Compile-cache-miss counters** — keyed by function + abstract shape
  signature (shapes/dtypes of array leaves, values of static scalars),
  so a recompilation storm is attributable to the kernel and shape
  that minted it instead of showing up as an anonymous p99 spike.
- **Device-buffer census** — per-subsystem high-watermark gauges over
  the LARGEST single state pytree any of the subsystem's kernels has
  returned (a lower bound on its footprint — several plane states
  co-reside; the global ``jax.live_arrays()`` census in
  :meth:`DeviceProfiler.snapshot`, served by stats.py's
  ``/debug/prof``, is the total).
- **Capture unification** — when an XProf window is open
  (:func:`profile`/:func:`start`), every wrapped kernel call is
  additionally bracketed by a ``jax.profiler.TraceAnnotation`` carrying
  the kernel name and the active txid, so the device timeline reads
  "kernel:orset_read_keys[txid=...]" instead of anonymous XLA modules.

Cost discipline: with ``profiler.enabled`` False every hook is a single
attribute check + passthrough (no tree flattening, no jnp ops, zero
new compile-cache entries — tests/unit/test_obs_prof.py pins this).
Enabled (the default), the per-call cost is a few µs of host
bookkeeping on *batch-level* dispatches; the completion fetch happens
only for sampled txns, ``detail`` mode, or open captures.  Calls made
while a jit trace is being staged (a wrapped store fn composed into
fused_read / shard_map bodies) pass straight through — timing a trace
would record compilation, not execution.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Any, Callable, Dict, Optional

from antidote_tpu.obs.spans import tracer

# ------------------------------------------------------------------ capture
# (one capture at a time, mirroring jax.profiler's own constraint)

_capture_lock = threading.Lock()
_active_dir: Optional[str] = None


def annotate(name: str):
    """Context manager labeling the enclosed host+device work in a
    profiler capture; no-op cost when no capture is active."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a JAX profiler trace of the enclosed block into
    ``log_dir`` (inspect with TensorBoard's profile plugin / XProf)."""
    start(log_dir)
    try:
        yield log_dir
    finally:
        stop()


def start(log_dir: str) -> None:
    """Begin a capture (idempotent per process: one capture at a time).
    While the window is open, wrapped kernel calls auto-annotate the
    device timeline with their name and active txid."""
    global _active_dir
    import jax

    with _capture_lock:
        if _active_dir is not None:
            raise RuntimeError(
                f"profiler already capturing to {_active_dir}")
        jax.profiler.start_trace(log_dir)
        _active_dir = log_dir


def stop() -> str:
    """End the capture; returns the trace directory."""
    global _active_dir
    import jax

    with _capture_lock:
        if _active_dir is None:
            raise RuntimeError("no profiler capture active")
        jax.profiler.stop_trace()
        out, _active_dir = _active_dir, None
        return out


def active_dir() -> Optional[str]:
    return _active_dir


# --------------------------------------------------------- kernel-span layer

_trace_clean_fn: Optional[Callable[[], bool]] = None


def _trace_clean() -> bool:
    """True when no jax trace is being staged on this thread — wrapped
    kernels called *inside* another jit's trace (fused_read bodies,
    shard_map locals) must pass through untimed."""
    global _trace_clean_fn
    if _trace_clean_fn is None:
        try:
            from jax.core import trace_state_clean as fn
        except Exception:  # pragma: no cover — very old/absent jax
            fn = lambda: True  # noqa: E731
        _trace_clean_fn = fn
    return _trace_clean_fn()


def _sig(args: tuple, kwargs: dict) -> tuple:
    """Abstract-shape signature of a call: (shape, dtype) per array
    leaf, the value itself for Python scalars.  Value-keying scalars is
    right for THIS codebase's wrapped kernels, where a raw Python
    scalar only ever reaches a jit as a static arg (pallas block_k /
    interpret, rga_merge actor_bits — distinct values mint distinct
    programs); a kernel taking a *traced* Python scalar would have its
    misses overcounted, which the per-kernel signature cap below
    bounds."""
    import jax

    out = []
    for x in jax.tree_util.tree_leaves((args, kwargs)):
        if x is None or isinstance(x, (bool, int, float, str)):
            out.append(("static", x))
        else:
            out.append((tuple(getattr(x, "shape", ())),
                        str(getattr(x, "dtype", ""))))
    return tuple(out)


def _force(out) -> bool:
    """Honest completion barrier: device→host fetch of ONE scalar of
    the result (benches/_util.py fetch) — the only completion clock
    that works through the remote-TPU tunnel.  Returns False when the
    result holds no fetchable array (pure-host outputs)."""
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(out):
        shape = getattr(leaf, "shape", None)
        if shape is None or not hasattr(leaf, "dtype"):
            continue
        if any(s == 0 for s in shape):
            continue
        idx = tuple(0 for _ in shape)
        np.asarray(leaf[idx] if shape else leaf)
        return True
    return False


def _nbytes(out) -> int:
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(out):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


#: per-kernel signature-set bound: past this the set clears en masse
#: (the spans decision-cache idiom) — the miss counter may then
#: recount old shapes, but a long-running node cannot grow host memory
#: without bound when a kernel's signature space is large
_SHAPES_CAP = 1024


class _KernelStat:
    """Aggregate for one wrapped kernel (mutated under the profiler
    lock; snapshot() copies the scalars out)."""

    __slots__ = ("subsystem", "calls", "dispatch_s", "complete_s",
                 "completions", "compile_misses", "shapes",
                 "bytes_out_hwm", "last_call_us")

    def __init__(self, subsystem: str):
        self.subsystem = subsystem
        self.calls = 0
        self.dispatch_s = 0.0
        self.complete_s = 0.0
        self.completions = 0
        self.compile_misses = 0
        self.shapes: set = set()
        self.bytes_out_hwm = 0
        self.last_call_us = 0


class DeviceProfiler:
    """Process-global kernel profiler (all DCs share it, like
    stats.registry and obs.spans.tracer)."""

    def __init__(self):
        #: master switch — False makes every wrapped call a bare
        #: passthrough (Config.kernel_profile via obs.configure)
        self.enabled = True
        #: honest completion fetch on EVERY call, not just sampled
        #: ones — bench/diagnosis mode, too heavy for serving
        self.detail = False
        self._stats: Dict[str, _KernelStat] = {}
        self._subsys_hwm: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------------- configuration

    def configure(self, enabled: Optional[bool] = None,
                  detail: Optional[bool] = None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if detail is not None:
            self.detail = bool(detail)

    def reset(self) -> None:
        """Drop all aggregates (test isolation)."""
        with self._lock:
            self._stats.clear()
            self._subsys_hwm.clear()

    # ------------------------------------------------------------- wrapping

    def wrap(self, fn, name: Optional[str] = None,
             subsystem: str = "mat"):
        """Wrap a jitted callable in the kernel-span layer.  Semantics
        are preserved exactly (args pass through, donation and
        exceptions included); ``__name__`` is kept so callers that key
        caches on it (device_plane._FUSED_CACHE) see no change."""
        kname = name or getattr(fn, "__name__", repr(fn))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not self.enabled or not _trace_clean():
                return fn(*args, **kwargs)
            return self._call(fn, kname, subsystem, args, kwargs)

        wrapper.__kernel_span__ = (kname, subsystem)
        return wrapper

    def _stat(self, kname: str, subsystem: str) -> _KernelStat:
        st = self._stats.get(kname)
        if st is None:
            with self._lock:
                st = self._stats.setdefault(kname, _KernelStat(subsystem))
        return st

    def _call(self, fn, kname: str, subsystem: str, args, kwargs):
        from antidote_tpu import stats as _stats

        reg = _stats.registry
        st = self._stat(kname, subsystem)
        # the underlying jit object's id joins the key: several distinct
        # programs can share one kernel NAME (fused_read's per-pattern
        # jits, _sm's per-instance shard_maps), and same-shape calls of
        # a DIFFERENT program are still fresh XLA compiles (id reuse
        # after a dropped jit is GC'd can undercount — acceptable for a
        # storm detector)
        sig = (id(fn),) + _sig(args, kwargs)
        if sig not in st.shapes:
            # first call at a new abstract shape = a jit compile-cache
            # miss for this kernel (jax specializes per shape); counting
            # here attributes a recompilation storm to its source
            with self._lock:
                if sig not in st.shapes:
                    if len(st.shapes) >= _SHAPES_CAP:
                        st.shapes.clear()
                    st.shapes.add(sig)
                    st.compile_misses += 1
                    reg.kernel_compile_misses.inc(kernel=kname)
        cur = tracer.current()
        cap = _active_dir is not None
        t0_us = time.time_ns() // 1000
        t0 = time.perf_counter()
        if cap:
            label = f"kernel:{kname}"
            if cur is not None and cur.txid is not None:
                label += f"[txid={cur.txid!r}]"
            with annotate(label):
                out = fn(*args, **kwargs)
        else:
            out = fn(*args, **kwargs)
        dispatch = time.perf_counter() - t0
        dur = dispatch
        completed = False
        if cur is not None or cap or self.detail:
            completed = _force(out)
            if completed:
                dur = time.perf_counter() - t0
        nb = _nbytes(out)
        with self._lock:
            st.calls += 1
            st.dispatch_s += dispatch
            st.last_call_us = t0_us
            if completed:
                st.completions += 1
                st.complete_s += dur
            if nb > st.bytes_out_hwm:
                st.bytes_out_hwm = nb
            if nb > self._subsys_hwm.get(subsystem, 0):
                self._subsys_hwm[subsystem] = nb
                reg.device_buffer_hwm.set(nb, subsystem=subsystem)
        reg.kernel_calls.inc(kernel=kname, subsystem=subsystem)
        reg.kernel_dispatch_latency.observe(dispatch)
        if completed:
            reg.kernel_complete_latency.observe(dur)
        if cur is not None:
            tracer.record_span(
                f"kernel:{kname}", "kernel", cur.txid, t0_us,
                int(dur * 1e6), parent_id=cur.span_id,
                subsystem=subsystem, complete=completed)
        return out

    # -------------------------------------------------------------- queries

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready profiler state — the /debug/prof body."""
        with self._lock:
            kernels = {
                name: {
                    "subsystem": st.subsystem,
                    "calls": st.calls,
                    "compile_misses": st.compile_misses,
                    "dispatch_total_s": round(st.dispatch_s, 6),
                    "dispatch_mean_s": round(
                        st.dispatch_s / st.calls, 9) if st.calls else 0.0,
                    "completions": st.completions,
                    "complete_mean_s": round(
                        st.complete_s / st.completions, 9)
                    if st.completions else None,
                    "bytes_out_hwm": st.bytes_out_hwm,
                    "last_call_us": st.last_call_us,
                }
                for name, st in self._stats.items()
            }
            subsys = dict(self._subsys_hwm)
        return {
            "enabled": self.enabled,
            "detail": self.detail,
            "capture_dir": _active_dir,
            "kernels": kernels,
            "subsystem_bytes_hwm": subsys,
            "live_buffers": self._census(),
        }

    @staticmethod
    def _census() -> Optional[Dict[str, int]]:
        """Global live-device-buffer census.  Only runs when jax is
        already imported (never drags the runtime in from an endpoint)
        and degrades to None on any failure — a diagnostic read must
        not take the server down."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None or not hasattr(jax, "live_arrays"):
            return None
        try:
            arrs = jax.live_arrays()
            return {"count": len(arrs),
                    "bytes": int(sum(int(getattr(a, "nbytes", 0) or 0)
                                     for a in arrs))}
        except Exception:  # noqa: BLE001 — census is best-effort
            return None


#: process-wide profiler (all DCs share it, like stats.registry)
profiler = DeviceProfiler()


def kernel_span(subsystem: str, name: Optional[str] = None):
    """Decorator marking a jitted entry point as a profiled kernel —
    the instrumentation idiom tools/trace_lint.py enforces on every
    public ``@jax.jit`` function under antidote_tpu/mat/::

        @kernel_span("mat.store")
        @partial(jax.jit, donate_argnums=(0,))
        def orset_append(...): ...
    """

    def deco(fn):
        return profiler.wrap(fn, name=name, subsystem=subsystem)

    return deco

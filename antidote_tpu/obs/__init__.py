"""Transaction-lifecycle observability — spans, flight recorder, probes.

The reference leaned on BEAM tooling (observer's process/event views,
error_logger) for runtime forensics; this package rebuilds the two
halves natively for the TPU serving stack:

- :mod:`antidote_tpu.obs.spans` — a txid-correlated span tree across
  every plane (coordinator → log → device plane → inter-DC →
  dep-gate), held in a bounded in-process ring, queryable in tests and
  exportable as Chrome ``trace_event`` JSON (loadable in Perfetto
  alongside the JAX profiler captures :mod:`antidote_tpu.obs.prof`
  produces).
- :mod:`antidote_tpu.obs.events` — a per-subsystem flight recorder:
  bounded rings of structured events, dumped to disk automatically on
  txn aborts, error-monitor trips, and probe violations.
- :mod:`antidote_tpu.obs.probe` — online self-checks: the set_aw
  read-inclusion probe (chasing the VERDICT round-5 transient miss)
  and the ISSUE 7 causal-probe auditor (write→remote-read staleness +
  causal-order tripwire).
- :mod:`antidote_tpu.obs.prof` — the device-plane profiler (ISSUE 2):
  kernel spans over the jitted mat/ and interdc entry points,
  compile-cache-miss counters, device-buffer high-watermarks, and the
  XProf capture API (the old ``antidote_tpu.tracing`` shim was retired
  to a one-release import error, ISSUE 7).
- :mod:`antidote_tpu.obs.pipeline` — the pipeline snapshot (ISSUE 7):
  every registered DC's ship buffers, SubBuf gap state, gate
  backlogs, ingest staging, and stable watermarks as ONE JSON
  document, served at ``/debug/pipeline``.

Everything here is process-global, mirroring ``stats.registry`` (the
reference's metrics are BEAM-node-global the same way): all DCs in a
process share one tracer and one recorder, and the exporter surfaces
(``/debug/spans``, flight-recorder dumps) read the shared state.
"""

from __future__ import annotations

from antidote_tpu.obs.events import FlightRecorder, recorder  # noqa: F401
from antidote_tpu.obs.prof import DeviceProfiler, profiler  # noqa: F401
from antidote_tpu.obs.spans import Span, Tracer, tracer  # noqa: F401


def configure(sample_rate: float | None = None,
              capacity: int | None = None,
              dump_dir: str | None = None,
              selfcheck_set_aw: float | None = None,
              kernel_profile: bool | None = None) -> None:
    """Apply config knobs to the process-global tracer/recorder/probe/
    profiler (Node.__init__ forwards Config.trace_sample_rate & friends
    here).  ``None`` leaves a setting untouched, so tests and operators
    can override a single knob without reciting the rest."""
    from antidote_tpu.obs import probe as _probe

    if sample_rate is not None:
        tracer.sample_rate = float(sample_rate)
    if capacity is not None:
        tracer.set_capacity(int(capacity))
    if dump_dir is not None:
        recorder.dump_dir = dump_dir
    if selfcheck_set_aw is not None:
        _probe.SELF_CHECK_RATE = float(selfcheck_set_aw)
    if kernel_profile is not None:
        profiler.configure(enabled=bool(kernel_profile))

"""Online self-checks — the set_aw read-inclusion probe.

VERDICT round 5 documents an open causal-correctness bug: a
device-served ``set_aw`` read transiently misses one OLD element in
roughly 1/10 heavy federation runs.  The probe is the tripwire: a
sampled fraction of device-served set_aw reads is re-materialized from
the durable log at the SAME snapshot (the host-oracle-exact path,
``PartitionManager._read_from_log``) and the element sets compared.
Inclusion is the property under test — every element the log replay
shows live at the snapshot must appear in the device fold's state (the
dot-collapse keeps element presence exact; see the device_plane module
doc).  A violation dumps the flight recorder (``force=True`` — this is
the forensic record the round-6 hunt exists for) and logs at ERROR so
the error monitor counts it.

The probe only arms on reads with an EXPLICIT snapshot: a read-latest
device fold races commits that land between the fold and the log
replay, which would flag phantom misses; an explicit VC filters both
sides to the same op window (``op_in_read_snapshot``), so a reported
miss is real.
"""

from __future__ import annotations

import logging
import random

from antidote_tpu.config import Config as _Config
from antidote_tpu.obs.events import recorder

log = logging.getLogger(__name__)

#: probability a device-served set_aw read is cross-checked
#: (Config.obs_selfcheck_set_aw via obs.configure — Config is the
#: single source of the default; off by default, the replay costs a
#: per-key log scan)
SELF_CHECK_RATE: float = _Config().obs_selfcheck_set_aw


def should_check(read_vc) -> bool:
    """Arm the probe?  Explicit-snapshot reads only (module doc)."""
    rate = SELF_CHECK_RATE
    if rate <= 0.0 or read_vc is None:
        return False
    return rate >= 1.0 or random.random() < rate


def missing_elements(device_state, oracle_state) -> set:
    """Elements live in the log-replay oracle but absent from the
    device fold — the inclusion violation set.  Both states are the
    set_aw host shape (element -> live dots); extra elements on the
    device side are NOT flagged here (that is a staleness question,
    not the inclusion property this probe guards)."""
    return set(oracle_state) - set(device_state)


def verify_set_aw_inclusion(partition: int, key, read_vc, device_state,
                            oracle_state) -> set:
    """Record the check; on violation, dump the flight recorder and
    trip the error monitor.  Returns the missing-element set so the
    caller (and tests) can assert on it."""
    missing = missing_elements(device_state, oracle_state)
    recorder.record("probe", "set_aw_check", partition=partition,
                    key=key, missing=len(missing))
    if missing:
        extra = {
            "partition": partition,
            "key": key,
            "read_vc": dict(read_vc) if read_vc is not None else None,
            "missing": sorted(repr(e) for e in missing),
            "device_elements": sorted(repr(e) for e in device_state),
            "oracle_elements": sorted(repr(e) for e in oracle_state),
        }
        recorder.dump("set_aw_inclusion", extra=extra, force=True)
        log.error(
            "set_aw inclusion probe: device read of %r (partition %d) "
            "missed %d element(s) present in the log replay", key,
            partition, len(missing))
    return missing

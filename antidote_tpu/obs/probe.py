"""Online self-checks — the set_aw read-inclusion probe and the
causal-probe auditor.

The causal probe (ISSUE 7) is the end-to-end tripwire over the whole
replication pipeline: each round commits a UNIQUE element to a probe
key on its home DC, then causally reads the key back on every other
DC registered in the process AT the write's commit clock.  Clock-SI's
wait_for_clock promise says the read must return only once the clock
is covered — so the element MUST be present; a miss is a causal-order
violation (the exact class of bug the round-5 heartbeat race was),
which bumps ``antidote_vis_probe_violations_total``, dumps the flight
recorder (force — this is the forensic record), and logs at ERROR.
The time from commit to the causal read returning is the *observed*
write->remote-read staleness — recorded into
``antidote_vis_probe_staleness_seconds``, the measured counterpart of
the carried-wallclock visibility-lag histograms in stats.py.

VERDICT round 5 documents an open causal-correctness bug: a
device-served ``set_aw`` read transiently misses one OLD element in
roughly 1/10 heavy federation runs.  The probe is the tripwire: a
sampled fraction of device-served set_aw reads is re-materialized from
the durable log at the SAME snapshot (the host-oracle-exact path,
``PartitionManager._read_from_log``) and the element sets compared.
Inclusion is the property under test — every element the log replay
shows live at the snapshot must appear in the device fold's state (the
dot-collapse keeps element presence exact; see the device_plane module
doc).  A violation dumps the flight recorder (``force=True`` — this is
the forensic record the round-6 hunt exists for) and logs at ERROR so
the error monitor counts it.

The probe only arms on reads with an EXPLICIT snapshot: a read-latest
device fold races commits that land between the fold and the log
replay, which would flag phantom misses; an explicit VC filters both
sides to the same op window (``op_in_read_snapshot``), so a reported
miss is real.
"""

from __future__ import annotations

import logging
import random

from antidote_tpu.config import Config as _Config
from antidote_tpu.obs.events import recorder

log = logging.getLogger(__name__)

#: probability a device-served set_aw read is cross-checked
#: (Config.obs_selfcheck_set_aw via obs.configure — Config is the
#: single source of the default; off by default, the replay costs a
#: per-key log scan)
SELF_CHECK_RATE: float = _Config().obs_selfcheck_set_aw


def should_check(read_vc) -> bool:
    """Arm the probe?  Explicit-snapshot reads only (module doc)."""
    rate = SELF_CHECK_RATE
    if rate <= 0.0 or read_vc is None:
        return False
    return rate >= 1.0 or random.random() < rate


def missing_elements(device_state, oracle_state) -> set:
    """Elements live in the log-replay oracle but absent from the
    device fold — the inclusion violation set.  Both states are the
    set_aw host shape (element -> live dots); extra elements on the
    device side are NOT flagged here (that is a staleness question,
    not the inclusion property this probe guards)."""
    return set(oracle_state) - set(device_state)


class CausalProbe:
    """Continuous write->remote-read auditor for one home DC.

    Peers are discovered through the pipeline-snapshot registry
    (antidote_tpu/obs/pipeline.py — every DataCenter in the process
    registers there), filtered to the DCs the home DC is actually
    connected to, so the probe needs no wiring beyond the Config knob
    (``obs_causal_probe_s``)."""

    #: one probe key per home DC keeps concurrent probers from
    #: certification-aborting each other
    KEY_BUCKET = "__obs__"

    def __init__(self, local, period_s: float = 1.0):
        import threading

        self.local = local
        self.period_s = period_s
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        self.rounds = 0
        self.violations = 0
        #: per-peer depth (ISSUE 17): peer dc_id -> {rounds,
        #: violations, last_rtt_s, last_violation_at_us} — the
        #: attribution surface /debug/pipeline's probe section and
        #: slo_report expose (the global counters cannot name a peer)
        self.peer_stats: dict = {}
        self.last_violation_at_us = None

    def _peer_entry(self, peer_id) -> dict:
        return self.peer_stats.setdefault(str(peer_id), {
            "rounds": 0, "violations": 0, "last_rtt_s": None,
            "last_violation_at_us": None})

    def probe_stats(self) -> dict:
        """Copy of the per-peer depth map (safe to serialize while the
        probe thread keeps writing — entries are small flat dicts)."""
        return {p: dict(v) for p, v in list(self.peer_stats.items())}

    def _key(self):
        return (f"__causal_probe__{self.local.node.dc_id}", "set_aw",
                self.KEY_BUCKET)

    def _peers(self):
        from antidote_tpu.obs import pipeline

        connected = set(getattr(self.local, "connected_dcs", ()))
        return [dc for dc in pipeline.endpoints()
                if dc is not self.local
                and hasattr(dc, "read_objects_static")
                and getattr(dc, "node", None) is not None
                and dc.node.dc_id in connected]

    def run_once(self) -> int:
        """One probe round; returns the number of peers checked.

        Each peer gets its OWN fresh write: one shared write with
        serial reads would charge every earlier peer's read duration
        to the later peers' staleness samples (at N peers the
        histogram p99 inflates ~N-fold as a pure iteration-order
        artifact), so the write→causal-read pair is per peer and the
        sample is exact."""
        import time

        from antidote_tpu import stats

        checked = 0
        for peer in self._peers():
            if self._stop.is_set():
                break
            self._seq += 1
            elem = f"probe:{self.local.node.dc_id}:{self._seq}"
            key = self._key()
            t0 = time.perf_counter()
            try:
                ct = self.local.update_objects_static(
                    None, [(key, "add", elem)])
            except Exception:  # noqa: BLE001 — a refused probe write
                # (maintenance window, cert abort) is not a violation
                recorder.record("probe", "causal_probe_write_failed",
                                dc=str(self.local.node.dc_id))
                continue
            try:
                vals, _vc = peer.read_objects_static(ct, [key])
            except TimeoutError:
                # availability bound, not a consistency event: the
                # peer's clock never covered the commit in time
                recorder.record("probe", "causal_probe_timeout",
                                dc=str(self.local.node.dc_id),
                                peer=str(peer.node.dc_id))
                continue
            staleness_s = time.perf_counter() - t0
            stats.registry.vis_probe_staleness.observe(staleness_s)
            stats.registry.vis_probe_rtt.set(
                staleness_s, dc=str(self.local.node.dc_id),
                peer=str(peer.node.dc_id))
            ps = self._peer_entry(peer.node.dc_id)
            ps["rounds"] += 1
            ps["last_rtt_s"] = round(staleness_s, 6)
            recorder.record("probe", "causal_probe",
                            dc=str(self.local.node.dc_id),
                            peer=str(peer.node.dc_id),
                            staleness_s=round(staleness_s, 6),
                            elem=elem)
            checked += 1
            missing = elem not in vals[0]
            # retire the element: an always-on auditor must not grow
            # its probe key (and every round's read payload, and the
            # replicated set state) without bound — the remove
            # replicates like any op, keeping the key O(in-flight)
            try:
                self.local.update_objects_static(
                    ct, [(key, "remove", elem)])
            except Exception:  # noqa: BLE001 — best-effort retirement
                pass
            if missing:
                self.violations += 1
                now_us = time.time_ns() // 1000
                ps["violations"] += 1
                ps["last_violation_at_us"] = now_us
                self.last_violation_at_us = now_us
                stats.registry.vis_probe_violations.inc()
                from antidote_tpu.obs import pipeline

                recorder.dump("causal_probe", force=True, extra={
                    "writer_dc": str(self.local.node.dc_id),
                    "reader_dc": str(peer.node.dc_id),
                    "elem": elem,
                    "commit_vc": dict(ct) if ct is not None else None,
                    "visible": sorted(repr(e) for e in vals[0]),
                    "pipeline": pipeline.snapshot(),
                })
                log.error(
                    "causal probe violation: %r read at its own commit "
                    "clock on %r is missing element %r written by %r",
                    key, peer.node.dc_id, elem, self.local.node.dc_id)
        self.rounds += 1
        return checked

    # ------------------------------------------------------- background

    def start(self) -> None:
        import threading

        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"causal-probe-{self.local.node.dc_id}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — the auditor must not die
                log.exception("causal probe round failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def verify_set_aw_inclusion(partition: int, key, read_vc, device_state,
                            oracle_state) -> set:
    """Record the check; on violation, dump the flight recorder and
    trip the error monitor.  Returns the missing-element set so the
    caller (and tests) can assert on it."""
    missing = missing_elements(device_state, oracle_state)
    recorder.record("probe", "set_aw_check", partition=partition,
                    key=key, missing=len(missing))
    if missing:
        extra = {
            "partition": partition,
            "key": key,
            "read_vc": dict(read_vc) if read_vc is not None else None,
            "missing": sorted(repr(e) for e in missing),
            "device_elements": sorted(repr(e) for e in device_state),
            "oracle_elements": sorted(repr(e) for e in oracle_state),
        }
        recorder.dump("set_aw_inclusion", extra=extra, force=True)
        log.error(
            "set_aw inclusion probe: device read of %r (partition %d) "
            "missed %d element(s) present in the log replay", key,
            partition, len(missing))
    return missing

"""Pipeline snapshot — the whole replication pipeline as ONE object.

PRs 1-6 instrumented each plane separately (spans, kernel profiler,
GATE_*/INGEST_*/SHIP_* counters); what no single surface could answer
is "where is the pipeline holding data RIGHT NOW?"  This module
aggregates, in one JSON document per registered DataCenter:

- **ship**: each outbound stream's staged-txn depth, estimated bytes,
  oldest-staged age, and outbox length (the async sender's buffer —
  antidote_tpu/interdc/sender.py);
- **sub_bufs**: each inbound (origin, partition) stream's gap state,
  buffered-txn count, and opid watermark (interdc/sub_buf.py);
- **gates**: each partition's dependency-gate backlog, per-origin
  queue depths, applied watermark vector, and device-ring occupancy
  (interdc/dep.py);
- **ingest**: each partition's materializer staging — rows coalescing
  toward the next packed flush, per type plane, with the oldest-row
  age (mat/device_plane.py staging for mat/ingest.py);
- **stable**: the published stable snapshot and each partition's
  safe-time vector (the quantity the VIS_* safe-time-lag gauges age);
- **log**: each partition's durable-log group-commit state — staged
  records/bytes, oldest staged age, written vs synced watermarks, and
  the drain counters (oplog/log.py queue_stats, ISSUE 9) — plus the
  retention view (ISSUE 10): on-disk file size, retained vs truncated
  logical bytes, and the newest checkpoint's age/keys/cut
  (oplog/partition.py log_stats, which also refreshes the
  LOG_*/CKPT_* growth gauges);
- **fabric**: the node fabric's answer-plane economy (ISSUE 12) —
  transport kind, native-answered RPC count (the GIL never taken),
  live published answers, inbound queue depth (cluster/nativelink.py
  fabric_counters; refreshes the FABRIC_* gauges on every read);
- **native**: the native-plane flight recorder's rings (ISSUE 16) —
  per-ring occupancy, drain cursors, overwrite losses, and heartbeat
  age for the node link's and the fabric hub's telemetry rings
  (cluster/nativelink.py, interdc/tcp.py);
- **probe**: the causal-probe auditor's depth (ISSUE 17) — per-peer
  write->read round-trip, per-peer violation counts, and the
  last-violation wallclock (obs/probe.py peer_stats), so an SLO
  breach on the probe families names the peer;
- **threads** (top level): component-named live threads
  (``antidote-fab-*`` / ``antidote-sub-*`` / ``antidote-nl-*``) with
  live counts, so a stall dump names the blocked component instead of
  ``Thread-N``; native C++ event threads appear as ``native-<ring>``
  entries carrying their last-heartbeat age (ISSUE 16).

Served at ``GET /debug/pipeline`` by the metrics server (stats.py),
embedded in causal-probe violation dumps (obs/probe.py), and attached
to the causal checker's failure forensics (tests/causal_core.py).

Registration is by weakref: every DataCenter registers itself at
construction and unregisters at close, so a leaked test DC cannot pin
itself alive through this module.  All reads are defensive — a racy
or half-built DC yields a partial section, never an exception (a
diagnostic read must not take the server down).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import weakref
from typing import Any, Dict, List

from antidote_tpu.obs.events import _jsonable

log = logging.getLogger(__name__)

_lock = threading.Lock()
_endpoints: List["weakref.ref"] = []

#: sections whose last evaluation failed, keyed by section name — the
#: first failure of an episode is logged, repeats stay quiet, and a
#: success re-arms the latch (the watchdog episode-latch discipline,
#: ISSUE 17: a permanently-broken section must not masquerade as an
#: idle one)
_section_failed: Dict[str, str] = {}


def register(dc) -> None:
    """Track a DC assembly for pipeline snapshots (weakly)."""
    with _lock:
        _endpoints.append(weakref.ref(dc))


def unregister(dc) -> None:
    with _lock:
        _endpoints[:] = [r for r in _endpoints
                         if r() is not None and r() is not dc]


def endpoints() -> list:
    """Live registered DC assemblies (also the causal probe's peer
    discovery, obs/probe.py)."""
    with _lock:
        out = []
        for r in _endpoints:
            dc = r()
            if dc is not None:
                out.append(dc)
        return out


def _section(name, fn):
    """Run one snapshot section; a failure becomes an error marker
    instead of killing the whole document — but the FIRST failure of
    each episode is logged (latched per section; a success re-arms),
    so a section that broke forever is visible in the log exactly
    once instead of silently reading as empty on every scrape."""
    try:
        out = fn()
    except Exception as e:  # noqa: BLE001 — diagnostics must not throw
        if name not in _section_failed:
            log.warning("pipeline snapshot section %s failed "
                        "(latched — logged once per episode): %r",
                        name, e, exc_info=True)
        _section_failed[name] = repr(e)
        return {"error": repr(e)}
    _section_failed.pop(name, None)
    return out


def _ship_section(dc) -> Dict[str, Any]:
    senders = getattr(dc, "senders", [])
    if isinstance(senders, dict):  # federation: {partition: sender}
        senders = senders.values()
    out = {}
    for sender in senders:
        out[str(sender.partition)] = sender.queue_stats()
    return out


def _sub_buf_section(dc) -> Dict[str, Any]:
    out = {}
    for (origin, p), buf in dict(getattr(dc, "sub_bufs", {})).items():
        out[f"{origin}:{p}"] = buf.gap_stats()
    return out


def _gate_section(dc) -> Dict[str, Any]:
    gates = getattr(dc, "dep_gates", None)
    if gates is None:  # federation: {partition: gate}
        gates = getattr(dc, "gates", {})
    items = gates.items() if isinstance(gates, dict) else enumerate(gates)
    return {str(p): gate.queue_stats() for p, gate in items}


def _ingest_section(dc) -> Dict[str, Any]:
    now_us = time.monotonic_ns() // 1000
    out: Dict[str, Any] = {}
    node = getattr(dc, "node", None)
    for p, pm in enumerate(getattr(node, "partitions", [])):
        dev = getattr(pm, "device", None)
        if dev is None:
            continue
        planes = {}
        staged_total = 0
        oldest_age_us = 0
        for tn, plane in getattr(dev, "planes", {}).items():
            rows = getattr(plane, "rows", None)
            if not rows:
                continue
            n = len(rows)
            staged_total += n
            age = max(now_us - getattr(plane, "_stage_t0_us", now_us), 0)
            oldest_age_us = max(oldest_age_us, age)
            planes[tn] = {"staged_rows": n, "oldest_age_us": age}
        out[str(p)] = {"staged_rows": staged_total,
                       "oldest_age_us": oldest_age_us,
                       "planes": planes}
    return out


def _log_section(dc) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    node = getattr(dc, "node", None)
    for p, pm in enumerate(getattr(node, "partitions", [])):
        plog = getattr(pm, "log", None)
        stats_fn = getattr(plog, "log_stats", None)
        if stats_fn is None:
            continue  # remote member slice: not this process's log
        out[str(p)] = stats_fn()
    return out


def _fabric_section(dc) -> Dict[str, Any]:
    """The node fabric's answer-plane economy (ISSUE 12): which
    transport the member runs, and — on the native plane — how many
    RPCs the C++ event threads answered from the published-answer
    table without ever taking the GIL, how many answers are live, and
    the inbound queue depth.  Empty for a DataCenter with no node
    fabric (single-process ring)."""
    srv = getattr(dc, "srv", None)
    link = getattr(srv, "link", None)
    if link is None:
        return {}
    out: Dict[str, Any] = {"kind": srv.fabric_kind()}
    counters = getattr(link, "fabric_counters", None)
    if counters is not None:
        c = counters()
        out.update(c)
        # the FABRIC_* gauges refresh on every pipeline read as well
        # as the gossip cadence (native answers never enter Python, so
        # only a pull can observe them); the one pulled snapshot
        # feeds both the section and the gauges
        srv._refresh_fabric_gauges(c)
    return out


def _native_section(dc) -> Dict[str, Any]:
    """The native-plane flight recorder's rings (ISSUE 16): per-ring
    occupancy, drain cursors, cumulative overwrite losses, heartbeat
    age, and the enable flag — the node link's ring and (when this DC
    publishes through the C++ hub) the fabric hub's.  Quick cursor
    reads only (atomics, PyDLL class); the DRAIN rides its own
    cadences, never a pipeline read."""
    out: Dict[str, Any] = {}
    link = getattr(getattr(dc, "srv", None), "link", None)
    info = getattr(link, "telemetry_info", None)
    if info is not None:
        d = info()
        if d:
            out["nodelink"] = d
    info = getattr(getattr(dc, "bus", None), "telemetry_info", None)
    if info is not None:
        d = info()
        if d:
            out["fabric"] = d
    return out


def _threads_section() -> Dict[str, Any]:
    """Component-named live threads (ISSUE 12): every transport /
    fabric / sub-sender thread carries an ``antidote-*`` name
    (``antidote-fab-*``, ``antidote-sub-*``, ``antidote-nl-*``), so
    stall forensics and the causal-probe dumps attribute a blocked
    send to a component instead of ``Thread-N``.  Name -> {"count":
    live threads} (worker pools index their name stem).  Native event
    threads live in C++ — invisible to ``threading.enumerate`` — so
    they appear as ``native-<ring>`` entries carrying their ring's
    last-heartbeat age (ISSUE 16): a stall dump shows which event
    thread went QUIET, not merely that it was spawned."""
    out: Dict[str, Any] = {}
    for t in threading.enumerate():
        if t.name.startswith("antidote-"):
            entry = out.setdefault(t.name, {"count": 0})
            entry["count"] += 1
    from antidote_tpu.obs import nativeobs

    for ring, age in nativeobs.watchdog.ages().items():
        entry = out.setdefault(f"native-{ring}", {"count": 1})
        entry["heartbeat_age_s"] = age
    return dict(sorted(out.items()))


def _stable_section(dc) -> Dict[str, Any]:
    stable = getattr(dc, "stable", None)
    if stable is None:
        return {}
    out: Dict[str, Any] = {
        "snapshot": {str(k): v
                     for k, v in dict(stable.get_stable_snapshot()).items()}
    }
    per_part = {}
    for p, src in enumerate(getattr(stable, "sources", []) or []):
        per_part[str(p)] = {str(k): v for k, v in dict(src()).items()}
    out["per_partition"] = per_part
    return out


def _probe_section(dc) -> Dict[str, Any]:
    """The causal probe's depth (ISSUE 17): per-peer round-trip and
    last-violation wallclock, so an SLO breach on the probe families
    attributes to a peer instead of a global counter."""
    pr = getattr(dc, "_causal_probe", None)
    if pr is None:
        return {"enabled": False}
    return {
        "enabled": True,
        "period_s": pr.period_s,
        "rounds": pr.rounds,
        "violations": pr.violations,
        "last_violation_at_us": pr.last_violation_at_us,
        "peers": pr.probe_stats(),
    }


def dc_snapshot(dc) -> Dict[str, Any]:
    """One DC's pipeline state, every section independently guarded.
    Section latch keys carry the DC name so one DC's broken section
    cannot re-arm (or mask) another's."""
    try:
        who = str(dc.node.dc_id)
    except Exception:  # noqa: BLE001 — half-built DC still snapshots
        who = "?"
    return {
        "ship": _section(f"{who}.ship", lambda: _ship_section(dc)),
        "sub_bufs": _section(f"{who}.sub_bufs",
                             lambda: _sub_buf_section(dc)),
        "gates": _section(f"{who}.gates", lambda: _gate_section(dc)),
        "ingest": _section(f"{who}.ingest",
                           lambda: _ingest_section(dc)),
        "log": _section(f"{who}.log", lambda: _log_section(dc)),
        "stable": _section(f"{who}.stable",
                           lambda: _stable_section(dc)),
        "fabric": _section(f"{who}.fabric",
                           lambda: _fabric_section(dc)),
        "native": _section(f"{who}.native",
                           lambda: _native_section(dc)),
        "probe": _section(f"{who}.probe",
                          lambda: _probe_section(dc)),
        "connected_dcs": _section(
            f"{who}.connected_dcs",
            lambda: [str(d) for d in getattr(dc, "connected_dcs", [])]),
    }


def snapshot() -> Dict[str, Any]:
    """The /debug/pipeline body: every registered DC's pipeline state
    plus the wallclock it was taken at."""
    dcs = {}
    for dc in endpoints():
        try:
            name = str(dc.node.dc_id)
            member = getattr(dc, "member_index", None)
            if member is not None:  # federation: one entry per member
                name = f"{name}[{member}]"
        except Exception:  # noqa: BLE001 — half-closed DC
            continue
        dcs[name] = dc_snapshot(dc)
    return {"at_us": time.time_ns() // 1000, "dcs": dcs,
            "threads": _section("threads", _threads_section)}


def snapshot_json() -> str:
    return json.dumps(_jsonable(snapshot()))
